"""shardcheck — static sharding contracts over the kernel manifest.

The third analysis tier (``python -m crdt_tpu.analysis --shard``): the
ROADMAP's mesh item shards the *object axis* of the dense planes
(``shard_map``/pjit over ``parallel/mesh.py``), and the decomposition
"local join per shard + ICI all-reduce for the global lattice join" is
provably safe only for kernels whose jaxprs respect that axis.  Every
:class:`~crdt_tpu.analysis.kernels.KernelSpec` row declares a
:class:`~crdt_tpu.analysis.kernels.ShardContract`; this module traces
each manifested kernel abstractly (the same TraceCase ladders
kernelcheck walks, plus mesh-shaped cases whose operands are re-shaped
to their per-shard extents under an abstract ``jax.sharding.Mesh`` of
sizes {1,2,4,8}) and walks the ``ClosedJaxpr`` tracking which dims
derive from the object axis:

* **SC01 cross-object flow** — a ``pointwise``-declared kernel whose
  jaxpr folds, slices, sorts, scans or re-groups the object axis, or
  gathers/scatters through it with indices NOT declared ``routed``:
  one shard's rows would need another shard's data, so shard-local
  execution silently computes the wrong lattice join.
* **SC02 collective contract** — ``reduction`` kernels must lower
  EXACTLY their declared collectives (today only the ``parallel/``
  shard_map joins lower any); ``pointwise``/``replicated`` kernels must
  lower none.  An undeclared collective is a hidden cross-shard
  dependency; a declared-but-absent one is a stale contract.
* **SC03 host round-trip** (AST, :mod:`tracer`-style lexical rules) —
  ``int()``/``float()``/``.item()``/``np.asarray()`` applied to a
  jitted kernel's output inside the ``parallel/``, ``batch/``,
  ``sync/``, ``serve/``, ``gc/`` hot paths: on a sharded fleet that is
  a device sync plus a cross-shard gather per call.
* **SC04 ragged shards** — every capacity-ladder rung of every
  object-axis operand must divide evenly by every declared mesh size
  (times the contract's ``granule``); a ragged shard means one device
  owns a different program shape than its peers.
* **SC05 mesh recompile budget** — distinct lowerings at each mesh
  size are bounded by the row's existing ``compile_budget`` (KC04
  bounds the unsharded ladder; this bounds each sharded replica of
  it).

Findings anchor at equation source frames (jax keeps user frames
through tracing) and reuse the ``# crdtlint: disable=SCxx`` pragma +
``baseline.json`` park/stale machinery unchanged.  One consistency
screw, KC01-style: an SC pragma that suppressed nothing this run —
the kernel's contract traces clean now — is re-flagged live as a
stale sanction, so sanctions rot loudly, never silently.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import time
from typing import Dict, List, Optional, Sequence, Set

from .core import (
    Baseline, Finding, LintResult, ParsedFile, load_files, repo_root,
)
from .jaxpr_rules import _eqn_loc, _flat_avals, _site_line, _walk
from .kernels import (
    ALL_LEAVES, MANIFEST, KernelSpec, ShardContract, iter_jit_sites,
)

SHARD_RULES = ("SC01", "SC02", "SC03", "SC04", "SC05")

#: hot-path packages SC03 scans for host round-trips on kernel outputs
SC03_SCOPE = ("crdt_tpu/parallel/", "crdt_tpu/batch/", "crdt_tpu/sync/",
              "crdt_tpu/serve/", "crdt_tpu/gc/")

#: jaxpr primitive name -> declarable collective name (psum_scatter is
#: how reduce_scatter spells itself in a traced jaxpr)
_COLLECTIVE_BY_PRIM = {
    "psum": "psum", "pmax": "pmax", "pmin": "pmin",
    "all_gather": "all_gather", "all_to_all": "all_to_all",
    "ppermute": "ppermute", "psum_scatter": "reduce_scatter",
}

#: primitives that FOLD an axis (params["axes"]/["dimensions"])
_REDUCE_PRIMS = {
    "reduce_sum", "reduce_max", "reduce_min", "reduce_prod",
    "reduce_and", "reduce_or", "reduce_xor", "argmax", "argmin",
    "reduce",
}

_SCATTER_PRIMS = {
    "scatter", "scatter-add", "scatter-mul", "scatter-sub",
    "scatter-max", "scatter-min",
}

_CALL_PRIMS = {
    "pjit", "closed_call", "core_call", "remat", "checkpoint",
    "custom_jvp_call", "custom_vjp_call", "custom_vjp_call_jaxpr",
    "shard_map", "custom_partitioning",
}


@dataclasses.dataclass
class ShardReport:
    """Everything one shardcheck run learned beyond the findings."""

    kernels: int = 0
    traced: int = 0
    cases: int = 0            # base-ladder trace cases analyzed
    mesh_cases: int = 0       # mesh-shaped (sharded-operand) cases
    contracts: Dict[str, int] = dataclasses.field(default_factory=dict)
    collectives: Dict[str, list] = dataclasses.field(default_factory=dict)
    skipped: List[dict] = dataclasses.field(default_factory=list)
    trace_errors: List[str] = dataclasses.field(default_factory=list)
    unknown_prims: List[str] = dataclasses.field(default_factory=list)
    opaque: List[str] = dataclasses.field(default_factory=list)
    sc03_files: int = 0
    elapsed_s: float = 0.0

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


# ---------------------------------------------------------------------------
# object-axis provenance over a ClosedJaxpr
# ---------------------------------------------------------------------------


class _Prov:
    """Walks one jaxpr propagating two taints per variable: the set of
    dims that derive from the object axis, and whether the *value*
    derives from a ``routed`` (object-id) operand.  Routed value-taint
    is sticky and conservative — it only ever SANCTIONS indexing, so
    over-propagation weakens SC01 toward silence, never toward a false
    positive.  Primitives with no handler and no shape match drop dim
    taint and are recorded in ``unknown`` for visibility."""

    def __init__(self, flag, unknown: Set[str]):
        self.flag = flag          # callable(eqn, what) -> None
        self.unknown = unknown
        self.opaque = False       # saw a pallas_call (refs: can't track)

    # -- var helpers --------------------------------------------------------

    @staticmethod
    def _is_lit(v) -> bool:
        return not hasattr(v, "count") and hasattr(v, "val")

    @staticmethod
    def _shape(v) -> tuple:
        return tuple(getattr(getattr(v, "aval", None), "shape", ()) or ())

    def run(self, jaxpr, in_dims, in_routed) -> None:
        dims: dict = {}
        routed: set = set()
        for v, d in zip(jaxpr.invars, in_dims):
            if d:
                dims[v] = frozenset(d)
        for v, r in zip(jaxpr.invars, in_routed):
            if r:
                routed.add(v)
        self._eval(jaxpr, dims, routed)

    # -- the interpreter ----------------------------------------------------

    def _eval(self, jaxpr, dims: dict, routed: set) -> None:
        for eqn in jaxpr.eqns:
            self._step(eqn, dims, routed)

    def _get(self, dims, v) -> frozenset:
        if self._is_lit(v):
            return frozenset()
        return dims.get(v, frozenset())

    def _routed(self, routed, v) -> bool:
        return (not self._is_lit(v)) and v in routed

    def _set_out(self, eqn, dims, routed, taints, any_in_routed) -> None:
        for i, ov in enumerate(eqn.outvars):
            t = taints[i] if isinstance(taints, list) else taints
            t = frozenset(d for d in t if d < len(self._shape(ov)))
            if t:
                dims[ov] = t
            if any_in_routed:
                routed.add(ov)

    def _step(self, eqn, dims: dict, routed: set) -> None:  # noqa: C901
        name = eqn.primitive.name
        in_dims = [self._get(dims, v) for v in eqn.invars]
        in_routed = any(self._routed(routed, v) for v in eqn.invars)
        any_taint = any(in_dims)
        out = lambda t: self._set_out(eqn, dims, routed, t, in_routed)

        def fold_ok(taint, folded_dims, v, what) -> frozenset:
            """Dims of ``taint`` folded by this eqn: flag the ones with
            extent > 1 (folding a singleton object slice mixes
            nothing), return the surviving taint."""
            hit = {d for d in taint if d in folded_dims}
            if any(self._shape(v)[d] > 1 for d in hit
                   if d < len(self._shape(v))):
                self.flag(eqn, what)
            return frozenset(taint - hit)

        if "pallas" in name:
            self.opaque = True
            return  # refs/memory semantics: opaque to dim provenance

        if name in _CALL_PRIMS or name.endswith("_call"):
            self._recurse(eqn, dims, routed, in_dims, in_routed)
            return
        if name == "while":
            self._while(eqn, dims, routed, in_dims, in_routed)
            return
        if name == "scan":
            self._scan(eqn, dims, routed, in_dims, in_routed)
            return
        if name == "cond":
            self._cond(eqn, dims, routed, in_dims, in_routed)
            return

        if not any_taint:
            # nothing object-derived flows in: outputs inherit only
            # the routed value-taint
            out(frozenset())
            return

        v0 = eqn.invars[0]
        t0 = in_dims[0]

        if name in _REDUCE_PRIMS:
            axes = set(eqn.params.get("axes",
                                      eqn.params.get("dimensions", ())))
            union = frozenset().union(*in_dims)
            kept = fold_ok(union, axes, v0,
                           f"{name} folds the object axis")
            remap = {d: d - sum(1 for a in axes if a < d)
                     for d in kept}
            out(frozenset(remap.values()))
        elif name.startswith("cum"):
            axis = eqn.params.get("axis", 0)
            if axis in t0 and self._shape(v0)[axis] > 1:
                self.flag(eqn, f"{name} runs a prefix fold along the "
                               "object axis")
            out(t0)
        elif name == "sort":
            dim = eqn.params.get("dimension", -1)
            union = frozenset().union(*in_dims)
            if dim in union and self._shape(v0)[dim] > 1:
                self.flag(eqn, "sort permutes rows along the object axis")
            out([in_dims[i] if i < len(in_dims) else union
                 for i in range(len(eqn.outvars))])
        elif name == "rev":
            folded = set(eqn.params.get("dimensions", ()))
            hit = t0 & folded
            if any(self._shape(v0)[d] > 1 for d in hit):
                self.flag(eqn, "reverse reorders the object axis")
            out(t0)
        elif name == "concatenate":
            dim = eqn.params.get("dimension", 0)
            union = frozenset().union(*in_dims)
            if dim in union and self._shape(eqn.outvars[0])[dim] > 1:
                self.flag(eqn, "concatenate grows the object axis")
            out(union)
        elif name == "pad":
            cfg = eqn.params.get("padding_config", ())
            hit = {d for d in t0 if d < len(cfg) and any(cfg[d])}
            if any(self._shape(v0)[d] > 1 for d in hit):
                self.flag(eqn, "pad resizes the object axis")
            out(t0)
        elif name == "slice":
            starts = eqn.params.get("start_indices", ())
            limits = eqn.params.get("limit_indices", ())
            strides = eqn.params.get("strides") or (1,) * len(starts)
            shp = self._shape(v0)
            bad = {d for d in t0
                   if d < len(shp) and shp[d] > 1
                   and (starts[d] != 0 or limits[d] != shp[d]
                        or strides[d] != 1)}
            if bad:
                self.flag(eqn, "static slice selects a sub-range of the "
                               "object axis")
            out(t0 - bad)
        elif name == "squeeze":
            sq = set(eqn.params.get("dimensions", ()))
            out(frozenset(d - sum(1 for s in sq if s < d)
                          for d in t0 if d not in sq))
        elif name == "transpose":
            perm = list(eqn.params.get("permutation", ()))
            out(frozenset(perm.index(d) for d in t0 if d in perm))
        elif name == "broadcast_in_dim":
            bcd = list(eqn.params.get("broadcast_dimensions", ()))
            out(frozenset(bcd[d] for d in t0 if d < len(bcd)))
        elif name == "reshape":
            out(self._reshape(eqn, t0, v0))
        elif name == "dynamic_slice":
            self._dynamic_slice(eqn, dims, routed, t0, out)
        elif name == "dynamic_update_slice":
            self._dynamic_update(eqn, dims, routed, t0, out)
        elif name == "gather":
            self._gather(eqn, dims, routed, t0, out)
        elif name in _SCATTER_PRIMS:
            self._scatter(eqn, dims, routed, t0, out)
        elif name == "dot_general":
            self._dot(eqn, in_dims, out)
        elif name == "top_k":
            shp = self._shape(v0)
            last = len(shp) - 1
            if last in t0 and shp[last] > 1:
                self.flag(eqn, "top_k selects across the object axis")
            out(t0 - {last})
        elif name == "iota":
            out(frozenset())
        else:
            # elementwise family (add/mul/select_n/convert/bitwise/
            # compare/...): operands are scalar, output-shaped, or
            # rank-equal with degenerate (size-1) broadcast dims — dim
            # taint unions positionally either way (a broadcast
            # singleton's taint rides its dim index unchanged)
            oshape = self._shape(eqn.outvars[0])
            shapes = [self._shape(v) for v in eqn.invars]
            if all(s == oshape or s == ()
                   or (len(s) == len(oshape)
                       and all(x == y or x == 1
                               for x, y in zip(s, oshape)))
                   for s in shapes):
                out(frozenset().union(*in_dims))
            else:
                self.unknown.add(name)
                out(frozenset())

    # -- structured handlers ------------------------------------------------

    def _reshape(self, eqn, t0, v0) -> frozenset:
        a = list(self._shape(v0))
        b = list(self._shape(eqn.outvars[0]))
        # inserting/removing/moving size-1 dims can't mix objects: when
        # the nontrivial extents line up positionally, map them through
        # (a tainted singleton just drops — one row has nothing to leak)
        nta = [d for d in range(len(a)) if a[d] != 1]
        ntb = [d for d in range(len(b)) if b[d] != 1]
        if [a[d] for d in nta] == [b[d] for d in ntb]:
            return frozenset(ntb[nta.index(d)] for d in t0 if d in nta)
        mapped: dict = {}
        folded: set = set()
        i = j = 0
        while i < len(a) and j < len(b):
            ai, bj = [i], [j]
            pa, pb = a[i], b[j]
            i += 1
            j += 1
            while pa != pb:
                if pa < pb:
                    pa *= a[i]
                    ai.append(i)
                    i += 1
                else:
                    pb *= b[j]
                    bj.append(j)
                    j += 1
            if len(ai) == 1 and len(bj) == 1:
                mapped[ai[0]] = bj[0]
            else:
                folded.update(ai)
        folded.update(range(i, len(a)))  # trailing unmatched (size-1)
        hit = {d for d in t0 if d in folded and d < len(a) and a[d] > 1}
        if hit:
            self.flag(eqn, "reshape folds the object axis into/out of "
                           "other dims")
        return frozenset(mapped[d] for d in t0 if d in mapped)

    def _dynamic_slice(self, eqn, dims, routed, t0, out) -> None:
        sizes = eqn.params.get("slice_sizes", ())
        operand = eqn.invars[0]
        starts = eqn.invars[1:]
        shp = self._shape(operand)
        kept = set(t0)
        for d in sorted(t0):
            if d < len(sizes) and sizes[d] < shp[d] and shp[d] > 1:
                kept.discard(d)
                idx_ok = (d < len(starts)
                          and self._routed(routed, starts[d]))
                if not idx_ok:
                    self.flag(eqn, "dynamic_slice selects along the "
                                   "object axis with a non-routed start")
        out(frozenset(kept))

    def _dynamic_update(self, eqn, dims, routed, t0, out) -> None:
        operand, update = eqn.invars[0], eqn.invars[1]
        starts = eqn.invars[2:]
        oshp, ushp = self._shape(operand), self._shape(update)
        for d in sorted(t0):
            if (d < len(ushp) and ushp[d] < oshp[d] and oshp[d] > 1
                    and not (d < len(starts)
                             and self._routed(routed, starts[d]))):
                self.flag(eqn, "dynamic_update_slice writes along the "
                               "object axis at a non-routed offset")
        out(t0)

    def _gather(self, eqn, dims, routed, t0, out) -> None:
        dn = eqn.params.get("dimension_numbers")
        sizes = eqn.params.get("slice_sizes", ())
        operand, indices = eqn.invars[0], eqn.invars[1]
        shp = self._shape(operand)
        ishp = self._shape(indices)
        collapsed = set(getattr(dn, "collapsed_slice_dims", ()))
        offset = list(getattr(dn, "offset_dims", ()))
        ob = list(getattr(dn, "operand_batching_dims", ()) or ())
        ib = list(getattr(dn, "start_indices_batching_dims", ()) or ())
        out_rank = len(self._shape(eqn.outvars[0]))
        batch_out = [p for p in range(out_rank) if p not in offset]
        ivd = len(ishp) - 1  # lax fixes index_vector_dim last
        noncollapsed = [d for d in range(len(shp))
                        if d not in collapsed and d not in ob]
        taint = set()
        for d in sorted(t0):
            if d in ob:
                # operand batching dim (take_along_axis & friends):
                # element-aligned with the matching indices dim — the
                # object rows never cross, the taint rides through
                b = ib[ob.index(d)] if ob.index(d) < len(ib) else None
                if b is not None and b < ivd and b < len(batch_out):
                    taint.add(batch_out[b])
                continue
            full = d < len(sizes) and sizes[d] == shp[d]
            if full and d in noncollapsed:
                k = noncollapsed.index(d)
                if k < len(offset):
                    taint.add(offset[k])
            elif shp[d] > 1 and not self._routed(routed, indices):
                self.flag(eqn, "gather indexes the object axis with "
                               "non-routed indices")
        out(frozenset(taint))

    def _scatter(self, eqn, dims, routed, t0, out) -> None:
        dn = eqn.params.get("dimension_numbers")
        operand, indices = eqn.invars[0], eqn.invars[1]
        shp = self._shape(operand)
        sdims = set(getattr(dn, "scatter_dims_to_operand_dims", ()))
        for d in sorted(t0):
            if d in sdims and shp[d] > 1 \
                    and not self._routed(routed, indices):
                self.flag(eqn, f"{eqn.primitive.name} writes the object "
                               "axis through non-routed indices")
        out(t0)  # output aliases the operand's layout

    def _dot(self, eqn, in_dims, out) -> None:
        ((lc, rc), (lb, rb)) = eqn.params["dimension_numbers"]
        lhs, rhs = eqn.invars[0], eqn.invars[1]
        lshp, rshp = self._shape(lhs), self._shape(rhs)
        taint = set()
        for d in in_dims[0]:
            if d in lc:
                if lshp[d] > 1:
                    self.flag(eqn, "dot_general contracts the object axis")
            elif d in lb:
                taint.add(list(lb).index(d))
            else:
                free = [x for x in range(len(lshp))
                        if x not in lc and x not in lb]
                taint.add(len(lb) + free.index(d))
        nlfree = len(lshp) - len(lc) - len(lb)
        for d in in_dims[1] if len(in_dims) > 1 else ():
            if d in rc:
                if rshp[d] > 1:
                    self.flag(eqn, "dot_general contracts the object axis")
            elif d in rb:
                taint.add(list(rb).index(d))
            else:
                free = [x for x in range(len(rshp))
                        if x not in rc and x not in rb]
                taint.add(len(rb) + nlfree + free.index(d))
        out(frozenset(taint))

    # -- control flow -------------------------------------------------------

    @staticmethod
    def _inner(obj):
        return getattr(obj, "jaxpr", obj)

    def _run_inner(self, inner, in_dims, in_routed):
        inner = self._inner(inner)
        sub_dims: dict = {}
        sub_routed: set = set()
        for v, d in zip(inner.invars, in_dims):
            if d:
                sub_dims[v] = frozenset(d)
        for v, r in zip(inner.invars, in_routed):
            if r:
                sub_routed.add(v)
        self._eval(inner, sub_dims, sub_routed)
        return ([self._get(sub_dims, ov) for ov in inner.outvars],
                [self._routed(sub_routed, ov) for ov in inner.outvars])

    def _recurse(self, eqn, dims, routed, in_dims, in_routed) -> None:
        from .jaxpr_rules import _sub_jaxprs

        subs = _sub_jaxprs(eqn)
        inner = self._inner(subs[0]) if subs else None
        if inner is None or len(inner.invars) != len(eqn.invars):
            # arity mismatch (hidden consts): conservative same-shape
            self._set_out(eqn, dims, routed, frozenset(), in_routed)
            if any(in_dims):
                self.unknown.add(eqn.primitive.name)
            return
        routes = [self._routed(routed, v) for v in eqn.invars]
        out_dims, out_routed = self._run_inner(inner, in_dims, routes)
        for ov, t, r in zip(eqn.outvars, out_dims, out_routed):
            t = frozenset(d for d in t if d < len(self._shape(ov)))
            if t:
                dims[ov] = t
            if r or in_routed:
                routed.add(ov)

    def _while(self, eqn, dims, routed, in_dims, in_routed) -> None:
        cn = eqn.params.get("cond_nconsts", 0)
        bn = eqn.params.get("body_nconsts", 0)
        body = self._inner(eqn.params["body_jaxpr"])
        consts_d = in_dims[cn:cn + bn]
        carry_d = in_dims[cn + bn:]
        routes = [self._routed(routed, v) for v in eqn.invars]
        carry_r = routes[cn + bn:]
        for _ in range(2):  # taint fixpoint over the carry
            out_d, out_r = self._run_inner(
                body, consts_d + carry_d,
                routes[cn:cn + bn] + carry_r)
            new_d = [a | b for a, b in zip(carry_d, out_d)]
            new_r = [a or b for a, b in zip(carry_r, out_r)]
            if new_d == carry_d and new_r == carry_r:
                break
            carry_d, carry_r = new_d, new_r
        for ov, t, r in zip(eqn.outvars, carry_d, carry_r):
            t = frozenset(d for d in t if d < len(self._shape(ov)))
            if t:
                dims[ov] = t
            if r or in_routed:
                routed.add(ov)

    def _scan(self, eqn, dims, routed, in_dims, in_routed) -> None:
        nc = eqn.params.get("num_consts", 0)
        ncar = eqn.params.get("num_carry", 0)
        body = self._inner(eqn.params["jaxpr"])
        routes = [self._routed(routed, v) for v in eqn.invars]
        consts_d = in_dims[:nc]
        carry_d = list(in_dims[nc:nc + ncar])
        xs_d = []
        for v, t in zip(eqn.invars[nc + ncar:], in_dims[nc + ncar:]):
            if 0 in t and self._shape(v)[0] > 1:
                self.flag(eqn, "scan iterates over the object axis with "
                               "a sequential carry")
            xs_d.append(frozenset(d - 1 for d in t if d > 0))
        carry_r = routes[nc:nc + ncar]
        xs_r = routes[nc + ncar:]
        out_d = out_r = None
        for _ in range(2):
            out_d, out_r = self._run_inner(
                body, consts_d + carry_d + xs_d,
                routes[:nc] + carry_r + xs_r)
            new_d = [a | b for a, b in zip(carry_d, out_d[:ncar])]
            new_r = [a or b for a, b in zip(carry_r, out_r[:ncar])]
            if new_d == carry_d and new_r == carry_r:
                break
            carry_d, carry_r = new_d, new_r
        ys_d = [frozenset(d + 1 for d in t) for t in out_d[ncar:]]
        final_d = carry_d + ys_d
        final_r = carry_r + out_r[ncar:]
        for ov, t, r in zip(eqn.outvars, final_d, final_r):
            t = frozenset(d for d in t if d < len(self._shape(ov)))
            if t:
                dims[ov] = t
            if r or in_routed:
                routed.add(ov)

    def _cond(self, eqn, dims, routed, in_dims, in_routed) -> None:
        branches = eqn.params.get("branches", ())
        routes = [self._routed(routed, v) for v in eqn.invars]
        acc_d = acc_r = None
        for br in branches:
            out_d, out_r = self._run_inner(br, in_dims[1:], routes[1:])
            if acc_d is None:
                acc_d, acc_r = list(out_d), list(out_r)
            else:
                acc_d = [a | b for a, b in zip(acc_d, out_d)]
                acc_r = [a or b for a, b in zip(acc_r, out_r)]
        for ov, t, r in zip(eqn.outvars, acc_d or [], acc_r or []):
            t = frozenset(d for d in t if d < len(self._shape(ov)))
            if t:
                dims[ov] = t
            if r or in_routed:
                routed.add(ov)


# ---------------------------------------------------------------------------
# per-spec checking
# ---------------------------------------------------------------------------


def _resolve_obj(contract: ShardContract, leaves) -> Dict[int, int]:
    """Flattened-leaf index -> object-axis dim, for one case's args."""
    out: Dict[int, int] = {}
    for leaf, axis in contract.obj:
        if leaf == ALL_LEAVES:
            for i, x in enumerate(leaves):
                if len(x.shape) > axis:
                    out[i] = axis
        elif isinstance(leaf, int) and leaf < len(leaves) \
                and len(leaves[leaf].shape) > axis:
            out[leaf] = axis
    return out


def _shard_args(args, obj_axes: Dict[int, int], s: int):
    """The args re-shaped to their per-shard extents under an abstract
    ``Mesh(("objects", s))`` — exactly the operand shapes a shard_map
    body sees, without needing s physical devices."""
    import jax
    from jax.sharding import AbstractMesh, NamedSharding, PartitionSpec

    mesh = AbstractMesh((("objects", s),))
    leaves, treedef = jax.tree_util.tree_flatten(args)
    out = []
    for i, leaf in enumerate(leaves):
        ax = obj_axes.get(i)
        if ax is None:
            out.append(leaf)
            continue
        spec = [None] * len(leaf.shape)
        spec[ax] = "objects"
        shard = NamedSharding(mesh, PartitionSpec(*spec)).shard_shape(
            tuple(leaf.shape))
        out.append(jax.ShapeDtypeStruct(shard, leaf.dtype))
    return jax.tree_util.tree_unflatten(treedef, out)


def _loc_for(spec, eqn, files_by_rel, root):
    loc = _eqn_loc(eqn, root) if eqn is not None else None
    if loc is not None:
        return loc
    return spec.path, _site_line(spec, files_by_rel)


def _check_spec(spec: KernelSpec, cases, files_by_rel: dict, root: str,
                report: ShardReport) -> List[Finding]:
    import jax

    c = spec.sharding
    findings: List[Finding] = []
    seen: set = set()
    found_coll: Dict[str, tuple] = {}  # collective -> anchor loc
    keys_by_s: Dict[int, set] = {}
    sc04_seen: set = set()
    unknown: Set[str] = set()
    opaque = False

    def analyze(closed, case, leaves, obj_axes, rung):
        nonlocal opaque
        report.cases += 1
        for eqn, _ in _walk(closed.jaxpr):
            coll = _COLLECTIVE_BY_PRIM.get(eqn.primitive.name)
            if coll is not None and coll not in found_coll:
                found_coll[coll] = _loc_for(spec, eqn, files_by_rel, root)
        if c.sclass != "pointwise":
            return
        invars = closed.jaxpr.invars
        if len(invars) != len(leaves):
            report.trace_errors.append(
                f"{spec.name} [{rung}]: {len(leaves)} arg leaves but "
                f"{len(invars)} jaxpr invars — contract leaf indices "
                "cannot be aligned")
            return

        def flag(eqn, what):
            loc = _loc_for(spec, eqn, files_by_rel, root)
            key = ("SC01", loc, what)
            if key in seen:
                return
            seen.add(key)
            findings.append(Finding(
                "SC01", loc[0], loc[1], 0,
                f"kernel {spec.name} [{rung}]: {what} — cross-object "
                "data flow in a pointwise-declared kernel: shard-local "
                "execution would need another shard's rows; declare a "
                "reduction contract with its collective, declare the "
                "index operand routed, or fix the kernel",
            ))

        prov = _Prov(flag, unknown)
        in_dims = [frozenset({obj_axes[i]}) if i in obj_axes
                   else frozenset() for i in range(len(leaves))]
        in_routed = [i in c.routed for i in range(len(leaves))]
        prov.run(closed.jaxpr, in_dims, in_routed)
        opaque = opaque or prov.opaque

    for case in cases:
        leaves = jax.tree_util.tree_leaves(case.args)
        obj_axes = _resolve_obj(c, leaves)

        # SC04: ragged shards, pure arithmetic on the declared ladder
        for s in c.mesh_sizes:
            if s == 1:
                continue
            for i, ax in sorted(obj_axes.items()):
                size = leaves[i].shape[ax]
                if size < s * c.granule:
                    continue  # below one granule per shard: stays dense
                if size % s == 0 and (size // s) % c.granule == 0:
                    continue
                key = (case.rung, s)
                if key in sc04_seen:
                    continue
                sc04_seen.add(key)
                findings.append(Finding(
                    "SC04", spec.path, _site_line(spec, files_by_rel), 0,
                    f"kernel {spec.name} [{case.rung}]: object-axis "
                    f"extent {size} (arg leaf {i}, dim {ax}) does not "
                    f"shard evenly over mesh size {s} (granule "
                    f"{c.granule}) — a ragged shard gives one device a "
                    "different program shape than its peers; pad the "
                    "rung or restrict the contract's mesh_sizes",
                ))

        try:
            closed = jax.make_jaxpr(case.fn)(*case.args)
        except Exception as e:
            report.trace_errors.append(
                f"{spec.name} [{case.rung}]: {type(e).__name__}: {e}")
            continue
        analyze(closed, case, leaves, obj_axes, case.rung)

        # mesh-shaped cases: the shard-local program at the declared
        # mesh sizes (pointwise only: its statics never bind the object
        # extent — a reduction kernel's factory rebinds per shard).
        # SC05's lowering keys are pure shape arithmetic, counted at
        # EVERY valid size; the jaxpr itself is traced once per case at
        # the largest valid size (extents never change the primitive
        # structure, only the budget counts care about each size)
        if c.sclass != "pointwise" or not obj_axes:
            continue
        valid = [s for s in c.mesh_sizes
                 if s > 1 and all(
                     leaves[i].shape[ax] % s == 0
                     and leaves[i].shape[ax] >= s * c.granule
                     and (leaves[i].shape[ax] // s) % c.granule == 0
                     for i, ax in obj_axes.items())]
        for s in valid:
            keys_by_s.setdefault(s, set()).add(
                (case.key, _flat_avals(_shard_args(case.args,
                                                   obj_axes, s))))
        if not valid:
            continue  # SC04 already spoke, or the rung stays dense
        s = max(valid)
        sliced = _shard_args(case.args, obj_axes, s)
        try:
            closed_s = jax.make_jaxpr(case.fn)(*sliced)
        except Exception as e:
            report.trace_errors.append(
                f"{spec.name} [{case.rung}.mesh{s}]: "
                f"{type(e).__name__}: {e} — the kernel's statics "
                "bind the object extent; it cannot trace at shard "
                "shapes")
            continue
        report.mesh_cases += 1
        sliced_leaves = jax.tree_util.tree_leaves(sliced)
        analyze(closed_s, case, sliced_leaves, obj_axes,
                f"{case.rung}.mesh{s}")

    # SC05: per-mesh-size lowering budget
    for s, keys in sorted(keys_by_s.items()):
        if len(keys) > spec.compile_budget:
            findings.append(Finding(
                "SC05", spec.path, _site_line(spec, files_by_rel), 0,
                f"kernel {spec.name}: {len(keys)} distinct lowerings at "
                f"mesh size {s} (budget {spec.compile_budget}) — every "
                "shard recompiles that many times on the regrow path; "
                "the jit cache keys on more than the capacity rungs",
            ))

    # SC02: the collective contract
    declared = set(c.collectives)
    found = set(found_coll)
    report.collectives[spec.name] = sorted(found)
    extra = found - declared
    missing = declared - found
    if extra:
        prim = sorted(extra)[0]
        loc = found_coll[prim]
        findings.append(Finding(
            "SC02", loc[0], loc[1], 0,
            f"kernel {spec.name}: lowers undeclared collective(s) "
            f"{sorted(extra)} (declared: {sorted(declared) or 'none'}, "
            f"class {c.sclass!r}) — an undeclared collective is a "
            "hidden cross-shard dependency; declare it on the "
            "reduction contract or remove it from the kernel",
        ))
    if missing:
        findings.append(Finding(
            "SC02", spec.path, _site_line(spec, files_by_rel), 0,
            f"kernel {spec.name}: declares collective(s) "
            f"{sorted(missing)} the traced jaxpr never lowers — a "
            "stale contract hides the cross-shard cost model; fix the "
            "declaration",
        ))

    if unknown:
        for u in sorted(unknown):
            if u not in report.unknown_prims:
                report.unknown_prims.append(u)
    if opaque and spec.name not in report.opaque:
        report.opaque.append(spec.name)
    return findings


# ---------------------------------------------------------------------------
# SC03: host round-trips on kernel outputs (AST tier, tracer.py style)
# ---------------------------------------------------------------------------

_HOST_COERCIONS = {"int", "float"}
_NP_MODULES = {"np", "numpy"}
_NP_FUNCS = {"asarray", "array"}


def _np_converter(func: ast.AST) -> bool:
    return (isinstance(func, ast.Attribute)
            and func.attr in _NP_FUNCS
            and isinstance(func.value, ast.Name)
            and func.value.id in _NP_MODULES)


def _base_name(node: ast.AST) -> Optional[ast.AST]:
    while isinstance(node, ast.Subscript):
        node = node.value
    return node


def check_host_roundtrips(files: Sequence[ParsedFile],
                          specs: Sequence[KernelSpec]) -> List[Finding]:
    """SC03, fully lexical (the tracer.py discipline): inside the mesh
    hot-path packages, a local bound from a jitted-kernel call that
    flows into ``int()``/``float()``/``.item()``/``np.asarray()`` is a
    host round-trip — on a sharded fleet, a device sync plus a
    cross-shard gather per call.  Deliberate sample points (the
    occupancy observatory's six-int fetch) carry pragmas with their
    cadence as the justification."""
    by_path: Dict[str, set] = {}
    for s in specs:
        by_path.setdefault(s.path, set()).add(s.jit_name.split(".")[0])
    findings: List[Finding] = []
    for pf in files:
        if not pf.rel.startswith(SC03_SCOPE):
            continue
        producers = {site.name.split(".")[0]
                     for site in iter_jit_sites(pf.tree)}
        producers |= by_path.get(pf.rel, set())
        producers.discard("<lambda>")
        if not producers:
            continue
        for fn in ast.walk(pf.tree):
            if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                findings.extend(_scan_fn(pf, fn, producers))
    return findings


def _scan_fn(pf: ParsedFile, fn: ast.AST, producers: set) -> List[Finding]:
    def is_producer_call(node) -> bool:
        if not isinstance(node, ast.Call):
            return False
        f = node.func
        name = f.attr if isinstance(f, ast.Attribute) else \
            f.id if isinstance(f, ast.Name) else ""
        return name in producers

    # pass 1: taint locals bound (transitively) from producer calls;
    # two sweeps approximate a fixpoint over lexical order
    tainted: set = set()
    for _ in range(2):
        for node in ast.walk(fn):
            if not isinstance(node, ast.Assign):
                continue
            val = _base_name(node.value)
            src_tainted = (is_producer_call(val)
                           or (isinstance(val, ast.Name)
                               and val.id in tainted))
            if not src_tainted:
                continue
            for tgt in node.targets:
                tgts = tgt.elts if isinstance(tgt, (ast.Tuple, ast.List)) \
                    else [tgt]
                for t in tgts:
                    if isinstance(t, ast.Name):
                        tainted.add(t.id)

    def device_value(node) -> bool:
        base = _base_name(node)
        return (is_producer_call(base)
                or (isinstance(base, ast.Name) and base.id in tainted))

    out: List[Finding] = []
    emitted: set = set()

    def emit(node, conv):
        key = (node.lineno, conv)
        if key in emitted:
            return
        emitted.add(key)
        out.append(Finding(
            "SC03", pf.rel, node.lineno, node.col_offset,
            f"host round-trip: {conv} materializes a jitted kernel's "
            "output on the host inside a mesh hot path — on a sharded "
            "fleet this is a device sync + cross-shard gather per "
            "call; keep the value on device, fold the read into the "
            "kernel, or pragma the deliberate sample point with its "
            "cadence",
        ))

    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if isinstance(f, ast.Name) and f.id in _HOST_COERCIONS:
            if node.args and device_value(node.args[0]):
                emit(node, f"{f.id}()")
        elif _np_converter(f):
            if node.args and device_value(node.args[0]):
                emit(node, f"np.{f.attr}()")
        elif isinstance(f, ast.Attribute) and f.attr == "item" \
                and not node.args:
            if device_value(f.value):
                emit(node, ".item()")
    return out


# ---------------------------------------------------------------------------
# the runner
# ---------------------------------------------------------------------------


def run_shardcheck(specs: Optional[Sequence[KernelSpec]] = None,
                   baseline: Optional[Baseline] = None,
                   root: Optional[str] = None,
                   ) -> tuple:
    """Trace every manifested kernel against its sharding contract.

    Returns ``(LintResult, ShardReport)``.  Triage mirrors
    kernelcheck's: pragma at the finding's line, then the baseline,
    everything else live — plus the stale-sanction re-flag: an SC
    pragma that suppressed nothing this run is itself a live finding.
    """
    t0 = time.perf_counter()
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    from ..config import enable_x64

    enable_x64()  # the batch package's import-time contract

    if specs is None:
        specs = MANIFEST
    root = root or repo_root()
    report = ShardReport(kernels=len(specs))

    paths = sorted({s.path for s in specs})
    files, parse_errors = load_files(
        [os.path.join(root, p) for p in paths], root=root)
    files_by_rel = {f.rel: f for f in files}

    raw: List[Finding] = []
    for spec in specs:
        c = spec.sharding
        if c is None:
            report.skipped.append({
                "kernel": spec.name,
                "reason": "no sharding contract (the kernel-manifest "
                          "tier-1 rule flags this)"})
            continue
        report.contracts[c.sclass] = report.contracts.get(c.sclass, 0) + 1
        if spec.build is None or c.sclass == "host_only":
            report.skipped.append({
                "kernel": spec.name,
                "reason": c.reason or spec.notrace_reason or c.sclass})
            continue
        try:
            cases = spec.build()
        except Exception as e:
            report.trace_errors.append(
                f"{spec.name} [build]: {type(e).__name__}: {e}")
            continue
        report.traced += 1
        raw.extend(_check_spec(spec, cases, files_by_rel, root, report))

    # SC03 scans the whole hot-path scope, not just kernel-owning files
    sc03_paths = []
    for prefix in SC03_SCOPE:
        base = os.path.join(root, prefix)
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = sorted(
                d for d in dirnames if d != "__pycache__")
            for fname in sorted(filenames):
                if fname.endswith(".py"):
                    sc03_paths.append(os.path.join(dirpath, fname))
    sc03_files, sc03_errors = load_files(sc03_paths, root=root)
    parse_errors += sc03_errors
    report.sc03_files = len(sc03_files)
    for pf in sc03_files:
        files_by_rel.setdefault(pf.rel, pf)
    raw.extend(check_host_roundtrips(sc03_files, specs))

    # findings anchor at equation user frames, which may live in helper
    # modules (ops/, gc/) that own no jit site — load those too so their
    # pragmas are honored
    missing = sorted({f.path for f in raw} - set(files_by_rel))
    if missing:
        extra, extra_errors = load_files(
            [os.path.join(root, p) for p in missing], root=root)
        parse_errors += extra_errors
        for pf in extra:
            files_by_rel.setdefault(pf.rel, pf)

    # triage: pragmas, then baseline — the crdtlint machinery verbatim
    live: List[Finding] = []
    suppressed: List[Finding] = []
    baselined: List[Finding] = []
    for f in raw:
        pf = files_by_rel.get(f.path)
        if pf is not None and pf.suppressed(f.rule, f.line):
            suppressed.append(f)
        elif baseline is not None and baseline.covers(f):
            baselined.append(f)
        else:
            live.append(f)

    # the stale-sanction screw (KC01 discipline, generalized): an SC
    # pragma that suppressed nothing this run means the contract now
    # traces clean — the sanction must come off so the check re-arms.
    # A pragma is only judged where its rule actually RAN this pass:
    # SC03 in the scanned hot-path set, the trace rules in
    # kernel-owning or finding-anchored files — a subset run (fixture
    # specs) must not re-flag the rest of the tree's sanctions
    used = {(f.rule, f.path, f.line) for f in suppressed}
    spec_paths = {s.path for s in specs}
    sc03_rels = {pf.rel for pf in sc03_files}
    anchored = set(missing)
    for pf in files_by_rel.values():
        for line, rules in sorted(pf._line_pragmas.items()):
            for r in sorted(rules):
                if r not in SHARD_RULES or (r, pf.rel, line) in used:
                    continue
                if r == "SC03":
                    if pf.rel not in sc03_rels:
                        continue
                elif pf.rel not in spec_paths and pf.rel not in anchored:
                    continue
                live.append(Finding(
                    r, pf.rel, line, 0,
                    f"stale {r} sanction: a pragma suppresses a "
                    f"{r} finding here, but the kernel's sharding "
                    "contract traces clean on this tree — remove "
                    "the pragma so the check re-arms",
                ))

    live.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    result = LintResult(
        findings=live,
        suppressed=suppressed,
        baselined=baselined,
        stale_baseline=baseline.stale_entries() if baseline else [],
        files=len(files_by_rel),
        parse_errors=parse_errors + report.trace_errors,
    )
    report.elapsed_s = round(time.perf_counter() - t0, 3)
    return result, report
