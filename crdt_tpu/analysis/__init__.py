"""crdtlint — AST-based static analysis for this repo's contracts.

PR 3's HIGH-severity review finding — a counter and a histogram sharing
the ``executor.regrow`` name, crashing executor recovery at runtime —
is fully decidable from the source text.  This package moves that bug
class (and three more like it) from "runtime surprise" to "CI failure":

* :mod:`~crdt_tpu.analysis.telemetry` — every metric name declared
  anywhere in the tree, cross-checked for type collisions and against
  the documented namespace manifest
  (:mod:`crdt_tpu.obs.namespace`).
* :mod:`~crdt_tpu.analysis.locks` — Eraser-style lockset discipline for
  the threaded modules: attributes written both inside and outside
  ``with self._lock``, unlocked read-modify-writes, acquisition-order
  deadlock cycles in the lexical lock-order graph, and blocking
  syscalls (fsync, sleep, socket I/O) made under a held lock.
* :mod:`~crdt_tpu.analysis.tracer` — jax tracer hygiene: host coercion
  of traced values inside jit-decorated functions, int64 flowing into
  the Pallas modules (the jax-0.4.x Mosaic-skew class), dict-iteration
  order feeding jit inputs.
* :mod:`~crdt_tpu.analysis.wire` — the wire/sync error contract: decode
  paths raise :class:`~crdt_tpu.error.CrdtError` subclasses, never bare
  ``ValueError``; no swallowing ``except Exception``; every
  ``from_wire``/``to_wire`` leg feeds ``record_wire``.

Run it: ``python -m crdt_tpu.analysis`` (or ``scripts/crdtlint.py``);
``--json`` for machine output.  Suppress one finding with a
``# crdtlint: disable=RULE`` pragma on the flagged line; park a known
finding in ``crdt_tpu/analysis/baseline.json`` with a justification.
Stdlib-only by hard contract: the lint never imports jax, numpy, or any
module that does (``tests/test_analysis.py`` pins this), so it runs in
<5 s on a box with no accelerator stack at all.

Two deeper tiers share the pragma/baseline/exit-code machinery but DO
import jax (CPU-pinned, abstract tracing only):

* kernelcheck (``--kernels``, rules KC01-KC05,
  :mod:`~crdt_tpu.analysis.jaxpr_rules`) — traces every manifested
  kernel and lints the jaxprs: Mosaic dtype lowering, scatter
  determinism, baked consts, recompile budgets, hidden callbacks.
* shardcheck (``--shard``, rules SC01-SC05,
  :mod:`~crdt_tpu.analysis.shard_rules`) — verifies each kernel's
  declared object-axis :class:`~crdt_tpu.analysis.kernels.
  ShardContract` by re-tracing under abstract object meshes: no
  cross-object data flow in pointwise kernels, collectives lowered
  exactly as declared, no host round-trips on the mesh hot path, even
  shard divisibility, per-shard compile budgets.
"""

from .core import (  # noqa: F401
    Baseline,
    Finding,
    LintResult,
    ParsedFile,
    default_targets,
    load_files,
    run_lint,
)

__all__ = [
    "Baseline",
    "Finding",
    "LintResult",
    "ParsedFile",
    "default_targets",
    "load_files",
    "run_lint",
]
