"""``python -m crdt_tpu.analysis`` — run crdtlint over the tree.

Exit codes: 0 clean (live findings all pragma'd or baselined), 1 live
findings or parse errors, 2 usage error.  ``--json`` emits the full
machine-readable result on stdout (what ``tests/test_analysis.py`` and
CI consume); the default human output is one ``path:line:col: rule:
message`` line per finding, grep- and editor-jumpable.

The default (AST) tier never imports jax/numpy — it must run (fast) on
boxes with no accelerator stack, and tier-1 budgets the whole run under
5 seconds.  ``--kernels`` runs the SECOND tier instead: kernelcheck
(:mod:`crdt_tpu.analysis.jaxpr_rules`) imports jax under
``JAX_PLATFORMS=cpu``, traces every manifested kernel abstractly and
lints the jaxprs (KC01-KC05); same exit codes, same ``--json`` shape
plus a ``kernelcheck`` stats block, same baseline file.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

from .core import (
    Baseline, default_targets, load_files, repo_root, rule_names, run_lint,
)

DEFAULT_BASELINE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "baseline.json")


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="crdtlint",
        description="AST-based static analysis for crdt_tpu contracts "
                    "(telemetry namespaces, lock discipline, tracer "
                    "hygiene, wire error contracts)",
    )
    parser.add_argument("paths", nargs="*",
                        help="files/directories to lint (default: the "
                             "whole repo except tests/)")
    parser.add_argument("--json", action="store_true", dest="as_json",
                        help="machine-readable output")
    parser.add_argument("--baseline", default=DEFAULT_BASELINE,
                        help="baseline JSON (default: the shipped "
                             "crdt_tpu/analysis/baseline.json)")
    parser.add_argument("--no-baseline", action="store_true",
                        help="ignore the baseline (audit mode: every "
                             "finding is live)")
    parser.add_argument("--rule", action="append", dest="rules",
                        metavar="RULE",
                        help="run only this rule (repeatable)")
    parser.add_argument("--list-rules", action="store_true",
                        help="print registered rule names and exit")
    parser.add_argument("--kernels", action="store_true",
                        help="run the jaxpr tier (kernelcheck, KC01-KC05) "
                             "instead of the AST lint; imports jax under "
                             "JAX_PLATFORMS=cpu")
    args = parser.parse_args(argv)

    if args.kernels:
        if args.paths or args.rules:
            print("crdtlint: --kernels takes no paths/--rule (the kernel "
                  "manifest defines the scan set)", file=sys.stderr)
            return 2
        return _main_kernels(args)

    if args.list_rules:
        for name in rule_names():
            print(name)
        return 0
    if args.rules:
        unknown = set(args.rules) - set(rule_names())
        if unknown:
            print(f"crdtlint: unknown rule(s): {', '.join(sorted(unknown))}",
                  file=sys.stderr)
            return 2

    t0 = time.perf_counter()
    if args.paths:
        targets = []
        for p in args.paths:
            if os.path.isdir(p):
                targets.extend(default_targets(root=p))
            elif os.path.isfile(p):
                targets.append(p)
            else:
                print(f"crdtlint: no such path: {p}", file=sys.stderr)
                return 2
    else:
        targets = default_targets()

    baseline = None
    if not args.no_baseline and os.path.exists(args.baseline):
        try:
            baseline = Baseline.load(args.baseline)
        except (ValueError, json.JSONDecodeError) as e:
            print(f"crdtlint: bad baseline {args.baseline}: {e}",
                  file=sys.stderr)
            return 2

    files, parse_errors = load_files(targets, root=repo_root())
    result = run_lint(files, baseline=baseline, only_rules=args.rules)
    result.parse_errors = parse_errors
    dt = time.perf_counter() - t0

    if args.as_json:
        out = result.to_json()
        out["elapsed_s"] = round(dt, 3)
        json.dump(out, sys.stdout, indent=2)
        print()
        return 0 if result.ok else 1

    for f in result.findings:
        print(f.render())
    for err in parse_errors:
        print(f"{err} [parse-error]")
    tallies = (
        f"{result.files} files, {len(result.findings)} finding(s), "
        f"{len(result.suppressed)} pragma-suppressed, "
        f"{len(result.baselined)} baselined, {dt:.2f}s"
    )
    if result.stale_baseline:
        print(f"crdtlint: {len(result.stale_baseline)} stale baseline "
              "entr(ies) matched nothing — delete them:", file=sys.stderr)
        for e in result.stale_baseline:
            print(f"  - {e['rule']} @ {e['path']}: {e['message'][:80]}",
                  file=sys.stderr)
    print(("OK: " if result.ok else "FAIL: ") + tallies,
          file=sys.stderr)
    return 0 if result.ok else 1


def _main_kernels(args) -> int:
    """The --kernels tier: trace the manifest, lint the jaxprs."""
    # jax must see the platform pin before first import — kernelcheck
    # is a static analyzer, it never needs (or wants) an accelerator
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    baseline = None
    if not args.no_baseline and os.path.exists(args.baseline):
        try:
            baseline = Baseline.load(args.baseline)
        except (ValueError, json.JSONDecodeError) as e:
            print(f"crdtlint: bad baseline {args.baseline}: {e}",
                  file=sys.stderr)
            return 2

    from .jaxpr_rules import run_kernelcheck

    result, report = run_kernelcheck(baseline=baseline)

    if args.as_json:
        out = result.to_json()
        out["kernelcheck"] = report.to_json()
        out["elapsed_s"] = report.elapsed_s
        json.dump(out, sys.stdout, indent=2)
        print()
        return 0 if result.ok else 1

    for f in result.findings:
        print(f.render())
    for err in result.parse_errors:
        print(f"{err} [trace-error]")
    for sk in report.skipped:
        print(f"kernelcheck: not traced: {sk['kernel']} ({sk['reason']})",
              file=sys.stderr)
    if result.stale_baseline:
        print(f"kernelcheck: {len(result.stale_baseline)} stale baseline "
              "entr(ies) matched nothing — delete them", file=sys.stderr)
    tallies = (
        f"{report.kernels} kernels ({report.traced} traced, "
        f"{report.cases} trace cases, {len(report.skipped)} declared "
        f"no-trace), {len(result.findings)} finding(s), "
        f"{len(result.suppressed)} pragma-suppressed, "
        f"{len(result.baselined)} baselined, {report.elapsed_s:.2f}s"
    )
    print(("OK: " if result.ok else "FAIL: ") + tallies, file=sys.stderr)
    return 0 if result.ok else 1


if __name__ == "__main__":
    sys.exit(main())
