"""``python -m crdt_tpu.analysis`` — run crdtlint over the tree.

Exit codes: 0 clean (live findings all pragma'd or baselined), 1 live
findings or parse errors, 2 usage error.  ``--json`` emits the full
machine-readable result on stdout (what ``tests/test_analysis.py`` and
CI consume); the default human output is one ``path:line:col: rule:
message`` line per finding, grep- and editor-jumpable.

The lint never imports jax/numpy — it must run (fast) on boxes with no
accelerator stack, and tier-1 budgets the whole run under 5 seconds.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

from .core import (
    Baseline, default_targets, load_files, repo_root, rule_names, run_lint,
)

DEFAULT_BASELINE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "baseline.json")


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="crdtlint",
        description="AST-based static analysis for crdt_tpu contracts "
                    "(telemetry namespaces, lock discipline, tracer "
                    "hygiene, wire error contracts)",
    )
    parser.add_argument("paths", nargs="*",
                        help="files/directories to lint (default: the "
                             "whole repo except tests/)")
    parser.add_argument("--json", action="store_true", dest="as_json",
                        help="machine-readable output")
    parser.add_argument("--baseline", default=DEFAULT_BASELINE,
                        help="baseline JSON (default: the shipped "
                             "crdt_tpu/analysis/baseline.json)")
    parser.add_argument("--no-baseline", action="store_true",
                        help="ignore the baseline (audit mode: every "
                             "finding is live)")
    parser.add_argument("--rule", action="append", dest="rules",
                        metavar="RULE",
                        help="run only this rule (repeatable)")
    parser.add_argument("--list-rules", action="store_true",
                        help="print registered rule names and exit")
    args = parser.parse_args(argv)

    if args.list_rules:
        for name in rule_names():
            print(name)
        return 0
    if args.rules:
        unknown = set(args.rules) - set(rule_names())
        if unknown:
            print(f"crdtlint: unknown rule(s): {', '.join(sorted(unknown))}",
                  file=sys.stderr)
            return 2

    t0 = time.perf_counter()
    if args.paths:
        targets = []
        for p in args.paths:
            if os.path.isdir(p):
                targets.extend(default_targets(root=p))
            elif os.path.isfile(p):
                targets.append(p)
            else:
                print(f"crdtlint: no such path: {p}", file=sys.stderr)
                return 2
    else:
        targets = default_targets()

    baseline = None
    if not args.no_baseline and os.path.exists(args.baseline):
        try:
            baseline = Baseline.load(args.baseline)
        except (ValueError, json.JSONDecodeError) as e:
            print(f"crdtlint: bad baseline {args.baseline}: {e}",
                  file=sys.stderr)
            return 2

    files, parse_errors = load_files(targets, root=repo_root())
    result = run_lint(files, baseline=baseline, only_rules=args.rules)
    result.parse_errors = parse_errors
    dt = time.perf_counter() - t0

    if args.as_json:
        out = result.to_json()
        out["elapsed_s"] = round(dt, 3)
        json.dump(out, sys.stdout, indent=2)
        print()
        return 0 if result.ok else 1

    for f in result.findings:
        print(f.render())
    for err in parse_errors:
        print(f"{err} [parse-error]")
    tallies = (
        f"{result.files} files, {len(result.findings)} finding(s), "
        f"{len(result.suppressed)} pragma-suppressed, "
        f"{len(result.baselined)} baselined, {dt:.2f}s"
    )
    if result.stale_baseline:
        print(f"crdtlint: {len(result.stale_baseline)} stale baseline "
              "entr(ies) matched nothing — delete them:", file=sys.stderr)
        for e in result.stale_baseline:
            print(f"  - {e['rule']} @ {e['path']}: {e['message'][:80]}",
                  file=sys.stderr)
    print(("OK: " if result.ok else "FAIL: ") + tallies,
          file=sys.stderr)
    return 0 if result.ok else 1


if __name__ == "__main__":
    sys.exit(main())
