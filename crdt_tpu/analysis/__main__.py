"""``python -m crdt_tpu.analysis`` — run crdtlint over the tree.

Exit codes: 0 clean (live findings all pragma'd or baselined), 1 live
findings or parse errors, 2 usage error.  ``--json`` emits the full
machine-readable result on stdout (what ``tests/test_analysis.py`` and
CI consume); the default human output is one ``path:line:col: rule:
message`` line per finding, grep- and editor-jumpable.

Three tiers, one rule-id range each:

* default (AST) tier — crdtlint proper: stdlib-only by hard contract,
  never imports jax/numpy, runs in <5 s on a box with no accelerator
  stack (rules by name: ``telemetry-*``, ``lock-*``, ``tracer-*``,
  ``wire-*``, ``kernel-manifest``, ...).
* ``--kernels`` — kernelcheck (:mod:`crdt_tpu.analysis.jaxpr_rules`,
  **KC01-KC05**): imports jax under ``JAX_PLATFORMS=cpu``, traces every
  manifested kernel abstractly and lints the jaxprs.
* ``--shard`` — shardcheck (:mod:`crdt_tpu.analysis.shard_rules`,
  **SC01-SC05**): checks every manifested kernel against its declared
  sharding contract (object-axis provenance, collective contracts, host
  round-trips in mesh hot paths, shard divisibility, per-mesh-size
  compile budgets), including mesh-shaped trace cases at sizes
  {1,2,4,8}.

All tiers share exit codes, the ``--json`` shape (plus a per-tier stats
block), the pragma syntax, and the baseline file.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

from .core import (
    Baseline, default_targets, load_files, repo_root, rule_names, run_lint,
)

DEFAULT_BASELINE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "baseline.json")


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="crdtlint",
        description="AST-based static analysis for crdt_tpu contracts "
                    "(telemetry namespaces, lock discipline, tracer "
                    "hygiene, wire error contracts)",
    )
    parser.add_argument("paths", nargs="*",
                        help="files/directories to lint (default: the "
                             "whole repo except tests/)")
    parser.add_argument("--json", action="store_true", dest="as_json",
                        help="machine-readable output")
    parser.add_argument("--baseline", default=DEFAULT_BASELINE,
                        help="baseline JSON (default: the shipped "
                             "crdt_tpu/analysis/baseline.json)")
    parser.add_argument("--no-baseline", action="store_true",
                        help="ignore the baseline (audit mode: every "
                             "finding is live)")
    parser.add_argument("--rule", action="append", dest="rules",
                        metavar="RULE",
                        help="run only this rule (repeatable)")
    parser.add_argument("--list-rules", action="store_true",
                        help="print registered rule names and exit")
    parser.add_argument("--kernels", action="store_true",
                        help="run the jaxpr tier (kernelcheck, KC01-KC05) "
                             "instead of the AST lint; imports jax under "
                             "JAX_PLATFORMS=cpu")
    parser.add_argument("--shard", action="store_true",
                        help="run the sharding-contract tier (shardcheck, "
                             "SC01-SC05) instead of the AST lint; imports "
                             "jax under JAX_PLATFORMS=cpu")
    args = parser.parse_args(argv)

    if args.kernels and args.shard:
        print("crdtlint: --kernels and --shard are separate tiers; pick "
              "one", file=sys.stderr)
        return 2
    if args.kernels or args.shard:
        if args.paths or args.rules:
            flag = "--kernels" if args.kernels else "--shard"
            print(f"crdtlint: {flag} takes no paths/--rule (the kernel "
                  "manifest defines the scan set)", file=sys.stderr)
            return 2
        return _main_kernels(args) if args.kernels else _main_shard(args)

    if args.list_rules:
        for name in rule_names():
            print(name)
        return 0
    if args.rules:
        unknown = set(args.rules) - set(rule_names())
        if unknown:
            print(f"crdtlint: unknown rule(s): {', '.join(sorted(unknown))}",
                  file=sys.stderr)
            return 2

    t0 = time.perf_counter()
    if args.paths:
        targets = []
        for p in args.paths:
            if os.path.isdir(p):
                targets.extend(default_targets(root=p))
            elif os.path.isfile(p):
                targets.append(p)
            else:
                print(f"crdtlint: no such path: {p}", file=sys.stderr)
                return 2
    else:
        targets = default_targets()

    baseline = None
    if not args.no_baseline and os.path.exists(args.baseline):
        try:
            baseline = Baseline.load(args.baseline)
        except (ValueError, json.JSONDecodeError) as e:
            print(f"crdtlint: bad baseline {args.baseline}: {e}",
                  file=sys.stderr)
            return 2

    files, parse_errors = load_files(targets, root=repo_root())
    result = run_lint(files, baseline=baseline, only_rules=args.rules)
    result.parse_errors = parse_errors
    dt = time.perf_counter() - t0

    if args.as_json:
        out = result.to_json()
        out["elapsed_s"] = round(dt, 3)
        json.dump(out, sys.stdout, indent=2)
        print()
        return 0 if result.ok else 1

    for f in result.findings:
        print(f.render())
    for err in parse_errors:
        print(f"{err} [parse-error]")
    tallies = (
        f"{result.files} files, {len(result.findings)} finding(s), "
        f"{len(result.suppressed)} pragma-suppressed, "
        f"{len(result.baselined)} baselined, {dt:.2f}s"
    )
    if result.stale_baseline:
        print(f"crdtlint: {len(result.stale_baseline)} stale baseline "
              "entr(ies) matched nothing — delete them:", file=sys.stderr)
        for e in result.stale_baseline:
            print(f"  - {e['rule']} @ {e['path']}: {e['message'][:80]}",
                  file=sys.stderr)
    print(("OK: " if result.ok else "FAIL: ") + tallies,
          file=sys.stderr)
    return 0 if result.ok else 1


def _main_kernels(args) -> int:
    """The --kernels tier: trace the manifest, lint the jaxprs."""
    # jax must see the platform pin before first import — kernelcheck
    # is a static analyzer, it never needs (or wants) an accelerator
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    baseline = None
    if not args.no_baseline and os.path.exists(args.baseline):
        try:
            baseline = Baseline.load(args.baseline)
        except (ValueError, json.JSONDecodeError) as e:
            print(f"crdtlint: bad baseline {args.baseline}: {e}",
                  file=sys.stderr)
            return 2

    from .jaxpr_rules import run_kernelcheck

    result, report = run_kernelcheck(baseline=baseline)

    if args.as_json:
        out = result.to_json()
        out["kernelcheck"] = report.to_json()
        out["elapsed_s"] = report.elapsed_s
        json.dump(out, sys.stdout, indent=2)
        print()
        return 0 if result.ok else 1

    for f in result.findings:
        print(f.render())
    for err in result.parse_errors:
        print(f"{err} [trace-error]")
    for sk in report.skipped:
        print(f"kernelcheck: not traced: {sk['kernel']} ({sk['reason']})",
              file=sys.stderr)
    if result.stale_baseline:
        print(f"kernelcheck: {len(result.stale_baseline)} stale baseline "
              "entr(ies) matched nothing — delete them", file=sys.stderr)
    tallies = (
        f"{report.kernels} kernels ({report.traced} traced, "
        f"{report.cases} trace cases, {len(report.skipped)} declared "
        f"no-trace), {len(result.findings)} finding(s), "
        f"{len(result.suppressed)} pragma-suppressed, "
        f"{len(result.baselined)} baselined, {report.elapsed_s:.2f}s"
    )
    print(("OK: " if result.ok else "FAIL: ") + tallies, file=sys.stderr)
    return 0 if result.ok else 1


def _main_shard(args) -> int:
    """The --shard tier: trace the manifest against sharding contracts."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    baseline = None
    if not args.no_baseline and os.path.exists(args.baseline):
        try:
            baseline = Baseline.load(args.baseline)
        except (ValueError, json.JSONDecodeError) as e:
            print(f"crdtlint: bad baseline {args.baseline}: {e}",
                  file=sys.stderr)
            return 2

    from .shard_rules import run_shardcheck

    result, report = run_shardcheck(baseline=baseline)

    if args.as_json:
        out = result.to_json()
        out["shardcheck"] = report.to_json()
        out["elapsed_s"] = report.elapsed_s
        json.dump(out, sys.stdout, indent=2)
        print()
        return 0 if result.ok else 1

    for f in result.findings:
        print(f.render())
    for err in result.parse_errors:
        print(f"{err} [trace-error]")
    for sk in report.skipped:
        print(f"shardcheck: not traced: {sk['kernel']} ({sk['reason']})",
              file=sys.stderr)
    if report.unknown_prims:
        print("shardcheck: provenance dropped at primitive(s): "
              + ", ".join(report.unknown_prims), file=sys.stderr)
    if result.stale_baseline:
        print(f"shardcheck: {len(result.stale_baseline)} stale baseline "
              "entr(ies) matched nothing — delete them", file=sys.stderr)
    contracts = ", ".join(f"{k}={v}"
                          for k, v in sorted(report.contracts.items()))
    tallies = (
        f"{report.kernels} kernels ({contracts}; {report.traced} traced, "
        f"{report.cases} cases incl {report.mesh_cases} mesh-shaped, "
        f"{len(report.skipped)} untraced), {len(result.findings)} "
        f"finding(s), {len(result.suppressed)} pragma-suppressed, "
        f"{len(result.baselined)} baselined, {report.elapsed_s:.2f}s"
    )
    print(("OK: " if result.ok else "FAIL: ") + tallies, file=sys.stderr)
    return 0 if result.ok else 1


if __name__ == "__main__":
    sys.exit(main())
