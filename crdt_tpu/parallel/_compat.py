"""JAX API-skew shims for the parallel layer.

``shard_map`` moved twice across the jax versions this repo must run
on: new releases export it at the top level with a ``check_vma``
keyword; 0.4.x ships it under ``jax.experimental.shard_map`` with the
same knob spelled ``check_rep``.  Callers here write the modern
spelling and this shim translates downward, so the collectives code
stays single-source.
"""

from __future__ import annotations

try:  # jax >= 0.6: top-level export, `check_vma` spelling
    from jax import shard_map as _shard_map

    _CHECK_KW = "check_vma"
except ImportError:  # jax 0.4.x: experimental home, `check_rep` spelling
    from jax.experimental.shard_map import shard_map as _shard_map

    _CHECK_KW = "check_rep"


def shard_map(f, **kwargs):
    """``jax.shard_map`` under either API generation; accepts the
    modern ``check_vma`` keyword everywhere."""
    if "check_vma" in kwargs and _CHECK_KW != "check_vma":
        kwargs[_CHECK_KW] = kwargs.pop("check_vma")
    return _shard_map(f, **kwargs)
