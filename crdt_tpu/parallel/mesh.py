"""Mesh construction and batch sharding helpers.

Axis conventions (SURVEY.md §2.3):

* ``"objects"`` — the data-parallel axis: independent CRDT objects shard
  across devices (the analogue of DP; no cross-device traffic for pairwise
  merges).
* ``"replicas"`` — the replica axis: N copies of the *same* objects whose
  global join needs cross-device collectives over ICI (the analogue of a
  comm backend's all-reduce).
"""

from __future__ import annotations

from typing import Dict, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(axes: Dict[str, int] | None = None, devices: Sequence | None = None) -> Mesh:
    """Build a mesh from ``{axis_name: size}``.

    Defaults to a 1-D ``objects`` mesh over all local devices."""
    devices = list(devices) if devices is not None else jax.devices()
    if axes is None:
        axes = {"objects": len(devices)}
    sizes = list(axes.values())
    if int(np.prod(sizes)) != len(devices):
        raise ValueError(f"mesh axes {axes} need {np.prod(sizes)} devices, have {len(devices)}")
    dev_array = np.asarray(devices).reshape(sizes)
    return Mesh(dev_array, tuple(axes.keys()))


def shard_batch(batch, mesh: Mesh, axis: str = "objects"):
    """Shard every array of a batch pytree along its leading (object) axis."""

    def put(x):
        spec = P(axis, *([None] * (x.ndim - 1)))
        return jax.device_put(x, NamedSharding(mesh, spec))

    return jax.tree_util.tree_map(put, batch)


def replicate(batch, mesh: Mesh):
    """Fully replicate a batch pytree over the mesh."""

    def put(x):
        return jax.device_put(x, NamedSharding(mesh, P()))

    return jax.tree_util.tree_map(put, batch)
