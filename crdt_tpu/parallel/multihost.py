"""Multi-host distributed backend: DCN x ICI meshes for global joins.

The reference delegates transport entirely to the user (serialized
state/ops, `/root/reference/src/lib.rs:62-83`) and simulates replicas
in-process (`/root/reference/test/orswot.rs:37-76`); it has no comm
backend at all (SURVEY.md §2.3).  This module is the TPU-native
equivalent of the NCCL/MPI layer a distributed deployment of the
reference would need: the same lattice-join collectives the single-host
mesh runs (``crdt_tpu.parallel.collective``) scaled across hosts and
pod slices, with XLA routing each axis over the right physical tier.

Design (the scaling-book recipe — pick a mesh, annotate, let XLA insert
collectives):

* **``objects`` rides DCN** (the leading, slowest tier): the object
  axis is embarrassingly parallel — distinct CRDT objects never
  exchange data during a join (each object's merge is independent,
  `/root/reference/src/orswot.rs:89-156` is per-object) — so sharding
  it across pod slices puts ZERO join traffic on the slow links; each
  slice anti-entropies its own object partition.
* **``replicas`` rides ICI** (fast intra-slice): the N-way global join
  all-gathers member tables and all-reduce-maxes clock planes across
  the replica axis (``VClock::merge`` ≡ elementwise max,
  `/root/reference/src/vclock.rs:131-137`) — the bandwidth-heavy
  collective stays on the fast tier.

Axis NAMES are unchanged from the single-host convention
(``crdt_tpu.parallel.mesh``), so every collective in
``parallel.collective`` and the ``JoinExecutor`` run over a multi-host
mesh without modification — only the device placement differs.

Single-process fallback: with one process (tests, the judge's virtual
CPU mesh, a dev box), :func:`initialize` is a no-op and
:func:`make_multihost_mesh` degrades to the plain device mesh, so the
same program text runs everywhere — the multi-host path is a launch
configuration, not a code path.

**Interning across hosts.** Dense planes built on different hosts mix
inside a cross-host collective, so the actor/member interning MUST be
deterministic and shared: use ``Universe.identity`` (dense index ==
value; what the native bulk wire codec requires anyway) or distribute
one pre-agreed registry.  Per-host insertion-order registries map
DIFFERENT actors to the SAME dense id and the join silently conflates
them — caught the first time the two-process example ran
(``examples/multihost_cpu.py``; ``tests/test_multihost_mp.py`` pins the
working setup).
"""

from __future__ import annotations

import os
from typing import Dict, Sequence

import numpy as np

__all__ = [
    "initialize",
    "topology",
    "make_multihost_mesh",
    "global_batch_from_local",
    "local_shard",
]


def initialize(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
    **kwargs,
) -> dict:
    """Join (or skip joining) the distributed runtime; return topology.

    Thin, idempotent wrapper over ``jax.distributed.initialize``:

    * explicit args win; otherwise the standard env vars
      (``JAX_COORDINATOR_ADDRESS`` / ``JAX_NUM_PROCESSES`` /
      ``JAX_PROCESS_ID``) or the cluster's autodetection are used;
    * single-process (no coordinator configured anywhere) is a NO-OP —
      the same program runs on a laptop, the judge's virtual CPU mesh,
      or a v5e pod without edits;
    * calling twice is safe (already-initialized is detected, not
      raised).

    Returns :func:`topology` — ``{processes, process_id, devices,
    local_devices}``.
    """
    import jax

    configured = (
        coordinator_address
        or os.environ.get("JAX_COORDINATOR_ADDRESS")
        or kwargs.get("cluster_detection_method")
    )
    if configured:
        try:
            jax.distributed.initialize(
                coordinator_address=coordinator_address,
                num_processes=num_processes,
                process_id=process_id,
                **kwargs,
            )
        except RuntimeError as e:
            if "already initialized" not in str(e).lower():
                raise
    return topology()


def topology() -> dict:
    """The live process/device topology as plain data."""
    import jax

    return {
        "processes": jax.process_count(),
        "process_id": jax.process_index(),
        "devices": len(jax.devices()),
        "local_devices": len(jax.local_devices()),
    }


def make_multihost_mesh(
    ici_axes: Dict[str, int] | None = None,
    dcn_axes: Dict[str, int] | None = None,
    devices: Sequence | None = None,
):
    """Build a mesh whose ``dcn_axes`` span slices/hosts over DCN and
    whose ``ici_axes`` stay inside a slice on ICI.

    ``make_multihost_mesh({"replicas": 4, "objects": 2},
    dcn_axes={"objects_dcn": 2})`` on 2 slices of 8 chips yields a mesh
    with axes ``("objects_dcn", "replicas", "objects")`` — DCN axes
    lead, matching ``mesh_utils.create_hybrid_device_mesh``'s layout
    contract, and collectives over the trailing axes compile to
    ICI-local ops.

    With one process or no ``dcn_axes`` this is exactly
    :func:`crdt_tpu.parallel.mesh.make_mesh` over the merged axes — the
    single-host degenerate case.
    """
    import jax
    from jax.sharding import Mesh

    from .mesh import make_mesh

    ici_axes = dict(ici_axes or {})
    dcn_axes = dict(dcn_axes or {})
    if devices is None:
        devices = jax.devices()

    if not dcn_axes or jax.process_count() == 1:
        merged = {**dcn_axes, **ici_axes} or None
        return make_mesh(merged, devices=devices)

    from jax.experimental import mesh_utils

    # granule choice: TPU pods group by slice_index; CPU multi-process
    # (and single-slice multi-host) have no slice structure, so the
    # process is the DCN granule
    n_slices = len({getattr(d, "slice_index", None) for d in devices})
    dev_array = mesh_utils.create_hybrid_device_mesh(
        list(ici_axes.values()),
        list(dcn_axes.values()),
        devices=devices,
        process_is_granule=(n_slices != int(np.prod(list(dcn_axes.values())))),
    )
    # hybrid layout: DCN dims lead the returned array
    names = tuple(dcn_axes.keys()) + tuple(ici_axes.keys())
    shape = tuple(dcn_axes.values()) + tuple(ici_axes.values())
    return Mesh(dev_array.reshape(shape), names)


def local_shard(n: int, axis_size: int, index: int) -> slice:
    """The half-open object range process ``index`` of ``axis_size``
    owns out of ``n`` objects (even split, remainder to the front)."""
    base, rem = divmod(n, axis_size)
    start = index * base + min(index, rem)
    return slice(start, start + base + (1 if index < rem else 0))


def global_batch_from_local(mesh, batch, axis: str = "objects"):
    """Assemble a globally-sharded batch from per-process local planes.

    Multi-host ingest: each host parses ITS shard of the wire blobs
    (``OrswotBatch.from_wire`` on the host-local slice — the bulk codec
    never crosses hosts) and this stitches the host-local planes into
    one global jax.Array per plane, sharded along ``axis``, without any
    all-gather: ``jax.make_array_from_process_local_data`` just adopts
    each host's buffers.

    ``batch`` is any pytree of arrays whose leading dimension is the
    (host-local part of the) object axis.  Single-process: a plain
    ``device_put`` with the same sharding.
    """
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    def put(x):
        sharding = NamedSharding(mesh, P(axis, *([None] * (x.ndim - 1))))
        return jax.make_array_from_process_local_data(sharding, np.asarray(x))

    return jax.tree_util.tree_map(put, batch)
