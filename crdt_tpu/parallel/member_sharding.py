"""Member-universe sharding — context parallelism for huge sets.

SURVEY.md §5: the structural analogue of sequence/context parallelism in
this domain is scaling the **member axis** of ORSWOT: a set too big for one
device's member table is hash-partitioned across a mesh axis, merged
shard-locally, with the set clock joined globally.  The reference has no
counterpart (its sets are in-memory HashMaps, `/root/reference/src/orswot.rs:26-30`)
— this is a new first-class component the TPU design must supply.

Why shard-local merge is exact (`orswot.rs:89-156` semantics):

* The per-member dot algebra needs only (both sides' dot clocks for that
  member, both sides' **set clocks**).  Members are routed by
  ``member_id % n_shards``, so any member lives on the same shard on both
  sides of a merge — alignment never crosses shards.
* Each shard carries a replicated copy of the full set clock.  A merge
  joins the two replicated clocks identically on every shard, so clock
  coherence is preserved *without* a collective.
* A deferred remove row for member ``m`` routes to ``m``'s shard; replay
  (`orswot.rs:195-243`) compares the (replicated) set clock with the row's
  clock and subtracts from that shard's member table only — shard-local.

The one place a collective IS required: **op application**.  ``Op::Add``
witnesses its dot on the shard holding the member, so the replicated
clocks diverge until :func:`rebroadcast_clock` joins them with an
all-reduce ``pmax`` over the member-shard axis (ICI).  Merges after the
rebroadcast are coherent again.

State layout: the standard 5-tuple with a leading shard axis —
``clock u[S, N, A] (replicated content), ids i32[S, N, Mс], dots
u[S, N, Mс, A], d_ids i32[S, N, Dс], d_clocks u[S, N, Dс, A]`` — sharded
over a mesh axis (default ``"members"``).  ``Mс`` is the per-shard member
capacity; the logical capacity is ``S × Mс``.
"""

from __future__ import annotations

import functools
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ._compat import shard_map

from ..ops import orswot_ops
from ..error import raise_for_overflow
from ..obs.kernels import observed_kernel

EMPTY = orswot_ops.EMPTY


def member_shard(member_ids, n_shards: int):
    """Routing hash: which shard owns each (non-negative) member id."""
    return member_ids % n_shards


def partition_dense(clock, ids, dots, d_ids, d_clocks, n_shards: int,
                    m_cap_shard: int, d_cap_shard: int):
    """Host-side: split dense single-device ORSWOT arrays ``[N, ...]`` into
    member-sharded arrays ``[S, N, ...]`` (numpy).

    Members route by :func:`member_shard`; the set clock is replicated
    into every shard row.  Raises if any shard overflows its capacity —
    by the pigeonhole bound a balanced hash keeps ``≈ M/S`` members per
    shard, so ``m_cap_shard ≥ ceil(m_cap / n_shards)`` plus slack is the
    sizing rule."""
    clock = np.asarray(clock)
    ids = np.asarray(ids)
    dots = np.asarray(dots)
    d_ids = np.asarray(d_ids)
    d_clocks = np.asarray(d_clocks)
    n, a = clock.shape
    s_clock = np.broadcast_to(clock, (n_shards,) + clock.shape).copy()
    s_ids = np.full((n_shards, n, m_cap_shard), EMPTY, dtype=ids.dtype)
    s_dots = np.zeros((n_shards, n, m_cap_shard, a), dtype=dots.dtype)
    s_dids = np.full((n_shards, n, d_cap_shard), EMPTY, dtype=d_ids.dtype)
    s_dclocks = np.zeros((n_shards, n, d_cap_shard, a), dtype=d_clocks.dtype)

    def route(table_ids, payload, out_ids, out_payload, cap, what):
        live_obj, live_slot = np.nonzero(table_ids != EMPTY)
        mids = table_ids[live_obj, live_slot]
        shard = member_shard(mids, n_shards)
        # stable per-(shard, object) slot assignment in input order
        counters = {}
        for k in range(live_obj.size):
            key = (int(shard[k]), int(live_obj[k]))
            slot = counters.get(key, 0)
            if slot >= cap:
                raise ValueError(
                    f"{what}: shard {key[0]} object {key[1]} exceeds "
                    f"per-shard capacity {cap}"
                )
            counters[key] = slot + 1
            out_ids[key[0], key[1], slot] = mids[k]
            out_payload[key[0], key[1], slot] = payload[live_obj[k], live_slot[k]]

    route(ids, dots, s_ids, s_dots, m_cap_shard, "members")
    route(d_ids, d_clocks, s_dids, s_dclocks, d_cap_shard, "deferred")
    return s_clock, s_ids, s_dots, s_dids, s_dclocks


def unpartition_dense(s_clock, s_ids, s_dots, s_dids, s_dclocks,
                      m_cap: int, d_cap: int):
    """Host-side inverse of :func:`partition_dense`: collapse the shard
    axis back into single dense tables in canonical ascending-id order."""
    s_clock = np.asarray(s_clock)
    s_ids = np.asarray(s_ids)
    s_dots = np.asarray(s_dots)
    s_dids = np.asarray(s_dids)
    s_dclocks = np.asarray(s_dclocks)
    n_shards, n, _, a = s_dots.shape
    clock = s_clock.max(axis=0)  # replicated content — max is a no-op join

    ids = np.full((n, m_cap), EMPTY, dtype=s_ids.dtype)
    dots = np.zeros((n, m_cap, a), dtype=s_dots.dtype)
    d_ids = np.full((n, d_cap), EMPTY, dtype=s_dids.dtype)
    d_clocks = np.zeros((n, d_cap, a), dtype=s_dclocks.dtype)

    def collect(src_ids, src_payload, out_ids, out_payload, cap, sort_ids):
        sh, obj, slot = np.nonzero(src_ids != EMPTY)
        mids = src_ids[sh, obj, slot]
        order = np.lexsort((mids, obj)) if sort_ids else np.argsort(obj, kind="stable")
        counters = {}
        for k in order:
            i = int(obj[k])
            pos = counters.get(i, 0)
            if pos >= cap:
                raise ValueError(f"object {i} exceeds capacity {cap} on collect")
            counters[i] = pos + 1
            out_ids[i, pos] = mids[k]
            out_payload[i, pos] = src_payload[sh[k], obj[k], slot[k]]

    collect(s_ids, s_dots, ids, dots, m_cap, sort_ids=True)
    collect(s_dids, s_dclocks, d_ids, d_clocks, d_cap, sort_ids=False)
    return clock, ids, dots, d_ids, d_clocks


def member_sharded_merge(state_a, state_b, mesh: Mesh, axis: str = "members",
                         check: bool = True, impl: str | None = None):
    """Pairwise merge of two member-sharded states — fully shard-local
    (zero collectives): each device runs the standard merge kernel on its
    member partition with the replicated set clocks.  Reuses the cached
    jitted shard-local merge from :mod:`crdt_tpu.parallel.collective`
    (member sharding IS object-axis sharding over the shard dimension —
    the member-specific work is the routing/partition layer around it).

    ``state_a``/``state_b``: 5-tuples of ``[S, N, ...]`` arrays sharded
    over ``axis``.  Returns the merged 5-tuple (same sharding).  With
    ``check=True`` the per-shard overflow bitmap is raised host-side."""
    from .collective import shard_local_merge_fn

    m_cap, d_cap = state_a[1].shape[-1], state_a[3].shape[-1]
    state, overflow = shard_local_merge_fn(mesh, axis, m_cap, d_cap, impl)(
        tuple(state_a), tuple(state_b)
    )
    if check:
        raise_for_overflow(np.asarray(overflow), "member-sharded merge")
    return state


@functools.lru_cache(maxsize=64)
def _clock_join_fn(mesh: Mesh, axis: str):
    spec = P(axis)

    @jax.jit
    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(spec,),
        out_specs=spec,
        check_vma=False,
    )
    def _join(local_clock):
        # local_clock: [K, N, A] — K shard rows co-located on this device.
        # Join across the co-located rows first, then across devices, and
        # broadcast back so EVERY shard row (not just row-for-row across
        # devices) sees the full clock.
        local = jnp.max(local_clock, axis=0, keepdims=True)
        joined = jax.lax.pmax(local, axis)
        return jnp.broadcast_to(joined, local_clock.shape)

    return observed_kernel("parallel.member_clock_join")(_join)


def rebroadcast_clock(state, mesh: Mesh, axis: str = "members"):
    """Join the per-shard set-clock copies — a max over shard rows
    co-located on each device plus an all-reduce ``pmax`` across the
    member-shard axis, broadcast back to every row.  Required after op
    application (an ``Add`` witnesses its dot only on the owning shard)
    and before the next merge, so every shard again sees the full set
    clock.  This is the 'join clocks globally' collective of the
    member-sharding design; it rides ICI inside a slice."""
    clock, ids, dots, d_ids, d_clocks = state
    return (_clock_join_fn(mesh, axis)(clock), ids, dots, d_ids, d_clocks)


def sharded_apply_add(state, actor_idx, counter, member_id, mesh: Mesh,
                      axis: str = "members"):
    """Batched ``Op::Add`` against a member-sharded state: every shard
    sees the op, only the owning shard (``member_id % S``) applies it;
    the clock rebroadcast then restores coherence.  ``actor_idx`` /
    ``counter`` / ``member_id``: ``[N]`` (one op per object)."""
    n_shards = state[0].shape[0]
    shard_row = jnp.arange(n_shards, dtype=jnp.int32)
    state_out, overflow = _apply_add_fn(mesh, axis, n_shards)(
        tuple(state), shard_row, actor_idx, counter, member_id
    )
    raise_for_overflow(np.asarray(overflow), "member-sharded add")
    return rebroadcast_clock(state_out, mesh, axis)


@functools.lru_cache(maxsize=64)
def _apply_add_fn(mesh: Mesh, axis: str, n_shards: int):
    spec = P(axis)
    rep = P()

    @jax.jit
    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=((spec,) * 5, spec, rep, rep, rep),
        out_specs=((spec,) * 5, spec),
        check_vma=False,
    )
    def _local(s, my_shards, a_idx, cnt, mid):
        # block shapes: state [K, N, ...] (K shards per device), ops [N]
        mine = member_shard(mid, n_shards)[None, :] == my_shards[:, None]
        # non-owners apply a no-op: counter 0 is always already witnessed
        eff_cnt = jnp.where(mine, cnt[None, :], 0)
        k = s[0].shape[0]
        tile = lambda x: jnp.broadcast_to(x[None, :], (k,) + x.shape)
        *new_state, over = orswot_ops.apply_add(*s, tile(a_idx), eff_cnt, tile(mid))
        return tuple(new_state), over

    return observed_kernel("parallel.member_apply_add")(_local)
