"""Host-level join executor — elastic recovery for device batches.

SURVEY.md §5: the reference's fault-tolerance story is purely algebraic —
idempotent merge makes redelivery safe (`/root/reference/src/traits.rs:36`),
deferred removes buffer causally-future ops (`orswot.rs:195-203`) — and the
TPU build adds "a host-level retry/requeue for failed device batches" on
top.  This module is that component.

On TPU the two batch failure modes are:

* **capacity overflow** — the static-shape concession (SURVEY.md §7.3):
  a join's survivor set outgrows the padded member/deferred slot axes.
  The kernels report this as a per-object overflow bitmap; recovery is to
  regrow the slot axes (``with_capacity``) and re-run the join.  Because
  merge is idempotent and the regrown batch is the same CRDT state, the
  retry is always algebraically safe.
* **transient device failure** — a dispatch raising ``RuntimeError``
  (device OOM, a remote-TPU tunnel dropping, preemption).  Recovery is to
  requeue the same join up to ``max_retries`` times.

The executor joins a queue of batches into one state — as a left fold
(one recoverable pair merge per step) or, on TPU backends by default, as
the type's pairwise-tree reduction with recovery at whole-tree
granularity (``strategy`` field) — finishing with a defer-plunger
self-merge (`/root/reference/test/orswot.rs:61-62`) so buffered removes
flush.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Optional, Sequence

from ..error import CapacityOverflowError
from ..obs import events as obs_events
from ..obs import kernels as obs_kernels
from ..utils import tracing


def _record_recovery(kind: str, **fields) -> None:
    """Executor recoveries (regrows, transient requeues) are rare and
    diagnostic-grade: count them always-on AND leave a flight-recorder
    event, so a fleet that silently regrew mid-join shows up on
    ``/events`` with the capacities it regrew to.

    The counter lives under ``executor.recovery.*`` — a namespace
    disjoint from the ``executor.regrow`` SPAN below, because the obs
    registry claims one metric type per name and the span forwards into
    a histogram of the same name.
    """
    tracing.count(f"executor.recovery.{kind}")
    if kind == "regrow":
        # stamp the capacity-ladder transition for the kernel
        # observatory: the next compile each kernel pays on the regrown
        # shapes is ladder-attributed, not shape churn
        # (crdt_tpu/obs/kernels.py storm_report)
        obs_kernels.note_ladder_transition(kind)
    obs_events.record(f"executor.{kind}", **fields)


@dataclasses.dataclass
class JoinStats:
    """What happened during a ``join_all`` run."""

    joins: int = 0
    overflow_regrows: int = 0
    transient_retries: int = 0
    final_member_capacity: Optional[int] = None
    final_deferred_capacity: Optional[int] = None


class JoinError(RuntimeError):
    """A join could not be completed within the executor's limits."""


# substrings that mark a RuntimeError as plausibly transient (device-side,
# worth requeueing); anything else is treated as deterministic and raised
# without burning the retry budget on backoff sleeps
_TRANSIENT_MARKERS = (
    "unavailable",
    "deadline",
    "aborted",
    "cancelled",
    "preempt",
    "connection",
    "socket",
    "tunnel",
    "device gone",
    "device lost",
    "out of memory",
    "resource exhausted",
)


def _is_transient(err: BaseException) -> bool:
    msg = str(err).lower()
    return any(marker in msg for marker in _TRANSIENT_MARKERS)


@dataclasses.dataclass
class JoinExecutor:
    """Join driver with overflow regrowth and transient retry.

    The schedule is the ``strategy`` field: a left fold (recovery per
    pair merge) or the batch type's pairwise-tree reduction (recovery
    re-runs the whole tree — safe because merge is idempotent).

    Works with any batch type exposing ``merge(other, check=True)`` that
    raises :class:`~crdt_tpu.error.CapacityOverflowError` on capacity
    overflow; elastic regrowth additionally needs ``with_capacity``/
    ``member_capacity``/``deferred_capacity`` (``OrswotBatch`` has all
    three; types without capacities — counters, registers — simply never
    overflow).  Only the axis the error names is regrown.

    ``max_capacity`` bounds geometric regrowth (×2 per overflow);
    ``max_retries`` bounds requeues of a join whose dispatch raised
    ``RuntimeError``.
    """

    max_capacity: int = 1 << 16
    max_retries: int = 2
    grow_factor: int = 2
    retry_backoff_s: float = 0.5  # doubles per retry; 0 disables sleeping
    # join schedule: "sequential" = left fold, one recoverable pair merge
    # at a time; "tree" = the type's pairwise-tree reduction
    # (``join_fleet``) — log-depth, each level one batched call, recovery
    # at whole-tree granularity; "auto" = tree on TPU backends (the
    # launch shape accelerators want), sequential elsewhere (measured
    # faster on a single CPU core — PERF.md)
    strategy: str = "auto"

    def join_all(
        self,
        batches: Sequence[Any],
        plunger: bool = True,
        stats: Optional[JoinStats] = None,
    ) -> Any:
        """Fold ``batches`` into one joined batch (anti-entropy)."""
        if not batches:
            raise ValueError("join_all needs at least one batch")
        stats = stats if stats is not None else JoinStats()
        if self._use_tree(batches):
            return self._join_tree(list(batches), plunger, stats)
        acc = batches[0]
        with tracing.span("executor.join_all"):
            for nxt in batches[1:]:
                acc, nxt = self._equalize(acc, nxt)
                acc = self._merge_recovering(acc, nxt, stats)
            if plunger:
                acc = self._merge_recovering(acc, acc, stats)
        stats.final_member_capacity = getattr(acc, "member_capacity", None)
        stats.final_deferred_capacity = getattr(acc, "deferred_capacity", None)
        return acc

    def _use_tree(self, batches: Sequence[Any]) -> bool:
        if self.strategy not in ("sequential", "tree", "auto"):
            raise ValueError(
                f"unknown strategy {self.strategy!r}; use 'sequential', "
                "'tree' or 'auto'"
            )
        if self.strategy == "sequential" or len(batches) < 2:
            return False
        if not hasattr(type(batches[0]), "join_fleet"):
            if self.strategy == "tree":
                raise ValueError(
                    f"strategy='tree' requires {type(batches[0]).__name__} to "
                    "implement join_fleet; use 'sequential' or 'auto'"
                )
            return False
        if self.strategy == "tree":
            return True
        import jax

        return jax.default_backend() == "tpu"

    def _join_tree(self, batches: list, plunger: bool, stats: JoinStats) -> Any:
        """Whole-tree join with the same two recoveries as the fold:
        capacity overflow regrows every fleet and re-runs the tree
        (idempotent merge makes the re-run algebraically safe), transient
        RuntimeErrors requeue up to ``max_retries``."""
        # equalize all fleets to the max capacities up front
        if hasattr(batches[0], "with_capacity"):
            m = max(b.member_capacity for b in batches)
            d = max(b.deferred_capacity for b in batches)
            batches = [
                b if (b.member_capacity, b.deferred_capacity) == (m, d)
                else b.with_capacity(m, d)
                for b in batches
            ]
        retries = 0
        with tracing.span("executor.join_all_tree"):
            while True:
                try:
                    out = type(batches[0]).join_fleet(
                        batches, check=True, plunger=plunger
                    )
                    stats.joins += len(batches) - 1 + (1 if plunger else 0)
                    stats.final_member_capacity = getattr(
                        out, "member_capacity", None
                    )
                    stats.final_deferred_capacity = getattr(
                        out, "deferred_capacity", None
                    )
                    return out
                except CapacityOverflowError as overflow:
                    if not hasattr(batches[0], "with_capacity"):
                        raise
                    m = batches[0].member_capacity
                    d = batches[0].deferred_capacity
                    new_m = self._grown(m, overflow.member)
                    new_d = self._grown(d, overflow.deferred)
                    if new_m == m and new_d == d:
                        raise JoinError(
                            f"tree join overflowed at max_capacity="
                            f"{self.max_capacity} (member_capacity={m}, "
                            f"deferred_capacity={d})"
                        ) from overflow
                    stats.overflow_regrows += 1
                    # before/after capacity stamps: the capacity
                    # observatory's regrow_timeline correlates these
                    # events with the occupancy curve that forced them
                    _record_recovery("regrow", schedule="tree",
                                     member_capacity_before=m,
                                     deferred_capacity_before=d,
                                     member_capacity=new_m,
                                     deferred_capacity=new_d)
                    with tracing.span("executor.regrow"):
                        batches = [b.with_capacity(new_m, new_d) for b in batches]
                except RuntimeError as transient:
                    if isinstance(transient, JoinError) or not _is_transient(
                        transient
                    ):
                        raise
                    retries += 1
                    if retries > self.max_retries:
                        raise JoinError(
                            f"tree join failed after {self.max_retries} retries"
                        ) from transient
                    stats.transient_retries += 1
                    _record_recovery("transient_retry", schedule="tree",
                                     attempt=retries,
                                     error=str(transient)[:200])
                    if self.retry_backoff_s > 0:
                        time.sleep(self.retry_backoff_s * (2 ** (retries - 1)))

    def _grown(self, cur: int, hit: bool) -> int:
        if not hit:
            return cur
        # never shrink: a batch may already exceed max_capacity
        return max(cur, min(max(1, cur) * self.grow_factor, self.max_capacity))

    # -- internals ---------------------------------------------------------

    def _equalize(self, a: Any, b: Any):
        """Bring two batches to a common capacity before merging."""
        if not hasattr(a, "with_capacity") or not hasattr(b, "with_capacity"):
            return a, b
        m = max(a.member_capacity, b.member_capacity)
        d = max(a.deferred_capacity, b.deferred_capacity)
        if (a.member_capacity, a.deferred_capacity) == (m, d) == (
            b.member_capacity,
            b.deferred_capacity,
        ):
            return a, b
        return a.with_capacity(m, d), b.with_capacity(m, d)

    def _merge_recovering(self, acc: Any, nxt: Any, stats: JoinStats) -> Any:
        retries = 0
        while True:
            try:
                with tracing.span("executor.merge"):
                    out = acc.merge(nxt, check=True)
                stats.joins += 1
                return out
            except CapacityOverflowError as overflow:
                # capacity overflow: regrow the overflowed axes and requeue
                if not hasattr(acc, "with_capacity"):
                    raise
                m = getattr(acc, "member_capacity", 0)
                d = getattr(acc, "deferred_capacity", 0)
                new_m = self._grown(m, overflow.member)
                new_d = self._grown(d, overflow.deferred)
                if new_m == m and new_d == d:
                    raise JoinError(
                        f"join overflowed at max_capacity={self.max_capacity} "
                        f"(member_capacity={m}, deferred_capacity={d})"
                    ) from overflow
                stats.overflow_regrows += 1
                _record_recovery("regrow", schedule="sequential",
                                 member_capacity_before=m,
                                 deferred_capacity_before=d,
                                 member_capacity=new_m,
                                 deferred_capacity=new_d)
                with tracing.span("executor.regrow"):
                    acc = acc.with_capacity(new_m, new_d)
                    nxt = nxt.with_capacity(new_m, new_d)
            except RuntimeError as transient:
                # XLA surfaces tunnel drops, preemption AND deterministic
                # failures (shape/compile errors) as RuntimeError subclasses;
                # only messages carrying transient markers are requeued —
                # deterministic failures surface immediately
                if isinstance(transient, JoinError) or not _is_transient(transient):
                    raise
                retries += 1
                if retries > self.max_retries:
                    raise JoinError(
                        f"join failed after {self.max_retries} retries"
                    ) from transient
                stats.transient_retries += 1
                _record_recovery("transient_retry", schedule="sequential",
                                 attempt=retries,
                                 error=str(transient)[:200])
                if self.retry_backoff_s > 0:
                    time.sleep(self.retry_backoff_s * (2 ** (retries - 1)))


def join_all(batches: Sequence[Any], **kwargs: Any) -> Any:
    """One-shot convenience: ``JoinExecutor().join_all(batches)``."""
    executor_kwargs = {
        k: kwargs.pop(k)
        for k in (
            "max_capacity", "max_retries", "grow_factor", "retry_backoff_s",
            "strategy",
        )
        if k in kwargs
    }
    return JoinExecutor(**executor_kwargs).join_all(batches, **kwargs)
