"""Collective lattice joins — anti-entropy as an all-reduce (SURVEY.md §5).

Because ``CvRDT::merge`` is associative, commutative, and idempotent
(`/root/reference/src/traits.rs:9-12`), the global join of N replicas is a
reduction with merge as the combiner:

* **clock-shaped state** (VClock / GCounter / PNCounter): merge is pointwise
  max (`vclock.rs:131-137`), so the cross-device join is literally
  ``lax.pmax`` — one XLA collective riding ICI.
* **ORSWOT state**: merge is the dot-algebra kernel; the cross-device join
  is an **all-gather + canonical-order fold** with merge as the combiner —
  see :func:`allgather_join_orswot` for why a ppermute ring is *unsafe*
  for this type (the reference merge is merge-order-sensitive).
* **replica-axis stacks on one device**: a binary tree of pairwise merges
  (log2 R kernel launches, all fused under one jit).

Anti-entropy-to-fixpoint (`BASELINE.md` config ★) = fold/collective join +
one extra self-merge pass to flush deferred removes (the reference's
"defer plunger", `test/orswot.rs:61-62`), iterated until stable.
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
from jax import shard_map

from ..error import raise_for_overflow
from ..ops import orswot_ops


# -- clock-shaped types ------------------------------------------------------


def all_reduce_clock_join(clocks, mesh: Mesh, axis: str = "replicas"):
    """Global VClock/GCounter/PNCounter join across a mesh axis.

    ``clocks``: an array whose leading axis is the replica axis, sharded
    one replica per device over ``axis`` (leading size must equal the mesh
    axis size); the join is an all-reduce-max — the direct ICI collective
    form of N-way ``VClock::merge``.  Every replica row of the output holds
    the global join."""
    if clocks.shape[0] != mesh.shape[axis]:
        raise ValueError(
            f"leading replica axis {clocks.shape[0]} != mesh axis "
            f"{axis}={mesh.shape[axis]} (one replica shard per device)"
        )
    spec = P(axis, *([None] * (clocks.ndim - 1)))

    @functools.partial(
        shard_map, mesh=mesh, in_specs=(spec,), out_specs=spec, check_vma=False
    )
    def _join(local):
        # reduce the local replicas, then all-reduce across devices
        local_join = jnp.max(local, axis=0, keepdims=True)
        return jax.lax.pmax(local_join, axis_name=axis)

    return jax.jit(_join)(clocks)


# -- generic tree reduction over a replica axis ------------------------------


def tree_reduce_merge(stack, merge_fn: Callable):
    """Reduce a replica-stacked pytree (leading axis R on every leaf) to a
    single state with a binary merge tree — log2(R) pairwise batch merges,
    all inside one jit trace.

    ``merge_fn(a, b) -> merged`` takes and returns the pytree without the
    replica axis.

    CAVEAT: safe for types whose merge is truly commutative (clocks,
    counters, LWW, MVReg).  For ORSWOT, merge order leaves different stale
    dots in entry clocks (`orswot.rs:94-103` asymmetry), so use the
    sequential left fold (:func:`fold_reduce_merge`) when bit-parity with
    the scalar N-way join matters."""
    leaves = jax.tree_util.tree_leaves(stack)
    r = leaves[0].shape[0]

    def take(i):
        return jax.tree_util.tree_map(lambda x: x[i], stack)

    # tree via repeated halving over python ints (static under jit)
    parts = [take(i) for i in range(r)]
    while len(parts) > 1:
        nxt = []
        for i in range(0, len(parts) - 1, 2):
            nxt.append(merge_fn(parts[i], parts[i + 1]))
        if len(parts) % 2 == 1:
            nxt.append(parts[-1])
        parts = nxt
    return parts[0]


def fold_reduce_merge(stack, merge_fn: Callable):
    """Sequential left fold over the replica axis — replica order 0..R-1,
    bit-matching the scalar idiom ``for w in witnesses: merged.merge(w)``
    (`test/orswot.rs:53-56`).  R-1 batch merges, each fully parallel over
    the object axis."""
    leaves = jax.tree_util.tree_leaves(stack)
    r = leaves[0].shape[0]

    def take(i):
        return jax.tree_util.tree_map(lambda x: x[i], stack)

    acc = take(0)
    for i in range(1, r):
        acc = merge_fn(acc, take(i))
    return acc


# -- ORSWOT collective join --------------------------------------------------


def _orswot_pair_merge(a, b, m_cap: int, d_cap: int):
    """Pairwise merge over state tuples; returns (state5, overflow)."""
    *state, overflow = orswot_ops.merge(
        a[0], a[1], a[2], a[3], a[4], b[0], b[1], b[2], b[3], b[4], m_cap, d_cap
    )
    return tuple(state), overflow


@functools.lru_cache(maxsize=64)
def shard_local_merge_fn(mesh: Mesh, axis: str, m_cap: int, d_cap: int):
    """Cached jitted shard-local pairwise merge over state 5-tuples —
    cache keyed on (mesh, axis, capacities) so loop-heavy callers compile
    once, not per call."""
    spec = P(axis)

    @jax.jit
    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=((spec,) * 5, (spec,) * 5),
        out_specs=((spec,) * 5, spec),
        check_vma=False,
    )
    def _local(sa, sb):
        return _orswot_pair_merge(sa, sb, m_cap, d_cap)

    return _local


def shard_local_pairwise_merge(a, b, mesh: Mesh, axis: str = "objects"):
    """Pairwise ORSWOT merge of two object-sharded batches with a
    **zero-collective guarantee**: each device merges only its own object
    shard under ``shard_map``, so the compiled program provably moves no
    data across devices — and the merge kernel's deferred/deferred-free
    dispatch (`orswot_ops.merge`) is decided *per shard*, so shards whose
    objects carry no deferred rows stay on the fast path even when other
    shards don't.

    ``a``/``b``: OrswotBatch-shaped pytrees sharded over ``axis``.
    Returns ``(merged_state5, overflow)`` with the same sharding."""
    m_cap, d_cap = a.ids.shape[-1], a.d_ids.shape[-1]
    state_a = (a.clock, a.ids, a.dots, a.d_ids, a.d_clocks)
    state_b = (b.clock, b.ids, b.dots, b.d_ids, b.d_clocks)
    return shard_local_merge_fn(mesh, axis, m_cap, d_cap)(state_a, state_b)


def _fold_orswot_stack(stack5, m_cap: int, d_cap: int):
    """Canonical left fold over a replica-stacked ORSWOT state 5-tuple
    (leading axis R on every array), ORing capacity overflow across every
    pairwise merge.  THE one place the canonical-order + overflow invariant
    lives; both the collective join and on-device anti-entropy fold through
    here."""
    r = stack5[0].shape[0]
    acc = tuple(x[0] for x in stack5)
    # [..., 2]: member / deferred overflow flags (orswot_ops.merge)
    overflow = jnp.zeros(stack5[0].shape[1:2] + (2,), dtype=bool)
    for i in range(1, r):
        acc, over = _orswot_pair_merge(acc, tuple(x[i] for x in stack5), m_cap, d_cap)
        overflow |= over
    return acc, overflow


def gather_fold_orswot(local, axis: str, m_cap: int, d_cap: int):
    """The ORSWOT cross-device join body, for use INSIDE shard_map: all-gather
    each state array over ``axis`` and fold in canonical device order 0..D-1
    (D is the all-gather's leading axis — derived, not caller-supplied, so a
    wrong device count can't silently truncate the fold).

    ``local``: 5-tuple of per-device state arrays (no leading replica axis).
    Returns ``(state5, overflow)`` where overflow is the OR of every pairwise
    merge's capacity-overflow flags.  The canonical order keeps the result
    identical on every device AND bit-equal to the scalar left-fold oracle —
    a ppermute ring (different fold origin per device) breaks both, because
    the reference merge is order-sensitive (`orswot.rs:94-103` asymmetry)."""
    gathered = tuple(jax.lax.all_gather(x, axis) for x in local)  # [D, ...]
    return _fold_orswot_stack(gathered, m_cap, d_cap)


def allgather_join_orswot(batch, mesh: Mesh, axis: str = "replicas", check: bool = True):
    """All-reduce ORSWOT state across a mesh axis with merge as the
    combiner; result is identical on every device and bit-equal to the
    scalar left-fold join in device order 0..D-1 (see
    :func:`gather_fold_orswot` for why the fold order is canonical and a
    ppermute ring is not used).

    ``batch``: an :class:`OrswotBatch` whose leading axis is the replica
    axis, sharded one replica per device over ``axis``.  Raises on
    capacity overflow when ``check`` (pass ``check=False`` to skip the
    host sync)."""
    from ..batch.orswot_batch import OrswotBatch

    m_cap = batch.ids.shape[-1]
    d_cap = batch.d_ids.shape[-1]
    n_dev = mesh.shape[axis]
    if batch.clock.shape[0] != n_dev:
        raise ValueError(
            f"leading replica axis {batch.clock.shape[0]} != mesh axis "
            f"{axis}={n_dev} (one replica shard per device)"
        )
    arrays = (batch.clock, batch.ids, batch.dots, batch.d_ids, batch.d_clocks)
    specs = tuple(P(axis, *([None] * (a.ndim - 1))) for a in arrays)
    over_spec = P(axis, None)

    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(specs,),
        out_specs=(specs, over_spec),
        check_vma=False,
    )
    def _join(local):
        acc, overflow = gather_fold_orswot(
            tuple(x[0] for x in local), axis, m_cap, d_cap
        )
        return tuple(x[None] for x in acc), jnp.any(overflow, axis=0)[None]

    (clock, ids, dots, d_ids, d_clocks), overflow = jax.jit(_join)(arrays)
    if check:
        raise_for_overflow(overflow, "collective join")
    return OrswotBatch(clock=clock, ids=ids, dots=dots, d_ids=d_ids, d_clocks=d_clocks)


def _fold_map_stack(stack_state, kernel):
    """Canonical left fold over a replica-stacked Map state pytree (leading
    axis R on every leaf), ORing overflow across every pairwise merge —
    the Map analogue of :func:`_fold_orswot_stack`, recursing through the
    nested value state via the (static) value kernel."""
    leaves, treedef = jax.tree_util.tree_flatten(stack_state)
    r = leaves[0].shape[0]

    def take(i):
        return jax.tree_util.tree_unflatten(treedef, [x[i] for x in leaves])

    acc = take(0)
    overflow = None
    for i in range(1, r):
        acc, over = kernel.merge(acc, take(i))
        overflow = over if overflow is None else overflow | over
    if overflow is None:
        overflow = jnp.zeros((), dtype=bool)
    return acc, overflow


@functools.lru_cache(maxsize=64)
def _map_join_fn(mesh: Mesh, axis: str, kernel, flat_specs, spec_tree):
    """Cached jitted Map collective join — bounded like the sibling
    compiled-fn caches so long-lived drivers creating fresh meshes or
    kernels don't pin executables forever."""
    specs = jax.tree_util.tree_unflatten(spec_tree, list(flat_specs))

    @jax.jit
    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(specs,),
        out_specs=(specs, P(axis)),
        check_vma=False,
    )
    def _join(local_state):
        local = jax.tree_util.tree_map(lambda x: x[0], local_state)
        gathered = jax.tree_util.tree_map(
            lambda x: jax.lax.all_gather(x, axis), local
        )
        acc, overflow = _fold_map_stack(gathered, kernel)
        return (
            jax.tree_util.tree_map(lambda x: x[None], acc),
            jnp.any(overflow)[None],
        )

    return _join


def allgather_join_map(batch, mesh: Mesh, axis: str = "replicas", check: bool = True):
    """All-reduce Map state across a mesh axis with the recursive
    reset-remove merge (`/root/reference/src/map.rs:192-269`) as the
    combiner — same canonical-fold contract as
    :func:`allgather_join_orswot`: all-gather every state leaf (including
    the nested value state) over ``axis``, fold in device order 0..D-1,
    result identical on every device and bit-equal to the scalar N-way
    left fold.

    ``batch``: a :class:`~crdt_tpu.batch.map_batch.MapBatch` whose leading
    axis is the replica axis, one replica shard per device over ``axis``."""
    from ..batch.map_batch import MapBatch

    kernel = batch.kernel
    n_dev = mesh.shape[axis]
    if batch.clock.shape[0] != n_dev:
        raise ValueError(
            f"leading replica axis {batch.clock.shape[0]} != mesh axis "
            f"{axis}={n_dev} (one replica shard per device)"
        )
    state = batch.state
    specs = jax.tree_util.tree_map(
        lambda x: P(axis, *([None] * (x.ndim - 1))), state
    )
    flat_specs, spec_tree = jax.tree_util.tree_flatten(specs)
    join = _map_join_fn(mesh, axis, kernel, tuple(flat_specs), spec_tree)
    joined, overflow = join(state)
    if check and bool(jnp.any(overflow)):
        raise ValueError(
            "Map collective join overflow: raise key/deferred/value capacities"
        )
    return MapBatch.from_state(joined, kernel)


# -- anti-entropy to fixpoint ------------------------------------------------


@functools.lru_cache(maxsize=None)
def _anti_entropy_kernels(m_cap: int, d_cap: int):
    """Jitted fold/plunge kernels, cached per capacity so repeated
    anti_entropy calls hit the XLA compile cache instead of retracing
    (jax.jit caches by function identity; a per-call closure defeats it).
    Shapes (R, N, A) still key the underlying jit cache as usual."""

    @jax.jit
    def _fold(arrays):
        acc, overflow = _fold_orswot_stack(arrays, m_cap, d_cap)
        return acc, jnp.any(overflow, axis=0)

    @jax.jit
    def _plunge(acc):
        nxt, over = _orswot_pair_merge(acc, acc, m_cap, d_cap)
        same = jnp.array(True)
        for x, y in zip(nxt, acc):
            same &= jnp.array_equal(x, y)
        return nxt, same, jnp.any(over, axis=0)

    return _fold, _plunge


def anti_entropy(stack, max_rounds: int = 3, check: bool = True):
    """Converge a replica-stacked :class:`OrswotBatch` (leading axis R) to
    its fixpoint on one device/shard: left-fold-join the replicas in order
    0..R-1 (bit-parity with the scalar N-way join — see
    :func:`fold_reduce_merge`), then keep self-merging (the "defer
    plunger") until the state stops changing or ``max_rounds`` is hit.
    Returns ``(merged, rounds_used)``.

    Deferred removes make a single pass insufficient in general: a remove
    buffered under a future clock applies only once the joined clock covers
    it (`orswot.rs:195-211`).

    Capacity overflow across every merge is accumulated in-graph and raised
    once at the end when ``check`` — one host sync per round (the
    changed/overflow scalars), not one per merge."""
    from ..batch.orswot_batch import OrswotBatch

    m_cap = stack.ids.shape[-1]
    d_cap = stack.d_ids.shape[-1]
    arrays = (stack.clock, stack.ids, stack.dots, stack.d_ids, stack.d_clocks)

    import numpy as np

    _fold, _plunge = _anti_entropy_kernels(m_cap, d_cap)
    acc, over_dev = _fold(arrays)
    overflow = np.array(jax.device_get(over_dev), dtype=bool)  # writable copy
    rounds = 1
    for _ in range(max_rounds - 1):
        acc, same_dev, over_dev = _plunge(acc)
        rounds += 1
        same, over = jax.device_get((same_dev, over_dev))
        overflow |= np.asarray(over, dtype=bool)
        if same:
            break
    if check:
        raise_for_overflow(overflow, "anti-entropy")
    merged = OrswotBatch(
        clock=acc[0], ids=acc[1], dots=acc[2], d_ids=acc[3], d_clocks=acc[4]
    )
    return merged, rounds
