"""Collective lattice joins — anti-entropy as an all-reduce (SURVEY.md §5).

Because ``CvRDT::merge`` is associative, commutative, and idempotent
(`/root/reference/src/traits.rs:9-12`), the global join of N replicas is a
reduction with merge as the combiner:

* **clock-shaped state** (VClock / GCounter / PNCounter): merge is pointwise
  max (`vclock.rs:131-137`), so the cross-device join is literally
  ``lax.pmax`` — one XLA collective riding ICI.
* **ORSWOT state**: merge is the dot-algebra kernel; the cross-device join
  is an **all-gather + canonical-order fold** with merge as the combiner —
  see :func:`allgather_join_orswot` for why a ppermute ring is *unsafe*
  for this type (the reference merge is merge-order-sensitive).
* **replica-axis stacks on one device**: a binary tree of pairwise merges
  (log2 R kernel launches, all fused under one jit).

Anti-entropy-to-fixpoint (`BASELINE.md` config ★) = fold/collective join +
one extra self-merge pass to flush deferred removes (the reference's
"defer plunger", `test/orswot.rs:61-62`), iterated until stable.
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ._compat import shard_map

from ..error import CapacityOverflowError, raise_for_overflow
from ..obs.kernels import observed_kernel
from ..ops import orswot_ops


# -- clock-shaped types ------------------------------------------------------


def _check_replica_axis(leading: int, mesh: Mesh, axis: str) -> None:
    """Every collective join shards one replica per device over ``axis``;
    a mismatched leading axis means the caller stacked the fleet wrong."""
    if leading != mesh.shape[axis]:
        raise ValueError(
            f"leading replica axis {leading} != mesh axis "
            f"{axis}={mesh.shape[axis]} (one replica shard per device)"
        )


def all_reduce_clock_join(clocks, mesh: Mesh, axis: str = "replicas"):
    """Global VClock/GCounter/PNCounter join across a mesh axis.

    ``clocks``: an array whose leading axis is the replica axis, sharded
    one replica per device over ``axis`` (leading size must equal the mesh
    axis size); the join is an all-reduce-max — the direct ICI collective
    form of N-way ``VClock::merge``.  Every replica row of the output holds
    the global join."""
    _check_replica_axis(clocks.shape[0], mesh, axis)
    return _clock_join_fn(mesh, axis, clocks.ndim)(clocks)


@functools.lru_cache(maxsize=64)
def _clock_join_fn(mesh: Mesh, axis: str, ndim: int):
    """Cached jitted clock all-reduce (jax.jit caches by function identity;
    a per-call closure would retrace+recompile every call)."""
    spec = P(axis, *([None] * (ndim - 1)))

    @jax.jit
    @functools.partial(
        shard_map, mesh=mesh, in_specs=(spec,), out_specs=spec, check_vma=False
    )
    def _join(local):
        # reduce the local replicas, then all-reduce across devices
        local_join = jnp.max(local, axis=0, keepdims=True)
        return jax.lax.pmax(local_join, axis_name=axis)

    return observed_kernel("parallel.clock_join")(_join)


# -- generic tree reduction over a replica axis ------------------------------


def tree_reduce_merge(stack, merge_fn: Callable):
    """Reduce a replica-stacked pytree (leading axis R on every leaf) to a
    single state with a binary merge tree — log2(R) pairwise batch merges,
    all inside one jit trace.

    ``merge_fn(a, b) -> merged`` takes and returns the pytree without the
    replica axis.

    CAVEAT: safe for types whose merge is truly commutative (clocks,
    counters, LWW, MVReg).  For ORSWOT, merge order leaves different stale
    dots in entry clocks (`orswot.rs:94-103` asymmetry), so use the
    sequential left fold (:func:`fold_reduce_merge`) when bit-parity with
    the scalar N-way join matters."""
    leaves = jax.tree_util.tree_leaves(stack)
    r = leaves[0].shape[0]

    def take(i):
        return jax.tree_util.tree_map(lambda x: x[i], stack)

    # tree via repeated halving over python ints (static under jit)
    parts = [take(i) for i in range(r)]
    while len(parts) > 1:
        nxt = []
        for i in range(0, len(parts) - 1, 2):
            nxt.append(merge_fn(parts[i], parts[i + 1]))
        if len(parts) % 2 == 1:
            nxt.append(parts[-1])
        parts = nxt
    return parts[0]


def fold_reduce_merge(stack, merge_fn: Callable):
    """Sequential left fold over the replica axis — replica order 0..R-1,
    bit-matching the scalar idiom ``for w in witnesses: merged.merge(w)``
    (`test/orswot.rs:53-56`).  R-1 batch merges, each fully parallel over
    the object axis."""
    leaves = jax.tree_util.tree_leaves(stack)
    r = leaves[0].shape[0]

    def take(i):
        return jax.tree_util.tree_map(lambda x: x[i], stack)

    acc = take(0)
    for i in range(1, r):
        acc = merge_fn(acc, take(i))
    return acc


# -- ORSWOT collective join --------------------------------------------------


def _orswot_pair_merge(a, b, m_cap: int, d_cap: int, impl: str | None = None):
    """Pairwise merge over state tuples; returns (state5, overflow)."""
    *state, overflow = orswot_ops.merge(
        a[0], a[1], a[2], a[3], a[4], b[0], b[1], b[2], b[3], b[4],
        m_cap, d_cap, impl=impl,
    )
    return tuple(state), overflow


@functools.lru_cache(maxsize=64)
def shard_local_merge_fn(mesh: Mesh, axis: str, m_cap: int, d_cap: int,
                         impl: str | None = None):
    """Cached jitted shard-local pairwise merge over state 5-tuples —
    cache keyed on (mesh, axis, capacities, merge impl) so loop-heavy
    callers compile once, not per call."""
    spec = P(axis)

    @jax.jit
    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=((spec,) * 5, (spec,) * 5),
        out_specs=((spec,) * 5, spec),
        check_vma=False,
    )
    def _local(sa, sb):
        return _orswot_pair_merge(sa, sb, m_cap, d_cap, impl)

    return observed_kernel("parallel.shard_local_merge")(_local)


def shard_local_pairwise_merge(a, b, mesh: Mesh, axis: str = "objects",
                               impl: str | None = None):
    """Pairwise ORSWOT merge of two object-sharded batches with a
    **zero-collective guarantee**: each device merges only its own object
    shard under ``shard_map``, so the compiled program provably moves no
    data across devices — and the merge kernel's deferred/deferred-free
    dispatch (`orswot_ops.merge`) is decided *per shard*, so shards whose
    objects carry no deferred rows stay on the fast path even when other
    shards don't.

    ``a``/``b``: OrswotBatch-shaped pytrees sharded over ``axis``.
    Returns ``(merged_state5, overflow)`` with the same sharding."""
    m_cap, d_cap = a.ids.shape[-1], a.d_ids.shape[-1]
    state_a = (a.clock, a.ids, a.dots, a.d_ids, a.d_clocks)
    state_b = (b.clock, b.ids, b.dots, b.d_ids, b.d_clocks)
    return shard_local_merge_fn(mesh, axis, m_cap, d_cap, impl)(state_a, state_b)


def _fold_orswot_stack(stack5, m_cap: int, d_cap: int,
                       impl: str | None = None):
    """Canonical left fold over a replica-stacked ORSWOT state 5-tuple
    (leading axis R on every array), ORing capacity overflow across every
    pairwise merge.  Delegates to ``orswot_ops.fold_merge_sequential``
    (the one home of the canonical-order + overflow invariant) — always
    the PAIRWISE loop here: this runs inside ``shard_map``, where the
    fused-fold dispatch of ``orswot_ops.fold_merge`` would put a
    ``pallas_call`` under a collective trace."""
    out = orswot_ops.fold_merge_sequential(
        *stack5, m_cap, d_cap, plunger=False, impl=impl
    )
    return out[:5], out[5]


def gather_fold_orswot(local, axis: str, m_cap: int, d_cap: int,
                       impl: str | None = None):
    """The ORSWOT cross-device join body, for use INSIDE shard_map: all-gather
    each state array over ``axis`` and fold in canonical device order 0..D-1
    (D is the all-gather's leading axis — derived, not caller-supplied, so a
    wrong device count can't silently truncate the fold).

    ``local``: 5-tuple of per-device state arrays (no leading replica axis).
    Returns ``(state5, overflow)`` where overflow is the OR of every pairwise
    merge's capacity-overflow flags.  The canonical order keeps the result
    identical on every device AND bit-equal to the scalar left-fold oracle —
    a ppermute ring (different fold origin per device) breaks both, because
    the reference merge is order-sensitive (`orswot.rs:94-103` asymmetry)."""
    gathered = tuple(jax.lax.all_gather(x, axis) for x in local)  # [D, ...]
    return _fold_orswot_stack(gathered, m_cap, d_cap, impl)


def allgather_join_orswot(batch, mesh: Mesh, axis: str = "replicas",
                          check: bool = True, impl: str | None = None,
                          object_axis: str | None = None):
    """All-reduce ORSWOT state across a mesh axis with merge as the
    combiner; result is identical on every device and bit-equal to the
    scalar left-fold join in device order 0..D-1 (see
    :func:`gather_fold_orswot` for why the fold order is canonical and a
    ppermute ring is not used).

    ``batch``: an :class:`OrswotBatch` whose leading axis is the replica
    axis, sharded one replica per device over ``axis``.  Raises on
    capacity overflow when ``check`` (pass ``check=False`` to skip the
    host sync).

    ``object_axis``: optionally shard the OBJECT dimension over a second
    mesh axis — the multi-host layout (``parallel.multihost``): objects
    partition over the slow tier (DCN) with zero cross-partition join
    traffic (each object's merge is independent,
    `/root/reference/src/orswot.rs:89-156` is per-object), while the
    replica collective stays on the fast tier."""
    from ..batch.orswot_batch import OrswotBatch

    m_cap = batch.ids.shape[-1]
    d_cap = batch.d_ids.shape[-1]
    _check_replica_axis(batch.clock.shape[0], mesh, axis)
    arrays = (batch.clock, batch.ids, batch.dots, batch.d_ids, batch.d_clocks)
    join = _orswot_join_fn(
        mesh, axis, m_cap, d_cap, tuple(a.ndim for a in arrays), impl,
        object_axis,
    )
    (clock, ids, dots, d_ids, d_clocks), overflow = join(arrays)
    if check:
        raise_for_overflow(overflow, "collective join")
    return OrswotBatch(clock=clock, ids=ids, dots=dots, d_ids=d_ids, d_clocks=d_clocks)


@functools.lru_cache(maxsize=64)
def _orswot_join_fn(mesh: Mesh, axis: str, m_cap: int, d_cap: int,
                    ndims: tuple, impl: str | None = None,
                    object_axis: str | None = None):
    """Cached jitted ORSWOT collective join (see :func:`_clock_join_fn`)."""
    specs = tuple(
        P(axis, object_axis, *([None] * (nd - 2))) for nd in ndims
    )
    over_spec = P(axis, object_axis)

    @jax.jit
    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(specs,),
        out_specs=(specs, over_spec),
        check_vma=False,
    )
    def _join(local):
        acc, overflow = gather_fold_orswot(
            tuple(x[0] for x in local), axis, m_cap, d_cap, impl
        )
        over = jnp.any(overflow, axis=0)[None]
        if object_axis is not None:
            # SPMD control-flow consistency: with objects sharded over a
            # second (possibly multi-process) axis, a shard-local raise
            # would diverge — the overflowed process raises while its
            # peers proceed and then hang at the next collective.  OR
            # the flags across the object axis so EVERY process takes
            # the same raise/no-raise branch; regrowth is global anyway
            # (with_capacity recompiles every process's program).
            flags = jax.lax.pmax(
                jnp.any(over, axis=(0, 1)).astype(jnp.int32), object_axis
            )
            over = jnp.broadcast_to(flags.astype(jnp.bool_), over.shape)
        return tuple(x[None] for x in acc), over

    return observed_kernel("parallel.orswot_join")(_join)


def _fold_map_stack(stack_state, kernel):
    """Canonical left fold over a replica-stacked Map state pytree (leading
    axis R on every leaf), ORing overflow across every pairwise merge —
    the Map analogue of :func:`_fold_orswot_stack`, recursing through the
    nested value state via the (static) value kernel."""
    leaves, treedef = jax.tree_util.tree_flatten(stack_state)
    r = leaves[0].shape[0]

    def take(i):
        return jax.tree_util.tree_unflatten(treedef, [x[i] for x in leaves])

    acc = take(0)
    overflow = None
    for i in range(1, r):
        acc, over = kernel.merge(acc, take(i))
        overflow = over if overflow is None else overflow | over
    if overflow is None:
        overflow = jnp.zeros((), dtype=bool)
    return acc, overflow


@functools.lru_cache(maxsize=64)
def _map_join_fn(mesh: Mesh, axis: str, kernel, flat_specs, spec_tree):
    """Cached jitted Map collective join — bounded like the sibling
    compiled-fn caches so long-lived drivers creating fresh meshes or
    kernels don't pin executables forever."""
    specs = jax.tree_util.tree_unflatten(spec_tree, list(flat_specs))

    @jax.jit
    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(specs,),
        out_specs=(specs, P(axis)),
        check_vma=False,
    )
    def _join(local_state):
        local = jax.tree_util.tree_map(lambda x: x[0], local_state)
        gathered = jax.tree_util.tree_map(
            lambda x: jax.lax.all_gather(x, axis), local
        )
        acc, overflow = _fold_map_stack(gathered, kernel)
        return (
            jax.tree_util.tree_map(lambda x: x[None], acc),
            jnp.any(overflow)[None],
        )

    return observed_kernel("parallel.map_join")(_join)


def allgather_join_map(batch, mesh: Mesh, axis: str = "replicas", check: bool = True):
    """All-reduce Map state across a mesh axis with the recursive
    reset-remove merge (`/root/reference/src/map.rs:192-269`) as the
    combiner — same canonical-fold contract as
    :func:`allgather_join_orswot`: all-gather every state leaf (including
    the nested value state) over ``axis``, fold in device order 0..D-1,
    result identical on every device and bit-equal to the scalar N-way
    left fold.

    ``batch``: a :class:`~crdt_tpu.batch.map_batch.MapBatch` whose leading
    axis is the replica axis, one replica shard per device over ``axis``."""
    from ..batch.map_batch import MapBatch

    kernel = batch.kernel
    _check_replica_axis(batch.clock.shape[0], mesh, axis)
    state = batch.state
    specs = jax.tree_util.tree_map(
        lambda x: P(axis, *([None] * (x.ndim - 1))), state
    )
    flat_specs, spec_tree = jax.tree_util.tree_flatten(specs)
    join = _map_join_fn(mesh, axis, kernel, tuple(flat_specs), spec_tree)
    joined, overflow = join(state)
    if check and bool(jnp.any(overflow)):
        raise ValueError(
            "Map collective join overflow: raise key/deferred/value capacities"
        )
    return MapBatch.from_state(joined, kernel)


# -- LWWReg / MVReg / GSet collective joins ----------------------------------


def _fold_lww_stack(vals, markers):
    """Canonical left fold of a replica-stacked LWW state ``(vals[R, N],
    markers[R, N])`` with the pairwise rule (`lwwreg.rs:43-67`), ORing the
    equal-marker/different-value conflict bitmap across every step.

    The fold — not a one-shot argmax over the stack — is deliberate: the
    scalar N-way join errors on *any* pairwise equal-marker conflict it
    encounters en route (e.g. markers ``[5, 5, 9]`` with different values
    conflicts at step 1 even though the global max is unique), so bit- and
    error-parity require replaying the same prefix-max walk."""
    from ..ops import lww_ops

    r = vals.shape[0]
    acc_v, acc_m = vals[0], markers[0]
    conflict = jnp.zeros(vals.shape[1:], dtype=bool)
    for i in range(1, r):
        acc_v, acc_m, c = lww_ops.merge(acc_v, acc_m, vals[i], markers[i])
        conflict |= c
    return acc_v, acc_m, conflict


@functools.lru_cache(maxsize=64)
def _lww_join_fn(mesh: Mesh, axis: str, ndim: int):
    """Cached jitted LWW collective join (jax.jit caches by function
    identity — a per-call closure would retrace+recompile every call)."""
    spec = P(axis, *([None] * (ndim - 1)))

    @jax.jit
    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(spec, spec),
        out_specs=(spec, spec, spec),
        check_vma=False,
    )
    def _join(vals, markers):
        vg = jax.lax.all_gather(vals[0], axis)  # [D, N]
        mg = jax.lax.all_gather(markers[0], axis)
        v, m, conflict = _fold_lww_stack(vg, mg)
        return v[None], m[None], conflict[None]

    return observed_kernel("parallel.lww_join")(_join)


def allgather_join_lww(batch, mesh: Mesh, axis: str = "replicas", check: bool = True):
    """All-reduce LWW register state across a mesh axis: all-gather the
    ``(vals, markers)`` columns over ``axis`` and left-fold in canonical
    device order 0..D-1 with the marker-max select (`lwwreg.rs:43-67`) —
    BASELINE config 5's 10M-register fleet joined in one collective.

    ``batch``: an :class:`~crdt_tpu.batch.lwwreg_batch.LWWRegBatch` whose
    leading axis is the replica axis, one replica shard per device.
    Returns ``(joined, conflict_bitmap)``; when ``check``, raises
    :class:`~crdt_tpu.error.ConflictingMarker` if any element hit an
    equal-marker/different-value pair mid-fold (batched kernels cannot
    raise per-element — SURVEY.md §7.3 — so the bitmap surfaces
    host-side).  The joined rows are identical on every device."""
    from ..batch.lwwreg_batch import LWWRegBatch
    from ..error import ConflictingMarker

    _check_replica_axis(batch.vals.shape[0], mesh, axis)
    join = _lww_join_fn(mesh, axis, batch.vals.ndim)
    vals, markers, conflict = join(batch.vals, batch.markers)
    if check and bool(jnp.any(conflict)):
        idx = jnp.nonzero(conflict[0])[0]
        raise ConflictingMarker(
            f"{idx.shape[0]} conflicting marker(s) in collective join, "
            f"first at {int(idx[0])}"
        )
    return LWWRegBatch(vals=vals, markers=markers), conflict


def _fold_mvreg_stack(clocks, vals, k_cap: int):
    """Canonical left fold of a replica-stacked MVReg antichain
    ``(clocks[R, N, K, A], vals[R, N, K])``: pairwise keep-undominated
    merge + re-pack each step (`mvreg.rs:121-153`), ORing antichain
    overflow across steps."""
    from ..ops import mvreg_ops

    r = clocks.shape[0]
    acc_c, acc_v = clocks[0], vals[0]
    overflow = jnp.zeros(clocks.shape[1:2], dtype=bool)
    for i in range(1, r):
        c2, v2, keep = mvreg_ops.merge(acc_c, acc_v, clocks[i], vals[i])
        acc_c, acc_v, over = mvreg_ops.compact(c2, v2, keep, k_cap)
        overflow |= over
    return acc_c, acc_v, overflow


@functools.lru_cache(maxsize=64)
def _mvreg_join_fn(mesh: Mesh, axis: str, k_cap: int, c_ndim: int, v_ndim: int):
    """Cached jitted MVReg collective join (see :func:`_lww_join_fn`)."""
    c_spec = P(axis, *([None] * (c_ndim - 1)))
    v_spec = P(axis, *([None] * (v_ndim - 1)))
    o_spec = P(axis, None)

    @jax.jit
    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(c_spec, v_spec),
        out_specs=(c_spec, v_spec, o_spec),
        check_vma=False,
    )
    def _join(clocks, vals):
        cg = jax.lax.all_gather(clocks[0], axis)  # [D, N, K, A]
        vg = jax.lax.all_gather(vals[0], axis)
        c, v, overflow = _fold_mvreg_stack(cg, vg, k_cap)
        return c[None], v[None], overflow[None]

    return observed_kernel("parallel.mvreg_join")(_join)


def allgather_join_mvreg(batch, mesh: Mesh, axis: str = "replicas", check: bool = True):
    """All-reduce MVReg antichain state across a mesh axis: all-gather the
    ``(clocks, vals)`` planes over ``axis`` and left-fold in canonical
    device order 0..D-1 with the keep-mutually-undominated merge
    (`mvreg.rs:121-153`), re-packing to K slots per step.

    ``batch``: an :class:`~crdt_tpu.batch.mvreg_batch.MVRegBatch` whose
    leading axis is the replica axis, one replica shard per device.
    Raises on antichain overflow past ``mv_capacity`` when ``check``.
    The joined rows are identical on every device; set-equality (not slot
    order) is the reference's own equality (`mvreg.rs:74-96`), but the
    canonical fold keeps even slot order bit-equal to the scalar N-way
    left fold."""
    from ..batch.mvreg_batch import MVRegBatch

    k_cap = batch.clocks.shape[-2]
    _check_replica_axis(batch.clocks.shape[0], mesh, axis)
    join = _mvreg_join_fn(mesh, axis, k_cap, batch.clocks.ndim, batch.vals.ndim)
    clocks, vals, overflow = join(batch.clocks, batch.vals)
    if check and bool(jnp.any(overflow)):
        raise CapacityOverflowError(
            "MVReg collective-join antichain overflow: raise CrdtConfig.mv_capacity",
            member=True, deferred=False,
        )
    return MVRegBatch(clocks=clocks, vals=vals)


def allgather_join_gset(batch, mesh: Mesh, axis: str = "replicas"):
    """Global GSet join across a mesh axis.  Union is commutative and
    idempotent with no order sensitivity (`gset.rs:30-34`), so unlike the
    ORSWOT/LWW/MVReg folds this is a direct all-reduce: one ``pmax`` over
    the membership bitmap (bool max ≡ OR) riding ICI.

    ``batch``: a :class:`~crdt_tpu.batch.gset_batch.GSetBatch` whose
    leading axis is the replica axis, one replica shard per device.
    Every replica row of the output holds the global union."""
    from ..batch.gset_batch import GSetBatch

    # bool max ≡ OR, so the bitmap union IS the clock join over u8
    # (collectives don't take bool); one shard_map body to maintain
    joined = all_reduce_clock_join(batch.bits.astype(jnp.uint8), mesh, axis)
    return GSetBatch(bits=joined.astype(bool))


# -- fleet-observability all-gather -------------------------------------------


def allgather_fleet_snapshots(observatory):
    """Aggregate fleet telemetry across the processes of a jax mesh —
    the scraper-free path for pjit deployments with NO network peers to
    gossip with: every process encodes its observatory's merged
    snapshot frame (:meth:`crdt_tpu.obs.fleet.FleetObservatory.encode`
    — versioned + CRC-guarded, so a skewed process fails loudly at
    decode), the frames ride one ``process_allgather`` over DCN (byte
    payloads padded to the fleet max, lengths gathered first), and
    every process folds every frame into its observatory.  Because the
    snapshot merge is commutative/associative/idempotent, all processes
    converge to the SAME fleet view — including each process's own
    echoed frame, which the G-Counter semantics absorb as a no-op.

    Returns the merged :class:`~crdt_tpu.obs.fleet.FleetSnapshot`.
    Single-process meshes degrade to a local capture+merge, so the
    call is safe unconditionally."""
    import numpy as np

    frame = observatory.encode()
    if jax.process_count() == 1:
        # nothing to gather; the encode above already refreshed the
        # local slice into the merged state
        return observatory.merged(refresh=False)

    from jax.experimental import multihost_utils

    data = np.frombuffer(frame, dtype=np.uint8)
    sizes = np.atleast_1d(np.asarray(
        multihost_utils.process_allgather(np.int64(data.size))
    )).reshape(-1)
    pad = int(sizes.max())
    buf = np.zeros(pad, dtype=np.uint8)
    buf[:data.size] = data
    gathered = np.atleast_2d(np.asarray(
        multihost_utils.process_allgather(buf)
    ))
    for row, size in zip(gathered, sizes):
        observatory.merge_frame(bytes(row[:int(size)]))
    return observatory.merged(refresh=False)


# -- anti-entropy to fixpoint ------------------------------------------------


@functools.lru_cache(maxsize=None)
def _anti_entropy_kernels(m_cap: int, d_cap: int, impl: str | None = None):
    """Jitted fold/plunge kernels, cached per capacity (and merge impl) so
    repeated anti_entropy calls hit the XLA compile cache instead of
    retracing (jax.jit caches by function identity; a per-call closure
    defeats it).  Shapes (R, N, A) still key the underlying jit cache as
    usual."""

    @jax.jit
    def _fold(arrays):
        acc, overflow = _fold_orswot_stack(arrays, m_cap, d_cap, impl)
        # the scalar overflow bit folds all objects by design: it is the
        # kernel's host-raise diagnostic, and the mesh lowering is a
        # shard-local any + one-bit OR on the host, never a data gather
        return acc, jnp.any(overflow, axis=0)  # crdtlint: disable=SC01 — scalar overflow diagnostic, shard-local any + host OR

    @jax.jit
    def _plunge(acc):
        nxt, over = _orswot_pair_merge(acc, acc, m_cap, d_cap, impl)
        same = jnp.array(True)
        for x, y in zip(nxt, acc):
            # the fixpoint predicate folds all objects by design: it is a
            # one-bit convergence flag, and the mesh lowering is a
            # shard-local all + one-bit AND on the host
            same &= jnp.array_equal(x, y)  # crdtlint: disable=SC01 — scalar fixpoint flag, shard-local all + host AND
        return nxt, same, jnp.any(over, axis=0)  # crdtlint: disable=SC01 — scalar overflow diagnostic, shard-local any + host OR

    return (observed_kernel("parallel.anti_entropy_fold")(_fold),
            observed_kernel("parallel.anti_entropy_plunge")(_plunge))


def anti_entropy(stack, max_rounds: int = 3, check: bool = True,
                 impl: str | None = None):
    """Converge a replica-stacked :class:`OrswotBatch` (leading axis R) to
    its fixpoint on one device/shard: left-fold-join the replicas in order
    0..R-1 (bit-parity with the scalar N-way join — see
    :func:`fold_reduce_merge`), then keep self-merging (the "defer
    plunger") until the state stops changing or ``max_rounds`` is hit.
    Returns ``(merged, rounds_used)``.

    Deferred removes make a single pass insufficient in general: a remove
    buffered under a future clock applies only once the joined clock covers
    it (`orswot.rs:195-211`).

    Capacity overflow across every merge is accumulated in-graph and raised
    once at the end when ``check`` — one host sync per round (the
    changed/overflow scalars), not one per merge."""
    from ..batch.orswot_batch import OrswotBatch

    m_cap = stack.ids.shape[-1]
    d_cap = stack.d_ids.shape[-1]
    arrays = (stack.clock, stack.ids, stack.dots, stack.d_ids, stack.d_clocks)

    import numpy as np

    _fold, _plunge = _anti_entropy_kernels(m_cap, d_cap, impl)
    acc, over_dev = _fold(arrays)
    overflow = np.array(jax.device_get(over_dev), dtype=bool)  # writable copy
    rounds = 1
    for _ in range(max_rounds - 1):
        acc, same_dev, over_dev = _plunge(acc)
        rounds += 1
        same, over = jax.device_get((same_dev, over_dev))
        overflow |= np.asarray(over, dtype=bool)
        if same:
            break
    if check:
        raise_for_overflow(overflow, "anti-entropy")
    merged = OrswotBatch(
        clock=acc[0], ids=acc[1], dots=acc[2], d_ids=acc[3], d_clocks=acc[4]
    )
    return merged, rounds
