"""Device-mesh parallelism: sharded object axes + collective lattice joins.

The reference has no comm backend — replication is user-transported bytes
(SURVEY.md §2.3).  The TPU-native equivalent: every CvRDT merge is an
associative, commutative, idempotent join, so an N-replica global join *is*
an all-reduce with merge as the combiner — `lax.pmax` over ICI for the
clock-shaped types, an all-gather + canonical-order fold for ORSWOT state
(whose reference merge is order-sensitive; see collective.py).  Objects
shard over the mesh's data axis; replicas reduce over the replica axis.
"""

from ..config import enable_x64 as _enable_x64

_enable_x64()

from .mesh import make_mesh, replicate, shard_batch
from .multihost import (
    global_batch_from_local,
    initialize,
    local_shard,
    make_multihost_mesh,
    topology,
)
from .executor import JoinError, JoinExecutor, JoinStats, join_all
from .collective import (
    all_reduce_clock_join,
    allgather_join_gset,
    allgather_join_lww,
    allgather_join_map,
    allgather_join_mvreg,
    allgather_join_orswot,
    anti_entropy,
    fold_reduce_merge,
    gather_fold_orswot,
    tree_reduce_merge,
)

__all__ = [
    "all_reduce_clock_join",
    "allgather_join_gset",
    "allgather_join_lww",
    "allgather_join_map",
    "allgather_join_mvreg",
    "allgather_join_orswot",
    "gather_fold_orswot",
    "anti_entropy",
    "fold_reduce_merge",
    "join_all",
    "JoinError",
    "JoinExecutor",
    "JoinStats",
    "make_mesh",
    "replicate",
    "shard_batch",
    "tree_reduce_merge",
    "initialize",
    "topology",
    "make_multihost_mesh",
    "global_batch_from_local",
    "local_shard",
]
