"""Convergence observatory — divergence aging, the fleet stability
frontier, and the runtime lattice auditor.

The reference's ``Causal::truncate`` (`traits.rs:44-47`) is only safe
at clocks the whole fleet has provably converged past, and the batched
read front-end's session guarantees (``ReadCtx``, `ctx.rs:12-21`) are
only honest if staleness is measurable — yet until this module nothing
in the repo knew *how old* any divergence was or *which* clocks the
fleet had durably agreed on.  Three measurement planes, in the
observatory-before-subsystem pattern of PRs 9/13/14:

* **Divergence aging** — every digest exchange (flat or tree descent)
  names the diverged rows; :class:`StabilityTracker.observe_descent`
  maps them onto the digest tree's TOP-LEVEL subtrees (the same
  node-coverage ranges the descent's first comparison uses — at most
  :data:`~crdt_tpu.sync.tree.TREE_K` of them, the root's children) and
  tracks each ``(peer, subtree)`` from its first diverged sighting
  (*birth*) to the first exchange that finds it clean again
  (*resolution*).  Resolution ages feed the
  ``sync.stability.divergence_age_s`` log2 histogram plus p50/max
  gauges; still-diverged subtrees feed ``sync.stability.outstanding``
  and the per-peer ``sync.peer.<peer>.divergence_age_s`` oldest-age
  gauge — a subtree that stays diverged across rounds is an alertable
  series, not invisible churn.

* **Fleet stability frontier** — a CLEAN converged exchange (digest-
  tree root equality, or flat digest-vector equality, with ZERO
  divergence found) proves the peer's COMMITTED state byte-identical
  to ours: both digests folded state each node already held before
  the session, so "the peer witnessed every dot in our per-subtree
  version vectors" survives anything that happens afterwards — a
  session that shipped deltas defers its evidence to the next idle
  re-sync instead, because the peer could still discard the
  un-committed merge on a late failure.
  :class:`StabilityTracker.observe_converged` records those
  per-subtree clocks per peer (one jitted frontier fold —
  :func:`subtree_version_vectors`, memoized beside the digest vector);
  :meth:`StabilityTracker.frontier` takes the element-wise MIN over
  every non-quarantined peer — per subtree, plus the fleet-min clock —
  under the same liveness rules as the GC watermark
  (:mod:`crdt_tpu.gc.watermark`): unheard roster peers pin zero, stale
  peers freeze their last contribution, silence past ``quarantine_s``
  excludes a dead peer.  Published as ``crdt_tpu_stability_frontier_*``
  gauges, min-joined across the PR 6 fleet lattice
  (:meth:`~crdt_tpu.obs.fleet.FleetSnapshot.fleet_stability`), served
  at ``GET /stability``, persisted in durable snapshots and restored
  as a monotone floor on rejoin (same discipline as
  ``GcEngine.restore_watermark``: stability is monotone — counters at
  or below a previously fleet-stable frontier were converged past by
  every peer THEN, and counters only grow).  This is the exact
  structure the future truncate-epoch proposer and op-log stability
  compaction consume.

* **Runtime lattice auditor** — :meth:`StabilityTracker.audit` is the
  online tripwire for the whole lattice stack: per gossip round it
  re-merges a seeded random sample of objects against their own state
  through the real wire codec (``gather_blobs`` → ``from_wire`` →
  ``merge``) and re-digests them — idempotence means the digest must
  be bit-stable against the live fleet's rows — and cross-checks the
  published frontier against the local per-subtree version vectors and
  every freshly-advertised peer version vector.  Checks and violations
  count under ``stability.audit.{checks,violations}``; ANY violation
  additionally lands a loud ``stability.audit_violation`` flight-
  recorder event naming the plane that lied.

Frontier semantics caveat (documented, not hidden): a peer that
crashes and restores from a snapshot OLDER than its last converged
session can briefly lag the frontier until its rejoin delta sync
completes — the same at-least-once window the GC watermark's restore
already accepts; drive checkpoints at round end (the scheduler's
default) to keep the window one round wide.

Stdlib-only at module scope (the obs-package discipline): numpy and
jax import lazily inside the fold/audit paths, so a scraper box can
import this module for :meth:`StabilityTracker.snapshot` shapes
without the device runtime.
"""

from __future__ import annotations

import dataclasses
import functools
import random
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

from . import convergence as convergence_mod
from . import events as events_mod
from . import metrics as metrics_mod

#: resolved divergence ages retained for the p50/max gauges
RESOLVED_WINDOW = 512

#: gauge sentinel: no divergence has ever been observed/resolved
AGE_UNKNOWN = -1.0


def subtree_layout(n: int) -> Tuple[int, int]:
    """``(subtrees, span)`` of the digest tree's top children level for
    an ``n``-object fleet: subtree ``s`` covers objects ``[s*span,
    (s+1)*span)`` — the node-coverage rule of :mod:`crdt_tpu.sync.tree`
    (node ``i`` at level ``l`` covers leaves ``[i*k**l, (i+1)*k**l)``),
    evaluated at the level just below the root.  At most ``TREE_K``
    subtrees by construction (they are the root's children), so every
    per-subtree table here is bounded independent of fleet size."""
    from ..sync.tree import TREE_K

    if n <= 0:
        return 0, 1
    levels, size = 1, n
    while size > 1:
        size = -(-size // TREE_K)
        levels += 1
    if levels < 2:  # a one-object fleet folds straight to the root
        return 1, 1
    span = TREE_K ** (levels - 2)
    return -(-n // span), span


@functools.lru_cache(maxsize=None)
def _frontier_kernel(subtrees: int):
    """ONE jitted frontier fold: ``clock[S*span, W] -> vv[S, W]`` — the
    per-subtree version-vector summary (pointwise max over each
    subtree's object rows), the per-subtree analogue of
    :func:`crdt_tpu.sync.digest.version_vector`.  ``subtrees`` is
    static (the factory closes over it), so the lowering count walks
    the same bounded ladder as every other manifest row."""
    import jax
    import jax.numpy as jnp

    from .kernels import observed_kernel

    def kernel(clock):
        return jnp.max(
            clock.reshape(subtrees, -1, clock.shape[-1]), axis=1)

    return observed_kernel("obs.stability.frontier_fold")(jax.jit(kernel))


def _clock_plane(batch):
    """The batch's clock plane flattened to ``[N, W]`` (PNCounter's
    ``[N, 2, A]`` flattens to ``[N, 2A]`` — same convention as its
    version vector), or None for clockless types (LWW)."""
    import numpy as np

    from ..batch.gcounter_batch import GCounterBatch
    from ..batch.lwwreg_batch import LWWRegBatch
    from ..batch.orswot_batch import OrswotBatch
    from ..batch.pncounter_batch import PNCounterBatch
    from ..batch.vclock_batch import VClockBatch

    if isinstance(batch, OrswotBatch):
        clocks = batch.clock
    elif isinstance(batch, PNCounterBatch):
        clocks = batch.planes
    elif isinstance(batch, (GCounterBatch, VClockBatch)):
        clocks = batch.clocks
    elif isinstance(batch, LWWRegBatch):
        return None
    else:
        raise TypeError(
            f"no clock plane for {type(batch).__name__} "
            "(supported: Orswot/PNCounter/GCounter/VClock batches)"
        )
    host = np.asarray(clocks)
    return host.reshape(host.shape[0], -1)


def subtree_version_vectors(batch):
    """``uint64[S, W]`` per-subtree version vectors of ``batch``
    (:func:`subtree_layout` rows), or None for clockless types.
    Memoized on the batch object beside the digest vector
    (:class:`crdt_tpu.sync.digest.DigestCache` — mutating paths always
    produce a new batch, so a hit can never serve stale clocks; idle
    converged rounds therefore run ZERO frontier folds)."""
    import numpy as np

    from ..sync import digest as digest_mod

    cache = digest_mod.digest_cache()
    cached = cache.get(batch, None, "subtree_vv")
    if cached is not None:
        return cached
    host = _clock_plane(batch)
    if host is None:
        return None
    n = int(host.shape[0])
    subtrees, span = subtree_layout(n)
    if subtrees == 0:
        out = np.zeros((0, host.shape[1]), dtype=np.uint64)
    else:
        import jax.numpy as jnp

        pad = subtrees * span - n
        if pad:
            host = np.concatenate(
                [host, np.zeros((pad,) + host.shape[1:], host.dtype)])
        out = np.asarray(
            _frontier_kernel(subtrees)(jnp.asarray(host))
        ).astype(np.uint64)
    cache.put(batch, None, "subtree_vv", out)
    return out


def _align_rows(rows: List, width: int) -> List:
    """Zero-pad clock rows to a common actor width (implied-0 counters,
    the `vclock.rs:206-210` rule — conservative, never unsafe)."""
    import numpy as np

    out = []
    for r in rows:
        r = np.asarray(r, dtype=np.uint64).reshape(-1)
        if r.size < width:
            r = np.concatenate(
                [r, np.zeros(width - r.size, dtype=np.uint64)])
        out.append(r[:width] if r.size > width else r)
    return out


@dataclasses.dataclass
class FrontierReport:
    """One frontier computation's outcome.

    ``clock`` is the fleet-min frontier (``uint64[W]``): the
    element-wise min over every contributing peer's WHOLE-FLEET version
    vector at its last converged session — a peer that converged with
    our whole state witnessed every dot at or below that vector (dots
    mint monotonically per actor), so counters at or below ``clock``
    are witnessed by every non-quarantined peer on EVERY object.
    ``subtree_clocks`` is ``uint64[S, W]`` — the per-subtree min-join,
    never below ``clock`` (the fleet-wide claim covers every subtree).
    All-zero whenever any included roster peer is unheard."""

    clock: object                 # numpy uint64[W]
    subtree_clocks: object        # numpy uint64[S, W]
    subtrees: int = 0
    peers: int = 0                # peers contributing converged clocks
    stale: int = 0                # contributing but past stale_after_s
    unheard: int = 0              # roster peers never converged with
    excluded: int = 0             # quarantined out of the minimum
    age_s: float = 0.0            # oldest contributing observation's age

    @property
    def frozen(self) -> bool:
        return self.stale > 0 or self.unheard > 0


@dataclasses.dataclass
class AuditReport:
    """One lattice-audit pass's outcome.  ``violations`` entries name
    the plane that lied (``merge_idempotence`` / ``frontier_local`` /
    ``frontier_peer_vv``) with enough detail to reproduce."""

    checks: int = 0
    sampled: int = 0
    violations: List[dict] = dataclasses.field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.violations


class _PeerStability:
    __slots__ = ("outstanding", "clocks", "converged_ts")

    def __init__(self):
        # subtree -> birth timestamp of the CURRENT divergence episode
        # (monotonic seconds); absent = currently believed converged
        self.outstanding: Dict[int, float] = {}
        # per-subtree converged clocks: tuple of row-tuples (stdlib —
        # numpy only enters at fold/min time), element-wise-max merged
        # so the evidence is monotone per (peer, subtree)
        self.clocks: Optional[Tuple[Tuple[int, ...], ...]] = None
        self.converged_ts: Optional[float] = None


class StabilityTracker:
    """Divergence aging + stability frontier + lattice auditor for one
    observer (a :class:`~crdt_tpu.cluster.gossip.ClusterNode` owns a
    private one, like its lag tracker; standalone sessions feed the
    process-global :func:`tracker`).

    ``stale_after_s`` / ``quarantine_s`` mirror the GC watermark's
    liveness knobs; ``tracker`` is the
    :class:`~crdt_tpu.obs.convergence.ConvergenceTracker` whose cached
    peer version vectors the auditor cross-checks (the process-global
    one by default); ``audit_sample`` / ``audit_every`` bound the
    auditor's per-round budget (0 disables it); ``clock`` is
    injectable for tests (monotonic seconds).
    """

    def __init__(self, *,
                 registry: Optional[metrics_mod.MetricsRegistry] = None,
                 tracker: Optional[convergence_mod.ConvergenceTracker]
                 = None,
                 stale_after_s: float = 30.0,
                 quarantine_s: float = 300.0,
                 audit_sample: int = 8,
                 audit_every: int = 1,
                 seed: int = 0,
                 clock=time.monotonic):
        if not 0.0 < stale_after_s <= quarantine_s:
            raise ValueError(
                f"need 0 < stale_after_s <= quarantine_s, got "
                f"{stale_after_s}/{quarantine_s}"
            )
        self._registry = registry
        self._tracker = tracker
        self.stale_after_s = stale_after_s
        self.quarantine_s = quarantine_s
        self.audit_sample = int(audit_sample)
        self.audit_every = int(audit_every)
        self._clock = clock
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self._peers: Dict[str, _PeerStability] = {}
        # resolved divergence ages, bounded (the p50/max gauge window)
        self._resolved: deque = deque(maxlen=RESOLVED_WINDOW)
        self._resolved_total = 0
        # roster peers never converged with quarantine off their first
        # sighting (there is no observation to age them by)
        self._first_seen: Dict[str, float] = {}
        # a fleet-min clock persisted by a snapshot and restored across
        # a restart — a safe monotone floor, for every subtree (module
        # docstring: the fleet-wide claim covers every object)
        self._floor: Optional[Tuple[int, ...]] = None
        # the last PUBLISHED clocks: the per-observer monotone floors
        # ("the frontier never regresses per observer")
        self._published: Optional[tuple] = None           # [S][W]
        self._published_global: Optional[Tuple[int, ...]] = None
        self._audit_rounds = 0
        self._audit_checks = 0
        self._audit_violations = 0
        self._last_violation: Optional[dict] = None

    def _reg(self) -> metrics_mod.MetricsRegistry:
        return self._registry if self._registry is not None \
            else metrics_mod.registry()

    def _conv(self) -> convergence_mod.ConvergenceTracker:
        return self._tracker if self._tracker is not None \
            else convergence_mod.tracker()

    def _state(self, peer: str) -> _PeerStability:
        st = self._peers.get(peer)
        if st is None:
            st = self._peers[peer] = _PeerStability()
        return st

    # -- plane 1: divergence aging -------------------------------------------

    def observe_descent(self, peer: str, diverged_ids, objects: int,
                        at: Optional[float] = None) -> None:
        """Fold one digest exchange's diverged row set vs ``peer`` into
        the birth→resolution tracker: rows map onto top-level subtrees
        (:func:`subtree_layout`), newly-diverged subtrees are born at
        this observation, and tracked subtrees ABSENT from the set are
        resolved — their digests match again, so the episode's age is
        measured and published.  An episode that spans many exchanges
        keeps its original birth (the age grows, which is the point)."""
        subtrees, span = subtree_layout(int(objects))
        now = self._clock() if at is None else at
        current = {int(i) // span for i in diverged_ids}
        resolved: List[Tuple[int, float]] = []
        with self._lock:
            st = self._state(peer)
            for s in list(st.outstanding):
                if s not in current:
                    resolved.append((s, max(0.0, now - st.outstanding.pop(s))))
            for s in current:
                st.outstanding.setdefault(s, now)
            for _, age in resolved:
                self._resolved.append(age)
            self._resolved_total += len(resolved)
        self._publish_aging(peer, resolved)

    def resolve_all(self, peer: str, at: Optional[float] = None) -> None:
        """Resolve every outstanding subtree vs ``peer`` — what a
        converged session means (the digest oracle found NOTHING
        diverged)."""
        now = self._clock() if at is None else at
        resolved: List[Tuple[int, float]] = []
        with self._lock:
            st = self._state(peer)
            for s in list(st.outstanding):
                resolved.append((s, max(0.0, now - st.outstanding.pop(s))))
            for _, age in resolved:
                self._resolved.append(age)
            self._resolved_total += len(resolved)
        self._publish_aging(peer, resolved)

    def _publish_aging(self, peer: str,
                       resolved: List[Tuple[int, float]]) -> None:
        from ..utils import tracing

        now = self._clock()
        with self._lock:
            outstanding = sum(
                len(st.outstanding) for st in self._peers.values())
            births = self._peers[peer].outstanding.values() \
                if peer in self._peers else ()
            oldest = (now - min(births)) if births else 0.0
            window = sorted(self._resolved)
        reg = self._reg()
        for _, age in resolved:
            reg.observe("sync.stability.divergence_age_s", age)
        if resolved:
            tracing.count("sync.stability.resolved", len(resolved))
            ages = [age for _, age in resolved]
            events_mod.record(
                "stability.resolved", peer=peer, subtrees=len(resolved),
                max_age_s=round(max(ages), 6))
        reg.gauge_set("sync.stability.outstanding", outstanding)
        reg.gauge_set(f"sync.peer.{peer}.divergence_age_s",
                      round(max(0.0, oldest), 6))
        if window:
            mid = window[min(len(window) - 1,
                             max(0, int(round(0.5 * (len(window) - 1)))))]
            reg.gauge_set("sync.stability.divergence_age_p50_s",
                          round(mid, 6))
            reg.gauge_set("sync.stability.divergence_age_max_s",
                          round(window[-1], 6))
        else:
            reg.gauge_set("sync.stability.divergence_age_p50_s", AGE_UNKNOWN)
            reg.gauge_set("sync.stability.divergence_age_max_s", AGE_UNKNOWN)

    def oldest_divergence_age_s(self) -> float:
        """Age of the oldest still-diverged subtree across every peer
        (0 = nothing outstanding) — what the demo prints at
        convergence."""
        now = self._clock()
        with self._lock:
            births = [b for st in self._peers.values()
                      for b in st.outstanding.values()]
        return max(0.0, now - min(births)) if births else 0.0

    # -- plane 2: the fleet stability frontier -------------------------------

    def observe_converged(self, peer: str, batch,
                          at: Optional[float] = None) -> None:
        """One CLEAN converged exchange vs ``peer``: the digest oracle
        proved the peer's committed state byte-identical to ``batch``
        (zero divergence — no uncommitted merge involved), so the peer
        has witnessed every dot in the batch's per-subtree version
        vectors.  Records those clocks (element-wise-max merged —
        evidence is monotone) and resolves all outstanding divergence
        aging.  Callers must only feed sessions that shipped NO deltas
        (:mod:`crdt_tpu.sync.session` enforces this); a delta session's
        evidence lands on the next idle re-sync."""
        self.resolve_all(peer, at=at)
        svv = subtree_version_vectors(batch)
        if svv is None:
            return  # clockless type: aging only, no frontier plane
        now = self._clock() if at is None else at
        fresh = tuple(tuple(int(c) for c in row) for row in svv)
        with self._lock:
            st = self._state(peer)
            old = st.clocks
            if old is None or len(old) != len(fresh):
                st.clocks = fresh
            else:
                st.clocks = tuple(
                    tuple(max(a, b) for a, b in
                          _zip_pad(old_row, new_row))
                    for old_row, new_row in zip(old, fresh))
            st.converged_ts = now
            self._first_seen.pop(peer, None)

    def frontier(self, batch, peers=None,
                 at: Optional[float] = None) -> Optional[FrontierReport]:
        """Compute (and publish) the stability frontier given the local
        ``batch`` and an optional peer roster.

        Without a roster, every peer with recorded converged clocks
        contributes (subject to quarantine).  With one, roster peers
        WITHOUT recorded clocks pin the frontier at zero until their
        quarantine expires — "I have never converged with n3" made
        explicit, exactly the GC watermark's membership rule.  The
        local node always contributes its own subtree clocks (a
        peer-less fleet's frontier is its own frontier).  The restored
        floor and the last published value apply as element-wise
        maxima, so the published series is monotone per observer.
        Returns None (publishing nothing) for clockless batch types."""
        import numpy as np

        svv = subtree_version_vectors(batch)
        if svv is None:
            return None
        subtrees = int(svv.shape[0])
        width = int(svv.shape[1]) if svv.ndim == 2 else 0
        now = self._clock() if at is None else at
        report = FrontierReport(
            clock=np.zeros(width, np.uint64),
            subtree_clocks=np.zeros((subtrees, width), np.uint64),
            subtrees=subtrees)

        contributing: List[tuple] = []
        with self._lock:
            known = {p for p, st in self._peers.items()
                     if st.clocks is not None}
            roster = set(peers) if peers is not None else set(known)
            for peer in sorted(roster | known):
                st = self._peers.get(peer)
                if st is None or st.clocks is None:
                    if peer not in roster:
                        continue
                    first = self._first_seen.setdefault(peer, now)
                    if now - first > self.quarantine_s:
                        report.excluded += 1
                    else:
                        report.unheard += 1
                    continue
                self._first_seen.pop(peer, None)
                age = max(0.0, now - st.converged_ts)
                if age > self.quarantine_s:
                    report.excluded += 1
                    continue
                report.peers += 1
                report.age_s = max(report.age_s, age)
                if age > self.stale_after_s:
                    report.stale += 1
                contributing.append(st.clocks)
            floor = self._floor
            published = self._published
            published_global = self._published_global

        local_vv = svv.max(axis=0).astype(np.uint64) if subtrees else \
            np.zeros(width, np.uint64)
        if report.unheard:
            clocks = np.zeros((subtrees, width), np.uint64)
            fleet_min = np.zeros(width, np.uint64)
        else:
            clocks = svv.astype(np.uint64).copy()
            fleet_min = local_vv.copy()
            for peer_clocks in contributing:
                # the peer's whole-fleet clock at convergence: the max
                # over its subtree rows (all recorded at one converged
                # session) — every dot at or below it was in the state
                # the peer proved byte-identical, so it bounds the
                # fleet-min clock
                rows = _align_rows(list(peer_clocks), width)
                peer_global = np.zeros(width, np.uint64)
                for r in rows:
                    peer_global = np.maximum(peer_global, r)
                fleet_min = np.minimum(fleet_min, peer_global)
                for s in range(min(subtrees, len(rows))):
                    clocks[s] = np.minimum(clocks[s], rows[s])
                # a peer whose table is SHORTER than the local subtree
                # count has no per-subtree evidence for the missing
                # rows: pin them 0 (the fleet-min floor below re-raises
                # what the fleet-wide claim still covers)
                for s in range(len(peer_clocks), subtrees):
                    clocks[s] = 0
        # monotone floors, element-wise max (stability is monotone —
        # module docstring): the restored snapshot clock and the last
        # published values may only ever RAISE the minimum.  The
        # fleet-min clock floors every subtree row too — its
        # justification is fleet-wide, covering every object.
        if floor is not None:
            fl = _align_rows([floor], width)[0]
            fleet_min = np.maximum(fleet_min, fl)
        if published_global is not None:
            fleet_min = np.maximum(
                fleet_min, _align_rows([published_global], width)[0])
        for s in range(subtrees):
            clocks[s] = np.maximum(clocks[s], fleet_min)
            if published is not None and s < len(published):
                clocks[s] = np.maximum(
                    clocks[s], _align_rows([published[s]], width)[0])
        report.subtree_clocks = clocks
        report.clock = fleet_min
        with self._lock:
            self._published = tuple(
                tuple(int(c) for c in row) for row in clocks)
            self._published_global = tuple(int(c) for c in fleet_min)

        lag = int((local_vv - np.minimum(local_vv, report.clock))
                  .max(initial=0))
        reg = self._reg()
        reg.gauge_set("stability.frontier.peers", report.peers)
        reg.gauge_set("stability.frontier.stale", report.stale)
        reg.gauge_set("stability.frontier.unheard", report.unheard)
        reg.gauge_set("stability.frontier.excluded", report.excluded)
        reg.gauge_set("stability.frontier.subtrees", subtrees)
        reg.gauge_set("stability.frontier.age_s", round(report.age_s, 3))
        reg.gauge_set("stability.frontier.max_counter",
                      int(report.clock.max(initial=0)))
        reg.gauge_set("stability.frontier.lag", lag)
        for s in range(subtrees):
            reg.gauge_set(f"stability.frontier.subtree.{s}.max_counter",
                          int(clocks[s].max(initial=0)))
        return report

    def frontier_clock(self):
        """The last published fleet-min frontier clock as
        ``uint64[W]`` (None until :meth:`frontier` ran) — what a
        durable checkpoint persists and :meth:`restore` floors a
        rejoined observer with."""
        import numpy as np

        with self._lock:
            published = self._published_global
        if published is None:
            return None
        return np.asarray(published, dtype=np.uint64)

    def subtree_frontier_clocks(self):
        """The last published per-subtree frontier clocks as
        ``uint64[S, W]`` (None until :meth:`frontier` ran)."""
        import numpy as np

        with self._lock:
            published = self._published
        if published is None:
            return None
        return np.asarray(published, dtype=np.uint64)

    def restore(self, clock) -> None:
        """Seed the frontier with a fleet-min clock persisted by a
        snapshot (:mod:`crdt_tpu.durable`): counters at or below it
        were fleet-converged when the snapshot was taken, and stability
        is monotone, so the restored value is a safe floor under every
        future minimum — a restarted observer's frontier resumes
        instead of regressing to zero until its peers re-converge.
        Accepts one flat clock (a 2-D array floors at its row-wise
        minimum — the conservative read of a per-subtree table)."""
        import numpy as np

        arr = np.asarray(clock, dtype=np.uint64)
        if arr.ndim > 1:
            arr = arr.min(axis=0)
        with self._lock:
            self._floor = tuple(int(c) for c in arr.reshape(-1))

    def forget(self, peer: str) -> None:
        """Drop a peer's frontier/aging bookkeeping (it left the
        roster)."""
        with self._lock:
            self._peers.pop(peer, None)
            self._first_seen.pop(peer, None)

    # -- plane 3: the runtime lattice auditor --------------------------------

    def maybe_audit(self, batch, universe=None, peers=None
                    ) -> Optional[AuditReport]:
        """The per-round cadence hook: runs :meth:`audit` every
        ``audit_every``-th call (0 disables the auditor)."""
        if self.audit_every <= 0:
            return None
        with self._lock:
            self._audit_rounds += 1
            due = self._audit_rounds % self.audit_every == 0
        if not due:
            return None
        return self.audit(batch, universe, peers=peers)

    def audit(self, batch, universe=None, peers=None,
              sample: Optional[int] = None) -> AuditReport:
        """One budget-bounded lattice self-check (module docstring):
        sampled merge idempotence through the real wire codec, frontier
        vs local subtree version vectors, frontier vs freshly-advertised
        peer version vectors.  Violations are loud: counter + a
        ``stability.audit_violation`` flight-recorder event each."""
        from ..utils import tracing

        report = AuditReport()
        with tracing.span("stability.audit"):
            self._audit_merge_idempotence(
                batch, universe, report,
                self.audit_sample if sample is None else int(sample))
            self._audit_frontier(batch, report)
        with self._lock:
            self._audit_checks += report.checks
            self._audit_violations += len(report.violations)
            if report.violations:
                self._last_violation = dict(report.violations[-1])
        tracing.count("stability.audit.checks", report.checks)
        if report.violations:
            tracing.count("stability.audit.violations",
                          len(report.violations))
            for v in report.violations:
                events_mod.record("stability.audit_violation", **{
                    k: (vv if isinstance(vv, (int, float, str, bool))
                        else str(vv)[:200])
                    for k, vv in v.items()})
        return report

    def _audit_merge_idempotence(self, batch, universe, report,
                                 sample: int) -> None:
        """Sampled self-merge: gather N random rows through the wire
        codec, merge the sub-fleet with ITSELF, and require the merged
        digests bit-equal to the live fleet's rows — one check covers
        wire-roundtrip fidelity, merge idempotence (the ACI contract's
        I) and digest stability at once."""
        import numpy as np

        from ..sync import digest as digest_mod

        try:
            ref = np.asarray(digest_mod.digest_of(batch, universe),
                             dtype=np.uint64)
        except TypeError:
            return  # no digest kernel for this batch type
        n = int(ref.shape[0])
        k = min(int(sample), n)
        if k <= 0:
            return
        with self._lock:
            ids = np.asarray(
                sorted(self._rng.sample(range(n), k)), dtype=np.int64)
        try:
            from ..sync.delta import gather_blobs

            blobs = gather_blobs(batch, ids, universe)
            sub = type(batch).from_wire(blobs, universe)
            merged = sub.merge(sub)
        except (TypeError, AttributeError):
            return  # batch type without the wire/merge surface
        got = np.asarray(digest_mod.digest_of(merged, universe),
                         dtype=np.uint64)
        report.checks += k
        report.sampled += k
        bad = ids[got != ref[ids]]
        if bad.size:
            report.violations.append({
                "plane": "merge_idempotence",
                "objects": ",".join(str(int(b)) for b in bad[:16]),
                "count": int(bad.size),
            })

    def _audit_frontier(self, batch, report) -> None:
        """Frontier soundness: the published frontier must never exceed
        the local per-subtree version vectors (we claim the fleet
        converged past clocks we ourselves hold), and the fleet-min
        clock must never exceed any FRESHLY-advertised peer version
        vector (a peer that just told us its applied clock cannot be
        behind what we published as fleet-stable)."""
        import numpy as np

        with self._lock:
            published_global = self._published_global
        if published_global is None:
            return
        svv = subtree_version_vectors(batch)
        if svv is not None and svv.shape[0]:
            # the fleet-min clock claims every peer witnessed every dot
            # at or below it — dots WE hold included, so it can never
            # exceed the local whole-fleet version vector
            report.checks += 1
            local_vv = svv.max(axis=0).astype(np.uint64)
            width = max(int(local_vv.shape[0]), len(published_global))
            fr, local = _align_rows([published_global, local_vv], width)
            if (fr > local).any():
                report.violations.append({
                    "plane": "frontier_local",
                    "frontier_max": int(fr.max(initial=0)),
                    "local_max": int(local.max(initial=0)),
                })
        fleet_min = np.asarray(published_global, dtype=np.uint64)
        now = self._clock()
        with self._lock:
            # cross-check only peers THIS observer holds frontier
            # evidence for: the minimum ran over their clocks, so their
            # advertised VVs are the exact soundness bound (a foreign
            # fleet's labels in the shared convergence tracker are not)
            tracked = {p for p, st in self._peers.items()
                       if st.clocks is not None}
        for peer, (vv, seen_ts) in sorted(
                self._conv().version_vectors().items()):
            if peer not in tracked:
                continue
            if seen_ts is None or now - seen_ts > self.stale_after_s:
                continue  # stale advertisement: not comparable evidence
            report.checks += 1
            width = max(len(vv), int(fleet_min.shape[0]))
            fr, theirs = _align_rows([fleet_min, vv], width)
            if (fr > theirs).any():
                report.violations.append({
                    "plane": "frontier_peer_vv",
                    "peer": peer,
                    "frontier_max": int(fr.max(initial=0)),
                    "peer_vv_max": int(theirs.max(initial=0)),
                })

    # -- snapshots ------------------------------------------------------------

    def snapshot(self) -> dict:
        """JSON-ready state — what ``GET /stability`` serves: the
        published frontier (per-subtree and fleet-min clocks), the
        divergence-aging view (per-peer outstanding subtrees with live
        ages, resolved stats), and the audit totals."""
        now = self._clock()
        with self._lock:
            published = self._published
            published_global = self._published_global
            aging = {
                peer: {
                    "outstanding": {
                        str(s): round(max(0.0, now - born), 6)
                        for s, born in st.outstanding.items()
                    },
                    "converged_age_s": (
                        None if st.converged_ts is None
                        else round(max(0.0, now - st.converged_ts), 6)),
                }
                for peer, st in self._peers.items()
            }
            window = sorted(self._resolved)
            resolved_total = self._resolved_total
            audit = {
                "checks": self._audit_checks,
                "violations": self._audit_violations,
                "last_violation": self._last_violation,
            }
        clocks = [list(row) for row in published] \
            if published is not None else None
        fleet_min = list(published_global) \
            if published_global is not None else None
        return {
            "frontier": {
                "subtree_clocks": clocks,
                "fleet_min": fleet_min,
                "subtrees": len(clocks) if clocks is not None else 0,
            },
            "aging": {
                "peers": aging,
                "resolved_total": resolved_total,
                "resolved_age_p50_s": (
                    window[len(window) // 2] if window else None),
                "resolved_age_max_s": window[-1] if window else None,
            },
            "audit": audit,
        }

    def reset(self) -> None:
        with self._lock:
            self._peers.clear()
            self._resolved.clear()
            self._resolved_total = 0
            self._first_seen.clear()
            self._floor = None
            self._published = None
            self._published_global = None
            self._audit_rounds = 0
            self._audit_checks = 0
            self._audit_violations = 0
            self._last_violation = None


def _zip_pad(a: tuple, b: tuple):
    """zip two counter rows, implied-0 past either end."""
    width = max(len(a), len(b))
    for i in range(width):
        yield (a[i] if i < len(a) else 0), (b[i] if i < len(b) else 0)


# -- the default (process-global) tracker -------------------------------------

_DEFAULT: Optional[StabilityTracker] = None
_DEFAULT_LOCK = threading.Lock()


def tracker() -> StabilityTracker:
    """The process-global stability tracker — what standalone sessions
    feed and ``GET /stability`` serves by default (cluster nodes own
    private ones so in-process fleets keep their observers apart)."""
    global _DEFAULT
    if _DEFAULT is None:
        with _DEFAULT_LOCK:
            if _DEFAULT is None:
                _DEFAULT = StabilityTracker()
    return _DEFAULT


#: package-level alias (``crdt_tpu.obs.stability_tracker``) — the
#: un-shadowed name next to ``convergence.tracker`` / ``lag_tracker``
stability_tracker = tracker
