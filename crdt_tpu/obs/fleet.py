"""Fleet observatory — CRDT-merged cross-process telemetry.

One process's registry snapshot answers "what did *this* replica do";
a fleet needs the union.  The insight this module dogfoods is that a
telemetry snapshot is itself a join-semilattice, so fleet aggregation
is one more commutative/associative/idempotent merge — the same
anti-entropy shape the CRDTs under observation use (Shapiro et al.;
riak_dt shipped its stats the same way).  Per-kind join semantics:

* **counters** — per-node-keyed, merged by per-node ``max`` (counter
  values are monotone per process, so the latest capture dominates):
  a G-Counter with the node id as the actor.  The *fleet* counter is
  the sum over nodes, and re-delivered snapshots are idempotent — the
  acceptance property a gossiping, duplicating transport demands.
* **gauges** — LWW by capture stamp ``(wall_ts, seq)``, per
  ``(name, node)``; the fleet gauge is the newest capture fleet-wide.
* **histograms** — per-node LWW by capture stamp (bucket counts are
  monotone per process, so newest-capture-wins is the per-node join);
  the fleet histogram is the bucket-wise sum across nodes.

Snapshots travel as versioned, CRC-guarded frames (the same envelope
discipline as :mod:`crdt_tpu.sync.delta`: mixed versions fail loudly
as :class:`~crdt_tpu.error.SyncProtocolError`, never misparse) over
two paths: piggybacked on gossip sync sessions
(:class:`~crdt_tpu.sync.session.SyncSession` ``observatory=``), and an
all-gather over :func:`crdt_tpu.parallel.collective.
allgather_fleet_snapshots` for pjit meshes with no network peers.
Because an observatory ships its *merged* snapshot, slices spread
transitively: a node learns about peers it never dialed.

The flight-recorder tail each slice carries feeds
:func:`stitch_trace`: given the fleet-unique trace ID a sync hello
negotiated, it reconstructs the cross-peer session timeline from the
merged slices — both halves of one session, one ordered story.

Stdlib-only (no jax, no numpy): an observatory must be importable from
any process that owns a metrics registry, scraper boxes included.
"""

from __future__ import annotations

import itertools
import json
import struct
import threading
import time
import zlib
from typing import Dict, Iterable, List, Optional

from ..error import SyncProtocolError
from . import convergence as convergence_mod
from . import events as events_mod
from . import metrics as metrics_mod
from .capacity import ETA_NOT_GROWING
from .namespace import sanitize as _sanitize

#: bumped whenever the snapshot grammar changes; a peer speaking a
#: different version must fail loudly at the first frame
FLEET_PROTOCOL_VERSION = 1

#: frame type byte — disjoint from the sync codec's 0x01-0x0f range so
#: a misrouted frame is an immediate unknown-type rejection either way
FRAME_FLEET_SNAPSHOT = 0x21

_HEADER = struct.Struct("<BBIQ")  # version | type | crc32 | payload_len

#: flight-recorder events retained per node slice (the stitcher's
#: working set; bounded so a snapshot frame stays a few KB)
EVENTS_TAIL = 128

_CAPTURE_SEQ = itertools.count(1)


def _canon(obj) -> str:
    """Canonical JSON — the deterministic tie-breaker and equality key."""
    return json.dumps(obj, sort_keys=True, separators=(",", ":"))


def _stamp_key(entry) -> tuple:
    """Total order over stamped entries ``[ts, seq, value]``: capture
    stamp first, canonical value as the final tie-break so the LWW pick
    stays commutative even for (theoretically) equal stamps."""
    return (entry[0], entry[1], _canon(entry[2]))


def _merge_stamped(a: Dict[str, list], b: Dict[str, list]) -> Dict[str, list]:
    """Pointwise LWW join of two ``{name: [ts, seq, value]}`` maps."""
    out = dict(a)
    for name, entry in b.items():
        cur = out.get(name)
        if cur is None or _stamp_key(entry) > _stamp_key(cur):
            out[name] = entry
    return out


def _merge_events(a: List[dict], b: List[dict]) -> List[dict]:
    """Union of two event tails from ONE node, keyed by the recorder's
    per-process ``seq`` (idempotent under re-delivery), trimmed to the
    newest :data:`EVENTS_TAIL`."""
    by_seq = {ev.get("seq", 0): ev for ev in a}
    for ev in b:
        by_seq.setdefault(ev.get("seq", 0), ev)
    tail = [by_seq[s] for s in sorted(by_seq)]
    return tail[-EVENTS_TAIL:]


class FleetSnapshot:
    """A mergeable fleet telemetry state: one slice per node id.

    ``slices`` maps node id → JSON-ready slice dict (see
    :func:`capture_slice` for the shape).  Instances are treated as
    immutable: :meth:`merge` returns a new snapshot, so a scrape can
    render one while gossip merges another.
    """

    __slots__ = ("slices",)

    def __init__(self, slices: Optional[Dict[str, dict]] = None):
        self.slices = slices or {}

    # -- the lattice ---------------------------------------------------------

    def merge(self, other: "FleetSnapshot") -> "FleetSnapshot":
        """The join: per-kind semantics within a node (counters max,
        gauges/histograms/convergence LWW by capture stamp, event tails
        seq-unioned), slice union across nodes.  Commutative,
        associative, idempotent — property-tested in
        ``tests/test_fleet_obs.py``."""
        merged = dict(self.slices)
        for node, theirs in other.slices.items():
            mine = merged.get(node)
            merged[node] = theirs if mine is None \
                else _merge_slice(mine, theirs)
        return FleetSnapshot(merged)

    def __eq__(self, other) -> bool:
        return isinstance(other, FleetSnapshot) and \
            _canon(self.slices) == _canon(other.slices)

    def __hash__(self):  # canonical-JSON equality needs a matching hash
        return hash(_canon(self.slices))

    # -- fleet views ---------------------------------------------------------

    def nodes(self) -> List[str]:
        return sorted(self.slices)

    def fleet_counters(self) -> Dict[str, int]:
        """Every counter name → the SUM of the per-node values (the
        G-Counter read: each node contributes its own latest value
        exactly once, however many times its snapshot was delivered)."""
        out: Dict[str, int] = {}
        for sl in self.slices.values():
            for name, v in sl.get("counters", {}).items():
                out[name] = out.get(name, 0) + int(v)
        return out

    def counters_by_node(self, name: str) -> Dict[str, int]:
        return {
            node: int(sl["counters"][name])
            for node, sl in self.slices.items()
            if name in sl.get("counters", {})
        }

    def fleet_gauges(self) -> Dict[str, float]:
        """Every gauge name → the newest capture's value fleet-wide
        (LWW across nodes, same order as within a node)."""
        best: Dict[str, list] = {}
        for sl in self.slices.values():
            best = _merge_stamped(best, sl.get("gauges", {}))
        return {name: entry[2] for name, entry in best.items()}

    def fleet_histograms(self) -> Dict[str, dict]:
        """Every histogram name → the bucket-wise sum across nodes
        (count/sum add, min/max combine) — each node's latest capture
        contributes once."""
        out: Dict[str, dict] = {}
        for sl in self.slices.values():
            for name, entry in sl.get("histograms", {}).items():
                h = entry[2]
                acc = out.get(name)
                if acc is None:
                    acc = out[name] = {
                        "count": 0, "sum": 0.0, "min": None, "max": None,
                        "buckets": {},
                    }
                acc["count"] += int(h.get("count", 0))
                acc["sum"] += float(h.get("sum", 0.0))
                for bound in ("min", "max"):
                    v = h.get(bound)
                    if v is None:
                        continue
                    cur = acc[bound]
                    pick = min if bound == "min" else max
                    acc[bound] = v if cur is None else pick(cur, v)
                for e, n in h.get("buckets", {}).items():
                    acc["buckets"][e] = acc["buckets"].get(e, 0) + int(n)
        return out

    def fleet_capacity(self) -> Dict[str, dict]:
        """Every ``capacity.*`` gauge → ``{"sum", "max", "nodes"}``
        across each node's OWN latest value.

        The LWW fleet-gauge read is wrong for capacity: "newest capture
        wins" answers *somebody's* plane bytes, while capacity planning
        needs the fleet footprint (sum of per-node bytes/live rows) and
        the worst node (max utilization/watermark; for ``eta_s`` the
        max is over growing planes only — a ``-1`` "not growing"
        sentinel must not shadow a finite horizon).  Per-node values
        stay LWW within the slice, so re-delivery cannot double-count.
        """
        out: Dict[str, dict] = {}
        for sl in self.slices.values():
            for name, entry in sl.get("gauges", {}).items():
                if not name.startswith("capacity."):
                    continue
                v = float(entry[2])
                acc = out.get(name)
                if acc is None:
                    acc = out[name] = {"sum": 0.0, "max": None, "nodes": 0}
                acc["sum"] += v
                if name.endswith(".eta_s") and v < 0:
                    pass  # not-growing sentinel: excluded from the max
                elif acc["max"] is None or v > acc["max"]:
                    acc["max"] = v
                acc["nodes"] += 1
        for acc in out.values():
            if acc["max"] is None:
                acc["max"] = ETA_NOT_GROWING
        return out

    def fleet_stability(self) -> Dict[str, dict]:
        """The stability-frontier clock gauges
        (``stability.frontier.{max_counter,subtree.<i>.max_counter}``,
        :mod:`crdt_tpu.obs.stability`) reduced fleet-wide by MIN — the
        per-subtree min-join: a clock is FLEET-stable only if every
        observer's frontier has passed it, so the fleet read is the
        minimum over nodes, never LWW ("some node's frontier") and
        never a sum.  Count/diagnostic gauges (peers/stale/unheard/...)
        stay per-node.  Returns ``{name: {"min", "nodes"}}``."""
        out: Dict[str, dict] = {}
        for sl in self.slices.values():
            for name, entry in sl.get("gauges", {}).items():
                if not name.startswith("stability.frontier.") \
                        or not name.endswith("max_counter"):
                    continue
                v = float(entry[2])
                acc = out.get(name)
                if acc is None:
                    acc = out[name] = {"min": v, "nodes": 0}
                acc["min"] = min(acc["min"], v)
                acc["nodes"] += 1
        return out

    def fleet_heat(self) -> dict:
        """The heat observatory reduced fleet-wide
        (:mod:`crdt_tpu.obs.heat`): per-subtree attribution counters
        ride the normal G-Counter read (each node's latest value
        summed once — re-delivered slices max-merge per node, so they
        never double-count), and the per-node top-k hot-object gauges
        (``heat.hot.<rank>.{obj,count}``) get the sketch's semilattice
        join host-side: same-object counts SUM across nodes, then
        re-rank.  Returns ``{"subtree": {name: total}, "hot":
        [{"obj", "count", "nodes"}, ...]}``."""
        subtree = {
            name: int(v) for name, v in self.fleet_counters().items()
            if name.startswith("heat.subtree.")
        }
        acc: Dict[int, int] = {}
        seen: Dict[int, int] = {}
        for sl in self.slices.values():
            ranks: Dict[str, dict] = {}
            for name, entry in sl.get("gauges", {}).items():
                parts = name.split(".")
                if len(parts) != 4 or parts[0] != "heat" \
                        or parts[1] != "hot":
                    continue
                ranks.setdefault(parts[2], {})[parts[3]] = float(entry[2])
            for r in ranks.values():
                if "obj" in r and r.get("count", 0) > 0:
                    obj = int(r["obj"])
                    acc[obj] = acc.get(obj, 0) + int(r["count"])
                    seen[obj] = seen.get(obj, 0) + 1
        hot = [{"obj": o, "count": c, "nodes": seen[o]}
               for o, c in sorted(acc.items(),
                                  key=lambda kv: (-kv[1], kv[0]))]
        return {"subtree": subtree, "hot": hot}

    def fleet_lag(self) -> Dict[str, dict]:
        """The write-to-visible lag gauges (``sync.peer.<peer>.lag_*``,
        :mod:`crdt_tpu.obs.latency`) reduced fleet-wide: per leaf
        (``lag_p50_s`` / ``lag_p99_s`` / ``lag_outstanding`` /
        ``lag_current_s``), the MAX over every (node, origin-peer)
        series plus the series count.  The LWW fleet-gauge read answers
        "some pair's lag"; an operator asks "the WORST write-to-visible
        lag anywhere in the fleet" — that is the max, and a fleet that
        quiesced reads 0 on ``lag_current_s`` here exactly when every
        pair does."""
        out: Dict[str, dict] = {}
        for sl in self.slices.values():
            for name, entry in sl.get("gauges", {}).items():
                parts = name.split(".")
                if len(parts) != 4 or parts[:2] != ["sync", "peer"] \
                        or not parts[3].startswith("lag_"):
                    continue
                v = float(entry[2])
                acc = out.setdefault(parts[3], {"max": 0.0, "series": 0})
                acc["max"] = max(acc["max"], v)
                acc["series"] += 1
        return out

    def events(self, node: Optional[str] = None) -> List[dict]:
        """Retained flight-recorder events, each annotated with its
        ``node``, ordered by wall-clock then per-process seq.  The
        ordering key is ``wall_ts`` deliberately — the per-process
        ``mono_ts`` (duration math) shares no epoch across nodes, so
        it stays out of the merge/ordering key."""
        out = []
        for nid, sl in self.slices.items():
            if node is not None and nid != node:
                continue
            for ev in sl.get("events", []):
                ev = dict(ev)
                ev["node"] = nid
                out.append(ev)
        out.sort(key=lambda e: (e.get("wall_ts", e.get("wall", 0.0)),
                                e.get("seq", 0)))
        return out

    def to_json(self) -> dict:
        """JSON-ready view: the raw slices plus the fleet aggregates
        (what ``/fleet?format=json`` serves)."""
        return {
            "version": FLEET_PROTOCOL_VERSION,
            "nodes": self.nodes(),
            "slices": self.slices,
            "fleet": {
                "counters": self.fleet_counters(),
                "gauges": self.fleet_gauges(),
                "histograms": self.fleet_histograms(),
                "capacity": self.fleet_capacity(),
                "lag": self.fleet_lag(),
                "stability": self.fleet_stability(),
                "heat": self.fleet_heat(),
            },
        }


def _merge_slice(a: dict, b: dict) -> dict:
    """Join two slices OF THE SAME NODE (see module docstring for the
    per-kind semantics)."""
    counters = dict(a.get("counters", {}))
    for name, v in b.get("counters", {}).items():
        cur = counters.get(name)
        counters[name] = int(v) if cur is None else max(int(cur), int(v))
    return {
        "ts": max(a.get("ts", 0.0), b.get("ts", 0.0)),
        "seq": max(a.get("seq", 0), b.get("seq", 0)),
        "counters": counters,
        "gauges": _merge_stamped(a.get("gauges", {}), b.get("gauges", {})),
        "histograms": _merge_stamped(
            a.get("histograms", {}), b.get("histograms", {})
        ),
        "convergence": max(
            a.get("convergence", [0.0, 0, {}]),
            b.get("convergence", [0.0, 0, {}]),
            key=_stamp_key,
        ),
        "events_dropped": max(
            int(a.get("events_dropped", 0)), int(b.get("events_dropped", 0))
        ),
        "events": _merge_events(a.get("events", []), b.get("events", [])),
    }


def capture_slice(node_id: str, *,
                  registry: Optional[metrics_mod.MetricsRegistry] = None,
                  tracker: Optional[convergence_mod.ConvergenceTracker] = None,
                  recorder: Optional[events_mod.FlightRecorder] = None,
                  events_tail: int = EVENTS_TAIL) -> FleetSnapshot:
    """One node's live telemetry as a single-slice snapshot: the
    registry snapshot re-shaped into the lattice (stamped with this
    capture's ``(wall_ts, seq)``), the convergence tracker state, the
    events-dropped count and a bounded flight-recorder tail."""
    if registry is None:
        # read boundary: drain the kernel observatory's pending
        # per-call aggregates so fleet slices carry fresh kernel.*
        # rows (default registry only — same discipline as export.py)
        from . import kernels as kernels_mod

        kernels_mod.publish()
    reg = registry if registry is not None else metrics_mod.registry()
    trk = tracker if tracker is not None else convergence_mod.tracker()
    rec = recorder if recorder is not None else events_mod.recorder()
    snap = reg.snapshot()
    ts, seq = time.time(), next(_CAPTURE_SEQ)
    hists = {}
    for name, h in snap["histograms"].items():
        hists[name] = [ts, seq, {
            "count": h["count"],
            "sum": h["sum"],
            "min": h["min"],
            "max": h["max"],
            # JSON object keys are strings; exponents stay str end-to-end
            "buckets": {str(e): n for e, n in h["buckets"].items()},
        }]
    tail = rec.snapshot()[-max(0, events_tail):]
    slice_ = {
        "ts": ts,
        "seq": seq,
        "counters": {k: int(v) for k, v in snap["counters"].items()},
        "gauges": {k: [ts, seq, float(v)]
                   for k, v in snap["gauges"].items()},
        "histograms": hists,
        "convergence": [ts, seq, trk.snapshot()],
        "events_dropped": rec.dropped,
        "events": tail,
    }
    return FleetSnapshot({node_id: slice_})


# ---- the wire codec ---------------------------------------------------------


def encode_snapshot(snap: FleetSnapshot) -> bytes:
    """A fleet-snapshot frame: the versioned+CRC envelope around the
    canonical-JSON payload (same discipline as the sync codec —
    truncation/tampering is a clean rejection, mixed versions fail
    loudly)."""
    payload = _canon(snap.slices).encode("utf-8")
    return _HEADER.pack(
        FLEET_PROTOCOL_VERSION, FRAME_FLEET_SNAPSHOT,
        zlib.crc32(payload), len(payload),
    ) + payload


def _reject(reason: str, message: str) -> SyncProtocolError:
    from ..utils import tracing

    tracing.count(f"obs.fleet.frames.rejected.{reason}")
    events_mod.record("obs.fleet.frame_rejected", reason=reason,
                      error=message[:200])
    return SyncProtocolError(message)


def decode_snapshot(frame: bytes) -> FleetSnapshot:
    """Validate and decode one fleet-snapshot frame.  Raises
    :class:`~crdt_tpu.error.SyncProtocolError` on a version mismatch,
    unknown type, truncated/overlong frame, CRC mismatch, or a payload
    that is not a slices object — the caller never merges garbage."""
    from ..utils import tracing

    if len(frame) < _HEADER.size:
        raise _reject(
            "truncated",
            f"truncated fleet frame: {len(frame)} bytes < "
            f"{_HEADER.size}-byte header"
        )
    version, ftype, crc, plen = _HEADER.unpack_from(frame)
    if version != FLEET_PROTOCOL_VERSION:
        raise _reject(
            "version_mismatch",
            f"fleet snapshot version mismatch: peer sent v{version}, "
            f"this build speaks v{FLEET_PROTOCOL_VERSION}"
        )
    if ftype != FRAME_FLEET_SNAPSHOT:
        raise _reject(
            "unknown_type", f"unknown fleet frame type {ftype:#04x}"
        )
    payload = frame[_HEADER.size:]
    if len(payload) != plen:
        raise _reject(
            "length_mismatch",
            f"fleet frame length mismatch: header says {plen} payload "
            f"bytes, frame carries {len(payload)}"
        )
    if zlib.crc32(payload) != crc:
        raise _reject(
            "crc_mismatch",
            "fleet snapshot frame CRC mismatch (tampered or corrupted "
            "in transit)"
        )
    try:
        slices = json.loads(payload.decode("utf-8"))
    except (UnicodeDecodeError, ValueError) as e:
        raise _reject("malformed_payload",
                      f"malformed fleet snapshot payload: {e}") from None
    if not isinstance(slices, dict) or not all(
        isinstance(k, str) and isinstance(v, dict)
        for k, v in slices.items()
    ):
        raise _reject(
            "malformed_payload",
            "fleet snapshot payload is not a {node: slice} object"
        )
    tracing.count("obs.fleet.frames.decoded")
    return FleetSnapshot(slices)


def merge_snapshots(frames: Iterable[bytes]) -> FleetSnapshot:
    """Decode and fold a batch of snapshot frames — the shared body of
    the transport-piggyback and collective all-gather paths."""
    acc = FleetSnapshot()
    for frame in frames:
        acc = acc.merge(decode_snapshot(frame))
    return acc


# ---- the trace stitcher -----------------------------------------------------


def stitch_trace(snapshot_or_events, trace_id: str) -> List[dict]:
    """The cross-peer timeline of one sync session: every flight-
    recorder event (from every node slice) stamped with ``trace_id``,
    ordered by wall clock then per-process seq, each annotated with the
    node that recorded it.  Both halves of a session carry the SAME
    hello-negotiated trace ID, so this is the whole story — dial,
    digest exchange, delta, converged — interleaved across peers.

    Accepts a :class:`FleetSnapshot` or a pre-annotated event list (the
    shape :meth:`FleetSnapshot.events` returns)."""
    evs = snapshot_or_events.events() \
        if isinstance(snapshot_or_events, FleetSnapshot) \
        else list(snapshot_or_events)
    return [
        ev for ev in evs
        if ev.get("fields", {}).get("trace") == trace_id
        or ev.get("session") == trace_id
    ]


# ---- Prometheus rendering ---------------------------------------------------

#: the merged-fleet metric prefix — deliberately distinct from the
#: per-process ``crdt_tpu_`` namespace so one Prometheus can scrape
#: both ``/metrics`` and ``/fleet`` of the same node without the fleet
#: aggregate shadowing the local series
FLEET_PROM_PREFIX = "crdt_tpu_fleet"


def fleet_prometheus_text(snap: FleetSnapshot,
                          prefix: str = FLEET_PROM_PREFIX) -> str:
    """The merged fleet snapshot as Prometheus text exposition:
    counters summed over nodes (``*_total``), gauges LWW fleet-wide,
    histograms bucket-wise summed, plus ``<prefix>_nodes`` (distinct
    nodes merged so far) — one scrape of ANY node answers for the
    fleet."""
    lines = [
        f"# TYPE {prefix}_nodes gauge",
        f"{prefix}_nodes {len(snap.slices)}",
    ]
    counters = snap.fleet_counters()
    for name in sorted(counters):
        mname = f"{prefix}_{_sanitize(name)}_total"
        lines.append(f"# TYPE {mname} counter")
        lines.append(f"{mname} {int(counters[name])}")
    gauges = snap.fleet_gauges()
    for name in sorted(gauges):
        mname = f"{prefix}_{_sanitize(name)}"
        v = gauges[name]
        rendered = str(int(v)) if float(v).is_integer() else repr(float(v))
        lines.append(f"# TYPE {mname} gauge")
        lines.append(f"{mname} {rendered}")
    # capacity gauges additionally get the sum/max fleet reduction (the
    # LWW series above answers "some node's value"; capacity planning
    # needs the fleet footprint and the worst node — see fleet_capacity)
    cap = snap.fleet_capacity()
    for name in sorted(cap):
        base = f"{prefix}_{_sanitize(name)}"
        for reduction in ("sum", "max"):
            v = float(cap[name][reduction])
            rendered = str(int(v)) if v.is_integer() else repr(v)
            lines.append(f"# TYPE {base}_{reduction} gauge")
            lines.append(f"{base}_{reduction} {rendered}")
    # stability-frontier clocks get the MIN-join reduction
    # (fleet_stability): a clock is fleet-stable only when EVERY
    # observer's frontier passed it — the per-subtree min-join the
    # truncate-epoch proposer will read
    stab = snap.fleet_stability()
    for name in sorted(stab):
        base = f"{prefix}_{_sanitize(name)}_min"
        v = float(stab[name]["min"])
        rendered = str(int(v)) if v.is_integer() else repr(v)
        lines.append(f"# TYPE {base} gauge")
        lines.append(f"{base} {rendered}")
    # write-to-visible lag gets the worst-pair reduction (fleet_lag):
    # one scrape answers "the worst replication lag anywhere", and the
    # quiescence pin — lag_current_s_max == 0 — holds fleet-wide
    # exactly when it holds for every (node, origin) pair
    lag = snap.fleet_lag()
    for leaf in sorted(lag):
        base = f"{prefix}_sync_{_sanitize(leaf)}_max"
        v = float(lag[leaf]["max"])
        rendered = str(int(v)) if v.is_integer() else repr(v)
        lines.append(f"# TYPE {base} gauge")
        lines.append(f"{base} {rendered}")
    # the fleet-merged hot-object list (fleet_heat): the per-node
    # Space-Saving sketches' semilattice join, re-ranked — bounded to
    # the same top ranks each node publishes
    heat = snap.fleet_heat()
    for rank, h in enumerate(heat["hot"][:8]):
        base = f"{prefix}_heat_hot_{rank}"
        lines.append(f"# TYPE {base}_obj gauge")
        lines.append(f"{base}_obj {h['obj']}")
        lines.append(f"# TYPE {base}_count gauge")
        lines.append(f"{base}_count {h['count']}")
    hists = snap.fleet_histograms()
    import math

    for name in sorted(hists):
        h = hists[name]
        mname = f"{prefix}_{_sanitize(name)}"
        lines.append(f"# TYPE {mname} histogram")
        running = 0
        for e in sorted(h["buckets"], key=int):
            running += h["buckets"][e]
            exp = int(e)
            bound = 0.0 if exp == metrics_mod.Histogram.ZERO_BUCKET \
                else math.ldexp(1.0, exp)
            b = str(int(bound)) if bound.is_integer() else repr(bound)
            lines.append(f'{mname}_bucket{{le="{b}"}} {running}')
        lines.append(f'{mname}_bucket{{le="+Inf"}} {h["count"]}')
        s = h["sum"]
        lines.append(
            f"{mname}_sum {str(int(s)) if float(s).is_integer() else repr(s)}"
        )
        lines.append(f"{mname}_count {h['count']}")
    return "\n".join(lines) + "\n"


# ---- the observatory --------------------------------------------------------


class FleetObservatory:
    """One node's accumulation point for fleet telemetry.

    Owns the merged :class:`FleetSnapshot` under a lock; gossip
    sessions feed peer frames in (:meth:`merge_frame`) and ship the
    merged state out (:meth:`encode` — merged, not just local, so
    slices spread transitively through the fleet), while ``/fleet``
    scrapes read a refreshed copy (:meth:`merged`).

    ``node_id`` labels this process's slice; in-process multi-node
    harnesses (tests, the ``--gossip`` demo) share one metrics
    registry, so their slices differ by capture time and node label —
    the lattice does not care.
    """

    def __init__(self, node_id: Optional[str] = None, *,
                 registry: Optional[metrics_mod.MetricsRegistry] = None,
                 tracker: Optional[convergence_mod.ConvergenceTracker]
                 = None,
                 recorder: Optional[events_mod.FlightRecorder] = None,
                 events_tail: int = EVENTS_TAIL):
        self.node_id = node_id or f"proc-{events_mod._PROC_TAG}"
        self._registry = registry
        self._tracker = tracker
        self._recorder = recorder
        self._events_tail = events_tail
        self._lock = threading.Lock()
        self._merged = FleetSnapshot()

    def capture(self) -> FleetSnapshot:
        """Capture this node's live slice, fold it into the merged
        state, and return the single-slice snapshot."""
        local = capture_slice(
            self.node_id, registry=self._registry, tracker=self._tracker,
            recorder=self._recorder, events_tail=self._events_tail,
        )
        with self._lock:
            self._merged = self._merged.merge(local)
        return local

    def merge(self, snap: FleetSnapshot) -> FleetSnapshot:
        """Fold a peer snapshot in; returns the new merged state.
        Idempotent — re-delivered snapshots (an ARQ retransmit, a
        gossip echo of our own slice) change nothing."""
        with self._lock:
            self._merged = merged = self._merged.merge(snap)
        from ..utils import tracing

        tracing.count("obs.fleet.merges")
        reg = self._registry if self._registry is not None \
            else metrics_mod.registry()
        reg.gauge_set("obs.fleet.nodes", len(merged.slices))
        return merged

    def merge_frame(self, frame: bytes) -> FleetSnapshot:
        """Decode one wire frame and fold it in (raises
        :class:`~crdt_tpu.error.SyncProtocolError` on a bad frame
        WITHOUT touching the merged state)."""
        return self.merge(decode_snapshot(frame))

    def merged(self, refresh: bool = True) -> FleetSnapshot:
        """The merged fleet snapshot; ``refresh`` folds a fresh local
        capture in first so the local slice is never stale."""
        if refresh:
            self.capture()
        with self._lock:
            return self._merged

    def encode(self, refresh: bool = True) -> bytes:
        """The merged snapshot as one wire frame — what a gossip
        session piggybacks.  Shipping the MERGED state (not just the
        local slice) is what makes snapshot dissemination itself an
        anti-entropy protocol."""
        snap = self.merged(refresh=refresh)
        frame = encode_snapshot(snap)
        reg = self._registry if self._registry is not None \
            else metrics_mod.registry()
        reg.observe("obs.fleet.snapshot_bytes", len(frame))
        return frame

    def reset(self) -> None:
        with self._lock:
            self._merged = FleetSnapshot()


# -- the default (process-global) observatory --------------------------------

_DEFAULT: Optional[FleetObservatory] = None
_DEFAULT_LOCK = threading.Lock()


def observatory() -> FleetObservatory:
    """The process-global observatory — what ``/fleet`` serves when the
    server was not handed a private one, and the default aggregation
    point for single-node-per-process deployments."""
    global _DEFAULT
    if _DEFAULT is None:
        with _DEFAULT_LOCK:
            if _DEFAULT is None:
                _DEFAULT = FleetObservatory()
    return _DEFAULT
