"""Convergence telemetry — how far apart replicas are, and for how long.

The sync protocol already *computes* everything an operator needs to
answer "are my replicas converging?" — the digest exchange yields the
exact diverged set, the session report carries rounds and byte costs —
but PR 2 threw that away after printing.  This module keeps it, per
peer:

* ``sync.peer.<peer>.divergence`` / ``.divergence_frac`` — gauges from
  the most recent digest exchange: how many objects (and what fraction
  of the fleet) differed from that peer.
* ``sync.peer.<peer>.rounds_to_converge`` — digest exchanges the last
  session needed (1 = clean delta sync, 3 = a full-state retry).
* ``sync.peer.<peer>.staleness_s`` — seconds since the last *converged*
  sync with that peer; the anti-entropy freshness alarm.  Recomputed at
  read time (:meth:`ConvergenceTracker.refresh`), so a scrape always
  sees the live age, not the age at last sync.
* ``sync.peer.<peer>.delta_ratio`` — the last session's payload bytes
  over the full-state reference, with a bounded history kept for the
  JSON snapshot (the O(divergence) claim, live instead of bench-only).
  Populated when the session knows a reference size: either the
  ``SyncSession(full_state_bytes=...)`` hint, or the exact full frame a
  fallback path shipped.  A pure delta session without the hint leaves
  the gauge untouched rather than serializing full state to measure it.

:class:`~crdt_tpu.sync.session.SyncSession` feeds this automatically
through the default tracker; nothing here imports the sync package, so
the dependency points protocol → telemetry only.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, Optional

from . import metrics

_HISTORY = 64  # delta_ratio observations retained per peer

#: gauge sentinels a roster peer is SEEDED with at membership admission
#: (:meth:`ConvergenceTracker.register_peer`), before any digest
#: exchange: staleness is infinite (never converged — worse than any
#: finite age, so alerts and the gossip urgency ranking both fire) and
#: divergence is UNKNOWN, which must read as -1, never as a reassuring 0
NEVER_SYNCED_STALENESS = float("inf")
UNKNOWN_DIVERGENCE = -1


class _PeerState:
    __slots__ = (
        "divergence", "objects", "rounds_to_converge", "sessions",
        "converged_sessions", "last_converged_ts", "delta_ratios",
        "divergence_resolved", "version_vector", "version_vector_ts",
        "diverged_subtrees",
    )

    def __init__(self):
        self.divergence = 0
        self.objects = 0
        # widest diverged internal frontier the last tree descent saw
        # (0 = converged or flat-mode peer) — a cheap "how clustered is
        # the divergence" signal the gossip urgency tiebreaks on
        self.diverged_subtrees = 0
        self.rounds_to_converge = 0
        self.sessions = 0
        self.converged_sessions = 0
        self.last_converged_ts: Optional[float] = None
        self.delta_ratios: deque = deque(maxlen=_HISTORY)
        # `divergence` documents what the last digest exchange FOUND; a
        # session that then converged has resolved it, which the fleet
        # health view (gossip's fleet_divergence_max / eta_rounds)
        # needs to tell apart from divergence still outstanding
        self.divergence_resolved = True
        # the peer's most recent version-vector summary (the digest
        # frame already ships it) — the fleet low-watermark's input
        # (crdt_tpu/gc/watermark.py); a tuple of ints so this module
        # stays numpy-free
        self.version_vector: Optional[tuple] = None
        self.version_vector_ts: Optional[float] = None


class ConvergenceTracker:
    """Per-peer convergence state, mirrored into registry gauges."""

    def __init__(self, registry: Optional[metrics.MetricsRegistry] = None):
        self._registry = registry
        self._lock = threading.Lock()
        self._peers: Dict[str, _PeerState] = {}

    def _reg(self) -> metrics.MetricsRegistry:
        return self._registry if self._registry is not None \
            else metrics.registry()

    def _state(self, peer: str) -> _PeerState:
        st = self._peers.get(peer)
        if st is None:
            st = self._peers[peer] = _PeerState()
        return st

    def register_peer(self, peer: str) -> None:
        """Seed the per-peer gauges for a roster peer admitted BEFORE
        any digest exchange (:meth:`crdt_tpu.cluster.membership.
        Membership.add` calls this): without the seed, a peer that
        never completes a session is simply absent from ``/metrics`` —
        a dashboard cannot tell "silent peer" from "no such peer".
        Idempotent, and a peer with observed state is left untouched
        (the sentinels must never clobber real measurements)."""
        with self._lock:
            if peer in self._peers:
                return
            self._state(peer)
        reg = self._reg()
        reg.gauge_set(f"sync.peer.{peer}.staleness_s",
                      NEVER_SYNCED_STALENESS)
        reg.gauge_set(f"sync.peer.{peer}.divergence", UNKNOWN_DIVERGENCE)
        reg.gauge_set(f"sync.peer.{peer}.divergence_frac",
                      UNKNOWN_DIVERGENCE)

    def observe_divergence(self, peer: str, diverged: int,
                           objects: int) -> None:
        """Record one digest exchange's outcome vs ``peer``: ``diverged``
        of ``objects`` fleet rows differ."""
        with self._lock:
            st = self._state(peer)
            st.divergence = int(diverged)
            st.objects = int(objects)
            st.divergence_resolved = diverged == 0
        reg = self._reg()
        reg.gauge_set(f"sync.peer.{peer}.divergence", diverged)
        reg.gauge_set(
            f"sync.peer.{peer}.divergence_frac",
            diverged / objects if objects else 0.0,
        )

    def observe_session(self, peer: str, *, converged: bool, rounds: int,
                        payload_bytes: int = 0,
                        full_state_bytes: Optional[int] = None) -> None:
        """Record one finished session vs ``peer``.  ``rounds`` is the
        session's digest-exchange count; ``payload_bytes`` over
        ``full_state_bytes`` (when known) is the live delta_ratio."""
        ratio = None
        if full_state_bytes:
            ratio = payload_bytes / full_state_bytes
        with self._lock:
            st = self._state(peer)
            st.sessions += 1
            st.rounds_to_converge = int(rounds)
            if converged:
                st.converged_sessions += 1
                st.last_converged_ts = time.monotonic()
                st.divergence_resolved = True
            if ratio is not None:
                st.delta_ratios.append(ratio)
        reg = self._reg()
        reg.gauge_set(f"sync.peer.{peer}.rounds_to_converge", rounds)
        if converged:
            reg.gauge_set(f"sync.peer.{peer}.staleness_s", 0.0)
        if ratio is not None:
            reg.gauge_set(f"sync.peer.{peer}.delta_ratio", ratio)

    def observe_tree(self, peer: str, subtrees: int) -> None:
        """Record one tree descent's widest diverged internal frontier
        vs ``peer`` (:class:`~crdt_tpu.sync.session.SyncSession` tree
        mode).  Feeds the ``sync.peer.<peer>.diverged_subtrees`` gauge
        and the third :meth:`urgency` component: between two peers with
        equal staleness and diverged fraction, the one whose divergence
        spans MORE subtrees costs more descent frames to reconcile and
        ranks more urgent — syncing it first amortizes better."""
        with self._lock:
            self._state(peer).diverged_subtrees = int(subtrees)
        self._reg().gauge_set(
            f"sync.peer.{peer}.diverged_subtrees", int(subtrees))

    def observe_version_vector(self, peer: str, vv,
                               at: Optional[float] = None) -> None:
        """Cache ``peer``'s version-vector summary from a digest
        exchange (any iterable of counters; stored as a tuple of ints).
        The fleet low-watermark (:class:`crdt_tpu.gc.watermark.
        FleetWatermark`) takes the element-wise minimum over these.
        ``at`` overrides the observation timestamp (monotonic seconds;
        tests inject fake clocks through it)."""
        frozen = tuple(int(c) for c in vv)
        now = time.monotonic() if at is None else at
        with self._lock:
            st = self._state(peer)
            st.version_vector = frozen
            st.version_vector_ts = now

    def version_vectors(self) -> Dict[str, tuple]:
        """``{peer: (version_vector, observed_ts)}`` for every peer a
        digest exchange has shipped one for (monotonic timestamps — age
        against ``time.monotonic()``)."""
        with self._lock:
            return {
                peer: (st.version_vector, st.version_vector_ts)
                for peer, st in self._peers.items()
                if st.version_vector is not None
            }

    def refresh(self) -> None:
        """Recompute the read-time gauges (staleness ages).  The export
        surface calls this before every scrape so ``staleness_s`` is the
        live age of the last converged sync, not a stale write."""
        now = time.monotonic()
        with self._lock:
            ages = {
                peer: now - st.last_converged_ts
                for peer, st in self._peers.items()
                if st.last_converged_ts is not None
            }
        reg = self._reg()
        for peer, age in ages.items():
            reg.gauge_set(f"sync.peer.{peer}.staleness_s", age)

    def snapshot(self) -> dict:
        """JSON-ready per-peer state, staleness computed at call time."""
        now = time.monotonic()
        with self._lock:
            return {
                peer: {
                    "divergence": st.divergence,
                    "objects": st.objects,
                    "divergence_frac": (
                        st.divergence / st.objects if st.objects else 0.0
                    ),
                    "rounds_to_converge": st.rounds_to_converge,
                    "divergence_resolved": st.divergence_resolved,
                    "diverged_subtrees": st.diverged_subtrees,
                    "sessions": st.sessions,
                    "converged_sessions": st.converged_sessions,
                    "staleness_s": (
                        None if st.last_converged_ts is None
                        else now - st.last_converged_ts
                    ),
                    "delta_ratio_history": list(st.delta_ratios),
                }
                for peer, st in self._peers.items()
            }

    def urgency(self, peer: str) -> tuple:
        """How badly ``peer`` needs a sync, as a sort key: ``(staleness
        seconds, last diverged fraction, diverged subtree count)`` —
        all +inf for a peer never converged with (never-synced peers
        rank first).  The gossip scheduler
        (:mod:`crdt_tpu.cluster.gossip`) sorts candidates by this key,
        descending — the policy "sync whoever you've ignored longest,
        break ties toward whoever differed most, then toward whoever's
        divergence is spread over the most subtrees (the costliest
        descent)" lives here, next to the gauges it reads."""
        now = time.monotonic()
        with self._lock:
            st = self._peers.get(peer)
            if st is None or st.last_converged_ts is None:
                return (float("inf"), float("inf"), float("inf"))
            frac = st.divergence / st.objects if st.objects else 0.0
            return (now - st.last_converged_ts, frac, st.diverged_subtrees)

    def reset(self) -> None:
        with self._lock:
            self._peers.clear()


# -- the default (process-global) tracker ------------------------------------

_DEFAULT = ConvergenceTracker()


def tracker() -> ConvergenceTracker:
    return _DEFAULT
