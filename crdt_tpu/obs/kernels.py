"""Runtime kernel observatory — the dynamic companion to kernelcheck.

PR 8's kernelcheck proves kernel contracts *statically*: it traces every
manifested ``jax.jit`` entry point abstractly and bounds its distinct
lowerings (KC04 ``compile_budget``).  Nothing watched the same kernels
*at runtime*: a shape-churn bug that recompiles a hot kernel per batch,
a regrow ladder walking further than planned, or a kernel whose device
time quietly doubled were all invisible until a bench diff.  This
module closes that gap with an always-on, always-cheap registry keyed
on the SAME single source of kernel identity — the
:data:`crdt_tpu.analysis.kernels.MANIFEST` rows:

* :func:`observed_kernel` — the one-line instrumentation every
  manifested jit entry point wears (decorator above the ``jax.jit``
  site, or a wrap around a factory's return).  Each call pays two
  ``perf_counter`` reads, one ``_cache_size()`` fetch, the shape-walk
  bytes estimate and a few dict increments under the profile's own
  lock; ``bench_kernel_obs`` gates the total below 1% of
  ``bench_e2e_wire`` wall.
* **Compile tracking** — a jit cache growing across a call IS a
  lowering+compile: counted per kernel (``kernel.<label>.compiles`` +
  the process-wide ``kernel.compiles``), flight-recorded as a
  ``kernel.compile`` event carrying the arg-shape signature and the
  call's wall, and classified against the executor's capacity-ladder
  stamps (:func:`note_ladder_transition`, bumped by
  ``executor.regrow``/``executor.shrink``) so an expected
  ladder-transition recompile is distinguishable from shape churn
  (:func:`storm_report`).  KC04's static budget becomes a runtime
  gauge: ``kernel.<label>.compile_budget_frac`` with an ok/warn/
  critical watermark like the PR 9 capacity gauges.
* **Device accounting** — per-kernel log2 wall histograms
  (``kernel.<label>.wall``; compile calls are recorded on the compile
  event instead, so the histogram stays steady-state), bytes-moved
  counters and a GB/s gauge, plus one-time-per-compilation XLA
  ``cost_analysis()`` capture (:meth:`KernelProfile.capture_cost`,
  lazy — triggered by ``/kernels?cost=1`` or the bench, never on the
  hot path) giving every kernel a roofline position.
* **Device memory** — :func:`sample_device_memory` folds
  ``jax.live_arrays()`` into ``devicemem.*`` gauges (total + per-dtype
  live bytes) and, when a
  :class:`~crdt_tpu.obs.capacity.CapacityTracker` is supplied, the
  tracked-vs-live fraction — closing the gap between "plane bytes by
  construction" and what the device actually holds.  Sampled on the
  PR 9 capacity cadence (``CapacityTracker.sample_device_memory``).

Timing semantics: by default a call's wall is the DISPATCH wall (jax
dispatch is async; blocking every call would not be "always cheap").
With ``CRDT_TRACE=1`` or :func:`set_blocking` the wrapper blocks on the
outputs — true device time — which is how ``bench_kernel_obs`` fills
the GB/s gauges.  The per-call fast path touches ONLY the
profile's own lock (dict increments); pending aggregates drain into
the registry at every read boundary (``/kernels``, ``/metrics``,
``json_snapshot``, fleet slice capture) via :func:`publish`, so
exported state is fresh and scrapes never see a torn histogram.

Single-source discipline, enforced both ways: :meth:`KernelObservatory.
instrument` REJECTS names without a manifest row, and the
manifest↔runtime cross-check test (``tests/test_kernel_obs.py``) walks
:func:`warm_manifest` and asserts every traceable row is instrumented.

Stdlib-only at module scope (the obs import-lightness contract): jax
and the analysis manifest import lazily, and a process that never calls
a kernel never pays for either.
"""

from __future__ import annotations

import math
import os
import sys
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from . import events as events_mod
from . import metrics as metrics_mod

#: compile_budget_frac watermark thresholds: a long-lived process that
#: has compiled every declared ladder rung sits at 1.0; anything past
#: DOUBLE the declared budget is runtime shape churn kernelcheck never
#: sanctioned.  (Deliberately looser than the PR 9 capacity 0.7/0.9 —
#: warmup legitimately spends the whole budget.)
BUDGET_WARN_FRAC = 1.0
BUDGET_CRITICAL_FRAC = 2.0

WATERMARK_STATES = ("ok", "warn", "critical")

#: leaves summarized into a compile event's arg-shape signature
_SIG_LEAVES = 16


def _jax():
    """The already-imported jax module (kernel wrappers only ever run
    after their jitted target imported it)."""
    return sys.modules["jax"]


def _tree_bytes(*trees: Any) -> int:
    """Array bytes across call trees, on the always-on budget: computed
    as ``prod(shape) * itemsize`` (a jax Array's ``.nbytes`` property
    costs ~3us; the shape/dtype path is ~10x cheaper) over an
    iterative stdlib tuple/list/dict walk, with ONE jax
    ``tree_leaves`` fallback per registered-pytree node (the
    flax-struct map states).  Unknown leaves count 0 — the result is
    an HBM-traffic lower bound by contract."""
    total = 0
    stack = list(trees)
    while stack:
        obj = stack.pop()
        shape = getattr(obj, "shape", None)
        if shape is not None:
            dt = getattr(obj, "dtype", None)
            if dt is not None:
                try:
                    total += math.prod(shape) * dt.itemsize
                except (TypeError, AttributeError):
                    pass
                continue
        if isinstance(obj, (tuple, list)):
            stack.extend(obj)
        elif isinstance(obj, dict):
            stack.extend(obj.values())
        elif obj is None or isinstance(obj, (int, float, bool, str,
                                             bytes)):
            pass
        else:
            try:  # registered pytree node (flax struct state)
                leaves = _jax().tree_util.tree_leaves(obj)
            except Exception:
                continue
            if not (len(leaves) == 1 and leaves[0] is obj):
                stack.extend(leaves)
    return total


def _shape_signature(args: tuple, kwargs: dict) -> str:
    """A compact ``dtype[shape]`` signature of one call's arguments —
    what a ``kernel.compile`` event records so a recompile storm's
    churning axis is readable straight off ``/events``."""
    leaves = _jax().tree_util.tree_leaves((args, kwargs))
    parts = []
    for leaf in leaves[:_SIG_LEAVES]:
        if hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
            dt = getattr(leaf.dtype, "name", str(leaf.dtype))
            parts.append(f"{dt}{list(leaf.shape)}")
        else:
            parts.append(repr(leaf)[:24])
    if len(leaves) > _SIG_LEAVES:
        parts.append(f"+{len(leaves) - _SIG_LEAVES} more")
    return ",".join(parts)


def _lower_args(args: tuple, kwargs: dict) -> tuple:
    """The call's arguments with array leaves abstracted to
    ``ShapeDtypeStruct`` (statics kept concrete) — enough to re-``lower``
    the kernel later for a cost_analysis capture without holding device
    buffers alive."""
    jax = _jax()

    def conv(x):
        if hasattr(x, "shape") and hasattr(x, "dtype") \
                and not isinstance(x, (bool, int, float)):
            return jax.ShapeDtypeStruct(x.shape, x.dtype)
        return x

    return (jax.tree_util.tree_map(conv, args),
            jax.tree_util.tree_map(conv, kwargs))


# -- ladder-transition stamps (executor.regrow / executor.shrink) ------------

_LADDER_LOCK = threading.Lock()
_LADDER_EPOCH = 0
_LADDER_MONO: float = float("-inf")


def note_ladder_transition(kind: str = "regrow") -> None:
    """Stamp a capacity-ladder transition (called by the executor's
    regrow path and the GC re-pack next to their flight-recorder
    events).  The FIRST compile a kernel pays after a transition is
    ladder-attributed; repeats without a fresh transition are shape
    churn.  ``kind`` is informational (regrow/shrink)."""
    global _LADDER_EPOCH, _LADDER_MONO
    with _LADDER_LOCK:
        _LADDER_EPOCH += 1
        _LADDER_MONO = time.monotonic()


def _ladder_epoch() -> int:
    with _LADDER_LOCK:
        return _LADDER_EPOCH


# -- blocking switch ---------------------------------------------------------

_BLOCKING = os.environ.get("CRDT_TRACE") == "1"


def set_blocking(on: bool = True) -> None:
    """Block on kernel outputs so recorded walls are device time (what
    ``bench_kernel_obs`` does for the GB/s roofline).  Off by default:
    the always-on path records dispatch wall only."""
    global _BLOCKING
    _BLOCKING = on


class KernelProfile:
    """One manifested kernel's runtime record.

    ``label`` is the metric-segment form of the manifest ``name``
    (dots → underscores: ``batch.orswot.merge`` →
    ``batch_orswot_merge``), so every published name fits the
    one-dynamic-segment namespace grammar
    (``kernel.<label>.{calls,compiles,wall,...}``)."""

    def __init__(self, spec, registry: metrics_mod.MetricsRegistry):
        self.name: str = spec.name
        self.label: str = spec.name.replace(".", "_").replace("-", "_")
        self.compile_budget: int = spec.compile_budget
        self.traceable: bool = spec.build is not None
        self.notrace_reason: str = spec.notrace_reason
        self.instrumented = False
        self.instances = 0
        self.calls = 0
        self.compiles = 0
        self.errors = 0
        self.bytes_total = 0
        self.wall_total_s = 0.0
        # device-true (blocking-mode) accumulation behind the GB/s gauge
        self.blocking_bytes = 0
        self.blocking_wall_s = 0.0
        self.last_signature: Optional[str] = None
        self.cost: Optional[dict] = None
        self._cost_at_compiles = -1
        self._lower_sig: Optional[tuple] = None
        self._last_fn: Any = None
        self._ladder_seen = _ladder_epoch()
        self._lock = threading.Lock()
        self._reg = registry
        self._handles: Optional[tuple] = None
        self._wall_name = f"kernel.{self.label}.wall"
        # pending (not-yet-published) per-call aggregates: the hot path
        # only touches these under the profile lock; publish() drains
        # them into the registry in one lock acquisition per metric
        self._pend_calls = 0
        self._pend_bytes = 0
        self._pend_buckets: Dict[int, int] = {}
        self._pend_count = 0
        self._pend_sum = 0.0
        self._pend_min = math.inf
        self._pend_max = -math.inf

    # handle creation claims the names once; the per-call path reuses
    # the cached handles (counters lock themselves, gauges are LWW)
    def _ensure_handles(self):
        if self._handles is None:
            reg = self._reg
            label = self.label
            self._handles = (
                reg.counter(f"kernel.{label}.calls"),
                reg.counter(f"kernel.{label}.compiles"),
                reg.counter(f"kernel.{label}.bytes"),
                reg.counter(f"kernel.{label}.errors"),
                reg.gauge(f"kernel.{label}.gbps"),
                reg.gauge(f"kernel.{label}.compile_budget_frac"),
            )
            reg.histogram(f"kernel.{label}.wall")
        return self._handles

    @property
    def budget_frac(self) -> float:
        return self.compiles / self.compile_budget \
            if self.compile_budget > 0 else float(self.compiles)

    @property
    def watermark(self) -> str:
        f = self.budget_frac
        if f >= BUDGET_CRITICAL_FRAC:
            return "critical"
        if f >= BUDGET_WARN_FRAC:
            return "warn"
        return "ok"

    # -- per-call recording (wrapper-driven) ---------------------------------

    def record_call(self, dt: float, nbytes: int, blocking: bool) -> None:
        """The always-on per-call path: ONE profile-lock acquisition,
        dict increments only — no registry traffic.  publish() drains
        the pending aggregates at scrape/snapshot boundaries."""
        e = metrics_mod.log2_bucket(dt)
        with self._lock:
            self.calls += 1
            self.wall_total_s += dt
            self.bytes_total += nbytes
            self._pend_calls += 1
            self._pend_bytes += nbytes
            self._pend_buckets[e] = self._pend_buckets.get(e, 0) + 1
            self._pend_count += 1
            self._pend_sum += dt
            if dt < self._pend_min:
                self._pend_min = dt
            if dt > self._pend_max:
                self._pend_max = dt
            if blocking:
                self.blocking_bytes += nbytes
                self.blocking_wall_s += dt

    def publish(self) -> None:
        """Drain the pending per-call aggregates into the registry.
        Called at every read boundary (``/kernels``, ``/metrics``,
        ``json_snapshot``, fleet slice capture, :meth:`KernelObservatory.
        table`) so exported state is fresh without the hot path ever
        paying a registry round-trip."""
        with self._lock:
            if self._pend_count == 0 and self._pend_calls == 0:
                return
            calls, nbytes = self._pend_calls, self._pend_bytes
            buckets = self._pend_buckets
            count, total = self._pend_count, self._pend_sum
            vmin, vmax = self._pend_min, self._pend_max
            gbps = self.blocking_bytes / self.blocking_wall_s / 1e9 \
                if self.blocking_wall_s > 0.0 else None
            self._pend_calls = 0
            self._pend_bytes = 0
            self._pend_buckets = {}
            self._pend_count = 0
            self._pend_sum = 0.0
            self._pend_min = math.inf
            self._pend_max = -math.inf
        calls_c, _, bytes_c, _, gbps_g, _ = self._ensure_handles()
        if calls:
            calls_c.inc(calls)
        if nbytes:
            bytes_c.inc(nbytes)
        self._reg.observe_aggregate(self._wall_name, buckets, count,
                                    total, vmin, vmax)
        if gbps is not None:
            gbps_g.set(gbps)

    def record_compile(self, count: int, dt: float, args: tuple,
                       kwargs: dict, fn: Any, nbytes: int) -> None:
        calls, compiles_c, bytes_c, _, _, frac_g = self._ensure_handles()
        calls.inc()
        compiles_c.inc(count)
        self._reg.counter_inc("kernel.compiles", count)
        if nbytes:
            bytes_c.inc(nbytes)
        epoch = _ladder_epoch()
        try:
            sig = _shape_signature(args, kwargs)
        except Exception:  # a signature must never fail the kernel call
            sig = "<unavailable>"
        with self._lock:
            first = self.compiles == 0
            ladder = epoch > self._ladder_seen
            self._ladder_seen = epoch
            self.calls += 1
            self.compiles += count
            self.bytes_total += nbytes
            self.last_signature = sig
            self._last_fn = fn
            try:
                self._lower_sig = _lower_args(args, kwargs)
            except Exception:
                self._lower_sig = None
            n = self.compiles
        frac_g.set(self.budget_frac)
        _observatory_budget_refresh()
        events_mod.record(
            "kernel.compile", kernel=self.name, shapes=sig,
            wall_s=round(dt, 6), count=count, n=n,
            ladder=ladder, first=first,
        )

    def record_error(self) -> None:
        handles = self._ensure_handles()
        handles[3].inc()
        with self._lock:
            self.errors += 1

    # -- one-time-per-compilation XLA cost capture ---------------------------

    def capture_cost(self) -> Optional[dict]:
        """Lower+compile the last compiled signature and read the
        backend's ``cost_analysis()`` (flops / bytes accessed, where
        reported).  Deliberately LAZY — a second compile per signature
        is cheap next to the first but not free, so it runs on demand
        (``/kernels?cost=1``, the bench) and memoizes until the kernel
        compiles again.  Returns the cost dict or None."""
        with self._lock:
            if self._lower_sig is None or self._last_fn is None:
                return self.cost
            if self._cost_at_compiles == self.compiles:
                return self.cost
            fn, (la, lkw), at = self._last_fn, self._lower_sig, self.compiles
        try:
            lowered = fn.lower(*la, **lkw)
            ca = lowered.compile().cost_analysis()
            if isinstance(ca, (list, tuple)):
                ca = ca[0] if ca else {}
            cost = {
                "flops": float(ca.get("flops", 0.0)),
                "bytes_accessed": float(ca.get("bytes accessed", 0.0)),
            }
        except Exception as e:  # backends legitimately decline
            self._reg.counter_inc("kernel.cost.unavailable")
            events_mod.record("kernel.cost_unavailable", kernel=self.name,
                              error=type(e).__name__)
            return self.cost
        reg = self._reg
        reg.gauge_set(f"kernel.{self.label}.cost_flops", cost["flops"])
        reg.gauge_set(f"kernel.{self.label}.cost_bytes",
                      cost["bytes_accessed"])
        with self._lock:
            self.cost = cost
            self._cost_at_compiles = at
        return cost


class _ObservedKernel:
    """The per-jit-site callable wrapper.  Transparent by construction:
    ``__wrapped__`` reaches the plain Python function (kernelcheck's
    ``_unjit`` discipline), unknown attributes (``lower``,
    ``clear_cache``) forward to the jitted target."""

    def __init__(self, profile: KernelProfile, jitted: Callable):
        self._fn = jitted
        self._profile = profile
        self._cache_seen = self._cache_size()
        self.__wrapped__ = getattr(jitted, "__wrapped__", jitted)
        self.__name__ = getattr(jitted, "__name__", profile.label)
        self.__doc__ = getattr(jitted, "__doc__", None)
        self.__module__ = getattr(jitted, "__module__", __name__)

    def _cache_size(self) -> int:
        try:
            return self._fn._cache_size()
        except Exception:
            return 0

    def __call__(self, *args, **kwargs):
        prof = self._profile
        t0 = time.perf_counter()
        try:
            out = self._fn(*args, **kwargs)
            if _BLOCKING:
                _jax().block_until_ready(out)
        except BaseException:
            prof.record_error()
            raise
        dt = time.perf_counter() - t0
        size = self._cache_size()
        compiled = size - self._cache_seen
        self._cache_seen = size
        try:
            nbytes = _tree_bytes(args, kwargs, out)
        except Exception:
            nbytes = 0
        if compiled > 0:
            # a compiling call's wall is dominated by the compile: it
            # rides the kernel.compile event, keeping the wall
            # histogram a steady-state distribution
            prof.record_compile(compiled, dt, args, kwargs, self._fn,
                                nbytes)
        else:
            prof.record_call(dt, nbytes, _BLOCKING)
        return out

    def __getattr__(self, item):
        return getattr(object.__getattribute__(self, "_fn"), item)

    def __repr__(self):
        return f"<observed kernel {self._profile.name!r} of {self._fn!r}>"


class KernelObservatory:
    """The process's runtime kernel registry: one
    :class:`KernelProfile` per manifest row, created eagerly from
    :data:`crdt_tpu.analysis.kernels.MANIFEST` so the ``/kernels``
    table shows un-instrumented rows as explicit gaps, not absences."""

    def __init__(self, registry: Optional[metrics_mod.MetricsRegistry]
                 = None):
        from ..analysis.kernels import MANIFEST  # stdlib-only import

        self._registry = registry if registry is not None \
            else metrics_mod.registry()
        self._lock = threading.Lock()
        self._profiles: Dict[str, KernelProfile] = {
            spec.name: KernelProfile(spec, self._registry)
            for spec in MANIFEST
        }

    def profile(self, name: str) -> KernelProfile:
        try:
            return self._profiles[name]
        except KeyError:
            raise ValueError(
                f"kernel {name!r} has no KernelSpec row in "
                "crdt_tpu/analysis/kernels.py — the runtime observatory "
                "shares the manifest's single source of kernel identity; "
                "add the row first (same discipline as obs/namespace.py)"
            ) from None

    def instrument(self, name: str, jitted: Callable) -> Callable:
        prof = self.profile(name)
        with self._lock:
            prof.instrumented = True
            prof.instances += 1
        return _ObservedKernel(prof, jitted)

    # -- views ---------------------------------------------------------------

    def profiles(self) -> Dict[str, KernelProfile]:
        return dict(self._profiles)

    def instrumented_names(self) -> set:
        return {n for n, p in self._profiles.items() if p.instrumented}

    def worst_budget_state(self) -> int:
        return max(
            (WATERMARK_STATES.index(p.watermark)
             for p in self._profiles.values() if p.instrumented),
            default=0,
        )

    def publish(self) -> None:
        """Drain every instrumented profile's pending per-call
        aggregates into the registry (see :meth:`KernelProfile.
        publish`)."""
        for prof in self._profiles.values():
            if prof.instrumented:
                prof.publish()

    def capture_costs(self, names: Optional[List[str]] = None) -> dict:
        """Run the lazy cost capture for every instrumented kernel (or
        the named subset); returns ``{name: cost}`` for the captures
        that succeeded."""
        out = {}
        for name, prof in sorted(self._profiles.items()):
            if names is not None and name not in names:
                continue
            cost = prof.capture_cost()
            if cost is not None:
                out[name] = cost
        return out

    def table(self) -> List[dict]:
        """The per-kernel runtime table ``/kernels?format=json``
        serves: identity, compile accounting vs the declared budget,
        wall quantiles from the registry histogram, throughput, and
        the captured XLA cost."""
        self.publish()
        snap = self._registry.snapshot()
        hists = snap.get("histograms", {})
        rows = []
        for name, p in sorted(self._profiles.items()):
            h = hists.get(f"kernel.{p.label}.wall")
            row = {
                "kernel": name,
                "label": p.label,
                "instrumented": p.instrumented,
                "instances": p.instances,
                "calls": p.calls,
                "compiles": p.compiles,
                "errors": p.errors,
                "compile_budget": p.compile_budget,
                "compile_budget_frac": round(p.budget_frac, 4),
                "watermark": p.watermark,
                "bytes_total": p.bytes_total,
                "wall_p50_s": _hist_quantile(h, 0.5),
                "wall_p99_s": _hist_quantile(h, 0.99),
                "gbps": round(
                    p.blocking_bytes / p.blocking_wall_s / 1e9, 4
                ) if p.blocking_wall_s > 0 else None,
                "last_compile_shapes": p.last_signature,
                "cost_flops": p.cost["flops"] if p.cost else None,
                "cost_bytes_accessed":
                    p.cost["bytes_accessed"] if p.cost else None,
            }
            if not p.traceable:
                row["notrace_reason"] = p.notrace_reason
            rows.append(row)
        return rows


def _hist_quantile(h: Optional[dict], q: float) -> Optional[float]:
    """Approximate quantile from a log2-bucket snapshot: the upper
    bound of the bucket where the cumulative count crosses ``q`` (an
    at-most-2x overestimate — the honest resolution of power-of-two
    buckets)."""
    if not h or not h.get("count"):
        return None
    target = q * h["count"]
    running = 0
    for e in sorted(h["buckets"]):
        running += h["buckets"][e]
        if running >= target:
            return 0.0 if e == metrics_mod.Histogram.ZERO_BUCKET \
                else math.ldexp(1.0, e)
    return h.get("max")


# -- the process-global observatory ------------------------------------------

_DEFAULT: Optional[KernelObservatory] = None
_DEFAULT_LOCK = threading.Lock()


def kernel_observatory() -> KernelObservatory:
    global _DEFAULT
    if _DEFAULT is None:
        with _DEFAULT_LOCK:
            if _DEFAULT is None:
                _DEFAULT = KernelObservatory()
    return _DEFAULT


def publish() -> None:
    """Drain the process-global observatory's pending per-call
    aggregates into the default registry (no-op before any kernel was
    instrumented — this must not force the manifest import)."""
    obs = _DEFAULT
    if obs is not None:
        obs.publish()


def _observatory_budget_refresh() -> None:
    obs = _DEFAULT
    if obs is not None:
        obs._registry.gauge_set("kernel.budget.watermark",
                                obs.worst_budget_state())


def observed_kernel(name: str) -> Callable:
    """Instrument one manifested jit entry point::

        @observed_kernel("batch.orswot.merge")
        @functools.partial(jax.jit, static_argnums=(10, 11, 12))
        def _merge(...): ...

    or, for factory-built kernels,
    ``return observed_kernel("sync.tree.fold")(jax.jit(kernel))``.
    ``name`` must be a manifest row (ValueError otherwise — the
    runtime registry refuses names kernelcheck has never heard of).
    Factories re-invoked with different statics/meshes attach multiple
    instances to ONE profile; compile counts aggregate across them."""

    def deco(jitted: Callable) -> Callable:
        return kernel_observatory().instrument(name, jitted)

    return deco


def warm_manifest() -> set:
    """Instrument every traceable manifest row without executing a
    kernel: building each row's trace cases imports its module (
    decorated kernels attach at import) and invokes its kernel factory
    (factory kernels attach at build).  Returns the instrumented name
    set — what the manifest↔runtime cross-check asserts against."""
    from ..analysis.kernels import MANIFEST

    for spec in MANIFEST:
        if spec.build is not None:
            spec.build()
    return kernel_observatory().instrumented_names()


# -- recompile-storm detection -----------------------------------------------


def storm_report(recorder: Optional[events_mod.FlightRecorder] = None,
                 since_seq: int = 0) -> dict:
    """Classify the flight recorder's ``kernel.compile`` events (with
    ``seq > since_seq`` — pass the last event's seq after warmup to
    scope a steady-state epoch): per kernel, how many compiles were
    ladder-attributed (first compile after an ``executor.regrow``/
    ``executor.shrink`` stamp), how many were first-ever (warmup), and
    which were neither — the shape-churn residue.  ``storm`` is True
    when any unexplained compile exists in the window."""
    rec = recorder if recorder is not None else events_mod.recorder()
    kernels: Dict[str, dict] = {}
    total = 0
    unexplained_total = 0
    for ev in rec.snapshot(kind="kernel.compile"):
        if ev["seq"] <= since_seq:
            continue
        f = ev.get("fields", {})
        k = f.get("kernel", "<unknown>")
        d = kernels.setdefault(k, {
            "compiles": 0, "ladder": 0, "first": 0, "unexplained": [],
        })
        n = int(f.get("count", 1))
        d["compiles"] += n
        total += n
        if f.get("ladder"):
            d["ladder"] += n
        elif f.get("first"):
            d["first"] += n
        else:
            unexplained_total += n
            d["unexplained"].append({
                "seq": ev["seq"],
                "shapes": f.get("shapes"),
                "wall_s": f.get("wall_s"),
            })
    return {
        "kernels": kernels,
        "compiles": total,
        "unexplained": unexplained_total,
        "storm": unexplained_total > 0,
    }


def last_event_seq(recorder: Optional[events_mod.FlightRecorder]
                   = None) -> int:
    """The recorder's newest retained seq — the warmup boundary a
    steady-state assertion passes to :func:`storm_report`."""
    rec = recorder if recorder is not None else events_mod.recorder()
    evs = rec.snapshot()
    return evs[-1]["seq"] if evs else 0


# -- device-memory accounting ------------------------------------------------

_SEEN_DTYPES: set = set()
_DEVMEM_LOCK = threading.Lock()


def sample_device_memory(registry: Optional[metrics_mod.MetricsRegistry]
                         = None, tracker=None) -> Optional[dict]:
    """Fold ``jax.live_arrays()`` into the ``devicemem.*`` gauge family
    (total live bytes, array count, per-dtype bytes); with a
    :class:`~crdt_tpu.obs.capacity.CapacityTracker` the tracked-plane
    bytes and tracked fraction ride along — the construction-vs-device
    gap.  No-op (returns None) when jax was never imported: sampling
    must not drag the device runtime into a scalar process."""
    jax = sys.modules.get("jax")
    if jax is None:
        return None
    reg = registry if registry is not None else metrics_mod.registry()
    total = 0
    count = 0
    by_dtype: Dict[str, int] = {}
    for arr in jax.live_arrays():
        nb = getattr(arr, "nbytes", None)
        if nb is None:
            continue
        count += 1
        total += int(nb)
        dt = getattr(arr.dtype, "name", str(arr.dtype))
        by_dtype[dt] = by_dtype.get(dt, 0) + int(nb)
    reg.counter_inc("devicemem.samples")
    reg.gauge_set("devicemem.live_bytes", total)
    reg.gauge_set("devicemem.arrays", count)
    with _DEVMEM_LOCK:
        stale = _SEEN_DTYPES - set(by_dtype)
        _SEEN_DTYPES.update(by_dtype)
    for dt, nb in sorted(by_dtype.items()):
        reg.gauge_set(f"devicemem.dtype.{dt}.bytes", nb)
    for dt in sorted(stale):  # a freed family drops to 0, not to stale
        reg.gauge_set(f"devicemem.dtype.{dt}.bytes", 0)
    out = {"live_bytes": total, "arrays": count, "by_dtype": by_dtype}
    if tracker is not None:
        tracked = sum(p.occupancy.bytes for p in tracker.planes().values())
        frac = tracked / total if total > 0 else 0.0
        reg.gauge_set("devicemem.tracked_bytes", tracked)
        reg.gauge_set("devicemem.tracked_frac", frac)
        out["tracked_bytes"] = tracked
        out["tracked_frac"] = frac
    return out
