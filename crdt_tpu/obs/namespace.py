"""The metric-namespace manifest — one source of truth for `crdt_tpu_*`.

PERF.md's "Metric naming" table used to be prose only; a counter and a
histogram silently sharing a name (`executor.regrow`, PR 3) showed that
the namespace needs to be machine-checkable.  This module IS the table:
every metric the process may emit matches exactly one :class:`NameSpec`
pattern here, with its registry type.  Two consumers:

* :mod:`crdt_tpu.obs.export` — the Prometheus prefix and name
  sanitization live here, so the exported name for any internal name is
  derivable without running the exporter.
* :mod:`crdt_tpu.analysis.telemetry` — the static namespace lint
  extracts every metric name declared in the source tree and fails on
  names outside this table (and on cross-type collisions).

Patterns are dotted, with ``*`` matching exactly one segment (segments
never contain dots by convention; dynamic segments — peer labels,
kernel names, fallback reasons — are single identifiers).  Adding a
metric family means adding a row here FIRST; the lint turns a missing
row into a CI failure, which is the point.

Stdlib-only: no jax, no numpy — the lint must be runnable without the
device runtime.
"""

from __future__ import annotations

from typing import Iterable, NamedTuple, Optional

#: the Prometheus metric-name prefix every exported name carries
PROM_PREFIX = "crdt_tpu"

#: registry types a name can claim (one per name, forever)
KINDS = ("counter", "gauge", "histogram")

_SAN = {ord(c): "_" for c in ".-/ "}


def sanitize(name: str) -> str:
    """Dotted internal metric name → Prometheus-legal metric name body
    (dots/dashes/slashes/spaces to underscores, anything else
    non-alphanumeric likewise)."""
    out = name.translate(_SAN)
    return "".join(c if c.isalnum() or c == "_" else "_" for c in out)


def prometheus_name(name: str, kind: str) -> str:
    """The exported Prometheus name for an internal dotted name:
    ``crdt_tpu_<sanitized>`` plus the ``_total`` suffix for counters
    (histograms grow ``_bucket``/``_sum``/``_count`` series at render
    time; the base name is returned here)."""
    base = f"{PROM_PREFIX}_{sanitize(name)}"
    return f"{base}_total" if kind == "counter" else base


class NameSpec(NamedTuple):
    """One documented metric family: a dotted pattern (``*`` = exactly
    one segment), its registry type, and what it measures."""

    pattern: str
    kind: str
    doc: str

    def matches(self, name: str) -> bool:
        pat = self.pattern.split(".")
        got = name.split(".")
        if len(pat) != len(got):
            return False
        return all(p == "*" or p == g for p, g in zip(pat, got))


#: Every metric family the process may emit.  The namespace lint
#: (`python -m crdt_tpu.analysis`) fails the build on any call site
#: whose name matches no row, or whose type disagrees with the row.
NAMESPACE: tuple[NameSpec, ...] = (
    # -- wire codec accounting (batch/wirebulk.record_wire) ------------------
    NameSpec("wire.*.*.native", "counter",
             "blobs that took the native path, per <type>.<direction>"),
    NameSpec("wire.*.*.fallback", "counter",
             "blobs that fell back to the Python codec"),
    NameSpec("wire.*.*.fallback_reason.*", "counter",
             "fallback blobs by reason (no_engine/non_identity/grammar/"
             "overflow_zigzag)"),
    # -- sync protocol frames (utils/tracing.record_sync + sync/delta) ------
    NameSpec("wire.sync.*.bytes", "counter",
             "bytes on the wire per sync leg (digest/delta/full)"),
    NameSpec("wire.sync.*.objects", "counter",
             "objects shipped per sync leg"),
    NameSpec("wire.sync.*.frame_bytes", "histogram",
             "per-frame size distribution per sync leg"),
    NameSpec("sync.frame.*.decoded", "counter",
             "accepted frames by type (digest/delta/full)"),
    NameSpec("sync.frame.rejected.*", "counter",
             "rejected frames by reason (truncated/version_mismatch/...)"),
    # -- sync sessions (sync/session.py) -------------------------------------
    NameSpec("sync.sessions", "counter", "sessions started"),
    NameSpec("sync.errors", "counter", "sessions that raised"),
    NameSpec("sync.digest_collision", "counter",
             "post-delta digest mismatches (64-bit collision / mode skew)"),
    NameSpec("sync.full_state_fallback", "counter",
             "sessions that shipped full state"),
    NameSpec("sync.full_state_fallback.*", "counter",
             "full-state fallbacks by reason (requested/threshold/"
             "digest_collision)"),
    NameSpec("sync.digest_exchange", "histogram",
             "digest-exchange phase wall time (span)"),
    # -- digest-tree descent (sync/session.py, sync/digest.py) ---------------
    NameSpec("sync.tree.descents", "counter",
             "sessions that ran the v3 subtree descent (root exchange)"),
    NameSpec("sync.tree.cutover", "counter",
             "descents that fell back to the flat exchange at the "
             "dense-divergence byte threshold"),
    NameSpec("sync.tree.collision", "counter",
             "descents where a differing parent had no differing child "
             "(truncated-lane collision / XOR cancellation) — fell back "
             "to the flat exchange"),
    NameSpec("sync.tree.fallback.*", "counter",
             "tree-capable sessions that ran flat, by reason "
             "(capability/version)"),
    NameSpec("sync.tree.spec_blasts", "counter",
             "descents that ran the v4 speculative streaming blast "
             "(all levels pipelined, ~1 RTT-equivalent)"),
    NameSpec("sync.tree.speculate.*", "counter",
             "speculated subtree lane blocks by outcome: hit = the "
             "true diverged walk used the block, miss = shipped but "
             "discarded (bounded by the dense-cutover byte budget)"),
    NameSpec("sync.delta.chunked_exchanges", "counter",
             "delta phases that streamed fixed-row DELTA_CHUNK frames "
             "through the ARQ window instead of one lock-step frame"),
    NameSpec("sync.digest.eager", "counter",
             "flat sessions that shipped phase 1 inside the hello "
             "flight (same wire sequence, one wait instead of two)"),
    NameSpec("sync.tree.exchange", "histogram",
             "tree root-compare + descent phase wall time (span)"),
    NameSpec("sync.digest.cache.*", "counter",
             "digest memo consults by outcome (hit/miss) — a converged "
             "re-sync must be all hits (zero digest-kernel launches)"),
    NameSpec("sync.delta_exchange", "histogram",
             "delta-exchange phase wall time (span)"),
    NameSpec("sync.full_state_exchange", "histogram",
             "full-state exchange wall time (span)"),
    # -- per-peer convergence gauges (obs/convergence.py) --------------------
    NameSpec("sync.peer.*.divergence", "gauge",
             "objects diverged at the last digest exchange (-1 = roster "
             "peer admitted but never exchanged — unknown, not zero)"),
    NameSpec("sync.peer.*.divergence_frac", "gauge",
             "diverged fraction of the fleet (-1 = never exchanged)"),
    NameSpec("sync.peer.*.rounds_to_converge", "gauge",
             "digest exchanges the last session needed"),
    NameSpec("sync.peer.*.staleness_s", "gauge",
             "seconds since the last converged sync (refreshed at "
             "scrape; +Inf = roster peer that has NEVER converged — "
             "seeded at membership admission so silent peers alert)"),
    NameSpec("sync.peer.*.delta_ratio", "gauge",
             "last session's payload bytes over the full-state reference"),
    NameSpec("sync.peer.*.diverged_subtrees", "gauge",
             "widest diverged internal frontier the last tree descent "
             "saw (0 = converged or flat-mode peer); urgency tiebreak"),
    # -- convergence observatory (obs/stability.py) ---------------------------
    NameSpec("sync.peer.*.divergence_age_s", "gauge",
             "age of this peer's OLDEST still-diverged subtree (0 = "
             "nothing outstanding) — a subtree stuck diverged across "
             "rounds shows up here, not as invisible churn"),
    NameSpec("sync.stability.divergence_age_s", "histogram",
             "birth-to-resolution age of diverged subtrees, per "
             "(peer, subtree) episode"),
    NameSpec("sync.stability.divergence_age_p50_s", "gauge",
             "median resolved divergence age over the bounded window "
             "(-1 = nothing resolved yet)"),
    NameSpec("sync.stability.divergence_age_max_s", "gauge",
             "worst resolved divergence age over the bounded window "
             "(-1 = nothing resolved yet)"),
    NameSpec("sync.stability.outstanding", "gauge",
             "(peer, subtree) pairs currently diverged at this observer"),
    NameSpec("sync.stability.resolved", "counter",
             "divergence episodes resolved (a later exchange found the "
             "subtree clean again)"),
    NameSpec("stability.frontier.*", "gauge",
             "fleet stability frontier state (peers/stale/unheard/"
             "excluded contributing counts, subtrees, age_s, "
             "max_counter of the fleet-min clock, lag behind the local "
             "frontier) — the clock below which every non-quarantined "
             "peer has provably converged"),
    NameSpec("stability.frontier.subtree.*.max_counter", "gauge",
             "per-subtree frontier clock (max over actors) — the "
             "structure the truncate-epoch proposer and op-log "
             "stability compaction will consume"),
    NameSpec("stability.audit.checks", "counter",
             "lattice-auditor checks performed (sampled self-merge "
             "idempotence + frontier soundness cross-checks)"),
    NameSpec("stability.audit.violations", "counter",
             "lattice-auditor violations — ANY nonzero value is a "
             "lattice-stack bug (loud stability.audit_violation event "
             "carries the plane that lied)"),
    NameSpec("stability.audit", "histogram",
             "one lattice-audit pass (span)"),
    # -- latency observatory (obs/latency.py, sync/session.py,
    # cluster/transport.py) ---------------------------------------------------
    NameSpec("sync.peer.*.network_wait_frac", "gauge",
             "fraction of the last session's wall spent blocked on the "
             "wire (~1 = RTT-bound, pipelining wins)"),
    NameSpec("sync.peer.*.unaccounted_frac", "gauge",
             "fraction of the last session's wall the profiler could "
             "not attribute — large values are a profiler finding"),
    NameSpec("sync.profile.*", "histogram",
             "per-session critical-path decomposition, seconds "
             "(wall/serialize/network_wait/kernel/other/unaccounted)"),
    NameSpec("sync.peer.*.lag_p50_s", "gauge",
             "median write-to-visible replication lag from this origin "
             "peer, over the bounded sample window"),
    NameSpec("sync.peer.*.lag_p99_s", "gauge",
             "p99 write-to-visible replication lag from this origin peer"),
    NameSpec("sync.peer.*.lag_outstanding", "gauge",
             "sidecar-stamped peer writes not yet visible locally"),
    NameSpec("sync.peer.*.lag_current_s", "gauge",
             "age of the oldest shipped-but-not-yet-visible peer write "
             "(0 = quiescent: everything stamped is visible)"),
    NameSpec("sync.lag.samples", "counter",
             "write-to-visible lag measurements taken (all peers)"),
    NameSpec("sync.lag.fallback.*", "counter",
             "lag sidecars degraded by reason (capability = peer too "
             "old to speak the sidecar; clock_domain = cross-process "
             "monotonic stamps, not comparable)"),
    NameSpec("sync.slo.converged_frac", "gauge",
             "fraction of recent gossip rounds that converged within "
             "the SLO budget (obs/latency.py LagTracker.observe_round)"),
    NameSpec("cluster.transport.*.rtt_srtt_s", "gauge",
             "per-link Jacobson/Karels smoothed RTT over ARQ ack "
             "round-trips (Karn-filtered)"),
    NameSpec("cluster.transport.*.rtt_rttvar_s", "gauge",
             "per-link RTT mean deviation"),
    NameSpec("cluster.transport.*.rtt_rto_s", "gauge",
             "per-link adaptive retransmit timer srtt + 4*rttvar, "
             "clamped to [min_rto_s, max_backoff_s]"),
    NameSpec("cluster.transport.*.rtt_samples", "gauge",
             "per-link RTT samples folded into the estimator"),
    # -- cluster runtime (cluster/membership.py, cluster/gossip.py,
    # cluster/transport.py, cluster/faults.py) -------------------------------
    NameSpec("cluster.peers.*", "gauge",
             "peer count per health state (alive/suspect/dead)"),
    NameSpec("cluster.peer.*.state", "gauge",
             "per-peer health as a level (0 alive, 1 suspect, 2 dead)"),
    NameSpec("cluster.peer.*.consecutive_failures", "gauge",
             "per-peer consecutive failed sessions (resets on success)"),
    NameSpec("cluster.peer_transition.*", "counter",
             "peer health transitions by destination state"),
    NameSpec("cluster.rounds", "counter", "gossip rounds started"),
    NameSpec("cluster.round", "histogram", "gossip round wall time (span)"),
    NameSpec("cluster.sessions.*", "counter",
             "gossip-driven sessions by outcome (ok/failed/skipped_busy)"),
    NameSpec("cluster.transport.retransmits", "counter",
             "ARQ data frames re-sent after an ack timeout"),
    NameSpec("cluster.transport.timeouts", "counter",
             "transport legs that blew their deadline (SyncTimeoutError)"),
    NameSpec("cluster.transport.corrupt", "counter",
             "ARQ envelopes dropped as malformed (treated as loss)"),
    NameSpec("cluster.transport.duplicates", "counter",
             "duplicate ARQ data frames suppressed at the receiver"),
    NameSpec("cluster.transport.transient_errors", "counter",
             "transport legs that failed and were retried with backoff"),
    NameSpec("cluster.transport.window.sacks", "counter",
             "selective-ack frames sent (out-of-order data buffered "
             "while a cumulative gap is outstanding)"),
    NameSpec("cluster.transport.window.ooo", "counter",
             "data frames accepted out of order into the reorder "
             "buffer (delivered once the gap fills)"),
    NameSpec("cluster.transport.window.sacked", "counter",
             "in-flight frames a peer SACK marked received (their "
             "retransmit timers stop; only the gap frames re-send)"),
    NameSpec("cluster.transport.fallback.window", "counter",
             "windowed transports degraded to a smaller window by "
             "hello negotiation (0/absent peer window = stop-and-wait "
             "peer) — mixed fleets degrade loudly, never error"),
    NameSpec("cluster.transport.*.window_inflight_hw", "gauge",
             "per-link high-water mark of unacked ARQ frames in "
             "flight (≤ the negotiated window)"),
    NameSpec("cluster.faults.*", "counter",
             "injected faults by kind (drop/delay/truncate/duplicate/"
             "disconnect) — nonzero outside tests means faults.py leaked "
             "into production wiring"),
    # -- gossip-round fleet health (cluster/gossip.py) -----------------------
    NameSpec("cluster.gossip.*", "gauge",
             "last gossip round's health (attempted/ok/failed/"
             "skipped_busy) + fleet convergence view (fleet_divergence_"
             "max, eta_rounds — peers still diverged over the fanout)"),
    # -- op-based write front-end (oplog/, cluster/gossip.py,
    # sync/session.py, batch/wireloop.py) ------------------------------------
    NameSpec("oplog.submitted", "counter",
             "ops appended to an op log (writers, wire frames, session "
             "piggybacks)"),
    NameSpec("oplog.pending", "gauge",
             "ops queued in the node's op log awaiting the fold"),
    NameSpec("oplog.parked", "gauge",
             "adds parked on a causal gap (missing predecessor dots)"),
    NameSpec("oplog.log_depth", "gauge",
             "ops buffered in the op log right now (refreshed by the "
             "log itself on every append/drain — nonzero while a "
             "session holds the fold lock)"),
    NameSpec("oplog.watermark", "gauge",
             "highest per-actor dot counter the op log has seen (max "
             "over actors) — the cheap write-progress signal"),
    NameSpec("oplog.apply.*", "counter",
             "apply_ops outcomes (ops/applied/duplicates/parked/"
             "released/rm_rounds)"),
    NameSpec("oplog.apply_ops", "histogram",
             "one scatter-fold apply call (span)"),
    NameSpec("oplog.exchange", "histogram",
             "session op-piggyback wall time (span)"),
    NameSpec("oplog.frames.decoded", "counter", "accepted op frames"),
    NameSpec("oplog.frames.rejected.*", "counter",
             "rejected op frames by reason (truncated/version_mismatch/"
             "crc_mismatch/bad_kind/...)"),
    NameSpec("wire.oplog.*.ops", "counter",
             "ops moved through the op-frame codec per direction "
             "(encode/decode)"),
    NameSpec("wire.oplog.*.bytes", "counter",
             "op-frame bytes per direction (encode/decode)"),
    # -- fleet observatory (obs/fleet.py, obs/export.py) ---------------------
    NameSpec("obs.events.dropped", "gauge",
             "flight-recorder events evicted by the ring bound "
             "(refreshed at scrape time)"),
    NameSpec("obs.fleet.merges", "counter",
             "peer fleet snapshots merged into this observatory"),
    NameSpec("obs.fleet.nodes", "gauge",
             "distinct nodes in the merged fleet snapshot"),
    NameSpec("obs.fleet.frames.decoded", "counter",
             "accepted fleet-snapshot frames"),
    NameSpec("obs.fleet.frames.rejected.*", "counter",
             "rejected fleet frames by reason (truncated/"
             "version_mismatch/crc_mismatch/...)"),
    NameSpec("obs.fleet.exchange", "histogram",
             "piggybacked snapshot-exchange wall time (span)"),
    NameSpec("obs.fleet.snapshot_bytes", "histogram",
             "encoded merged-snapshot frame size"),
    # -- capacity observatory (obs/capacity.py, batch/occupancy.py) ----------
    NameSpec("capacity.samples", "counter",
             "occupancy sampling passes (any plane family)"),
    NameSpec("capacity.watermark", "gauge",
             "overall capacity watermark (0 ok / 1 warn / 2 critical — "
             "the max across tracked planes; /healthz's status)"),
    NameSpec("capacity.*.bytes", "gauge",
             "exact plane bytes per tracked plane label (== device "
             "buffer nbytes by construction)"),
    NameSpec("capacity.*.objects", "gauge",
             "fleet rows per tracked plane (log segments for op logs)"),
    NameSpec("capacity.*.slots", "gauge",
             "padded cells along the binding slot axis"),
    NameSpec("capacity.*.live", "gauge",
             "live cells along the binding slot axis, fleet-wide"),
    NameSpec("capacity.*.live_max", "gauge",
             "busiest object's live slot count — the distance-to-"
             "overflow statistic growth rates and ETAs track"),
    NameSpec("capacity.*.tombstones", "gauge",
             "live deferred-remove/tombstone rows, fleet-wide"),
    NameSpec("capacity.*.utilization", "gauge",
             "live_max over the plane's regrow ceiling"),
    NameSpec("capacity.*.growth_rows_per_s", "gauge",
             "EWMA growth of live_max, rows/s (absent until two "
             "samples)"),
    NameSpec("capacity.*.eta_s", "gauge",
             "seconds until live_max hits the regrow ceiling at the "
             "EWMA rate (-1 = not growing, 0 = already there)"),
    NameSpec("capacity.*.watermark", "gauge",
             "per-plane watermark (0 ok / 1 warn / 2 critical)"),
    # -- causal GC (gc/watermark.py, gc/policy.py, gc/repack.py) -------------
    NameSpec("gc.runs", "counter", "causal-GC collection passes"),
    NameSpec("gc.shrinks", "counter",
             "plane re-packs that shrank a capacity rung"),
    NameSpec("gc.reclaimed_bytes", "counter",
             "bytes released by re-packing and op-buffer compaction"),
    NameSpec("gc.tombstones_cleared", "counter",
             "deferred-remove tombstone rows settled by GC"),
    NameSpec("gc.oplog_ops_dropped", "counter",
             "buffered ops dropped as already-witnessed below the "
             "fleet watermark"),
    NameSpec("gc.collect", "histogram",
             "one causal-GC collection pass (span)"),
    NameSpec("gc.watermark.*", "gauge",
             "fleet low-watermark state (peers/stale/unheard/excluded "
             "contributing counts, age_s of the oldest contribution, "
             "max_counter of the watermark clock, lag behind the local "
             "frontier)"),
    # -- durable replicas (durable/, cluster/gossip.py) ----------------------
    NameSpec("durable.snapshots", "counter",
             "snapshot generations written (atomic rename-into-place)"),
    NameSpec("durable.snapshot.decoded", "counter",
             "snapshot generations that decoded AND passed the "
             "digest-root self-check"),
    NameSpec("durable.snapshot.rejected.*", "counter",
             "snapshot loads rejected by reason (truncated/bad_magic/"
             "version_mismatch/crc_mismatch/root_mismatch/...)"),
    NameSpec("durable.snapshot.fallbacks", "counter",
             "recoveries that fell back past a rejected generation"),
    NameSpec("durable.wal.frames", "counter",
             "op frames appended to WAL segments (fsync'd before the "
             "in-memory fold)"),
    NameSpec("durable.wal.bytes", "counter",
             "bytes appended to WAL segments"),
    NameSpec("durable.wal.torn", "counter",
             "WAL segments whose torn tail was truncated (the expected "
             "kill -9 mid-append shape; the bytes were never "
             "acknowledged durable)"),
    NameSpec("durable.wal.segments_dropped", "counter",
             "WAL segments deleted by checkpoint/watermark truncation"),
    NameSpec("durable.snapshot.generation", "gauge",
             "latest snapshot generation number"),
    NameSpec("durable.snapshot.bytes", "gauge",
             "latest snapshot file size"),
    NameSpec("durable.snapshot.age_s", "gauge",
             "seconds since the last checkpoint (refreshed at "
             "round-end cadence checks)"),
    NameSpec("durable.wal.depth", "gauge",
             "op frames in retained WAL segments — the replay a "
             "recovery right now would face"),
    NameSpec("durable.wal.pending_bytes", "gauge",
             "bytes across retained WAL segments"),
    NameSpec("durable.replay.frames", "gauge",
             "WAL frames the last recovery replayed"),
    NameSpec("durable.replay.ops", "gauge",
             "ops the last recovery replayed through the causal-gap "
             "apply path"),
    NameSpec("durable.recovery.wall_s", "gauge",
             "last recovery's wall time (restore + verify + replay)"),
    NameSpec("durable.checkpoint", "histogram",
             "one checkpoint pass: snapshot write + WAL roll/truncate "
             "(span)"),
    NameSpec("durable.recover", "histogram",
             "one recovery: restore + root verify + WAL replay (span)"),
    # -- native engine (native/engine.py) ------------------------------------
    NameSpec("native.engine.*.calls", "counter",
             "native kernel invocations per entry point"),
    NameSpec("native.engine.*.objects", "counter",
             "objects processed per native entry point"),
    # -- the read front-end (crdt_tpu/serve) ---------------------------------
    NameSpec("serve.reads", "counter",
             "rows resolved by the gather engine (one per read row)"),
    NameSpec("serve.batches", "counter", "read batches gathered"),
    NameSpec("serve.batch_depth", "gauge",
             "decoded read batches staged ahead of the gather "
             "(the serve loop's bounded decode queue)"),
    NameSpec("serve.admit.*", "counter",
             "admitted read batches by consistency mode "
             "(eventual/ryw/monotonic/frontier)"),
    NameSpec("serve.park.*", "counter",
             "read batches that parked awaiting visibility, by mode"),
    NameSpec("serve.reject.*", "counter",
             "read batches terminally rejected by admission, by mode "
             "(the typed ConsistencyUnavailableError)"),
    NameSpec("serve.not_stable_rows", "counter",
             "frontier-mode rows above the stability frontier "
             "(stamped ST_NOT_STABLE instead of served as stable)"),
    NameSpec("serve.stalls", "counter",
             "serve-loop gather waits past the stall threshold "
             "(decode thread behind)"),
    NameSpec("serve.reads_per_s", "gauge",
             "rows/s of the most recent served batch"),
    NameSpec("serve.read_latency", "histogram",
             "per-batch serve wall (admission park included)"),
    NameSpec("serve.park_wait", "histogram",
             "admission park wall per parked batch"),
    NameSpec("serve.latency.*", "histogram",
             "per-batch serve wall by consistency mode "
             "(eventual/ryw/monotonic/frontier) — the PR 17 gap: "
             "serve.read_latency aggregated, nothing split by mode"),
    NameSpec("serve.park_wait_s", "histogram",
             "admission park duration in seconds per parked batch "
             "(what /healthz's serve section reports as wall)"),
    NameSpec("serve.frames.decoded", "counter", "accepted serve frames"),
    NameSpec("serve.frames.rejected.*", "counter",
             "rejected serve frames by reason (truncated/"
             "version_mismatch/bad_kind/...)"),
    NameSpec("wire.serve.*.ops", "counter",
             "read rows per serve wire direction (encode/decode)"),
    NameSpec("wire.serve.*.bytes", "counter",
             "serve frame bytes per direction"),
    # -- pipelined wire loop (batch/wireloop.py) -----------------------------
    NameSpec("wireloop.stalls", "counter",
             "folds that waited on the parse thread past the threshold"),
    NameSpec("wireloop.staging_free", "gauge",
             "free staging plane sets (0 = parse-bound)"),
    NameSpec("wireloop.parsed_depth", "gauge",
             "parsed fleets queued ahead of the fold"),
    # -- executor (parallel/executor.py) -------------------------------------
    NameSpec("executor.recovery.*", "counter",
             "recoveries by kind (regrow/transient_retry) — disjoint from "
             "the executor.* spans by construction (the PR 3 collision)"),
    NameSpec("executor.join_all", "histogram", "sequential fold span"),
    NameSpec("executor.join_all_tree", "histogram", "tree join span"),
    NameSpec("executor.merge", "histogram", "one recoverable pair merge"),
    NameSpec("executor.regrow", "histogram", "capacity regrow span"),
    NameSpec("executor.shrink", "histogram",
             "capacity shrink (GC re-pack) span — the regrow path in "
             "reverse (crdt_tpu/gc/repack.py)"),
    # -- kernels (utils/tracing.timed_kernel, obs/kernels.py) ----------------
    NameSpec("kernel.*.errors", "counter",
             "raising calls per timed/observed kernel label"),
    NameSpec("kernel.*.calls", "counter",
             "invocations per observed kernel label (manifest name with "
             "dots flattened to underscores)"),
    NameSpec("kernel.*.compiles", "counter",
             "jit cache misses (lowering+compile) per observed kernel"),
    NameSpec("kernel.*.bytes", "counter",
             "array bytes moved through an observed kernel (inputs + "
             "outputs; an HBM-traffic lower bound)"),
    NameSpec("kernel.*.wall", "histogram",
             "per-call wall per observed kernel (dispatch wall by "
             "default; device time under CRDT_TRACE=1/set_blocking; "
             "compiling calls excluded — they ride kernel.compile "
             "events)"),
    NameSpec("kernel.*.gbps", "gauge",
             "bytes-moved throughput per observed kernel (blocking-mode "
             "samples only — the bandwidth-roofline coordinate)"),
    NameSpec("kernel.*.compile_budget_frac", "gauge",
             "runtime compiles over the kernelcheck KC04 compile_budget "
             "— KC04's static bound as a live watermark (>1 sustained "
             "in steady state = shape churn)"),
    NameSpec("kernel.*.cost_flops", "gauge",
             "XLA cost_analysis flops for the last captured lowering"),
    NameSpec("kernel.*.cost_bytes", "gauge",
             "XLA cost_analysis bytes-accessed for the last captured "
             "lowering"),
    NameSpec("kernel.compiles", "counter",
             "process-wide jit compiles across all observed kernels "
             "(zero growth after warmup = the steady-state invariant)"),
    NameSpec("kernel.budget.watermark", "gauge",
             "worst per-kernel compile-budget state (0 ok / 1 warn / 2 "
             "critical), like capacity.watermark"),
    NameSpec("kernel.cost.unavailable", "counter",
             "cost_analysis captures the backend declined"),
    # -- device memory (obs/kernels.sample_device_memory, capacity
    # cadence) ----------------------------------------------------------------
    NameSpec("devicemem.samples", "counter",
             "device-memory sampling passes (jax.live_arrays walks)"),
    NameSpec("devicemem.live_bytes", "gauge",
             "bytes held by live jax arrays process-wide — what the "
             "device actually holds vs plane bytes by construction"),
    NameSpec("devicemem.arrays", "gauge", "live jax array count"),
    NameSpec("devicemem.dtype.*.bytes", "gauge",
             "live array bytes by dtype family (a freed family reads "
             "0, never a stale level)"),
    NameSpec("devicemem.tracked_bytes", "gauge",
             "plane bytes the capacity tracker accounts for"),
    NameSpec("devicemem.tracked_frac", "gauge",
             "tracked_bytes over live_bytes — how much of device "
             "memory the capacity observatory explains"),
    # -- profiler capture (utils/tracing.profile) ----------------------------
    NameSpec("obs.profiler_unavailable", "counter",
             "XLA profiler trace setups that failed (exception class "
             "in the one-time obs.profiler_unavailable event) — why "
             "the trace directory is empty"),
    # -- heat & placement observatory (obs/heat.py) --------------------------
    NameSpec("heat.subtree.*.reads", "counter",
             "read rows attributed to digest-tree subtree <i> "
             "(serve gather batches folded by obs.heat.subtree_fold)"),
    NameSpec("heat.subtree.*.writes", "counter",
             "write rows attributed to subtree <i> (oplog drain "
             "batches)"),
    NameSpec("heat.subtree.*.repair", "counter",
             "sync delta rows applied in subtree <i> — anti-entropy "
             "churn, the objects that actually moved over the wire"),
    NameSpec("heat.subtree.*.reads_per_s", "gauge",
             "half-life-decayed read rate for subtree <i>"),
    NameSpec("heat.subtree.*.writes_per_s", "gauge",
             "half-life-decayed write rate for subtree <i>"),
    NameSpec("heat.subtree.*.repair_per_s", "gauge",
             "half-life-decayed repair rate for subtree <i>"),
    NameSpec("heat.reads.*", "counter",
             "read rows attributed per consistency mode "
             "(eventual/ryw/monotonic/frontier)"),
    NameSpec("heat.updates", "counter",
             "heat record batches folded (sketch + subtree kernels)"),
    NameSpec("heat.hot.*.obj", "gauge",
             "object id at hot rank <r> from the Space-Saving sketch"),
    NameSpec("heat.hot.*.count", "gauge",
             "sketch count at hot rank <r> (overestimate by at most "
             "the entry's recorded error)"),
    NameSpec("heat.zipf.s_hat", "gauge",
             "Zipf exponent fitted from the sketch's guaranteed "
             "rank-frequency counts (checkable vs WorkloadGen.zipf_s)"),
    NameSpec("heat.zipf.fit_r2", "gauge",
             "goodness of the Zipf rank-frequency fit (1 = a clean "
             "power law)"),
    # -- mesh-sharded fleets (crdt_tpu/mesh/) --------------------------------
    NameSpec("mesh.layout.shards", "gauge",
             "shard count of the active mesh layout"),
    NameSpec("mesh.layout.granule", "gauge",
             "shard-boundary granule (a pow2 subtree span) the layout "
             "snapped to — every boundary is a multiple of this"),
    NameSpec("mesh.layout.imbalance", "gauge",
             "planner-predicted max/mean shard load for the active "
             "layout (1.0 = perfectly balanced; matches "
             "/heat?plan=mesh:S&granule=G)"),
    NameSpec("mesh.shard.*.objects", "gauge",
             "logical (unpadded) object rows owned by shard <s>"),
    NameSpec("mesh.shard.*.load", "gauge",
             "measured heat (reads+writes+repair) attributed to shard "
             "<s>'s leaf range — compare against the planner's "
             "predicted loads"),
    NameSpec("mesh.step.rounds", "counter",
             "pjit'd anti-entropy steps executed (ONE kernel launch "
             "per round, all shards)"),
    NameSpec("mesh.step.digest_bytes", "counter",
             "bytes moved by the step's digest all_gather (the whole "
             "collective bill of a converged round)"),
    NameSpec("mesh.sync.rounds", "counter",
             "shard-subset sync passes (digest compare + per-shard "
             "descent)"),
    NameSpec("mesh.sync.shards_synced", "counter",
             "diverged shards repaired by a shard-scoped descent"),
    NameSpec("mesh.sync.shards_skipped", "counter",
             "converged shards a sync pass never touched (their "
             "subtree bytes stayed home)"),
    NameSpec("mesh.sync.delta_bytes", "counter",
             "delta payload bytes shipped by shard-subset sync "
             "(diverged shards only)"),
    NameSpec("mesh.sync.objects", "counter",
             "diverged object rows repaired by shard-subset sync"),
    NameSpec("mesh.durable.snapshots", "counter",
             "fleet checkpoint passes (S per-shard generations + one "
             "manifest)"),
    NameSpec("mesh.durable.restores", "counter",
             "fleet restores that re-verified every shard's subtree "
             "root against the manifest"),
    NameSpec("mesh.durable.rejected.*", "counter",
             "fleet restore rejections by reason (manifest_missing/"
             "manifest_corrupt/shard_missing/root_mismatch/"
             "layout_mismatch)"),
    NameSpec("mesh.contract.refused", "counter",
             "kernel dispatches the runtime contract gate refused "
             "(host_only/replicated/mesh-size outside the contract "
             "ladder) — the typed MeshContractError path"),
    # -- bench probes (bench.py bench_obs_overhead) --------------------------
    NameSpec("obs.overhead.count_probe", "counter",
             "bench_obs_overhead per-op counter cost probe"),
    NameSpec("obs.overhead.gauge_probe", "gauge",
             "bench_obs_overhead per-op gauge cost probe"),
)


def match(name: str, kind: Optional[str] = None) -> Optional[NameSpec]:
    """The manifest row ``name`` falls under, or None.  With ``kind``,
    the row must also agree on the registry type (a name matching a row
    of a different type is a namespace violation, not a match)."""
    for spec in NAMESPACE:
        if spec.matches(name):
            return spec if kind is None or spec.kind == kind else None
    return None


def patterns(kind: Optional[str] = None) -> Iterable[NameSpec]:
    """All manifest rows, optionally filtered by registry type."""
    return tuple(s for s in NAMESPACE if kind is None or s.kind == kind)
