"""Export surfaces: Prometheus text, JSON snapshots, a live HTTP thread.

Three consumers, three shapes:

* :func:`prometheus_text` — text exposition (format 0.0.4) of the
  metric registry under the ``crdt_tpu_`` namespace: counters as
  ``*_total``, gauges bare, histograms as ``_bucket``/``_sum``/
  ``_count`` with power-of-two ``le`` bounds.  Dotted metric names
  sanitize to underscores at scrape time so hot paths never pay for it.
* :func:`json_snapshot` — one dict with the registry snapshot, the
  flight-recorder events, and the per-peer convergence state; what
  ``bench.py`` embeds in the artifact tail and ``/events`` serves.
* :class:`MetricsServer` / :func:`start_metrics_server` — an opt-in,
  stdlib-only background HTTP thread serving ``GET /metrics`` (Prom
  text), ``GET /events`` (JSON; ``?session=`` / ``?kind=`` filters),
  ``GET /fleet`` (the CRDT-merged cross-process snapshot from
  :mod:`crdt_tpu.obs.fleet` — Prom text by default, ``?format=json``
  for per-node slices, ``?trace=<id>`` for a stitched cross-peer
  session timeline), ``GET /kernels`` (the runtime kernel observatory:
  per-kernel compile counts, budget fracs, wall quantiles and
  device-memory gauges — ``?format=json`` for the table +
  recompile-storm report, ``?cost=1`` to capture XLA cost analysis)
  and ``GET /healthz``.  Daemon threads throughout:
  an exporter must never
  keep a replica process alive or take it down — handler errors are
  swallowed into 500s and ``stop()`` is idempotent.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Optional

from . import convergence, events, metrics
from .namespace import PROM_PREFIX, sanitize as _sanitize


def _fmt(v: float) -> str:
    """Prometheus sample value: integers render bare, floats via repr,
    non-finite values in the exposition format's canonical spelling
    (the never-synced staleness sentinel is ``+Inf``)."""
    import math

    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, float) and not math.isfinite(v):
        if math.isnan(v):
            return "NaN"
        return "+Inf" if v > 0 else "-Inf"
    if isinstance(v, int) or (isinstance(v, float) and v.is_integer()):
        return str(int(v))
    return repr(float(v))


def prometheus_text(registry: Optional[metrics.MetricsRegistry] = None,
                    prefix: str = PROM_PREFIX,
                    tracker: Optional[convergence.ConvergenceTracker] = None,
                    name_prefixes: Optional[tuple] = None) -> str:
    """The registry as Prometheus text exposition.  Refreshes the
    read-time convergence gauges (staleness ages) first so a scrape
    sees live ages — the default tracker when rendering the default
    registry, else only a caller-supplied ``tracker`` (the one whose
    gauges land in ``registry``): scraping a private registry must not
    write the global tracker's gauges into the process-global one.
    ``name_prefixes`` restricts the rendered families to internal names
    starting with one of the given dotted prefixes (what ``/kernels``
    uses to serve just the ``kernel.``/``devicemem.`` plane)."""
    if tracker is None and registry is None:
        tracker = convergence.tracker()
    if tracker is not None:
        tracker.refresh()
    if registry is None:
        # read boundary: drain the kernel observatory's pending
        # per-call aggregates so the scrape sees fresh kernel.* rows
        # (default registry only, same discipline as the gauge below)
        from . import kernels as kernels_mod

        kernels_mod.publish()
        # scrape-time refresh of the flight recorder's eviction count:
        # `dropped` is a Python property, and an alert on "the ring is
        # overflowing faster than anyone reads it" needs it as a gauge.
        # Default registry only — a private-registry scrape must not
        # write global recorder state into the global registry's twin.
        metrics.registry().gauge_set(
            "obs.events.dropped", events.recorder().dropped
        )
    reg = registry if registry is not None else metrics.registry()
    snap = reg.snapshot()
    if name_prefixes is not None:
        def _keep(table):
            return {k: v for k, v in table.items()
                    if k.startswith(name_prefixes)}

        snap = {kind: _keep(table) for kind, table in snap.items()}
    lines = []
    for name in sorted(snap["counters"]):
        mname = f"{prefix}_{_sanitize(name)}_total"
        lines.append(f"# TYPE {mname} counter")
        lines.append(f"{mname} {_fmt(snap['counters'][name])}")
    for name in sorted(snap["gauges"]):
        mname = f"{prefix}_{_sanitize(name)}"
        lines.append(f"# TYPE {mname} gauge")
        lines.append(f"{mname} {_fmt(snap['gauges'][name])}")
    for name in sorted(snap["histograms"]):
        h = snap["histograms"][name]
        mname = f"{prefix}_{_sanitize(name)}"
        lines.append(f"# TYPE {mname} histogram")
        running = 0
        import math

        for e in sorted(h["buckets"]):
            running += h["buckets"][e]
            bound = 0.0 if e == metrics.Histogram.ZERO_BUCKET \
                else math.ldexp(1.0, e)
            lines.append(
                f'{mname}_bucket{{le="{_fmt(bound)}"}} {running}'
            )
        lines.append(f'{mname}_bucket{{le="+Inf"}} {h["count"]}')
        lines.append(f"{mname}_sum {_fmt(h['sum'])}")
        lines.append(f"{mname}_count {h['count']}")
    return "\n".join(lines) + "\n"


def json_snapshot(registry: Optional[metrics.MetricsRegistry] = None) -> dict:
    """One JSON-ready dict: metrics + flight-recorder events + per-peer
    convergence state (what ``/events`` and the bench artifact embed)."""
    if registry is None:
        from . import kernels as kernels_mod

        kernels_mod.publish()
    reg = registry if registry is not None else metrics.registry()
    rec = events.recorder()
    return {
        "metrics": reg.snapshot(),
        "events": rec.snapshot(),
        "events_dropped": rec.dropped,
        "convergence": convergence.tracker().snapshot(),
    }


# ---- the background HTTP exporter ------------------------------------------


class MetricsServer:
    """A daemon HTTP thread serving ``/metrics``, ``/events``,
    ``/fleet``, ``/kernels``, ``/healthz`` on localhost.  Construct via
    :func:`start_metrics_server`; ``port`` is the bound port (useful
    with ``port=0``), ``scrapes`` counts GETs per path (a peer that
    wants to linger "until someone scraped me" — the TCP example's
    ``--linger`` — polls it)."""

    def __init__(self, host: str, port: int,
                 registry: Optional[metrics.MetricsRegistry] = None,
                 tracker: Optional[convergence.ConvergenceTracker] = None,
                 observatory=None, capacity=None, stability=None,
                 heat=None):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        self._registry = registry
        self._tracker = tracker
        self._observatory = observatory
        self._capacity = capacity
        self._stability = stability
        self._heat = heat
        self._t0 = time.monotonic()
        self.scrapes: dict = {}
        self._scrape_lock = threading.Lock()
        server_self = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):  # the exporter must be silent
                pass

            def do_GET(self):
                try:
                    body, ctype, status = server_self._render(self.path)
                except Exception as e:  # noqa: BLE001 — a scrape bug
                    # must 500, never kill the serving thread
                    body = f"exporter error: {type(e).__name__}: {e}\n".encode()
                    ctype, status = "text/plain; charset=utf-8", 500
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self.host = host
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="obs-metrics-exporter",
            daemon=True,
        )
        self._thread.start()

    def _render(self, path: str) -> tuple:
        from urllib.parse import parse_qs, urlparse

        parsed = urlparse(path)
        route = parsed.path.rstrip("/") or "/"
        with self._scrape_lock:
            self.scrapes[route] = self.scrapes.get(route, 0) + 1
        if route == "/metrics":
            text = prometheus_text(self._registry, tracker=self._tracker)
            return text.encode(), "text/plain; version=0.0.4; charset=utf-8", 200
        if route == "/events":
            q = parse_qs(parsed.query)
            rec = events.recorder()
            evs = rec.snapshot(
                kind=q.get("kind", [None])[0],
                session=q.get("session", [None])[0],
            )
            body = json.dumps({
                "events": evs,
                "dropped": rec.dropped,
                "convergence": convergence.tracker().snapshot(),
            }).encode()
            return body, "application/json", 200
        if route == "/fleet":
            from . import fleet as fleet_mod

            obs = self._observatory if self._observatory is not None \
                else fleet_mod.observatory()
            snap = obs.merged()  # refreshes the local slice per scrape
            q = parse_qs(parsed.query)
            trace = q.get("trace", [None])[0]
            if trace is not None:
                body = json.dumps({
                    "trace": trace,
                    "timeline": fleet_mod.stitch_trace(snap, trace),
                }).encode()
                return body, "application/json", 200
            if q.get("format", [None])[0] == "json":
                return (json.dumps(snap.to_json()).encode(),
                        "application/json", 200)
            text = fleet_mod.fleet_prometheus_text(snap)
            return (text.encode(),
                    "text/plain; version=0.0.4; charset=utf-8", 200)
        if route == "/kernels":
            # the runtime kernel observatory (crdt_tpu/obs/kernels.py):
            # prom text of the kernel./devicemem. plane by default,
            # ?format=json for the per-kernel table (compiles, budget
            # frac, wall quantiles, GB/s, cost analysis) + the
            # recompile-storm classification.  ?cost=1 triggers the
            # lazy XLA cost_analysis capture first (one extra
            # lower+compile per kernel signature — deliberate, so the
            # default scrape stays cheap).  Device-memory gauges
            # refresh per scrape on the default registry (same
            # discipline as obs.events.dropped above).
            from . import kernels as kernels_mod

            q = parse_qs(parsed.query)
            obs = kernels_mod.kernel_observatory()
            if self._registry is None:
                kernels_mod.sample_device_memory(tracker=self._capacity)
            if q.get("cost", [None])[0]:
                obs.capture_costs()
            if q.get("format", [None])[0] == "json":
                body = json.dumps({
                    "kernels": obs.table(),
                    "storm": kernels_mod.storm_report(),
                }).encode()
                return body, "application/json", 200
            text = prometheus_text(
                self._registry, tracker=self._tracker,
                name_prefixes=("kernel.", "devicemem."))
            return (text.encode(),
                    "text/plain; version=0.0.4; charset=utf-8", 200)
        if route == "/stability":
            # the convergence observatory (crdt_tpu/obs/stability.py):
            # the published frontier (per-subtree + fleet-min clocks —
            # what the future truncate-epoch proposer consumes), the
            # divergence-aging view (which subtrees are stuck diverged,
            # and for how long) and the lattice-audit totals.  JSON
            # only: the clock VECTORS are the payload, and the scalar
            # gauges already ride /metrics as crdt_tpu_stability_*.
            from . import stability as stability_mod

            trk = self._stability if self._stability is not None \
                else stability_mod.tracker()
            body = json.dumps(trk.snapshot()).encode()
            return body, "application/json", 200
        if route == "/heat":
            # the heat & placement observatory (crdt_tpu/obs/heat.py):
            # prom text of the heat. plane by default (counters,
            # EWMA rates, top-k gauges — publish() refreshes them
            # first so a scrape never reads a stale window),
            # ?format=json for the full attribution snapshot (layout,
            # per-subtree split, decoded hot list with error bounds,
            # Zipf fit), ?plan=mesh:8 / ?plan=ring:5,k=3 for a scored
            # placement report against the measured heat.
            from . import heat as heat_mod

            trk = self._heat if self._heat is not None \
                else heat_mod.tracker()
            trk.publish()
            q = parse_qs(parsed.query)
            plan = q.get("plan", [None])[0]
            if plan is not None:
                # ?granule=G (mesh plans): subtree-aligned shard
                # boundaries, so the report prices exactly the layout
                # crdt_tpu.mesh.state.choose_layout would build
                granule = q.get("granule", [None])[0]
                try:
                    report = trk.plan_report(
                        plan,
                        granule=int(granule) if granule is not None
                        else None)
                except ValueError as e:
                    return (f"{e}\n".encode(),
                            "text/plain; charset=utf-8", 400)
                body = json.dumps({"heat": trk.snapshot(),
                                   "report": report}).encode()
                return body, "application/json", 200
            if q.get("format", [None])[0] == "json":
                return (json.dumps(trk.snapshot()).encode(),
                        "application/json", 200)
            # render from the TRACKER's registry: a node-private heat
            # tracker publishes its counters there, not into the
            # server-wide registry
            text = prometheus_text(
                trk.registry(), tracker=self._tracker,
                name_prefixes=("heat.",))
            return (text.encode(),
                    "text/plain; version=0.0.4; charset=utf-8", 200)
        if route == "/healthz":
            # liveness + the capacity watermark: `status` mirrors the
            # tracker's overall watermark state (ok/warn/critical; "ok"
            # when nothing is tracked yet), with the per-plane
            # breakdown under `capacity` so an operator's first curl
            # answers "how close is this node to its regrow ceiling".
            # Always HTTP 200 — a critical watermark is an alert, not
            # a liveness failure (restarting the process would make
            # the memory story WORSE).
            from . import capacity as capacity_mod

            cap = self._capacity if self._capacity is not None \
                else capacity_mod.capacity_tracker()
            wm = cap.watermark()
            # the read front-end's vitals ride liveness too: an operator
            # diagnosing "reads are failing" wants the admit/park/reject
            # split from the same curl that answers "is it up".  Totals
            # only — the per-mode breakdown stays on /metrics.
            reg = self._registry if self._registry is not None \
                else metrics.registry()
            snap = reg.snapshot()
            counters = snap["counters"]
            hists = snap["histograms"]

            def _fam(prefix: str) -> int:
                return sum(v for k, v in counters.items()
                           if k.startswith(prefix))

            def _wall(name: str) -> Optional[dict]:
                h = hists.get(name)
                if not h or not h.get("count"):
                    return None
                return {"count": h["count"],
                        "mean_s": round(h["sum"] / h["count"], 6),
                        "max_s": round(h["max"], 6)}

            # duration, not just counts (the PR 17 gap): per-mode
            # serve walls + how long admission parks actually held
            latency = {}
            for mode in ("eventual", "ryw", "monotonic", "frontier"):
                w = _wall("serve.latency." + mode)
                if w is not None:
                    latency[mode] = w

            body = json.dumps({
                "status": wm["state"],
                "uptime_s": round(time.monotonic() - self._t0, 3),
                "capacity": wm,
                "serve": {
                    "reads": counters.get("serve.reads", 0),
                    "batches": counters.get("serve.batches", 0),
                    "admitted": _fam("serve.admit."),
                    "parked": _fam("serve.park."),
                    "rejected": _fam("serve.reject."),
                    "not_stable_rows": counters.get(
                        "serve.not_stable_rows", 0),
                    "latency": latency,
                    "park_wait": _wall("serve.park_wait_s"),
                },
            }).encode()
            return body, "application/json", 200
        return (b"not found (try /metrics, /events, /fleet, /kernels, "
                b"/stability, /heat, /healthz)\n"), \
            "text/plain; charset=utf-8", 404

    def scrape_counts(self) -> dict:
        """Per-route GET counts so far (a consistent copy) — take one as
        the ``since`` baseline for :meth:`scraped`."""
        with self._scrape_lock:
            return dict(self.scrapes)

    def scraped(self, *routes: str, since: Optional[dict] = None) -> bool:
        """True once every named route has been GET'd at least once —
        strictly more times than in ``since`` (a prior
        :meth:`scrape_counts` baseline) when given, so a linger can wait
        for scrapes of the *final* state rather than counting ones that
        raced the work itself."""
        base = since or {}
        with self._scrape_lock:
            return all(self.scrapes.get(r, 0) > base.get(r, 0)
                       for r in routes)

    def stop(self) -> None:
        """Shut the exporter down; idempotent."""
        try:
            self._httpd.shutdown()
            self._httpd.server_close()
        except Exception:  # noqa: BLE001 — double-stop must be a no-op
            pass
        self._thread.join(timeout=5)


def start_metrics_server(port: int = 0, host: str = "127.0.0.1",
                         registry: Optional[metrics.MetricsRegistry] = None,
                         tracker: Optional[convergence.ConvergenceTracker]
                         = None, observatory=None,
                         capacity=None, stability=None,
                         heat=None) -> MetricsServer:
    """Start the opt-in background exporter; ``port=0`` picks a free
    port (read it back from ``server.port``).  ``tracker`` pairs a
    custom ``registry`` with the convergence tracker writing into it
    (see :func:`prometheus_text`); ``observatory`` is the
    :class:`~crdt_tpu.obs.fleet.FleetObservatory` behind ``/fleet``
    (default: the process-global one); ``capacity`` is the
    :class:`~crdt_tpu.obs.capacity.CapacityTracker` whose watermark
    ``/healthz`` reports (default: the process-global one);
    ``stability`` is the :class:`~crdt_tpu.obs.stability.
    StabilityTracker` behind ``/stability`` (default: the
    process-global one); ``heat`` is the
    :class:`~crdt_tpu.obs.heat.HeatTracker` behind ``/heat``
    (default: the process-global one)."""
    return MetricsServer(host, port, registry, tracker, observatory,
                         capacity, stability, heat)
