"""Typed metric registry — counters, gauges, log2-bucketed histograms.

The flat span/counter dicts in :mod:`crdt_tpu.utils.tracing` only become
legible when ``bench.py`` diffs snapshots after the fact; a live export
surface (:mod:`crdt_tpu.obs.export`) needs metrics with *types*, because
a Prometheus scrape renders a counter, a gauge, and a histogram
differently and a consumer alerts on them differently:

* :class:`Counter` — monotonically increasing event counts (the
  always-on ``wire.*`` native-vs-fallback accounting, sync frame
  bytes).  Resets only with the registry.
* :class:`Gauge` — a point-in-time level (wire-loop staging-pool
  occupancy, parse-queue depth, per-peer digest divergence).  Last
  write wins.
* :class:`Histogram` — log2-bucketed distributions (span latencies,
  sync frame sizes).  Power-of-two buckets make ``observe`` one
  ``frexp`` + dict increment — cheap enough to stay always-on — while
  still answering "how many syncs took >128 ms" from the export.

Everything here is dependency-free and import-light: no JAX, no numpy.
Thread-safety: the one-shot registry methods (``counter_inc`` /
``gauge_set`` / ``observe``) and ``snapshot`` run under the registry
lock; a :class:`Counter` handle locks itself so cached-handle ``inc``
never drops increments; :class:`Gauge` handle writes are last-write-
wins by contract; :class:`Histogram` handles should be fed through
``registry.observe`` (multi-field updates need the registry lock to
keep snapshots untorn).  The
existing :mod:`crdt_tpu.utils.tracing` API re-routes into the default
registry, so every current ``span``/``count``/``record_sync``/
``record_wire`` call site feeds this module with no churn at the call
sites (see ``Tracer.forward_metrics``).
"""

from __future__ import annotations

import math
import threading
from typing import Dict, Iterator, Optional, Tuple


class Counter:
    """A monotonically increasing event count.

    ``inc`` takes the counter's own lock: handles are cached by hot
    paths and mutated outside the registry lock, and a read-modify-write
    without one can drop increments under concurrent writers — which the
    monotonic-counter contract forbids.
    """

    __slots__ = ("name", "value", "_lock")

    def __init__(self, name: str):
        self.name = name
        self.value = 0
        self._lock = threading.Lock()

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self.value += int(n)


class Gauge:
    """A point-in-time level; last write wins.

    Handle mutation is deliberately unsynchronized: a gauge tolerates a
    lost write by contract (the racing ``set`` that wins *is* the
    current level).  ``inc`` is read-modify-write — only use it on
    gauges with a single writer.
    """

    __slots__ = ("name", "value")

    def __init__(self, name: str):
        self.name = name
        self.value = 0.0

    def set(self, v: float) -> None:
        self.value = float(v)

    def inc(self, n: float = 1.0) -> None:
        self.value += float(n)


def log2_bucket(v: float) -> int:
    """The log2 bucket exponent for one observation: ``frexp`` puts
    ``v = m * 2**e`` with ``0.5 <= m < 1`` in ``[2**(e-1), 2**e)``;
    pulling exact powers of two (``m == 0.5``) down one exponent makes
    bucket ``e`` hold ``(2**(e-1), 2**e]``, so 4.0 exports under
    ``le="4"``, not ``le="8"`` (Prometheus ``le`` bounds are
    inclusive).  Non-positive values land in the floor bucket.
    Exposed so always-on instruments (the kernel observatory's
    per-call path) can bucket locally and merge via
    :meth:`MetricsRegistry.observe_aggregate`."""
    if v > 0.0:
        m, e = math.frexp(v)
        return e - 1 if m == 0.5 else e
    return Histogram.ZERO_BUCKET


class Histogram:
    """Log2-bucketed distribution: bucket ``e`` counts observations in
    ``(2**(e-1), 2**e]``.  Non-positive observations land in a floor
    bucket (exponent :data:`ZERO_BUCKET`) so a zero-length span is
    counted, not lost.  Sum/count/min/max ride along so the export can
    emit Prometheus ``_sum``/``_count`` and the mean survives bucketing.

    Observe through ``registry.observe`` under concurrency: ``observe``
    updates several fields, and only the registry lock keeps a
    concurrent ``snapshot`` from seeing them torn.
    """

    ZERO_BUCKET = -1075  # below the smallest subnormal double's exponent

    __slots__ = ("name", "count", "sum", "min", "max", "buckets")

    def __init__(self, name: str):
        self.name = name
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = -math.inf
        self.buckets: Dict[int, int] = {}

    def observe(self, v: float) -> None:
        v = float(v)
        e = log2_bucket(v)
        self.buckets[e] = self.buckets.get(e, 0) + 1
        self.count += 1
        self.sum += v
        if v < self.min:
            self.min = v
        if v > self.max:
            self.max = v

    def cumulative(self) -> Iterator[Tuple[float, int]]:
        """``(upper_bound, cumulative_count)`` pairs in ascending bound
        order — the Prometheus ``le`` series (without the +Inf bucket,
        which equals :attr:`count`)."""
        running = 0
        for e in sorted(self.buckets):
            running += self.buckets[e]
            bound = 0.0 if e == self.ZERO_BUCKET else math.ldexp(1.0, e)
            yield bound, running


class MetricsRegistry:
    """One process's named metrics, behind one lock.

    Names are free-form dotted strings (``wire.sync.digest.bytes``);
    the Prometheus exporter sanitizes them at scrape time, so hot paths
    never pay for name mangling.  A name is permanently one type —
    re-registering ``x`` as a gauge after counting it raises, because a
    silent type flip would corrupt the export.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._histograms: Dict[str, Histogram] = {}

    def _claim(self, name: str, kind: str) -> None:
        for other_kind, table in (("counter", self._counters),
                                  ("gauge", self._gauges),
                                  ("histogram", self._histograms)):
            if other_kind != kind and name in table:
                raise ValueError(
                    f"metric {name!r} is already a {other_kind}, "
                    f"cannot re-register as a {kind}"
                )

    # -- typed handles (hot paths hold these to skip the dict lookup) --------

    def counter(self, name: str) -> Counter:
        with self._lock:
            c = self._counters.get(name)
            if c is None:
                self._claim(name, "counter")
                c = self._counters[name] = Counter(name)
            return c

    def gauge(self, name: str) -> Gauge:
        with self._lock:
            g = self._gauges.get(name)
            if g is None:
                self._claim(name, "gauge")
                g = self._gauges[name] = Gauge(name)
            return g

    def histogram(self, name: str) -> Histogram:
        with self._lock:
            h = self._histograms.get(name)
            if h is None:
                self._claim(name, "histogram")
                h = self._histograms[name] = Histogram(name)
            return h

    # -- one-shot observations ------------------------------------------------

    def counter_inc(self, name: str, n: int = 1) -> None:
        with self._lock:
            c = self._counters.get(name)
            if c is None:
                self._claim(name, "counter")
                c = self._counters[name] = Counter(name)
            c.inc(n)

    def gauge_set(self, name: str, v: float) -> None:
        with self._lock:
            g = self._gauges.get(name)
            if g is None:
                self._claim(name, "gauge")
                g = self._gauges[name] = Gauge(name)
            g.set(v)

    def observe(self, name: str, v: float) -> None:
        with self._lock:
            h = self._histograms.get(name)
            if h is None:
                self._claim(name, "histogram")
                h = self._histograms[name] = Histogram(name)
            h.observe(v)

    def observe_aggregate(self, name: str, buckets: Dict[int, int],
                          count: int, total: float,
                          vmin: float, vmax: float) -> None:
        """Merge a locally-aggregated log2 distribution (buckets keyed
        by :func:`log2_bucket` exponent) into ``name`` in one lock
        acquisition — how deferred instruments (the kernel
        observatory's per-call wall accounting) publish without paying
        a registry round-trip per observation."""
        if count <= 0:
            return
        with self._lock:
            h = self._histograms.get(name)
            if h is None:
                self._claim(name, "histogram")
                h = self._histograms[name] = Histogram(name)
            for e, c in buckets.items():
                h.buckets[e] = h.buckets.get(e, 0) + c
            h.count += count
            h.sum += total
            if vmin < h.min:
                h.min = vmin
            if vmax > h.max:
                h.max = vmax

    # -- snapshots ------------------------------------------------------------

    def snapshot(self) -> dict:
        """A JSON-ready consistent copy: ``{"counters": {name: int},
        "gauges": {name: float}, "histograms": {name: {count, sum, min,
        max, buckets: {exponent: count}}}}`` — taken under the lock, so
        a scrape concurrent with writers never sees a torn histogram."""
        with self._lock:
            return {
                "counters": {k: c.value for k, c in self._counters.items()},
                "gauges": {k: g.value for k, g in self._gauges.items()},
                "histograms": {
                    k: {
                        "count": h.count,
                        "sum": h.sum,
                        "min": (None if h.count == 0 else h.min),
                        "max": (None if h.count == 0 else h.max),
                        "buckets": dict(h.buckets),
                    }
                    for k, h in self._histograms.items()
                },
            }

    def counters_snapshot(self) -> Dict[str, int]:
        with self._lock:
            return {k: c.value for k, c in self._counters.items()}

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._histograms.clear()


# -- the default (process-global) registry -----------------------------------

_DEFAULT: Optional[MetricsRegistry] = None
_DEFAULT_LOCK = threading.Lock()


def registry() -> MetricsRegistry:
    """The process-global registry every always-on instrument feeds and
    the ``/metrics`` exporter scrapes."""
    global _DEFAULT
    if _DEFAULT is None:
        with _DEFAULT_LOCK:
            if _DEFAULT is None:
                _DEFAULT = MetricsRegistry()
    return _DEFAULT
