"""Flight recorder — a bounded ring buffer of structured events.

Metrics answer "how much, how fast"; they cannot answer "what happened
just before this sync failed".  The flight recorder keeps the last N
structured events — sync phase transitions, digest collisions,
full-state fallbacks, ``SyncProtocolError``\\s, native-parse fallback
reasons, wire-loop stalls — stamped with BOTH clocks (``wall_ts`` for
display and fleet-merge ordering, ``mono_ts`` for skew-immune duration
math) and, where one
exists, the :class:`~crdt_tpu.sync.session.SyncSession` session ID, so
a failed session's whole trajectory can be read back from ``/events``
(or :func:`snapshot` in a debugger) after the fact.

Bounded by design: the buffer is a ``deque(maxlen=...)`` so a chatty
instrument can never grow memory — old events fall off the front and
the ``dropped`` count says how many did.  Appends are a deque push
under a lock (deque appends are O(1) and never resize), cheap enough
to leave always-on next to the counters.
"""

from __future__ import annotations

import itertools
import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional


class FlightRecorder:
    """The bounded event ring.  ``capacity`` is the number of retained
    events; the default keeps a few complete sync sessions' worth."""

    def __init__(self, capacity: int = 2048):
        if capacity < 1:
            raise ValueError(f"capacity {capacity} < 1")
        self.capacity = capacity
        self._buf: deque = deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._seq = 0
        self._recorded = 0

    def record(self, kind: str, session: Optional[str] = None,
               **fields) -> None:
        """Append one event.  ``kind`` is a dotted event family
        (``sync.phase``, ``wireloop.stall``); ``session`` threads a sync
        session ID through; ``fields`` is free-form JSON-ready detail.

        Two timestamps by design: ``wall_ts`` (``time.time()``) is for
        human display and the fleet-merge ordering key; ``mono_ts``
        (``time.monotonic()``) is for cross-event DURATION math
        (``regrow_timeline``, the latency profiler) — immune to
        wall-clock skew and NTP steps, and deliberately kept OUT of the
        fleet-merge key, since monotonic clocks from different
        processes share no epoch."""
        ev = {
            "seq": 0,  # patched under the lock
            "mono_ts": time.monotonic(),
            "wall_ts": time.time(),
            "kind": kind,
        }
        if session is not None:
            ev["session"] = session
        if fields:
            ev["fields"] = fields
        with self._lock:
            self._seq += 1
            self._recorded += 1
            ev["seq"] = self._seq
            self._buf.append(ev)

    @property
    def dropped(self) -> int:
        """Events evicted by the ring bound since the last :meth:`clear`.

        The exporter refreshes this into the ``obs.events.dropped``
        gauge (``crdt_tpu_obs_events_dropped``) at scrape time, so "the
        ring overflows faster than anyone reads it" is alertable, not
        just a Python property."""
        with self._lock:
            return self._recorded - len(self._buf)

    def snapshot(self, kind: Optional[str] = None,
                 session: Optional[str] = None) -> List[Dict]:
        """Retained events oldest-first, optionally filtered by ``kind``
        prefix (``kind="sync"`` matches ``sync.phase``) and/or exact
        ``session`` ID.  Returns copies — callers may mutate freely."""
        with self._lock:
            evs = list(self._buf)
        out = []
        for ev in evs:
            if kind is not None and not (
                ev["kind"] == kind or ev["kind"].startswith(kind + ".")
            ):
                continue
            if session is not None and ev.get("session") != session:
                continue
            out.append(dict(ev))
        return out

    def clear(self) -> None:
        with self._lock:
            self._buf.clear()
            self._recorded = 0


# -- the default (process-global) recorder -----------------------------------

_DEFAULT = FlightRecorder()


def recorder() -> FlightRecorder:
    return _DEFAULT


def record(kind: str, session: Optional[str] = None, **fields) -> None:
    """Append one event to the process-global flight recorder."""
    _DEFAULT.record(kind, session=session, **fields)


# -- session IDs -------------------------------------------------------------

_SESSION_SEQ = itertools.count(1)
# a per-process random component so two peer processes syncing the same
# fleet never mint the same ID (the whole point of threading session IDs
# through the recorder is telling their event streams apart)
_PROC_TAG = os.urandom(3).hex()


def new_session_id() -> str:
    """A short, process-unique session ID (``sync-<proc>-<n>``) for
    stamping one :class:`~crdt_tpu.sync.session.SyncSession`'s events."""
    return f"sync-{_PROC_TAG}-{next(_SESSION_SEQ):04x}"
