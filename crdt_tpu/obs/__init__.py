"""Observability — metrics registry, flight recorder, live export.

The reference crate has zero observability (SURVEY §5: no logging
crates, only ``Display`` impls); this package is the TPU port's
first-class answer, in five parts:

* :mod:`crdt_tpu.obs.metrics` — a typed registry (counters, gauges,
  log2-bucketed histograms) that every always-on instrument feeds; the
  legacy :mod:`crdt_tpu.utils.tracing` span/counter API re-routes into
  it, so existing call sites needed no churn.
* :mod:`crdt_tpu.obs.events` — a bounded ring-buffer flight recorder of
  structured events (sync phase transitions, digest collisions,
  full-state fallbacks, protocol errors, native-parse fallback reasons,
  wire-loop stalls), stamped with monotonic time and per-session IDs.
* :mod:`crdt_tpu.obs.export` — Prometheus text exposition + JSON
  snapshots, plus an opt-in stdlib-only HTTP thread serving
  ``/metrics``, ``/events``, ``/healthz``
  (``examples/replicate_tcp.py --metrics-port``).
* :mod:`crdt_tpu.obs.convergence` — per-peer digest-divergence gauges,
  rounds-to-converge, staleness age, and delta-ratio history, computed
  from the digest vectors the sync protocol already exchanges.
* :mod:`crdt_tpu.obs.fleet` — the cross-process plane: registry
  snapshots as a join-semilattice (counters G-Counter-merged per node,
  gauges LWW, histograms bucket-wise), CRC-guarded snapshot frames
  piggybacked on gossip sessions or all-gathered over a mesh, the
  ``/fleet`` aggregate, and the trace-ID timeline stitcher.
* :mod:`crdt_tpu.obs.latency` — the time plane: per-session
  critical-path profiles (serialize / network-wait / kernel, with the
  unaccounted residual as its own alertable series), Jacobson/Karels
  transport RTT estimation feeding adaptive retransmit timers, and
  write-to-visible replication lag per (origin, observer) pair with a
  convergence-SLO window.
* :mod:`crdt_tpu.obs.capacity` — the memory plane: dense-plane
  occupancy samples (jitted kernels in
  :mod:`crdt_tpu.batch.occupancy`) turned into ``crdt_tpu_capacity_*``
  gauges, EWMA growth rates, time-to-overflow ETAs against the
  executor's regrow ceiling, and the ok/warn/critical watermark
  ``/healthz`` reports.
* :mod:`crdt_tpu.obs.stability` — the agreement plane: divergence
  aging (birth→resolution tracking of diverged digest subtrees), the
  fleet stability frontier (the per-subtree clock below which every
  non-quarantined peer has provably converged — what coordinated
  truncation will consume, min-joined across the fleet lattice and
  served at ``/stability``), and the runtime lattice auditor (sampled
  merge-idempotence + frontier-soundness self-checks, the online
  tripwire for the whole lattice stack).
* :mod:`crdt_tpu.obs.heat` — the placement plane: per-subtree traffic
  attribution (read/write/repair heat folded by jitted scatter-add
  kernels onto the PR 15 ``subtree_layout``), an on-device
  Space-Saving top-k sketch with a Zipf-exponent estimator, and the
  shard/ring placement planner behind ``GET /heat`` — the measurement
  half of the mesh-sharding and partial-replication items.
* :mod:`crdt_tpu.obs.kernels` — the kernel plane: the runtime kernel
  observatory (dynamic companion to kernelcheck, keyed on the SAME
  :data:`crdt_tpu.analysis.kernels.MANIFEST` rows) — per-kernel
  compile/recompile tracking with ladder-vs-shape-churn
  classification, always-cheap wall histograms, lazy XLA
  ``cost_analysis`` capture, device-memory gauges, and the
  ``/kernels`` table.

Import-light by design: nothing here imports JAX or numpy, so the
scalar engine (and any process that only wants a counter) pays nothing
for it.  PERF.md "Observability" documents naming conventions and how
to read the flight recorder after a failed sync.
"""

from . import (  # noqa: F401
    capacity,
    convergence,
    events,
    fleet,
    heat,
    kernels,
    latency,
    metrics,
    stability,
)
from .capacity import CapacityTracker, Occupancy, capacity_tracker  # noqa: F401
from .convergence import ConvergenceTracker, tracker  # noqa: F401
from .events import FlightRecorder, new_session_id, record, recorder  # noqa: F401
from .fleet import (  # noqa: F401
    FleetObservatory,
    FleetSnapshot,
    observatory,
    stitch_trace,
)
from .heat import HeatTracker, heat_tracker  # noqa: F401
from .kernels import (  # noqa: F401
    KernelObservatory,
    KernelProfile,
    kernel_observatory,
    observed_kernel,
    sample_device_memory,
    storm_report,
)
from .latency import (  # noqa: F401
    LagTracker,
    RttEstimator,
    SessionProfile,
    lag_tracker,
)
from .metrics import (  # noqa: F401
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    registry,
)
from .stability import (  # noqa: F401
    AuditReport,
    FrontierReport,
    StabilityTracker,
    stability_tracker,
)

__all__ = [
    "AuditReport",
    "CapacityTracker",
    "ConvergenceTracker",
    "Counter",
    "FrontierReport",
    "HeatTracker",
    "heat_tracker",
    "StabilityTracker",
    "stability_tracker",
    "FleetObservatory",
    "FleetSnapshot",
    "FlightRecorder",
    "Gauge",
    "Histogram",
    "KernelObservatory",
    "KernelProfile",
    "LagTracker",
    "MetricsRegistry",
    "Occupancy",
    "RttEstimator",
    "SessionProfile",
    "capacity_tracker",
    "kernel_observatory",
    "lag_tracker",
    "observed_kernel",
    "sample_device_memory",
    "storm_report",
    "new_session_id",
    "observatory",
    "record",
    "recorder",
    "registry",
    "stitch_trace",
    "tracker",
]


def start_metrics_server(port: int = 0, host: str = "127.0.0.1"):
    """Start the background ``/metrics`` HTTP exporter (lazy import so
    merely importing :mod:`crdt_tpu.obs` never touches http.server)."""
    from .export import start_metrics_server as _start

    return _start(port=port, host=host)
