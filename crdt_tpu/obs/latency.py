"""Latency observatory — where the time goes, and how stale a read is.

The stack can survive kill -9 (durable/) and sync in O(log N) bytes
(sync/tree), but until this module it could not answer the first two
questions a serving fleet gets asked: *how stale is a read from this
replica*, and *which leg of a sync session actually costs the wall
time*.  Three measurement planes, all host-side and stdlib/numpy-free
unless noted:

* :class:`SessionProfile` — the critical path of ONE sync session,
  accounted in integer nanoseconds.  :class:`~crdt_tpu.sync.session.
  SyncSession` stamps a monotonic clock around every frame send/recv
  (``network``), every encode/decode (``serialize``), every digest/
  tree/delta-apply kernel call (``kernel``) and the piggyback
  bookkeeping (``other``); the residual the stamps missed is
  ``unaccounted`` — which is itself published (if the profiler loses
  track of time, that is a finding, not a rounding error).  The
  identity ``serialize + network + kernel + other + unaccounted ==
  wall`` holds to the nanosecond by construction and is pinned in
  ``tests/test_latency.py``.

* :class:`RttEstimator` — Jacobson/Karels SRTT/RTTVAR (SIGCOMM '88)
  over the ack round-trips :class:`~crdt_tpu.cluster.transport.
  ResilientTransport` already performs (it round-trips every DATA
  frame; before this module it threw the timing away).  Karn's rule:
  retransmitted frames never contribute samples.  The estimator feeds
  the transport's adaptive retransmit timer (``srtt + 4·rttvar``,
  clamped to the RetryPolicy bounds) and the per-link
  ``cluster.transport.<link>.rtt_*`` gauges.

* :class:`LagTracker` — write-to-visible replication lag per
  ``(origin, observer)`` pair.  The origin node stamps every ingested
  op dot ``(actor, counter)`` with a monotonic nanosecond clock
  (:meth:`LagTracker.record_ingest_batch` — bounded: newest
  :data:`STAMPS_PER_ACTOR` dots per actor, :data:`MAX_ACTORS` actors);
  the stamps ride sync sessions as a hello-negotiated LAG sidecar
  frame (:data:`crdt_tpu.sync.delta.FRAME_LAG` — the 23 B/op op-frame
  wire format is untouched).  The observer measures an entry the
  moment its dot becomes visible in the local clock plane — at the
  session's digest-convergence check, and again after every op-log
  fold (:meth:`observe_visibility`) — and publishes
  ``sync.peer.<peer>.lag_{p50_s,p99_s,outstanding,current_s}``.  Monotonic
  clocks are only comparable within one clock domain, so the sidecar
  carries the origin's process tag: a cross-process entry degrades
  loudly (``sync.lag.fallback.clock_domain``) instead of publishing a
  garbage number, exactly like every other capability mismatch.

The convergence SLO rides along: :meth:`LagTracker.observe_round`
keeps a bounded window of gossip-round outcomes and publishes
``sync.slo.converged_frac`` — the fraction of recent rounds that
converged within the target budget.

PERF.md "Latency & lag" documents the metric table and how to read a
:class:`SessionProfile`.
"""

from __future__ import annotations

import contextlib
import threading
import time
from collections import deque
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

from . import metrics as metrics_mod

#: newest ingest stamps retained per origin actor (the sidecar is
#: bounded by construction: MAX_ACTORS * STAMPS_PER_ACTOR entries)
STAMPS_PER_ACTOR = 8
#: distinct origin actors the stamp table tracks
MAX_ACTORS = 512
#: measured write-to-visible samples retained per peer
LAG_WINDOW = 512
#: gossip-round outcomes the SLO window retains
SLO_WINDOW = 128
#: default convergence-SLO budget: a round "meets SLO" when it
#: converged and finished within this many seconds
SLO_BUDGET_S = 1.0


# ---- session critical-path profile ------------------------------------------

#: the accounted categories, in report order
PROFILE_CATEGORIES = ("serialize", "network", "kernel", "other")


class SessionProfile:
    """Integer-nanosecond accounting of one sync session's wall time.

    Used single-threaded by the session that owns it (the lock-step
    protocol drives one leg at a time), so there is no lock.  Stamping
    is leaf-only by convention — :meth:`clock` regions must not nest
    (nesting would double-charge the overlap and break the accounting
    identity; the session instruments leaf call sites only).
    """

    __slots__ = ("wall_ns", "serialize_ns", "network_ns", "kernel_ns",
                 "other_ns", "frames_sent", "frames_received", "_t0",
                 "_depth")

    def __init__(self):
        self.wall_ns = 0
        self.serialize_ns = 0
        self.network_ns = 0
        self.kernel_ns = 0
        self.other_ns = 0
        self.frames_sent = 0
        self.frames_received = 0
        self._t0: Optional[int] = None
        self._depth = 0

    # -- stamping ------------------------------------------------------------

    def start(self) -> None:
        self._t0 = time.monotonic_ns()

    def add(self, category: str, ns: int) -> None:
        setattr(self, f"{category}_ns",
                getattr(self, f"{category}_ns") + int(ns))

    @contextlib.contextmanager
    def clock(self, category: str) -> Iterator[None]:
        """Charge the region's wall time to ``category``.  Nested
        regions charge only the innermost category for the overlap
        (the outer region's stamp still covers its exclusive tail), so
        a mis-nested call site degrades to slight over-counting of the
        inner category — never to time counted twice."""
        t0 = time.monotonic_ns()
        self._depth += 1
        try:
            yield
        finally:
            self._depth -= 1
            if self._depth == 0:
                self.add(category, time.monotonic_ns() - t0)

    def finish(self) -> None:
        """Close the profile: the wall clock stops here.  Idempotent —
        the last call wins (the session finalizes once, in ``sync``)."""
        if self._t0 is not None:
            self.wall_ns = time.monotonic_ns() - self._t0

    # -- derived views -------------------------------------------------------

    @property
    def accounted_ns(self) -> int:
        return (self.serialize_ns + self.network_ns + self.kernel_ns
                + self.other_ns)

    @property
    def unaccounted_ns(self) -> int:
        """The residual the stamps missed — by construction the
        accounting identity ``accounted + unaccounted == wall`` holds
        to the nanosecond.  Large values mean the profiler lost track
        of a phase; the session publishes this as its own histogram so
        that is alertable."""
        return self.wall_ns - self.accounted_ns

    @property
    def network_wait_frac(self) -> float:
        """Fraction of the session wall spent blocked on the wire —
        the number the gossip scheduler and the windowed-ARQ bench
        read: ~1.0 means the protocol is RTT-bound (pipelining wins),
        ~0.0 means it is compute/serialize-bound (pipelining won't)."""
        return self.network_ns / self.wall_ns if self.wall_ns else 0.0

    def to_dict(self) -> dict:
        return {
            "wall_ns": self.wall_ns,
            "serialize_ns": self.serialize_ns,
            "network_ns": self.network_ns,
            "kernel_ns": self.kernel_ns,
            "other_ns": self.other_ns,
            "unaccounted_ns": self.unaccounted_ns,
            "network_wait_frac": round(self.network_wait_frac, 6),
            "frames_sent": self.frames_sent,
            "frames_received": self.frames_received,
        }

    def __repr__(self) -> str:  # the demo prints these
        ms = 1e6
        return (
            f"SessionProfile(wall={self.wall_ns / ms:.2f}ms "
            f"serialize={self.serialize_ns / ms:.2f} "
            f"network={self.network_ns / ms:.2f} "
            f"kernel={self.kernel_ns / ms:.2f} "
            f"other={self.other_ns / ms:.2f} "
            f"unaccounted={self.unaccounted_ns / ms:.2f})"
        )


# ---- Jacobson/Karels RTT estimation -----------------------------------------


class RttEstimator:
    """SRTT/RTTVAR per Jacobson/Karels (SIGCOMM '88, RFC 6298 shape).

    First sample seeds ``srtt = s``, ``rttvar = s/2``; thereafter
    ``rttvar = (1-β)·rttvar + β·|srtt - s|`` then
    ``srtt = (1-α)·srtt + α·s`` with the classic gains α=1/8, β=1/4.
    :meth:`rto` is the retransmit timer ``srtt + 4·rttvar`` clamped
    into the caller's bounds — the caller supplies them so the policy
    (RetryPolicy) stays the single source of truth for limits.

    Thread-safe via one small lock: the transport's send path and a
    scraper may race.
    """

    __slots__ = ("alpha", "beta", "srtt_s", "rttvar_s", "samples",
                 "last_sample_s", "_lock")

    def __init__(self, alpha: float = 1.0 / 8, beta: float = 1.0 / 4):
        self.alpha = float(alpha)
        self.beta = float(beta)
        self.srtt_s: Optional[float] = None
        self.rttvar_s: Optional[float] = None
        self.samples = 0
        self.last_sample_s: Optional[float] = None
        self._lock = threading.Lock()

    def observe(self, sample_s: float) -> None:
        """Fold one round-trip sample in.  Callers apply Karn's rule
        (never sample a retransmitted frame) — the estimator cannot
        tell a first ack from a late one."""
        s = float(sample_s)
        if s < 0.0:
            return  # a clock that stepped backwards is not a sample
        with self._lock:
            if self.srtt_s is None:
                self.srtt_s = s
                self.rttvar_s = s / 2.0
            else:
                self.rttvar_s = ((1.0 - self.beta) * self.rttvar_s
                                 + self.beta * abs(self.srtt_s - s))
                self.srtt_s = (1.0 - self.alpha) * self.srtt_s + self.alpha * s
            self.samples += 1
            self.last_sample_s = s

    def rto(self, floor_s: float, cap_s: float,
            default_s: Optional[float] = None) -> Optional[float]:
        """The adaptive retransmit timer ``srtt + 4·rttvar`` clamped to
        ``[floor_s, cap_s]``; ``default_s`` (clamped too) before the
        first sample, or None when no default is given."""
        with self._lock:
            raw = (None if self.srtt_s is None
                   else self.srtt_s + 4.0 * self.rttvar_s)
        if raw is None:
            if default_s is None:
                return None
            raw = default_s
        return min(max(raw, float(floor_s)), float(cap_s))

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "srtt_s": self.srtt_s,
                "rttvar_s": self.rttvar_s,
                "samples": self.samples,
                "last_sample_s": self.last_sample_s,
            }


# ---- write-to-visible lag ---------------------------------------------------


def _percentile(sorted_samples: Sequence[float], q: float) -> float:
    """Nearest-rank percentile of an ascending-sorted sequence."""
    if not sorted_samples:
        return 0.0
    idx = min(len(sorted_samples) - 1,
              max(0, int(round(q * (len(sorted_samples) - 1)))))
    return float(sorted_samples[idx])


class _PeerLag:
    """One origin peer's lag state at this observer."""

    __slots__ = ("samples", "pending", "measured_frontier")

    def __init__(self):
        # measured write-to-visible seconds, bounded window
        self.samples: deque = deque(maxlen=LAG_WINDOW)
        # not-yet-visible sidecar entries: {actor: [(counter, mono_ns)]}
        self.pending: Dict[int, List[Tuple[int, int]]] = {}
        # highest counter already measured (or discarded) per actor —
        # re-delivered sidecar entries must not re-measure
        self.measured_frontier: Dict[int, int] = {}


class LagTracker:
    """Origin-timestamp table + per-peer write-to-visible lag gauges.

    One instance per replica (``ClusterNode`` owns one); the registry
    defaults to the process-global one so in-process fleets share a
    scrape surface, with peer labels keeping the pairs apart.
    ``proc_tag`` names this node's monotonic clock domain — entries
    from another domain are counted and dropped, never compared.
    """

    def __init__(self, registry: Optional[metrics_mod.MetricsRegistry]
                 = None, *,
                 proc_tag: Optional[str] = None,
                 slo_budget_s: float = SLO_BUDGET_S,
                 per_actor: int = STAMPS_PER_ACTOR,
                 max_actors: int = MAX_ACTORS):
        from . import events as events_mod

        self._registry = registry
        self.proc_tag = proc_tag if proc_tag is not None \
            else events_mod._PROC_TAG
        self.slo_budget_s = float(slo_budget_s)
        self.per_actor = int(per_actor)
        self.max_actors = int(max_actors)
        self._lock = threading.Lock()
        # origin side: {actor: deque[(counter, mono_ns)]}
        self._stamps: Dict[int, deque] = {}
        # observer side
        self._peers: Dict[str, _PeerLag] = {}
        self._slo: deque = deque(maxlen=SLO_WINDOW)

    def _reg(self) -> metrics_mod.MetricsRegistry:
        return self._registry if self._registry is not None \
            else metrics_mod.registry()

    # -- origin side: stamp ingested writes ----------------------------------

    def record_ingest(self, actor: int, counter: int,
                      mono_ns: Optional[int] = None) -> None:
        """Stamp one ingested dot ``(actor, counter)`` with the origin
        monotonic clock.  Bounded: newest ``per_actor`` dots per actor,
        ``max_actors`` actors (beyond that, new actors are dropped —
        lag measurement degrades, ingest never blocks)."""
        now = time.monotonic_ns() if mono_ns is None else int(mono_ns)
        with self._lock:
            dq = self._stamps.get(int(actor))
            if dq is None:
                if len(self._stamps) >= self.max_actors:
                    return
                dq = self._stamps[int(actor)] = deque(maxlen=self.per_actor)
            dq.append((int(counter), now))

    def record_ingest_batch(self, ops) -> None:
        """Stamp the dot frontier of one :class:`~crdt_tpu.oplog.
        records.OpBatch`: per dotted actor, the batch's highest counter
        (one stamp per actor per batch keeps the table — and the
        sidecar — bounded by actors, not by write rate)."""
        if ops is None or len(ops) == 0:
            return
        now = time.monotonic_ns()
        frontier: Dict[int, int] = {}
        for actor, counter in zip(ops.actor.tolist(), ops.counter.tolist()):
            a, c = int(actor), int(counter)
            if frontier.get(a, -1) < c:
                frontier[a] = c
        for a, c in frontier.items():
            self.record_ingest(a, c, mono_ns=now)

    def export_entries(self) -> List[Tuple[int, int, int]]:
        """The sidecar payload: every retained ``(actor, counter,
        origin_mono_ns)`` stamp, actor-major, counter-ascending."""
        with self._lock:
            out = []
            for actor in sorted(self._stamps):
                out.extend((actor, c, t) for c, t in self._stamps[actor])
        return out

    # -- observer side: sidecar in, visibility measured ----------------------

    def ingest_sidecar(self, peer: str,
                       entries: Sequence[Tuple[int, int, int]],
                       origin_proc: str) -> int:
        """Fold a peer's sidecar entries into the pending set; returns
        how many were accepted.  Entries from another monotonic clock
        domain are dropped loudly (``sync.lag.fallback.clock_domain``)
        — a cross-process monotonic diff is not a latency, and a
        degraded gauge beats a lying one.  Own echoes (the peer
        re-shipping OUR stamps once transitive sidecars exist) and
        already-measured counters are skipped silently."""
        from ..utils import tracing

        if origin_proc != self.proc_tag:
            tracing.count("sync.lag.fallback.clock_domain")
            return 0
        accepted = 0
        with self._lock:
            st = self._peers.get(peer)
            if st is None:
                st = self._peers[peer] = _PeerLag()
            for actor, counter, mono_ns in entries:
                actor, counter = int(actor), int(counter)
                if counter <= st.measured_frontier.get(actor, -1):
                    continue
                bucket = st.pending.setdefault(actor, [])
                if any(c == counter for c, _ in bucket):
                    continue
                bucket.append((counter, int(mono_ns)))
                accepted += 1
        return accepted

    def observe_visibility(self, visible, peer: Optional[str] = None
                           ) -> int:
        """Measure every pending entry whose dot the local planes now
        witness: ``visible`` maps actor → highest visible counter (any
        indexable — the per-actor max of the batch clock plane).  Runs
        at the session's converged check and after every op-log fold
        (the two moments visibility advances).  Returns the number of
        new samples; refreshes the per-peer gauges either way."""
        from ..utils import tracing

        measured = 0
        now = time.monotonic_ns()
        with self._lock:
            peers = ([peer] if peer is not None else list(self._peers))
            for name in peers:
                st = self._peers.get(name)
                if st is None:
                    continue
                for actor in list(st.pending):
                    try:
                        vis = int(visible[actor])
                    except (IndexError, KeyError, TypeError):
                        continue
                    keep = []
                    for counter, mono_ns in st.pending[actor]:
                        if counter <= vis:
                            st.samples.append(
                                max(0, now - mono_ns) / 1e9)
                            st.measured_frontier[actor] = max(
                                st.measured_frontier.get(actor, -1),
                                counter)
                            measured += 1
                        else:
                            keep.append((counter, mono_ns))
                    if keep:
                        st.pending[actor] = keep
                    else:
                        del st.pending[actor]
        if measured:
            tracing.count("sync.lag.samples", measured)
        self.refresh()
        return measured

    # -- gauges ---------------------------------------------------------------

    def refresh(self) -> None:
        """Recompute the per-peer lag gauges: p50/p99 over the sample
        window, the outstanding (shipped-but-not-yet-visible) entry
        count, and ``current_s`` — the age of the OLDEST outstanding
        entry (0 when everything shipped is visible: the quiescent
        fleet reads zero, which is the acceptance pin)."""
        now = time.monotonic_ns()
        with self._lock:
            views = []
            for name, st in self._peers.items():
                samples = sorted(st.samples)
                outstanding = sum(len(v) for v in st.pending.values())
                oldest = min(
                    (t for v in st.pending.values() for _, t in v),
                    default=None)
                views.append((name, samples, outstanding, oldest))
        reg = self._reg()
        for name, samples, outstanding, oldest in views:
            reg.gauge_set(f"sync.peer.{name}.lag_p50_s",
                          _percentile(samples, 0.50))
            reg.gauge_set(f"sync.peer.{name}.lag_p99_s",
                          _percentile(samples, 0.99))
            reg.gauge_set(f"sync.peer.{name}.lag_outstanding", outstanding)
            reg.gauge_set(
                f"sync.peer.{name}.lag_current_s",
                0.0 if oldest is None else max(0, now - oldest) / 1e9)

    # -- the convergence SLO ---------------------------------------------------

    def observe_round(self, converged: bool, wall_s: float) -> float:
        """Record one gossip round's outcome; returns (and publishes as
        ``sync.slo.converged_frac``) the fraction of the recent window
        that converged within the SLO budget."""
        ok = bool(converged) and float(wall_s) <= self.slo_budget_s
        with self._lock:
            self._slo.append(ok)
            frac = sum(self._slo) / len(self._slo)
        self._reg().gauge_set("sync.slo.converged_frac", frac)
        return frac

    # -- snapshots -------------------------------------------------------------

    def snapshot(self) -> dict:
        """JSON-ready per-peer lag state (what the demo prints)."""
        with self._lock:
            out = {}
            for name, st in self._peers.items():
                samples = sorted(st.samples)
                out[name] = {
                    "samples": len(st.samples),
                    "p50_s": _percentile(samples, 0.50),
                    "p99_s": _percentile(samples, 0.99),
                    "outstanding": sum(
                        len(v) for v in st.pending.values()),
                }
            return {
                "peers": out,
                "stamped_actors": len(self._stamps),
                "slo_window": len(self._slo),
                "slo_converged_frac": (
                    sum(self._slo) / len(self._slo) if self._slo else None),
            }

    def reset(self) -> None:
        with self._lock:
            self._stamps.clear()
            self._peers.clear()
            self._slo.clear()


# -- the default (process-global) tracker -------------------------------------

_DEFAULT: Optional[LagTracker] = None
_DEFAULT_LOCK = threading.Lock()


def lag_tracker() -> LagTracker:
    """The process-global lag tracker — what scheduler-less deployments
    and the examples stamp into by default (cluster nodes own private
    ones so multi-node in-process fleets keep their pairs apart)."""
    global _DEFAULT
    if _DEFAULT is None:
        with _DEFAULT_LOCK:
            if _DEFAULT is None:
                _DEFAULT = LagTracker()
    return _DEFAULT
