"""Heat & placement observatory — per-subtree traffic attribution,
on-device top-k/Zipf sketches, and a shard/ring placement planner.

Both remaining ROADMAP tentpoles — mesh-sharded fleets and partial
replication — are *placement decisions over the object axis*, and the
reference's own heritage (ported from Basho's ``riak_dt``, `lib.rs:1-2`;
Riak places objects on a consistent-hash ring with replication factor
k << N) says the hard part is balancing k-owner load under skew.
Nothing before this module measured *where* traffic lands: PR 17's
serve path and the oplog write path count volume, not per-object heat.
In the observatory-before-subsystem pattern of PRs 9/13/14/15, three
measurement planes land the numbers first:

* **Per-subtree heat accumulation** — every serve gather batch (read
  heat, split by consistency mode), every oplog fold batch (write
  heat), and every sync delta row-set (repair heat: which objects
  churn over the wire) folds through one jitted scatter-add kernel
  into per-subtree counters aligned to the PR 15
  :func:`~crdt_tpu.obs.stability.subtree_layout` — the digest tree's
  top-children ranges, i.e. the shard sync unit the mesh and
  partial-replication items will shard on.  Lifetime totals publish as
  ``heat.subtree.<i>.{reads,writes,repair}`` counters (they ride the
  PR 6 fleet lattice's G-Counter read, so ``/fleet`` sums them across
  nodes); half-life-decayed EWMA windows publish as
  ``heat.subtree.<i>.{reads,writes,repair}_per_s`` gauges.

* **Hot-object identification** — a batched Space-Saving top-k sketch
  updated entirely on device (:func:`_sketch_kernel`: in-batch
  aggregation by sort + segment-sum, matched entries scatter-add,
  unmatched candidates enter at ``total + table_min`` with their
  per-entry overestimate recorded in an error column, one
  ``lax.top_k`` keeps the table).  Decoded counts are OVERestimates by
  at most each entry's ``err``; ``count - err`` is the classic
  guaranteed lower bound, and that is what the Zipf rank-frequency fit
  (:func:`zipf_fit`) consumes so tail churn does not flatten the
  estimated exponent.  The fitted ``heat.zipf.s_hat`` is checkable
  against :class:`~crdt_tpu.utils.workload.WorkloadGen`'s ``zipf_s``
  ground truth.  Sketches are join-semilattices (same-object counts
  SUM across nodes, :func:`merge_hot`), so per-node top-k gauges merge
  into a fleet-wide hot list on ``/fleet``.

* **Placement planner** — :func:`score_plan` prices hypothetical
  placements against measured heat at subtree granularity: ``mesh:S``
  scores S-way contiguous object-range shardings (per-shard load,
  ``imbalance = max/mean`` — the ``shard_map`` balance bill), and
  ``ring:N,k=K`` scores hash-ring k-owner layouts (per-owner load
  ``skew`` plus ``movement_frac``: the heat-weighted fraction of
  replica assignments that differ from the same ring before its newest
  owner joined — the consistent-hash stability bill, ~1/N for a sane
  ring vs ~1 for mod-N).  Served at ``GET /heat`` (``?format=json``,
  ``?plan=mesh:8``, ``?plan=ring:5,k=3``).

Cluster nodes own private trackers (same discipline as the lag and
stability observers) so in-process fleets keep their attribution
apart; standalone serve loops and sync sessions fall back to the
process-global :func:`tracker`.  All registry writes go through an
injectable :class:`~crdt_tpu.obs.metrics.MetricsRegistry` so fleet
tests can capture genuinely per-node slices.
"""

from __future__ import annotations

import functools
import hashlib
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

from . import metrics as metrics_mod

#: traffic classes, in publication order
CLASSES = ("reads", "writes", "repair")

#: Space-Saving table width — error bound is ~(untracked mass / capacity)
DEFAULT_CAPACITY = 128

#: EWMA half-life for the *_per_s gauges
DEFAULT_HALFLIFE_S = 30.0

#: top-k ranks exported as heat.hot.<rank>.{obj,count} gauges
HOT_GAUGE_RANKS = 8

#: decoded ranks offered to the Zipf rank-frequency fit
ZIPF_FIT_RANKS = 32

#: minimum positive ranks before a fit is attempted
MIN_FIT_RANKS = 6

#: update batches pad to pow2 with this floor (same ladder discipline
#: as the serve gathers, so the jit cache stays a short rung list)
PAD_FLOOR = 8

#: virtual points per owner on the scored hash ring
RING_VNODES = 64


def _host_int():
    """host id/weight dtype matching the jit default (int64 under x64,
    int32 otherwise) so trace-ladder dtypes and runtime dtypes agree."""
    import numpy as np
    from ..config import enable_x64
    return np.int64 if enable_x64() else np.int32


def _pad_pow2(ids: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """pad ids to a pow2 batch (floor 8); padding rows carry weight 0
    so the kernels never count them."""
    import numpy as np
    b = max(PAD_FLOOR, 1 << max(0, int(ids.size) - 1).bit_length())
    out = np.zeros(b, dtype=ids.dtype)
    out[:ids.size] = ids
    w = np.zeros(b, dtype=ids.dtype)
    w[:ids.size] = 1
    return out, w


# -- jitted heat kernels -------------------------------------------------------


@functools.lru_cache(maxsize=None)
def _fold_kernel(subtrees: int, span: int):
    """ids → per-subtree scatter-add (``segment = id // span``), the
    attribution half of every record call.  Integer lattice: the fold
    is order-free, so batches may arrive in any interleaving."""
    import jax
    import jax.numpy as jnp
    from .kernels import observed_kernel

    def kernel(ids, weights):
        sub = jnp.clip(ids // span, 0, subtrees - 1)
        return jnp.zeros((subtrees,), weights.dtype).at[sub].add(weights)

    return observed_kernel("obs.heat.subtree_fold")(jax.jit(kernel))


@functools.lru_cache(maxsize=None)
def _sketch_kernel(capacity: int):
    """One batched Space-Saving update, entirely on device.

    In-batch duplicates aggregate first (sort by id, change-flag
    cumsum segment ids, segment-sum), matched table entries scatter-add
    their group totals, unmatched groups become candidates entering at
    ``total + min(table)`` with that floor recorded as their ``err``
    (the per-entry overestimate Space-Saving guarantees), and one
    ``top_k`` over the ``capacity + batch`` pool keeps the table.
    Padding rows (weight 0) are never live, and candidate count ``-1``
    rows can never displace the table's always-``>= 0`` entries."""
    import jax
    import jax.numpy as jnp
    from .kernels import observed_kernel

    def kernel(tab_ids, tab_counts, tab_errs, ids, weights):
        b = ids.shape[0]
        order = jnp.argsort(ids)
        sid = ids[order]
        sw = weights[order]
        starts = jnp.concatenate(
            [jnp.ones((1,), jnp.bool_), sid[1:] != sid[:-1]])
        seg = jnp.cumsum(starts) - 1
        totals = jax.ops.segment_sum(sw, seg, num_segments=b)
        first = jax.ops.segment_min(jnp.arange(b), seg, num_segments=b)
        gid = sid[jnp.clip(first, 0, b - 1)]
        live = totals > 0
        hit = (tab_ids[:, None] == gid[None, :]) & live[None, :]
        grown = tab_counts + jnp.sum(
            jnp.where(hit, totals[None, :], 0), axis=1)
        floor = jnp.min(grown)
        fresh = live & ~jnp.any(hit, axis=0)
        cand_counts = jnp.where(fresh, totals + floor, -1)
        cand_errs = jnp.where(fresh, floor, 0)
        top, idx = jax.lax.top_k(
            jnp.concatenate([grown, cand_counts]), capacity)
        all_ids = jnp.concatenate([tab_ids, gid])
        all_errs = jnp.concatenate([tab_errs, cand_errs])
        return all_ids[idx], jnp.maximum(top, 0), all_errs[idx]

    return observed_kernel("obs.heat.sketch_update")(jax.jit(kernel))


# -- Zipf rank-frequency fit ---------------------------------------------------


def zipf_fit(counts: Sequence[float]) -> Tuple[Optional[float],
                                               Optional[float]]:
    """Least-squares fit of ``log(count)`` vs ``log(rank)`` over the
    positive counts (sorted descending, rank 1-based): a Zipf(s) law
    is a line of slope ``-s``.  Returns ``(s_hat, r2)``, or
    ``(None, None)`` below :data:`MIN_FIT_RANKS` usable ranks."""
    import numpy as np
    c = np.asarray([v for v in counts if v > 0], dtype=np.float64)
    if c.size < MIN_FIT_RANKS:
        return None, None
    c = np.sort(c)[::-1]
    x = np.log(np.arange(1, c.size + 1, dtype=np.float64))
    y = np.log(c)
    slope, intercept = np.polyfit(x, y, 1)
    fitted = slope * x + intercept
    ss_res = float(np.sum((y - fitted) ** 2))
    ss_tot = float(np.sum((y - np.mean(y)) ** 2))
    r2 = 1.0 if ss_tot <= 0 else 1.0 - ss_res / ss_tot
    return float(-slope), float(r2)


def merge_hot(hot_lists: Sequence[Sequence[dict]]) -> List[dict]:
    """Join decoded per-node sketches host-side: counts (and error
    bounds) for the same object SUM — the sketch's semilattice join —
    then re-rank.  Input rows are :meth:`HeatTracker.snapshot`'s
    ``hot`` entries (``{"obj", "count", "err"}``)."""
    acc: Dict[int, int] = {}
    err: Dict[int, int] = {}
    for hot in hot_lists:
        for h in hot:
            obj = int(h["obj"])
            acc[obj] = acc.get(obj, 0) + int(h["count"])
            err[obj] = err.get(obj, 0) + int(h.get("err", 0))
    ranked = sorted(acc.items(), key=lambda kv: (-kv[1], kv[0]))
    return [{"obj": o, "count": c, "err": err[o]} for o, c in ranked]


# -- the placement planner -----------------------------------------------------


def parse_plan(spec: str) -> Tuple[str, Dict[str, int]]:
    """``"mesh:8"`` → ``("mesh", {"shards": 8})``;
    ``"ring:5,k=3"`` → ``("ring", {"owners": 5, "k": 3})``.
    ValueError on anything else (the ``/heat`` route surfaces it)."""
    kind, sep, rest = spec.partition(":")
    kind = kind.strip().lower()
    try:
        if kind == "mesh" and sep:
            shards = int(rest.strip())
            if shards < 1:
                raise ValueError
            return "mesh", {"shards": shards}
        if kind == "ring" and sep:
            head, _, tail = rest.partition(",")
            owners = int(head.strip())
            k = 2
            if tail:
                kk, _, kv = tail.partition("=")
                if kk.strip() != "k":
                    raise ValueError
                k = int(kv.strip())
            if owners < 1 or k < 1:
                raise ValueError
            return "ring", {"owners": owners, "k": k}
    except ValueError:
        pass
    raise ValueError(
        "bad plan spec %r (want mesh:<shards> or ring:<owners>[,k=<k>])"
        % (spec,))


def _ring_hash(key: str) -> int:
    # stable across processes (python's hash() is salted per run)
    return int.from_bytes(
        hashlib.blake2b(key.encode(), digest_size=8).digest(), "big")


def _ring_owners(names: Sequence[str], subtrees: int,
                 k: int) -> List[Tuple[str, ...]]:
    """subtree → k-owner preference list on a blake2b ring with
    :data:`RING_VNODES` virtual points per owner (distinct successor
    owners clockwise from the subtree's point — Riak's preference
    list, at subtree granularity)."""
    points = sorted(
        (_ring_hash("%s#%d" % (name, v)), name)
        for name in names for v in range(RING_VNODES))
    hashes = [p[0] for p in points]
    owners: List[Tuple[str, ...]] = []
    import bisect
    for s in range(subtrees):
        at = bisect.bisect_right(hashes, _ring_hash("subtree-%d" % s))
        chosen: List[str] = []
        for off in range(len(points)):
            name = points[(at + off) % len(points)][1]
            if name not in chosen:
                chosen.append(name)
                if len(chosen) == k:
                    break
        owners.append(tuple(chosen))
    return owners


def _imbalance(loads: np.ndarray) -> float:
    import numpy as np
    mean = float(np.mean(loads))
    return 1.0 if mean <= 0 else float(np.max(loads)) / mean


def mesh_bounds(n: int, shards: int, granule: int | None = None) -> list:
    """Logical shard boundaries for ``mesh:<shards>`` over ``n``
    objects — the ONE formula the planner scores and
    :func:`crdt_tpu.mesh.state.choose_layout` instantiates, so a scored
    layout is always a buildable one.

    Without a granule: the historical even split.  With one (a
    positive power of two — the pow2 subtree spans ``subtree_layout``
    hands out), every shard owns ``ceil(ceil(n/shards)/granule) *
    granule`` padded rows and the logical boundaries are the padded
    ones clipped to ``n`` — subtree-aligned by construction."""
    if granule is None:
        return [int(round(s * n / shards)) for s in range(shards + 1)]
    g = int(granule)
    if g < 1 or (g & (g - 1)) != 0:
        raise ValueError(
            f"granule {granule!r} must be a positive power of two "
            "(a subtree span)")
    rows = -(-int(n) // int(shards))      # ceil(n / shards)
    per = -(-rows // g) * g               # snapped up to the granule
    return [min(s * per, int(n)) for s in range(int(shards) + 1)]


def score_plan(spec: str, heat: np.ndarray, *, n: int,
               span: int, granule: int | None = None) -> dict:
    """Score one placement spec against a measured per-subtree heat
    vector (any non-negative weights; the tracker passes
    reads+writes+repair totals).  Pure host arithmetic — the planner
    prices layouts, it does not move data.  ``granule`` (mesh plans
    only) snaps shard boundaries to subtree-aligned multiples, pricing
    exactly the layouts the mesh runtime can instantiate."""
    import numpy as np
    kind, params = parse_plan(spec)
    heat = np.asarray(heat, dtype=np.float64)
    subtrees = int(heat.size)
    total = float(np.sum(heat))
    out = {"plan": spec, "kind": kind, "heat_total": round(total, 3),
           "granularity": {"subtrees": subtrees, "span": int(span),
                           "objects": int(n)}}
    if granule is not None and kind != "mesh":
        raise ValueError("granule= only applies to mesh:<shards> plans")
    if kind == "mesh":
        shards = params["shards"]
        bounds = mesh_bounds(n, shards, granule)
        loads = np.zeros(shards, dtype=np.float64)
        for i in range(subtrees):
            lo, hi = i * span, min((i + 1) * span, n)
            width = max(hi - lo, 1)
            for s in range(shards):
                ov = min(hi, bounds[s + 1]) - max(lo, bounds[s])
                if ov > 0:
                    # subtree heat spread uniformly over its object
                    # range — subtree granularity is all we measured
                    loads[s] += heat[i] * ov / width
        out.update(
            shards=shards,
            loads=[round(float(v), 3) for v in loads],
            max_load=round(float(np.max(loads)) if shards else 0.0, 3),
            mean_load=round(float(np.mean(loads)) if shards else 0.0, 3),
            imbalance=round(_imbalance(loads), 4))
        if granule is not None:
            out["granule"] = int(granule)
            out["bounds"] = [int(b) for b in bounds]
        return out
    owners = params["owners"]
    k = min(params["k"], owners)
    names = ["node-%d" % i for i in range(owners)]
    assign = _ring_owners(names, subtrees, k)
    loads = {name: 0.0 for name in names}
    for i, chosen in enumerate(assign):
        for name in chosen:
            loads[name] += float(heat[i]) / k
    load_vec = np.asarray(list(loads.values()), dtype=np.float64)
    # movement bill: replica assignments that differ from the same
    # ring before its newest owner joined (~1/N for a sane ring; a
    # naive mod-N placement would move ~everything)
    moved = 0.0
    if owners > 1:
        prev = _ring_owners(names[:-1], subtrees, min(k, owners - 1))
        for i, chosen in enumerate(assign):
            gained = set(chosen) - set(prev[i])
            moved += float(heat[i]) * len(gained) / k
    out.update(
        owners=owners, k=k, vnodes=RING_VNODES,
        loads={name: round(v, 3) for name, v in loads.items()},
        skew=round(_imbalance(load_vec), 4),
        movement_frac=round(moved / total, 4) if total > 0 else 0.0)
    return out


# -- the tracker ---------------------------------------------------------------


class HeatTracker:
    """Per-node heat attribution: serve loops call
    :meth:`record_reads`, the gossip drain calls :meth:`record_writes`,
    sync sessions call :meth:`record_repair`; the gossip round cadence
    calls :meth:`publish`.  ``registry=`` injects a private
    :class:`~crdt_tpu.obs.metrics.MetricsRegistry` (fleet tests);
    ``clock=`` injects time for deterministic EWMA tests."""

    def __init__(self, *, capacity: int = DEFAULT_CAPACITY,
                 halflife_s: float = DEFAULT_HALFLIFE_S,
                 registry=None, clock=time.monotonic):
        self._lock = threading.Lock()
        self._registry = registry
        self._clock = clock
        self._capacity = int(capacity)
        self._halflife_s = float(halflife_s)
        self._t0 = clock()
        self._n = 0
        self._subtrees = 0
        self._span = 1
        self._totals: Dict[str, np.ndarray] = {}
        self._ewma: Dict[str, np.ndarray] = {}
        self._rows = {cls: 0 for cls in CLASSES}
        self._mode_reads: Dict[str, int] = {}
        self._sketch = None  # (ids, counts, errs) device arrays
        self._updates = 0
        self._last_publish = None  # (t, {cls: totals copy})

    # -- recording -------------------------------------------------------------

    def record_reads(self, obj_ids, n: int, mode: str = "eventual"):
        """Fold one serve gather batch (row object ids) as read heat,
        attributed to ``mode``'s admission class."""
        self._record("reads", obj_ids, n, mode=mode)

    def record_writes(self, obj_ids, n: int):
        """Fold one oplog drain batch (``OpBatch.obj``) as write heat."""
        self._record("writes", obj_ids, n)

    def record_repair(self, obj_ids, n: int):
        """Fold one applied sync delta row-set as repair heat — the
        objects that actually churned over the wire."""
        self._record("repair", obj_ids, n)

    def _record(self, cls: str, obj_ids, n: int, mode=None):
        import numpy as np
        ids = np.asarray(obj_ids).reshape(-1)
        if ids.size == 0 or n <= 0:
            return
        ids = ids.astype(_host_int(), copy=False)
        with self._lock:
            # helpers compute, this lexically-locked frame assigns —
            # the lock-discipline lint's calling convention
            if int(n) > self._n:
                (self._n, self._subtrees, self._span, self._totals,
                 self._ewma, self._last_publish) = self._grow_layout(int(n))
            per = self._fold_locked(ids)
            self._totals[cls] += per
            self._rows[cls] += int(ids.size)
            if mode is not None:
                self._mode_reads[mode] = (
                    self._mode_reads.get(mode, 0) + int(ids.size))
            self._sketch = self._sketch_fold(ids)
            self._updates += 1
            reg = self._reg()
            for i in np.flatnonzero(per):
                self._inc_subtree(reg, cls, int(i), int(per[i]))
            if mode is not None:
                reg.counter_inc(f"heat.reads.{mode}", int(ids.size))
            reg.counter_inc("heat.updates")

    @staticmethod
    def _inc_subtree(reg, cls: str, i: int, v: int):
        # literal name tails per class — the telemetry lint reads these
        # call sites, and heat.subtree.*.<class> rows must stay
        # distinct from the *_per_s gauge rows
        if cls == "reads":
            reg.counter_inc(f"heat.subtree.{i}.reads", v)
        elif cls == "writes":
            reg.counter_inc(f"heat.subtree.{i}.writes", v)
        else:
            reg.counter_inc(f"heat.subtree.{i}.repair", v)

    def _reg(self):
        return self._registry if self._registry is not None \
            else metrics_mod.registry()

    def registry(self):
        """The :class:`~crdt_tpu.obs.metrics.MetricsRegistry` this
        tracker publishes into — the injected private one, else the
        process default (what the ``/heat`` prom scrape renders)."""
        return self._reg()

    def _grow_layout(self, n: int) -> tuple:
        """Compute the post-growth layout state for ``n > self._n``
        WITHOUT touching self (caller holds the lock and assigns):
        ``(n, subtrees, span, totals, ewma, last_publish)``."""
        import numpy as np
        from . import stability as stability_mod
        subtrees, span = stability_mod.subtree_layout(n)
        if self._n == 0:
            totals = {cls: np.zeros(subtrees, np.int64)
                      for cls in CLASSES}
            ewma = {cls: np.zeros(subtrees, np.float64)
                    for cls in CLASSES}
            return n, subtrees, span, totals, ewma, self._last_publish
        if (subtrees, span) == (self._subtrees, self._span):
            return (n, subtrees, span, self._totals, self._ewma,
                    self._last_publish)

        # the fleet regrew past a span boundary: old spans divide the
        # new span (both TREE_K powers), so old subtree ranges nest
        # whole inside new ones — re-bin exactly
        def rebin(old, dtype):
            new = np.zeros(subtrees, dtype)
            for i in range(self._subtrees):
                new[min(i * self._span // span, subtrees - 1)] += old[i]
            return new

        totals = {cls: rebin(self._totals[cls], np.int64)
                  for cls in CLASSES}
        ewma = {cls: rebin(self._ewma[cls], np.float64)
                for cls in CLASSES}
        last = self._last_publish
        if last is not None:
            t, prev = last
            last = (t, {cls: rebin(prev[cls], np.int64)
                        for cls in CLASSES})
        return n, subtrees, span, totals, ewma, last

    def _fold_locked(self, ids: np.ndarray) -> np.ndarray:
        import numpy as np
        pad_ids, w = _pad_pow2(ids)
        out = _fold_kernel(self._subtrees, self._span)(pad_ids, w)
        return np.asarray(out).astype(np.int64)

    def _sketch_fold(self, ids: np.ndarray) -> tuple:
        """One device sketch update — returns the new table (caller
        holds the lock and assigns ``self._sketch``)."""
        import numpy as np
        table = self._sketch
        if table is None:
            z = np.zeros(self._capacity, dtype=ids.dtype)
            table = (np.full(self._capacity, -1, ids.dtype),
                     z, z.copy())
        pad_ids, w = _pad_pow2(ids)
        return _sketch_kernel(self._capacity)(*table, pad_ids, w)

    # -- decoding / publication ------------------------------------------------

    def _decode_hot_locked(self) -> List[dict]:
        import numpy as np
        if self._sketch is None:
            return []
        ids = np.asarray(self._sketch[0])
        counts = np.asarray(self._sketch[1])
        errs = np.asarray(self._sketch[2])
        keep = np.flatnonzero((ids >= 0) & (counts > 0))
        order = keep[np.argsort(-counts[keep], kind="stable")]
        return [{"obj": int(ids[i]), "count": int(counts[i]),
                 "err": int(errs[i])} for i in order]

    @staticmethod
    def _zipf(hot: List[dict]) -> Tuple[Optional[float],
                                        Optional[float]]:
        # fit on the GUARANTEED counts (count - err): tail entries that
        # rode in on churn carry err ~ count, drop out of the fit, and
        # stop flattening the slope
        return zipf_fit(
            [h["count"] - h["err"] for h in hot[:ZIPF_FIT_RANKS]])

    def publish(self):
        """Refresh the gauge surface: EWMA ``*_per_s`` rates (half-life
        :attr:`halflife_s`; the first publish seeds the window with the
        lifetime mean rate), top-:data:`HOT_GAUGE_RANKS` hot-object
        gauges, and the fitted Zipf exponent."""
        import numpy as np
        with self._lock:
            if self._n == 0:
                return
            now = self._clock()
            reg = self._reg()
            totals = {cls: self._totals[cls].copy() for cls in CLASSES}
            if self._last_publish is None:
                dt = max(now - self._t0, 1e-9)
                for cls in CLASSES:
                    self._ewma[cls] = totals[cls] / dt
            else:
                t0, prev = self._last_publish
                dt = max(now - t0, 1e-9)
                alpha = 1.0 - 0.5 ** (dt / self._halflife_s)
                for cls in CLASSES:
                    rate = (totals[cls] - prev[cls]) / dt
                    self._ewma[cls] = (alpha * rate
                                       + (1.0 - alpha) * self._ewma[cls])
            self._last_publish = (now, totals)
            for i in range(self._subtrees):
                reg.gauge_set(f"heat.subtree.{i}.reads_per_s",
                              float(self._ewma["reads"][i]))
                reg.gauge_set(f"heat.subtree.{i}.writes_per_s",
                              float(self._ewma["writes"][i]))
                reg.gauge_set(f"heat.subtree.{i}.repair_per_s",
                              float(self._ewma["repair"][i]))
            hot = self._decode_hot_locked()
            for rank in range(min(HOT_GAUGE_RANKS, len(hot))):
                reg.gauge_set(f"heat.hot.{rank}.obj",
                              float(hot[rank]["obj"]))
                reg.gauge_set(f"heat.hot.{rank}.count",
                              float(hot[rank]["count"]))
            s_hat, r2 = self._zipf(hot)
            if s_hat is not None:
                reg.gauge_set("heat.zipf.s_hat", s_hat)
                reg.gauge_set("heat.zipf.fit_r2", r2)

    def hot(self, k: int = HOT_GAUGE_RANKS) -> List[dict]:
        """decoded top-k ``{"obj", "count", "err"}`` rows, hottest first."""
        with self._lock:
            return self._decode_hot_locked()[:k]

    def snapshot(self) -> dict:
        """The JSON the ``/heat`` route serves."""
        with self._lock:
            hot = self._decode_hot_locked()
            s_hat, r2 = self._zipf(hot)
            sub = []
            for i in range(self._subtrees):
                sub.append({
                    "reads": int(self._totals["reads"][i]),
                    "writes": int(self._totals["writes"][i]),
                    "repair": int(self._totals["repair"][i]),
                    "reads_per_s": round(float(self._ewma["reads"][i]), 3),
                    "writes_per_s": round(float(self._ewma["writes"][i]), 3),
                    "repair_per_s": round(float(self._ewma["repair"][i]), 3),
                })
            return {
                "layout": {"objects": self._n,
                           "subtrees": self._subtrees,
                           "span": self._span},
                "rows": dict(self._rows),
                "updates": self._updates,
                "reads_by_mode": dict(self._mode_reads),
                "subtree": sub,
                "hot": hot[:ZIPF_FIT_RANKS],
                "sketch": {
                    "capacity": self._capacity,
                    # worst per-entry overestimate among kept entries
                    "error_bound": max([h["err"] for h in hot], default=0),
                },
                "zipf": {"s_hat": s_hat, "r2": r2},
            }

    # -- planning --------------------------------------------------------------

    def heat_vector(self) -> np.ndarray:
        """reads+writes+repair per subtree — what the planner scores."""
        import numpy as np
        with self._lock:
            if self._subtrees == 0:
                return np.zeros(0, np.float64)
            out = np.zeros(self._subtrees, np.float64)
            for cls in CLASSES:
                out += self._totals[cls]
            return out

    def plan_report(self, spec: str,
                    granule: int | None = None) -> dict:
        """Score one ``mesh:<S>`` / ``ring:<N>[,k=<K>]`` placement spec
        against this node's measured heat (:func:`score_plan`);
        ``granule`` snaps mesh-plan boundaries subtree-aligned (the
        ``?granule=`` query parameter of ``GET /heat``)."""
        import numpy as np
        with self._lock:
            heat = np.zeros(max(self._subtrees, 1), np.float64)
            for cls in CLASSES:
                if cls in self._totals:
                    heat[:self._subtrees] += self._totals[cls]
            return score_plan(spec, heat, n=max(self._n, 1),
                              span=self._span, granule=granule)

    def reset(self):
        with self._lock:
            self._n = 0
            self._subtrees = 0
            self._span = 1
            self._totals = {}
            self._ewma = {}
            self._rows = {cls: 0 for cls in CLASSES}
            self._mode_reads = {}
            self._sketch = None
            self._updates = 0
            self._last_publish = None
            self._t0 = self._clock()


# -- the default (process-global) tracker -------------------------------------

_DEFAULT: Optional[HeatTracker] = None
_DEFAULT_LOCK = threading.Lock()


def tracker() -> HeatTracker:
    """The process-global heat tracker — what standalone serve loops
    and sync sessions feed and ``GET /heat`` serves by default
    (cluster nodes own private ones so in-process fleets keep their
    attribution apart)."""
    global _DEFAULT
    if _DEFAULT is None:
        with _DEFAULT_LOCK:
            if _DEFAULT is None:
                _DEFAULT = HeatTracker()
    return _DEFAULT


#: package-level alias (``crdt_tpu.obs.heat_tracker``) — the
#: un-shadowed name next to ``convergence.tracker`` / ``stability_tracker``
heat_tracker = tracker
