"""Capacity observatory — plane occupancy, growth rates, overflow ETAs.

The causal-GC roadmap item has no oracle and mesh-shard capacity
planning has no data until someone *measures* the dense planes.  The
kernels live in :mod:`crdt_tpu.batch.occupancy` (jitted reductions, one
small host fetch per sample); this module turns their
:class:`~crdt_tpu.batch.occupancy.Occupancy` samples into operator
signal:

* ``crdt_tpu_capacity_<plane>_*`` gauges — exact plane bytes, padded
  vs live slots, busiest-object live count, tombstone rows, EWMA
  growth rate (rows/s) and a time-to-overflow ETA against the
  executor's ``max_capacity`` regrow ceiling
  (:class:`crdt_tpu.parallel.executor.JoinExecutor`).
* a **watermark state** (``ok``/``warn``/``critical``) per plane and
  overall, surfaced as the ``/healthz`` JSON body
  (:mod:`crdt_tpu.obs.export`) and the ``crdt_tpu_capacity_watermark``
  gauge, so "this fleet is 90% of the way to its regrow ceiling" is an
  alert, not an autopsy.
* :meth:`CapacityTracker.regrow_timeline` — the executor's regrow
  events (now stamped with before/after capacities) read back from the
  flight recorder as one ordered story, so a regrowing fleet's
  capacity history correlates with the occupancy curve that forced it.

The oplog buffers get the same treatment (:meth:`CapacityTracker.
sample_oplog` / :meth:`sample_gap_buffer`): the PR 7 "bounded, loud
overflow" op log and causal-gap park buffer report their occupancy
before they throw, not after.

Capacity gauges are plain registry gauges, so they ride the PR 6 fleet
lattice for free (per-node LWW slices); :meth:`crdt_tpu.obs.fleet.
FleetSnapshot.fleet_capacity` adds the fleet max/sum reduction
``/fleet`` serves.

Stdlib-only at module scope (the obs import-lightness contract): the
kernel module imports lazily inside :meth:`CapacityTracker.sample`.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Callable, Dict, List, Optional

from . import events as events_mod
from . import metrics as metrics_mod

#: the executor's default regrow ceiling
#: (:class:`crdt_tpu.parallel.executor.JoinExecutor` ``max_capacity``)
#: — the default overflow horizon ETAs count down toward
DEFAULT_CEILING = 1 << 16


@dataclasses.dataclass(frozen=True)
class Occupancy:
    """One plane family's occupancy at one instant.

    ``slot_capacity`` is the *binding* per-object axis — the one a
    capacity regrow widens (member slots for ORSWOT, key slots for Map,
    actor columns for the counter planes, the buffer bound for op
    logs); ``live_max`` is the busiest object's live count along it,
    i.e. the distance-to-overflow statistic.  ``bytes`` is the exact
    byte footprint of the live arrays (sum of plane ``nbytes``), pinned
    equal to the device buffers by the long-soak test.

    Defined here (stdlib-only) so op-buffer samples need no jax; the
    kernels that fill it for dense batches live in
    :mod:`crdt_tpu.batch.occupancy`.
    """

    kind: str               # orswot / vclock / gcounter / pncounter / map /
    #                         oplog / oplog_gap
    objects: int            # N (fleet rows; log segments for op logs)
    bytes: int              # exact plane bytes == sum of buffer nbytes
    slot_capacity: int      # binding axis width per object
    slots: int              # total padded cells along the binding axis
    live: int               # live cells along the binding axis, fleet-wide
    live_max: int           # busiest object's live count (overflow distance)
    actors: int = 0         # actor columns carried (0 = not applicable)
    actors_live: int = 0    # actor columns with any nonzero dot
    tombstone_capacity: int = 0  # deferred slots per object (0 = none)
    tombstones: int = 0     # live deferred/tombstone rows, fleet-wide
    tombstones_max: int = 0  # busiest object's tombstone rows (the
    #                          deferred axis's shrink-fit statistic)

    @property
    def utilization(self) -> float:
        """Live fraction of the binding axis, fleet-wide."""
        return self.live / self.slots if self.slots else 0.0

#: watermark states, in severity order (the overall state is the max)
WATERMARK_STATES = ("ok", "warn", "critical")

#: ``eta_s`` gauge sentinel: the plane is not growing (rate <= 0), so
#: there is no finite overflow horizon — exported as -1, never +Inf,
#: so JSON consumers and Prometheus alerts stay arithmetic-safe
ETA_NOT_GROWING = -1.0


@dataclasses.dataclass
class PlaneCapacity:
    """One tracked plane's latest sample + derived series."""

    occupancy: Occupancy
    ceiling: int                 # regrow ceiling ETAs count toward
    rate: Optional[float]        # EWMA live_max growth, rows/s
    eta_s: float                 # seconds to ceiling (ETA_NOT_GROWING
    #                              when rate <= 0; 0.0 when already there)
    state: str                   # ok / warn / critical
    sampled_at: float            # tracker-clock timestamp


class CapacityTracker:
    """Samples plane occupancy into gauges, growth rates and ETAs.

    One tracker per registry (the process-global pair is the default);
    every :meth:`sample` publishes the plane's gauges, folds the
    busiest-object live count into an EWMA growth rate, derives the
    overflow ETA against the plane's ceiling, and re-computes the
    watermark.  ``warn_frac``/``critical_frac`` are utilization-of-
    ceiling thresholds on the busiest object; ``alpha`` is the EWMA
    smoothing weight on instantaneous rates; ``clock`` is injectable
    for tests (monotonic seconds).
    """

    def __init__(self, registry: Optional[metrics_mod.MetricsRegistry]
                 = None, *,
                 max_capacity: int = DEFAULT_CEILING,
                 warn_frac: float = 0.7,
                 critical_frac: float = 0.9,
                 alpha: float = 0.3,
                 clock: Callable[[], float] = time.monotonic):
        if not 0.0 < warn_frac <= critical_frac <= 1.0:
            raise ValueError(
                f"need 0 < warn_frac <= critical_frac <= 1, got "
                f"{warn_frac}/{critical_frac}"
            )
        self._registry = registry
        self.max_capacity = max_capacity
        self.warn_frac = warn_frac
        self.critical_frac = critical_frac
        self.alpha = alpha
        self._clock = clock
        self._lock = threading.Lock()
        self._planes: Dict[str, PlaneCapacity] = {}

    def _reg(self) -> metrics_mod.MetricsRegistry:
        return self._registry if self._registry is not None \
            else metrics_mod.registry()

    # -- sampling ------------------------------------------------------------

    def sample(self, batch, label: Optional[str] = None, *,
               ceiling: Optional[int] = None):
        """Measure ``batch``'s planes (one jitted reduction + one host
        fetch) and publish.  Returns the
        :class:`~crdt_tpu.batch.occupancy.Occupancy`.  Raises
        ``TypeError`` for batch types without dense planes."""
        from ..batch import occupancy as batch_occupancy

        occ = batch_occupancy.occupancy_of(batch)
        return self.observe(occ, label=label, ceiling=ceiling)

    def sample_oplog(self, log, label: str = "oplog"):
        """The op log's occupancy (buffered ops vs its bound, exact
        column bytes) — the backpressure signal the bounded buffer
        never exposed before it threw."""
        o = log.occupancy()
        return self.observe(_buffer_occupancy("oplog", o), label=label,
                            ceiling=o["capacity"])

    def sample_gap_buffer(self, applier, label: str = "oplog_gap"):
        """The causal-gap park buffer's occupancy (parked adds vs
        ``park_capacity``) — a climbing gauge here means predecessor
        dots are not arriving."""
        o = applier.occupancy()
        return self.observe(_buffer_occupancy("oplog_gap", o), label=label,
                            ceiling=o["capacity"])

    def sample_device_memory(self):
        """Fold ``jax.live_arrays()`` into the ``devicemem.*`` gauges
        (total + per-dtype live bytes, and the tracked-vs-live
        fraction against this tracker's plane bytes) — the
        construction-vs-device gap, on the same cadence as the plane
        samples.  Delegates to :func:`crdt_tpu.obs.kernels.
        sample_device_memory`; a no-op returning None when jax was
        never imported."""
        from . import kernels as kernels_mod

        return kernels_mod.sample_device_memory(
            registry=self._reg(), tracker=self)

    def observe(self, occ, label: Optional[str] = None, *,
                ceiling: Optional[int] = None):
        """Fold one pre-computed occupancy sample in and publish its
        gauges.  ``label`` names the gauge family (defaults to the
        occupancy's ``kind``; one dotted segment)."""
        label = label if label is not None else occ.kind
        if not label or "." in label or "/" in label:
            raise ValueError(
                f"capacity label must be a single metric segment, "
                f"got {label!r}"
            )
        if ceiling is None:
            # actor planes cannot regrow through the executor: their
            # horizon is the interning table's width itself
            ceiling = occ.slot_capacity \
                if occ.kind in ("vclock", "gcounter", "pncounter") \
                else self.max_capacity
        now = self._clock()
        capacity_changed = False
        with self._lock:
            prev = self._planes.get(label)
            rate = prev.rate if prev is not None else None
            if prev is not None and \
                    prev.occupancy.slot_capacity != occ.slot_capacity:
                # the plane was re-packed (GC shrink) or regrown between
                # samples: the live_max delta measures the capacity
                # event, not write demand — a stale positive EWMA would
                # count down a bogus ETA against the new rung, so the
                # rate re-seeds from scratch
                capacity_changed = True
                rate = None
                prev = None
            if prev is not None and now > prev.sampled_at:
                inst = (occ.live_max - prev.occupancy.live_max) \
                    / (now - prev.sampled_at)
                rate = inst if rate is None \
                    else self.alpha * inst + (1.0 - self.alpha) * rate
            headroom = ceiling - occ.live_max
            if headroom <= 0:
                eta = 0.0
            elif rate is not None and rate > 0:
                eta = headroom / rate
            else:
                eta = ETA_NOT_GROWING
            util = occ.live_max / ceiling if ceiling > 0 else 0.0
            if util >= self.critical_frac:
                state = "critical"
            elif util >= self.warn_frac:
                state = "warn"
            else:
                state = "ok"
            self._planes[label] = PlaneCapacity(
                occupancy=occ, ceiling=ceiling, rate=rate, eta_s=eta,
                state=state, sampled_at=now,
            )
            overall = max(
                (WATERMARK_STATES.index(p.state)
                 for p in self._planes.values()),
                default=0,
            )
        reg = self._reg()
        reg.counter_inc("capacity.samples")
        reg.gauge_set(f"capacity.{label}.bytes", occ.bytes)
        reg.gauge_set(f"capacity.{label}.objects", occ.objects)
        reg.gauge_set(f"capacity.{label}.slots", occ.slots)
        reg.gauge_set(f"capacity.{label}.live", occ.live)
        reg.gauge_set(f"capacity.{label}.live_max", occ.live_max)
        reg.gauge_set(f"capacity.{label}.tombstones", occ.tombstones)
        reg.gauge_set(f"capacity.{label}.utilization", util)
        if rate is not None:
            reg.gauge_set(f"capacity.{label}.growth_rows_per_s", rate)
        elif capacity_changed:
            # overwrite the pre-shrink/regrow rate: the exported gauge
            # must not keep reporting a stale positive growth against
            # the new capacity while the EWMA re-seeds
            reg.gauge_set(f"capacity.{label}.growth_rows_per_s", 0.0)
        reg.gauge_set(f"capacity.{label}.eta_s", eta)
        reg.gauge_set(f"capacity.{label}.watermark",
                      WATERMARK_STATES.index(state))
        reg.gauge_set("capacity.watermark", overall)
        return occ

    # -- the watermark view (what /healthz serves) ---------------------------

    def watermark(self) -> dict:
        """The current watermark: overall ``state`` (the max severity
        across tracked planes; ``ok`` with none tracked) plus a
        per-plane breakdown — the ``/healthz`` JSON body."""
        with self._lock:
            planes = dict(self._planes)
        state_idx = 0
        detail = {}
        for label, p in sorted(planes.items()):
            state_idx = max(state_idx, WATERMARK_STATES.index(p.state))
            detail[label] = {
                "state": p.state,
                "live_max": p.occupancy.live_max,
                "ceiling": p.ceiling,
                "utilization": round(
                    p.occupancy.live_max / p.ceiling, 6
                ) if p.ceiling else 0.0,
                "bytes": p.occupancy.bytes,
                "growth_rows_per_s": p.rate,
                "eta_s": p.eta_s,
            }
        return {"state": WATERMARK_STATES[state_idx], "planes": detail}

    def planes(self) -> Dict[str, PlaneCapacity]:
        """A consistent copy of the per-plane tracking state."""
        with self._lock:
            return dict(self._planes)

    def reset(self) -> None:
        with self._lock:
            self._planes.clear()

    # -- regrow correlation --------------------------------------------------

    def regrow_timeline(self, recorder: Optional[events_mod.FlightRecorder]
                        = None) -> List[dict]:
        """The executor's capacity regrows as an ordered timeline:
        every ``executor.regrow`` flight-recorder event with its
        before/after capacity stamps
        (:func:`crdt_tpu.parallel.executor._record_recovery` writes
        them), so an occupancy curve can be correlated with the regrow
        that answered it."""
        rec = recorder if recorder is not None else events_mod.recorder()
        out = []
        for ev in rec.snapshot(kind="executor.regrow"):
            f = ev.get("fields", {})
            out.append({
                # mono_ts is the duration-math stamp (wall-skew immune);
                # wall_ts rides along for human display
                "mono_ts": ev["mono_ts"],
                "wall_ts": ev["wall_ts"],
                "schedule": f.get("schedule"),
                "member_capacity": (f.get("member_capacity_before"),
                                    f.get("member_capacity")),
                "deferred_capacity": (f.get("deferred_capacity_before"),
                                      f.get("deferred_capacity")),
            })
        return out


def _buffer_occupancy(kind: str, o: dict) -> Occupancy:
    """An op-buffer occupancy dict (``OpLog.occupancy()`` /
    ``OpApplier.occupancy()`` shape) as an :class:`Occupancy`."""
    return Occupancy(
        kind=kind, objects=int(o.get("segments", 0)), bytes=int(o["bytes"]),
        slot_capacity=int(o["capacity"]), slots=int(o["capacity"]),
        live=int(o["ops"]), live_max=int(o["ops"]),
    )


# -- the default (process-global) tracker -------------------------------------

_DEFAULT: Optional[CapacityTracker] = None
_DEFAULT_LOCK = threading.Lock()


def capacity_tracker() -> CapacityTracker:
    """The process-global tracker — what ``/healthz`` consults and the
    gossip runtime samples into by default."""
    global _DEFAULT
    if _DEFAULT is None:
        with _DEFAULT_LOCK:
            if _DEFAULT is None:
                _DEFAULT = CapacityTracker()
    return _DEFAULT
