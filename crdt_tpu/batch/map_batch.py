"""MapBatch — N reset-remove CRDT maps on device (L4 composition).

Dense form of `/root/reference/src/map.rs:83-99`: map clock, key-slot tables
(interned key ids + per-key entry clocks + nested value state) and a
deferred-remove table.  The nested value type is a value kernel
(:mod:`crdt_tpu.batch.val_kernels`) — ``MVRegKernel``, ``OrswotKernel`` or a
nested ``MapKernel`` — so ``Map<K, MVReg>``, ``Map<K, Orswot>`` and
``Map<K, Map<K2, V>>`` (`/root/reference/test/map.rs:8`) each compile to one
fused merge kernel.

``merge`` runs the vectorized per-key dot algebra + recursive value join
(:func:`crdt_tpu.ops.map_ops.merge`); ``apply_up`` / ``apply_rm`` apply one
op per object across the batch.  Keys are interned through the shared member
registry (any hashable key, `map.rs:12-13`).
"""

from __future__ import annotations

import functools
from typing import Any, Sequence

import jax
import numpy as np
from flax import struct

from ..error import CapacityOverflowError, WireFormatError
from ..config import counter_dtype
from ..ops import map_ops
from ..ops.orswot_ops import EMPTY
from ..scalar.map import Entry, Map
from ..scalar.vclock import VClock
from ..utils.interning import Universe
from ..utils.hostmem import gc_paused
from ..obs.kernels import observed_kernel
from .val_kernels import MapKernel
from .vclock_batch import row_to_vclock


def _clock_to_row(vc: VClock, row, universe: Universe) -> None:
    for actor, counter in vc.dots.items():
        row[universe.actor_idx(actor)] = counter


def _map_wire_leg(val_kernel) -> str | None:
    """The native wire-codec leg name for a value kernel, or None when
    only the Python path serves this composition."""
    from .val_kernels import MVRegKernel, OrswotKernel

    if type(val_kernel) is MVRegKernel:
        return "mvreg"
    if type(val_kernel) is OrswotKernel:
        return "orswot"
    if (
        type(val_kernel) is MapKernel
        and type(val_kernel.val_kernel) is MVRegKernel
    ):
        # the reference's canonical nesting Map<K, Map<K2, MVReg>>
        # (`/root/reference/test/map.rs:8`)
        return "map_mvreg"
    return None


@struct.dataclass
class MapBatch:
    clock: jax.Array  # u64[N, A]
    keys: jax.Array  # int32[N, K]  (-1 = empty)
    entry_clocks: jax.Array  # u64[N, K, A]
    vals: Any  # nested value state, leaves [N, K, *inner]
    d_keys: jax.Array  # int32[N, D] (-1 = empty)
    d_clocks: jax.Array  # u64[N, D, A]
    kernel: MapKernel = struct.field(pytree_node=False)

    @property
    def state(self):
        return (
            self.clock,
            self.keys,
            self.entry_clocks,
            self.vals,
            self.d_keys,
            self.d_clocks,
        )

    @classmethod
    def from_state(cls, state, kernel: MapKernel) -> "MapBatch":
        clock, keys, eclocks, vals, d_keys, d_clocks = state
        return cls(
            clock=clock,
            keys=keys,
            entry_clocks=eclocks,
            vals=vals,
            d_keys=d_keys,
            d_clocks=d_clocks,
            kernel=kernel,
        )

    @classmethod
    def zeros(cls, n: int, universe: Universe, val_kernel) -> "MapBatch":
        kernel = MapKernel.from_config(universe.config, val_kernel)
        return cls.from_state(kernel.zeros((n,)), kernel)

    @classmethod
    @gc_paused
    def from_scalar(
        cls, states: Sequence[Map], universe: Universe, val_kernel
    ) -> "MapBatch":
        import jax.numpy as jnp

        cfg = universe.config
        kernel = MapKernel.from_config(cfg, val_kernel)
        n, k, d, a = len(states), cfg.key_capacity, cfg.deferred_capacity, cfg.num_actors
        dt = counter_dtype(cfg)
        clock = np.zeros((n, a), dtype=dt)
        keys = np.full((n, k), EMPTY, dtype=np.int32)
        eclocks = np.zeros((n, k, a), dtype=dt)
        d_keys = np.full((n, d), EMPTY, dtype=np.int32)
        d_clocks = np.zeros((n, d, a), dtype=dt)
        vals_flat = []
        for i, m in enumerate(states):
            if len(m.entries) > k:
                raise ValueError(f"map {i} has {len(m.entries)} keys > key_capacity {k}")
            _clock_to_row(m.clock, clock[i], universe)
            slot_vals = [val_kernel.default_scalar() for _ in range(k)]
            for j, (key, entry) in enumerate(m.entries.items()):
                keys[i, j] = universe.member_id(key)
                _clock_to_row(entry.clock, eclocks[i, j], universe)
                slot_vals[j] = entry.val
            vals_flat.extend(slot_vals)
            rows = [
                (clock_key, key)
                for clock_key, key_set in m.deferred.items()
                for key in key_set
            ]
            if len(rows) > d:
                raise ValueError(
                    f"map {i} has {len(rows)} deferred rows > deferred_capacity {d}"
                )
            for j, (clock_key, key) in enumerate(rows):
                d_keys[i, j] = universe.member_id(key)
                _clock_to_row(VClock.from_key(clock_key), d_clocks[i, j], universe)

        leaves = val_kernel.from_scalar_vals(vals_flat, universe)
        vals = jax.tree.map(lambda l: l.reshape(n, k, *l.shape[1:]), leaves)
        return cls(
            clock=jnp.asarray(clock),
            keys=jnp.asarray(keys),
            entry_clocks=jnp.asarray(eclocks),
            vals=vals,
            d_keys=jnp.asarray(d_keys),
            d_clocks=jnp.asarray(d_clocks),
            kernel=kernel,
        )

    @classmethod
    @gc_paused
    def from_wire(
        cls, blobs: Sequence[bytes], universe: Universe, val_kernel
    ) -> "MapBatch":
        """Bulk ingest from wire blobs (``to_binary(map)`` payloads).

        The native fast path covers the ``Map<int, MVReg<int>>``,
        ``Map<int, Orswot<int>>`` and ``Map<int, Map<int, MVReg<int>>>``
        monomorphizations (identity universe — the last is the
        reference's canonical nesting, `/root/reference/test/map.rs:8`);
        any other composition — and any blob outside the integer-keyed
        grammar — takes the per-blob Python decoder, so the result always
        equals
        ``from_scalar([from_binary(b) for b in blobs], uni, val_kernel)``.
        Other nestings bulk-transport via ``checkpoint.save_bytes``."""
        import jax.numpy as jnp

        from ..utils.serde import from_binary
        from .wirebulk import (
            concat_blobs, fallback_reason, probe_engine, record_wire,
        )

        cfg = universe.config
        leg = _map_wire_leg(val_kernel)
        engine = None
        if leg is not None:
            engine = probe_engine(
                universe, f"map_{leg}_ingest_wire", counter_dtype(cfg)
            )
        if engine is None:
            record_wire("map", "from_wire", fallback=len(blobs),
                        reason="no_native_leg" if leg is None
                        else fallback_reason(universe))
            return cls.from_scalar(
                [from_binary(b) for b in blobs], universe, val_kernel
            )
        buf, offsets = concat_blobs(blobs)
        if leg == "mvreg":
            (clock, keys, eclocks, *val_planes,
             d_keys, d_clocks, status) = engine.map_mvreg_ingest_wire(
                buf, offsets, cfg.num_actors, cfg.key_capacity,
                cfg.deferred_capacity, val_kernel.mv_capacity,
                counter_dtype(cfg),
            )
            value_overflow_msg = (
                f"a value antichain wider than mv_capacity "
                f"{val_kernel.mv_capacity}"
            )
        elif leg == "map_mvreg":
            (clock, keys, eclocks, *val_planes,
             d_keys, d_clocks, status) = engine.map_map_mvreg_ingest_wire(
                buf, offsets, cfg.num_actors, cfg.key_capacity,
                cfg.deferred_capacity, val_kernel.key_capacity,
                val_kernel.deferred_capacity,
                val_kernel.val_kernel.mv_capacity, counter_dtype(cfg),
            )
            value_overflow_msg = (
                f"an inner map exceeding key_capacity "
                f"{val_kernel.key_capacity} / deferred_capacity "
                f"{val_kernel.deferred_capacity} / mv_capacity "
                f"{val_kernel.val_kernel.mv_capacity}"
            )
        else:
            (clock, keys, eclocks, *val_planes,
             d_keys, d_clocks, status) = engine.map_orswot_ingest_wire(
                buf, offsets, cfg.num_actors, cfg.key_capacity,
                cfg.deferred_capacity, val_kernel.member_capacity,
                val_kernel.deferred_capacity, counter_dtype(cfg),
            )
            value_overflow_msg = (
                f"a value set exceeding member_capacity "
                f"{val_kernel.member_capacity} / deferred_capacity "
                f"{val_kernel.deferred_capacity}"
            )
        n_fb = 0
        if status.any():
            hard = np.nonzero(status > 1)[0]
            if hard.size:
                first = int(hard[0])
                code = int(status[first])
                if code == 2:
                    raise WireFormatError(
                        f"map {first} has more keys than key_capacity "
                        f"{cfg.key_capacity}"
                    )
                if code == 3:
                    raise WireFormatError(
                        f"map {first} has more deferred rows than "
                        f"deferred_capacity {cfg.deferred_capacity}"
                    )
                if code == 5:
                    raise WireFormatError(f"map {first} has {value_overflow_msg}")
                raise WireFormatError(
                    f"map {first}: actor outside the identity registry "
                    f"range [0, {cfg.num_actors})"
                )
            fb = np.nonzero(status == 1)[0].tolist()
            n_fb = len(fb)
            sub = cls.from_scalar(
                [from_binary(blobs[i]) for i in fb], universe, val_kernel
            )
            idx = np.asarray(fb, dtype=np.int64)
            clock[idx] = np.asarray(sub.clock)
            keys[idx] = np.asarray(sub.keys)
            eclocks[idx] = np.asarray(sub.entry_clocks)
            for plane, sub_plane in zip(
                val_planes, jax.tree_util.tree_leaves(sub.vals)
            ):
                plane[idx] = np.asarray(sub_plane)
            d_keys[idx] = np.asarray(sub.d_keys)
            d_clocks[idx] = np.asarray(sub.d_clocks)
        record_wire("map", "from_wire", native=len(blobs) - n_fb,
                    fallback=n_fb, reason="grammar")
        vals = tuple(jnp.asarray(p) for p in val_planes)
        if leg == "map_mvreg":
            # re-nest the flat engine planes into the MapKernel vals
            # pytree: (iclock, ikeys, ieclocks, (vclocks, vvals),
            # id_keys, id_clocks)
            vals = vals[:3] + ((vals[3], vals[4]),) + vals[5:]
        return cls(
            clock=jnp.asarray(clock),
            keys=jnp.asarray(keys),
            entry_clocks=jnp.asarray(eclocks),
            vals=vals,
            d_keys=jnp.asarray(d_keys),
            d_clocks=jnp.asarray(d_clocks),
            kernel=MapKernel.from_config(cfg, val_kernel),
        )

    @gc_paused
    def to_wire(self, universe: Universe) -> list[bytes]:
        """Bulk egress to wire blobs, byte-identical to
        ``[to_binary(s) for s in self.to_scalar(uni)]`` (fast paths for
        the ``Map<int, MVReg<int>>`` / ``Map<int, Orswot<int>>``
        monomorphizations; u64 counters at/above 2^63 and other
        compositions take the Python encoder)."""
        from ..utils.serde import to_binary
        from .wirebulk import (
            counters_overflow_zigzag, fallback_reason, probe_engine,
            record_wire, slice_blobs,
        )

        n = self.clock.shape[0]
        if n == 0:
            return []
        leg = _map_wire_leg(self.kernel.val_kernel)
        engine = None
        if leg is not None:
            engine = probe_engine(
                universe, f"map_{leg}_encode_wire",
                counter_dtype(universe.config),
            )
        reason = "no_native_leg" if leg is None else fallback_reason(universe)
        planes = None
        if engine is not None:
            planes = tuple(np.asarray(x) for x in (
                self.clock, self.keys, self.entry_clocks,
                *jax.tree_util.tree_leaves(self.vals),
                self.d_keys, self.d_clocks,
            ))
            if counters_overflow_zigzag(planes):
                engine = None
                reason = "overflow_zigzag"
        if engine is None:
            record_wire("map", "to_wire", fallback=n, reason=reason)
            return [to_binary(s) for s in self.to_scalar(universe)]
        encode = getattr(engine, f"map_{leg}_encode_wire")
        buf, offsets = encode(*planes)
        record_wire("map", "to_wire", native=n)
        return slice_blobs(buf, offsets)

    @gc_paused
    def to_scalar(self, universe: Universe) -> list[Map]:
        kernel = self.kernel
        vk = kernel.val_kernel
        clock = np.asarray(self.clock)
        keys = np.asarray(self.keys)
        eclocks = np.asarray(self.entry_clocks)
        d_keys = np.asarray(self.d_keys)
        d_clocks = np.asarray(self.d_clocks)
        n, k = keys.shape
        flat = jax.tree.map(lambda l: l.reshape(n * k, *l.shape[2:]), self.vals)
        scalar_vals = vk.to_scalar_vals(flat, universe)

        out = []
        for i in range(n):
            # a SERIALIZABLE val_type (the registered class / MapOf),
            # not the bound factory — so to_binary(to_scalar()[i]) works
            m = Map(vk.scalar_val_type())
            m.clock = row_to_vclock(clock[i], universe)
            for j in range(k):
                if keys[i, j] == EMPTY:
                    continue
                key = universe.members.lookup(int(keys[i, j]))
                m.entries[key] = Entry(
                    clock=row_to_vclock(eclocks[i, j], universe),
                    val=scalar_vals[i * k + j],
                )
            for j in range(d_keys.shape[1]):
                if d_keys[i, j] == EMPTY:
                    continue
                key = universe.members.lookup(int(d_keys[i, j]))
                ck = row_to_vclock(d_clocks[i, j], universe).key()
                m.deferred.setdefault(ck, set()).add(key)
            out.append(m)
        return out

    # -- state path ---------------------------------------------------------

    def merge(self, other: "MapBatch", check: bool = True) -> "MapBatch":
        """`map.rs:192-269`; raises :class:`CapacityOverflowError` on any
        capacity overflow (key, deferred, or nested value — the kernel's
        flag is collapsed, so elastic recovery grows the whole envelope
        via :meth:`with_capacity`)."""
        if self.kernel != other.kernel:
            # capacity-only mismatches (e.g. path-dependent nested growth
            # after elastic regrows) unify to the pointwise max; genuine
            # structural mismatches raise inside unified()
            target = self.kernel.unified(other.kernel)
            a = self if self.kernel == target else MapBatch.from_state(
                self.kernel.grow_state(self.state, target), target
            )
            b = other if other.kernel == target else MapBatch.from_state(
                other.kernel.grow_state(other.state, target), target
            )
            return a.merge(b, check)
        state, overflow = _merge(self.state, other.state, self.kernel)
        if check and bool(np.any(np.asarray(overflow))):  # crdtlint: disable=SC03 — overflow host-raise contract, one bool per batch call
            raise CapacityOverflowError(
                "MapBatch merge overflow: raise key/deferred/value capacities",
                member=True, deferred=True,
            )
        return MapBatch.from_state(state, self.kernel)

    # -- elastic-capacity protocol (crdt_tpu.parallel.JoinExecutor) ----------
    # Generic slot-axis names: the key axis reports as member_capacity, the
    # map-level deferred table as deferred_capacity.  Because the merge's
    # overflow flag does not name the overflowed axis (it may be a NESTED
    # value capacity), with_capacity scales the nested value kernel's
    # capacities by the same factor as the key axis — growth always makes
    # progress no matter which axis actually overflowed.

    @property
    def member_capacity(self) -> int:
        return self.keys.shape[-1]

    @property
    def deferred_capacity(self) -> int:
        return self.d_keys.shape[-1]

    def with_capacity(
        self, member_capacity: int | None = None,
        deferred_capacity: int | None = None,
    ) -> "MapBatch":
        """Pad the key/deferred axes EXACTLY to the requested capacities
        (so an executor's ``max_capacity`` bound holds for the named
        axes); the nested value axes scale by the key-growth factor —
        inherent overshoot the collapsed overflow flag forces, since the
        overflow may live in a nested capacity.  Never shrinks."""
        import dataclasses

        k, d = self.member_capacity, self.deferred_capacity
        new_k = k if member_capacity is None else member_capacity
        new_d = d if deferred_capacity is None else deferred_capacity
        if new_k < k or new_d < d:
            raise ValueError("with_capacity cannot shrink (would drop live slots)")
        if (new_k, new_d) == (k, d):
            return self
        factor = max(-(-new_k // k), -(-new_d // d), 1)
        target = dataclasses.replace(
            self.kernel,
            key_capacity=new_k,
            deferred_capacity=new_d,
            val_kernel=self.kernel.val_kernel.grown(factor),
        )
        state = self.kernel.grow_state(self.state, target)
        return MapBatch.from_state(state, target)

    def truncate(self, clock: jax.Array, check: bool = True) -> "MapBatch":
        """``Causal::truncate`` (`map.rs:131-158`); ``clock``: u64[N, A]."""
        state, overflow = _truncate(self.state, clock, self.kernel)
        if check and bool(np.any(np.asarray(overflow))):  # crdtlint: disable=SC03 — overflow host-raise contract, one bool per batch call
            raise ValueError("MapBatch truncate overflow")
        return MapBatch.from_state(state, self.kernel)

    # -- op path ------------------------------------------------------------

    def apply_rm(self, rm_clock, key_id, check: bool = True) -> "MapBatch":
        """Batched ``Op::Rm`` (`map.rs:166-168`)."""
        state, overflow = _apply_rm(self.state, rm_clock, key_id, self.kernel)
        if check and bool(np.any(np.asarray(overflow))):  # crdtlint: disable=SC03 — overflow host-raise contract, one bool per batch call
            raise ValueError("MapBatch apply_rm overflow: raise deferred_capacity")
        return MapBatch.from_state(state, self.kernel)

    def apply_up(
        self, actor_idx, counter, key_id, nested_op: str, nested_args: tuple,
        check: bool = True,
    ) -> "MapBatch":
        """Batched ``Op::Up`` (`map.rs:169-187`).

        ``nested_op`` names a value-kernel op method (``"apply_put"``,
        ``"apply_add"``, ``"apply_remove"``); ``nested_args`` are its
        per-object array arguments.  The (static op, traced args) split
        keeps the whole update one jitted XLA program per op kind."""
        state, overflow = _apply_up(
            self.state, actor_idx, counter, key_id, nested_args, nested_op, self.kernel
        )
        if check and bool(np.any(np.asarray(overflow))):  # crdtlint: disable=SC03 — overflow host-raise contract, one bool per batch call
            raise ValueError("MapBatch apply_up overflow: raise key_capacity")
        return MapBatch.from_state(state, self.kernel)

    # -- reads (`map.rs:271-302`) -------------------------------------------

    def len_counts(self) -> jax.Array:
        """Entry counts per object (`map.rs:282-288`)."""
        import jax.numpy as jnp

        return jnp.sum(self.keys != EMPTY, axis=-1)

    def contains(self, key_id) -> jax.Array:
        """Key-presence bitmap."""
        import jax.numpy as jnp

        return jnp.any(self.keys == key_id[..., None], axis=-1)


@observed_kernel("batch.map.merge")
@functools.partial(jax.jit, static_argnums=(2,))
def _merge(state_a, state_b, kernel: MapKernel):
    return kernel.merge(state_a, state_b)


@observed_kernel("batch.map.truncate")
@functools.partial(jax.jit, static_argnums=(2,))
def _truncate(state, clock, kernel: MapKernel):
    return kernel.truncate(state, clock)


@observed_kernel("batch.map.apply_rm")
@functools.partial(jax.jit, static_argnums=(3,))
def _apply_rm(state, rm_clock, key_id, kernel: MapKernel):
    return map_ops.apply_rm(state, rm_clock, key_id, kernel.val_kernel)


@observed_kernel("batch.map.apply_up")
@functools.partial(jax.jit, static_argnums=(5, 6))
def _apply_up(state, actor_idx, counter, key_id, nested_args, nested_op, kernel):
    vk = kernel.val_kernel
    nested = getattr(vk, nested_op)
    return map_ops.apply_up(
        state, actor_idx, counter, key_id, lambda v: nested(v, *nested_args), vk
    )
