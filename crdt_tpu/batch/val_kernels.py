"""Value kernels — the batched analogue of the ``V: Val<A>`` bound.

The reference's Map accepts any causal CRDT as its value type
(`/root/reference/src/map.rs:16-25`).  On device that generic bound becomes
a *value kernel*: a small frozen (hashable, jit-static) object that knows
how to ``merge``, ``truncate`` and zero its dense value state, with every
operation rank-polymorphic over leading batch axes so the same kernel works
at any nesting depth.  :mod:`crdt_tpu.ops.map_ops` consumes these; nesting a
:class:`MapKernel` inside another reproduces ``Map<K, Map<K2, V>>``
(`/root/reference/test/map.rs:8`) as one fused XLA program per nesting shape
(SURVEY.md §7.0 "host recursion + monomorphic fused kernels").

Device protocol (value state ``v`` is a tuple-pytree; ``clock``/``overflow``
shapes follow the leading batch axes):

* ``zeros(batch_shape) -> v`` / ``zeros_like(v) -> v`` — the ``Default``
  bound (`map.rs:22`), with sentinel-aware empties (ids use ``-1``)
* ``merge(va, vb) -> (v, overflow)`` — ``CvRDT::merge``
* ``truncate(v, clock) -> (v, overflow)`` — ``Causal::truncate``; must be a
  no-op for an all-zero clock (deferred settling relies on it)

Host protocol (scalar ↔ dense conversion, parity/test path):

* ``default_scalar()`` — a fresh scalar CRDT of the value type
* ``from_scalar_vals(scalars, universe) -> v`` with leaves ``[n, *inner]``
* ``to_scalar_vals(v, universe) -> list`` of scalar CRDTs
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp

from ..config import CrdtConfig, dtype_for_bits
from ..ops import clock_ops, map_ops, mvreg_ops, orswot_ops
from ..ops.orswot_ops import EMPTY


@dataclasses.dataclass(frozen=True)
class MVRegKernel:
    """Nested multi-value register (`/root/reference/src/mvreg.rs`)."""

    mv_capacity: int
    num_actors: int
    counter_bits: int = 64

    @classmethod
    def from_config(cls, cfg: CrdtConfig) -> "MVRegKernel":
        return cls(mv_capacity=cfg.mv_capacity, num_actors=cfg.num_actors,
                   counter_bits=cfg.counter_bits)

    def zeros(self, batch_shape):
        dt = dtype_for_bits(self.counter_bits)
        return (
            jnp.zeros((*batch_shape, self.mv_capacity, self.num_actors), dt),
            jnp.zeros((*batch_shape, self.mv_capacity), dt),
        )

    def zeros_like(self, v):
        return jax.tree.map(jnp.zeros_like, v)

    def merge(self, va, vb):
        clocks, vals, keep = mvreg_ops.merge(va[0], va[1], vb[0], vb[1])
        clocks, vals, over = mvreg_ops.compact(clocks, vals, keep, self.mv_capacity)
        return (clocks, vals), over

    def truncate(self, v, clock):
        """`mvreg.rs:100-113`: subtract from every val clock, drop emptied."""
        clocks, vals = v
        new = clock_ops.subtract(clocks, clock[..., None, :])
        live = ~clock_ops.is_empty(new)
        out = (jnp.where(live[..., None], new, 0), jnp.where(live, vals, 0))
        return out, jnp.zeros(clocks.shape[:-2], bool)

    def apply_put(self, v, op_clock, op_val):
        """Nested ``Op::Put`` (`mvreg.rs:158-186`) for Map ``Op::Up``."""
        c2, v2, keep = mvreg_ops.apply_put(v[0], v[1], op_clock, op_val)
        c2, v2, over = mvreg_ops.compact(c2, v2, keep, self.mv_capacity)
        return (c2, v2), over

    # -- elastic growth (MapBatch.with_capacity) -----------------------------

    def grown(self, factor: int) -> "MVRegKernel":
        """A kernel with every capacity axis scaled by ``factor``."""
        return dataclasses.replace(self, mv_capacity=self.mv_capacity * factor)

    def unified(self, other: "MVRegKernel") -> "MVRegKernel":
        """The pointwise-max-capacity kernel covering both sides; raises on
        a structural mismatch (different type/actors/width)."""
        if (type(other) is not MVRegKernel
                or other.num_actors != self.num_actors
                or other.counter_bits != self.counter_bits):
            raise ValueError(f"incompatible value kernels: {self} vs {other}")
        return dataclasses.replace(
            self, mv_capacity=max(self.mv_capacity, other.mv_capacity)
        )

    def grow_state(self, v, target: "MVRegKernel"):
        """Pad value state built under ``self`` to ``target``'s shapes
        (new antichain slots are dead: empty clocks, zero payloads)."""
        clocks, vals = v
        pad = target.mv_capacity - self.mv_capacity
        if pad < 0:
            raise ValueError("grow_state cannot shrink")
        if pad == 0:
            return v
        return (
            jnp.pad(clocks, [(0, 0)] * (clocks.ndim - 2) + [(0, pad), (0, 0)]),
            jnp.pad(vals, [(0, 0)] * (vals.ndim - 1) + [(0, pad)]),
        )

    # -- host conversion ----------------------------------------------------

    def default_scalar(self):
        from ..scalar.mvreg import MVReg

        return MVReg()

    def scalar_val_type(self):
        """The serializable ``Map.val_type`` for this kernel (what
        ``to_binary`` can round-trip, unlike the bound factory)."""
        from ..scalar.mvreg import MVReg

        return MVReg

    def from_scalar_vals(self, scalars, universe):
        from .mvreg_batch import MVRegBatch

        b = MVRegBatch.from_scalar(list(scalars), universe)
        return (b.clocks, b.vals)

    def to_scalar_vals(self, v, universe):
        from .mvreg_batch import MVRegBatch

        return MVRegBatch(clocks=v[0], vals=v[1]).to_scalar(universe)


@dataclasses.dataclass(frozen=True)
class OrswotKernel:
    """Nested add-wins OR-Set (`/root/reference/src/orswot.rs`)."""

    member_capacity: int
    deferred_capacity: int
    num_actors: int
    counter_bits: int = 64
    # pairwise-merge implementation (orswot_ops.resolve_merge_impl):
    # "auto" resolves env override / backend default at trace time
    merge_impl: str = "auto"

    @classmethod
    def from_config(cls, cfg: CrdtConfig) -> "OrswotKernel":
        return cls(
            member_capacity=cfg.member_capacity,
            deferred_capacity=cfg.deferred_capacity,
            num_actors=cfg.num_actors,
            counter_bits=cfg.counter_bits,
            merge_impl=cfg.merge_impl,
        )

    def zeros(self, batch_shape):
        dt = dtype_for_bits(self.counter_bits)
        m, d, a = self.member_capacity, self.deferred_capacity, self.num_actors
        return (
            jnp.zeros((*batch_shape, a), dt),
            jnp.full((*batch_shape, m), EMPTY, jnp.int32),
            jnp.zeros((*batch_shape, m, a), dt),
            jnp.full((*batch_shape, d), EMPTY, jnp.int32),
            jnp.zeros((*batch_shape, d, a), dt),
        )

    def zeros_like(self, v):
        clock, ids, dots, d_ids, d_clocks = v
        return (
            jnp.zeros_like(clock),
            jnp.full_like(ids, EMPTY),
            jnp.zeros_like(dots),
            jnp.full_like(d_ids, EMPTY),
            jnp.zeros_like(d_clocks),
        )

    def merge(self, va, vb):
        out = orswot_ops.merge(
            *va, *vb, self.member_capacity, self.deferred_capacity,
            impl=self.merge_impl,
        )
        # protocol: one overflow flag per object (the Map layer has no
        # per-axis elastic recovery) — collapse the member/deferred pair
        return out[:5], jnp.any(out[5], axis=-1)

    def truncate_full(self, v, clock):
        """`orswot.rs:159-172`: merge with an empty set carrying ``clock``,
        then subtract ``clock`` from the set clock and every member clock.
        Returns the un-collapsed member/deferred overflow pair
        (``bool[..., 2]``) for callers that report per-axis overflow
        (``OrswotBatch.truncate``)."""
        empty = self.zeros_like(v)
        out = orswot_ops.merge(
            *v, clock, *empty[1:],
            self.member_capacity, self.deferred_capacity,
            impl=self.merge_impl,
        )
        mclock, ids, dots, d_ids, d_clocks = out[:5]
        over = out[5]
        mclock = clock_ops.subtract(mclock, clock)
        dots = clock_ops.subtract(dots, clock[..., None, :])
        live = ~clock_ops.is_empty(dots) & (ids != EMPTY)
        ids = jnp.where(live, ids, EMPTY)
        dots = jnp.where(live[..., None], dots, 0)
        return (mclock, ids, dots, d_ids, d_clocks), over

    def truncate(self, v, clock):
        """Protocol form: overflow collapsed to one flag per object."""
        out, over = self.truncate_full(v, clock)
        return out, jnp.any(over, axis=-1)

    def apply_add(self, v, actor_idx, counter, member_id):
        """Nested ``Op::Add`` (`orswot.rs:66-79`) for Map ``Op::Up``."""
        out = orswot_ops.apply_add(*v, actor_idx, counter, member_id)
        return out[:5], out[5]

    def apply_remove(self, v, rm_clock, member_id):
        """Nested ``Op::Rm`` (`orswot.rs:195-211`) for Map ``Op::Up``."""
        out = orswot_ops.apply_remove(*v, rm_clock, member_id)
        return out[:5], out[5]

    # -- elastic growth (MapBatch.with_capacity) -----------------------------

    def grown(self, factor: int) -> "OrswotKernel":
        return dataclasses.replace(
            self,
            member_capacity=self.member_capacity * factor,
            deferred_capacity=self.deferred_capacity * factor,
        )

    def unified(self, other: "OrswotKernel") -> "OrswotKernel":
        """See :meth:`MVRegKernel.unified`."""
        if (type(other) is not OrswotKernel
                or other.num_actors != self.num_actors
                or other.counter_bits != self.counter_bits):
            raise ValueError(f"incompatible value kernels: {self} vs {other}")
        return dataclasses.replace(
            self,
            member_capacity=max(self.member_capacity, other.member_capacity),
            deferred_capacity=max(self.deferred_capacity, other.deferred_capacity),
        )

    def grow_state(self, v, target: "OrswotKernel"):
        # one padding implementation for standalone AND map-nested sets:
        # OrswotBatch.with_capacity is rank-polymorphic over leading axes
        from .orswot_batch import OrswotBatch

        b = OrswotBatch(clock=v[0], ids=v[1], dots=v[2], d_ids=v[3],
                        d_clocks=v[4])
        g = b.with_capacity(target.member_capacity, target.deferred_capacity)
        return (g.clock, g.ids, g.dots, g.d_ids, g.d_clocks)

    # -- host conversion ----------------------------------------------------

    def default_scalar(self):
        from ..scalar.orswot import Orswot

        return Orswot()

    def scalar_val_type(self):
        """See :meth:`MVRegKernel.scalar_val_type`."""
        from ..scalar.orswot import Orswot

        return Orswot

    def from_scalar_vals(self, scalars, universe):
        from .orswot_batch import OrswotBatch

        b = OrswotBatch.from_scalar(list(scalars), universe)
        return (b.clock, b.ids, b.dots, b.d_ids, b.d_clocks)

    def to_scalar_vals(self, v, universe):
        from .orswot_batch import OrswotBatch

        return OrswotBatch(
            clock=v[0], ids=v[1], dots=v[2], d_ids=v[3], d_clocks=v[4]
        ).to_scalar(universe)


@dataclasses.dataclass(frozen=True)
class MapKernel:
    """Nested Map — recursion into :mod:`crdt_tpu.ops.map_ops`
    (`map.rs:16-25` admits another Map as ``V``)."""

    key_capacity: int
    deferred_capacity: int
    num_actors: int
    val_kernel: Any
    counter_bits: int = 64

    @classmethod
    def from_config(cls, cfg: CrdtConfig, val_kernel) -> "MapKernel":
        vk_bits = getattr(val_kernel, "counter_bits", cfg.counter_bits)
        if vk_bits != cfg.counter_bits:
            raise ValueError(
                f"value kernel counter_bits={vk_bits} != config "
                f"counter_bits={cfg.counter_bits}; nested planes must share "
                "one width (build the value kernel with from_config)"
            )
        return cls(
            key_capacity=cfg.key_capacity,
            deferred_capacity=cfg.deferred_capacity,
            num_actors=cfg.num_actors,
            val_kernel=val_kernel,
            counter_bits=cfg.counter_bits,
        )

    def zeros(self, batch_shape):
        dt = dtype_for_bits(self.counter_bits)
        k, d, a = self.key_capacity, self.deferred_capacity, self.num_actors
        return (
            jnp.zeros((*batch_shape, a), dt),
            jnp.full((*batch_shape, k), EMPTY, jnp.int32),
            jnp.zeros((*batch_shape, k, a), dt),
            self.val_kernel.zeros((*batch_shape, k)),
            jnp.full((*batch_shape, d), EMPTY, jnp.int32),
            jnp.zeros((*batch_shape, d, a), dt),
        )

    def zeros_like(self, v):
        clock, keys, eclocks, vals, d_keys, d_clocks = v
        return (
            jnp.zeros_like(clock),
            jnp.full_like(keys, EMPTY),
            jnp.zeros_like(eclocks),
            self.val_kernel.zeros_like(vals),
            jnp.full_like(d_keys, EMPTY),
            jnp.zeros_like(d_clocks),
        )

    def merge(self, va, vb):
        return map_ops.merge(
            va, vb, self.val_kernel, self.key_capacity, self.deferred_capacity
        )

    def truncate(self, v, clock):
        return map_ops.truncate(v, clock, self.val_kernel)

    # -- elastic growth (MapBatch.with_capacity) -----------------------------

    def grown(self, factor: int) -> "MapKernel":
        """Scale every capacity axis — key, deferred, and the nested value
        kernel's — by ``factor``.  The Map merge's overflow flag is
        collapsed (key / deferred / nested value), so elastic recovery
        grows the whole capacity envelope together."""
        return dataclasses.replace(
            self,
            key_capacity=self.key_capacity * factor,
            deferred_capacity=self.deferred_capacity * factor,
            val_kernel=self.val_kernel.grown(factor),
        )

    def unified(self, other: "MapKernel") -> "MapKernel":
        """Pointwise-max capacities, recursing into the value kernel."""
        if (type(other) is not MapKernel
                or other.num_actors != self.num_actors
                or other.counter_bits != self.counter_bits):
            raise ValueError(f"incompatible value kernels: {self} vs {other}")
        return dataclasses.replace(
            self,
            key_capacity=max(self.key_capacity, other.key_capacity),
            deferred_capacity=max(self.deferred_capacity, other.deferred_capacity),
            val_kernel=self.val_kernel.unified(other.val_kernel),
        )

    def grow_state(self, v, target: "MapKernel"):
        clock, keys, eclocks, vals, d_keys, d_clocks = v
        pk = target.key_capacity - self.key_capacity
        pd = target.deferred_capacity - self.deferred_capacity
        if pk < 0 or pd < 0:
            raise ValueError("grow_state cannot shrink")

        def pad_axis(x, ax, pad, fill=0):
            if pad == 0:
                return x
            cfg = [(0, 0)] * x.ndim
            cfg[ax] = (0, pad)
            return jnp.pad(x, cfg, constant_values=fill)

        keys = pad_axis(keys, keys.ndim - 1, pk, EMPTY)
        eclocks = pad_axis(eclocks, eclocks.ndim - 2, pk)
        d_keys = pad_axis(d_keys, d_keys.ndim - 1, pd, EMPTY)
        d_clocks = pad_axis(d_clocks, d_clocks.ndim - 2, pd)
        # value leaves: new key slots filled with the value kernel's empty
        # state, then the nested capacity axes grown leaf-wise
        key_ax = keys.ndim - 1
        if pk:
            batch_shape = keys.shape[:-1] + (pk,)
            empties = self.val_kernel.zeros(batch_shape)
            vals = jax.tree.map(
                lambda x, e: jnp.concatenate([x, e], axis=key_ax), vals, empties
            )
        vals = self.val_kernel.grow_state(vals, target.val_kernel)
        return (clock, keys, eclocks, vals, d_keys, d_clocks)

    # -- host conversion ----------------------------------------------------

    def default_scalar(self):
        from ..scalar.map import Map

        return Map(self.val_kernel.default_scalar)

    def scalar_val_type(self):
        """Nested maps serialize their val_type as ``MapOf(inner)``."""
        from ..utils.serde import MapOf

        return MapOf(self.val_kernel.scalar_val_type())

    def from_scalar_vals(self, scalars, universe):
        from .map_batch import MapBatch

        b = MapBatch.from_scalar(list(scalars), universe, self.val_kernel)
        return b.state

    def to_scalar_vals(self, v, universe):
        from .map_batch import MapBatch

        return MapBatch.from_state(v, self).to_scalar(universe)


# -- kernel (de)serialization for checkpoints --------------------------------

_KERNEL_CLASSES = {
    "MVRegKernel": MVRegKernel,
    "OrswotKernel": OrswotKernel,
    "MapKernel": MapKernel,
}


def kernel_to_spec(kernel) -> dict:
    """A plain-dict description of a (possibly nested) value kernel, for the
    checkpoint metadata blob (`crdt_tpu.utils.checkpoint`)."""
    spec = {"cls": type(kernel).__name__}
    for f in dataclasses.fields(kernel):
        v = getattr(kernel, f.name)
        spec[f.name] = kernel_to_spec(v) if dataclasses.is_dataclass(v) else v
    return spec


def kernel_from_spec(spec: dict):
    """Inverse of :func:`kernel_to_spec`."""
    cls = _KERNEL_CLASSES[spec["cls"]]
    kwargs = {
        k: (kernel_from_spec(v) if isinstance(v, dict) else v)
        for k, v in spec.items()
        if k != "cls"
    }
    return cls(**kwargs)
