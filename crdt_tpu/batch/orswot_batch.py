"""OrswotBatch — N add-wins OR-sets on device (the flagship type).

Dense form of `/root/reference/src/orswot.rs:26-30`: set clock, member-slot
tables (interned ids + per-member dot clocks) and a deferred-remove table.
``merge`` runs the vectorized dot-algebra kernel
(:func:`crdt_tpu.ops.orswot_ops.merge`); the op path (`apply_add` /
`apply_remove`) applies one op per object across the batch.
"""

from __future__ import annotations

import functools
from typing import Sequence

import jax
import jax.numpy as jnp
from flax import struct

from ..config import counter_dtype
from ..error import CapacityOverflowError, raise_for_overflow
from ..ops import orswot_ops
from ..scalar.orswot import Orswot
from ..scalar.vclock import VClock
from ..utils.interning import Universe
from ..utils.hostmem import gc_paused
from ..obs.kernels import observed_kernel
from .vclock_batch import VClockBatch


def _np_planes(n, cfg):
    """Empty dense planes ``(clock, ids, dots, d_ids, d_clocks)`` as numpy
    arrays — the one place the shape/dtype/fill scheme lives (``zeros``
    and both bulk-ingest paths build on it)."""
    import numpy as np

    a, m, d = cfg.num_actors, cfg.member_capacity, cfg.deferred_capacity
    dt = counter_dtype(cfg)
    return (
        np.zeros((n, a), dtype=dt),
        np.full((n, m), orswot_ops.EMPTY, dtype=np.int32),
        np.zeros((n, m, a), dtype=dt),
        np.full((n, d), orswot_ops.EMPTY, dtype=np.int32),
        np.zeros((n, d, a), dtype=dt),
    )


def _next_pow2(c: int) -> int:
    return 1 if c <= 0 else 1 << (c - 1).bit_length()


# host-path egress slice size: per-call conversion cost grows superlinearly
# past a few hundred thousand objects (measured 2.5x at 1M vs 4x250k with
# identical final heap — INGEST_PROFILE.md), so to_scalar converts fleets
# in slices of this many objects
_EGRESS_SLICE = 250_000


def _resolve_members(universe, id_array):
    """Member-name resolution for a cell column: one registry lookup per
    UNIQUE id present, plus the inverse index per cell.  Shared by the
    Python egress loop and the native extension (same parity reason as
    ``OrswotBatch._actor_names``)."""
    import numpy as np

    uniq, inv = np.unique(id_array, return_inverse=True)
    member_of = universe.members.lookup
    return [member_of(int(m)) for m in uniq], inv


def _on_accelerator(x) -> bool:
    try:
        return any(dev.platform != "cpu" for dev in x.devices())
    except Exception:
        return False


@observed_kernel("batch.orswot.device_nnz")
@jax.jit
def _device_nnz(clock, ids, dots, d_ids, d_clocks):
    """Populated-cell counts for the five planes, as one tiny fetch."""
    return jnp.stack(
        [
            jnp.count_nonzero(clock),
            jnp.sum(ids != orswot_ops.EMPTY),
            jnp.count_nonzero(dots),
            jnp.sum(d_ids != orswot_ops.EMPTY),
            jnp.count_nonzero(d_clocks),
        ]
    ).astype(jnp.int64)


@observed_kernel("batch.orswot.device_compact")
@functools.partial(jax.jit, static_argnames=("sizes", "with_entries"))
def _device_compact(clock, ids, dots, d_ids, d_clocks, sizes,
                    with_entries=True):
    """Size-bounded sparsification ON DEVICE: only compact coordinate
    columns ever cross the host boundary (the axon tunnel moves dense
    planes at ~10 MB/s, so dense `np.asarray` egress costs minutes at 1M
    objects — `reports/INGEST_PROFILE.md`).  ``jnp.nonzero(size=k)``
    keeps numpy's row-major cell order (objects ascending, slots within),
    which the scalar reconstruction relies on; padding rows land at the
    END of each column and the caller trims them with the exact counts
    from :func:`_device_nnz`.  Indices are narrowed to int32 (N ≤ 2^31)
    to halve transfer bytes."""
    kc, ke, kd, kq, kh = sizes
    i32 = lambda *xs: tuple(x.astype(jnp.int32) for x in xs)  # noqa: E731
    co, ca = jnp.nonzero(clock, size=kc, fill_value=0)
    if with_entries:
        eo, es = jnp.nonzero(ids != orswot_ops.EMPTY, size=ke, fill_value=0)
        entries = i32(eo, es) + (ids[eo, es],)
    else:
        # `to_coo` reconstructs member ids from the dot bundle; skipping
        # the entry pass saves both the device nonzero and its transfer
        z = jnp.zeros((0,), jnp.int32)
        entries = (z, z, jnp.zeros((0,), ids.dtype))
    do, ds, da = jnp.nonzero(dots, size=kd, fill_value=0)
    qo, qr = jnp.nonzero(d_ids != orswot_ops.EMPTY, size=kq, fill_value=0)
    ho, hr, ha = jnp.nonzero(d_clocks, size=kh, fill_value=0)
    return (
        i32(co, ca) + (clock[co, ca],),
        entries,
        i32(do, ds) + (ids[do, ds], da.astype(jnp.int32), dots[do, ds, da]),
        i32(qo, qr) + (d_ids[qo, qr],),
        i32(ho, hr, ha) + (d_clocks[ho, hr, ha],),
    )


def _pad_cols(cols, k, id_fill=False):
    """Right-pad coordinate columns to length ``k`` with scatter-neutral
    rows: coordinate 0 everywhere, value 0 (counters) or EMPTY (id
    planes) — both are identities for the ``max`` scatter the expander
    uses, so padding never perturbs the planes while keeping the jit
    cache keyed on power-of-two sizes only."""
    import numpy as np

    out = []
    for j, c in enumerate(cols):
        is_val = j == len(cols) - 1
        # coordinate columns must be integer indexers on device; callers
        # may pass Python lists or empty arrays (np.asarray([]) is
        # float64).  Value columns arrive pre-cast to their plane dtype.
        c = np.asarray(c) if is_val else np.asarray(c, dtype=np.int32)
        fill = orswot_ops.EMPTY if (is_val and id_fill) else 0
        pad = np.full(k - c.shape[0], fill, dtype=c.dtype)
        out.append(np.concatenate([c, pad]) if k > c.shape[0] else c)
    return tuple(out)


@observed_kernel("batch.orswot.device_expand")
@functools.partial(jax.jit, static_argnames=("n", "a", "m", "d"))
def _device_expand(cells, n, a, m, d):
    """Inverse of :func:`_device_compact`: max-scatter compact columns
    into dense planes ON DEVICE, so ingest ships columns (~200× smaller
    than dense state at reference-shaped sparsity) instead of dense
    planes through the tunnel.  ``max`` is the right scatter everywhere:
    counter cells join by the lattice rule, and id planes start at
    EMPTY = -1 with real ids ≥ 0 written at most once per slot (host-side
    validation), so ``max`` equals assignment while padding rows
    (value EMPTY) are no-ops."""
    (co, ca, cc), (eo, es, em), (do, ds, da, dc), (qo, qr, qm), (ho, hr, ha, hc) = cells
    dt = cc.dtype
    return (
        jnp.zeros((n, a), dt).at[co, ca].max(cc),
        jnp.full((n, m), orswot_ops.EMPTY, jnp.int32).at[eo, es].max(em.astype(jnp.int32)),
        jnp.zeros((n, m, a), dt).at[do, ds, da].max(dc),
        jnp.full((n, d), orswot_ops.EMPTY, jnp.int32).at[qo, qr].max(qm.astype(jnp.int32)),
        jnp.zeros((n, d, a), dt).at[ho, hr, ha].max(hc),
    )


def _build_planes(n, cfg, clock_cells, entry_cells, dot_cells, dref_cells,
                  dclk_cells, via_device=None, join_counters=False):
    """Shared ingest tail: scatter validated coordinate groups into the
    five dense planes.  ``via_device=True`` pads the columns to
    power-of-two lengths and max-scatters ON DEVICE
    (:func:`_device_expand`) so only compact columns cross the tunnel;
    the host path is the original vectorized numpy scatter —
    plain assignment when the caller guarantees unique coordinates
    (``join_counters=False``; ``np.ufunc.at`` is far slower), lattice
    ``np.maximum.at`` when duplicates must join by max.  Callers must
    pass value columns already cast to their plane dtype (counter dtype
    / int32 ids) — padding derives its dtype from the column."""
    import numpy as np

    if via_device is None:
        via_device = jax.default_backend() != "cpu"
    a, m, d = cfg.num_actors, cfg.member_capacity, cfg.deferred_capacity

    if via_device:
        # device scatter-max joins duplicates either way, matching both
        # callers (unique coords are a special case of max-join)
        padded = tuple(
            _pad_cols(
                tuple(np.ascontiguousarray(np.asarray(c)) for c in cols),
                _next_pow2(np.asarray(cols[0]).shape[0]),
                id_fill=id_fill,
            )
            for cols, id_fill in (
                (clock_cells, False),
                (entry_cells, True),
                (dot_cells, False),
                (dref_cells, True),
                (dclk_cells, False),
            )
        )
        return _device_expand(padded, n=n, a=a, m=m, d=d)

    clock, ids, dots, d_ids, d_clocks = _np_planes(n, cfg)

    def scatter(plane, idx, vals):
        if join_counters:
            np.maximum.at(plane, idx, vals)
        else:
            plane[idx] = vals

    co, ca, cc = (np.asarray(x) for x in clock_cells)
    if co.size:
        scatter(clock, (co, ca), cc)
    eo, es, em = (np.asarray(x) for x in entry_cells)
    if eo.size:
        ids[eo, es] = em
    do, ds, da, dc = (np.asarray(x) for x in dot_cells)
    if do.size:
        scatter(dots, (do, ds, da), dc)
    qo, qr, qm = (np.asarray(x) for x in dref_cells)
    if qo.size:
        d_ids[qo, qr] = qm
    ho, hr, ha, hc = (np.asarray(x) for x in dclk_cells)
    if ho.size:
        scatter(d_clocks, (ho, hr, ha), hc)
    return tuple(jnp.asarray(x) for x in (clock, ids, dots, d_ids, d_clocks))


@struct.dataclass
class OrswotBatch:
    clock: jax.Array  # u64[N, A]
    ids: jax.Array  # int32[N, M]  (-1 = empty)
    dots: jax.Array  # u64[N, M, A]
    d_ids: jax.Array  # int32[N, D] (-1 = empty)
    d_clocks: jax.Array  # u64[N, D, A]

    @classmethod
    def zeros(cls, n: int, universe: Universe) -> "OrswotBatch":
        return cls(*(jnp.asarray(x) for x in _np_planes(n, universe.config)))

    @classmethod
    @gc_paused
    def from_scalar(
        cls, states: Sequence[Orswot], universe: Universe,
        via_device: bool | None = None,
    ) -> "OrswotBatch":
        """Bulk ingest: one Python pass per object collects the flat COO
        value columns with C-level ``list.extend(map(...))`` loops — never
        a per-dot Python append — plus per-object/per-entry *counts*; the
        (object, slot) coordinate columns are then synthesized in bulk
        with ``np.repeat``/``np.arange`` and the scatters build the dense
        tables — on device when the backend is an accelerator, so only
        compact columns cross the tunnel (:func:`_build_planes`).  The
        per-dot Python bytecode of the append-based walk is what bounded
        ingest at ~30k obj/s at 1M scale (``bench.py`` ``ingest`` line);
        this path keeps the unavoidable O(total dots) work in C."""
        import numpy as np

        cfg = universe.config
        n = len(states)
        m, d = cfg.member_capacity, cfg.deferred_capacity
        dt = counter_dtype(cfg)
        aidx = universe.actors.intern
        midx = universe.members.intern

        ca, cc = [], []  # set-clock columns (actor, counter)
        c_counts = np.empty(n, dtype=np.int64)  # clock dots per object
        em = []  # entry member ids, object-major / insertion order
        e_counts = np.empty(n, dtype=np.int64)  # entries per object
        ga, gc = [], []  # entry-dot columns (actor, counter)
        g_counts = []  # dots per entry, aligned with em
        qm = []  # deferred member ids
        q_counts = np.empty(n, dtype=np.int64)  # deferred rows per object
        ha, hc = [], []  # deferred-clock columns
        h_counts = []  # clock dots per deferred row, aligned with qm

        for i, s in enumerate(states):
          try:
            cd = s.clock.dots
            c_counts[i] = len(cd)
            ca.extend(map(aidx, cd))
            cc.extend(cd.values())

            ents = s.entries
            if len(ents) > m:
                raise ValueError(
                    f"object {i}: {len(ents)} members > member_capacity {m}"
                )
            e_counts[i] = len(ents)
            em.extend(map(midx, ents))
            for vc in ents.values():
                vd = vc.dots
                g_counts.append(len(vd))
                ga.extend(map(aidx, vd))
                gc.extend(vd.values())

            nrows = sum(len(members) for members in s.deferred.values())
            if nrows > d:
                raise ValueError(
                    f"object {i}: {nrows} deferred rows > deferred_capacity {d}"
                )
            q_counts[i] = nrows
            for ck, members in s.deferred.items():
                # one interned column pair per witnessing clock, shared by
                # every member row buffered under it
                pa = [aidx(actor) for actor, _ in ck]
                pc = [counter for _, counter in ck]
                for member in members:
                    qm.append(midx(member))
                    h_counts.append(len(pa))
                    ha.extend(pa)
                    hc.extend(pc)
          except AttributeError as e:
            # a decodable-but-wrong-typed object graph (e.g. a corrupted
            # from_binary payload whose tag flip decoded a GCounter where
            # a VClock belongs, or a ctx type where an Orswot belongs)
            # surfaces as the documented contract exception, not a raw
            # AttributeError (found by the wire mutation fuzz)
            raise TypeError(
                f"object {i}: malformed scalar state "
                f"({type(s).__name__}: {e})"
            ) from None

        def _obj_slot(counts):
            """(object, within-object slot) coordinate columns for rows
            laid out object-major with ``counts`` rows per object."""
            obj = np.repeat(np.arange(counts.shape[0]), counts)
            starts = np.repeat(np.cumsum(counts) - counts, counts)
            return obj, np.arange(obj.shape[0]) - starts

        ei = np.zeros(0, dtype=np.int64)
        ev = np.zeros(0, dtype=dt)
        em32 = np.zeros(0, dtype=np.int32)
        clock_cells = (ei, ei, ev)
        entry_cells = (ei, ei, em32)
        dot_cells = (ei, ei, ei, ev)
        dref_cells = (ei, ei, em32)
        dclk_cells = (ei, ei, ei, ev)
        if ca:
            co = np.repeat(np.arange(n), c_counts)
            clock_cells = (co, np.asarray(ca), np.asarray(cc, dtype=dt))
        if em:
            eo, es = _obj_slot(e_counts)
            entry_cells = (eo, es, np.asarray(em, dtype=np.int32))
            if ga:
                g_counts_arr = np.asarray(g_counts)
                go = np.repeat(eo, g_counts_arr)
                gs = np.repeat(es, g_counts_arr)
                dot_cells = (go, gs, np.asarray(ga), np.asarray(gc, dtype=dt))
        if qm:
            qo, qs = _obj_slot(q_counts)
            dref_cells = (qo, qs, np.asarray(qm, dtype=np.int32))
            if ha:
                h_counts_arr = np.asarray(h_counts)
                ho = np.repeat(qo, h_counts_arr)
                hs = np.repeat(qs, h_counts_arr)
                dclk_cells = (ho, hs, np.asarray(ha), np.asarray(hc, dtype=dt))

        return cls(
            *_build_planes(
                n, cfg, clock_cells, entry_cells, dot_cells, dref_cells,
                dclk_cells, via_device=via_device,
            )
        )

    @classmethod
    @gc_paused
    def from_wire(
        cls, blobs: Sequence[bytes], universe: Universe,
        via_device: bool | None = None,
    ) -> "OrswotBatch":
        """Bulk ingest straight from wire blobs (``to_binary(orswot)``
        payloads — the replication format, replacing the reference's host
        serde `lib.rs:62-83` as the bulk path).

        Fast path: with an **identity universe** (``Universe.identity`` —
        int actors < ``num_actors``, int32 members) and the native engine
        available, the blobs are parsed IN PARALLEL by the C++ decoder
        (`crdt_tpu/native/wire_ingest.cpp`) directly into dense planes —
        no Python objects, no per-value interning; measured ≥10× the
        ``from_binary``+``from_scalar`` walk at 1M objects.  Blobs
        outside the integer-keyed grammar (string members, big-int
        counters) fall back to the Python decoder per blob, so the fast
        path never changes semantics — ``from_wire(blobs, uni)`` always
        equals ``from_scalar([from_binary(b) for b in blobs], uni)``.

        Without an identity universe (arbitrary hashable actors/members)
        or without the native engine, the whole batch takes the Python
        path.

        ``via_device`` (default: True on accelerator backends) routes the
        parsed state through compact COO columns and a device-side dense
        expand (:meth:`from_coo`) instead of shipping dense planes — the
        axon tunnel moves dense data at ~10 MB/s, so a 1M-object fleet's
        ~325 MB of planes would cost ~30 s while its ~16 MB of columns
        cost ~2 s.  The device route canonicalizes member-slot order
        (ascending id), which is semantically identical."""
        from ..utils.serde import from_binary
        from .wirebulk import orswot_planes_from_wire

        n = len(blobs)
        if n == 0:
            return cls.zeros(0, universe)
        planes = orswot_planes_from_wire(blobs, universe)
        if planes is None:
            # no native fast path (engine missing / non-identity
            # universe): the whole batch decodes in Python
            return cls.from_scalar(
                [from_binary(b) for b in blobs], universe
            )
        clock, ids, dots, d_ids, d_clocks = planes
        if via_device is None:
            via_device = jax.default_backend() != "cpu"
        if via_device:
            # compact columns + device-side expand: dense planes never
            # transit the tunnel (they are ~20x the column bytes).  The
            # extraction reuses to_coo's host path — one sparsification
            # implementation to maintain — and from_coo's slot assignment
            # canonicalizes member order (ascending id), which is
            # semantically identical to the wire order the host route
            # preserves.
            tmp = cls(clock=clock, ids=ids, dots=dots, d_ids=d_ids,
                      d_clocks=d_clocks)
            clock_coords, dot_coords, q, h = tmp.to_coo(via_device=False)
            kwargs = {}
            if q[0].size:
                kwargs = {"deferred_members": q, "deferred_coords": h}
            return cls.from_coo(
                n, universe, clock_coords=clock_coords,
                dot_coords=dot_coords, via_device=True, **kwargs,
            )
        return cls(
            clock=jnp.asarray(clock), ids=jnp.asarray(ids),
            dots=jnp.asarray(dots), d_ids=jnp.asarray(d_ids),
            d_clocks=jnp.asarray(d_clocks),
        )

    @gc_paused
    def to_wire(self, universe: Universe) -> list[bytes]:
        """Bulk egress to wire blobs — the inverse of :meth:`from_wire`,
        byte-identical to ``[to_binary(s) for s in self.to_scalar(uni)]``.

        Fast path (identity universe + native engine): the parallel C++
        encoder (`crdt_tpu/native/wire_ingest.cpp`) serializes the dense
        planes directly — no scalar objects; the deterministic orderings
        of the serde codec (encoded-bytes pair sort, repr-sorted clock
        keys) are reproduced exactly.  Counters at or above 2^63 (u64
        planes only) and non-identity universes take the Python path."""
        import numpy as np

        from ..utils.serde import to_binary
        from .wirebulk import orswot_planes_to_wire

        n = self.clock.shape[0]
        if n == 0:
            return []
        blobs = orswot_planes_to_wire(
            np.asarray(self.clock), np.asarray(self.ids),
            np.asarray(self.dots), np.asarray(self.d_ids),
            np.asarray(self.d_clocks), universe,
        )
        if blobs is None:
            return [to_binary(s) for s in self.to_scalar(universe)]
        return blobs

    @classmethod
    def from_coo(
        cls, n: int, universe: Universe, *,
        clock_coords, dot_coords, deferred_members=None, deferred_coords=None,
        via_device: bool | None = None,
    ) -> "OrswotBatch":
        """Columnar bulk ingest — build ``n`` dense states straight from
        COO coordinate arrays, without materializing any scalar objects
        (the per-object Python walk is what bounds :meth:`from_scalar` at
        ~130k obj/s — ``reports/INGEST_PROFILE.md``).  Validation and
        slot assignment stay host-side on the compact columns; the dense
        scatter runs on device on accelerator backends
        (:func:`_build_planes`), so dense planes never transit the
        tunnel.

        * ``clock_coords`` — ``(obj, actor_idx, counter)`` arrays for the
          set clocks.
        * ``dot_coords`` — ``(obj, member_id, actor_idx, counter)`` arrays
          for the member dot clocks; member slots are assigned per object
          in ascending member-id order (the engine's canonical order).
        * ``deferred_members`` — optional ``(obj, row, member_id)`` arrays;
          ``deferred_coords`` — ``(obj, row, actor_idx, counter)`` arrays
          giving each deferred row's witnessing clock.  Rows index the
          deferred table directly (a row is one buffered
          (member, clock) remove, `orswot.rs:29`).

        Duplicate *counter* coordinates (clock, dot, deferred-clock cells)
        join by ``max`` — the lattice's own rule, so re-ingesting
        overlapping exports is idempotent.  ``deferred_members`` rows are
        assignments, not lattice cells: two entries naming the same
        ``(obj, row)`` with different member ids are a conflict and raise.
        Actor indices must already be dense (``universe.actor_idx``);
        member ids are the interned int32 ids (``universe.member_id``).
        Raises ``ValueError`` on a negative member id (the ``EMPTY``
        sentinel leaking from an upstream export) in either ``dot_coords``
        or ``deferred_members``, when an object's distinct members exceed
        ``member_capacity``, when a deferred row index falls outside
        ``[0, deferred_capacity)``, or when only one of the two deferred
        argument pairs is supplied."""
        import numpy as np

        cfg = universe.config
        m, d = cfg.member_capacity, cfg.deferred_capacity
        dt = counter_dtype(cfg)
        ei = np.zeros(0, dtype=np.int64)
        ev = np.zeros(0, dtype=dt)
        em32 = np.zeros(0, dtype=np.int32)
        entry_cells = (ei, ei, em32)
        dot_cells = (ei, ei, ei, ev)
        dref_cells = (ei, ei, em32)
        dclk_cells = (ei, ei, ei, ev)

        co, ca, cc = (np.asarray(x) for x in clock_coords)
        clock_cells = (co, ca, cc.astype(dt))

        do, dm, da, dc = (np.asarray(x) for x in dot_coords)
        if do.size:
            if dm.min(initial=0) < 0:
                raise ValueError(
                    f"negative member id {int(dm.min())} in dot_coords "
                    "(EMPTY sentinel leaking from an export?)"
                )
            # slot assignment: unique (obj, member) pairs, ascending member
            # id within each object — np.unique's lexicographic sort gives
            # exactly that, and searchsorted ranks each pair within its
            # object's group
            pair_key = do.astype(np.int64) * (1 << 32) + dm.astype(np.int64)
            uniq, inv = np.unique(pair_key, return_inverse=True)
            uo = (uniq >> 32).astype(np.int64)
            um = (uniq & ((1 << 32) - 1)).astype(np.int32)
            slot = np.arange(uniq.size) - np.searchsorted(uo, uo)
            counts = np.bincount(uo, minlength=n)
            if counts.max(initial=0) > m:
                bad = int(np.argmax(counts))
                raise ValueError(
                    f"object {bad}: {int(counts[bad])} members > member_capacity {m}"
                )
            entry_cells = (uo, slot, um)
            dot_cells = (do, slot[inv], da, dc.astype(dt))

        if (deferred_members is None) != (deferred_coords is None):
            raise ValueError(
                "deferred_members and deferred_coords must be supplied together "
                "(a deferred row is a (member, clock) pair)"
            )
        if deferred_members is not None:
            def _check_rows(rows, label):
                if rows.size and (rows.min() < 0 or rows.max() >= d):
                    raise ValueError(
                        f"{label} row indices must lie in [0, "
                        f"deferred_capacity={d}); got "
                        f"[{int(rows.min())}, {int(rows.max())}]"
                    )

            qo, qr, qm = (np.asarray(x) for x in deferred_members)
            _check_rows(qr, "deferred_members")
            if qo.size:
                if qm.min(initial=0) < 0:
                    raise ValueError(
                        f"negative member id {int(qm.min())} in "
                        "deferred_members (EMPTY sentinel leaking from an "
                        "export?) — the row would be invisible to kernels "
                        "while its clock still scatters into d_clocks"
                    )
                # duplicate (obj, row) keys are assignments, not lattice
                # cells: silently last-write-winning would drop a remove
                key = qo.astype(np.int64) * d + qr.astype(np.int64)
                order = np.argsort(key, kind="stable")
                sk, sm = key[order], qm[order]
                dup = sk[1:] == sk[:-1]
                if np.any(dup & (sm[1:] != sm[:-1])):
                    i = int(np.nonzero(dup & (sm[1:] != sm[:-1]))[0][0])
                    raise ValueError(
                        f"conflicting deferred_members assignments for "
                        f"(obj={int(sk[i]) // d}, row={int(sk[i]) % d}): "
                        f"member ids {int(sm[i])} and {int(sm[i + 1])}"
                    )
                dref_cells = (qo, qr, qm.astype(np.int32))
            ho, hr, ha, hc = (np.asarray(x) for x in deferred_coords)
            _check_rows(hr, "deferred_coords")
            if ho.size:
                dclk_cells = (ho, hr, ha, hc.astype(dt))

        return cls(
            *_build_planes(
                n, cfg, clock_cells, entry_cells, dot_cells, dref_cells,
                dclk_cells, via_device=via_device, join_counters=True,
            )
        )

    def _cells(self, via_device: bool | None = None, want_entries: bool = True):
        """The five populated-cell coordinate bundles — clock, entry ids,
        entry dots (slot AND member id), deferred ids, deferred clocks —
        as host numpy columns.  When the planes live on an accelerator
        (auto-detected), sparsification runs ON DEVICE
        (:func:`_device_compact`) and only compact columns cross the
        tunnel; on CPU the same bundles come from ``np.nonzero``
        directly.  Both paths emit cells in row-major order.
        ``want_entries=False`` returns an empty entry bundle without
        computing or transferring it (``to_coo`` derives member ids from
        the dot bundle instead)."""
        import numpy as np

        if via_device is None:
            via_device = _on_accelerator(self.clock)
        planes = (self.clock, self.ids, self.dots, self.d_ids, self.d_clocks)
        if via_device:
            counts = [int(c) for c in np.asarray(_device_nnz(*planes))]  # crdtlint: disable=SC03 — snapshot sparsify sizes become statics, host fetch is the point
            if not want_entries:
                counts[1] = 0
            sizes = tuple(_next_pow2(c) for c in counts)
            bundles = jax.device_get(
                _device_compact(*planes, sizes=sizes, with_entries=want_entries)
            )
            return tuple(
                tuple(col[:c] for col in b) for b, c in zip(bundles, counts)
            )
        clock, ids, dots, d_ids, d_clocks = (np.asarray(x) for x in planes)
        co, ca = np.nonzero(clock)
        if want_entries:
            eo, es = np.nonzero(ids != orswot_ops.EMPTY)
            entries = (eo, es, ids[eo, es])
        else:
            z = np.zeros(0, dtype=np.int64)
            entries = (z, z, np.zeros(0, dtype=ids.dtype))
        do, ds, da = np.nonzero(dots)
        qo, qr = np.nonzero(d_ids != orswot_ops.EMPTY)
        ho, hr, ha = np.nonzero(d_clocks)
        return (
            (co, ca, clock[co, ca]),
            entries,
            (do, ds, ids[do, ds], da, dots[do, ds, da]),
            (qo, qr, d_ids[qo, qr]),
            (ho, hr, ha, d_clocks[ho, hr, ha]),
        )

    def to_coo(self, via_device: bool | None = None):
        """Columnar bulk egress — the inverse of :meth:`from_coo`: four
        coordinate-array tuples of populated cells (no Python objects;
        pair with :meth:`from_coo` for checkpoint-scale export of live
        fleets).  Returns ``(clock_coords, dot_coords, deferred_members,
        deferred_coords)``.  On an accelerator backend the
        sparsification runs on device and only compact columns transfer
        (see :meth:`_cells`)."""
        (co, ca, cv), _e, (do, _ds, dm, da, dv), q, h = self._cells(
            via_device, want_entries=False
        )
        return ((co, ca, cv), (do, dm, da, dv), q, h)

    @gc_paused
    def _actor_names(self, universe: Universe) -> list:
        """Per-actor-column names, hoisted out of the per-cell loops: the
        actor universe is dense (one list index per cell instead of a
        method call; only interned columns can carry data, the rest stay
        None).  Shared by the Python egress loop and the native
        extension so the two resolutions can never diverge."""
        n_interned = len(universe.actors)
        return [
            universe.actors.lookup(i) if i < n_interned else None
            for i in range(self.clock.shape[1])
        ]

    def to_scalar(
        self, universe: Universe, via_device: bool | None = None
    ) -> list[Orswot]:
        """Bulk egress: :meth:`_cells` extracts every populated cell in
        five vectorized passes (on device when the planes live on an
        accelerator — dense planes never cross the tunnel); the Python
        loop only walks actual dots (sparse), never the dense
        ``[N, M, A]`` volume.

        Host-path fleets convert in bounded slices: one monolithic pass
        measured 2.5× SLOWER at 1M than the same work in 250k slices
        (51k vs 128k obj/s, outputs all kept live either way — the cost
        grows superlinearly with per-call size, not with the resulting
        heap; `reports/INGEST_PROFILE.md` reproduction section)."""
        import numpy as np

        from ..scalar.vclock import VClock

        if via_device is None:
            via_device = _on_accelerator(self.clock)
        n_total = self.clock.shape[0]

        if not via_device and n_total > _EGRESS_SLICE * 3 // 2:
            # numpy views, not jnp slicing: one zero-copy np.asarray per
            # plane, then each slice is a view — no XLA slice dispatch or
            # per-slice plane copies
            planes = tuple(
                np.asarray(x)
                for x in (self.clock, self.ids, self.dots,
                          self.d_ids, self.d_clocks)
            )
            out: list = []
            s0 = 0
            while s0 < n_total:
                # a short final remainder (< slice/2) merges into this
                # slice instead of becoming a tiny ragged call
                end = s0 + _EGRESS_SLICE
                if n_total - end < _EGRESS_SLICE // 2:
                    end = n_total
                sub = OrswotBatch(*(p[s0:end] for p in planes))
                out.extend(sub.to_scalar(universe, via_device=False))
                s0 = end
            return out

        # native fast path: hand the cell bundles to the C extension,
        # which constructs the Orswot/VClock objects through the C API
        # (no interpreter frames per object).  Names are resolved
        # host-side — one registry lookup per actor column / unique
        # member id — so interned and identity universes both apply.
        # Measured >=3x the Python loop (VERDICT r4 item 6).
        if n_total > 0:
            try:
                from ..native import scalarize

                ext = scalarize.load()
            except (RuntimeError, OSError):
                ext = None
            if ext is not None:
                from ..scalar.orswot import Orswot as _Ors

                cells = self._cells(via_device)
                (co, ca, cv), (eo, es, em), (do, ds, _dm, da, dv), (
                    qo, qr, qm,
                ), (ho, hr, ha, hv) = cells
                actor_name = self._actor_names(universe)
                uniq_names, inv = _resolve_members(universe, em)
                q_names, q_inv = _resolve_members(universe, qm)
                i64 = lambda x: np.ascontiguousarray(x, dtype=np.int64)
                u64 = lambda x: np.ascontiguousarray(x, dtype=np.uint64)
                return ext.orswot_from_cells(
                    _Ors, VClock, n_total, actor_name,
                    i64(co), i64(ca), u64(cv),
                    i64(eo), i64(es), uniq_names, i64(inv),
                    i64(do), i64(ds), i64(da), u64(dv),
                    i64(qo), i64(qr), q_names, i64(q_inv),
                    i64(ho), i64(hr), i64(ha), u64(hv),
                )

        cells = self._cells(via_device)
        (co, ca, cv), (eo, es, em), (do, ds, _dm, da, dv), (qo, qr, qm), (
            ho, hr, ha, hv,
        ) = cells

        n = self.clock.shape[0]
        # registry lookups hoisted out of the per-cell loops (shared with
        # the native fast path above so the two can never diverge)
        actor_name = self._actor_names(universe)
        out = [Orswot() for _ in range(n)]

        for i, aix, v in zip(co.tolist(), ca.tolist(), cv.tolist()):
            out[i].clock.dots[actor_name[aix]] = v

        # entries in slot order (both cell paths emit row-major order),
        # matching the insertion order the naive path produced
        uniq_names, inv = _resolve_members(universe, em)
        entry_clocks = {}
        for i, j, u in zip(eo.tolist(), es.tolist(), inv.tolist()):
            vc = VClock()
            out[i].entries[uniq_names[u]] = vc
            entry_clocks[(i, j)] = vc
        for i, j, aix, v in zip(
            do.tolist(), ds.tolist(), da.tolist(), dv.tolist()
        ):
            entry_clocks[(i, j)].dots[actor_name[aix]] = v

        if qo.size:
            deferred_clocks = {}
            deferred_members = {}
            d_names, d_inv = _resolve_members(universe, qm)
            for i, j, u in zip(qo.tolist(), qr.tolist(), d_inv.tolist()):
                deferred_clocks[(i, j)] = VClock()
                deferred_members[(i, j)] = d_names[u]
            for i, j, aix, v in zip(
                ho.tolist(), hr.tolist(), ha.tolist(), hv.tolist()
            ):
                if (i, j) in deferred_clocks:
                    deferred_clocks[(i, j)].dots[actor_name[aix]] = v
            for (i, _j), vc in deferred_clocks.items():
                out[i].deferred.setdefault(vc.key(), set()).add(
                    deferred_members[(i, _j)]
                )
        return out

    @property
    def member_capacity(self) -> int:
        return self.ids.shape[-1]

    @property
    def deferred_capacity(self) -> int:
        return self.d_ids.shape[-1]

    def with_capacity(
        self, member_capacity: int | None = None, deferred_capacity: int | None = None
    ) -> "OrswotBatch":
        """Regrow the padded slot axes (elastic recovery from overflow).

        Capacities are this framework's static-shape concession (SURVEY.md
        §7.3); growing them pads with empty slots, which is semantically a
        no-op — empty slots are 'absent' (`orswot.rs` stores no entry at
        all), so the regrown batch is the same CRDT state."""
        m_new = self.member_capacity if member_capacity is None else member_capacity
        d_new = self.deferred_capacity if deferred_capacity is None else deferred_capacity
        if m_new < self.member_capacity or d_new < self.deferred_capacity:
            raise ValueError("with_capacity cannot shrink (would drop live slots)")
        pad_m = m_new - self.member_capacity
        pad_d = d_new - self.deferred_capacity
        if pad_m == 0 and pad_d == 0:
            return self

        def pad_slots(x, pad, tail_axes, fill=0):
            # slot axis is ndim-1-tail_axes; arbitrary leading batch axes
            # (replica-stacked batches are rank 3+, tests/test_sharding.py)
            widths = [(0, 0)] * x.ndim
            widths[x.ndim - 1 - tail_axes] = (0, pad)
            return jnp.pad(x, widths, constant_values=fill)

        return OrswotBatch(
            clock=self.clock,
            ids=pad_slots(self.ids, pad_m, 0, orswot_ops.EMPTY),
            dots=pad_slots(self.dots, pad_m, 1),
            d_ids=pad_slots(self.d_ids, pad_d, 0, orswot_ops.EMPTY),
            d_clocks=pad_slots(self.d_clocks, pad_d, 1),
        )

    # -- state path -------------------------------------------------------

    def merge(
        self, other: "OrswotBatch", check: bool = True,
        impl: str | None = None,
    ) -> "OrswotBatch":
        """Pairwise ORSWOT merge (`orswot.rs:89-156`).

        ``impl`` selects the kernel implementation; pass
        ``universe.config.merge_impl`` to apply a config's selection
        (batches are pure pytrees and do not carry the config), or leave
        ``None`` for the env/backend default — see
        :func:`crdt_tpu.ops.orswot_ops.resolve_merge_impl`.  The
        Map/value-kernel path (``OrswotKernel.from_config``) and the
        collectives thread it automatically."""
        m_cap = self.ids.shape[-1]
        d_cap = self.d_ids.shape[-1]
        clock, ids, dots, d_ids, d_clocks, overflow = _merge(
            self.clock, self.ids, self.dots, self.d_ids, self.d_clocks,
            other.clock, other.ids, other.dots, other.d_ids, other.d_clocks,
            m_cap, d_cap, impl,
        )
        if check:
            raise_for_overflow(overflow, "merge")
        return OrswotBatch(clock=clock, ids=ids, dots=dots, d_ids=d_ids, d_clocks=d_clocks)

    @classmethod
    def join_fleet(
        cls, fleets: Sequence["OrswotBatch"], check: bool = True,
        plunger: bool = True, impl: str | None = None,
    ) -> "OrswotBatch":
        """N-way anti-entropy join of replica fleets holding the same
        objects — the device-shaped form of the reference's merge-all
        loop (`/root/reference/test/orswot.rs:45-62`).

        Stacks the fleets on a new leading axis and reduces them as a
        pairwise tree (:func:`crdt_tpu.ops.orswot_ops.fold_merge_tree`):
        log-depth dependency chain, each level one batched merge.  The
        optional defer plunger flushes buffered removes at the end."""
        if len(fleets) == 0:
            raise ValueError("join_fleet needs at least one fleet")
        if len(fleets) == 1:
            # still run the plunger self-merge so the output is canonical
            # (ascending-id slot order, deferred flushed) regardless of
            # fleet count
            f = fleets[0]
            if not plunger:
                return f
            return f.merge(f, check=check, impl=impl)
        m_cap = fleets[0].ids.shape[-1]
        d_cap = fleets[0].d_ids.shape[-1]
        stacked = [
            jnp.stack([getattr(f, name) for f in fleets])
            for name in ("clock", "ids", "dots", "d_ids", "d_clocks")
        ]
        clock, ids, dots, d_ids, d_clocks, overflow = _fold_tree(
            *stacked, m_cap, d_cap, plunger, impl
        )
        if check:
            raise_for_overflow(overflow, "join_fleet")
        return cls(clock=clock, ids=ids, dots=dots, d_ids=d_ids, d_clocks=d_clocks)

    def truncate(self, clock, check: bool = True) -> "OrswotBatch":
        """``Causal::truncate`` (`orswot.rs:159-172`): forget causal history
        dominated by ``clock`` — the reference's merge-with-an-empty-set
        trick followed by subtracting ``clock`` from the set clock and
        every member clock.  ``clock``: ``[N, A]`` counter array, one
        truncation clock per object.  Same semantics as
        :meth:`~crdt_tpu.batch.val_kernels.OrswotKernel.truncate`, which
        serves the nested (Map) protocol."""
        m_cap = self.ids.shape[-1]
        d_cap = self.d_ids.shape[-1]
        (c, ids, dots, d_ids, d_clocks), overflow = _truncate(
            self.clock, self.ids, self.dots, self.d_ids, self.d_clocks,
            jnp.asarray(clock, dtype=self.clock.dtype), m_cap, d_cap,
        )
        if check:
            raise_for_overflow(overflow, "truncate")
        return OrswotBatch(
            clock=c, ids=ids, dots=dots, d_ids=d_ids, d_clocks=d_clocks
        )

    # -- op path ----------------------------------------------------------

    def apply_add(self, actor_idx, counter, member_id, check: bool = True) -> "OrswotBatch":
        """One ``Op::Add`` per object (`orswot.rs:66-79`)."""
        clock, ids, dots, d_ids, d_clocks, overflow = _apply_add(
            self.clock, self.ids, self.dots, self.d_ids, self.d_clocks,
            jnp.asarray(actor_idx), jnp.asarray(counter), jnp.asarray(member_id),
        )
        if check and bool(jnp.any(overflow)):
            raise CapacityOverflowError(
                "Orswot capacity overflow in apply_add: raise member_capacity",
                member=True,
                deferred=False,
            )
        return OrswotBatch(clock=clock, ids=ids, dots=dots, d_ids=d_ids, d_clocks=d_clocks)

    def apply_remove(self, rm_clock, member_id, check: bool = True) -> "OrswotBatch":
        """One ``Op::Rm`` per object (`orswot.rs:80-83,195-211`)."""
        clock, ids, dots, d_ids, d_clocks, overflow = _apply_remove(
            self.clock, self.ids, self.dots, self.d_ids, self.d_clocks,
            jnp.asarray(rm_clock), jnp.asarray(member_id),
        )
        if check and bool(jnp.any(overflow)):
            raise CapacityOverflowError(
                "Orswot capacity overflow in apply_remove: raise deferred_capacity",
                member=False,
                deferred=True,
            )
        return OrswotBatch(clock=clock, ids=ids, dots=dots, d_ids=d_ids, d_clocks=d_clocks)

    # -- reads ------------------------------------------------------------

    def contains(self, member_id):
        """Membership bitmap (`orswot.rs:214-224`)."""
        return orswot_ops.contains(self.ids, jnp.asarray(member_id))

    def member_count(self):
        return jnp.sum(self.ids != orswot_ops.EMPTY, axis=-1)

    def value_sets(self, universe: Universe) -> list[set]:
        """``value()`` per object (`orswot.rs:227-233`)."""
        import numpy as np

        ids = np.asarray(self.ids)
        return [
            {universe.members.lookup(int(x)) for x in row if x != orswot_ops.EMPTY}
            for row in ids
        ]


@observed_kernel("batch.orswot.merge")
@functools.partial(jax.jit, static_argnums=(10, 11, 12))
def _merge(ca, ia, da, dia, dca, cb, ib, db, dib, dcb, m_cap, d_cap, impl):
    return orswot_ops.merge(
        ca, ia, da, dia, dca, cb, ib, db, dib, dcb, m_cap, d_cap, impl=impl
    )


@observed_kernel("batch.orswot.fold_tree")
@functools.partial(jax.jit, static_argnums=(5, 6, 7, 8))
def _fold_tree(clock, ids, dots, d_ids, d_clocks, m_cap, d_cap, plunger, impl):
    return orswot_ops.fold_merge_tree(
        clock, ids, dots, d_ids, d_clocks, m_cap, d_cap, plunger=plunger,
        impl=impl,
    )


@observed_kernel("batch.orswot.apply_add")
@jax.jit
def _apply_add(clock, ids, dots, d_ids, d_clocks, actor_idx, counter, member_id):
    return orswot_ops.apply_add(clock, ids, dots, d_ids, d_clocks, actor_idx, counter, member_id)


@observed_kernel("batch.orswot.apply_remove")
@jax.jit
def _apply_remove(clock, ids, dots, d_ids, d_clocks, rm_clock, member_id):
    return orswot_ops.apply_remove(clock, ids, dots, d_ids, d_clocks, rm_clock, member_id)


@observed_kernel("batch.orswot.truncate")
@functools.partial(jax.jit, static_argnums=(6, 7))
def _truncate(clock, ids, dots, d_ids, d_clocks, t_clock, m_cap, d_cap):
    """One semantics, one home: delegates to the nested-protocol kernel
    (`val_kernels.OrswotKernel.truncate_full`), keeping the per-axis
    overflow pair for raise_for_overflow."""
    from .val_kernels import OrswotKernel

    kern = OrswotKernel(
        member_capacity=m_cap,
        deferred_capacity=d_cap,
        num_actors=clock.shape[-1],
        counter_bits=clock.dtype.itemsize * 8,
    )
    return kern.truncate_full((clock, ids, dots, d_ids, d_clocks), t_clock)
