"""OrswotBatch — N add-wins OR-sets on device (the flagship type).

Dense form of `/root/reference/src/orswot.rs:26-30`: set clock, member-slot
tables (interned ids + per-member dot clocks) and a deferred-remove table.
``merge`` runs the vectorized dot-algebra kernel
(:func:`crdt_tpu.ops.orswot_ops.merge`); the op path (`apply_add` /
`apply_remove`) applies one op per object across the batch.
"""

from __future__ import annotations

import functools
from typing import Sequence

import jax
import jax.numpy as jnp
from flax import struct

from ..config import counter_dtype
from ..error import CapacityOverflowError, raise_for_overflow
from ..ops import orswot_ops
from ..scalar.orswot import Orswot
from ..scalar.vclock import VClock
from ..utils.interning import Universe
from ..utils.hostmem import gc_paused
from .vclock_batch import VClockBatch


def _np_planes(n, cfg):
    """Empty dense planes ``(clock, ids, dots, d_ids, d_clocks)`` as numpy
    arrays — the one place the shape/dtype/fill scheme lives (``zeros``
    and both bulk-ingest paths build on it)."""
    import numpy as np

    a, m, d = cfg.num_actors, cfg.member_capacity, cfg.deferred_capacity
    dt = counter_dtype(cfg)
    return (
        np.zeros((n, a), dtype=dt),
        np.full((n, m), orswot_ops.EMPTY, dtype=np.int32),
        np.zeros((n, m, a), dtype=dt),
        np.full((n, d), orswot_ops.EMPTY, dtype=np.int32),
        np.zeros((n, d, a), dtype=dt),
    )


@struct.dataclass
class OrswotBatch:
    clock: jax.Array  # u64[N, A]
    ids: jax.Array  # int32[N, M]  (-1 = empty)
    dots: jax.Array  # u64[N, M, A]
    d_ids: jax.Array  # int32[N, D] (-1 = empty)
    d_clocks: jax.Array  # u64[N, D, A]

    @classmethod
    def zeros(cls, n: int, universe: Universe) -> "OrswotBatch":
        return cls(*(jnp.asarray(x) for x in _np_planes(n, universe.config)))

    @classmethod
    @gc_paused
    def from_scalar(cls, states: Sequence[Orswot], universe: Universe) -> "OrswotBatch":
        """Bulk ingest: one Python pass per object collects the flat COO
        value columns with C-level ``list.extend(map(...))`` loops — never
        a per-dot Python append — plus per-object/per-entry *counts*; the
        (object, slot) coordinate columns are then synthesized in bulk
        with ``np.repeat``/``np.arange`` and four vectorized scatters
        build the dense tables.  The per-dot Python bytecode of the
        append-based walk is what bounded ingest at ~30k obj/s at 1M
        scale (``bench.py`` ``ingest`` line); this path keeps the
        unavoidable O(total dots) work in C."""
        import numpy as np

        cfg = universe.config
        n = len(states)
        m, d = cfg.member_capacity, cfg.deferred_capacity
        dt = counter_dtype(cfg)
        aidx = universe.actors.intern
        midx = universe.members.intern

        ca, cc = [], []  # set-clock columns (actor, counter)
        c_counts = np.empty(n, dtype=np.int64)  # clock dots per object
        em = []  # entry member ids, object-major / insertion order
        e_counts = np.empty(n, dtype=np.int64)  # entries per object
        ga, gc = [], []  # entry-dot columns (actor, counter)
        g_counts = []  # dots per entry, aligned with em
        qm = []  # deferred member ids
        q_counts = np.empty(n, dtype=np.int64)  # deferred rows per object
        ha, hc = [], []  # deferred-clock columns
        h_counts = []  # clock dots per deferred row, aligned with qm

        for i, s in enumerate(states):
            cd = s.clock.dots
            c_counts[i] = len(cd)
            ca.extend(map(aidx, cd))
            cc.extend(cd.values())

            ents = s.entries
            if len(ents) > m:
                raise ValueError(
                    f"object {i}: {len(ents)} members > member_capacity {m}"
                )
            e_counts[i] = len(ents)
            em.extend(map(midx, ents))
            for vc in ents.values():
                vd = vc.dots
                g_counts.append(len(vd))
                ga.extend(map(aidx, vd))
                gc.extend(vd.values())

            nrows = sum(len(members) for members in s.deferred.values())
            if nrows > d:
                raise ValueError(
                    f"object {i}: {nrows} deferred rows > deferred_capacity {d}"
                )
            q_counts[i] = nrows
            for ck, members in s.deferred.items():
                # one interned column pair per witnessing clock, shared by
                # every member row buffered under it
                pa = [aidx(actor) for actor, _ in ck]
                pc = [counter for _, counter in ck]
                for member in members:
                    qm.append(midx(member))
                    h_counts.append(len(pa))
                    ha.extend(pa)
                    hc.extend(pc)

        def _obj_slot(counts):
            """(object, within-object slot) coordinate columns for rows
            laid out object-major with ``counts`` rows per object."""
            obj = np.repeat(np.arange(counts.shape[0]), counts)
            starts = np.repeat(np.cumsum(counts) - counts, counts)
            return obj, np.arange(obj.shape[0]) - starts

        clock, ids, dots, d_ids, d_clocks = _np_planes(n, cfg)
        if ca:
            co = np.repeat(np.arange(n), c_counts)
            clock[co, np.asarray(ca)] = np.asarray(cc, dtype=dt)
        if em:
            eo, es = _obj_slot(e_counts)
            ids[eo, es] = np.asarray(em, dtype=np.int32)
            if ga:
                g_counts_arr = np.asarray(g_counts)
                go = np.repeat(eo, g_counts_arr)
                gs = np.repeat(es, g_counts_arr)
                dots[go, gs, np.asarray(ga)] = np.asarray(gc, dtype=dt)
        if qm:
            qo, qs = _obj_slot(q_counts)
            d_ids[qo, qs] = np.asarray(qm, dtype=np.int32)
            if ha:
                h_counts_arr = np.asarray(h_counts)
                ho = np.repeat(qo, h_counts_arr)
                hs = np.repeat(qs, h_counts_arr)
                d_clocks[ho, hs, np.asarray(ha)] = np.asarray(hc, dtype=dt)

        return cls(
            clock=jnp.asarray(clock),
            ids=jnp.asarray(ids),
            dots=jnp.asarray(dots),
            d_ids=jnp.asarray(d_ids),
            d_clocks=jnp.asarray(d_clocks),
        )

    @classmethod
    def from_coo(
        cls, n: int, universe: Universe, *,
        clock_coords, dot_coords, deferred_members=None, deferred_coords=None,
    ) -> "OrswotBatch":
        """Columnar bulk ingest — build ``n`` dense states straight from
        COO coordinate arrays, without materializing any scalar objects
        (the per-object Python walk is what bounds :meth:`from_scalar` at
        ~130k obj/s — ``reports/INGEST_PROFILE.md``; this path is pure
        numpy scatters).

        * ``clock_coords`` — ``(obj, actor_idx, counter)`` arrays for the
          set clocks.
        * ``dot_coords`` — ``(obj, member_id, actor_idx, counter)`` arrays
          for the member dot clocks; member slots are assigned per object
          in ascending member-id order (the engine's canonical order).
        * ``deferred_members`` — optional ``(obj, row, member_id)`` arrays;
          ``deferred_coords`` — ``(obj, row, actor_idx, counter)`` arrays
          giving each deferred row's witnessing clock.  Rows index the
          deferred table directly (a row is one buffered
          (member, clock) remove, `orswot.rs:29`).

        Duplicate *counter* coordinates (clock, dot, deferred-clock cells)
        join by ``max`` — the lattice's own rule, so re-ingesting
        overlapping exports is idempotent.  ``deferred_members`` rows are
        assignments, not lattice cells: two entries naming the same
        ``(obj, row)`` with different member ids are a conflict and raise.
        Actor indices must already be dense (``universe.actor_idx``);
        member ids are the interned int32 ids (``universe.member_id``).
        Raises ``ValueError`` on a negative member id (the ``EMPTY``
        sentinel leaking from an upstream export) in either ``dot_coords``
        or ``deferred_members``, when an object's distinct members exceed
        ``member_capacity``, when a deferred row index falls outside
        ``[0, deferred_capacity)``, or when only one of the two deferred
        argument pairs is supplied."""
        import numpy as np

        cfg = universe.config
        m, d = cfg.member_capacity, cfg.deferred_capacity
        dt = counter_dtype(cfg)
        clock, ids, dots, d_ids, d_clocks = _np_planes(n, cfg)

        co, ca, cc = (np.asarray(x) for x in clock_coords)
        if co.size:
            np.maximum.at(clock, (co, ca), cc.astype(dt))

        do, dm, da, dc = (np.asarray(x) for x in dot_coords)
        if do.size:
            if dm.min(initial=0) < 0:
                raise ValueError(
                    f"negative member id {int(dm.min())} in dot_coords "
                    "(EMPTY sentinel leaking from an export?)"
                )
            # slot assignment: unique (obj, member) pairs, ascending member
            # id within each object — np.unique's lexicographic sort gives
            # exactly that, and searchsorted ranks each pair within its
            # object's group
            pair_key = do.astype(np.int64) * (1 << 32) + dm.astype(np.int64)
            uniq, inv = np.unique(pair_key, return_inverse=True)
            uo = (uniq >> 32).astype(np.int64)
            um = (uniq & ((1 << 32) - 1)).astype(np.int32)
            slot = np.arange(uniq.size) - np.searchsorted(uo, uo)
            counts = np.bincount(uo, minlength=n)
            if counts.max(initial=0) > m:
                bad = int(np.argmax(counts))
                raise ValueError(
                    f"object {bad}: {int(counts[bad])} members > member_capacity {m}"
                )
            ids[uo, slot] = um
            np.maximum.at(dots, (do, slot[inv], da), dc.astype(dt))

        if (deferred_members is None) != (deferred_coords is None):
            raise ValueError(
                "deferred_members and deferred_coords must be supplied together "
                "(a deferred row is a (member, clock) pair)"
            )
        if deferred_members is not None:
            def _check_rows(rows, label):
                if rows.size and (rows.min() < 0 or rows.max() >= d):
                    raise ValueError(
                        f"{label} row indices must lie in [0, "
                        f"deferred_capacity={d}); got "
                        f"[{int(rows.min())}, {int(rows.max())}]"
                    )

            qo, qr, qm = (np.asarray(x) for x in deferred_members)
            _check_rows(qr, "deferred_members")
            if qo.size:
                if qm.min(initial=0) < 0:
                    raise ValueError(
                        f"negative member id {int(qm.min())} in "
                        "deferred_members (EMPTY sentinel leaking from an "
                        "export?) — the row would be invisible to kernels "
                        "while its clock still scatters into d_clocks"
                    )
                # duplicate (obj, row) keys are assignments, not lattice
                # cells: silently last-write-winning would drop a remove
                key = qo.astype(np.int64) * d + qr.astype(np.int64)
                order = np.argsort(key, kind="stable")
                sk, sm = key[order], qm[order]
                dup = sk[1:] == sk[:-1]
                if np.any(dup & (sm[1:] != sm[:-1])):
                    i = int(np.nonzero(dup & (sm[1:] != sm[:-1]))[0][0])
                    raise ValueError(
                        f"conflicting deferred_members assignments for "
                        f"(obj={int(sk[i]) // d}, row={int(sk[i]) % d}): "
                        f"member ids {int(sm[i])} and {int(sm[i + 1])}"
                    )
                d_ids[qo, qr] = qm.astype(np.int32)
            ho, hr, ha, hc = (np.asarray(x) for x in deferred_coords)
            _check_rows(hr, "deferred_coords")
            if ho.size:
                np.maximum.at(d_clocks, (ho, hr, ha), hc.astype(dt))

        return cls(
            clock=jnp.asarray(clock), ids=jnp.asarray(ids),
            dots=jnp.asarray(dots), d_ids=jnp.asarray(d_ids),
            d_clocks=jnp.asarray(d_clocks),
        )

    def to_coo(self):
        """Columnar bulk egress — the inverse of :meth:`from_coo`: four
        coordinate-array tuples extracted with ``np.nonzero`` (no Python
        objects; pair with :meth:`from_coo` for checkpoint-scale export
        of live fleets).  Returns ``(clock_coords, dot_coords,
        deferred_members, deferred_coords)``."""
        import numpy as np

        clock = np.asarray(self.clock)
        ids = np.asarray(self.ids)
        dots = np.asarray(self.dots)
        d_ids = np.asarray(self.d_ids)
        d_clocks = np.asarray(self.d_clocks)

        co, ca = np.nonzero(clock)
        do, ds, da = np.nonzero(dots)
        qo, qr = np.nonzero(d_ids != orswot_ops.EMPTY)
        ho, hr, ha = np.nonzero(d_clocks)
        return (
            (co, ca, clock[co, ca]),
            (do, ids[do, ds], da, dots[do, ds, da]),
            (qo, qr, d_ids[qo, qr]),
            (ho, hr, ha, d_clocks[ho, hr, ha]),
        )

    @gc_paused
    def to_scalar(self, universe: Universe) -> list[Orswot]:
        """Bulk egress: ``np.nonzero`` extracts every populated cell in
        four vectorized passes; the Python loop only walks actual dots
        (sparse), never the dense ``[N, M, A]`` volume."""
        import numpy as np

        from ..scalar.vclock import VClock

        clock = np.asarray(self.clock)
        ids = np.asarray(self.ids)
        dots = np.asarray(self.dots)
        d_ids = np.asarray(self.d_ids)
        d_clocks = np.asarray(self.d_clocks)

        n = clock.shape[0]
        # registry lookups hoisted out of the per-cell loops: the actor
        # universe is dense (one list index per cell instead of a method
        # call; only interned columns can carry data, the rest stay None),
        # and member ids resolve once per UNIQUE id present
        n_interned = len(universe.actors)
        actor_name = [
            universe.actors.lookup(i) if i < n_interned else None
            for i in range(clock.shape[1])
        ]
        member_of = universe.members.lookup
        out = [Orswot() for _ in range(n)]

        oi, ai = np.nonzero(clock)
        for i, aix, v in zip(oi.tolist(), ai.tolist(), clock[oi, ai].tolist()):
            out[i].clock.dots[actor_name[aix]] = v

        # entries in slot order (np.nonzero is row-major), matching the
        # insertion order the naive path produced
        oi, si = np.nonzero(ids != orswot_ops.EMPTY)
        mids = ids[oi, si]
        uniq, inv = np.unique(mids, return_inverse=True)
        uniq_names = [member_of(int(m)) for m in uniq]
        entry_clocks = {}
        for i, j, u in zip(oi.tolist(), si.tolist(), inv.tolist()):
            vc = VClock()
            out[i].entries[uniq_names[u]] = vc
            entry_clocks[(i, j)] = vc
        oi, si, ai = np.nonzero(dots)
        for i, j, aix, v in zip(
            oi.tolist(), si.tolist(), ai.tolist(), dots[oi, si, ai].tolist()
        ):
            entry_clocks[(i, j)].dots[actor_name[aix]] = v

        oi, si = np.nonzero(d_ids != orswot_ops.EMPTY)
        if oi.size:
            deferred_clocks = {}
            deferred_members = {}
            d_mids = d_ids[oi, si]
            d_uniq, d_inv = np.unique(d_mids, return_inverse=True)
            d_names = [member_of(int(m)) for m in d_uniq]
            for i, j, u in zip(oi.tolist(), si.tolist(), d_inv.tolist()):
                deferred_clocks[(i, j)] = VClock()
                deferred_members[(i, j)] = d_names[u]
            oi, si, ai = np.nonzero(d_clocks)
            for i, j, aix, v in zip(
                oi.tolist(), si.tolist(), ai.tolist(), d_clocks[oi, si, ai].tolist()
            ):
                if (i, j) in deferred_clocks:
                    deferred_clocks[(i, j)].dots[actor_name[aix]] = v
            for (i, _j), vc in deferred_clocks.items():
                out[i].deferred.setdefault(vc.key(), set()).add(
                    deferred_members[(i, _j)]
                )
        return out

    @property
    def member_capacity(self) -> int:
        return self.ids.shape[-1]

    @property
    def deferred_capacity(self) -> int:
        return self.d_ids.shape[-1]

    def with_capacity(
        self, member_capacity: int | None = None, deferred_capacity: int | None = None
    ) -> "OrswotBatch":
        """Regrow the padded slot axes (elastic recovery from overflow).

        Capacities are this framework's static-shape concession (SURVEY.md
        §7.3); growing them pads with empty slots, which is semantically a
        no-op — empty slots are 'absent' (`orswot.rs` stores no entry at
        all), so the regrown batch is the same CRDT state."""
        m_new = self.member_capacity if member_capacity is None else member_capacity
        d_new = self.deferred_capacity if deferred_capacity is None else deferred_capacity
        if m_new < self.member_capacity or d_new < self.deferred_capacity:
            raise ValueError("with_capacity cannot shrink (would drop live slots)")
        pad_m = m_new - self.member_capacity
        pad_d = d_new - self.deferred_capacity
        if pad_m == 0 and pad_d == 0:
            return self

        def pad_slots(x, pad, tail_axes, fill=0):
            # slot axis is ndim-1-tail_axes; arbitrary leading batch axes
            # (replica-stacked batches are rank 3+, tests/test_sharding.py)
            widths = [(0, 0)] * x.ndim
            widths[x.ndim - 1 - tail_axes] = (0, pad)
            return jnp.pad(x, widths, constant_values=fill)

        return OrswotBatch(
            clock=self.clock,
            ids=pad_slots(self.ids, pad_m, 0, orswot_ops.EMPTY),
            dots=pad_slots(self.dots, pad_m, 1),
            d_ids=pad_slots(self.d_ids, pad_d, 0, orswot_ops.EMPTY),
            d_clocks=pad_slots(self.d_clocks, pad_d, 1),
        )

    # -- state path -------------------------------------------------------

    def merge(self, other: "OrswotBatch", check: bool = True) -> "OrswotBatch":
        """Pairwise ORSWOT merge (`orswot.rs:89-156`)."""
        m_cap = self.ids.shape[-1]
        d_cap = self.d_ids.shape[-1]
        clock, ids, dots, d_ids, d_clocks, overflow = _merge(
            self.clock, self.ids, self.dots, self.d_ids, self.d_clocks,
            other.clock, other.ids, other.dots, other.d_ids, other.d_clocks,
            m_cap, d_cap,
        )
        if check:
            raise_for_overflow(overflow, "merge")
        return OrswotBatch(clock=clock, ids=ids, dots=dots, d_ids=d_ids, d_clocks=d_clocks)

    @classmethod
    def join_fleet(
        cls, fleets: Sequence["OrswotBatch"], check: bool = True,
        plunger: bool = True,
    ) -> "OrswotBatch":
        """N-way anti-entropy join of replica fleets holding the same
        objects — the device-shaped form of the reference's merge-all
        loop (`/root/reference/test/orswot.rs:45-62`).

        Stacks the fleets on a new leading axis and reduces them as a
        pairwise tree (:func:`crdt_tpu.ops.orswot_ops.fold_merge_tree`):
        log-depth dependency chain, each level one batched merge.  The
        optional defer plunger flushes buffered removes at the end."""
        if len(fleets) == 0:
            raise ValueError("join_fleet needs at least one fleet")
        if len(fleets) == 1:
            # still run the plunger self-merge so the output is canonical
            # (ascending-id slot order, deferred flushed) regardless of
            # fleet count
            f = fleets[0]
            if not plunger:
                return f
            return f.merge(f, check=check)
        m_cap = fleets[0].ids.shape[-1]
        d_cap = fleets[0].d_ids.shape[-1]
        stacked = [
            jnp.stack([getattr(f, name) for f in fleets])
            for name in ("clock", "ids", "dots", "d_ids", "d_clocks")
        ]
        clock, ids, dots, d_ids, d_clocks, overflow = _fold_tree(
            *stacked, m_cap, d_cap, plunger
        )
        if check:
            raise_for_overflow(overflow, "join_fleet")
        return cls(clock=clock, ids=ids, dots=dots, d_ids=d_ids, d_clocks=d_clocks)

    # -- op path ----------------------------------------------------------

    def apply_add(self, actor_idx, counter, member_id, check: bool = True) -> "OrswotBatch":
        """One ``Op::Add`` per object (`orswot.rs:66-79`)."""
        clock, ids, dots, d_ids, d_clocks, overflow = _apply_add(
            self.clock, self.ids, self.dots, self.d_ids, self.d_clocks,
            jnp.asarray(actor_idx), jnp.asarray(counter), jnp.asarray(member_id),
        )
        if check and bool(jnp.any(overflow)):
            raise CapacityOverflowError(
                "Orswot capacity overflow in apply_add: raise member_capacity",
                member=True,
                deferred=False,
            )
        return OrswotBatch(clock=clock, ids=ids, dots=dots, d_ids=d_ids, d_clocks=d_clocks)

    def apply_remove(self, rm_clock, member_id, check: bool = True) -> "OrswotBatch":
        """One ``Op::Rm`` per object (`orswot.rs:80-83,195-211`)."""
        clock, ids, dots, d_ids, d_clocks, overflow = _apply_remove(
            self.clock, self.ids, self.dots, self.d_ids, self.d_clocks,
            jnp.asarray(rm_clock), jnp.asarray(member_id),
        )
        if check and bool(jnp.any(overflow)):
            raise CapacityOverflowError(
                "Orswot capacity overflow in apply_remove: raise deferred_capacity",
                member=False,
                deferred=True,
            )
        return OrswotBatch(clock=clock, ids=ids, dots=dots, d_ids=d_ids, d_clocks=d_clocks)

    # -- reads ------------------------------------------------------------

    def contains(self, member_id):
        """Membership bitmap (`orswot.rs:214-224`)."""
        return orswot_ops.contains(self.ids, jnp.asarray(member_id))

    def member_count(self):
        return jnp.sum(self.ids != orswot_ops.EMPTY, axis=-1)

    def value_sets(self, universe: Universe) -> list[set]:
        """``value()`` per object (`orswot.rs:227-233`)."""
        import numpy as np

        ids = np.asarray(self.ids)
        return [
            {universe.members.lookup(int(x)) for x in row if x != orswot_ops.EMPTY}
            for row in ids
        ]


@functools.partial(jax.jit, static_argnums=(10, 11))
def _merge(ca, ia, da, dia, dca, cb, ib, db, dib, dcb, m_cap, d_cap):
    return orswot_ops.merge(ca, ia, da, dia, dca, cb, ib, db, dib, dcb, m_cap, d_cap)


@functools.partial(jax.jit, static_argnums=(5, 6, 7))
def _fold_tree(clock, ids, dots, d_ids, d_clocks, m_cap, d_cap, plunger):
    return orswot_ops.fold_merge_tree(
        clock, ids, dots, d_ids, d_clocks, m_cap, d_cap, plunger=plunger
    )


@jax.jit
def _apply_add(clock, ids, dots, d_ids, d_clocks, actor_idx, counter, member_id):
    return orswot_ops.apply_add(clock, ids, dots, d_ids, d_clocks, actor_idx, counter, member_id)


@jax.jit
def _apply_remove(clock, ids, dots, d_ids, d_clocks, rm_clock, member_id):
    return orswot_ops.apply_remove(clock, ids, dots, d_ids, d_clocks, rm_clock, member_id)
