"""Shared scaffolding for the native bulk wire paths.

Each batch type's ``from_wire``/``to_wire`` follows the same shape
(`OrswotBatch.from_wire` is the reference implementation): probe the
native engine + identity universe, concatenate blobs, parse in
parallel, patch/raise per the status array, fall back to the Python
codec whenever the fast path cannot apply.  This module holds the two
pieces that are identical across types so they cannot drift.
"""

from __future__ import annotations

from typing import Sequence


def probe_engine(universe, fn_name: str, dtype=None):
    """The native engine module when the fast path applies, else None.

    Applies = identity universe AND the .so loads AND it exports the
    required symbol (an .so built from older sources loads fine but
    lacks newer entry points).  ``dtype=None`` probes a
    dtype-independent symbol (no u32/u64 suffix — the GSet bitmap
    codec)."""
    if not universe.is_identity:
        return None
    try:
        from ..native import engine

        if dtype is None:
            engine._fn_raw(fn_name)
        else:
            engine._fn(fn_name, dtype)
        return engine
    except (ImportError, OSError, RuntimeError, AttributeError, TypeError):
        return None


def concat_blobs(blobs: Sequence[bytes]):
    """``(buf, offsets)`` for the C parsers: one contiguous buffer plus
    int64[n+1] blob boundaries."""
    import numpy as np

    n = len(blobs)
    buf = b"".join(blobs)
    offsets = np.zeros(n + 1, dtype=np.int64)
    np.cumsum(
        np.fromiter((len(b) for b in blobs), dtype=np.int64, count=n),
        out=offsets[1:],
    )
    return buf, offsets


def slice_blobs(buf, offsets) -> list[bytes]:
    """Concatenated encoder output → per-object bytes (one copy per
    blob via a memoryview, no whole-buffer intermediate)."""
    mv = memoryview(buf)
    off = offsets.tolist()
    return [bytes(mv[off[i]:off[i + 1]]) for i in range(len(off) - 1)]
