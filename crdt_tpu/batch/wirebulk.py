"""Shared scaffolding for the native bulk wire paths.

Each batch type's ``from_wire``/``to_wire`` follows the same shape
(`OrswotBatch.from_wire` is the reference implementation): probe the
native engine + identity universe, concatenate blobs, parse in
parallel, patch/raise per the status array, fall back to the Python
codec whenever the fast path cannot apply.  This module holds the
pieces that are identical across types so they cannot drift — including
the whole counter-plane ingest/egress flow (status triage, per-blob
patch splice, the u64-zigzag egress guard) shared by the clock-shaped
legs (VClock / GCounter / PNCounter).
"""

from __future__ import annotations

from typing import Sequence

WIRE_TAG_VCLOCK = 0x20    # serde.py _T_VCLOCK
WIRE_TAG_GCOUNTER = 0x22  # serde.py _T_GCOUNTER


def probe_engine(universe, fn_name: str, dtype=None):
    """The native engine module when the fast path applies, else None.

    Applies = identity universe AND the .so loads AND it exports the
    required symbol (an .so built from older sources loads fine but
    lacks newer entry points).  ``dtype=None`` probes a
    dtype-independent symbol (no u32/u64 suffix — the GSet bitmap
    codec)."""
    if not universe.is_identity:
        return None
    try:
        from ..native import engine

        if dtype is None:
            engine._fn_raw(fn_name)
        else:
            engine._fn(fn_name, dtype)
        return engine
    except (ImportError, OSError, RuntimeError, AttributeError, TypeError):
        return None


def concat_blobs(blobs: Sequence[bytes]):
    """``(buf, offsets)`` for the C parsers: one contiguous buffer plus
    int64[n+1] blob boundaries."""
    import numpy as np

    n = len(blobs)
    buf = b"".join(blobs)
    offsets = np.zeros(n + 1, dtype=np.int64)
    np.cumsum(
        np.fromiter((len(b) for b in blobs), dtype=np.int64, count=n),
        out=offsets[1:],
    )
    return buf, offsets


def slice_blobs(buf, offsets) -> list[bytes]:
    """Concatenated encoder output → per-object bytes (one copy per
    blob via a memoryview, no whole-buffer intermediate)."""
    mv = memoryview(buf)
    off = offsets.tolist()
    return [bytes(mv[off[i]:off[i + 1]]) for i in range(len(off) - 1)]


def planes_from_wire(blobs, universe, probe_name, ingest, planes_of_scalars):
    """Dense counter planes from wire blobs — the shared ingest flow of
    the clock-shaped legs.

    ``ingest(engine, buf, offsets, cfg, dtype) -> (planes, status)``
    runs the type's native parser; ``planes_of_scalars(scalars)`` maps
    decoded scalar states to dense planes (the calling class's
    ``from_scalar(...)`` planes) and serves both the no-engine full
    fallback and the per-blob patch path, so the result always equals
    the pure-Python decode."""
    import numpy as np

    from ..config import counter_dtype
    from ..utils.serde import from_binary

    cfg = universe.config
    engine = probe_engine(universe, probe_name, counter_dtype(cfg))
    if engine is None:
        return planes_of_scalars([from_binary(b) for b in blobs])
    buf, offsets = concat_blobs(blobs)
    planes, status = ingest(engine, buf, offsets, cfg, counter_dtype(cfg))
    if status.any():
        hard = np.nonzero(status > 1)[0]
        if hard.size:
            first = int(hard[0])
            raise ValueError(
                f"object {first}: actor outside the identity registry "
                f"range [0, {cfg.num_actors})"
            )
        fb = np.nonzero(status == 1)[0].tolist()
        sub = np.asarray(planes_of_scalars([from_binary(blobs[i]) for i in fb]))
        planes[np.asarray(fb, dtype=np.int64)] = sub
    return planes


def counters_overflow_zigzag(planes) -> bool:
    """The shared u64 egress guard: True when any 8-byte counter plane
    holds a value at/above 2^63, whose zigzag encoding overflows the C
    emitter's uint64 (such states must take the Python encoder).

    4-byte planes can never overflow — they are skipped without the
    full-plane ``max`` scan, so u32 configs pay nothing here.  Accepts
    host or device arrays; the reduction runs where the plane lives and
    only the scalar crosses to the host."""
    for p in planes:
        if p.dtype.itemsize != 8 or p.size == 0:
            continue
        if int(p.max()) >= 1 << 63:
            return True
    return False


def planes_to_wire(planes, universe, probe_name, encode, python_path):
    """Wire blobs from dense counter planes — the shared egress flow,
    byte-identical to the scalar ``to_binary``.

    ``encode(engine, planes) -> (buf, offsets)`` runs the type's native
    encoder; ``python_path()`` is the full fallback: non-identity
    universes, missing engine, or the :func:`counters_overflow_zigzag`
    guard."""
    import numpy as np

    from ..config import counter_dtype

    if planes.shape[0] == 0:
        return []
    engine = probe_engine(universe, probe_name, counter_dtype(universe.config))
    host = None
    if engine is not None:
        host = np.asarray(planes)
        if counters_overflow_zigzag((host,)):
            engine = None
    if engine is None:
        return python_path()
    buf, offsets = encode(engine, host)
    return slice_blobs(buf, offsets)


def clockish_from_wire(blobs, universe, tag, planes_of_scalars):
    """``[N, A]`` planes from pure-clock-body blobs — the VClock/GCounter
    legs' tag-parameterized specialization of :func:`planes_from_wire`."""
    return planes_from_wire(
        blobs, universe, "clockish_ingest_wire",
        lambda engine, buf, offsets, cfg, dt: engine.clockish_ingest_wire(
            buf, offsets, tag, cfg.num_actors, dt
        ),
        planes_of_scalars,
    )


def clockish_to_wire(clocks, universe, tag, python_path):
    """Egress counterpart of :func:`clockish_from_wire`."""
    return planes_to_wire(
        clocks, universe, "clockish_encode_wire",
        lambda engine, host: engine.clockish_encode_wire(host, tag),
        python_path,
    )
