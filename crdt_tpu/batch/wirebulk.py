"""Shared scaffolding for the native bulk wire paths.

Each batch type's ``from_wire``/``to_wire`` follows the same shape
(`OrswotBatch.from_wire` is the reference implementation): probe the
native engine + identity universe, concatenate blobs, parse in
parallel, patch/raise per the status array, fall back to the Python
codec whenever the fast path cannot apply.  This module holds the
pieces that are identical across types so they cannot drift — including
the whole counter-plane ingest/egress flow (status triage, per-blob
patch splice, the u64-zigzag egress guard) shared by the clock-shaped
legs (VClock / GCounter / PNCounter).
"""

from __future__ import annotations

from typing import Sequence

from ..error import WireFormatError

WIRE_TAG_VCLOCK = 0x20    # serde.py _T_VCLOCK
WIRE_TAG_GCOUNTER = 0x22  # serde.py _T_GCOUNTER

# leg labels for the tag-parameterized clockish codec's counters
_TAG_LEG = {WIRE_TAG_VCLOCK: "vclock", WIRE_TAG_GCOUNTER: "gcounter"}


def record_wire(leg: str, direction: str, *, native: int = 0,
                fallback: int = 0, reason: str | None = None) -> None:
    """Count native-vs-fallback blobs for one bulk wire call.

    Feeds the always-on counters in :mod:`crdt_tpu.utils.tracing` under
    ``wire.<leg>.<direction>.{native,fallback}`` plus a
    ``...fallback_reason.<reason>`` detail counter, so the bench can
    report a per-stage ``native_fraction`` and a silent-fallback
    regression is visible from the JSON artifact alone (the round-5 e2e
    ingest collapse was initially blamed on exactly such an invisible
    fallback).  Reasons in use: ``no_engine`` (native library absent or
    symbol missing), ``non_identity`` (universe is not identity-interned),
    ``grammar`` (per-blob status==1 splice), ``overflow_zigzag`` (u64
    counters past the native encoder's range).

    A reasoned fallback also lands in the flight recorder (kind
    ``wire.fallback``) — one event per bulk call, so the recorder shows
    WHEN the native path was lost, which the monotonic counters alone
    cannot."""
    from ..obs import events as obs_events
    from ..utils import tracing

    prefix = f"wire.{leg}.{direction}"
    tracing.count(f"{prefix}.native", native)
    tracing.count(f"{prefix}.fallback", fallback)
    if reason is not None and fallback:
        tracing.count(f"{prefix}.fallback_reason.{reason}", fallback)
        obs_events.record("wire.fallback", leg=leg, direction=direction,
                          reason=reason, blobs=fallback)


def probe_engine(universe, fn_name: str, dtype=None):
    """The native engine module when the fast path applies, else None.

    Applies = identity universe AND the .so loads AND it exports the
    required symbol (an .so built from older sources loads fine but
    lacks newer entry points).  ``dtype=None`` probes a
    dtype-independent symbol (no u32/u64 suffix — the GSet bitmap
    codec)."""
    if not universe.is_identity:
        return None
    try:
        from ..native import engine

        if dtype is None:
            engine._fn_raw(fn_name)
        else:
            engine._fn(fn_name, dtype)
        return engine
    except (ImportError, OSError, RuntimeError, AttributeError, TypeError):
        return None


def concat_blobs(blobs: Sequence[bytes]):
    """``(buf, offsets)`` for the C parsers: one contiguous buffer plus
    int64[n+1] blob boundaries."""
    import numpy as np

    n = len(blobs)
    buf = b"".join(blobs)
    offsets = np.zeros(n + 1, dtype=np.int64)
    np.cumsum(
        np.fromiter((len(b) for b in blobs), dtype=np.int64, count=n),
        out=offsets[1:],
    )
    return buf, offsets


def slice_blobs(buf, offsets) -> list[bytes]:
    """Concatenated encoder output → per-object bytes (one copy per
    blob via a memoryview, no whole-buffer intermediate)."""
    mv = memoryview(buf)
    off = offsets.tolist()
    return [bytes(mv[off[i]:off[i + 1]]) for i in range(len(off) - 1)]


def fallback_reason(universe) -> str:
    """Why :func:`probe_engine` returned None — counter detail for
    :func:`record_wire` (``non_identity`` dominates: a present engine is
    still unusable without identity interning)."""
    return "non_identity" if not universe.is_identity else "no_engine"


def planes_from_wire(blobs, universe, probe_name, ingest, planes_of_scalars,
                     leg: str = "counters"):
    """Dense counter planes from wire blobs — the shared ingest flow of
    the clock-shaped legs.

    ``ingest(engine, buf, offsets, cfg, dtype) -> (planes, status)``
    runs the type's native parser; ``planes_of_scalars(scalars)`` maps
    decoded scalar states to dense planes (the calling class's
    ``from_scalar(...)`` planes) and serves both the no-engine full
    fallback and the per-blob patch path, so the result always equals
    the pure-Python decode.  ``leg`` labels the native/fallback
    counters (:func:`record_wire`)."""
    import numpy as np

    from ..config import counter_dtype
    from ..utils.serde import from_binary

    cfg = universe.config
    engine = probe_engine(universe, probe_name, counter_dtype(cfg))
    if engine is None:
        record_wire(leg, "from_wire", fallback=len(blobs),
                    reason=fallback_reason(universe))
        return planes_of_scalars([from_binary(b) for b in blobs])
    buf, offsets = concat_blobs(blobs)
    planes, status = ingest(engine, buf, offsets, cfg, counter_dtype(cfg))
    n_fb = 0
    if status.any():
        hard = np.nonzero(status > 1)[0]
        if hard.size:
            first = int(hard[0])
            raise WireFormatError(
                f"object {first}: actor outside the identity registry "
                f"range [0, {cfg.num_actors})"
            )
        fb = np.nonzero(status == 1)[0].tolist()
        n_fb = len(fb)
        sub = np.asarray(planes_of_scalars([from_binary(blobs[i]) for i in fb]))
        planes[np.asarray(fb, dtype=np.int64)] = sub
    record_wire(leg, "from_wire", native=len(blobs) - n_fb, fallback=n_fb,
                reason="grammar")
    return planes


def counters_overflow_zigzag(planes) -> bool:
    """The shared u64 egress guard: True when any 8-byte counter plane
    holds a value at/above 2^63, whose zigzag encoding overflows the C
    emitter's uint64 (such states must take the Python encoder).

    4-byte planes can never overflow — they are skipped without the
    full-plane ``max`` scan, so u32 configs pay nothing here.  Accepts
    host or device arrays; the reduction runs where the plane lives and
    only the scalar crosses to the host."""
    for p in planes:
        if p.dtype.itemsize != 8 or p.size == 0:
            continue
        if int(p.max()) >= 1 << 63:
            return True
    return False


def planes_to_wire(planes, universe, probe_name, encode, python_path,
                   leg: str = "counters"):
    """Wire blobs from dense counter planes — the shared egress flow,
    byte-identical to the scalar ``to_binary``.

    ``encode(engine, planes) -> (buf, offsets)`` runs the type's native
    encoder; ``python_path()`` is the full fallback: non-identity
    universes, missing engine, or the :func:`counters_overflow_zigzag`
    guard.  ``leg`` labels the native/fallback counters."""
    import numpy as np

    from ..config import counter_dtype

    n = planes.shape[0]
    if n == 0:
        return []
    engine = probe_engine(universe, probe_name, counter_dtype(universe.config))
    reason = fallback_reason(universe)
    host = None
    if engine is not None:
        host = np.asarray(planes)
        if counters_overflow_zigzag((host,)):
            engine = None
            reason = "overflow_zigzag"
    if engine is None:
        record_wire(leg, "to_wire", fallback=n, reason=reason)
        return python_path()
    buf, offsets = encode(engine, host)
    record_wire(leg, "to_wire", native=n)
    return slice_blobs(buf, offsets)


# ---- ORSWOT shared triage (OrswotBatch.from_wire + PipelinedWireLoop) ------


def orswot_planes_from_wire(blobs, universe, out=None):
    """Dense ORSWOT planes (host numpy) straight from wire blobs, with
    the full status triage — the shared ingest core of
    ``OrswotBatch.from_wire`` and :class:`crdt_tpu.batch.wireloop.
    PipelinedWireLoop`.

    Returns ``(clock, ids, dots, d_ids, d_clocks)``, or ``None`` when
    the native fast path does not apply at all (missing engine /
    non-identity universe) — the caller then takes its own full-Python
    route.  Every outcome is counted under the ``wire.orswot.from_wire``
    counters (:func:`record_wire`).

    ``out``: optional preallocated plane 5-tuple passed through to
    ``engine.orswot_ingest_wire`` for buffer REUSE across calls — fresh
    per-call plane allocations page-fault GBs at north-star chunk scale
    and were the measured e2e ingest collapse (PERF.md).

    Hard statuses raise ``ValueError`` with the caller's blob index;
    status==1 blobs (structure outside the fast-path grammar) are
    decoded by the Python codec and their rows spliced in, so the result
    always equals the pure-Python decode."""
    import numpy as np

    from ..config import counter_dtype

    cfg = universe.config
    engine = probe_engine(universe, "orswot_ingest_wire", counter_dtype(cfg))
    if engine is None:
        record_wire("orswot", "from_wire", fallback=len(blobs),
                    reason=fallback_reason(universe))
        return None
    buf, offsets = concat_blobs(blobs)
    clock, ids, dots, d_ids, d_clocks, status = engine.orswot_ingest_wire(
        buf, offsets, cfg.num_actors, cfg.member_capacity,
        cfg.deferred_capacity, counter_dtype(cfg), out=out,
    )
    n_fb = 0
    if status.any():
        # hard errors first, reported with the CALLER's blob index
        hard = np.nonzero(status > 1)[0]
        if hard.size:
            first = int(hard[0])
            code = int(status[first])
            if code == 2:
                raise WireFormatError(
                    f"object {first}: members > member_capacity "
                    f"{cfg.member_capacity}"
                )
            if code == 3:
                raise WireFormatError(
                    f"object {first}: deferred rows > deferred_capacity "
                    f"{cfg.deferred_capacity}"
                )
            raise WireFormatError(
                f"object {first}: actor outside the identity registry "
                f"range [0, {cfg.num_actors})"
            )
        # code 1: structure outside the fast-path grammar — decode those
        # blobs in Python and patch their rows (raises exactly where the
        # scalar path would, e.g. non-int members against an identity
        # registry)
        from ..utils.serde import from_binary
        from .orswot_batch import OrswotBatch

        fb = np.nonzero(status == 1)[0].tolist()
        n_fb = len(fb)
        try:
            sub = OrswotBatch.from_scalar(
                [from_binary(blobs[i]) for i in fb], universe
            )
        except (ValueError, TypeError) as e:
            # from_scalar reports indices relative to the fallback
            # sublist; translate so the operator can find the blob
            raise type(e)(
                f"{e} [object indices above are relative to the "
                f"python-fallback sublist; its blob indices are "
                f"{fb[:16]}{'...' if len(fb) > 16 else ''}]"
            ) from None
        idx = np.asarray(fb, dtype=np.int64)
        clock[idx] = np.asarray(sub.clock)
        ids[idx] = np.asarray(sub.ids)
        dots[idx] = np.asarray(sub.dots)
        d_ids[idx] = np.asarray(sub.d_ids)
        d_clocks[idx] = np.asarray(sub.d_clocks)
    record_wire("orswot", "from_wire", native=len(blobs) - n_fb,
                fallback=n_fb, reason="grammar")
    return clock, ids, dots, d_ids, d_clocks


def orswot_planes_to_wire(clock, ids, dots, d_ids, d_clocks, universe):
    """Wire blobs from dense host ORSWOT planes — the shared egress core
    of ``OrswotBatch.to_wire`` and the pipelined wire loop.

    Returns the blob list, or ``None`` when the Python encoder must run
    (missing engine / non-identity universe / the u64 zigzag-overflow
    guard) — the caller serializes via ``to_binary`` then.  Outcomes are
    counted under ``wire.orswot.to_wire``."""
    from ..config import counter_dtype

    n = clock.shape[0]
    if n == 0:
        return []
    engine = probe_engine(
        universe, "orswot_encode_wire", counter_dtype(universe.config)
    )
    reason = fallback_reason(universe)
    if engine is not None and counters_overflow_zigzag(
        (clock, dots, d_clocks)
    ):
        # zigzag of a >=2^63 counter exceeds u64; to_binary's big-int
        # varints handle it — take the Python path
        engine = None
        reason = "overflow_zigzag"
    if engine is None:
        record_wire("orswot", "to_wire", fallback=n, reason=reason)
        return None
    buf, offsets = engine.orswot_encode_wire(clock, ids, dots, d_ids, d_clocks)
    record_wire("orswot", "to_wire", native=n)
    return slice_blobs(buf, offsets)


def clockish_from_wire(blobs, universe, tag, planes_of_scalars):
    """``[N, A]`` planes from pure-clock-body blobs — the VClock/GCounter
    legs' tag-parameterized specialization of :func:`planes_from_wire`."""
    return planes_from_wire(
        blobs, universe, "clockish_ingest_wire",
        lambda engine, buf, offsets, cfg, dt: engine.clockish_ingest_wire(
            buf, offsets, tag, cfg.num_actors, dt
        ),
        planes_of_scalars,
        leg=_TAG_LEG.get(tag, "counters"),
    )


def clockish_to_wire(clocks, universe, tag, python_path):
    """Egress counterpart of :func:`clockish_from_wire`."""
    return planes_to_wire(
        clocks, universe, "clockish_encode_wire",
        lambda engine, host: engine.clockish_encode_wire(host, tag),
        python_path,
        leg=_TAG_LEG.get(tag, "counters"),
    )
