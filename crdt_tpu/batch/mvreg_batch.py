"""MVRegBatch — N multi-value registers (`/root/reference/src/mvreg.rs`).

Padded antichain per register: ``clocks u64[N, K, A]`` + payload ids
``vals u64[N, K]``; a slot is live iff its clock is non-empty.  Merge keeps
mutually-undominated values from both sides deduped by clock
(`mvreg.rs:121-153`) and re-packs into K slots, raising on overflow.
"""

from __future__ import annotations

import functools
from typing import Sequence

import jax
import jax.numpy as jnp
from flax import struct

from ..config import counter_dtype
from ..error import CapacityOverflowError, WireFormatError
from ..ops import clock_ops, mvreg_ops
from ..scalar.mvreg import MVReg
from ..utils.interning import Universe
from ..utils.hostmem import gc_paused
from ..obs.kernels import observed_kernel
from .vclock_batch import VClockBatch


@struct.dataclass
class MVRegBatch:
    clocks: jax.Array  # u64[N, K, A]
    vals: jax.Array  # u64[N, K] — interned payload ids

    @classmethod
    def zeros(cls, n: int, universe: Universe) -> "MVRegBatch":
        cfg = universe.config
        return cls(
            clocks=clock_ops.zeros((n, cfg.mv_capacity, cfg.num_actors), dtype=counter_dtype(cfg)),
            vals=jnp.zeros((n, cfg.mv_capacity), dtype=counter_dtype(cfg)),
        )

    @classmethod
    @gc_paused
    def from_scalar(cls, states: Sequence[MVReg], universe: Universe) -> "MVRegBatch":
        import numpy as np

        cfg = universe.config
        k, a = cfg.mv_capacity, cfg.num_actors
        dt = counter_dtype(cfg)
        clocks = np.zeros((len(states), k, a), dtype=dt)
        vals = np.zeros((len(states), k), dtype=dt)
        for i, reg in enumerate(states):
            if len(reg.vals) > k:
                raise ValueError(f"register {i} has {len(reg.vals)} values > mv_capacity {k}")
            for j, (vc, val) in enumerate(reg.vals):
                for actor, counter in vc.dots.items():
                    clocks[i, j, universe.actor_idx(actor)] = counter
                vals[i, j] = universe.member_id(val)
        return cls(clocks=jnp.asarray(clocks), vals=jnp.asarray(vals))

    @classmethod
    @gc_paused
    def from_wire(
        cls, blobs: Sequence[bytes], universe: Universe,
    ) -> "MVRegBatch":
        """Bulk ingest from wire blobs (``to_binary(mvreg)`` payloads) —
        the MVReg leg of the native bulk path (see
        :meth:`OrswotBatch.from_wire` for the contract: identity
        universe + native engine parse in parallel; anything outside the
        integer-keyed grammar falls back to the Python decoder per blob,
        so ``from_wire(blobs, uni)`` always equals
        ``from_scalar([from_binary(b) for b in blobs], uni)``)."""
        import numpy as np

        from ..utils.serde import from_binary
        from .wirebulk import (
            concat_blobs, fallback_reason, probe_engine, record_wire,
        )

        cfg = universe.config
        n = len(blobs)
        if n == 0:
            return cls.zeros(0, universe)
        engine = probe_engine(universe, "mvreg_ingest_wire", counter_dtype(cfg))
        if engine is None:
            record_wire("mvreg", "from_wire", fallback=n,
                        reason=fallback_reason(universe))
            return cls.from_scalar([from_binary(b) for b in blobs], universe)
        buf, offsets = concat_blobs(blobs)
        clocks, vals, status = engine.mvreg_ingest_wire(
            buf, offsets, cfg.mv_capacity, cfg.num_actors, counter_dtype(cfg)
        )
        n_fb = 0
        if status.any():
            hard = np.nonzero(status > 1)[0]
            if hard.size:
                first = int(hard[0])
                if int(status[first]) == 2:
                    raise WireFormatError(
                        f"register {first} has more values than mv_capacity "
                        f"{cfg.mv_capacity}"
                    )
                raise WireFormatError(
                    f"register {first}: actor outside the identity registry "
                    f"range [0, {cfg.num_actors})"
                )
            fb = np.nonzero(status == 1)[0].tolist()
            n_fb = len(fb)
            sub = cls.from_scalar(
                [from_binary(blobs[i]) for i in fb], universe
            )
            idx = np.asarray(fb, dtype=np.int64)
            clocks[idx] = np.asarray(sub.clocks)
            vals[idx] = np.asarray(sub.vals)
        record_wire("mvreg", "from_wire", native=n - n_fb, fallback=n_fb,
                    reason="grammar")
        return cls(clocks=jnp.asarray(clocks), vals=jnp.asarray(vals))

    @gc_paused
    def to_wire(self, universe: Universe) -> list[bytes]:
        """Bulk egress to wire blobs, byte-identical to
        ``[to_binary(s) for s in self.to_scalar(uni)]`` (the codec's
        sorted-pair-blob ordering is reproduced in C).  Counters/ids at
        or above 2^63 (u64 planes) and non-identity universes take the
        Python path."""
        import numpy as np

        from ..utils.serde import to_binary
        from .wirebulk import (
            fallback_reason, probe_engine, record_wire, slice_blobs,
        )

        n = self.clocks.shape[0]
        if n == 0:
            return []
        engine = probe_engine(
            universe, "mvreg_encode_wire", counter_dtype(universe.config)
        )
        reason = fallback_reason(universe)
        planes = None
        if engine is not None:
            planes = (np.asarray(self.clocks), np.asarray(self.vals))
            if planes[0].dtype.itemsize == 8 and any(
                int(p.max(initial=0)) >= 1 << 63 for p in planes
            ):
                engine = None
                reason = "overflow_zigzag"
        if engine is None:
            record_wire("mvreg", "to_wire", fallback=n, reason=reason)
            return [to_binary(s) for s in self.to_scalar(universe)]
        buf, offsets = engine.mvreg_encode_wire(*planes)
        record_wire("mvreg", "to_wire", native=n)
        return slice_blobs(buf, offsets)

    @gc_paused
    def to_scalar(self, universe: Universe) -> list[MVReg]:
        import numpy as np

        from .vclock_batch import row_to_vclock

        clocks = np.asarray(self.clocks)
        vals = np.asarray(self.vals)
        out = []
        for i in range(clocks.shape[0]):
            pairs = [
                (row_to_vclock(clocks[i, j], universe), universe.members.lookup(int(vals[i, j])))
                for j in range(clocks.shape[1])
                if clocks[i, j].any()
            ]
            out.append(MVReg(pairs))
        return out

    def merge(self, other: "MVRegBatch", check: bool = True) -> "MVRegBatch":
        """`mvreg.rs:121-153`; raises :class:`CapacityOverflowError` on
        antichain overflow past K (the executor's elastic recovery regrows
        via :meth:`with_capacity` and requeues)."""
        k = self.clocks.shape[-2]
        clocks, vals, overflow = _merge(self.clocks, self.vals, other.clocks, other.vals, k)
        if check and bool(jnp.any(overflow)):
            raise CapacityOverflowError(
                "MVReg antichain overflow: raise CrdtConfig.mv_capacity",
                member=True, deferred=False,
            )
        return MVRegBatch(clocks=clocks, vals=vals)

    def apply_put(self, op_clocks, op_vals, check: bool = True) -> "MVRegBatch":
        """Batched ``Op::Put`` (`mvreg.rs:158-186`), one op per register."""
        k = self.clocks.shape[-2]
        clocks, vals, overflow = _apply_put(
            self.clocks, self.vals, jnp.asarray(op_clocks), jnp.asarray(op_vals), k
        )
        if check and bool(jnp.any(overflow)):
            raise CapacityOverflowError(
                "MVReg antichain overflow: raise CrdtConfig.mv_capacity",
                member=True, deferred=False,
            )
        return MVRegBatch(clocks=clocks, vals=vals)

    def read_clock(self):
        """Folded clock per register (`mvreg.rs:216-222`)."""
        return mvreg_ops.read_clock(self.clocks)

    def truncate(self, clock) -> "MVRegBatch":
        """``Causal::truncate`` (`mvreg.rs:100-113`): subtract ``clock``
        from every val clock, dropping vals whose clock empties out.
        ``clock``: ``[N, A]`` counter array, one truncation clock per
        register.  Cannot overflow (it only removes)."""
        t = jnp.asarray(clock, dtype=self.clocks.dtype)
        clocks, vals = _truncate(self.clocks, self.vals, t)
        return MVRegBatch(clocks=clocks, vals=vals)

    # -- elastic-capacity protocol (crdt_tpu.parallel.JoinExecutor) ----------
    # The executor's generic slot-axis names are member/deferred; for a
    # register batch the one growable axis is the antichain (mv_capacity),
    # exposed under the protocol's "member" slot.  There is no deferred
    # axis — it reports 0 and with_capacity rejects attempts to grow it.

    @property
    def member_capacity(self) -> int:
        return self.clocks.shape[-2]

    @property
    def deferred_capacity(self) -> int:
        return 0

    def with_capacity(
        self, member_capacity: int | None = None,
        deferred_capacity: int | None = None,
    ) -> "MVRegBatch":
        """Pad the antichain axis to ``member_capacity`` slots (elastic
        regrowth; never shrinks — dominated-value compaction happens in
        merge, not here)."""
        if deferred_capacity:
            raise ValueError("MVRegBatch has no deferred axis to grow")
        import dataclasses

        from .val_kernels import MVRegKernel

        k = self.clocks.shape[-2]
        new_k = k if member_capacity is None else member_capacity
        if new_k < k:
            raise ValueError("with_capacity cannot shrink (would drop live slots)")
        if new_k == k:
            return self
        # one padding implementation for standalone AND map-nested
        # registers: the kernel's grow_state
        cur = MVRegKernel(mv_capacity=k, num_actors=self.clocks.shape[-1])
        clocks, vals = cur.grow_state(
            (self.clocks, self.vals), dataclasses.replace(cur, mv_capacity=new_k)
        )
        return MVRegBatch(clocks=clocks, vals=vals)


@observed_kernel("batch.mvreg.merge")
@functools.partial(jax.jit, static_argnums=(4,))
def _merge(ca, va, cb, vb, k_cap):
    clocks, vals, keep = mvreg_ops.merge(ca, va, cb, vb)
    return mvreg_ops.compact(clocks, vals, keep, k_cap)


@observed_kernel("batch.mvreg.apply_put")
@functools.partial(jax.jit, static_argnums=(4,))
def _apply_put(clocks, vals, op_clock, op_val, k_cap):
    clocks2, vals2, keep = mvreg_ops.apply_put(clocks, vals, op_clock, op_val)
    return mvreg_ops.compact(clocks2, vals2, keep, k_cap)


@observed_kernel("batch.mvreg.truncate")
@jax.jit
def _truncate(clocks, vals, t_clock):
    """Delegates to the nested-protocol kernel (`MVRegKernel.truncate`) —
    one home for the `mvreg.rs:100-113` semantics."""
    from .val_kernels import MVRegKernel

    kern = MVRegKernel(
        mv_capacity=clocks.shape[-2],
        num_actors=clocks.shape[-1],
        counter_bits=clocks.dtype.itemsize * 8,
    )
    (c, v), _ = kern.truncate((clocks, vals), t_clock)
    return c, v
