"""VClockBatch — N dense vector clocks on device.

The dense equivalent of `/root/reference/src/vclock.rs`: shape ``[N, A]``,
actor columns assigned by a :class:`crdt_tpu.utils.interning.Universe`.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
from flax import struct

from ..config import counter_dtype
from ..ops import clock_ops
from ..scalar.vclock import VClock
from ..utils.interning import Universe
from ..utils.hostmem import gc_paused
from ..obs.kernels import observed_kernel


def row_to_vclock(row, universe: Universe) -> VClock:
    """Convert one dense numpy clock row back to a scalar VClock.

    Shared by every batch type's ``to_scalar`` — operates on host numpy
    data, no device round-trips."""
    import numpy as np

    vc = VClock()
    for idx in np.nonzero(row)[0]:
        vc.dots[universe.actors.lookup(int(idx))] = int(row[idx])
    return vc


@struct.dataclass
class VClockBatch:
    clocks: jax.Array  # u64[N, A]

    # -- construction ----------------------------------------------------

    @classmethod
    def zeros(cls, n: int, universe: Universe) -> "VClockBatch":
        return cls(clocks=clock_ops.zeros(
            (n, universe.config.num_actors),
            dtype=counter_dtype(universe.config),
        ))

    @classmethod
    @gc_paused
    def from_scalar(cls, states: Sequence[VClock], universe: Universe) -> "VClockBatch":
        import numpy as np

        a = universe.config.num_actors
        buf = np.zeros((len(states), a), dtype=counter_dtype(universe.config))
        for i, vc in enumerate(states):
            for actor, counter in vc.dots.items():
                buf[i, universe.actor_idx(actor)] = counter
        return cls(clocks=jnp.asarray(buf))

    @gc_paused
    def to_scalar(self, universe: Universe) -> list[VClock]:
        import numpy as np

        return [row_to_vclock(row, universe) for row in np.asarray(self.clocks)]

    @classmethod
    @gc_paused
    def from_wire(cls, blobs: Sequence[bytes], universe: Universe) -> "VClockBatch":
        """Bulk ingest from wire blobs (``to_binary(vclock)`` payloads) —
        the causality-kernel leg of the native bulk path (see
        :meth:`crdt_tpu.batch.OrswotBatch.from_wire` for the contract:
        identity universe + native parallel parse, per-blob Python
        fallback outside the integer-keyed grammar, so the result always
        equals ``from_scalar([from_binary(b) for b in blobs], uni)``)."""
        from .wirebulk import WIRE_TAG_VCLOCK, clockish_from_wire

        return cls(clocks=jnp.asarray(clockish_from_wire(
            blobs, universe, WIRE_TAG_VCLOCK,
            lambda bs: cls.from_scalar(bs, universe).clocks,
        )))

    @gc_paused
    def to_wire(self, universe: Universe) -> list[bytes]:
        """Bulk egress to wire blobs, byte-identical to
        ``[to_binary(s) for s in self.to_scalar(uni)]``."""
        from ..utils.serde import to_binary
        from .wirebulk import WIRE_TAG_VCLOCK, clockish_to_wire

        return clockish_to_wire(
            self.clocks, universe, WIRE_TAG_VCLOCK,
            lambda: [to_binary(s) for s in self.to_scalar(universe)],
        )

    # -- CRDT contracts ---------------------------------------------------

    def merge(self, other: "VClockBatch") -> "VClockBatch":
        """Pairwise lattice join (`vclock.rs:131-137`)."""
        return VClockBatch(clocks=_merge(self.clocks, other.clocks))

    def witness(self, actor_idx, counter) -> "VClockBatch":
        return VClockBatch(
            clocks=clock_ops.witness(self.clocks, jnp.asarray(actor_idx), jnp.asarray(counter))
        )

    def subtract(self, other: "VClockBatch") -> "VClockBatch":
        return VClockBatch(clocks=clock_ops.subtract(self.clocks, other.clocks))

    def intersection(self, other: "VClockBatch") -> "VClockBatch":
        return VClockBatch(clocks=clock_ops.intersection(self.clocks, other.clocks))

    def truncate(self, other: "VClockBatch") -> "VClockBatch":
        return VClockBatch(clocks=clock_ops.truncate(self.clocks, other.clocks))

    def leq(self, other: "VClockBatch"):
        return clock_ops.leq(self.clocks, other.clocks)

    def concurrent(self, other: "VClockBatch"):
        return clock_ops.concurrent(self.clocks, other.clocks)

    def is_empty(self):
        return clock_ops.is_empty(self.clocks)


@observed_kernel("batch.vclock.merge")
@jax.jit
def _merge(a, b):
    return clock_ops.merge(a, b)
