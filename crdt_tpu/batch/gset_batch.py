"""GSetBatch — N grow-only sets as a membership bitmap.

The reference GSet (`/root/reference/src/gset.rs`) is a BTreeSet with
merge = union; the dense form is ``bool[N, U]`` over the interned member
universe, so merge is a single elementwise OR — the simplest possible
lattice join on the VPU.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
from flax import struct

from ..error import WireFormatError
from ..scalar.gset import GSet
from ..utils.interning import Universe
from ..utils.hostmem import gc_paused
from ..obs.kernels import observed_kernel


@struct.dataclass
class GSetBatch:
    bits: jax.Array  # bool[N, U]

    @classmethod
    def zeros(cls, n: int, member_capacity: int) -> "GSetBatch":
        return cls(bits=jnp.zeros((n, member_capacity), dtype=bool))

    @classmethod
    @gc_paused
    def from_scalar(
        cls, states: Sequence[GSet], universe: Universe, member_capacity: int
    ) -> "GSetBatch":
        import numpy as np

        buf = np.zeros((len(states), member_capacity), dtype=bool)
        for i, s in enumerate(states):
            for e in s.value:
                mid = universe.member_id(e)
                if mid >= member_capacity:
                    raise ValueError(
                        f"member universe overflow: id {mid} >= capacity {member_capacity}"
                    )
                buf[i, mid] = True
        return cls(bits=jnp.asarray(buf))

    @classmethod
    @gc_paused
    def from_wire(
        cls, blobs: Sequence[bytes], universe: Universe,
        member_capacity: int,
    ) -> "GSetBatch":
        """Bulk ingest from wire blobs (``to_binary(gset)`` payloads) —
        the GSet leg of the native bulk path (contract as in
        :meth:`OrswotBatch.from_wire`: identity universe + native engine,
        Python fallback per non-conforming blob, always equal to
        ``from_scalar([from_binary(b) for b in blobs], uni, U)``)."""
        import numpy as np

        from ..utils.serde import from_binary
        from .wirebulk import (
            concat_blobs, fallback_reason, probe_engine, record_wire,
        )

        n = len(blobs)
        if n == 0:
            return cls.zeros(0, member_capacity)
        engine = probe_engine(universe, "gset_ingest_wire")
        if engine is None:
            record_wire("gset", "from_wire", fallback=n,
                        reason=fallback_reason(universe))
            return cls.from_scalar(
                [from_binary(b) for b in blobs], universe, member_capacity
            )
        buf, offsets = concat_blobs(blobs)
        bits, status = engine.gset_ingest_wire(buf, offsets, member_capacity)
        n_fb = 0
        if status.any():
            hard = np.nonzero(status == 2)[0]
            if hard.size:
                raise WireFormatError(
                    f"member universe overflow: object {int(hard[0])} has a "
                    f"member id >= capacity {member_capacity}"
                )
            fb = np.nonzero(status)[0].tolist()
            n_fb = len(fb)
            sub = cls.from_scalar(
                [from_binary(blobs[i]) for i in fb], universe, member_capacity
            )
            idx = np.asarray(fb, dtype=np.int64)
            bits[idx] = np.asarray(sub.bits)
        record_wire("gset", "from_wire", native=n - n_fb, fallback=n_fb,
                    reason="grammar")
        return cls(bits=jnp.asarray(bits))

    @gc_paused
    def to_wire(self, universe: Universe) -> list[bytes]:
        """Bulk egress to wire blobs, byte-identical to
        ``[to_binary(s) for s in self.to_scalar(uni)]`` (sorted-items
        order reproduced in C); non-identity universes take the Python
        path."""
        from ..utils.serde import to_binary
        from .wirebulk import (
            fallback_reason, probe_engine, record_wire, slice_blobs,
        )

        n = self.bits.shape[0]
        if n == 0:
            return []
        engine = probe_engine(universe, "gset_encode_wire")
        if engine is None:
            record_wire("gset", "to_wire", fallback=n,
                        reason=fallback_reason(universe))
            return [to_binary(s) for s in self.to_scalar(universe)]
        import numpy as np

        buf, offsets = engine.gset_encode_wire(np.asarray(self.bits))
        record_wire("gset", "to_wire", native=n)
        return slice_blobs(buf, offsets)

    @gc_paused
    def to_scalar(self, universe: Universe) -> list[GSet]:
        import numpy as np

        out = []
        for row in np.asarray(self.bits):
            out.append(GSet({universe.members.lookup(int(i)) for i in np.nonzero(row)[0]}))
        return out

    def merge(self, other: "GSetBatch", check: bool = True) -> "GSetBatch":
        """Union (`gset.rs:30-34`).  Sides of different bitmap widths are
        first grown to the wider one (union over the missing columns is a
        no-op, so widening is state-neutral).  ``check`` is accepted for
        the executor's uniform merge signature; a same-width union cannot
        overflow, so there is nothing to check."""
        wa, wb = self.bits.shape[-1], other.bits.shape[-1]
        if wa != wb:
            w = max(wa, wb)
            return GSetBatch(bits=_merge(
                self.with_capacity(w).bits, other.with_capacity(w).bits
            ))
        return GSetBatch(bits=_merge(self.bits, other.bits))

    # -- elastic-capacity protocol (crdt_tpu.parallel.JoinExecutor) ----------
    # The bitmap width is the one growable axis (the member-universe bound
    # _check_ids enforces); merge itself can never overflow — same-width
    # OR — so growth happens ahead of inserts of newly-interned members.

    @property
    def member_capacity(self) -> int:
        return self.bits.shape[-1]

    @property
    def deferred_capacity(self) -> int:
        return 0

    def with_capacity(
        self, member_capacity: int | None = None,
        deferred_capacity: int | None = None,
    ) -> "GSetBatch":
        """Widen the membership bitmap (new columns start absent)."""
        if deferred_capacity:
            raise ValueError("GSetBatch has no deferred axis to grow")
        w = self.bits.shape[-1]
        new_w = w if member_capacity is None else member_capacity
        if new_w < w:
            raise ValueError("with_capacity cannot shrink (would drop members)")
        if new_w == w:
            return self
        pad = [(0, 0)] * (self.bits.ndim - 1) + [(0, new_w - w)]
        return GSetBatch(bits=jnp.pad(self.bits, pad))

    def _check_ids(self, member_ids):
        """The member registry is unbounded; the bitmap is not.  Reject ids
        past the bitmap width instead of silently dropping them (insert)
        or reading clamped garbage (contains)."""
        import numpy as np

        ids = np.asarray(member_ids)
        cap = self.bits.shape[-1]
        if (ids < 0).any() or (ids >= cap).any():
            bad = ids[(ids < 0) | (ids >= cap)]
            raise ValueError(f"member id(s) {bad.tolist()} out of bitmap capacity {cap}")
        return jnp.asarray(member_ids)

    def insert(self, member_ids) -> "GSetBatch":
        ids = self._check_ids(member_ids)
        onehot = jnp.arange(self.bits.shape[-1]) == ids[..., None]
        return GSetBatch(bits=self.bits | onehot)

    def contains(self, member_ids):
        ids = self._check_ids(member_ids)
        return jnp.take_along_axis(self.bits, ids[..., None], axis=-1)[..., 0]


@observed_kernel("batch.gset.merge")
@jax.jit
def _merge(a, b):
    return a | b
