"""PNCounterBatch — N inc/dec counters (`/root/reference/src/pncounter.rs`).

Two stacked GCounter planes: ``u64[N, 2, A]`` (P = plane 0, N = plane 1,
`pncounter.rs:33-36`); merge is one fused max, value is P − N.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
from flax import struct

from ..ops import clock_ops, counter_ops
from ..scalar.pncounter import PNCounter
from ..utils.interning import Universe
from ..utils.hostmem import gc_paused
from ..obs.kernels import observed_kernel
from ..config import counter_dtype
from .vclock_batch import VClockBatch


@struct.dataclass
class PNCounterBatch:
    planes: jax.Array  # u64[N, 2, A]

    @classmethod
    def zeros(cls, n: int, universe: Universe) -> "PNCounterBatch":
        return cls(planes=clock_ops.zeros(
            (n, 2, universe.config.num_actors),
            dtype=counter_dtype(universe.config),
        ))

    @classmethod
    @gc_paused
    def from_scalar(cls, states: Sequence[PNCounter], universe: Universe) -> "PNCounterBatch":
        p = VClockBatch.from_scalar([s.p.inner for s in states], universe)
        n = VClockBatch.from_scalar([s.n.inner for s in states], universe)
        return cls(planes=jnp.stack([p.clocks, n.clocks], axis=1))

    @gc_paused
    def to_scalar(self, universe: Universe) -> list[PNCounter]:
        from ..scalar.gcounter import GCounter

        p = VClockBatch(clocks=self.planes[:, 0]).to_scalar(universe)
        n = VClockBatch(clocks=self.planes[:, 1]).to_scalar(universe)
        return [PNCounter(GCounter(pi), GCounter(ni)) for pi, ni in zip(p, n)]

    @classmethod
    @gc_paused
    def from_wire(cls, blobs: Sequence[bytes], universe: Universe) -> "PNCounterBatch":
        """Bulk ingest from wire blobs (``to_binary(pncounter)`` payloads
        — two clock bodies, P then N, `pncounter.rs:33-36`).  Contract as
        :meth:`crdt_tpu.batch.OrswotBatch.from_wire`: identity universe +
        native parallel parse, per-blob Python fallback, always equal to
        ``from_scalar([from_binary(b) for b in blobs], uni)``."""
        from .wirebulk import planes_from_wire

        return cls(planes=jnp.asarray(planes_from_wire(
            blobs, universe, "pncounter_ingest_wire",
            lambda engine, buf, offsets, cfg, dt: engine.pncounter_ingest_wire(
                buf, offsets, cfg.num_actors, dt
            ),
            lambda bs: cls.from_scalar(bs, universe).planes,
            leg="pncounter",
        )))

    @gc_paused
    def to_wire(self, universe: Universe) -> list[bytes]:
        """Bulk egress to wire blobs, byte-identical to
        ``[to_binary(s) for s in self.to_scalar(uni)]``."""
        from ..utils.serde import to_binary
        from .wirebulk import planes_to_wire

        return planes_to_wire(
            self.planes, universe, "pncounter_encode_wire",
            lambda engine, host: engine.pncounter_encode_wire(host),
            lambda: [to_binary(s) for s in self.to_scalar(universe)],
            leg="pncounter",
        )

    def merge(self, other: "PNCounterBatch") -> "PNCounterBatch":
        """`pncounter.rs:90-95`."""
        return PNCounterBatch(planes=_merge(self.planes, other.planes))

    def inc(self, actor_idx) -> "PNCounterBatch":
        return self._bump(actor_idx, 0)

    def dec(self, actor_idx) -> "PNCounterBatch":
        return self._bump(actor_idx, 1)

    def _bump(self, actor_idx, plane: int) -> "PNCounterBatch":
        idx = jnp.asarray(actor_idx)
        target = self.planes[:, plane]
        counter = clock_ops.inc_counter(target, idx)
        updated = clock_ops.witness(target, idx, counter)
        return PNCounterBatch(planes=self.planes.at[:, plane].set(updated))

    def value(self):
        """`pncounter.rs:117-119`."""
        return counter_ops.pncounter_value(self.planes)


@observed_kernel("batch.pncounter.merge")
@jax.jit
def _merge(a, b):
    return counter_ops.pncounter_merge(a, b)
