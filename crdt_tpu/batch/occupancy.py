"""Plane-occupancy kernels — how full is every dense plane, exactly.

ROADMAP's causal-GC item admits the memory story for long-lived fleets
is "restart", and until this module nothing even *measured* the planes:
how many member slots are live vs padding, how many deferred-remove
tombstones a fleet is dragging, how many bytes the planes actually pin
on device, how close the busiest object is to the next capacity regrow.
These kernels are the oracle that item needs — and the signal
:mod:`crdt_tpu.obs.capacity` turns into ``crdt_tpu_capacity_*`` gauges,
growth rates and time-to-overflow ETAs.

One jitted reduction per plane family (a handful of ``count_nonzero``/
``sum``/``max`` folds over planes already resident on device), one tiny
int64 vector fetched to host per sample — cheap enough to sample every
gossip round.  Exact plane bytes are computed host-side from the live
arrays (``x.nbytes`` per plane leaf), so the reported number equals the
actual device-buffer footprint by construction; the long-soak test
(``tests/test_capacity_soak.py``) pins that equality under churn.

Every kernel here is rowed into the kernelcheck ``KernelSpec`` manifest
(``crdt_tpu/analysis/kernels.py``) with trace ladders across the
canonical capacity-regrow rungs, so the PR 8 jaxpr gate covers them
like any other kernel.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..obs.capacity import Occupancy
from ..obs.kernels import observed_kernel
from ..ops.orswot_ops import EMPTY

#: key/deferred-slot sentinel in MapBatch planes (-1 = empty)
_MAP_EMPTY = -1


def _tree_nbytes(*planes) -> int:
    """Exact byte footprint of a pytree of arrays — what the buffers
    actually pin (jax and numpy arrays agree on ``nbytes``)."""
    return int(sum(leaf.nbytes for leaf in jax.tree_util.tree_leaves(planes)))


# ---------------------------------------------------------------------------
# the jitted reductions (one per plane family, one host fetch each)
# ---------------------------------------------------------------------------


@observed_kernel("batch.occupancy.orswot")
@jax.jit
def _orswot_occupancy(clock, ids, dots, d_ids, d_clocks):
    """ORSWOT plane occupancy as one int64[6] fetch: live member slots
    (total, busiest object), live deferred tombstone rows (total,
    busiest object), populated clock cells, actor columns in use."""
    live = ids != EMPTY
    tombs = d_ids != EMPTY
    return jnp.stack(
        [
            jnp.sum(live),
            jnp.max(jnp.sum(live, axis=1)),
            jnp.sum(tombs),
            jnp.max(jnp.sum(tombs, axis=1)),
            jnp.count_nonzero(clock),
            jnp.sum(jnp.any(clock != 0, axis=0)),
        ]
    ).astype(jnp.int64)


@observed_kernel("batch.occupancy.clock")
@jax.jit
def _clock_occupancy(plane):
    """``[N, A]`` clock/counter plane occupancy as one int64[4] fetch:
    populated cells (total, busiest object), objects with any dot,
    actor columns in use."""
    nz = plane != 0
    return jnp.stack(
        [
            jnp.sum(nz),
            jnp.max(jnp.sum(nz, axis=1)),
            jnp.sum(jnp.any(nz, axis=1)),
            jnp.sum(jnp.any(nz, axis=0)),
        ]
    ).astype(jnp.int64)


@observed_kernel("batch.occupancy.pncounter")
@jax.jit
def _pn_occupancy(planes):
    """``[N, 2, A]`` PN-counter plane occupancy as one int64[4] fetch:
    populated cells across both planes, the busiest object's distinct
    live actors (P and N folded — the actor is live if either plane
    holds a dot), objects in use, actor columns in use."""
    merged = jnp.max(planes, axis=1)  # [N, A]: actor live in P or N
    nz = merged != 0
    return jnp.stack(
        [
            jnp.count_nonzero(planes),
            jnp.max(jnp.sum(nz, axis=1)),
            jnp.sum(jnp.any(nz, axis=1)),
            jnp.sum(jnp.any(nz, axis=0)),
        ]
    ).astype(jnp.int64)


@observed_kernel("batch.occupancy.map")
@jax.jit
def _map_occupancy(clock, keys, entry_clocks, d_keys, d_clocks):
    """Map plane occupancy as one int64[6] fetch: live key slots
    (total, busiest object), live deferred tombstone rows (total,
    busiest object), populated clock cells, actor columns in use."""
    live = keys != _MAP_EMPTY
    tombs = d_keys != _MAP_EMPTY
    return jnp.stack(
        [
            jnp.sum(live),
            jnp.max(jnp.sum(live, axis=1)),
            jnp.sum(tombs),
            jnp.max(jnp.sum(tombs, axis=1)),
            jnp.count_nonzero(clock),
            jnp.sum(jnp.any(clock != 0, axis=0)),
        ]
    ).astype(jnp.int64)


# ---------------------------------------------------------------------------
# the dispatch
# ---------------------------------------------------------------------------


def occupancy_of(batch) -> Occupancy:
    """The :class:`Occupancy` of any dense-plane batch type.

    Dispatches on the batch's plane attributes (the same duck typing
    the executor's regrow path uses): ORSWOT member/deferred slot
    tables, Map key/deferred tables, PN-counter ``[N, 2, A]`` planes,
    and the ``[N, A]`` clock planes (VClock and GCounter share the
    shape; ``kind`` keeps them apart).  Raises ``TypeError`` for batch
    types without dense planes to measure.
    """
    if hasattr(batch, "d_ids") and hasattr(batch, "ids"):
        stats = np.asarray(_orswot_occupancy(  # crdtlint: disable=SC03 — occupancy observatory sample point, six ints per gauge cadence
            batch.clock, batch.ids, batch.dots, batch.d_ids, batch.d_clocks
        ))
        n, m = batch.ids.shape
        return Occupancy(
            kind="orswot", objects=n,
            bytes=_tree_nbytes(batch.clock, batch.ids, batch.dots,
                               batch.d_ids, batch.d_clocks),
            slot_capacity=m, slots=n * m,
            live=int(stats[0]), live_max=int(stats[1]),
            tombstone_capacity=int(batch.d_ids.shape[1]),
            tombstones=int(stats[2]), tombstones_max=int(stats[3]),
            actors=int(batch.clock.shape[1]), actors_live=int(stats[5]),
        )
    if hasattr(batch, "d_keys") and hasattr(batch, "keys"):
        stats = np.asarray(_map_occupancy(  # crdtlint: disable=SC03 — occupancy observatory sample point, six ints per gauge cadence
            batch.clock, batch.keys, batch.entry_clocks,
            batch.d_keys, batch.d_clocks
        ))
        n, k = batch.keys.shape
        return Occupancy(
            kind="map", objects=n,
            bytes=_tree_nbytes(batch.state),
            slot_capacity=k, slots=n * k,
            live=int(stats[0]), live_max=int(stats[1]),
            tombstone_capacity=int(batch.d_keys.shape[1]),
            tombstones=int(stats[2]), tombstones_max=int(stats[3]),
            actors=int(batch.clock.shape[1]), actors_live=int(stats[5]),
        )
    if hasattr(batch, "planes"):
        stats = np.asarray(_pn_occupancy(batch.planes))  # crdtlint: disable=SC03 — occupancy observatory sample point, six ints per gauge cadence
        n, _, a = batch.planes.shape
        return Occupancy(
            kind="pncounter", objects=n, bytes=_tree_nbytes(batch.planes),
            slot_capacity=a, slots=n * 2 * a,
            live=int(stats[0]), live_max=int(stats[1]),
            actors=a, actors_live=int(stats[3]),
        )
    if hasattr(batch, "clocks"):
        stats = np.asarray(_clock_occupancy(batch.clocks))  # crdtlint: disable=SC03 — occupancy observatory sample point, six ints per gauge cadence
        n, a = batch.clocks.shape
        kind = type(batch).__name__.removesuffix("Batch").lower()
        return Occupancy(
            kind=kind or "clock", objects=n,
            bytes=_tree_nbytes(batch.clocks),
            slot_capacity=a, slots=n * a,
            live=int(stats[0]), live_max=int(stats[1]),
            actors=a, actors_live=int(stats[3]),
        )
    raise TypeError(
        f"no occupancy kernel for {type(batch).__name__} (dense-plane "
        "batch types only: Orswot/VClock/GCounter/PNCounter/Map)"
    )
