"""The TPU batch engine: SoA CRDT batches behind the scalar contracts.

Each type here is a frozen pytree (``flax.struct``) of dense device arrays —
N CRDT replicas/objects per batch — whose ``merge`` is a jitted lattice-join
kernel from :mod:`crdt_tpu.ops`, vectorized over the object axis and sharded
over a device mesh by :mod:`crdt_tpu.parallel`.

Conversion to/from the scalar engine (``from_scalar`` / ``to_scalar``) is the
parity boundary: tests pack random scalar states, merge on device, unpack,
and compare bit-for-bit with the scalar merge (SURVEY.md §7.0).
"""

from ..config import enable_x64 as _enable_x64

_enable_x64()

from .vclock_batch import VClockBatch
from .gcounter_batch import GCounterBatch
from .pncounter_batch import PNCounterBatch
from .lwwreg_batch import LWWRegBatch
from .mvreg_batch import MVRegBatch
from .orswot_batch import OrswotBatch
from .wireloop import PipelinedWireLoop
from .gset_batch import GSetBatch
from .map_batch import MapBatch
from .val_kernels import MapKernel, MVRegKernel, OrswotKernel

__all__ = [
    "GCounterBatch",
    "GSetBatch",
    "LWWRegBatch",
    "MapBatch",
    "MapKernel",
    "MVRegBatch",
    "MVRegKernel",
    "OrswotBatch",
    "OrswotKernel",
    "PipelinedWireLoop",
    "PNCounterBatch",
    "VClockBatch",
]
