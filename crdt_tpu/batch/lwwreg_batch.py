"""LWWRegBatch — N last-write-wins registers (`/root/reference/src/lwwreg.rs`).

Columns ``(vals u64[N], markers u64[N])``.  Values are interned payload ids
(any hashable Python value) or raw u64s; markers are unsigned ints (the
reference allows any Ord marker — the 10M-register benchmark uses u64
timestamps).  ``merge`` surfaces per-element conflicts as a bitmap and the
host raises :class:`ConflictingMarker`, keeping scalar error parity
(`lwwreg.rs:56-66`, SURVEY.md §7.3).
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
from flax import struct

from ..config import counter_dtype
from ..error import ConflictingMarker
from ..ops import lww_ops
from ..scalar.lwwreg import LWWReg
from ..utils.interning import Universe
from ..obs.kernels import observed_kernel
from ..utils.hostmem import gc_paused


@struct.dataclass
class LWWRegBatch:
    vals: jax.Array  # u64[N] — payload ids (interned via universe.members)
    markers: jax.Array  # u64[N]

    @classmethod
    @gc_paused
    def from_scalar(cls, states: Sequence[LWWReg], universe: Universe) -> "LWWRegBatch":
        import numpy as np

        # markers are TIMESTAMPS (u64 in the reference, lwwreg.rs:16-24),
        # not per-actor op counters — CrdtConfig.counter_bits deliberately
        # does NOT apply here (an epoch-millis marker overflows uint32)
        dt = counter_dtype()
        vals = np.asarray([universe.member_id(s.val) for s in states], dtype=dt)
        markers = np.asarray([s.marker for s in states], dtype=dt)
        return cls(vals=jnp.asarray(vals), markers=jnp.asarray(markers))

    @classmethod
    @gc_paused
    def from_wire(
        cls, blobs: Sequence[bytes], universe: Universe,
    ) -> "LWWRegBatch":
        """Bulk ingest from wire blobs (``to_binary(lwwreg)`` payloads) —
        the LWW leg of the native bulk path (contract as in
        :meth:`OrswotBatch.from_wire`: identity universe + native engine,
        Python fallback per non-conforming blob, always equal to
        ``from_scalar([from_binary(b) for b in blobs], uni)``)."""
        import numpy as np

        from ..utils.serde import from_binary
        from .wirebulk import (
            concat_blobs, fallback_reason, probe_engine, record_wire,
        )

        n = len(blobs)
        if n == 0:
            return cls(
                vals=jnp.zeros(0, dtype=counter_dtype()),
                markers=jnp.zeros(0, dtype=counter_dtype()),
            )
        engine = probe_engine(universe, "lww_ingest_wire", np.uint64)
        reason = fallback_reason(universe)
        if np.dtype(counter_dtype()) != np.uint64:
            # CRDT_TPU_NO_X64 narrows the marker planes to uint32; the C
            # codec is u64-only and jnp.asarray would silently truncate
            # markers the Python path rejects with OverflowError — take
            # the Python path so the contract (exact from_scalar
            # equality) holds in that mode too
            engine = None
            reason = "narrow_counters"
        if engine is None:
            record_wire("lwwreg", "from_wire", fallback=n, reason=reason)
            return cls.from_scalar([from_binary(b) for b in blobs], universe)
        buf, offsets = concat_blobs(blobs)
        vals, markers, status = engine.lww_ingest_wire(buf, offsets)
        n_fb = 0
        if status.any():
            fb = np.nonzero(status)[0].tolist()
            n_fb = len(fb)
            sub = cls.from_scalar(
                [from_binary(blobs[i]) for i in fb], universe
            )
            idx = np.asarray(fb, dtype=np.int64)
            vals[idx] = np.asarray(sub.vals)
            markers[idx] = np.asarray(sub.markers)
        record_wire("lwwreg", "from_wire", native=n - n_fb, fallback=n_fb,
                    reason="grammar")
        return cls(vals=jnp.asarray(vals), markers=jnp.asarray(markers))

    @gc_paused
    def to_wire(self, universe: Universe) -> list[bytes]:
        """Bulk egress to wire blobs, byte-identical to
        ``[to_binary(s) for s in self.to_scalar(uni)]``.  Values or
        markers at or above 2^63 and non-identity universes take the
        Python path (the codec's zigzag covers them as big ints)."""
        import numpy as np

        from ..utils.serde import to_binary
        from .wirebulk import (
            fallback_reason, probe_engine, record_wire, slice_blobs,
        )

        n = self.vals.shape[0]
        if n == 0:
            return []
        engine = probe_engine(universe, "lww_encode_wire", np.uint64)
        reason = fallback_reason(universe)
        planes = None
        if engine is not None:
            planes = (np.asarray(self.vals), np.asarray(self.markers))
            if any(
                p.dtype != np.uint64 or int(p.max(initial=0)) >= 1 << 63
                for p in planes
            ):
                # non-u64 planes (CRDT_TPU_NO_X64) would be reinterpreted
                # by the u64-only C encoder; >=2^63 exceeds its zigzag
                engine = None
                reason = "overflow_zigzag"
        if engine is None:
            record_wire("lwwreg", "to_wire", fallback=n, reason=reason)
            return [to_binary(s) for s in self.to_scalar(universe)]
        buf, offsets = engine.lww_encode_wire(*planes)
        record_wire("lwwreg", "to_wire", native=n)
        return slice_blobs(buf, offsets)

    @gc_paused
    def to_scalar(self, universe: Universe) -> list[LWWReg]:
        import numpy as np

        vals = np.asarray(self.vals)
        markers = np.asarray(self.markers)
        return [
            LWWReg(val=universe.members.lookup(int(v)), marker=int(m))
            for v, m in zip(vals, markers)
        ]

    def merge(self, other: "LWWRegBatch", check: bool = True) -> "LWWRegBatch":
        """Pairwise merge; raises :class:`ConflictingMarker` if any element
        hit an equal-marker/different-value conflict (`lwwreg.rs:56-66`).

        Pass ``check=False`` to skip the host sync and fetch the bitmap
        later via :meth:`merge_with_conflicts` semantics."""
        vals, markers, conflict = _merge(self.vals, self.markers, other.vals, other.markers)
        if check and bool(jnp.any(conflict)):
            idx = jnp.nonzero(conflict)[0]
            raise ConflictingMarker(f"{idx.shape[0]} conflicting marker(s), first at {int(idx[0])}")
        return LWWRegBatch(vals=vals, markers=markers)

    def merge_with_conflicts(self, other: "LWWRegBatch"):
        """Returns ``(merged, conflict_bitmap)`` without host sync."""
        vals, markers, conflict = _merge(self.vals, self.markers, other.vals, other.markers)
        return LWWRegBatch(vals=vals, markers=markers), conflict

    def update(self, new_vals, new_markers):
        """Batched ``update`` (`lwwreg.rs:104-118`); raises on conflict."""
        vals, markers, conflict = _merge(self.vals, self.markers, jnp.asarray(new_vals), jnp.asarray(new_markers))
        if bool(jnp.any(conflict)):
            raise ConflictingMarker()
        return LWWRegBatch(vals=vals, markers=markers)


@observed_kernel("batch.lwwreg.merge")
@jax.jit
def _merge(va, ma, vb, mb):
    return lww_ops.merge(va, ma, vb, mb)
