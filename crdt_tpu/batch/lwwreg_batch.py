"""LWWRegBatch — N last-write-wins registers (`/root/reference/src/lwwreg.rs`).

Columns ``(vals u64[N], markers u64[N])``.  Values are interned payload ids
(any hashable Python value) or raw u64s; markers are unsigned ints (the
reference allows any Ord marker — the 10M-register benchmark uses u64
timestamps).  ``merge`` surfaces per-element conflicts as a bitmap and the
host raises :class:`ConflictingMarker`, keeping scalar error parity
(`lwwreg.rs:56-66`, SURVEY.md §7.3).
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
from flax import struct

from ..config import counter_dtype
from ..error import ConflictingMarker
from ..ops import lww_ops
from ..scalar.lwwreg import LWWReg
from ..utils.interning import Universe
from ..utils.hostmem import gc_paused


@struct.dataclass
class LWWRegBatch:
    vals: jax.Array  # u64[N] — payload ids (interned via universe.members)
    markers: jax.Array  # u64[N]

    @classmethod
    @gc_paused
    def from_scalar(cls, states: Sequence[LWWReg], universe: Universe) -> "LWWRegBatch":
        import numpy as np

        # markers are TIMESTAMPS (u64 in the reference, lwwreg.rs:16-24),
        # not per-actor op counters — CrdtConfig.counter_bits deliberately
        # does NOT apply here (an epoch-millis marker overflows uint32)
        dt = counter_dtype()
        vals = np.asarray([universe.member_id(s.val) for s in states], dtype=dt)
        markers = np.asarray([s.marker for s in states], dtype=dt)
        return cls(vals=jnp.asarray(vals), markers=jnp.asarray(markers))

    @gc_paused
    def to_scalar(self, universe: Universe) -> list[LWWReg]:
        import numpy as np

        vals = np.asarray(self.vals)
        markers = np.asarray(self.markers)
        return [
            LWWReg(val=universe.members.lookup(int(v)), marker=int(m))
            for v, m in zip(vals, markers)
        ]

    def merge(self, other: "LWWRegBatch", check: bool = True) -> "LWWRegBatch":
        """Pairwise merge; raises :class:`ConflictingMarker` if any element
        hit an equal-marker/different-value conflict (`lwwreg.rs:56-66`).

        Pass ``check=False`` to skip the host sync and fetch the bitmap
        later via :meth:`merge_with_conflicts` semantics."""
        vals, markers, conflict = _merge(self.vals, self.markers, other.vals, other.markers)
        if check and bool(jnp.any(conflict)):
            idx = jnp.nonzero(conflict)[0]
            raise ConflictingMarker(f"{idx.shape[0]} conflicting marker(s), first at {int(idx[0])}")
        return LWWRegBatch(vals=vals, markers=markers)

    def merge_with_conflicts(self, other: "LWWRegBatch"):
        """Returns ``(merged, conflict_bitmap)`` without host sync."""
        vals, markers, conflict = _merge(self.vals, self.markers, other.vals, other.markers)
        return LWWRegBatch(vals=vals, markers=markers), conflict

    def update(self, new_vals, new_markers):
        """Batched ``update`` (`lwwreg.rs:104-118`); raises on conflict."""
        vals, markers, conflict = _merge(self.vals, self.markers, jnp.asarray(new_vals), jnp.asarray(new_markers))
        if bool(jnp.any(conflict)):
            raise ConflictingMarker()
        return LWWRegBatch(vals=vals, markers=markers)


@jax.jit
def _merge(va, ma, vb, mb):
    return lww_ops.merge(va, ma, vb, mb)
