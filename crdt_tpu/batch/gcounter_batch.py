"""GCounterBatch — N grow-only counters (`/root/reference/src/gcounter.rs`).

A GCounter *is* a VClock (`gcounter.rs:26-28`); the batch reuses the clock
buffer and adds the sum reduction for ``value`` (`gcounter.rs:76-78`).
"""

from __future__ import annotations

from typing import Sequence

import jax
from flax import struct

from ..ops import clock_ops, counter_ops
from ..scalar.gcounter import GCounter
from ..utils.interning import Universe
from ..utils.hostmem import gc_paused
from ..obs.kernels import observed_kernel
from ..config import counter_dtype
from .vclock_batch import VClockBatch


@struct.dataclass
class GCounterBatch:
    clocks: jax.Array  # u64[N, A]

    @classmethod
    def zeros(cls, n: int, universe: Universe) -> "GCounterBatch":
        return cls(clocks=clock_ops.zeros(
            (n, universe.config.num_actors),
            dtype=counter_dtype(universe.config),
        ))

    @classmethod
    @gc_paused
    def from_scalar(cls, states: Sequence[GCounter], universe: Universe) -> "GCounterBatch":
        inner = VClockBatch.from_scalar([g.inner for g in states], universe)
        return cls(clocks=inner.clocks)

    @gc_paused
    def to_scalar(self, universe: Universe) -> list[GCounter]:
        return [GCounter(vc) for vc in VClockBatch(clocks=self.clocks).to_scalar(universe)]

    @classmethod
    @gc_paused
    def from_wire(cls, blobs: Sequence[bytes], universe: Universe) -> "GCounterBatch":
        """Bulk ingest from wire blobs (``to_binary(gcounter)`` payloads,
        `gcounter.rs:26-28`: a GCounter IS a VClock, so this is the
        clock-body codec under the GCounter tag).  Contract as
        :meth:`crdt_tpu.batch.OrswotBatch.from_wire`: identity universe +
        native parallel parse, per-blob Python fallback, always equal to
        ``from_scalar([from_binary(b) for b in blobs], uni)``."""
        import jax.numpy as jnp

        from .wirebulk import WIRE_TAG_GCOUNTER, clockish_from_wire

        return cls(clocks=jnp.asarray(clockish_from_wire(
            blobs, universe, WIRE_TAG_GCOUNTER,
            lambda bs: cls.from_scalar(bs, universe).clocks,
        )))

    @gc_paused
    def to_wire(self, universe: Universe) -> list[bytes]:
        """Bulk egress to wire blobs, byte-identical to
        ``[to_binary(s) for s in self.to_scalar(uni)]``."""
        from ..utils.serde import to_binary
        from .wirebulk import WIRE_TAG_GCOUNTER, clockish_to_wire

        return clockish_to_wire(
            self.clocks, universe, WIRE_TAG_GCOUNTER,
            lambda: [to_binary(s) for s in self.to_scalar(universe)],
        )

    def merge(self, other: "GCounterBatch") -> "GCounterBatch":
        """`gcounter.rs:58-62`."""
        return GCounterBatch(clocks=_merge(self.clocks, other.clocks))

    def inc(self, actor_idx) -> "GCounterBatch":
        """Increment each counter at the given actor column (apply of the
        ``inc`` dot, `gcounter.rs:71-73`)."""
        import jax.numpy as jnp

        idx = jnp.asarray(actor_idx)
        counter = clock_ops.inc_counter(self.clocks, idx)
        return GCounterBatch(clocks=clock_ops.witness(self.clocks, idx, counter))

    def value(self):
        """`gcounter.rs:76-78`."""
        return counter_ops.gcounter_value(self.clocks)


@observed_kernel("batch.gcounter.merge")
@jax.jit
def _merge(a, b):
    return counter_ops.gcounter_merge(a, b)
