"""Pipelined wire replication loop — overlap host parse with folds.

The replication story is "serialize, ship, merge" (the reference
delegates transport, `/root/reference/src/lib.rs:62-83`); at fleet scale
the user-facing loop is *wire blobs in → anti-entropy fold → wire blobs
out*, processed in device-sized chunks.  The serial form of that loop —
``from_wire`` per replica fleet, then fold, then ``to_wire`` — measured
**13,908 merges/s** against a 3.17M merges/s fold kernel in the same
artifact (``BENCH_r05.json``): ingest was 87% of wall clock, ~160× off
the wire microbench.  Profiling found the collapse was NOT a silent
Python fallback (the native parser accepts 100% of e2e-shaped blobs —
the ``native_fraction`` counters now prove that from the artifact
alone); it was **allocation churn**: every ``from_wire`` call allocated
a fresh ~300 MB dense plane set per fleet, page-faulting ~2.5 GB of
zeroed memory per chunk and freeing it again, which measured 27× slower
than the identical parse into warm buffers (see PERF.md "wire-loop
pipeline").

:class:`PipelinedWireLoop` rebuilds the loop around that finding:

* **Staging-buffer reuse** — a small pool of preallocated plane sets
  (default 3: one being parsed into, up to two held as fold inputs);
  the native parser clears each object's rows itself
  (``engine.orswot_ingest_wire(..., out=...)``), so no allocation ever
  happens in steady state.
* **Parse/fold overlap** — a background thread parses fleet ``k+1``
  into a free staging set while the main thread folds fleet ``k``
  (the ctypes call into the OpenMP parser releases the GIL, so the
  overlap is real on multicore hosts; device folds dispatch
  asynchronously on accelerator backends).
* **Ping-pong fold accumulators** — the C merge kernel fully overwrites
  its outputs, so two reusable buffer sets absorb the whole fold with
  zero allocations (`engine.orswot_merge(out=...)`).
* **Instrumentation** — per-stage wall times and native-vs-fallback
  blob counts (via :mod:`crdt_tpu.utils.tracing` counters) are returned
  with the result, so the bench JSON can self-report ``native_fraction``
  per stage.  The loop also publishes live gauges
  (``wireloop.staging_free`` — free staging sets, ``wireloop.
  parsed_depth`` — parsed fleets waiting for the fold) to the obs
  registry, and a fold blocked on the parser for longer than
  ``stall_threshold_s`` leaves a ``wireloop.stall`` flight-recorder
  event: an operator watching ``/metrics`` sees a parse-bound loop as
  ``staging_free == 0`` plus a stall count, without attaching a
  profiler.

``bench_e2e_wire`` (bench.py) and ``examples/anti_entropy.py`` drive
this one implementation.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Callable, Iterable, Optional, Sequence

import numpy as np

from ..config import counter_dtype
from ..utils import tracing


def _fold_merge_kernel(m_cap: int, d_cap: int):
    """The loop's jitted pairwise fold merge, shared across loop
    instances per (m_cap, d_cap) via the jit cache of ONE function
    object — and registered with the runtime kernel observatory
    (``batch.wireloop.fold_merge``)."""
    import functools

    import jax

    from ..obs.kernels import observed_kernel
    from ..ops import orswot_ops

    key = (m_cap, d_cap)
    fn = _FOLD_MERGE_CACHE.get(key)
    if fn is None:
        fn = observed_kernel("batch.wireloop.fold_merge")(jax.jit(
            functools.partial(orswot_ops.merge, m_cap=m_cap, d_cap=d_cap)))
        _FOLD_MERGE_CACHE[key] = fn
    return fn


_FOLD_MERGE_CACHE: dict = {}
from ..utils.interning import Universe

_SENTINEL = object()


def _native_fold_engine():
    """The native engine module when its merge kernel is usable, else
    None (same probe discipline as wirebulk.probe_engine: an old .so may
    load yet lack newer entry points)."""
    try:
        from ..native import engine

        engine._fn("orswot_merge", np.uint32)
        return engine
    except (ImportError, OSError, RuntimeError, AttributeError, TypeError):
        return None


class PipelinedWireLoop:
    """Double-buffered ORSWOT wire replication: blobs in → fold → blobs
    out, with host parse overlapped against the fold.

    One instance owns the staging/accumulator buffer pools for a fixed
    ``universe`` (identity universes take the native parse/encode fast
    path; any other universe still works through the Python codec, just
    without the zero-allocation steady state).  ``run`` processes any
    number of rounds; buffers are sized on first use and reused across
    rounds and across ``run`` calls.

    ``fold_path``: ``"native"`` (C++ row kernels, the CPU best engine),
    ``"jnp"`` (jitted device merge, async dispatch), or None to pick
    native when available on a CPU backend, jnp otherwise.
    """

    def __init__(self, universe: Universe, *, fold_path: Optional[str] = None,
                 staging_sets: int = 3, stall_threshold_s: float = 0.1):
        if staging_sets < 2:
            raise ValueError("pipelining needs at least 2 staging sets")
        self.universe = universe
        self.cfg = universe.config
        self._staging_sets = staging_sets
        # a fold wait on the parser above this leaves a wireloop.stall
        # event in the flight recorder (0 disables the event, not the wait)
        self.stall_threshold_s = stall_threshold_s
        self._staging: list[tuple] = []
        self._pingpong: list[tuple] = []
        self._n: Optional[int] = None
        if fold_path is None:
            import jax

            engine = _native_fold_engine() if jax.default_backend() == "cpu" \
                else None
            fold_path = "native" if engine is not None else "jnp"
        if fold_path not in ("native", "jnp"):
            raise ValueError(f"fold_path {fold_path!r} is not native/jnp")
        self.fold_path = fold_path
        self._engine = _native_fold_engine() if fold_path == "native" else None
        if fold_path == "native" and self._engine is None:
            raise RuntimeError("fold_path='native' but the native engine "
                               "is unavailable")
        self._jit_merge = None
        self._overflow = None  # jnp path: lazily ORed bool[2] flags

    # -- buffers -------------------------------------------------------------

    def _plane_set(self, n: int) -> tuple:
        cfg = self.cfg
        dt = counter_dtype(cfg)
        a, m, d = cfg.num_actors, cfg.member_capacity, cfg.deferred_capacity
        return (
            np.zeros((n, a), dtype=dt),
            np.full((n, m), -1, dtype=np.int32),
            np.zeros((n, m, a), dtype=dt),
            np.full((n, d), -1, dtype=np.int32),
            np.zeros((n, d, a), dtype=dt),
        )

    def _ensure_buffers(self, n: int) -> None:
        if self._n == n:
            return
        self._n = n
        self._staging = [self._plane_set(n) for _ in range(self._staging_sets)]
        self._pingpong = (
            [self._plane_set(n) for _ in range(2)]
            if self.fold_path == "native" else []
        )

    # -- stages --------------------------------------------------------------

    def _parse_into(self, blobs: Sequence[bytes], staging: tuple) -> None:
        """Decode ``blobs`` into the ``staging`` plane set (native fast
        path with per-blob triage; full Python route when the fast path
        does not apply)."""
        from .wirebulk import orswot_planes_from_wire

        planes = orswot_planes_from_wire(blobs, self.universe, out=staging)
        if planes is None:
            # no native fast path: decode in Python and copy into the
            # staging set so the fold sees one buffer discipline
            from ..utils.serde import from_binary
            from .orswot_batch import OrswotBatch

            sub = OrswotBatch.from_scalar(
                [from_binary(b) for b in blobs], self.universe
            )
            for dst, src in zip(staging, (sub.clock, sub.ids, sub.dots,
                                          sub.d_ids, sub.d_clocks)):
                np.copyto(dst, np.asarray(src))

    def _merge_native(self, acc: tuple, rhs: tuple, out: tuple) -> tuple:
        res = self._engine.orswot_merge(*acc, *rhs, out=out)
        if res[5].any():
            from ..error import raise_for_overflow

            raise_for_overflow(res[5], "wire-loop fold")
        return res[:5]

    def _merge_jnp(self, acc: tuple, rhs: tuple) -> tuple:
        """One async-dispatched device merge; overflow flags accumulate
        in ``self._overflow`` (checked once per round, at the egress
        sync, so no host round-trip lands mid-fold)."""
        if self._jit_merge is None:
            cfg = self.cfg
            self._jit_merge = _fold_merge_kernel(
                cfg.member_capacity, cfg.deferred_capacity)
        out = self._jit_merge(*acc, *rhs)
        ov = out[5].reshape(-1, 2).any(axis=0)
        self._overflow = ov if self._overflow is None else \
            (self._overflow | ov)
        return out[:5]

    def _egress(self, acc: tuple) -> list[bytes]:
        from .wirebulk import orswot_planes_to_wire

        planes = tuple(np.asarray(x) for x in acc)
        blobs = orswot_planes_to_wire(*planes, self.universe)
        if blobs is not None:
            return blobs
        # Python route (non-identity universe / u64 zigzag overflow) —
        # already counted by orswot_planes_to_wire
        from ..utils.serde import to_binary
        from .orswot_batch import OrswotBatch

        batch = OrswotBatch(*(np.ascontiguousarray(p) for p in planes))
        return [to_binary(s) for s in batch.to_scalar(self.universe)]

    # -- the loop ------------------------------------------------------------

    def run(self, rounds: Iterable[Sequence[Sequence[bytes]]], *,
            overlap: bool = True, collect: str = "last",
            on_round: Optional[Callable[[int, list], None]] = None) -> dict:
        """Process ``rounds`` of replica-fleet blobs through parse →
        fold-to-fixpoint (left fold + defer-plunger self-merge) → egress.

        Each round is a sequence of ``r`` blob lists (one per replica
        fleet, equal lengths).  With ``overlap=True`` a background
        thread stays one fleet ahead of the fold; ``overlap=False`` runs
        the identical staged code serially (the A/B the bench reports).

        ``collect``: ``"last"`` keeps only the final round's egressed
        blobs (bounded memory at bench scale), ``"all"`` keeps every
        round's, ``"none"`` keeps none.  ``on_round(i, blobs)`` sees
        each round's output either way.

        Returns ``{"out_blobs", "rounds", "merges", "objects",
        "pipeline", "fold_path", "stage_s": {parse, fold, egress},
        "e2e_s", "wire_counters", "ingest_native_fraction",
        "egress_native_fraction"}`` — ``stage_s`` are per-stage wall
        sums (with overlap they can exceed ``e2e_s``; that surplus IS
        the overlap won), counters/fractions are the tracing deltas for
        this call."""
        if collect not in ("last", "all", "none"):
            raise ValueError(f"collect {collect!r} is not last/all/none")
        rounds = list(rounds)
        stage_s = {"parse": 0.0, "fold": 0.0, "egress": 0.0}
        counters_before = tracing.counters()
        out_blobs: list = []
        all_blobs: list = []
        merges = objects = 0
        t_all0 = time.perf_counter()

        free_q: "queue.Queue" = queue.Queue()
        parsed_q: "queue.Queue" = queue.Queue()

        def parse_one(blobs, staging):
            t0 = time.perf_counter()
            self._parse_into(blobs, staging)
            stage_s["parse"] += time.perf_counter() - t0

        def worker():
            try:
                for blobs in fleet_stream:
                    staging = free_q.get()
                    if staging is _SENTINEL:
                        return
                    parse_one(blobs, staging)
                    parsed_q.put(staging)
                parsed_q.put(_SENTINEL)
            except BaseException as e:  # surfaced in the main thread
                parsed_q.put(e)

        n_rounds = len(rounds)
        fleet_stream = [blobs for rnd in rounds for blobs in rnd]
        if not fleet_stream:
            return {
                "out_blobs": [], "rounds": 0, "merges": 0, "objects": 0,
                "pipeline": "overlapped" if overlap else "serial",
                "fold_path": self.fold_path,
                "stage_s": {k: 0.0 for k in stage_s}, "e2e_s": 0.0,
                "wire_counters": {}, "ingest_native_fraction": None,
                "egress_native_fraction": None,
            }
        n = len(fleet_stream[0])
        if any(len(b) != n for b in fleet_stream):
            raise ValueError("all fleets must hold the same object count")
        self._ensure_buffers(n)
        for st in self._staging:
            free_q.put(st)

        thread = None
        stream_iter = iter(fleet_stream)
        if overlap:
            thread = threading.Thread(target=worker, daemon=True,
                                      name="wireloop-parse")
            thread.start()

        from ..obs import events as obs_events
        from ..obs import metrics as obs_metrics

        reg = obs_metrics.registry()
        g_free = reg.gauge("wireloop.staging_free")
        g_depth = reg.gauge("wireloop.parsed_depth")

        def update_gauges():
            # qsize is advisory under concurrency, which is exactly what
            # a gauge is — last write wins, scrapes see the latest level
            g_free.set(free_q.qsize())
            g_depth.set(parsed_q.qsize())

        def next_staged():
            if overlap:
                t_wait0 = time.perf_counter()
                item = parsed_q.get()
                waited = time.perf_counter() - t_wait0
                if self.stall_threshold_s and waited > self.stall_threshold_s:
                    # the fold outran the parser: record the stall so a
                    # parse-bound loop is visible from /events, not just
                    # from a post-hoc stage_s diff
                    tracing.count("wireloop.stalls")
                    obs_events.record(
                        "wireloop.stall", waited_s=round(waited, 4),
                        staging_free=free_q.qsize(),
                    )
                update_gauges()
                if isinstance(item, BaseException):
                    raise item
                return item
            blobs = next(stream_iter, _SENTINEL)
            if blobs is _SENTINEL:
                return _SENTINEL
            staging = free_q.get()
            parse_one(blobs, staging)
            update_gauges()
            return staging

        try:
            for ri, rnd in enumerate(rounds):
                r = len(rnd)
                acc = None
                acc_staging = None  # staging set acc still aliases
                pp = 0
                t0 = time.perf_counter()
                for fi in range(r):
                    staged = next_staged()
                    assert staged is not _SENTINEL
                    if acc is None:
                        acc, acc_staging = staged, staged
                        continue
                    if self.fold_path == "native":
                        acc = self._merge_native(
                            acc, staged, self._pingpong[pp]
                        )
                        pp ^= 1
                    else:
                        acc = self._merge_jnp(
                            self._put_device(acc), self._put_device(staged)
                        )
                    # both consumed buffer sets go back to the parser
                    if acc_staging is not None:
                        free_q.put(acc_staging)
                        acc_staging = None
                    free_q.put(staged)
                # defer plunger: one self-merge flushes deferred removes
                if self.fold_path == "native":
                    acc = self._merge_native(acc, acc, self._pingpong[pp])
                    pp ^= 1
                else:
                    acc = self._merge_jnp(
                        self._put_device(acc), self._put_device(acc)
                    )
                if acc_staging is not None:
                    # r == 1: the plunger read straight from staging
                    free_q.put(acc_staging)
                    acc_staging = None
                stage_s["fold"] += time.perf_counter() - t0
                merges += n * r
                objects += n

                t0 = time.perf_counter()
                if self._overflow is not None:
                    # jnp path: one deferred overflow check per round —
                    # the egress fetch syncs the device anyway
                    from ..error import raise_for_overflow

                    ov, self._overflow = self._overflow, None
                    raise_for_overflow(ov, "wire-loop fold")
                blobs_out = self._egress(acc)
                stage_s["egress"] += time.perf_counter() - t0
                if on_round is not None:
                    on_round(ri, blobs_out)
                if collect == "all":
                    all_blobs.append(blobs_out)
                elif collect == "last":
                    out_blobs = blobs_out
        finally:
            if thread is not None:
                free_q.put(_SENTINEL)  # unblock a parser waiting for buffers
                thread.join(timeout=30)
                if thread.is_alive():
                    # a worker still parsing (main thread raised mid-fold
                    # on a slow parse) may write into the staging planes
                    # for a while yet — orphan the whole pool so the next
                    # run() allocates fresh buffers instead of handing
                    # the zombie's targets to a new worker
                    self._staging = []
                    self._pingpong = []
                    self._n = None

        e2e_s = time.perf_counter() - t_all0
        deltas = tracing.counters_since(counters_before)
        return {
            "out_blobs": all_blobs if collect == "all" else out_blobs,
            "rounds": n_rounds,
            "merges": merges,
            "objects": objects,
            "pipeline": "overlapped" if overlap else "serial",
            "fold_path": self.fold_path,
            "stage_s": {k: round(v, 4) for k, v in stage_s.items()},
            "e2e_s": round(e2e_s, 4),
            "wire_counters": deltas,
            "ingest_native_fraction": tracing.native_fraction(
                deltas, "wire.orswot.from_wire"
            ),
            "egress_native_fraction": tracing.native_fraction(
                deltas, "wire.orswot.to_wire"
            ),
        }

    def _put_device(self, planes: tuple):
        """Host staging planes → device arrays for the jnp fold.

        ``device_put`` copies host numpy buffers into the backend's own
        (aligned) allocations, so once the transfer completes the
        staging set is safe to hand back to the parser; blocking here
        costs only the H2D — the merges themselves still chain
        asynchronously.  Device-resident accumulators pass through
        untouched."""
        import jax

        if not isinstance(planes[0], np.ndarray):
            return planes
        moved = jax.device_put(planes)
        jax.block_until_ready(moved)
        return moved


class PipelinedOpLoop:
    """Pipelined op-frame ingest: decode op frames on a background
    thread while the main thread scatter-folds already-decoded batches
    — the op-path sibling of :class:`PipelinedWireLoop`, reusing its
    staging discipline (a bounded decode queue IS the staging pool: at
    most ``depth`` decoded batches are ever buffered, so a slow fold
    backpressures the parser instead of ballooning host memory) and its
    telemetry (``wireloop.staging_free`` / ``wireloop.parsed_depth``
    gauges, ``wireloop.stall`` events past ``stall_threshold_s``).

    The overlap is real on multicore hosts: frame decode is pure
    numpy/zlib on the host, while the fold is one jitted scatter per
    batch (:meth:`crdt_tpu.oplog.OpApplier.apply_ops`) that dispatches
    asynchronously on accelerator backends.  ``bench_oplog`` drives
    this one implementation for its pipelined numbers.
    """

    def __init__(self, universe: Universe, *, applier=None, depth: int = 4,
                 stall_threshold_s: float = 0.1):
        from ..oplog.apply import OpApplier

        if depth < 2:
            raise ValueError("pipelining needs a decode queue depth >= 2")
        self.universe = universe
        self.applier = applier if applier is not None else OpApplier(universe)
        self.depth = depth
        self.stall_threshold_s = stall_threshold_s

    def run(self, batch, frames: Iterable[bytes], *,
            overlap: bool = True) -> tuple:
        """Fold every op frame of ``frames`` into ``batch`` (decode →
        ``apply_ops`` per frame, decode running one frame ahead when
        ``overlap``).  Returns ``(folded_batch, stats)`` with
        ``stats = {"frames", "ops", "applied", "duplicates",
        "still_parked", "pipeline", "stage_s": {parse, fold},
        "e2e_s"}`` — the same per-stage accounting the wire loop
        reports, so the bench can show the overlap won."""
        from ..oplog.wire import decode_ops_frame

        frames = list(frames)
        stage_s = {"parse": 0.0, "fold": 0.0}
        stats = {"frames": len(frames), "ops": 0, "applied": 0,
                 "duplicates": 0}
        t_all0 = time.perf_counter()
        num_actors = self.universe.config.num_actors

        from ..obs import events as obs_events
        from ..obs import metrics as obs_metrics

        reg = obs_metrics.registry()
        g_free = reg.gauge("wireloop.staging_free")
        g_depth = reg.gauge("wireloop.parsed_depth")

        def decode_one(frame):
            t0 = time.perf_counter()
            ops = decode_ops_frame(frame, num_actors=num_actors)
            stage_s["parse"] += time.perf_counter() - t0
            return ops

        if overlap:
            parsed_q: "queue.Queue" = queue.Queue(maxsize=self.depth)

            def worker():
                try:
                    for frame in frames:
                        parsed_q.put(decode_one(frame))
                    parsed_q.put(_SENTINEL)
                except BaseException as e:  # surfaced in the main thread
                    parsed_q.put(e)

            thread = threading.Thread(target=worker, daemon=True,
                                      name="oploop-decode")
            thread.start()

            def staged():
                while True:
                    t0 = time.perf_counter()
                    item = parsed_q.get()
                    waited = time.perf_counter() - t0
                    if self.stall_threshold_s \
                            and waited > self.stall_threshold_s:
                        tracing.count("wireloop.stalls")
                        obs_events.record(
                            "wireloop.stall", waited_s=round(waited, 4),
                            staging_free=self.depth - parsed_q.qsize(),
                        )
                    g_free.set(self.depth - parsed_q.qsize())
                    g_depth.set(parsed_q.qsize())
                    if item is _SENTINEL:
                        return
                    if isinstance(item, BaseException):
                        raise item
                    yield item

            stream = staged()
        else:
            stream = (decode_one(f) for f in frames)

        try:
            for ops in stream:
                t0 = time.perf_counter()
                batch, report = self.applier.apply_ops(batch, ops)
                stage_s["fold"] += time.perf_counter() - t0
                stats["ops"] += report.ops
                stats["applied"] += report.applied
                stats["duplicates"] += report.duplicates
        finally:
            if overlap:
                # drain so an abandoned worker never blocks on a full
                # queue holding stale buffers
                while True:
                    try:
                        parsed_q.get_nowait()
                    except queue.Empty:
                        break
                thread.join(timeout=30)

        stats["still_parked"] = len(self.applier.parked)
        stats["pipeline"] = "overlapped" if overlap else "serial"
        stats["stage_s"] = {k: round(v, 4) for k, v in stage_s.items()}
        stats["e2e_s"] = round(time.perf_counter() - t_all0, 4)
        return batch, stats
