"""The pjit'd anti-entropy step: one kernel launch for the whole fleet.

An unsharded anti-entropy round is three launches (merge, digest,
tree); on an object mesh the whole round fuses into ONE ``shard_map``
program:

* **shard-local joins** — the pairwise ORSWOT lattice merge
  (:func:`crdt_tpu.parallel.collective._orswot_pair_merge`, the exact
  body ``parallel.shard_local_merge`` contracts as pointwise) runs
  unchanged per shard: each device merges only its own object rows,
  zero cross-device bytes.
* **the digest vector** — each shard digests its own rows with the
  SAME traced body the unsharded kernel jits
  (:func:`crdt_tpu.sync.digest.orswot_digest_body`), then the fleet
  vector is ONE ``all_gather`` of shard-local slices — per-object
  digests have no cross-row coupling, so concatenation in device
  order IS the unsharded vector, byte for byte.
* **reduction summaries** — exactly the collectives the reduction
  contracts declare: a ``pmax`` clock join for the fleet version
  vector, a ``psum`` member fold for the live-member count.

Dispatch consults the runtime contract gate
(:mod:`crdt_tpu.mesh.contracts`) for every composed kernel, so a
host_only/replicated row can never be placed on the mesh.
"""

from __future__ import annotations

import dataclasses
import functools

import numpy as np

from . import contracts
from .state import MESH_AXIS, ShardedBatch

#: manifest names the step composes — consulted at dispatch (per-shard
#: bodies run at mesh size 1 by construction; the step itself runs at
#: the mesh's size)
_SHARD_LOCAL_KERNELS = ("parallel.shard_local_merge",)
_SHARDED_KERNELS = ("sync.digest.orswot", "mesh.step.anti_entropy")


@dataclasses.dataclass(frozen=True)
class MeshStepResult:
    """One anti-entropy round's outputs: the merged sharded fleet, the
    logical digest vector (host u64, unpadded), the fleet version
    vector (pmax clock join) and the fleet live-member count (psum
    fold)."""

    batch: ShardedBatch
    digests: np.ndarray      # uint64[n] — byte-equal to the unsharded path
    version_vector: np.ndarray  # uint64[A]
    live_members: int


@functools.lru_cache(maxsize=32)
def _step_fn(mesh, axis: str, m_cap: int, d_cap: int, use_table: bool,
             impl=None):
    """Cached jitted mesh step (jax.jit caches by function identity; a
    per-call closure would retrace+recompile every call)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from ..obs.kernels import observed_kernel
    from ..ops import orswot_ops
    from ..parallel._compat import shard_map
    from ..parallel.collective import _orswot_pair_merge
    from ..sync.digest import orswot_digest_body

    digest_body = orswot_digest_body(use_table)
    spec, rep = P(axis), P()
    state = (spec,) * 5
    in_specs = (state, state, rep) + ((rep,) if use_table else ())

    @jax.jit
    @functools.partial(
        shard_map, mesh=mesh, in_specs=in_specs,
        out_specs=(state, spec, rep, rep, rep), check_vma=False,
    )
    def _step(sa, sb, asalts, *mtab):
        # shard-local lattice join: the pointwise-contract merge body,
        # per shard — no collective, each device touches only its rows
        merged, overflow = _orswot_pair_merge(sa, sb, m_cap, d_cap, impl)
        # shard-local digest slice (the unsharded kernel's exact body),
        # then the fleet vector as ONE all_gather in device order
        local = digest_body(*merged, asalts, *mtab)
        digests = jax.lax.all_gather(local, axis, axis=0, tiled=True)
        # the declared reduction collectives: pmax clock join + psum
        # member fold — object-axis folds are the reduction contract's
        # whole point, so no pointwise exemption is needed here
        vv = jax.lax.pmax(jnp.max(merged[0], axis=0), axis)
        members = jax.lax.psum(
            jnp.sum(merged[1] != orswot_ops.EMPTY, dtype=jnp.int32), axis)
        return merged, overflow, digests, vv, members

    return observed_kernel("mesh.step.anti_entropy")(_step)


def anti_entropy_step(a: ShardedBatch, b: ShardedBatch, *,
                      check: bool = True, impl=None) -> MeshStepResult:
    """Run one full anti-entropy round — merge + digest + fleet
    summaries — as ONE pjit'd step over the object mesh.

    ``a`` and ``b`` must share a layout and mesh (the same logical
    fleet, two replicas' states).  Raises
    :class:`~crdt_tpu.error.CapacityOverflowError` on slot overflow
    when ``check`` (shard-locally reduced, like every merge path)."""
    from ..error import raise_for_overflow
    from ..sync.digest import (_salts_device, actor_salt_table,
                               member_salt_table)
    from ..utils import tracing

    lay = a.layout
    if b.layout != lay or b.mesh != a.mesh:
        raise ValueError(
            "anti_entropy_step needs both fleets on one layout+mesh "
            f"(got {lay} vs {b.layout})")
    size = int(a.mesh.shape[MESH_AXIS])
    for name in _SHARDED_KERNELS:
        contracts.require_shardable(name, size)
    for name in _SHARD_LOCAL_KERNELS:
        # per-shard bodies: the object axis arrives pre-sliced, so they
        # run at mesh size 1 inside the step by construction
        contracts.require_shardable(name, 1)

    da, db = a.device, b.device
    m_cap, d_cap = int(da.ids.shape[-1]), int(da.d_ids.shape[-1])
    asalts = _salts_device(actor_salt_table(
        a.universe, num_actors=int(da.clock.shape[-1])))
    mtable = member_salt_table(a.universe)
    state_a = (da.clock, da.ids, da.dots, da.d_ids, da.d_clocks)
    state_b = (db.clock, db.ids, db.dots, db.d_ids, db.d_clocks)
    fn = _step_fn(a.mesh, MESH_AXIS, m_cap, d_cap, mtable is not None,
                  impl)
    args = (state_a, state_b, asalts) + (
        (_salts_device(mtable),) if mtable is not None else ())
    merged, overflow, digests, vv, members = fn(*args)

    if check:
        raise_for_overflow(overflow, "mesh anti_entropy_step")
    digests = np.asarray(digests).astype(np.uint64)[:lay.n]
    tracing.count("mesh.step.rounds")
    tracing.count("mesh.step.digest_bytes", int(digests.nbytes))
    out = type(da)(clock=merged[0], ids=merged[1], dots=merged[2],
                   d_ids=merged[3], d_clocks=merged[4])
    return MeshStepResult(
        batch=a.replace(out),
        digests=digests,
        version_vector=np.asarray(vv).astype(np.uint64),
        live_members=int(np.asarray(members)),
    )
