"""Per-shard snapshot generations + the fleet manifest that ties them.

A sharded fleet checkpoints as S independent per-shard snapshot
generations (each a normal :class:`~crdt_tpu.durable.snapshot.
SnapshotStore` under ``shard-NN/`` — atomic rename-in, CRC-guarded,
digest-root self-verifying, retained-generation fallback: the PR 12
machinery, folded in unchanged) plus ONE fleet manifest naming which
generation of each shard belongs to this checkpoint, the shard's
digest-tree root, and the layout that sliced it.

Write order is shards-then-manifest: a kill -9 mid-checkpoint leaves
the previous manifest pointing at previous generations, which the
stores retain (``retain >= 2``) — the fleet restore is always a
CONSISTENT cut, never a mix of old and new shards.

Restore re-verifies every shard twice: the per-shard store re-checks
the decoded planes against the root recorded INSIDE the generation
(the existing self-check), and this layer re-checks that root against
the one the MANIFEST recorded — a shard file swapped between
checkpoints fails loudly (``mesh.durable.rejected.root_mismatch``),
not silently reassembled."""

from __future__ import annotations

import binascii
import json
import os
from typing import Optional, Tuple

import numpy as np

from .state import MeshLayout

_MANIFEST = "fleet.json"
_MANIFEST_VERSION = 1


def _manifest_crc(obj: dict) -> int:
    body = json.dumps({k: v for k, v in sorted(obj.items())
                       if k != "crc"}, sort_keys=True).encode()
    return binascii.crc32(body) & 0xFFFFFFFF


class MeshSnapshotStore:
    """S per-shard snapshot stores + the fleet manifest, under one
    directory.  Same thread-safety contract as the per-shard store:
    callers serialize writes (the cluster node checkpoints under its
    busy lock); reads only ever see complete renamed-in files."""

    def __init__(self, dirpath, layout: MeshLayout, *, retain: int = 2,
                 fsync: bool = True):
        from ..durable.snapshot import SnapshotStore

        self.dirpath = os.fspath(dirpath)
        self.layout = layout
        os.makedirs(self.dirpath, exist_ok=True)
        self._stores = [
            SnapshotStore(os.path.join(self.dirpath, f"shard-{s:02d}"),
                          retain=retain, fsync=fsync)
            for s in range(layout.shards)
        ]
        self._fsync = bool(fsync)

    @property
    def manifest_path(self) -> str:
        return os.path.join(self.dirpath, _MANIFEST)

    def store(self, shard: int):
        """The per-shard :class:`SnapshotStore` (tests and repair
        tooling reach the retained generations through this)."""
        return self._stores[shard]

    # -- checkpoint ----------------------------------------------------------

    def write_fleet(self, batch, universe, *, node_id: str = "",
                    wal_seq: int = 0, watermark=None) -> dict:
        """Checkpoint the LOGICAL fleet batch: slice each shard's leaf
        range, write one generation per shard, then tie them with the
        fleet manifest (written last, renamed atomically).  Returns the
        manifest dict."""
        import jax

        from ..utils import tracing

        lay = self.layout
        n = int(jax.tree_util.tree_leaves(batch)[0].shape[0])
        if n != lay.n:
            raise ValueError(
                f"write_fleet got {n} rows for a layout of {lay.n}")
        gens, roots = [], []
        for s, (lo, hi) in enumerate(lay.ranges()):
            part = jax.tree_util.tree_map(lambda x: x[lo:hi], batch)
            snap = self._stores[s].write(
                part, universe, wal_seq=wal_seq, watermark=watermark,
                node_id=node_id)
            gens.append(int(snap.generation))
            roots.append(int(snap.root))
        manifest = {
            "version": _MANIFEST_VERSION,
            "node_id": node_id,
            "layout": lay.to_json(),
            "generations": gens,
            "roots": roots,
            "wal_seq": int(wal_seq),
        }
        manifest["crc"] = _manifest_crc(manifest)
        tmp = self.manifest_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(manifest, f, sort_keys=True)
            if self._fsync:
                f.flush()
                os.fsync(f.fileno())
        os.replace(tmp, self.manifest_path)
        tracing.count("mesh.durable.snapshots")
        return manifest

    # -- restore -------------------------------------------------------------

    def _reject(self, reason: str, message: str):
        from ..error import CheckpointFormatError
        from ..utils import tracing

        tracing.count(f"mesh.durable.rejected.{reason}")
        raise CheckpointFormatError(message)

    def read_manifest(self) -> dict:
        from ..error import DurabilityError
        from ..utils import tracing

        if not os.path.exists(self.manifest_path):
            tracing.count("mesh.durable.rejected.manifest_missing")
            raise DurabilityError(
                f"no fleet manifest under {self.dirpath} — nothing to "
                "restore (a fresh sharded replica)")
        try:
            with open(self.manifest_path) as f:
                manifest = json.load(f)
        except (OSError, ValueError) as e:
            self._reject("manifest_corrupt",
                         f"fleet manifest unreadable: {e}")
        if manifest.get("version") != _MANIFEST_VERSION:
            self._reject(
                "manifest_corrupt",
                f"fleet manifest version {manifest.get('version')!r} != "
                f"{_MANIFEST_VERSION}")
        if _manifest_crc(manifest) != manifest.get("crc"):
            self._reject("manifest_corrupt",
                         "fleet manifest CRC mismatch (torn write?)")
        return manifest

    def load_fleet(self, universe=None) -> Tuple[object, dict]:
        """Restore the logical fleet: decode every shard's manifest
        generation (the store re-verifies planes against the root
        recorded in the file), re-check each root against the
        MANIFEST's record, and reassemble rows in shard order.
        Returns ``(batch, manifest)``."""
        import jax
        import jax.numpy as jnp

        from ..error import CheckpointFormatError
        from ..utils import tracing

        manifest = self.read_manifest()
        lay = MeshLayout.from_json(manifest["layout"])
        if lay != self.layout:
            self._reject(
                "layout_mismatch",
                f"manifest layout {lay} != store layout {self.layout}")
        parts = []
        for s in range(lay.shards):
            gen, root = manifest["generations"][s], manifest["roots"][s]
            try:
                snap = self._stores[s].load(int(gen))
            except FileNotFoundError as e:
                self._reject("shard_missing", f"shard {s}: {e}")
            except CheckpointFormatError:
                tracing.count("mesh.durable.rejected.shard_missing")
                raise
            if int(snap.root) != int(root):
                self._reject(
                    "root_mismatch",
                    f"shard {s} generation {gen}: subtree root "
                    f"{int(snap.root):#x} != manifest {int(root):#x}")
            parts.append(snap.batch)
        batch = parts[0] if len(parts) == 1 else jax.tree_util.tree_map(
            lambda *xs: jnp.concatenate(xs, axis=0), *parts)
        tracing.count("mesh.durable.restores")
        return batch, manifest

    def latest_manifest(self) -> Optional[dict]:
        """The manifest if one exists and verifies, else None (fresh
        replica) — the polite probe restores use before committing to
        :meth:`load_fleet`."""
        if not os.path.exists(self.manifest_path):
            return None
        return self.read_manifest()


def shard_root_of(digests) -> int:
    """The digest-tree root of one shard's digest slice — what the
    manifest records per shard (the same fold
    :func:`crdt_tpu.sync.tree.build_tree` computes)."""
    from ..sync import tree as tree_mod

    return int(tree_mod.build_tree(np.asarray(digests,
                                              dtype=np.uint64)).root)
