"""Sharded fleet state: one logical replica across a device mesh.

The object axis is the data-parallel axis (SURVEY.md §2.3): a fleet of
N independent CRDT objects shards row-wise over ``parallel/mesh.py``'s
``objects`` mesh with NO cross-device traffic for pointwise kernels.
This module owns the two halves of that placement:

* :class:`MeshLayout` — the shard→leaf-range map.  Boundaries are
  chosen on **pow2 subtree granules** (the spans
  :func:`crdt_tpu.obs.stability.subtree_layout` hands out), so a shard
  always owns whole digest-tree subtrees and the PR 11 subtree descent
  can be pointed at exactly one shard's leaf range.  With a measured
  heat vector the granule is picked by the PR 18 placement planner
  (the ``plan=mesh:S`` imbalance score, granule-snapped via
  :func:`crdt_tpu.obs.heat.mesh_bounds` — the SAME formula ``GET
  /heat?plan=mesh:S&granule=G`` prices, so a scored layout is always a
  buildable one).
* :class:`ShardedBatch` — a batch pytree padded to ``shards *
  per_shard`` rows (zero rows digest to the XOR identity, so padding
  is invisible to every digest/tree comparison) and placed via
  ``NamedSharding`` over the object axis.

Object-id rebasing (the SC01 routed-leaf exemption, now actually
implemented): operands that carry object ids by VALUE — op batches,
read batches, delta row indices — index the GLOBAL object axis; on a
mesh each shard's planes start at ``s * per_shard``, so
:meth:`MeshLayout.rebase` splits global ids into ``(shard,
local_row)`` pairs and :meth:`MeshLayout.unbase` inverts it.
shardcheck sanctions gathers through routed leaves statically;
``tests/test_mesh.py`` cross-checks the runtime rebasing round-trips
against the declared routed contracts.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import numpy as np

#: the data-parallel mesh axis every plane shards over
MESH_AXIS = "objects"

#: the shard-count ladder shardcheck verifies statically and the
#: runtime tests exercise (analysis.kernels.MESH_SIZES, re-exported so
#: host-side callers need no jax-adjacent import)
MESH_SIZES = (1, 2, 4, 8)


@dataclasses.dataclass(frozen=True)
class MeshLayout:
    """The shard→leaf-range map of one sharded fleet.

    ``per_shard`` rows live on every device (a multiple of
    ``granule``); rows past ``n`` are zero padding on the tail
    device(s).  Logical shard ``s`` owns global rows
    ``[bounds[s], bounds[s+1])`` — padded rows digest to 0, so every
    digest/tree statement about the logical fleet survives sharding
    byte-identically."""

    n: int           # logical (unpadded) fleet rows
    shards: int      # mesh size over the object axis
    granule: int     # pow2 subtree span the boundaries snap to
    per_shard: int   # padded rows per device (multiple of granule)
    imbalance: float = 1.0  # planner-predicted max/mean shard load

    @property
    def padded(self) -> int:
        return self.shards * self.per_shard

    @property
    def bounds(self) -> tuple:
        """Logical boundaries, ``shards + 1`` entries clipped to n."""
        return tuple(min(s * self.per_shard, self.n)
                     for s in range(self.shards + 1))

    def ranges(self) -> tuple:
        """Per-shard logical ``(lo, hi)`` ranges."""
        b = self.bounds
        return tuple((b[s], b[s + 1]) for s in range(self.shards))

    def objects_of(self, shard: int) -> int:
        lo, hi = self.ranges()[shard]
        return hi - lo

    def shard_of(self, obj: int) -> int:
        if not 0 <= obj < self.n:
            raise IndexError(f"object {obj} outside fleet [0, {self.n})")
        return min(obj // self.per_shard, self.shards - 1)

    def rebase(self, ids) -> tuple:
        """Global object ids → ``(shard, local_row)`` — the routed-leaf
        rebasing every op/read/delta operand takes before it may index
        a shard's planes."""
        ids = np.asarray(ids, dtype=np.int64)
        if ids.size and (ids.min() < 0 or ids.max() >= self.n):
            raise IndexError(
                f"object ids outside fleet [0, {self.n}): "
                f"[{ids.min()}, {ids.max()}]")
        return ids // self.per_shard, ids % self.per_shard

    def unbase(self, shard, local) -> np.ndarray:
        """Inverse of :meth:`rebase`."""
        return (np.asarray(shard, dtype=np.int64) * self.per_shard
                + np.asarray(local, dtype=np.int64))

    def to_json(self) -> dict:
        return {"n": self.n, "shards": self.shards,
                "granule": self.granule, "per_shard": self.per_shard,
                "imbalance": self.imbalance}

    @classmethod
    def from_json(cls, obj: dict) -> "MeshLayout":
        return cls(n=int(obj["n"]), shards=int(obj["shards"]),
                   granule=int(obj["granule"]),
                   per_shard=int(obj["per_shard"]),
                   imbalance=float(obj.get("imbalance", 1.0)))


def choose_layout(n: int, shards: int, *,
                  heat: Optional[Sequence] = None,
                  span: Optional[int] = None,
                  granule: Optional[int] = None) -> MeshLayout:
    """Pick the shard→leaf-range map for ``n`` objects over ``shards``
    devices.

    The granule defaults to the digest tree's subtree span for this
    fleet size (:func:`~crdt_tpu.obs.stability.subtree_layout` — a
    power of 16, so always pow2).  With a measured per-subtree ``heat``
    vector, candidate granules (the span and its next two doublings)
    are priced through the placement planner's ``mesh:S`` score and
    the lowest predicted imbalance wins (ties to the smaller granule —
    finer boundaries repack cheaper).  An explicit ``granule`` skips
    the search but is still validated pow2."""
    from ..obs import heat as heat_mod
    from ..obs import stability as stability_mod

    if n < 1:
        raise ValueError(f"fleet size {n} < 1")
    if shards < 1:
        raise ValueError(f"shards {shards} < 1")
    if span is None:
        _subtrees, span = stability_mod.subtree_layout(n)
    span = max(1, int(span))
    imbalance = 1.0
    if granule is None:
        if heat is None:
            granule = span
        else:
            heat = np.asarray(heat, dtype=np.float64)
            best = None
            for cand in (span, span * 2, span * 4):
                report = heat_mod.score_plan(
                    f"mesh:{shards}", heat, n=n, span=span,
                    granule=cand)
                score = float(report["imbalance"])
                if best is None or score < best[0]:
                    best = (score, cand)
            imbalance, granule = best
    bounds = heat_mod.mesh_bounds(n, shards, granule)
    per_shard = -(-(-(-n // shards)) // int(granule)) * int(granule)
    layout = MeshLayout(n=int(n), shards=int(shards),
                        granule=int(granule), per_shard=per_shard,
                        imbalance=float(imbalance))
    assert list(layout.bounds) == list(bounds)  # one formula, two homes
    return layout


def _pad_batch(batch, pad: int, universe):
    """Append ``pad`` empty rows (zero/EMPTY planes — the states that
    digest to the XOR identity) to every leaf of a batch pytree."""
    import jax
    import jax.numpy as jnp

    if pad == 0:
        return batch
    z = type(batch).zeros(pad, universe)
    return jax.tree_util.tree_map(
        lambda a, b: jnp.concatenate([a, b], axis=0), batch, z)


class ShardedBatch:
    """A fleet batch living sharded over the object axis of a device
    mesh — the one logical replica, in S pieces.

    ``device`` is the padded batch pytree placed via ``NamedSharding``
    (each array's leading axis splits ``per_shard`` rows per device);
    ``layout`` is the shard→leaf-range map; ``universe`` is carried for
    digest salts and padding.  Construct with :meth:`shard`."""

    def __init__(self, device_batch, layout: MeshLayout, mesh,
                 universe=None):
        self.device = device_batch
        self.layout = layout
        self.mesh = mesh
        self.universe = universe

    @classmethod
    def shard(cls, batch, universe, *, shards: Optional[int] = None,
              mesh=None, heat=None, span: Optional[int] = None,
              granule: Optional[int] = None) -> "ShardedBatch":
        """Place ``batch`` on an object mesh: choose the layout
        (:func:`choose_layout`), pad the tail shard with
        digest-invisible empty rows, and ``device_put`` every plane
        with the object-axis ``NamedSharding``."""
        import jax

        from ..parallel import mesh as mesh_mod

        if mesh is None:
            if shards is None:
                raise ValueError("ShardedBatch.shard needs shards= or mesh=")
            devices = jax.devices()
            if shards > len(devices):
                raise ValueError(
                    f"shards {shards} > visible devices {len(devices)}")
            mesh = mesh_mod.make_mesh({MESH_AXIS: shards},
                                      devices[:shards])
        n = int(jax.tree_util.tree_leaves(batch)[0].shape[0])
        layout = choose_layout(n, int(mesh.shape[MESH_AXIS]),
                               heat=heat, span=span, granule=granule)
        padded = _pad_batch(batch, layout.padded - n, universe)
        dev = mesh_mod.shard_batch(padded, mesh, MESH_AXIS)
        return cls(dev, layout, mesh, universe)

    def logical(self):
        """The unpadded logical batch (rows ``[0, n)``), host-addressable
        — what digests, trees, snapshots and the scalar oracle compare
        against."""
        import jax

        lay = self.layout
        if lay.padded == lay.n:
            return self.device
        return jax.tree_util.tree_map(lambda x: x[:lay.n], self.device)

    def replace(self, device_batch) -> "ShardedBatch":
        """A new ShardedBatch around updated planes (same layout/mesh)."""
        return ShardedBatch(device_batch, self.layout, self.mesh,
                            self.universe)

    def publish_gauges(self, registry=None, heat_vector=None,
                       span: int = 1) -> None:
        """Publish the per-shard placement surface: ``mesh.layout.*``
        and ``mesh.shard.<s>.objects`` gauges, plus
        ``mesh.shard.<s>.load`` when a per-subtree heat vector is
        supplied (spread uniformly within subtrees, exactly like the
        planner's pricing)."""
        from ..obs import metrics

        reg = registry if registry is not None else metrics.registry()
        lay = self.layout
        reg.gauge_set("mesh.layout.shards", lay.shards)
        reg.gauge_set("mesh.layout.granule", lay.granule)
        reg.gauge_set("mesh.layout.imbalance", lay.imbalance)
        loads = shard_loads(lay, heat_vector, span) \
            if heat_vector is not None else None
        for s, (lo, hi) in enumerate(lay.ranges()):
            reg.gauge_set(f"mesh.shard.{s}.objects", hi - lo)
            if loads is not None:
                reg.gauge_set(f"mesh.shard.{s}.load", float(loads[s]))


def shard_loads(layout: MeshLayout, heat_vector, span: int) -> np.ndarray:
    """Measured per-subtree heat attributed to each shard's leaf range
    — the runtime counterpart of the planner's predicted ``loads`` (the
    same uniform within-subtree spread), so demo/tests can print
    measured vs predicted per shard."""
    heat = np.asarray(heat_vector, dtype=np.float64)
    span = max(1, int(span))
    loads = np.zeros(layout.shards, dtype=np.float64)
    bounds = layout.bounds
    for i in range(heat.size):
        lo, hi = i * span, min((i + 1) * span, layout.n)
        width = max(hi - lo, 1)
        for s in range(layout.shards):
            ov = min(hi, bounds[s + 1]) - max(lo, bounds[s])
            if ov > 0:
                loads[s] += heat[i] * ov / width
    return loads
