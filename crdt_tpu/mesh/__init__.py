"""Mesh-sharded fleets: one logical replica across a device mesh.

The fleet's object axis shards row-wise over ``parallel/mesh.py``'s
``objects`` mesh axis; this package owns everything above the raw
placement helpers:

* :mod:`~crdt_tpu.mesh.state` — :class:`MeshLayout` (subtree-granule
  shard boundaries, planner-priced) and :class:`ShardedBatch` (padded,
  NamedSharding-placed plane pytrees).
* :mod:`~crdt_tpu.mesh.contracts` — the runtime half of the static
  ShardContract manifest: dispatch-time refusal of host_only /
  replicated kernels, with the consumed-contract set pinned against
  the shardcheck manifest by tests.
* :mod:`~crdt_tpu.mesh.step` — the whole anti-entropy round as ONE
  pjit'd ``shard_map`` program (shard-local joins, one digest
  ``all_gather``, the declared pmax/psum fleet summaries).
* :mod:`~crdt_tpu.mesh.sync` — shard-subset repair: per-shard root
  compare, subtree descent scoped to the diverged shard's leaf range.
* :mod:`~crdt_tpu.mesh.durable` — per-shard snapshot generations tied
  by a fleet manifest; restore re-verifies every shard's subtree root.

Unlike the package root, importing :mod:`crdt_tpu.mesh` MAY touch jax
(it needs the x64 flip and device mesh machinery) — keep it out of
host-only import paths, exactly like :mod:`crdt_tpu.parallel`.
"""

from .contracts import (SHARDABLE_CLASSES, consumed_contracts,
                        contract_map, require_shardable)
from .durable import MeshSnapshotStore, shard_root_of
from .state import (MESH_AXIS, MESH_SIZES, MeshLayout, ShardedBatch,
                    choose_layout, shard_loads)
from .step import MeshStepResult, anti_entropy_step
from .sync import (ShardSyncStats, diverged_shards, shard_roots,
                   shard_subset_sync)

__all__ = [
    "MESH_AXIS",
    "MESH_SIZES",
    "MeshLayout",
    "MeshSnapshotStore",
    "MeshStepResult",
    "ShardSyncStats",
    "SHARDABLE_CLASSES",
    "ShardedBatch",
    "anti_entropy_step",
    "choose_layout",
    "consumed_contracts",
    "contract_map",
    "diverged_shards",
    "require_shardable",
    "shard_loads",
    "shard_root_of",
    "shard_roots",
    "shard_subset_sync",
]
