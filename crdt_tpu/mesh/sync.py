"""Shard-subset sync: repair one diverged shard, leave its neighbors home.

The flat session ships O(N) digest lanes before knowing WHERE the
divergence lives; the tree descent narrows that to subtrees.  On a
mesh the shard→leaf-range map (:class:`~crdt_tpu.mesh.state.
MeshLayout`, subtree-aligned by construction) adds the missing level:
compare one 8-byte root per shard first, then point the PR 11 subtree
descent at ONLY the diverged shard's leaf range — a fleet with one hot
shard syncs that shard's subtree bytes and nothing else
(counter-pinned: ``mesh.sync.shards_skipped`` shards contribute zero
descent or delta bytes).

Everything here is host-side orchestration over the existing digest /
tree / delta machinery — no new jitted kernel, no new wire format: the
delta rows ride :func:`crdt_tpu.sync.delta.gather_blobs` /
:func:`~crdt_tpu.sync.delta.apply_delta_rows` exactly like a flat
session's, with the row ids rebased per shard
(:meth:`~crdt_tpu.mesh.state.MeshLayout.rebase` — the routed-leaf
exemption's runtime half).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np

from .state import MeshLayout


def shard_roots(digests, layout: MeshLayout) -> np.ndarray:
    """Digest-tree root of each shard's logical digest slice —
    ``uint64[S]`` shard roots (8 bytes per shard on the wire), the
    same roots the per-shard snapshot manifest records
    (:func:`crdt_tpu.mesh.durable.shard_root_of`).  NOT a raw XOR
    fold: the tree's position-mixed leaves keep two rows that took
    IDENTICAL updates from cancelling each other out of the root
    (a raw XOR of per-row digest deltas would), so equal roots really
    mean an undiverged shard.  Empty shards root to the empty tree."""
    from ..sync import tree as tree_mod

    d = np.asarray(digests, dtype=np.uint64)
    if d.size != layout.n:
        raise ValueError(
            f"digest vector has {d.size} lanes, layout has {layout.n}")
    out = np.zeros(layout.shards, dtype=np.uint64)
    for s, (lo, hi) in enumerate(layout.ranges()):
        if hi > lo:
            out[s] = tree_mod.build_tree(d[lo:hi]).root
    return out


def diverged_shards(mine, theirs, layout: MeshLayout) -> np.ndarray:
    """Shard indices whose roots disagree, ascending — the shards a
    subset sync must descend into; everything else stays home."""
    a, b = shard_roots(mine, layout), shard_roots(theirs, layout)
    return np.nonzero(a != b)[0].astype(np.int64)


@dataclasses.dataclass
class ShardSyncStats:
    """One shard-subset sync pass's accounting (what the counters pin):
    which shards moved, the descent's wire-byte bill per diverged
    shard, and the delta payload that actually shipped."""

    shards_synced: int = 0
    shards_skipped: int = 0
    objects: int = 0
    root_bytes: int = 0        # the per-shard root compare (8B * S)
    descent_bytes: int = 0     # subtree-descent lanes, diverged shards only
    delta_bytes: int = 0       # delta row payloads, diverged shards only
    per_shard: dict = dataclasses.field(default_factory=dict)
    #: global ids of every repaired row — what the caller feeds the heat
    #: tracker (``record_repair``), exactly like a flat session's deltas
    object_ids: np.ndarray = dataclasses.field(
        default_factory=lambda: np.zeros(0, dtype=np.int64))


def shard_subset_sync(dst_batch, src_batch, layout: MeshLayout,
                      universe=None, *, applier=None,
                      dst_digests=None, src_digests=None):
    """Pull every diverged shard's rows from ``src`` into ``dst``:
    per-shard root compare, shard-scoped digest-tree descent for the
    byte bill, then gather/apply of exactly the diverged rows.

    Returns ``(merged_dst_batch, ShardSyncStats)``.  Pure host
    orchestration — both batches must be logical (unpadded) fleets of
    ``layout.n`` rows; digests may be passed in when the caller already
    holds them (the step result, the memo) to keep a converged pass at
    zero kernel launches."""
    from ..sync import delta as delta_mod
    from ..sync import digest as digest_mod
    from ..sync import tree as tree_mod
    from ..utils import tracing

    mine = np.asarray(
        dst_digests if dst_digests is not None
        else digest_mod.digest_of(dst_batch, universe), dtype=np.uint64)
    theirs = np.asarray(
        src_digests if src_digests is not None
        else digest_mod.digest_of(src_batch, universe), dtype=np.uint64)
    stats = ShardSyncStats(root_bytes=8 * layout.shards)
    diverged = diverged_shards(mine, theirs, layout)
    stats.shards_skipped = layout.shards - int(diverged.size)
    out = dst_batch
    all_ids = []
    for s in diverged:
        lo, hi = layout.ranges()[int(s)]
        # the PR 11 subtree descent, pointed at ONE shard's leaf range:
        # the lane bill below is what a tree-capable session would ship
        # for this shard and no other
        ta = tree_mod.build_tree(mine[lo:hi])
        tb = tree_mod.build_tree(theirs[lo:hi])
        _leaves, descent = tree_mod.simulate_descent(ta, tb)
        ids = lo + delta_mod.diverged_indices(mine[lo:hi], theirs[lo:hi])
        blobs = delta_mod.gather_blobs(src_batch, ids, universe)
        nbytes = sum(len(b) for b in blobs)
        out = delta_mod.apply_delta_rows(out, ids, blobs, universe,
                                         applier=applier)
        stats.shards_synced += 1
        stats.objects += int(ids.size)
        stats.descent_bytes += int(descent.payload_bytes)
        stats.delta_bytes += nbytes
        # rebased view of the rows this shard repaired (the routed-leaf
        # rebasing, observable per shard)
        shard_idx, local = layout.rebase(ids)
        assert set(shard_idx.tolist()) <= {int(s)}
        stats.per_shard[int(s)] = {
            "objects": int(ids.size), "delta_bytes": nbytes,
            "descent_bytes": int(descent.payload_bytes),
            "local_rows": local.tolist() if ids.size <= 64 else None,
        }
        all_ids.append(ids)
    if all_ids:
        stats.object_ids = np.concatenate(all_ids)
    tracing.count("mesh.sync.rounds")
    tracing.count("mesh.sync.shards_synced", stats.shards_synced)
    tracing.count("mesh.sync.shards_skipped", stats.shards_skipped)
    tracing.count("mesh.sync.objects", stats.objects)
    tracing.count("mesh.sync.delta_bytes", stats.delta_bytes)
    return out, stats
