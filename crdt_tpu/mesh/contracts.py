"""Runtime↔static sharding-contract cross-check (the dispatch gate).

shardcheck (:mod:`crdt_tpu.analysis.shard_rules`) statically proves
every manifested kernel against its declared
:class:`~crdt_tpu.analysis.kernels.ShardContract` on every CI run.
This module is the RUNTIME half of that guarantee: the mesh layer
consults the SAME manifest at dispatch time, so a kernel whose
contract says ``host_only`` or ``replicated`` can never be placed on
the object mesh — a typed :class:`~crdt_tpu.error.MeshContractError`,
not a silently-wrong collective program.

Single-source discipline (the :mod:`crdt_tpu.obs.namespace` pattern,
dynamically): :func:`contract_map` is derived from
:data:`~crdt_tpu.analysis.kernels.MANIFEST` — there is no second table
to drift.  ``tests/test_mesh.py`` pins that the runtime-consumed
contract set equals shardcheck's manifest exactly.

Import contract: stdlib-only (the manifest module keeps jax out of its
import path), so consulting a contract never drags the device runtime
into a host-side caller.
"""

from __future__ import annotations

import threading
from typing import Dict, FrozenSet

from ..analysis.kernels import MANIFEST, ShardContract
from ..error import MeshContractError

#: shard classes the mesh layer may dispatch (host_only/replicated are
#: refused — the typed-error satellite)
SHARDABLE_CLASSES = ("pointwise", "reduction")

_LOCK = threading.Lock()
_CONSUMED: set = set()


def contract_map() -> Dict[str, ShardContract]:
    """Every manifested kernel's declared sharding contract, by kernel
    name — exactly the rows shardcheck verifies (kernels with no
    ``sharding=`` declaration have no contract and are refused at
    dispatch like host_only ones)."""
    return {spec.name: spec.sharding for spec in MANIFEST
            if spec.sharding is not None}


def require_shardable(name: str, mesh_size: int) -> ShardContract:
    """The dispatch gate: look up ``name``'s contract and refuse — with
    a typed :class:`~crdt_tpu.error.MeshContractError` — anything the
    static checker would not sanction on an object mesh of
    ``mesh_size`` devices.  Returns the contract on success and records
    the name so tests can pin the runtime-consumed set against the
    manifest."""
    from ..utils import tracing

    contracts = contract_map()
    contract = contracts.get(name)
    if contract is None:
        tracing.count("mesh.contract.refused")
        raise MeshContractError(
            f"kernel {name!r} has no ShardContract row in the kernel "
            "manifest — shardcheck never proved it, so the mesh layer "
            "refuses to dispatch it",
            kernel=name, sclass="")
    if contract.sclass not in SHARDABLE_CLASSES:
        tracing.count("mesh.contract.refused")
        raise MeshContractError(
            f"kernel {name!r} is declared {contract.sclass!r} "
            f"({contract.reason or 'no reason recorded'}) — it cannot "
            "run sharded over the object mesh",
            kernel=name, sclass=contract.sclass)
    if int(mesh_size) not in tuple(contract.mesh_sizes):
        tracing.count("mesh.contract.refused")
        raise MeshContractError(
            f"kernel {name!r} is contracted for mesh sizes "
            f"{tuple(contract.mesh_sizes)}, not {mesh_size} — "
            "shardcheck only verified the declared ladder",
            kernel=name, sclass=contract.sclass)
    with _LOCK:
        _CONSUMED.add(name)
    return contract


def consumed_contracts() -> FrozenSet[str]:
    """Kernel names the runtime gate has approved so far this process —
    the set ``tests/test_mesh.py`` cross-checks against the manifest."""
    with _LOCK:
        return frozenset(_CONSUMED)
