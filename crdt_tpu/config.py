"""Global configuration for the crdt_tpu framework.

The reference library (`/root/reference/src/vclock.rs:23`) fixes
``Counter = u64``.  JAX needs ``jax_enable_x64`` for 64-bit integers, so we
enable it at import time (gate with ``CRDT_TPU_NO_X64=1`` to opt out, e.g.
for pure-f32 TPU perf experiments where counters fit in uint32).

The reference has no runtime configuration at all (no features, env vars or
flags — see SURVEY.md §5 "Config"); its only knobs are compile-time generics.
The TPU build replaces those generics with :class:`CrdtConfig`: capacities of
the dense SoA buffers (actor universe, member slots, deferred slots,
multi-value slots) and the counter dtype.
"""

from __future__ import annotations

import dataclasses
import os

_X64_ENABLED = False

# canonical ORSWOT pairwise-merge implementation names (the dispatch lives
# in crdt_tpu.ops.orswot_ops.resolve_merge_impl; configs accept "auto" too)
MERGE_IMPLS = ("rank", "unrolled", "pallas")


def enable_x64() -> bool:
    """Enable 64-bit types in JAX (idempotent). Returns True if enabled."""
    global _X64_ENABLED
    if os.environ.get("CRDT_TPU_NO_X64") == "1":
        return False
    if not _X64_ENABLED:
        import jax

        jax.config.update("jax_enable_x64", True)
        _X64_ENABLED = True
    return _X64_ENABLED


def x64_disabled():
    """Context manager forcing 32-bit trace mode for a kernel trace
    (Mosaic has no 64-bit support — Python-int literals must not become
    i64[] operands).  ``jax.enable_x64(False)`` on new jax,
    ``jax.experimental.disable_x64()`` on 0.4.x, where ``jax.enable_x64``
    does not exist."""
    import jax

    try:
        return jax.enable_x64(False)
    except AttributeError:
        from jax.experimental import disable_x64

        return disable_x64()


def pallas_mosaic_skew():
    """Reason string when the installed jax cannot run the interpret-mode
    Pallas ORSWOT kernels, else ``None`` — the ONE home for the
    "jax 0.4.x Pallas skew" version gate (ROADMAP carried item).

    Under jax 0.4.x (observed on 0.4.37), i64 scalars lowering into the
    interpret-mode kernels recurse forever in Mosaic's int64→int32
    truncation helper; the kernel entry points
    (:func:`crdt_tpu.ops.orswot_pallas.merge` / ``fold_merge`` and
    :func:`crdt_tpu.ops.orswot_fold_aligned.fold_merge`) call this and
    raise a typed :class:`crdt_tpu.error.UnsupportedBackendError` at
    the API boundary instead of failing deep in the compiler.  The test
    harness xfail gate (``tests/conftest.py``) keys off the SAME
    predicate, so the two can never drift.
    """
    import jax

    try:
        major, minor = (int(p) for p in jax.__version__.split(".")[:2])
    except ValueError:
        return None
    if (major, minor) >= (0, 5):
        return None
    return (
        f"jax {jax.__version__} cannot run the interpret-mode Pallas "
        "ORSWOT kernels: i64 scalars lowering into interpret mode "
        "recurse in Mosaic's int64->int32 truncation (the 0.4.37 skew; "
        "ROADMAP 'jax 0.4.x Pallas skew').  Remediation: upgrade to "
        "jax>=0.5, run on a real TPU backend (interpret=False), or use "
        "the portable jnp path (crdt_tpu.ops.orswot_ops)"
    )


def counter_dtype(config=None):
    """The dtype used for dense counters.

    The reference fixes ``Counter = u64`` (`vclock.rs:23`) and that is
    the default.  TPUs have no native 64-bit integers — XLA emulates
    them as register pairs, roughly doubling both arithmetic and HBM
    traffic — so :class:`CrdtConfig` can opt a batch universe into
    ``counter_bits=32`` where counters are known to fit (2^32 ops per
    actor); the scalar/u64 engines remain the parity oracle.
    """
    return dtype_for_bits(config.counter_bits if config is not None else 64)


def dtype_for_bits(bits: int):
    """Counter dtype for an explicit width (kernel dataclasses carry the
    width as a plain int so they stay hashable/static under jit)."""
    import jax.numpy as jnp

    if bits == 32:
        return jnp.uint32
    return jnp.uint64 if enable_x64() else jnp.uint32


@dataclasses.dataclass(frozen=True)
class CrdtConfig:
    """Static capacities for dense SoA CRDT batches.

    The reference stores unbounded BTreeMaps/HashMaps; XLA requires static
    shapes, so each axis gets a capacity.  Overflow policy: raising on the
    host at ingest time (capacities are checked when ops/states are packed,
    never on device).
    """

    num_actors: int = 64  # actor-universe size A (dense interned ids)
    member_capacity: int = 32  # Orswot member slots per object
    deferred_capacity: int = 8  # deferred (clock, member) rows per object
    mv_capacity: int = 8  # MVReg antichain slots per register
    key_capacity: int = 16  # Map key slots per object
    # counter width: 64 = reference parity (u64, vclock.rs:23), 32 = the
    # TPU-native width (no 64-bit emulation; counters must fit 2^32)
    counter_bits: int = 64
    # ORSWOT pairwise-merge implementation: "auto" (env override via
    # CRDT_MERGE_IMPL, else backend default), "rank", "unrolled", or
    # "pallas" — see crdt_tpu.ops.orswot_ops.resolve_merge_impl
    merge_impl: str = "auto"

    def __post_init__(self):
        for f in dataclasses.fields(self):
            v = getattr(self, f.name)
            if f.name == "merge_impl":
                if v != "auto" and v not in MERGE_IMPLS:
                    raise ValueError(
                        f"CrdtConfig.merge_impl must be 'auto' or one of "
                        f"{'/'.join(MERGE_IMPLS)}, got {v!r}"
                    )
                continue
            if not isinstance(v, int) or v <= 0:
                raise ValueError(f"CrdtConfig.{f.name} must be a positive int, got {v!r}")
        if self.counter_bits not in (32, 64):
            raise ValueError(
                f"CrdtConfig.counter_bits must be 32 or 64, got {self.counter_bits!r}"
            )

    @classmethod
    def tpu_default(cls, **overrides) -> "CrdtConfig":
        """The recommended production config for TPU workloads.

        ``counter_bits=32``: the measured product default (the unrolled
        and fused-Pallas fast paths are exact for uint32 only, and u64
        measured 1.5× the u32 cost even on CPU — `PERF.md` "Counter
        width").  The u64 default on :class:`CrdtConfig` itself stays for
        reference parity (`vclock.rs:23`); use this constructor when the
        per-actor op count fits 2^32."""
        return cls(**{"counter_bits": 32, **overrides})


DEFAULT_CONFIG = CrdtConfig()
