"""Experimental ORSWOT merge variants for TPU layout tuning.

The production jnp merge (:func:`crdt_tpu.ops.orswot_ops.merge`) leans on
``take_along_axis`` gathers and a counting-rank permutation — primitives
XLA:TPU executes far from the HBM roofline (measured ~8.5 GB/s effective
vs ~819 GB/s peak on v5e; ``reports/TPU_LATENCY.md``).  This module holds
the two candidate replacements, both *gather- and sort-free*: every
alignment and compaction step is expressed as unrolled one-hot selects
and max-reductions over the small static slot axes, exactly the style of
the Pallas tile math (:mod:`crdt_tpu.ops.orswot_pallas`), which XLA can
fuse into dense elementwise passes.

* :func:`merge_unrolled` — the Pallas tile math run as plain jnp on full
  ``[N, ...]`` arrays in the standard layout.  Zero new semantics: it IS
  ``orswot_pallas._merge_tile``, so parity with the production merge is
  inherited from ``tests/test_orswot_pallas.py`` and re-asserted in
  ``tests/test_orswot_lanes.py``.
* :func:`merge_lanes` / the ``*_t`` functions — the same math with every
  array transposed so the **object axis is minor**: ``clock[A, N]``,
  ``ids[M, N]``, ``dots[M, A, N]``.  On TPU the minor axis maps to the
  128-wide vector lanes; with ``N`` minor every elementwise op runs at
  full lane utilization regardless of how small ``A``/``M`` are (the
  standard layout wastes half the lanes at ``A = 64`` and worse below),
  and the per-slot one-hot selects become broadcasts over ``[A, N]``
  planes.  A fold should transpose once at ingest (:func:`to_lanes`),
  stay transposed across all ``R`` joins, and transpose back at egress
  (:func:`from_lanes`).

Semantics are `/root/reference/src/orswot.rs:89-156` throughout — the
rule-by-rule citations live in ``orswot_ops``/``orswot_pallas``; these
variants only change execution layout, never the algebra.  Counters are
uint32 (the bias-to-int32 trick of the Pallas path — order-preserving
``x ^ 0x8000_0000``; exact, since the merge only compares/maxes/selects).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from . import orswot_pallas as _op

EMPTY = _op.EMPTY
ZERO = _op.ZERO


def merge_unrolled(
    clock_a, ids_a, dots_a, dids_a, dclocks_a,
    clock_b, ids_b, dots_b, dids_b, dclocks_b,
    m_cap: int, d_cap: int,
):
    """Pairwise merge via the unrolled (gather/sort-free) tile math in the
    standard ``[N, ...]`` layout.  Drop-in for ``orswot_ops.merge``."""
    _op._check_dtypes(clock_a)
    _op._check_dtypes(clock_b)
    cdt = clock_a.dtype
    sa = _op._to_kernel_dtype((clock_a, ids_a, dots_a, dids_a, dclocks_a))
    sb = _op._to_kernel_dtype((clock_b, ids_b, dots_b, dids_b, dclocks_b))
    (clock, ids, dots, dids, dclk), over = _op._merge_tile(sa, sb, m_cap, d_cap)
    return (
        _op._from_kernel_dtype(clock, cdt), ids,
        _op._from_kernel_dtype(dots, cdt), dids,
        _op._from_kernel_dtype(dclk, cdt), over,
    )


# ---------------------------------------------------------------------------
# lanes-last (object-axis-minor) tile math
#
# Layout: clock[A, N], ids[M, N], dots[M, A, N], d_ids[D, N],
# d_clocks[D, A, N] — slot and actor axes lead, the batch axis is minor.
# Counter planes are bias-mapped int32 (see module docstring).
# ---------------------------------------------------------------------------


def to_lanes(state):
    """Transpose a standard ``[N, ...]`` state 5-tuple to lanes-last."""
    clock, ids, dots, d_ids, d_clocks = state
    return (
        clock.T, ids.T, jnp.transpose(dots, (1, 2, 0)),
        d_ids.T, jnp.transpose(d_clocks, (1, 2, 0)),
    )


def from_lanes(state):
    """Invert :func:`to_lanes`."""
    clock, ids, dots, d_ids, d_clocks = state
    return (
        clock.T, ids.T, jnp.transpose(dots, (2, 0, 1)),
        d_ids.T, jnp.transpose(d_clocks, (2, 0, 1)),
    )


# int32-domain bool reduces and clock subtract, shared with the Pallas
# tile math (one copy to keep in sync if the lowering trick changes)
_any_t = _op._any
_all_t = _op._all
_sub_t = _op._sub


def _align_against_t(ids_a, dots_a, ids_b, dots_b):
    """Per a-slot, the matching b dot clock (``ZERO`` if unmatched), plus
    the mask of b-slots consumed.  ``ids[M, N]``, ``dots[M, A, N]``."""
    m_b = ids_b.shape[0]
    valid_a = ids_a != EMPTY  # [Ma, N]
    e2 = jnp.full_like(dots_a, ZERO)
    b_cols = []
    for j in range(m_b):
        mj = valid_a & (ids_a == ids_b[j][None, :])  # [Ma, N]
        e2 = jnp.maximum(e2, jnp.where(mj[:, None, :], dots_b[j][None], ZERO))
        b_cols.append(_any_t(mj, axis=0))  # [N]
    return e2, jnp.stack(b_cols, axis=0)  # [Mb, N]


def _merge_rule_t(e1, e2, p1, p2, valid, self_clock, other_clock):
    """Three-way per-member dot algebra; ``e[M, A, N]``, masks ``[M, N]``,
    clocks ``[A, N]``."""
    sc = self_clock[None]  # [1, A, N]
    oc = other_clock[None]
    common = jnp.where(e1 == e2, e1, ZERO)
    c1 = _sub_t(_sub_t(e1, common), oc)
    c2 = _sub_t(_sub_t(e2, common), sc)
    out_both = jnp.maximum(common, jnp.maximum(c1, c2))
    keep1 = ~_all_t(e1 <= oc, axis=1)  # [M, N]
    out_only1 = jnp.where(keep1[:, None, :], e1, ZERO)
    out_only2 = _sub_t(e2, sc)
    both = (p1 & p2)[:, None, :]
    only1 = (p1 & ~p2)[:, None, :]
    out = jnp.where(both, out_both, jnp.where(only1, out_only1, out_only2))
    return jnp.where(valid[:, None, :], out, ZERO)


def _rank_select_t(keys, live, payload_ids, payload_clocks, cap):
    """Pack live slots in ascending-``keys`` order into ``cap`` output
    slots; ``keys``/``live``/``payload_ids [S, N]``, clocks ``[S, A, N]``."""
    s = keys.shape[0]
    rank = jnp.zeros(keys.shape, dtype=jnp.int32)
    for j in range(s):
        smaller = live & live[j][None] & (keys[j][None] < keys)
        rank = rank + smaller.astype(jnp.int32)
    out_ids, out_clocks = [], []
    for k in range(cap):
        sel = live & (rank == k)  # [S, N], at most one hot per column
        out_ids.append(
            jnp.sum(jnp.where(sel, payload_ids + 1, 0), axis=0, dtype=jnp.int32) - 1
        )
        out_clocks.append(
            jnp.max(jnp.where(sel[:, None, :], payload_clocks, ZERO), axis=0)
        )
    ids = jnp.stack(out_ids, axis=0)  # [cap, N]
    clocks = jnp.stack(out_clocks, axis=0)  # [cap, A, N]
    overflow = jnp.sum(live, axis=0, dtype=jnp.int32) > cap  # [N]
    return ids, clocks, overflow


def _merge_tile_t(sa, sb, m_cap: int, d_cap: int):
    """Full pairwise merge of two lanes-last states (biased-int32 planes).

    Mirrors ``orswot_pallas._merge_tile`` stage for stage; returns the
    merged 5-tuple plus ``overflow[2, N]``."""
    ca, ids_a, dots_a, dida, dca = sa
    cb, ids_b, dots_b, didb, dcb = sb

    # member alignment + dot algebra (`orswot.rs:92-138`)
    e2_for_a, b_matched = _align_against_t(ids_a, dots_a, ids_b, dots_b)
    valid_a = ids_a != EMPTY
    valid_b = ids_b != EMPTY
    nonempty = lambda clocks: _any_t(clocks != ZERO, axis=1)  # [S, N]
    out_a = _merge_rule_t(
        dots_a, e2_for_a,
        valid_a & nonempty(dots_a), valid_a & nonempty(e2_for_a),
        valid_a, ca, cb,
    )
    b_only = valid_b & ~b_matched
    out_b = jnp.where(b_only[:, None, :], _sub_t(dots_b, ca[None]), ZERO)

    ids_cat = jnp.concatenate(
        [jnp.where(valid_a, ids_a, EMPTY), jnp.where(b_only, ids_b, EMPTY)], axis=0
    )  # [Ma+Mb, N]
    dots_cat = jnp.concatenate([out_a, out_b], axis=0)  # [Ma+Mb, A, N]

    # deferred union + dedup, keep first (`orswot.rs:141-148`)
    d_ids = jnp.concatenate([dida, didb], axis=0)  # [Dn, N]
    d_clocks = jnp.concatenate([dca, dcb], axis=0)  # [Dn, A, N]
    dn = d_ids.shape[0]
    d_valid = d_ids != EMPTY
    dup_cols = [jnp.zeros(d_ids.shape[1:], dtype=bool)]
    for j in range(1, dn):
        dup_j = jnp.zeros(d_ids.shape[1:], dtype=bool)
        for i in range(j):
            same = (
                d_valid[i]
                & d_valid[j]
                & (d_ids[i] == d_ids[j])
                & _all_t(d_clocks[i] == d_clocks[j], axis=0)
            )
            dup_j = dup_j | same
        dup_cols.append(dup_j)
    is_dup = jnp.stack(dup_cols, axis=0)
    d_live = d_valid & ~is_dup
    d_ids = jnp.where(d_live, d_ids, EMPTY)
    d_clocks = jnp.where(d_live[:, None, :], d_clocks, ZERO)

    # clock join (`orswot.rs:153`) then deferred replay (`:155`)
    clock = jnp.maximum(ca, cb)
    rm = jnp.full_like(dots_cat, ZERO)
    for k in range(dn):
        match = (ids_cat == d_ids[k][None]) & d_live[k][None]  # [Mcat, N]
        rm = jnp.maximum(rm, jnp.where(match[:, None, :], d_clocks[k][None], ZERO))
    new_dots = _sub_t(dots_cat, rm)
    live = nonempty(new_dots) & (ids_cat != EMPTY)
    still_ahead = d_live & ~_all_t(d_clocks <= clock[None], axis=1)

    # canonical compaction (ascending member id / first-occurrence order)
    big = jnp.iinfo(jnp.int32).max
    m_keys = jnp.where(live, ids_cat, big)
    ids_out, dots_out, m_over = _rank_select_t(m_keys, live, ids_cat, new_dots, m_cap)
    slot_keys = jax.lax.broadcasted_iota(jnp.int32, d_ids.shape, 0)
    dids_out, dclk_out, d_over = _rank_select_t(
        slot_keys, still_ahead, d_ids, d_clocks, d_cap
    )
    return (clock, ids_out, dots_out, dids_out, dclk_out), jnp.stack(
        [m_over, d_over], axis=0
    )


def merge_t(sa, sb, m_cap: int, d_cap: int):
    """Pairwise merge of two lanes-last **uint32** states (5-tuples as
    produced by :func:`to_lanes`).  Returns ``(state, overflow[2, N])`` —
    stay in this layout across a fold and :func:`from_lanes` at the end."""
    _op._check_dtypes(sa[0])
    _op._check_dtypes(sb[0])
    cdt = sa[0].dtype
    out, over = _merge_tile_t(
        _op._to_kernel_dtype(sa), _op._to_kernel_dtype(sb), m_cap, d_cap
    )
    clock, ids, dots, dids, dclk = out
    return (
        _op._from_kernel_dtype(clock, cdt), ids,
        _op._from_kernel_dtype(dots, cdt), dids,
        _op._from_kernel_dtype(dclk, cdt),
    ), over


def stacked_to_lanes(stack):
    """Transpose stacked replica fleets ``[R, N, ...]`` (the
    ``fold_merge_tree``/bench layout) to lanes-last per fleet:
    ``clock[R, A, N]``, ``ids[R, M, N]``, ``dots[R, M, A, N]``, ... —
    :func:`to_lanes` mapped over the fleet axis, so the layout has one
    source of truth."""
    return jax.vmap(to_lanes)(tuple(stack))


def fold_merge_t(stack, m_cap: int, d_cap: int, plunger: bool = True):
    """Anti-entropy left fold over ``R`` stacked lanes-last fleets (from
    :func:`stacked_to_lanes`): fold fleet ``i`` into the accumulator for
    ``i = 1..R-1``, optionally finishing with the defer-plunger self-merge
    (`/root/reference/test/orswot.rs:61-62`) — the lanes-layout equivalent
    of the sequential jnp fold the bench times.  The whole fold runs in
    the biased-int32 kernel domain (one conversion in, one out — not one
    per merge).  Returns ``(state, overflow[2, N])`` with overflow
    OR-reduced over every merge."""
    _op._check_dtypes(stack[0])
    cdt = stack[0].dtype
    r = stack[0].shape[0]
    kstack = _op._to_kernel_dtype(stack)
    acc = tuple(x[0] for x in kstack)
    over_acc = jnp.zeros((2, stack[0].shape[-1]), bool)
    for i in range(1, r):
        acc, over = _merge_tile_t(acc, tuple(x[i] for x in kstack), m_cap, d_cap)
        over_acc = over_acc | over
    if plunger:
        acc, over = _merge_tile_t(acc, acc, m_cap, d_cap)
        over_acc = over_acc | over
    clock, ids, dots, dids, dclk = acc
    return (
        _op._from_kernel_dtype(clock, cdt), ids,
        _op._from_kernel_dtype(dots, cdt), dids,
        _op._from_kernel_dtype(dclk, cdt),
    ), over_acc


def merge_lanes(
    clock_a, ids_a, dots_a, dids_a, dclocks_a,
    clock_b, ids_b, dots_b, dids_b, dclocks_b,
    m_cap: int, d_cap: int,
):
    """Drop-in for ``orswot_ops.merge`` (single ``[N, ...]`` batch axis)
    that executes lanes-last: transpose in, merge, transpose out.  For
    real folds keep the state transposed instead (:func:`merge_t`) — the
    boundary transposes here exist so parity tests and one-shot callers
    can use the standard layout."""
    _op._check_dtypes(clock_a)
    sa = to_lanes((clock_a, ids_a, dots_a, dids_a, dclocks_a))
    sb = to_lanes((clock_b, ids_b, dots_b, dids_b, dclocks_b))
    out, over = merge_t(sa, sb, m_cap, d_cap)
    clock, ids, dots, dids, dclk = from_lanes(out)
    return clock, ids, dots, dids, dclk, over.T
