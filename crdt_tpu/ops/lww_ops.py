"""Batched last-write-wins register kernel.

Reference semantics (`/root/reference/src/lwwreg.rs:43-67`): keep the value
with the larger marker; equal markers with different values is an error.
Batched kernels can't raise per-element (SURVEY.md §7.3), so ``merge``
returns the merged ``(val, marker)`` plus a **conflict bitmap** the host
surfaces as :class:`crdt_tpu.error.ConflictingMarker` — keeping scalar-path
error parity.

Markers are unsigned ints (the 10M-register benchmark config uses u64
timestamps); values are any array dtype with elementwise equality.
"""

from __future__ import annotations

import jax.numpy as jnp


def merge(val_a, marker_a, val_b, marker_b):
    """Pairwise merge. Returns ``(val, marker, conflict)``.

    ``conflict[i]`` is True where ``marker_a == marker_b`` but the values
    differ (`lwwreg.rs:61-62`); the merged value there is ``val_a``
    (self-biased, matching the reference which leaves self untouched before
    erroring).
    """
    take_b = marker_b > marker_a
    val = jnp.where(take_b, val_b, val_a)
    marker = jnp.where(take_b, marker_b, marker_a)
    conflict = (marker_a == marker_b) & (val_a != val_b)
    return val, marker, conflict


def update(val, marker, new_val, new_marker):
    """Batched ``update`` (`lwwreg.rs:104-118`): same lattice rule as merge."""
    return merge(val, marker, new_val, new_marker)
