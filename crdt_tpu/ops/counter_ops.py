"""Batched counter kernels.

GCounter is a VClock newtype (`/root/reference/src/gcounter.rs:26-28`);
PNCounter stacks two of them (`/root/reference/src/pncounter.rs:33-36`).
A PNCounter batch is ``u64[..., 2, A]`` — plane 0 = P (increments),
plane 1 = N (decrements).
"""

from __future__ import annotations

import jax.numpy as jnp

from . import clock_ops

# GCounter: merge is the clock join, value is the actor-axis sum
gcounter_merge = clock_ops.merge
gcounter_value = clock_ops.value_sum


def pncounter_merge(a, b):
    """Merge P with P and N with N (`pncounter.rs:90-95`) — one max over
    the stacked planes."""
    return jnp.maximum(a, b)


def pncounter_value(pn):
    """P − N as signed (`pncounter.rs:117-119`)."""
    sums = jnp.sum(pn, axis=-1).astype(jnp.int64)
    return sums[..., 0] - sums[..., 1]
