"""Dense JAX/XLA join kernels over columnar SoA buffers — the TPU hot path.

Representation (SURVEY.md §7.0): actors are interned to dense int32 indices;
a vector clock batch is ``u64[..., A]`` with 0 meaning "absent" (the implied
-zero rule, `/root/reference/src/vclock.rs:206-210`).  Every kernel here is a
pure function over arrays, safe under ``jit`` / ``vmap`` / ``shard_map``.
"""

from ..config import enable_x64 as _enable_x64

_enable_x64()

# orswot_pallas / orswot_unrolled are imported on demand: they pull
# jax.experimental.pallas, which stays off the default import path
from . import clock_ops, counter_ops, lww_ops, mvreg_ops, orswot_ops
