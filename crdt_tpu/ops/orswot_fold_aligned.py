"""Union-aligned fused Pallas fold — the bandwidth-bound ORSWOT join.

The first fused fold (:mod:`crdt_tpu.ops.orswot_pallas`) iterates the full
pairwise tile merge — O(M²) alignment, per-slot rank-select compaction —
once per replica, and Mosaic stack-allocates ~1.4 MB of temporaries per
object for it, forcing 8-object tiles; measured on-chip it is
VPU-compute-bound at 0.60M merges/s while moving only ~3.4 GB/s
(`PERF.md`, 2026-08-01 window).  This kernel restructures the fold around
one observation: **the expensive work in the pairwise pipeline is
alignment and compaction, and neither needs to happen per step.**

Algorithm, per object tile:

1. **Union table, once** — the distinct member ids across all ``R``
   replica tables, built incrementally in first-occurrence order with
   id-plane ops only (``[T, U]`` compares; no ``[A]``-axis data moves).
2. **Align, once per replica** — replica ``r``'s dot rows gathered onto
   union slots by masked max (``U×M`` compares, ``[T, U, A]`` selects).
3. **Fold steps, pure elementwise** — with every side on the same slot
   table the pairwise dot-algebra (`/root/reference/src/orswot.rs:89-156`)
   is elementwise over ``[T, U, A]``: no sorting, no gathers, no
   compaction.  Each step replays the (narrow) deferred pipeline exactly
   like the pairwise merge — union+dedup, clock join, subtract, compact
   to ``d_cap`` — so step ``k`` is bit-identical to the jnp fold's step
   ``k`` whenever no capacity overflow occurs.
4. **Canonical compaction, once** — ascending-member-id rank selection of
   the final survivors into ``m_cap`` slots.

Contract vs the sequential jnp fold (``orswot_ops.merge`` left fold +
defer plunger, `/root/reference/test/orswot.rs:45-62`):

* **No overflow flagged ⇒ bit-identical outputs** (clock, member table,
  deferred table).  Asserted in ``tests/test_orswot_fold_aligned.py``.
* **Overflow flagged ⇒ outputs unspecified** (the host discards and
  regrows — `parallel/executor.py` — so truncated states are never
  observed).  The flag is conservative: it covers the jnp fold's
  per-step survivor overflow AND the union table itself outgrowing
  ``u_cap`` (a case the stepwise fold never sees because it truncates as
  it goes).  The kernel may therefore flag inputs the jnp fold would
  not; it never stays silent where the jnp fold would flag.

Traffic: each replica state is read exactly once and the joined state
written once — ``(R+1)/R`` states per merge instead of the sequential
fold's 3 (read acc + read replica + write acc).  At the north-star
shapes (A=64, M=16, D=2, u32, R=8) that is ~5.5 KB/merge vs the jnp
fold's measured 14.8 KB/merge (`PERF.md`).

Counters ride the same biased-int32 kernel domain as
:mod:`~crdt_tpu.ops.orswot_pallas` (``x ^ 0x8000_0000``; compare/max/
select only, exact over the full uint32 range), and the module reuses
its hard-won Mosaic idioms (`_emask`/`_bstack` i1 handling, int32
index-map constants, 32-bit trace mode).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..config import x64_disabled
from ..obs.kernels import observed_kernel

# jax 0.4.x spells pltpu.CompilerParams `TPUCompilerParams`
_compiler_params = getattr(pltpu, "CompilerParams", None) \
    or pltpu.TPUCompilerParams

from .orswot_pallas import (
    EMPTY,
    ZERO,
    _VMEM_LIMIT_BYTES,
    _all,
    _any,
    _bstack,
    _check_dtypes,
    _emask,
    _from_kernel_dtype,
    _gate_interpret,
    _interpret_default,
    _nonempty,
    _pad_to,
    _rank_select,
    _rank_select_slots,
    _state_specs,
    _sub,
    _to_kernel_dtype,
    _ZERO,
)

_SORT_MAX = np.int32(np.iinfo(np.int32).max)


# ---------------------------------------------------------------------------
# tile math
# ---------------------------------------------------------------------------


def _build_union(id_planes, u_cap: int):
    """Distinct member ids across the replica tables, first-occurrence
    order, into ``u_cap`` slots.

    ``id_planes`` is a list of ``[T, M]`` int32 planes.  Returns
    ``(union_ids [T, u_cap], n_union [T])`` — slots past the distinct
    count hold ``EMPTY``; ids past ``u_cap`` are dropped (the caller
    flags ``n_union > u_cap`` as overflow).  Id-plane ops only: per
    candidate, one ``[T, u_cap]`` membership test and a one-hot place at
    the running count."""
    t = id_planes[0].shape[0]
    union_ids = jnp.full((t, u_cap), EMPTY, jnp.int32)
    n_union = jnp.zeros((t,), jnp.int32)
    slot_iota = jnp.arange(u_cap, dtype=jnp.int32)
    for ids in id_planes:
        for m in range(ids.shape[-1]):
            cand = ids[..., m : m + 1]  # [T, 1]
            is_new = (cand[..., 0] != EMPTY) & ~_any(
                (union_ids != EMPTY) & (union_ids == cand)
            )
            place = _emask(is_new) & (
                slot_iota[None, :] == n_union[..., None]
            )
            union_ids = jnp.where(place, cand, union_ids)
            n_union = n_union + is_new.astype(jnp.int32)
    return union_ids, n_union


def _align_to_union(union_ids, ids, dots):
    """Replica dot rows gathered onto union slots (``ZERO`` rows where
    the member is absent).  ``ids``/``dots``: ``[T, M]`` / ``[T, M, A]``;
    returns ``[T, U, A]``."""
    out = jnp.full(union_ids.shape + dots.shape[-1:], ZERO, jnp.int32)
    for m in range(ids.shape[-1]):
        cand = ids[..., m : m + 1]
        match = (union_ids != EMPTY) & (union_ids == cand)  # [T, U]
        out = jnp.maximum(
            out, jnp.where(_emask(match), dots[..., m : m + 1, :], ZERO)
        )
    return out


def _step_members(acc, e2, c_prev, c_rep, union_valid, m_cap: int):
    """One fold step's member dot-algebra on union slots — the exact
    pairwise rule (`orswot.rs:92-138`) with self = accumulator (clock
    ``c_prev``), other = replica (clock ``c_rep``).  Returns
    ``(out [T, U, A], m_over [T])`` where ``m_over`` reproduces the jnp
    fold's pre-replay survivor count check."""
    sc = c_prev[..., None, :]
    oc = c_rep[..., None, :]
    p1 = _nonempty(acc)  # [T, U]
    p2 = _nonempty(e2)

    common = jnp.where(acc == e2, acc, ZERO)
    c1 = _sub(_sub(acc, common), oc)
    c2 = _sub(_sub(e2, common), sc)
    out_both = jnp.maximum(common, jnp.maximum(c1, c2))
    keep1 = ~_all(acc <= oc)  # keep FULL clock (`orswot.rs:94-103`)
    out_only1 = jnp.where(_emask(keep1), acc, ZERO)
    out_only2 = _sub(e2, sc)  # subtracted clock (`orswot.rs:132-138`)

    both = _emask(p1 & p2)
    only1 = _emask(p1 & ~p2)
    out = jnp.where(both, out_both, jnp.where(only1, out_only1, out_only2))
    out = jnp.where(_emask(union_valid), out, ZERO)

    n_surv = jnp.sum(
        (_nonempty(out) & union_valid).astype(jnp.int32), axis=-1
    )
    return out, n_surv > m_cap


def _step_deferred(union_ids, acc, c_new, d1_ids, d1_clocks, d2_ids, d2_clocks,
                   d_cap: int):
    """One fold step's deferred pipeline: union + dedup-keep-first
    (`orswot.rs:141-148`), replay against the member rows (`:155` →
    `:195-211`), retain still-ahead rows, compact to ``d_cap`` in
    first-occurrence slot order — bit-matching the pairwise merge's
    ``_dedup_deferred`` → ``_apply_deferred`` → ``compact`` chain."""
    d_ids = jnp.concatenate([d1_ids, d2_ids], axis=-1)  # [T, 2D]
    d_clocks = jnp.concatenate([d1_clocks, d2_clocks], axis=-2)
    dn = d_ids.shape[-1]
    d_valid = d_ids != EMPTY
    dup_cols = [jnp.zeros(d_ids.shape[:-1], dtype=bool)]
    for j in range(1, dn):
        dup_j = jnp.zeros(d_ids.shape[:-1], dtype=bool)
        for i in range(j):
            same = (
                d_valid[..., i]
                & d_valid[..., j]
                & (d_ids[..., i] == d_ids[..., j])
                & _all(d_clocks[..., i, :] == d_clocks[..., j, :])
            )
            dup_j = dup_j | same
        dup_cols.append(dup_j)
    d_live = d_valid & ~_bstack(dup_cols, axis=-1)

    # replay: subtract the join of matching deferred clocks per member
    rm = jnp.full_like(acc, ZERO)
    for k in range(dn):
        match = (
            (union_ids != EMPTY)
            & (union_ids == d_ids[..., k : k + 1])
            & d_live[..., k : k + 1]
        )
        rm = jnp.maximum(
            rm, jnp.where(_emask(match), d_clocks[..., k : k + 1, :], ZERO)
        )
    new_acc = _sub(acc, rm)

    still_ahead = d_live & ~_all(d_clocks <= c_new[..., None, :])
    d_ids_out, d_clocks_out, d_over = _rank_select_slots(
        still_ahead, d_ids, d_clocks, d_cap
    )
    return new_acc, d_ids_out, d_clocks_out, d_over


# ---------------------------------------------------------------------------
# pallas_call wrapper
# ---------------------------------------------------------------------------


def _tile_size(a, m, d, r, u_cap, vmem_budget=40 * 1024 * 1024):
    """Largest power-of-two object tile fitting the VMEM budget.

    Working set per object: the R input states + output, the aligned
    accumulator/replica planes (~4 live ``[U, A]`` temporaries — the
    elementwise steps keep at most the rule's select chain alive), and
    the final rank-select's per-slot selects.  Calibrate against the AOT
    memory plan (``scripts/aot_compile_check.py fold_aligned_ns``)."""
    import os

    forced = os.environ.get("CRDT_PALLAS_TILE")
    if forced:
        t = int(forced)
        if t < 8 or t & (t - 1):
            raise ValueError(
                f"CRDT_PALLAS_TILE={forced!r} must be a power of two >= 8"
            )
        return t
    state_bytes = 4 * (a + m + m * a + d + d * a)
    work_bytes = 4 * (6 * u_cap * a + 8 * d * a + 2 * r * m + 4 * u_cap)
    bytes_per_obj = (r + 1) * state_bytes + work_bytes
    # capped at 64, not the VMEM ceiling: Mosaic splits every wide op
    # into ~tile native registers, so compile time scales ~linearly with
    # the tile (measured: the r=4 kernel at tile 512 took 33 min to
    # compile — unusable inside a tunnel window; tile 64 keeps the
    # instruction count ~8x smaller while the grid pipeline still
    # overlaps HBM perfectly well at 977 tiles/chunk)
    t = 64
    while t > 8 and t * bytes_per_obj > vmem_budget:
        t //= 2
    if t * bytes_per_obj > vmem_budget:
        raise ValueError(
            f"aligned-fold working set ({t * bytes_per_obj} bytes at the "
            f"minimum tile of {t} objects, r={r}, u_cap={u_cap}) exceeds "
            f"the {vmem_budget}-byte VMEM budget; use the jnp fold "
            "(orswot_ops.merge left fold) or a smaller fold width R"
        )
    return t


def pad_to_tile(state, m_cap: int, d_cap: int, n_states: int, u_cap: int | None = None):
    """Pad ``[R, N, ...]`` stacked planes on the object axis to this
    kernel's tile size (fill: ``EMPTY`` for id planes, 0 for counters) so
    callers pay the padding copy once outside a timed loop."""
    a = state[0].shape[-1]
    m = state[1].shape[-1]
    d = state[3].shape[-1]
    r = n_states - 1
    t = _tile_size(a, m, d, r, u_cap if u_cap is not None else 2 * m_cap)
    return tuple(
        _pad_to(x, t, axis=1, fill=EMPTY if x.dtype == jnp.int32 else 0)
        for x in state
    )


@observed_kernel("ops.fold_aligned.fold_merge")
@functools.partial(jax.jit, static_argnames=(
    "m_cap", "d_cap", "u_cap", "interpret", "plunger", "prebiased"))
def fold_merge(
    clock, ids, dots, dids, dclocks,
    m_cap: int, d_cap: int, u_cap: int | None = None,
    interpret: bool | None = None, plunger: bool = True,
    prebiased: bool = False,
):
    """Anti-entropy fold of ``R`` stacked replica fleets (``[R, N, ...]``
    planes) into one ``[N, ...]`` state — drop-in for
    ``orswot_pallas.fold_merge`` (same signature plus ``u_cap``), built
    on the union-aligned tile math above.

    ``u_cap`` bounds the per-object distinct-member union across all
    replicas (default ``2 * m_cap``); a wider union flags member
    overflow.  See the module docstring for the overflow contract."""
    if interpret is None:
        interpret = _interpret_default()
    r, n, a = clock.shape
    m, d = ids.shape[-1], dids.shape[-1]
    if u_cap is None:
        u_cap = 2 * m_cap
    t = _tile_size(a, m, d, r, u_cap)
    state = (clock, ids, dots, dids, dclocks)
    if prebiased:
        if clock.dtype != jnp.int32:
            raise TypeError(
                f"prebiased fold expects int32 kernel-domain planes, got "
                f"{clock.dtype}; use orswot_pallas.to_kernel_domain() first"
            )
        cdt = None
        state = tuple(
            _pad_to(x, t, axis=1, fill=EMPTY if i in (1, 3) else ZERO)
            for i, x in enumerate(state)
        )
    else:
        _check_dtypes(clock)
        cdt = clock.dtype
        state = tuple(
            _pad_to(x, t, axis=1, fill=EMPTY if x.dtype == jnp.int32 else 0)
            for x in state
        )
        state = _to_kernel_dtype(state)
    n_pad = state[0].shape[1]

    def kernel(ca, ia, da, dia, dca, oc, oi, od, odi, odc, oover):
        # --- union + first alignment -----------------------------------
        union_ids, n_union = _build_union([ia[rr] for rr in range(r)], u_cap)
        union_valid = union_ids != EMPTY
        acc = _align_to_union(union_ids, ia[0], da[0])
        c_acc = ca[0]
        d_ids_acc, d_clocks_acc = dia[0], dca[0]
        m_over = n_union > u_cap
        d_over = jnp.zeros_like(m_over)

        def step(acc, c_acc, d_ids_acc, d_clocks_acc, e2, c_rep, d2i, d2c):
            out, over_m = _step_members(
                acc, e2, c_acc, c_rep, union_valid, m_cap
            )
            c_new = jnp.maximum(c_acc, c_rep)
            out, d_ids_o, d_clocks_o, over_d = _step_deferred(
                union_ids, out, c_new, d_ids_acc, d_clocks_acc, d2i, d2c,
                d_cap,
            )
            return out, c_new, d_ids_o, d_clocks_o, over_m, over_d

        for rr in range(1, r):
            e2 = _align_to_union(union_ids, ia[rr], da[rr])
            acc, c_acc, d_ids_acc, d_clocks_acc, om, od_ = step(
                acc, c_acc, d_ids_acc, d_clocks_acc, e2, ca[rr], dia[rr], dca[rr]
            )
            m_over, d_over = m_over | om, d_over | od_
        if plunger:
            acc, c_acc, d_ids_acc, d_clocks_acc, om, od_ = step(
                acc, c_acc, d_ids_acc, d_clocks_acc,
                acc, c_acc, d_ids_acc, d_clocks_acc,
            )
            m_over, d_over = m_over | om, d_over | od_

        # --- canonical compaction (ascending member id) ----------------
        live = _nonempty(acc) & union_valid
        keys = jnp.where(live, union_ids, _SORT_MAX)
        ids_out, dots_out, _ = _rank_select(keys, live, union_ids, acc, m_cap)

        for ref, val in zip(
            (oc, oi, od, odi, odc),
            (c_acc, ids_out, dots_out, d_ids_acc, d_clocks_acc),
        ):
            ref[...] = val
        oover[...] = _bstack([m_over, d_over], axis=-1).astype(jnp.int32)

    in_specs = []
    for x in state:
        rest = x.ndim - 2
        in_specs.append(
            pl.BlockSpec(
                (r, t) + x.shape[2:],
                lambda i, _r=rest: (_ZERO, i) + (_ZERO,) * _r,
            )
        )
    out_shape = (
        jax.ShapeDtypeStruct((n_pad, a), jnp.int32),
        jax.ShapeDtypeStruct((n_pad, m_cap), jnp.int32),
        jax.ShapeDtypeStruct((n_pad, m_cap, a), jnp.int32),
        jax.ShapeDtypeStruct((n_pad, d_cap), jnp.int32),
        jax.ShapeDtypeStruct((n_pad, d_cap, a), jnp.int32),
        jax.ShapeDtypeStruct((n_pad, 2), jnp.int32),
    )
    # 32-bit trace mode — see orswot_pallas.merge
    _gate_interpret(interpret)
    with x64_disabled():
        out = pl.pallas_call(
            kernel,
            grid=(n_pad // t,),
            in_specs=in_specs,
            out_specs=_state_specs(t, [s.shape for s in out_shape]),
            out_shape=out_shape,
            compiler_params=_compiler_params(
                vmem_limit_bytes=_VMEM_LIMIT_BYTES
            ),
            interpret=interpret,
        )(*state)
    c, i, dts, di, dc, over = (x[:n] for x in out)
    if prebiased:
        return c, i, dts, di, dc, over.astype(bool)
    return (
        _from_kernel_dtype(c, cdt), i, _from_kernel_dtype(dts, cdt), di,
        _from_kernel_dtype(dc, cdt), over.astype(bool),
    )
