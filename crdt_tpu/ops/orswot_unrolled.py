"""Gather/sort-free ORSWOT merge — the TPU-default implementation.

The rank-select pipeline (:func:`crdt_tpu.ops.orswot_ops.merge`) leans on
``take_along_axis`` gathers and a counting-rank permutation.  This module
runs the same algebra as unrolled one-hot selects and max-reductions over
the small static slot axes — the style of the Pallas tile math
(:mod:`crdt_tpu.ops.orswot_pallas`), which XLA fuses into dense
elementwise passes.  It trades O(M) extra reads of the dot tables for
regularity: measured 17% slower on the memory-bound CPU backend, but the
round-3 on-chip layout A/B made it the **TPU default** (54.0 ms vs the
rank path's 57.7 ms at config-4 shapes — `reports/LAYOUT_AB_TPU.md`).

The lanes-last (object-axis-minor) variant that shared this module lost
that A/B 2× (120 ms at config-4: the boundary transposes and broadcasted
[A, N] selects cost more than the lane under-utilization they recover)
and was deleted per the round-2 verdict's prune directive; see
`reports/LAYOUT_AB_TPU.md` for the numbers that killed it.

Semantics are `/root/reference/src/orswot.rs:89-156` throughout — the
rule-by-rule citations live in ``orswot_ops``/``orswot_pallas``; this
variant only changes execution layout, never the algebra.  Counters are
uint32 (the bias-to-int32 trick of the Pallas path — order-preserving
``x ^ 0x8000_0000``; exact, since the merge only compares/maxes/selects).
"""

from __future__ import annotations

from . import orswot_pallas as _op

EMPTY = _op.EMPTY
ZERO = _op.ZERO


def merge_unrolled(
    clock_a, ids_a, dots_a, dids_a, dclocks_a,
    clock_b, ids_b, dots_b, dids_b, dclocks_b,
    m_cap: int, d_cap: int,
):
    """Pairwise merge via the unrolled (gather/sort-free) tile math in the
    standard ``[N, ...]`` layout.  Drop-in for ``orswot_ops.merge``: it IS
    ``orswot_pallas._merge_tile`` run as plain jnp, so parity with the
    production merge is inherited from ``tests/test_orswot_pallas.py`` and
    re-asserted in ``tests/test_orswot_unrolled.py``."""
    _op._check_dtypes(clock_a)
    _op._check_dtypes(clock_b)
    cdt = clock_a.dtype
    sa = _op._to_kernel_dtype((clock_a, ids_a, dots_a, dids_a, dclocks_a))
    sb = _op._to_kernel_dtype((clock_b, ids_b, dots_b, dids_b, dclocks_b))
    (clock, ids, dots, dids, dclk), over = _op._merge_tile(sa, sb, m_cap, d_cap)
    return (
        _op._from_kernel_dtype(clock, cdt), ids,
        _op._from_kernel_dtype(dots, cdt), dids,
        _op._from_kernel_dtype(dclk, cdt), over,
    )
