"""Batched reset-remove Map kernels — CRDT composition on device (L4 on TPU).

Dense per-object state for ``Map<K, V>`` (`/root/reference/src/map.rs:83-99`):

* ``clock    u64[..., A]``    — the map clock
* ``keys     int32[..., K]``  — interned key ids, ``-1`` = empty slot
* ``eclocks  u64[..., K, A]`` — per-key entry clocks (add-witnesses)
* ``vals``                    — nested value state: a pytree whose leaves all
  carry the key axis right after the batch axes (``[..., K, *inner]``)
* ``d_keys   int32[..., D]``  — deferred-remove key ids
* ``d_clocks u64[..., D, A]`` — deferred-remove witnessing clocks

The nested value type is abstracted as a *value kernel* ``vk`` (duck-typed —
see :mod:`crdt_tpu.batch.val_kernels`): ``merge(va, vb) -> (v, overflow)``,
``truncate(v, clock) -> (v, overflow)`` and ``zeros_like(v)``, all
rank-polymorphic over leading batch axes.  Passing a Map kernel as ``vk``
nests maps to any static depth (`map.rs:16-25` admits any causal ``V``,
including another Map); the host-side recursion unrolls into one fused XLA
program per nesting shape (SURVEY.md §7.0).

``merge`` mirrors `/root/reference/src/map.rs:192-269` exactly: the Orswot
dot algebra per key, recursive ``val.merge`` plus reset-remove
``val.truncate``, the **asymmetric** deferred replay — other's deferred rows
already covered by self's clock are discarded without effect, because
`map.rs:256-260` replays them against the *pre-merge* entries which are then
overwritten by ``keep`` — and the final ``apply_deferred`` against the
joined clock (`map.rs:265-267`).  Sequential per-row clock subtracts compose
into a single subtract-by-join over the actor axis (``sub(sub(x, a), b) ==
sub(x, max(a, b))`` pointwise), which is what lets the replay vectorize.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from . import clock_ops
from .orswot_ops import EMPTY, _dedup_deferred, compact

_SORT_MAX = jnp.iinfo(jnp.int32).max


# -- pytree helpers over the key-slot axis ----------------------------------


def tree_gather(v, idx):
    """Gather value-state slots along the key axis (position ``idx.ndim-1``)."""
    ax = idx.ndim - 1

    def g(leaf):
        ii = idx.reshape(idx.shape + (1,) * (leaf.ndim - idx.ndim))
        return jnp.take_along_axis(leaf, ii, axis=ax)

    return jax.tree.map(g, v)


def tree_where(mask, v, w):
    """Slot-wise select between two value states; ``mask`` broadcasts from
    the left (leading axes)."""

    def s(a, b):
        mm = mask.reshape(mask.shape + (1,) * (a.ndim - mask.ndim))
        return jnp.where(mm, a, b)

    return jax.tree.map(s, v, w)


def tree_slice(v, ax, cap):
    """Slice the first ``cap`` slots along axis ``ax`` of every leaf."""
    return jax.tree.map(lambda leaf: jax.lax.slice_in_dim(leaf, 0, cap, axis=ax), v)


def tree_scatter_slot(v, slot, upd, do, num_slots):
    """Write ``upd`` (leaves ``[..., *inner]``) into key slot ``slot`` of
    ``v`` (leaves ``[..., K, *inner]``) for objects where ``do``."""
    onehot = (jnp.arange(num_slots) == slot[..., None]) & do[..., None]  # [..., K]

    def s(leaf, u):
        m = onehot.reshape(onehot.shape + (1,) * (leaf.ndim - onehot.ndim))
        return jnp.where(m, jnp.expand_dims(u, slot.ndim), leaf)

    return jax.tree.map(s, v, upd)


# -- key alignment ----------------------------------------------------------


def align_keyed(keys_a, keys_b):
    """Align two key tables on key id (the BTreeMap lookup of
    `map.rs:196-197` as a sort + adjacent-run match — no hashing on device).

    Returns ``(keys, idx_a, p_a, idx_b, p_b)`` over ``S = Ka + Kb`` slots:
    for each distinct key, ``idx_a``/``p_a`` give its slot in self's table
    and presence there, ``idx_b``/``p_b`` the same for other.  Gather
    payloads with :func:`tree_gather` and mask by presence.
    """
    k_a = keys_a.shape[-1]
    cat = jnp.concatenate([keys_a, keys_b], axis=-1)
    side = jnp.concatenate([jnp.zeros_like(keys_a), jnp.ones_like(keys_b)], axis=-1)
    src = jnp.broadcast_to(jnp.arange(cat.shape[-1]), cat.shape)

    order = jnp.argsort(jnp.where(cat == EMPTY, _SORT_MAX, cat), axis=-1, stable=True)
    s_ids = jnp.take_along_axis(cat, order, axis=-1)
    s_side = jnp.take_along_axis(side, order, axis=-1)
    s_src = jnp.take_along_axis(src, order, axis=-1)

    valid = s_ids != EMPTY
    adj = (s_ids[..., 1:] == s_ids[..., :-1]) & valid[..., 1:]
    same_as_prev = jnp.concatenate([jnp.zeros_like(valid[..., :1]), adj], axis=-1)
    same_as_next = jnp.concatenate([adj, jnp.zeros_like(valid[..., :1])], axis=-1)
    first = valid & ~same_as_prev

    # keys are unique within each side and the sort is stable, so a run is
    # [a], [b] or [a, b] — never longer, never [b, a]
    nxt_src = jnp.roll(s_src, -1, axis=-1)
    p_a = first & (s_side == 0)
    p_b = first & ((s_side == 1) | same_as_next)
    idx_a = jnp.where(p_a, s_src, 0)
    idx_b = jnp.where(s_side == 1, s_src, nxt_src) - k_a
    idx_b = jnp.clip(jnp.where(p_b, idx_b, 0), 0, max(cat.shape[-1] - k_a - 1, 0))
    keys = jnp.where(first, s_ids, EMPTY)
    return keys, idx_a, p_a, idx_b, p_b


# -- deferred settling ------------------------------------------------------


def _settle_deferred(clock, keys, eclocks, vals, d_keys, d_clocks, vk):
    """``apply_deferred`` (`map.rs:325-333`): replay every buffered
    ``(clock, key)`` row via ``apply_rm`` against the current clock; rows
    still ahead of it stay buffered (`map.rs:336-350`).  Matching rows'
    sequential subtracts compose into one subtract-by-join."""
    d_valid = d_keys != EMPTY
    match = keys[..., :, None] == jnp.where(d_valid, d_keys, EMPTY - 1)[..., None, :]
    rm = jnp.max(
        jnp.where(match[..., None], d_clocks[..., None, :, :], 0), axis=-2
    )  # [..., K, A]
    new_e = clock_ops.subtract(eclocks, rm)
    live = ~clock_ops.is_empty(new_e) & (keys != EMPTY)
    vals, over = vk.truncate(vals, rm)
    keys = jnp.where(live, keys, EMPTY)
    new_e = jnp.where(live[..., None], new_e, 0)
    vals = tree_where(live, vals, vk.zeros_like(vals))

    still_ahead = ~clock_ops.leq(d_clocks, clock[..., None, :]) & d_valid
    d_keys = jnp.where(still_ahead, d_keys, EMPTY)
    d_clocks = jnp.where(still_ahead[..., None], d_clocks, 0)
    return keys, new_e, vals, d_keys, d_clocks, jnp.any(over, axis=-1)


def compact_keyed(keys, eclocks, vals, vk, cap):
    """Pack live key slots first and truncate to ``cap`` slots.

    Returns ``(keys, eclocks, vals, overflow)``."""
    live = keys != EMPTY
    order = jnp.argsort(~live, axis=-1, stable=True)
    out_keys = jnp.take_along_axis(keys, order, axis=-1)[..., :cap]
    out_e = jnp.take_along_axis(eclocks, order[..., None], axis=-2)[..., :cap, :]
    out_v = tree_slice(tree_gather(vals, order), order.ndim - 1, cap)
    overflow = jnp.sum(live, axis=-1) > cap
    return out_keys, out_e, out_v, overflow


# -- state path -------------------------------------------------------------


def merge(state_a, state_b, vk, k_cap: int, d_cap: int):
    """Full pairwise Map merge (`map.rs:192-269`).

    ``state`` = ``(clock, keys, eclocks, vals, d_keys, d_clocks)``.  Returns
    ``(state, overflow)``; overflow is a per-object flag set when surviving
    keys exceed ``k_cap``, deferred rows exceed ``d_cap``, or a nested value
    kernel overflowed (host raises — capacity is the static-shape
    concession, SURVEY.md §7.3)."""
    clock_a, keys_a, ec_a, vals_a, dk_a, dc_a = state_a
    clock_b, keys_b, ec_b, vals_b, dk_b, dc_b = state_b

    keys, idx_a, p_a, idx_b, p_b = align_keyed(keys_a, keys_b)
    e1 = jnp.where(
        p_a[..., None], jnp.take_along_axis(ec_a, idx_a[..., None], axis=-2), 0
    )
    e2 = jnp.where(
        p_b[..., None], jnp.take_along_axis(ec_b, idx_b[..., None], axis=-2), 0
    )
    g1 = tree_gather(vals_a, idx_a)
    v1 = tree_where(p_a, g1, vk.zeros_like(g1))
    g2 = tree_gather(vals_b, idx_b)
    v2 = tree_where(p_b, g2, vk.zeros_like(g2))

    sc = clock_a[..., None, :]
    oc = clock_b[..., None, :]

    # present in both (`map.rs:213-240`)
    common0 = clock_ops.intersection(e1, e2)
    c1 = clock_ops.subtract(clock_ops.subtract(e1, common0), oc)
    c2 = clock_ops.subtract(clock_ops.subtract(e2, common0), sc)
    e_both = jnp.maximum(common0, jnp.maximum(c1, c2))
    # `map.rs:229-235` literally: deleters = (c1 ∨ c2) − merged entry clock.
    # c1, c2 ≤ e_both pointwise, so this is always empty and the nested
    # truncate in the both-branch is a no-op — exactly as in the reference.
    del_both = clock_ops.subtract(jnp.maximum(c1, c2), e_both)

    # only in self (`map.rs:198-211`): keep the SUBTRACTED clock (unlike
    # Orswot, which keeps the full clock — orswot.rs:94-103)
    e_only1 = clock_ops.subtract(e1, oc)
    del_only1 = clock_ops.subtract(oc, e_only1)

    # only in other (`map.rs:244-253`)
    e_only2 = clock_ops.subtract(e2, sc)
    del_only2 = clock_ops.subtract(sc, e_only2)

    both = p_a & p_b
    only1 = p_a & ~p_b
    eclocks = jnp.where(
        both[..., None], e_both, jnp.where(only1[..., None], e_only1, e_only2)
    )
    eclocks = jnp.where((p_a | p_b)[..., None], eclocks, 0)
    deleters = jnp.where(
        both[..., None], del_both, jnp.where(only1[..., None], del_only1, del_only2)
    )

    v_merged, over_vm = vk.merge(v1, v2)
    vals = tree_where(both, v_merged, tree_where(only1, v1, v2))
    vals, over_vt = vk.truncate(vals, deleters)

    survive = ~clock_ops.is_empty(eclocks) & (p_a | p_b)
    keys = jnp.where(survive, keys, EMPTY)
    eclocks = jnp.where(survive[..., None], eclocks, 0)
    vals = tree_where(survive, vals, vk.zeros_like(vals))

    # deferred: adopt other's rows NOT already covered by self's clock
    # (`map.rs:256-260` — covered rows are replayed against the pre-merge
    # entries, which `keep` then discards, so they have no effect), keep all
    # of self's rows, dedup exact (key, clock) pairs
    adopt = ~clock_ops.leq(dc_b, clock_a[..., None, :]) & (dk_b != EMPTY)
    d_keys = jnp.concatenate([dk_a, jnp.where(adopt, dk_b, EMPTY)], axis=-1)
    d_clocks = jnp.concatenate([dc_a, jnp.where(adopt[..., None], dc_b, 0)], axis=-2)
    d_keys, d_clocks = _dedup_deferred(d_keys, d_clocks)

    # clock join (`map.rs:265`), then apply_deferred (`map.rs:267`)
    clock = clock_ops.merge(clock_a, clock_b)
    keys, eclocks, vals, d_keys, d_clocks, over_def = _settle_deferred(
        clock, keys, eclocks, vals, d_keys, d_clocks, vk
    )

    keys, eclocks, vals, k_over = compact_keyed(keys, eclocks, vals, vk, k_cap)
    d_keys, d_clocks, d_over = compact(d_keys, d_clocks, d_cap)
    overflow = (
        jnp.any(over_vm & both & survive, axis=-1)
        | jnp.any(over_vt & survive, axis=-1)
        | over_def
        | k_over
        | d_over
    )
    return (clock, keys, eclocks, vals, d_keys, d_clocks), overflow


def truncate(state, clock, vk):
    """``Causal::truncate`` (`map.rs:131-158`): subtract ``clock`` from every
    entry clock (dropping emptied keys, truncating surviving values), filter
    deferred rows, subtract from the map clock."""
    mclock, keys, eclocks, vals, d_keys, d_clocks = state
    new_e = clock_ops.subtract(eclocks, clock[..., None, :])
    live = ~clock_ops.is_empty(new_e) & (keys != EMPTY)
    vals, over = vk.truncate(
        vals, jnp.broadcast_to(clock[..., None, :], eclocks.shape)
    )
    keys = jnp.where(live, keys, EMPTY)
    new_e = jnp.where(live[..., None], new_e, 0)
    vals = tree_where(live, vals, vk.zeros_like(vals))

    d_new = clock_ops.subtract(d_clocks, clock[..., None, :])
    d_live = ~clock_ops.is_empty(d_new) & (d_keys != EMPTY)
    d_keys = jnp.where(d_live, d_keys, EMPTY)
    d_new = jnp.where(d_live[..., None], d_new, 0)

    out_clock = clock_ops.subtract(mclock, clock)
    return (out_clock, keys, new_e, vals, d_keys, d_new), jnp.any(over, axis=-1)


# -- op path ----------------------------------------------------------------


def apply_up(state, actor_idx, counter, key_id, nested_apply, vk):
    """Batched ``Op::Up`` (`map.rs:163-189`): one nested update per object.

    ``nested_apply(v) -> (v, overflow)`` applies the per-object nested op to
    the gathered value-slot state (leaves ``[..., *inner]``); objects whose
    op is a dedup skip (`map.rs:170-173`) keep their original slot."""
    clock, keys, eclocks, vals, d_keys, d_clocks = state
    seen = jnp.take_along_axis(clock, actor_idx[..., None], axis=-1)[..., 0] >= counter

    existing = keys == key_id[..., None]
    has_slot = jnp.any(existing, axis=-1)
    free = keys == EMPTY
    has_free = jnp.any(free, axis=-1)
    slot = jnp.where(has_slot, jnp.argmax(existing, axis=-1), jnp.argmax(free, axis=-1))
    overflow = ~seen & ~has_slot & ~has_free
    do = ~seen & (has_slot | has_free)

    k = keys.shape[-1]
    onehot = jnp.arange(k) == slot[..., None]
    new_keys = jnp.where(do[..., None] & onehot, key_id[..., None], keys)
    # witness the dot on the entry clock and the map clock (`map.rs:181-185`)
    upd = (do[..., None] & onehot)[..., None] & (
        jnp.arange(eclocks.shape[-1]) == actor_idx[..., None, None]
    )
    new_e = jnp.where(upd, jnp.maximum(eclocks, counter[..., None, None]), eclocks)
    new_clock = jnp.where(
        do[..., None] & (jnp.arange(clock.shape[-1]) == actor_idx[..., None]),
        jnp.maximum(clock, counter[..., None]),
        clock,
    )

    v_slot = tree_gather(vals, slot[..., None])
    v_slot = jax.tree.map(lambda l: jnp.squeeze(l, axis=slot.ndim), v_slot)
    v_new, v_over = nested_apply(v_slot)
    vals = tree_scatter_slot(vals, slot, v_new, do, k)

    keys2, e2, vals2, dk2, dc2, over_def = _settle_deferred(
        new_clock, new_keys, new_e, vals, d_keys, d_clocks, vk
    )
    return (new_clock, keys2, e2, vals2, dk2, dc2), overflow | (v_over & do) | over_def


def apply_rm(state, rm_clock, key_id, vk):
    """Batched ``Op::Rm`` → ``apply_rm`` (`map.rs:336-350`): buffer the
    remove when its clock is ahead of the map clock, and always subtract it
    from the entry — dropping the key if emptied, truncating the nested
    value otherwise."""
    clock, keys, eclocks, vals, d_keys, d_clocks = state
    ahead = ~clock_ops.leq(rm_clock, clock)

    d_valid = d_keys != EMPTY
    same = (
        (d_keys == key_id[..., None])
        & clock_ops.eq(d_clocks, rm_clock[..., None, :])
        & d_valid
    )
    already = jnp.any(same, axis=-1)
    want = ahead & ~already
    free = ~d_valid
    has_free = jnp.any(free, axis=-1)
    dslot = jnp.argmax(free, axis=-1)
    overflow = want & ~has_free
    do_buf = (want & has_free)[..., None]
    onehot = jnp.arange(d_keys.shape[-1]) == dslot[..., None]
    new_dk = jnp.where(do_buf & onehot, key_id[..., None], d_keys)
    new_dc = jnp.where((do_buf & onehot)[..., None], rm_clock[..., None, :], d_clocks)

    target = keys == key_id[..., None]
    sub = clock_ops.subtract(eclocks, rm_clock[..., None, :])
    new_e = jnp.where(target[..., None], sub, eclocks)
    live = ~clock_ops.is_empty(new_e) & (keys != EMPTY)
    rm_slots = jnp.where(target[..., None], rm_clock[..., None, :], 0)
    vals, over_t = vk.truncate(vals, rm_slots)
    new_keys = jnp.where(live, keys, EMPTY)
    new_e = jnp.where(live[..., None], new_e, 0)
    vals = tree_where(live, vals, vk.zeros_like(vals))
    return (clock, new_keys, new_e, vals, new_dk, new_dc), overflow | jnp.any(
        over_t, axis=-1
    )
