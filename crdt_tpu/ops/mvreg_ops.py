"""Batched multi-value register kernel.

A register batch is a padded antichain: ``clocks u64[..., K, A]`` with a
payload array ``vals[..., K]``.  A slot is active iff its clock is non-empty
(a ``Put`` with an empty clock is a no-op, `/root/reference/src/mvreg.rs:161-163`,
and live values always carry dots).

``merge`` (`mvreg.rs:121-153`): keep each side's values not strictly
dominated by any value on the other side; values from ``other`` additionally
dedup against kept ``self`` values by clock equality.  Dominance is O(K²)
pairwise clock comparisons — fine for small K with masking discipline
(SURVEY.md §7.3).
"""

from __future__ import annotations

import jax.numpy as jnp

from . import clock_ops


def active(clocks):
    """Slot-occupancy mask ``[..., K]``."""
    return ~clock_ops.is_empty(clocks)


def merge(clocks_a, vals_a, clocks_b, vals_b):
    """Pairwise antichain merge.

    Returns ``(clocks, vals, keep)`` with 2K slots (self's survivors first,
    then other's); ``keep[..., 2K]`` marks live slots.  Use
    :func:`compact` to re-pack into K_cap slots.
    """
    act_a = active(clocks_a)  # [..., K]
    act_b = active(clocks_b)

    # pair[i, j] over the K axes: does b_j strictly dominate a_i?
    a_exp = clocks_a[..., :, None, :]  # [..., K, 1, A]
    b_exp = clocks_b[..., None, :, :]  # [..., 1, K, A]
    a_lt_b = clock_ops.lt(a_exp, b_exp)  # [..., K, K]
    b_lt_a = clock_ops.lt(b_exp, a_exp)
    a_eq_b = clock_ops.eq(a_exp, b_exp)

    # keep self vals with no dominating other val (`mvreg.rs:124-131`)
    keep_a = act_a & ~jnp.any(a_lt_b & act_b[..., None, :], axis=-1)
    # keep other vals with no dominating self val (`mvreg.rs:133-138`),
    # deduped by clock-equality against *kept* self vals (`mvreg.rs:139-148`)
    keep_b = act_b & ~jnp.any(b_lt_a & act_a[..., :, None], axis=-2)
    keep_b &= ~jnp.any(a_eq_b & keep_a[..., :, None], axis=-2)

    clocks = jnp.concatenate([clocks_a, clocks_b], axis=-2)
    vals = jnp.concatenate([vals_a, vals_b], axis=-1)
    keep = jnp.concatenate([keep_a, keep_b], axis=-1)
    clocks = jnp.where(keep[..., None], clocks, 0)
    vals = jnp.where(keep, vals, 0)
    return clocks, vals, keep


def compact(clocks, vals, keep, k_cap):
    """Pack live slots to the front and truncate to ``k_cap``.

    Returns ``(clocks, vals, overflow)`` where ``overflow`` flags registers
    whose live-slot count exceeded ``k_cap`` (host raises; capacities are a
    static-shape concession, `SURVEY.md §7.0`)."""
    order = jnp.argsort(~keep, axis=-1, stable=True)  # live slots first
    clocks = jnp.take_along_axis(clocks, order[..., None], axis=-2)[..., :k_cap, :]
    vals = jnp.take_along_axis(vals, order, axis=-1)[..., :k_cap]
    overflow = jnp.sum(keep, axis=-1) > k_cap
    return clocks, vals, overflow


def apply_put(clocks, vals, op_clock, op_val):
    """Batched ``Op::Put`` (`mvreg.rs:158-186`).

    Drops slots dominated-or-equal to the op clock, then adds the op value
    unless an existing (surviving) slot strictly dominates it.  The op slot
    reuses the first freed position via compaction by the caller; here we
    return 2K-slot outputs like :func:`merge` for uniformity: K existing
    slots (masked) + the op in slot K.
    """
    op_empty = clock_ops.is_empty(op_clock)  # [...]
    act = active(clocks)

    dominated = clock_ops.leq(clocks, op_clock[..., None, :])  # [..., K]
    retained = act & ~dominated
    # does any retained slot strictly dominate the op?
    dominates_op = clock_ops.lt(op_clock[..., None, :], clocks) & retained
    should_add = ~jnp.any(dominates_op, axis=-1) & ~op_empty

    # where the op is a no-op (empty clock), keep the original state
    keep_exist = jnp.where(op_empty[..., None], act, retained)
    out_clocks = jnp.where(keep_exist[..., None], clocks, 0)
    out_vals = jnp.where(keep_exist, vals, 0)

    add_clock = jnp.where(should_add[..., None], op_clock, 0)
    add_val = jnp.where(should_add, op_val, 0)
    clocks2 = jnp.concatenate([out_clocks, add_clock[..., None, :]], axis=-2)
    vals2 = jnp.concatenate([out_vals, add_val[..., None]], axis=-1)
    keep = jnp.concatenate([keep_exist, should_add[..., None]], axis=-1)
    return clocks2, vals2, keep


def read_clock(clocks):
    """Fold of every slot clock (`mvreg.rs:216-222`)."""
    return jnp.max(clocks, axis=-2)
