"""Fused Pallas TPU kernels for the ORSWOT merge hot path.

The jnp path (:mod:`crdt_tpu.ops.orswot_ops`) expresses the merge as
concat → argsort → gather → dot-algebra → compact; under XLA that is
several HBM round-trips over the ``[N, 2M, A]`` tables per merge.  These
kernels run the **entire** pairwise merge — alignment, dot algebra,
deferred union/dedup/replay, canonical compaction — for a tile of objects
inside VMEM, with exactly one HBM read of the inputs and one HBM write of
the outputs per object:

* :func:`merge` — fused pairwise merge, drop-in for
  ``orswot_ops.merge`` (bit-identical outputs, same signature).
* :func:`fold_merge` — the anti-entropy fold: joins ``R`` stacked replica
  fleets to fixpoint (left fold + defer-plunger self-merge,
  `/root/reference/test/orswot.rs:45-62`) while the accumulator lives in
  registers/VMEM across all ``R`` steps — the jnp fold re-reads the
  accumulator from HBM every step, so this saves ``~R×`` accumulator
  bandwidth, which dominates the north-star benchmark.

Design notes (vs the jnp path):

* Member alignment is O(M²) masked compares instead of a 2M argsort —
  there is no sort primitive in Mosaic, and for the padded member
  capacities this framework targets (M ≤ 64) the quadratic match is a
  handful of VPU passes over data already in VMEM.
* Canonical output order (ascending member id, then free slots — what the
  argsort path produces) is restored by *rank selection*: each survivor's
  output slot is the count of live members with a smaller id, and output
  slot ``k`` gathers its row with a one-hot masked reduction.  Deferred
  rows keep first-occurrence order (the jnp path's stable pack), via the
  same rank trick with slot index as the key.
* Counters are ``uint32`` on the Pallas path (Mosaic has no 64-bit
  vectors); the scalar/u64 path remains the parity oracle for u64.
  Inside the kernel counters are held as **bias-mapped int32** —
  ``x ^ 0x8000_0000`` bitcast to int32 — because Mosaic has no
  unsigned-integer reductions.  The XOR bias is an order-preserving
  bijection uint32→int32, and this kernel only ever *compares, maxes
  and selects* counters (never adds them), so signed-domain arithmetic
  is exact over the full uint32 range; counter ``0`` becomes the
  sentinel :data:`ZERO` (= INT32_MIN) inside the kernel.  The
  entry/exit bias is one fused XOR outside the kernel.

Deployment note: the kernels **AOT-compile clean for v5e** — verified
offline against a compile-only PJRT topology running the real Mosaic
compiler (`reports/PALLAS_LOCAL_AOT.md`; the journey there:
``reports/PALLAS_TPU_ATTEMPT.txt`` for the x64 pitfalls — 32-bit trace
mode, signed-domain reductions, int32 index-map constants — plus the i1
shape-cast, tiny-minor-broadcast, and scoped-VMEM fixes found by the
local AOT loop).  What remains unproven is *execution* through the
remote-TPU tunnel of this dev environment (terminal-side compile helper
fragility, libtpu version skew).  On TPU backends the benchmark harness
auto-attempts the fused fold after its jnp metrics are banked —
parity-gated against the scalar oracle, promoted to the headline only
if it wins (``CRDT_SKIP_PALLAS_HEADLINE=1`` disables the attempt);
the jnp path is the portable default and the two are bit-identical
(``tests/test_orswot_pallas.py``).

Semantics follow `/root/reference/src/orswot.rs:89-156` exactly — the
asymmetric keep rules (`orswot.rs:94-103` vs `:132-138`), deferred-map
union (`:141-148`), clock join (`:153`) and deferred replay (`:155`) — see
``orswot_ops`` for the rule-by-rule citations; parity with that path (and
transitively with the scalar engine) is asserted in
``tests/test_orswot_pallas.py``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..obs.kernels import observed_kernel

from ..config import x64_disabled

# jax 0.4.x spells pltpu.CompilerParams `TPUCompilerParams`
_compiler_params = getattr(pltpu, "CompilerParams", None) \
    or pltpu.TPUCompilerParams

EMPTY = -1
# biased-int32 representation of counter 0 (see module docstring): the
# kernel-internal "absent / empty clock lane" sentinel
ZERO = np.int32(-(2**31))
_BIAS = np.uint32(0x8000_0000)


# ---------------------------------------------------------------------------
# tile math (plain jnp on VMEM-resident values; shared by both kernels)
# ---------------------------------------------------------------------------


def _emask(b):
    """Rank-expand a boolean mask by one trailing axis, in the i32 domain.

    Mosaic's vector layout inference rejects shape casts on ``i1``
    vectors (``tpu.reshape vector<...xi1> -> vector<...x1xi1>``, found by
    local AOT compile against a v5e topology) — so the reshape runs on an
    int32 widening and the ``i1`` is re-derived by an elementwise compare
    in the target shape."""
    return b.astype(jnp.int32)[..., None] > 0


def _bstack(cols, axis=-1):
    """Stack boolean columns along a new axis via int32 (see :func:`_emask`:
    ``jnp.stack`` reshapes each ``i1`` column, which Mosaic cannot lower)."""
    return jnp.stack([c.astype(jnp.int32) for c in cols], axis=axis) > 0


def _align_against(ids_a, dots_a, ids_b, dots_b):
    """For each a-slot, the matching b dot clock (``ZERO`` — the biased
    empty lane — if unmatched), plus the mask of b-slots consumed by a
    match.  O(M_a · M_b) masked compares."""
    m_b = ids_b.shape[-1]
    valid_a = ids_a != EMPTY
    e2 = jnp.full_like(dots_a, ZERO)
    # columns are collected and stacked rather than written with
    # ``.at[..., j].set`` — under jax_enable_x64 the scatter's literal
    # start indices trace as int64 scalars, which Mosaic cannot lower
    b_cols = []
    for j in range(m_b):
        mj = valid_a & (ids_a == ids_b[..., j : j + 1])  # [T, M_a]
        e2 = jnp.maximum(e2, jnp.where(_emask(mj), dots_b[..., j : j + 1, :], ZERO))
        b_cols.append(_any(mj))
    return e2, _bstack(b_cols, axis=-1)


def _merge_rule(e1, e2, p1, p2, valid, self_clock, other_clock):
    """The three-way per-member dot-algebra (`orswot.rs:92-138`)."""
    sc = self_clock[..., None, :]
    oc = other_clock[..., None, :]
    common = jnp.where(e1 == e2, e1, ZERO)
    c1 = _sub(_sub(e1, common), oc)
    c2 = _sub(_sub(e2, common), sc)
    out_both = jnp.maximum(common, jnp.maximum(c1, c2))
    keep1 = ~_all(e1 <= oc)
    out_only1 = jnp.where(_emask(keep1), e1, ZERO)
    out_only2 = _sub(e2, sc)
    both = _emask(p1 & p2)
    only1 = _emask(p1 & ~p2)
    out = jnp.where(both, out_both, jnp.where(only1, out_only1, out_only2))
    return jnp.where(_emask(valid), out, ZERO)


def _sub(a, b):
    return jnp.where(a > b, a, ZERO)


def _any(x, axis=-1):
    """Bool any-reduce in the int32 domain.  JAX's Mosaic lowering proxies
    ``reduce_or`` through float literals (``jnp.where(b, 1.0, 0.0)`` +
    ``maximumf``), which become unsupported f64 under jax_enable_x64; an
    int32 max-reduce lowers natively (MAXSI)."""
    return jnp.max(x.astype(jnp.int32), axis=axis) > 0


def _all(x, axis=-1):
    """Bool all-reduce in the int32 domain (see :func:`_any`)."""
    return jnp.min(x.astype(jnp.int32), axis=axis) > 0


def _nonempty(clock):
    return _any(clock != ZERO)


def _rank_select(keys, live, payload_ids, payload_clocks, cap):
    """Pack live slots in ascending-``keys`` order into ``cap`` output slots.

    ``keys`` must be unique among live slots.  Returns
    ``(ids[T, cap], clocks[T, cap, A], overflow[T])``."""
    s = keys.shape[-1]
    rank = jnp.zeros(keys.shape, dtype=jnp.int32)
    for j in range(s):
        smaller = live & live[..., j : j + 1] & (keys[..., j : j + 1] < keys)
        rank = rank + smaller.astype(jnp.int32)
    # rank[j] = #live slots with key < key[j]  (only meaningful where live)
    out_ids = []
    out_clocks = []
    for k in range(cap):
        sel = live & (rank == k)  # [T, S], at most one hot
        out_ids.append(
            jnp.sum(jnp.where(sel, payload_ids + 1, 0), axis=-1, dtype=jnp.int32) - 1
        )
        out_clocks.append(
            jnp.max(jnp.where(_emask(sel), payload_clocks, ZERO), axis=-2)
        )
    ids = jnp.stack(out_ids, axis=-1)
    clocks = jnp.stack(out_clocks, axis=-2)
    overflow = jnp.sum(live, axis=-1, dtype=jnp.int32) > cap
    return ids, clocks, overflow


def _rank_select_slots(live, payload_ids, payload_clocks, cap):
    """Deferred-table pack: keep live slots in slot (first-occurrence)
    order — the specialization of :func:`_rank_select` for ``keys`` = the
    slot index, which is what the deferred compaction always uses.

    Everything is python-unrolled into 1-D ``[T]`` / 2-D ``[T, A]`` ops:
    the deferred concat axis is tiny (``2·d_cap``, typically 4), and
    Mosaic's vector layout inference CHECK-crashes
    (``array.h: limits[i] <= dim(i)``) on any ``[T, 1] → [T, s]``
    broadcast or ``axis=-2`` reduction over a minor axis smaller than the
    native tile — found by local AOT compile against a v5e topology (the
    member-table call is fine: its ``2·m_cap`` axis is tile-sized).  With
    slot-order keys the rank of slot ``j`` is just the running count of
    live slots before it, so no pairwise compare is needed at all."""
    s = live.shape[-1]
    run = jnp.zeros(live.shape[:-1], dtype=jnp.int32)
    rank = []
    for j in range(s):
        rank.append(run)
        run = run + live[..., j].astype(jnp.int32)
    out_ids = []
    out_clocks = []
    for k in range(cap):
        oid = jnp.full(live.shape[:-1], -1, dtype=jnp.int32)
        clk = jnp.full_like(payload_clocks[..., 0, :], ZERO)  # [T, A]
        for j in range(s):
            sel_j = live[..., j] & (rank[j] == k)  # [T], at most one hot over j
            oid = oid + jnp.where(sel_j, payload_ids[..., j] + 1, 0)
            clk = jnp.maximum(
                clk, jnp.where(_emask(sel_j), payload_clocks[..., j, :], ZERO)
            )
        out_ids.append(oid)
        out_clocks.append(clk)
    ids = jnp.stack(out_ids, axis=-1)
    clocks = jnp.stack(out_clocks, axis=-2)
    overflow = run > cap
    return ids, clocks, overflow


def _merge_tile(sa, sb, m_cap: int, d_cap: int):
    """Full pairwise merge of two tile states.

    A state is ``(clock[T,A], ids[T,M], dots[T,M,A], d_ids[T,D],
    d_clocks[T,D,A])``; output uses ``m_cap``/``d_cap`` slots."""
    ca, ids_a, dots_a, dida, dca = sa
    cb, ids_b, dots_b, didb, dcb = sb

    # --- member alignment + dot algebra (`orswot.rs:92-138`) ---
    e2_for_a, b_matched = _align_against(ids_a, dots_a, ids_b, dots_b)
    valid_a = ids_a != EMPTY
    valid_b = ids_b != EMPTY
    out_a = _merge_rule(
        dots_a, e2_for_a, valid_a & _nonempty(dots_a), valid_a & _nonempty(e2_for_a),
        valid_a, ca, cb,
    )
    # unmatched b members: the only-in-other rule (`orswot.rs:132-138`)
    b_only = valid_b & ~b_matched
    out_b = jnp.where(_emask(b_only), _sub(dots_b, ca[..., None, :]), ZERO)

    ids_cat = jnp.concatenate(
        [jnp.where(valid_a, ids_a, EMPTY), jnp.where(b_only, ids_b, EMPTY)], axis=-1
    )
    dots_cat = jnp.concatenate([out_a, out_b], axis=-2)  # [T, Ma+Mb, A]

    # --- deferred union + dedup, keep first (`orswot.rs:141-148`) ---
    d_ids = jnp.concatenate([dida, didb], axis=-1)  # [T, Da+Db]
    d_clocks = jnp.concatenate([dca, dcb], axis=-2)
    dn = d_ids.shape[-1]
    d_valid = d_ids != EMPTY
    # column-stack instead of .at[].set — see _align_against
    dup_cols = [jnp.zeros(d_ids.shape[:-1], dtype=bool)]
    for j in range(1, dn):
        dup_j = jnp.zeros(d_ids.shape[:-1], dtype=bool)
        for i in range(j):
            same = (
                d_valid[..., i]
                & d_valid[..., j]
                & (d_ids[..., i] == d_ids[..., j])
                & _all(d_clocks[..., i, :] == d_clocks[..., j, :])
            )
            dup_j = dup_j | same
        dup_cols.append(dup_j)
    is_dup = _bstack(dup_cols, axis=-1)
    d_live = d_valid & ~is_dup
    d_ids = jnp.where(d_live, d_ids, EMPTY)
    d_clocks = jnp.where(_emask(d_live), d_clocks, ZERO)

    # --- clock join (`orswot.rs:153`) then deferred replay (`:155`) ---
    clock = jnp.maximum(ca, cb)
    rm = jnp.full_like(dots_cat, ZERO)
    for k in range(dn):
        match = (ids_cat == d_ids[..., k : k + 1]) & d_live[..., k : k + 1]
        rm = jnp.maximum(
            rm, jnp.where(_emask(match), d_clocks[..., k : k + 1, :], ZERO)
        )
    new_dots = _sub(dots_cat, rm)
    live = _nonempty(new_dots) & (ids_cat != EMPTY)
    still_ahead = d_live & ~_all(d_clocks <= clock[..., None, :])

    # --- canonical compaction ---
    big = jnp.iinfo(jnp.int32).max
    m_keys = jnp.where(live, ids_cat, big)
    ids_out, dots_out, m_over = _rank_select(m_keys, live, ids_cat, new_dots, m_cap)
    dids_out, dclk_out, d_over = _rank_select_slots(
        still_ahead, d_ids, d_clocks, d_cap
    )
    return (clock, ids_out, dots_out, dids_out, dclk_out), _bstack(
        [m_over, d_over], axis=-1
    )


# ---------------------------------------------------------------------------
# pallas_call wrappers
# ---------------------------------------------------------------------------


def _check_dtypes(clock):
    if clock.dtype.itemsize > 4:
        raise TypeError(
            f"Pallas ORSWOT kernels need <=32-bit counters, got {clock.dtype}; "
            "use the jnp path (orswot_ops) for u64"
        )


def _to_kernel_dtype(state):
    """Bias-map the clock-valued planes to int32 for the kernel.

    ``state`` is the canonical 5-tuple ``(clock, ids, dots, d_ids,
    d_clocks)``; planes 0/2/4 carry counters and get the order-preserving
    ``x ^ 0x8000_0000`` bitcast (exact over the full uint32 range — the
    kernel only compares/maxes/selects counters), planes 1/3 are already
    int32 member ids."""
    clock, ids, dots, d_ids, d_clocks = state
    bias = lambda x: jax.lax.bitcast_convert_type(
        x.astype(jnp.uint32) ^ _BIAS, jnp.int32
    )
    return bias(clock), ids, bias(dots), d_ids, bias(d_clocks)


def _from_kernel_dtype(x, cdt):
    """Invert :func:`_to_kernel_dtype`'s bias on one counter plane."""
    return (jax.lax.bitcast_convert_type(x, jnp.uint32) ^ _BIAS).astype(cdt)


# Mosaic scoped-VMEM ceiling requested from the compiler.  v5e has 128 MiB
# of VMEM per core; leave headroom for the compiler's own buffers and the
# double-buffered HBM⇄VMEM pipeline of the input/output blocks.
_VMEM_LIMIT_BYTES = 96 * 1024 * 1024


def _tile_size(a, m, d, n_states=2, vmem_budget=48 * 1024 * 1024):
    """Largest power-of-two tile whose working set fits the VMEM budget.

    ``n_states`` is how many full states are live per tile object: 2 for a
    pairwise merge, R+1 for the fold (all R replica blocks plus the
    accumulator).  The temporaries term is calibrated against Mosaic's own
    scoped-stack accounting (local v5e AOT compile of the pairwise merge
    at a=16/m=8/d=2 reported 22.47 MiB for a 256-object tile ⇒ ~88 KiB
    per object ⇒ ~11 live ``[2m, a]`` planes per *survivor slot*): the
    unrolled rank-select keeps roughly one masked ``[2m, a]`` select live
    per output slot, and Mosaic stack-allocates them without reuse."""
    import os

    forced = os.environ.get("CRDT_PALLAS_TILE")
    if forced:
        # Read at TRACE time (like CRDT_MERGE_IMPL — jit caches are keyed
        # on shapes/dtypes only, so changing it after a first compile
        # keeps the old tile for same-shaped inputs; clear jit caches to
        # re-dispatch).  Bypasses the VMEM-budget model: the knob exists
        # for on-chip tile experiments where Mosaic's own scoped-vmem
        # error is the ground truth the model is calibrated against.
        try:
            t = int(forced)
        except ValueError:
            raise ValueError(
                f"CRDT_PALLAS_TILE={forced!r} is not an integer"
            ) from None
        if t < 8 or t & (t - 1):
            raise ValueError(
                f"CRDT_PALLAS_TILE={forced!r} must be a power of two >= 8"
            )
        return t
    state_bytes = 4 * (a + m + m * a + d + d * a)
    tmp_bytes = 4 * 11 * (2 * m) * m * a + 4 * 8 * d * a
    # the fold kernel unrolls n_states-1 sequential _merge_tile calls;
    # Mosaic reuses *some* dead stack slots across them, so the
    # temporaries term scales with the merge count but is capped
    # (calibration: pairwise merge, n_states=2, factor 1; local AOT
    # compiles of the fold bound the effective reuse)
    bytes_per_obj = n_states * state_bytes + min(max(1, n_states - 1), 4) * tmp_bytes
    t = 256
    while t > 8 and t * bytes_per_obj > vmem_budget:
        t //= 2
    if t * bytes_per_obj > vmem_budget:
        raise ValueError(
            f"ORSWOT working set ({t * bytes_per_obj} bytes at the minimum "
            f"tile of {t} objects, n_states={n_states}) exceeds the "
            f"{vmem_budget}-byte VMEM budget; use the jnp path "
            "(orswot_ops.merge) or a smaller fold width R"
        )
    return t


def _pad_to(x, t, axis=0, fill=0):
    n = x.shape[axis]
    pad = (-n) % t
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    # tile-alignment tail pad: the phantom rows are EMPTY-filled, sliced
    # back off after the pallas_call, and under a mesh each shard pads
    # its own slice — no real object ever crosses a shard boundary here
    return jnp.pad(x, widths, constant_values=fill)  # crdtlint: disable=SC01 — per-shard tile-alignment pad, sliced off after


_ZERO = np.int32(0)  # index-map constants must be 32-bit: under
# jax_enable_x64 a literal ``0`` traces as an int64 scalar, and Mosaic has
# no 64-bit support (the int64→int32 truncation recurses forever in its
# convert helper)


def _state_specs(t, shapes, batch_axes=1):
    """BlockSpecs blocking the leading object axis into tiles of ``t``."""
    specs = []
    for shp in shapes:
        block = (t,) + shp[batch_axes:]
        rest = len(shp) - batch_axes
        specs.append(pl.BlockSpec(block, lambda i, _r=rest: (i,) + (_ZERO,) * _r))
    return specs


def _interpret_default():
    return jax.default_backend() != "tpu"


def _gate_interpret(interpret: bool) -> None:
    """The "jax 0.4.x Pallas skew" version gate: interpret-mode kernel
    launches on a 0.4.x jax would recurse forever in Mosaic's
    int64→int32 truncation — raise the typed
    :class:`~crdt_tpu.error.UnsupportedBackendError` (with the
    remediation in its message) at the API boundary instead of failing
    deep in the compiler.  One predicate —
    :func:`crdt_tpu.config.pallas_mosaic_skew` — shared with the test
    harness's xfail gate (``tests/conftest.py``), so the gate and the
    expected-failure set can never drift.  Sits AFTER the dtype checks
    in every entry point: u64 rejection (a caller bug on any jax)
    outranks the version gate (an environment limit)."""
    if not interpret:
        return
    from ..config import pallas_mosaic_skew

    skew = pallas_mosaic_skew()
    if skew is not None:
        from ..error import UnsupportedBackendError

        raise UnsupportedBackendError(skew)


@observed_kernel("ops.pallas.merge")
@functools.partial(jax.jit, static_argnames=("m_cap", "d_cap", "interpret"))
def merge(
    clock_a, ids_a, dots_a, dids_a, dclocks_a,
    clock_b, ids_b, dots_b, dids_b, dclocks_b,
    m_cap: int, d_cap: int, interpret: bool | None = None,
):
    """Fused pairwise merge — drop-in for ``orswot_ops.merge`` (2-D batch
    ``[N, ...]`` states, uint32 counters).  Returns
    ``(clock, ids, dots, d_ids, d_clocks, overflow)``."""
    _check_dtypes(clock_a)
    _check_dtypes(clock_b)
    if interpret is None:
        interpret = _interpret_default()
    n, a = clock_a.shape
    m, d = ids_a.shape[-1], dids_a.shape[-1]
    t = _tile_size(a, m, d)
    sa = (clock_a, ids_a, dots_a, dids_a, dclocks_a)
    sb = (clock_b, ids_b, dots_b, dids_b, dclocks_b)
    sa = tuple(_pad_to(x, t, fill=EMPTY if x.dtype == jnp.int32 else 0) for x in sa)
    sb = tuple(_pad_to(x, t, fill=EMPTY if x.dtype == jnp.int32 else 0) for x in sb)
    sa, sb = _to_kernel_dtype(sa), _to_kernel_dtype(sb)
    n_pad = sa[0].shape[0]
    cdt = clock_a.dtype

    def kernel(ca, ia, da, dia, dca, cb, ib, db, dib, dcb, oc, oi, od, odi, odc, oover):
        out, over = _merge_tile(
            tuple(r[...] for r in (ca, ia, da, dia, dca)),
            tuple(r[...] for r in (cb, ib, db, dib, dcb)),
            m_cap, d_cap,
        )
        for ref, val in zip((oc, oi, od, odi, odc), out):
            ref[...] = val
        oover[...] = over.astype(jnp.int32)

    in_shapes = [x.shape for x in sa] * 2
    out_shape = (
        jax.ShapeDtypeStruct((n_pad, a), jnp.int32),
        jax.ShapeDtypeStruct((n_pad, m_cap), jnp.int32),
        jax.ShapeDtypeStruct((n_pad, m_cap, a), jnp.int32),
        jax.ShapeDtypeStruct((n_pad, d_cap), jnp.int32),
        jax.ShapeDtypeStruct((n_pad, d_cap, a), jnp.int32),
        jax.ShapeDtypeStruct((n_pad, 2), jnp.int32),
    )
    # the kernel must trace in 32-bit mode: under jax_enable_x64 every
    # Python-int literal (the `0`s in jnp.where etc.) becomes an i64[]
    # scalar operand, and Mosaic has no 64-bit support — its convert
    # helper recurses forever on the i64→i32 truncation
    _gate_interpret(interpret)
    with x64_disabled():
        out = pl.pallas_call(
            kernel,
            grid=(n_pad // t,),
            in_specs=_state_specs(t, in_shapes),
            out_specs=_state_specs(t, [s.shape for s in out_shape]),
            out_shape=out_shape,
            compiler_params=_compiler_params(
                vmem_limit_bytes=_VMEM_LIMIT_BYTES
            ),
            interpret=interpret,
        )(*sa, *sb)
    clock, ids, dots, dids, dclk, over = (x[:n] for x in out)
    return (
        _from_kernel_dtype(clock, cdt), ids, _from_kernel_dtype(dots, cdt),
        dids, _from_kernel_dtype(dclk, cdt), over.astype(bool),
    )


def pad_to_tile(state, m_cap: int, d_cap: int, n_states: int):
    """Pad ``[R, N, ...]`` stacked planes on the object axis to the fold's
    tile size, with the module's own fill policy (``EMPTY`` for id planes,
    0 for counter planes) — so callers can pay the padding copy ONCE
    outside a timed loop and :func:`fold_merge`'s internal `_pad_to`
    becomes a no-op.  Returns the padded 5-tuple."""
    a = state[0].shape[-1]
    m = state[1].shape[-1]
    d = state[3].shape[-1]
    t = _tile_size(a, m, d, n_states=n_states)
    return tuple(
        _pad_to(x, t, axis=1, fill=EMPTY if x.dtype == jnp.int32 else 0)
        for x in state
    )


def to_kernel_domain(state):
    """Public: map a canonical 5-tuple of ``[R, N, ...]`` planes into the
    kernel's biased-int32 domain (see :func:`_to_kernel_dtype`).  Pair
    with ``fold_merge(..., prebiased=True)`` to hoist the uint32↔int32
    conversion copies (~a full working set per call) out of a timed loop;
    XOR salting commutes with the bias, so salt chains work unchanged in
    this domain.  Rejects >32-bit counters like the in-band path (the
    bias cast would silently truncate them)."""
    _check_dtypes(state[0])
    return _to_kernel_dtype(state)


def from_kernel_domain(x, dtype):
    """Public inverse of :func:`to_kernel_domain` for one counter plane."""
    return _from_kernel_dtype(x, dtype)


@observed_kernel("ops.pallas.fold_merge")
@functools.partial(jax.jit, static_argnames=(
    "m_cap", "d_cap", "interpret", "plunger", "prebiased"))
def fold_merge(
    clock, ids, dots, dids, dclocks,
    m_cap: int, d_cap: int, interpret: bool | None = None, plunger: bool = True,
    prebiased: bool = False,
):
    """Anti-entropy fold: join ``R`` stacked replica fleets (arrays are
    ``[R, N, ...]``) into one ``[N, ...]`` state, entirely in VMEM.

    Left-folds replica ``r`` into the accumulator for ``r = 1..R-1`` and
    finishes with a defer-plunger self-merge
    (`/root/reference/test/orswot.rs:61-62`) so buffered removes flush —
    matching ``r`` sequential ``orswot_ops.merge`` calls bit-exactly, but
    with the accumulator never leaving the chip.

    ``prebiased=True``: the counter planes are already in the kernel's
    biased-int32 domain (:func:`to_kernel_domain`) and the outputs stay
    there — the entry/exit conversion copies drop out entirely (callers
    invert with :func:`from_kernel_domain` once, outside their loop)."""
    if interpret is None:
        interpret = _interpret_default()
    r, n, a = clock.shape
    m, d = ids.shape[-1], dids.shape[-1]
    # all R replica blocks plus the accumulator are live in VMEM per tile
    t = _tile_size(a, m, d, n_states=r + 1)
    state = (clock, ids, dots, dids, dclocks)
    if prebiased:
        if clock.dtype != jnp.int32:
            raise TypeError(
                f"prebiased fold expects int32 kernel-domain planes, got "
                f"{clock.dtype}; use to_kernel_domain() first"
            )
        cdt = None
        state = tuple(
            _pad_to(x, t, axis=1, fill=EMPTY if i in (1, 3) else ZERO)
            for i, x in enumerate(state)
        )
    else:
        _check_dtypes(clock)
        cdt = clock.dtype
        state = tuple(
            _pad_to(x, t, axis=1, fill=EMPTY if x.dtype == jnp.int32 else 0)
            for x in state
        )
        state = _to_kernel_dtype(state)
    n_pad = state[0].shape[1]

    def kernel(ca, ia, da, dia, dca, oc, oi, od, odi, odc, oover):
        refs = (ca, ia, da, dia, dca)
        acc = tuple(ref[0] for ref in refs)
        over_any = jnp.zeros((acc[0].shape[0], 2), dtype=bool)
        for rr in range(1, r):
            acc, over = _merge_tile(acc, tuple(ref[rr] for ref in refs), m_cap, d_cap)
            over_any = over_any | over
        if plunger:
            acc, over = _merge_tile(acc, acc, m_cap, d_cap)
            over_any = over_any | over
        for ref, val in zip((oc, oi, od, odi, odc), acc):
            ref[...] = val
        oover[...] = over_any.astype(jnp.int32)

    in_specs = []
    for x in state:
        rest = x.ndim - 2
        in_specs.append(
            pl.BlockSpec(
                (r, t) + x.shape[2:],
                lambda i, _r=rest: (_ZERO, i) + (_ZERO,) * _r,
            )
        )
    out_shape = (
        jax.ShapeDtypeStruct((n_pad, a), jnp.int32),
        jax.ShapeDtypeStruct((n_pad, m_cap), jnp.int32),
        jax.ShapeDtypeStruct((n_pad, m_cap, a), jnp.int32),
        jax.ShapeDtypeStruct((n_pad, d_cap), jnp.int32),
        jax.ShapeDtypeStruct((n_pad, d_cap, a), jnp.int32),
        jax.ShapeDtypeStruct((n_pad, 2), jnp.int32),
    )
    # 32-bit trace mode — see the matching comment in merge()
    _gate_interpret(interpret)
    with x64_disabled():
        out = pl.pallas_call(
            kernel,
            grid=(n_pad // t,),
            in_specs=in_specs,
            out_specs=_state_specs(t, [s.shape for s in out_shape]),
            out_shape=out_shape,
            compiler_params=_compiler_params(
                vmem_limit_bytes=_VMEM_LIMIT_BYTES
            ),
            interpret=interpret,
        )(*state)
    c, i, dts, di, dc, over = (x[:n] for x in out)
    if prebiased:
        return c, i, dts, di, dc, over.astype(bool)
    return (
        _from_kernel_dtype(c, cdt), i, _from_kernel_dtype(dts, cdt), di,
        _from_kernel_dtype(dc, cdt), over.astype(bool),
    )
