"""Batched ORSWOT kernels — the flagship merge (SURVEY.md §3.2, §7.3).

Dense per-object state (leading axes are free batch axes):

* ``clock   u64[..., A]``       — the set clock
* ``ids     int32[..., M]``     — interned member ids, ``-1`` = empty slot
* ``dots    u64[..., M, A]``    — per-member dot clocks (add-witnesses)
* ``d_ids   int32[..., D]``     — deferred-remove member ids, ``-1`` = empty
* ``d_clocks u64[..., D, A]``   — deferred-remove witnessing clocks

A member slot is live iff its id != -1; live members always carry non-empty
dot clocks (the reference never stores an entry with an empty clock —
`/root/reference/src/orswot.rs:132-138,205-210`).

``merge`` reproduces `/root/reference/src/orswot.rs:89-156` bit-exactly,
including the asymmetry: members only in *self* keep their **full** clock
when any dot is novel (`orswot.rs:94-103`), members only in *other* keep the
**subtracted** clock (`orswot.rs:132-138`).  The HashMap alignment of the
reference becomes an O(M²) masked broadcast match over the two member
tables — no hashing and no sorting on device (a single argsort remains in
the canonical ascending-id output compaction); for padded capacities
M ≤ 64 the quadratic match fuses into a few VPU passes and beats
sort+gather alignment ~2× at the BASELINE.md shapes.
"""

from __future__ import annotations

import jax.numpy as jnp

from . import clock_ops

EMPTY = -1
_SORT_MAX = jnp.iinfo(jnp.int32).max


# above this member capacity the O(M²·A) broadcast in the match alignment
# costs more than sort+gather (and its [..., M, M, A] masked-select
# intermediate stops fitting on chip — elastic regrowth can push M to 2^16)
_ALIGN_MATCH_MAX_M = 64


def _align(ids_a, dots_a, ids_b, dots_b):
    """Member-table alignment; static dispatch on M (shape-level, so each
    jit specialization compiles exactly one strategy)."""
    if ids_a.shape[-1] <= _ALIGN_MATCH_MAX_M:
        return _align_match(ids_a, dots_a, ids_b, dots_b)
    return _align_sorted(ids_a, dots_a, ids_b, dots_b)


def _align_match(ids_a, dots_a, ids_b, dots_b):
    """Align the two member tables on member id — O(M²) masked match.

    For each a-slot, gather the matching b dot clock (0 if unmatched); each
    b-slot not consumed by a match survives as a b-only slot.  Returns
    ``(ids, e1, e2, valid)`` over 2M slots (a's M slots first, then b's,
    b-matched slots blanked) — the same contract the previous sort-based
    alignment produced, but without the 2M argsort: the broadcast compare +
    masked-max reduce fuses into a handful of VPU passes and measures
    1.6-2.4× faster than sort+gather at the BASELINE.md shapes (M ≤ 32)
    on both CPU and TPU backends.
    """
    valid_a = ids_a != EMPTY
    valid_b = ids_b != EMPTY
    # [..., Ma, Mb]: a-slot i matches b-slot j (ids unique within a side)
    match = valid_a[..., :, None] & (ids_a[..., :, None] == ids_b[..., None, :])
    e2_for_a = jnp.max(
        jnp.where(match[..., None], dots_b[..., None, :, :], 0), axis=-2
    )
    b_matched = jnp.any(match, axis=-2)

    b_only = valid_b & ~b_matched
    out_ids = jnp.concatenate(
        [jnp.where(valid_a, ids_a, EMPTY), jnp.where(b_only, ids_b, EMPTY)], axis=-1
    )
    e1 = jnp.concatenate([dots_a, jnp.zeros_like(dots_b)], axis=-2)
    e2 = jnp.concatenate(
        [e2_for_a, jnp.where(b_only[..., None], dots_b, 0)], axis=-2
    )
    e1 = jnp.where((out_ids != EMPTY)[..., None], e1, 0)
    valid = out_ids != EMPTY
    return out_ids, e1, e2, valid


def _align_sorted(ids_a, dots_a, ids_b, dots_b):
    """Sort+gather alignment — O(M log M), used above
    ``_ALIGN_MATCH_MAX_M`` where the quadratic match's ``[..., M, M, A]``
    intermediate would dominate.  Concatenate both tables, sort by member
    id, and match adjacent duplicates (runs have length ≤ 2 since ids are
    unique within each side).  Same output contract as ``_align_match`` up
    to slot order, which ``compact_by_id`` canonicalizes anyway."""
    ids_cat = jnp.concatenate([ids_a, ids_b], axis=-1)  # [..., 2M]
    dots_cat = jnp.concatenate([dots_a, dots_b], axis=-2)  # [..., 2M, A]
    side = jnp.concatenate(
        [jnp.zeros_like(ids_a), jnp.ones_like(ids_b)], axis=-1
    )  # 0 = self, 1 = other

    key = jnp.where(ids_cat == EMPTY, _SORT_MAX, ids_cat)
    order = jnp.argsort(key, axis=-1, stable=True)
    s_ids = jnp.take_along_axis(ids_cat, order, axis=-1)
    s_dots = jnp.take_along_axis(dots_cat, order[..., None], axis=-2)
    s_side = jnp.take_along_axis(side, order, axis=-1)

    valid = s_ids != EMPTY
    nxt_same = jnp.concatenate(
        [(s_ids[..., 1:] == s_ids[..., :-1]) & valid[..., 1:],
         jnp.zeros_like(valid[..., :1])],
        axis=-1,
    )
    prv_same = jnp.concatenate(
        [jnp.zeros_like(valid[..., :1]),
         (s_ids[..., 1:] == s_ids[..., :-1]) & valid[..., :-1]],
        axis=-1,
    )
    first = valid & ~prv_same

    from_a = jnp.where((s_side == 0)[..., None], s_dots, 0)
    from_b = jnp.where((s_side == 1)[..., None], s_dots, 0)
    nxt = lambda x: jnp.concatenate([x[..., 1:, :], jnp.zeros_like(x[..., :1, :])], axis=-2)
    take_nxt = nxt_same[..., None]
    e1 = jnp.maximum(from_a, jnp.where(take_nxt, nxt(from_a), 0))
    e2 = jnp.maximum(from_b, jnp.where(take_nxt, nxt(from_b), 0))
    out_ids = jnp.where(first, s_ids, EMPTY)
    return out_ids, e1, e2, first


def _merge_aligned(e1, e2, present1, present2, self_clock, other_clock):
    """The per-member dot-algebra rule (`orswot.rs:92-138`), elementwise
    over the actor axis.  ``e1``/``e2``: ``[..., S, A]``; clocks ``[..., A]``."""
    sc = self_clock[..., None, :]
    oc = other_clock[..., None, :]

    # present in both (`orswot.rs:105-129`)
    common = clock_ops.intersection(e1, e2)
    c1 = clock_ops.subtract(clock_ops.subtract(e1, common), oc)
    c2 = clock_ops.subtract(clock_ops.subtract(e2, common), sc)
    out_both = jnp.maximum(common, jnp.maximum(c1, c2))

    # only in self (`orswot.rs:94-103`): keep FULL clock iff not dominated
    keep1 = ~clock_ops.leq(e1, oc)  # [..., S]
    out_only1 = jnp.where(keep1[..., None], e1, 0)

    # only in other (`orswot.rs:132-138`): keep the SUBTRACTED clock
    out_only2 = clock_ops.subtract(e2, sc)

    both = (present1 & present2)[..., None]
    only1 = (present1 & ~present2)[..., None]
    out = jnp.where(both, out_both, jnp.where(only1, out_only1, out_only2))
    return jnp.where((present1 | present2)[..., None], out, 0)


def _dedup_deferred(d_ids, d_clocks):
    """Drop exact (member, clock) duplicate rows, keeping the first.

    The reference's deferred map is ``{clock: {members}}``
    (`orswot.rs:29`) — pairs are unique by construction; after
    concatenating two tables we restore that invariant.  O(D²) pairwise
    compare — D is small."""
    same_member = d_ids[..., :, None] == d_ids[..., None, :]  # [..., D, D]
    same_clock = clock_ops.eq(d_clocks[..., :, None, :], d_clocks[..., None, :, :])
    valid = d_ids != EMPTY
    dup_pair = same_member & same_clock & valid[..., :, None] & valid[..., None, :]
    d = d_ids.shape[-1]
    earlier = jnp.tril(jnp.ones((d, d), dtype=bool), k=-1)
    is_dup = jnp.any(dup_pair & earlier, axis=-1)
    keep = valid & ~is_dup
    return jnp.where(keep, d_ids, EMPTY), jnp.where(keep[..., None], d_clocks, 0)


def _apply_deferred(clock, ids, dots, d_ids, d_clocks):
    """Replay buffered removes (`orswot.rs:195-243`), single pass.

    For each member, subtract the join of all matching deferred clocks
    (sequential subtracts compose into subtract-by-max); drop emptied
    members; retain only deferred rows still ahead of the set clock."""
    d_valid = d_ids != EMPTY
    match = ids[..., :, None] == jnp.where(d_valid, d_ids, EMPTY - 1)[..., None, :]
    # [..., M, A]: per-member join of matching deferred clocks
    rm = jnp.max(
        jnp.where(match[..., None], d_clocks[..., None, :, :], 0), axis=-2
    ) if d_ids.shape[-1] > 0 else jnp.zeros_like(dots)
    new_dots = clock_ops.subtract(dots, rm)
    live = ~clock_ops.is_empty(new_dots) & (ids != EMPTY)
    new_ids = jnp.where(live, ids, EMPTY)
    new_dots = jnp.where(live[..., None], new_dots, 0)

    # keep deferred rows whose clock is still not covered (`orswot.rs:197`)
    still_ahead = ~clock_ops.leq(d_clocks, clock[..., None, :]) & d_valid
    out_d_ids = jnp.where(still_ahead, d_ids, EMPTY)
    out_d_clocks = jnp.where(still_ahead[..., None], d_clocks, 0)
    return new_ids, new_dots, out_d_ids, out_d_clocks


def compact(ids, payload, cap):
    """Pack live slots first (original slot order) and truncate to ``cap``.

    ``payload`` has one extra trailing axis (the actor axis).  Returns
    ``(ids, payload, overflow)``."""
    live = ids != EMPTY
    order = jnp.argsort(~live, axis=-1, stable=True)
    ids = jnp.take_along_axis(ids, order, axis=-1)[..., :cap]
    payload = jnp.take_along_axis(payload, order[..., None], axis=-2)[..., :cap, :]
    overflow = jnp.sum(live, axis=-1) > cap
    return ids, payload, overflow


def compact_by_id(ids, payload, cap):
    """Pack live slots in ascending member-id order and truncate to ``cap``
    — the canonical member-table order every engine emits (C++ mirrors it,
    `crdt_core.cpp` ORSWOT merge; Pallas restores it by rank selection)."""
    live = ids != EMPTY
    key = jnp.where(live, ids, _SORT_MAX)
    order = jnp.argsort(key, axis=-1, stable=True)
    ids = jnp.take_along_axis(ids, order, axis=-1)[..., :cap]
    payload = jnp.take_along_axis(payload, order[..., None], axis=-2)[..., :cap, :]
    overflow = jnp.sum(live, axis=-1) > cap
    return ids, payload, overflow


def merge(
    clock_a, ids_a, dots_a, dids_a, dclocks_a,
    clock_b, ids_b, dots_b, dids_b, dclocks_b,
    m_cap: int, d_cap: int,
):
    """Full pairwise ORSWOT merge (`orswot.rs:89-156`).

    Returns ``(clock, ids, dots, d_ids, d_clocks, overflow)``; overflow is
    ``bool[..., 2]`` — ``[..., 0]`` set where survivors exceed ``m_cap``,
    ``[..., 1]`` where deferred rows exceed ``d_cap`` (host raises a
    :class:`~crdt_tpu.error.CapacityOverflowError` naming the axis —
    capacity is the static-shape concession, and elastic recovery grows
    only the overflowed axis).
    """
    ids, e1, e2, valid = _align(ids_a, dots_a, ids_b, dots_b)
    p1 = ~clock_ops.is_empty(e1) & valid
    p2 = ~clock_ops.is_empty(e2) & valid
    out_dots = _merge_aligned(e1, e2, p1, p2, clock_a, clock_b)
    survive = ~clock_ops.is_empty(out_dots)
    ids = jnp.where(survive, ids, EMPTY)
    out_dots = jnp.where(survive[..., None], out_dots, 0)

    # union + dedup the deferred tables (`orswot.rs:141-148`)
    d_ids = jnp.concatenate([dids_a, dids_b], axis=-1)
    d_clocks = jnp.concatenate([dclocks_a, dclocks_b], axis=-2)
    d_ids, d_clocks = _dedup_deferred(d_ids, d_clocks)

    # clock join (`orswot.rs:153`), then replay deferred (`orswot.rs:155`)
    clock = clock_ops.merge(clock_a, clock_b)
    ids, out_dots, d_ids, d_clocks = _apply_deferred(clock, ids, out_dots, d_ids, d_clocks)

    ids, out_dots, m_over = compact_by_id(ids, out_dots, m_cap)
    d_ids, d_clocks, d_over = compact(d_ids, d_clocks, d_cap)
    return clock, ids, out_dots, d_ids, d_clocks, jnp.stack([m_over, d_over], axis=-1)


def apply_add(clock, ids, dots, dids, dclocks, actor_idx, counter, member_id):
    """Batched ``Op::Add`` (`orswot.rs:66-79`): one add per object.

    Returns updated state + overflow flag (no free member slot)."""
    seen = jnp.take_along_axis(clock, actor_idx[..., None], axis=-1)[..., 0] >= counter

    existing = ids == member_id[..., None]  # [..., M]
    has_slot = jnp.any(existing, axis=-1)
    free = ids == EMPTY
    has_free = jnp.any(free, axis=-1)
    slot = jnp.where(
        has_slot, jnp.argmax(existing, axis=-1), jnp.argmax(free, axis=-1)
    )
    overflow = ~seen & ~has_slot & ~has_free

    do = (~seen & (has_slot | has_free))[..., None]
    onehot = jnp.arange(ids.shape[-1]) == slot[..., None]
    new_ids = jnp.where(do & onehot, member_id[..., None], ids)
    # witness the dot on the member clock and the set clock
    dot_update = (do & onehot)[..., None] & (
        jnp.arange(dots.shape[-1]) == actor_idx[..., None, None]
    )
    new_dots = jnp.where(dot_update, jnp.maximum(dots, counter[..., None, None]), dots)
    new_clock = jnp.where(
        do & (jnp.arange(clock.shape[-1]) == actor_idx[..., None]),
        jnp.maximum(clock, counter[..., None]),
        clock,
    )
    new_ids2, new_dots2, d_ids, d_clocks = _apply_deferred(
        new_clock, new_ids, new_dots, dids, dclocks
    )
    return new_clock, new_ids2, new_dots2, d_ids, d_clocks, overflow


def apply_remove(clock, ids, dots, dids, dclocks, rm_clock, member_id):
    """Batched ``Op::Rm`` → ``apply_remove`` (`orswot.rs:195-211`).

    Defers when the remove clock is ahead of the set clock, and always
    subtracts the remove clock from the member's dots.  Returns updated
    state + overflow flag (deferred table full)."""
    ahead = ~clock_ops.leq(rm_clock, clock)  # [...]

    # dedup: an identical (member, clock) row may already be buffered
    d_valid = dids != EMPTY
    same = (dids == member_id[..., None]) & clock_ops.eq(
        dclocks, rm_clock[..., None, :]
    ) & d_valid
    already = jnp.any(same, axis=-1)
    want_defer = ahead & ~already
    free = ~d_valid
    has_free = jnp.any(free, axis=-1)
    slot = jnp.argmax(free, axis=-1)
    overflow = want_defer & ~has_free
    do = (want_defer & has_free)[..., None]
    onehot = jnp.arange(dids.shape[-1]) == slot[..., None]
    new_dids = jnp.where(do & onehot, member_id[..., None], dids)
    new_dclocks = jnp.where((do & onehot)[..., None], rm_clock[..., None, :], dclocks)

    # subtract the remove clock from the member's dots (`orswot.rs:205-210`)
    target = ids == member_id[..., None]
    sub = clock_ops.subtract(dots, rm_clock[..., None, :])
    new_dots = jnp.where(target[..., None], sub, dots)
    live = ~clock_ops.is_empty(new_dots) & (ids != EMPTY)
    new_ids = jnp.where(live, ids, EMPTY)
    new_dots = jnp.where(live[..., None], new_dots, 0)
    return clock, new_ids, new_dots, new_dids, new_dclocks, overflow


def contains(ids, member_id):
    """Membership bitmap (`orswot.rs:214-224`)."""
    return jnp.any(ids == member_id[..., None], axis=-1)


def member_mask(ids):
    """Live-member mask — ``value()`` as a bitmap over slots."""
    return ids != EMPTY
