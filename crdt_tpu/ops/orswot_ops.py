"""Batched ORSWOT kernels — the flagship merge (SURVEY.md §3.2, §7.3).

Dense per-object state (leading axes are free batch axes):

* ``clock   u64[..., A]``       — the set clock
* ``ids     int32[..., M]``     — interned member ids, ``-1`` = empty slot
* ``dots    u64[..., M, A]``    — per-member dot clocks (add-witnesses)
* ``d_ids   int32[..., D]``     — deferred-remove member ids, ``-1`` = empty
* ``d_clocks u64[..., D, A]``   — deferred-remove witnessing clocks

A member slot is live iff its id != -1; live members always carry non-empty
dot clocks (the reference never stores an entry with an empty clock —
`/root/reference/src/orswot.rs:132-138,205-210`).

``merge`` reproduces `/root/reference/src/orswot.rs:89-156` bit-exactly,
including the asymmetry: members only in *self* keep their **full** clock
when any dot is novel (`orswot.rs:94-103`), members only in *other* keep the
**subtracted** clock (`orswot.rs:132-138`).  The HashMap alignment of the
reference becomes a boolean O(M²) member-id match (the actor axis never
enters the quadratic term) for padded capacities M ≤ 64, and sort+gather
alignment above that.

Narrow-table merges dispatch on ``lax.cond(any deferred row exists)``:
the deferred-free fast path decides each slot's survival with
OR-reductions over the actor axis, rank-selects the winning ``m_cap``
member ids with a counting-rank sort (``_stable_order`` — O(S²) bool
compares + a one-hot-sum inversion, far cheaper than a comparison sort at
slot counts ≤ 128), and computes the dot algebra only for the selected
slots; the
2M-wide merged table of the classic pipeline is never materialized.
Deferred-bearing batches take the full-width pipeline with dedup + replay.
See `reports/ORSWOT_PROFILE.md` for the measured effect (5.9× on the
BASELINE.md config-4 shapes).
"""

from __future__ import annotations

import jax.numpy as jnp

from . import clock_ops
from ..config import MERGE_IMPLS

EMPTY = -1
_SORT_MAX = jnp.iinfo(jnp.int32).max


# above this member capacity the O(M²) boolean match matrix costs more
# than sort+gather alignment (elastic regrowth can push M to 2^16, where
# the quadratic term would dominate even without the actor axis)
_ALIGN_MATCH_MAX_M = 64


def _align_sorted(ids_a, dots_a, ids_b, dots_b):
    """Sort+gather alignment — O(M log M), used above
    ``_ALIGN_MATCH_MAX_M`` where the quadratic match matrix would
    dominate.  Concatenate both tables, sort by member id, and match
    adjacent duplicates (runs have length ≤ 2 since ids are unique within
    each side).  Returns ``(ids, e1, e2, valid)`` over the 2M slots in
    sorted order, which ``compact_by_id`` canonicalizes anyway."""
    ids_cat = jnp.concatenate([ids_a, ids_b], axis=-1)  # [..., 2M]
    dots_cat = jnp.concatenate([dots_a, dots_b], axis=-2)  # [..., 2M, A]
    side = jnp.concatenate(
        [jnp.zeros_like(ids_a), jnp.ones_like(ids_b)], axis=-1
    )  # 0 = self, 1 = other

    key = jnp.where(ids_cat == EMPTY, _SORT_MAX, ids_cat)
    order = jnp.argsort(key, axis=-1, stable=True)
    s_ids = jnp.take_along_axis(ids_cat, order, axis=-1)
    s_dots = jnp.take_along_axis(dots_cat, order[..., None], axis=-2)
    s_side = jnp.take_along_axis(side, order, axis=-1)

    valid = s_ids != EMPTY
    nxt_same = jnp.concatenate(
        [(s_ids[..., 1:] == s_ids[..., :-1]) & valid[..., 1:],
         jnp.zeros_like(valid[..., :1])],
        axis=-1,
    )
    prv_same = jnp.concatenate(
        [jnp.zeros_like(valid[..., :1]),
         (s_ids[..., 1:] == s_ids[..., :-1]) & valid[..., :-1]],
        axis=-1,
    )
    first = valid & ~prv_same

    from_a = jnp.where((s_side == 0)[..., None], s_dots, 0)
    from_b = jnp.where((s_side == 1)[..., None], s_dots, 0)
    nxt = lambda x: jnp.concatenate([x[..., 1:, :], jnp.zeros_like(x[..., :1, :])], axis=-2)
    take_nxt = nxt_same[..., None]
    e1 = jnp.maximum(from_a, jnp.where(take_nxt, nxt(from_a), 0))
    e2 = jnp.maximum(from_b, jnp.where(take_nxt, nxt(from_b), 0))
    out_ids = jnp.where(first, s_ids, EMPTY)
    return out_ids, e1, e2, first


def _merge_aligned(e1, e2, present1, present2, self_clock, other_clock):
    """The per-member dot-algebra rule (`orswot.rs:92-138`), elementwise
    over the actor axis.  ``e1``/``e2``: ``[..., S, A]``; clocks ``[..., A]``."""
    sc = self_clock[..., None, :]
    oc = other_clock[..., None, :]

    # present in both (`orswot.rs:105-129`)
    common = clock_ops.intersection(e1, e2)
    c1 = clock_ops.subtract(clock_ops.subtract(e1, common), oc)
    c2 = clock_ops.subtract(clock_ops.subtract(e2, common), sc)
    out_both = jnp.maximum(common, jnp.maximum(c1, c2))

    # only in self (`orswot.rs:94-103`): keep FULL clock iff not dominated
    keep1 = ~clock_ops.leq(e1, oc)  # [..., S]
    out_only1 = jnp.where(keep1[..., None], e1, 0)

    # only in other (`orswot.rs:132-138`): keep the SUBTRACTED clock
    out_only2 = clock_ops.subtract(e2, sc)

    both = (present1 & present2)[..., None]
    only1 = (present1 & ~present2)[..., None]
    out = jnp.where(both, out_both, jnp.where(only1, out_only1, out_only2))
    return jnp.where((present1 | present2)[..., None], out, 0)


def _dedup_deferred(d_ids, d_clocks):
    """Drop exact (member, clock) duplicate rows, keeping the first.

    The reference's deferred map is ``{clock: {members}}``
    (`orswot.rs:29`) — pairs are unique by construction; after
    concatenating two tables we restore that invariant.  O(D²) pairwise
    compare — D is small."""
    same_member = d_ids[..., :, None] == d_ids[..., None, :]  # [..., D, D]
    same_clock = clock_ops.eq(d_clocks[..., :, None, :], d_clocks[..., None, :, :])
    valid = d_ids != EMPTY
    dup_pair = same_member & same_clock & valid[..., :, None] & valid[..., None, :]
    d = d_ids.shape[-1]
    earlier = jnp.tril(jnp.ones((d, d), dtype=bool), k=-1)
    is_dup = jnp.any(dup_pair & earlier, axis=-1)
    keep = valid & ~is_dup
    return jnp.where(keep, d_ids, EMPTY), jnp.where(keep[..., None], d_clocks, 0)


def _apply_deferred(clock, ids, dots, d_ids, d_clocks):
    """Replay buffered removes (`orswot.rs:195-243`), single pass.

    For each member, subtract the join of all matching deferred clocks
    (sequential subtracts compose into subtract-by-max); drop emptied
    members; retain only deferred rows still ahead of the set clock.

    The member×deferred cross product makes this the most bandwidth-heavy
    stage, which is why ``merge`` only enters it when a deferred row
    exists in the batch at all."""
    d_valid = d_ids != EMPTY
    match = ids[..., :, None] == jnp.where(d_valid, d_ids, EMPTY - 1)[..., None, :]
    # [..., M, A]: per-member join of matching deferred clocks
    rm = jnp.max(
        jnp.where(match[..., None], d_clocks[..., None, :, :], 0), axis=-2
    ) if d_ids.shape[-1] > 0 else jnp.zeros_like(dots)
    new_dots = clock_ops.subtract(dots, rm)
    live = ~clock_ops.is_empty(new_dots) & (ids != EMPTY)
    new_ids = jnp.where(live, ids, EMPTY)
    new_dots = jnp.where(live[..., None], new_dots, 0)

    # keep deferred rows whose clock is still not covered (`orswot.rs:197`)
    still_ahead = ~clock_ops.leq(d_clocks, clock[..., None, :]) & d_valid
    out_d_ids = jnp.where(still_ahead, d_ids, EMPTY)
    out_d_clocks = jnp.where(still_ahead[..., None], d_clocks, 0)
    return new_ids, new_dots, out_d_ids, out_d_clocks


# counting-rank sort is O(S²) bools per object; above this slot count the
# quadratic term loses to XLA's comparison sort
_RANK_SORT_MAX_S = 128


def _scatterless_default():
    """Whether to invert the rank permutation without a scatter.

    ``put_along_axis`` lowers to an XLA scatter; the dense one-hot-sum
    inversion reuses the ``[..., S, S]`` bool the counting rank already
    materialized and measured faster on BOTH backends with the r2
    rank-select kernel — CPU: 1.21x at config-4 (87 vs 105 ms), 1.26x at
    north-star fold shapes (4.50 vs 5.69 s/chunk-fold); TPU: scatters
    are served by XLA:TPU's generic scatter path, far slower than dense
    reductions at these tiny slot counts.  (The original CPU-prefers-
    scatter finding predated the rank-select rewrite.)
    ``CRDT_SCATTERLESS=0/1`` forces a path for A/B measurements
    (`scripts/tpu_experiments.py`)."""
    import os

    force = os.environ.get("CRDT_SCATTERLESS")
    if force is not None:
        return force == "1"
    return True


def _stable_order(key):
    """Permutation that stably sorts ``key`` ascending along the last axis.

    For the small static slot counts of the member/deferred tables this is
    a counting rank (``rank[i]`` = number of slots ordered before slot i,
    ties broken by slot index) — a handful of fused elementwise passes
    over an ``[..., S, S]`` bool, which beats XLA's generic comparison
    sort by a wide margin at S ≤ ~128.  The rank is inverted with a
    one-hot masked sum by default on every backend (a scatter under
    ``CRDT_SCATTERLESS=0`` — see :func:`_scatterless_default` for the
    measurements).  Larger S falls back to ``argsort``."""
    s = key.shape[-1]
    if s > _RANK_SORT_MAX_S:
        return jnp.argsort(key, axis=-1, stable=True)
    idx = jnp.arange(s, dtype=jnp.int32)
    ki = key[..., :, None]
    kj = key[..., None, :]
    before = (kj < ki) | ((kj == ki) & (idx[None, :] < idx[:, None]))
    rank = jnp.sum(before, axis=-1).astype(jnp.int32)  # position of slot i
    if _scatterless_default():
        # out[k] = i with rank[i] == k, as a one-hot masked sum — reuses
        # the [..., S, S] shape already materialized for `before`, and
        # avoids an XLA scatter entirely
        onehot = rank[..., None, :] == idx[:, None]  # [..., k, i]
        return jnp.sum(jnp.where(onehot, idx, 0), axis=-1, dtype=jnp.int32)
    return jnp.put_along_axis(
        jnp.zeros(rank.shape, jnp.int32),
        rank,
        jnp.broadcast_to(idx, rank.shape),
        axis=-1,
        inplace=False,
    )


def compact(ids, payload, cap):
    """Pack live slots first (original slot order) and truncate to ``cap``.

    ``payload`` has one extra trailing axis (the actor axis).  Returns
    ``(ids, payload, overflow)``."""
    live = ids != EMPTY
    order = _stable_order((~live).astype(jnp.int32))
    ids = jnp.take_along_axis(ids, order, axis=-1)[..., :cap]
    payload = jnp.take_along_axis(payload, order[..., None], axis=-2)[..., :cap, :]
    overflow = jnp.sum(live, axis=-1) > cap
    return ids, payload, overflow


def compact_by_id(ids, payload, cap):
    """Pack live slots in ascending member-id order and truncate to ``cap``
    — the canonical member-table order every engine emits (C++ mirrors it,
    `crdt_core.cpp` ORSWOT merge; Pallas restores it by rank selection)."""
    live = ids != EMPTY
    key = jnp.where(live, ids, _SORT_MAX)
    order = _stable_order(key)
    ids = jnp.take_along_axis(ids, order, axis=-1)[..., :cap]
    payload = jnp.take_along_axis(payload, order[..., None], axis=-2)[..., :cap, :]
    overflow = jnp.sum(live, axis=-1) > cap
    return ids, payload, overflow


def resolve_merge_impl(impl: str | None = None) -> str:
    """Resolve which pairwise-merge implementation ``merge`` dispatches to.

    Implementations: ``rank`` (the rank-select pipeline below, CPU
    default), ``unrolled`` (gather/sort-free tile math,
    :mod:`crdt_tpu.ops.orswot_unrolled`; exact for uint32 counters only —
    bit-equal outside the conservative-overflow objects, see
    ``tests/test_orswot_unrolled.py``), or ``pallas``.

    ``pallas`` — ROUND-5 DECISION (VERDICT r4 item 4): for PAIRWISE
    merges it is an alias of ``unrolled``.  The fused pairwise kernel
    (:mod:`crdt_tpu.ops.orswot_pallas`) measured on-chip strictly worse
    than the jnp path (0.60M vs 3.17M merges/s, 2026-08-01 window —
    VPU-compute-bound at 8-object tiles), and a fused PAIRWISE merge
    cannot beat jnp on traffic anyway (both read 2 states and write 1);
    it stays importable for benches/tests only.  Where ``pallas`` DOES
    pay is the R-way FOLD — each replica state read once instead of the
    sequential fold's 3-states-per-merge — which :func:`fold_merge`
    dispatches to the union-aligned fused kernel
    (:mod:`crdt_tpu.ops.orswot_fold_aligned`).

    Precedence: an explicit non-``"auto"`` choice (the ``impl=`` argument
    to :func:`merge`, usually fed from ``CrdtConfig.merge_impl``) wins;
    otherwise the ``CRDT_MERGE_IMPL`` env var (a process-level override —
    set it before the first compile; jit caches key on shapes only, so
    flipping it later does not retrace already-compiled shapes); otherwise
    the backend default from the round-3 on-chip layout A/B
    (`reports/LAYOUT_AB_TPU.md`): ``unrolled`` on TPU (54.0 ms vs the
    rank path's 57.7 ms at config-4 shapes), ``rank`` elsewhere (the
    unrolled tile math trades extra dot-table reads for regularity —
    measured 17% slower on the memory-bound CPU backend).  A/B harnesses
    should pass ``impl=`` explicitly — each choice is a distinct Python
    call graph, so no cache clearing is needed."""
    import os

    import jax

    if impl is not None and impl != "auto":
        if impl not in MERGE_IMPLS:
            raise ValueError(
                f"merge impl {impl!r} (CrdtConfig.merge_impl / "
                f"CRDT_MERGE_IMPL) is not one of rank/unrolled/pallas"
            )
        return impl
    env = os.environ.get("CRDT_MERGE_IMPL")
    if env is not None:
        if env not in MERGE_IMPLS:
            raise ValueError(
                f"CRDT_MERGE_IMPL={env!r} is not one of rank/unrolled/pallas"
            )
        return env
    return "unrolled" if jax.default_backend() == "tpu" else "rank"


def merge(
    clock_a, ids_a, dots_a, dids_a, dclocks_a,
    clock_b, ids_b, dots_b, dids_b, dclocks_b,
    m_cap: int, d_cap: int, impl: str | None = None,
):
    """Full pairwise ORSWOT merge (`orswot.rs:89-156`).

    Returns ``(clock, ids, dots, d_ids, d_clocks, overflow)``; overflow is
    ``bool[..., 2]`` — ``[..., 0]`` set where survivors exceed ``m_cap``,
    ``[..., 1]`` where deferred rows exceed ``d_cap`` (host raises a
    :class:`~crdt_tpu.error.CapacityOverflowError` naming the axis —
    capacity is the static-shape concession, and elastic recovery grows
    only the overflowed axis).

    Narrow member tables dispatch on "any deferred row in the batch"
    (``lax.cond``): the deferred-free fast path — the common case — never
    materializes the 2M-wide merged table at all.  It decides survival
    with cheap reductions, rank-selects the ``m_cap`` winning slots, and
    computes the dot algebra only for those; deferred-bearing batches take
    the full-width pipeline.

    ``impl`` selects the implementation (see :func:`resolve_merge_impl`
    for choices and precedence); ``None``/``"auto"`` resolves the
    env-var/backend default.
    """
    impl = resolve_merge_impl(impl)
    if impl in ("unrolled", "pallas") and clock_a.dtype.itemsize > 4:
        # the TPU fast paths are exact for <=32-bit counters only; wider
        # batches silently taking the rank path cost default-config users
        # the measured speedup (VERDICT r3 weak #6) — say so, once per trace
        import warnings

        warnings.warn(
            f"orswot merge impl {impl!r} requires <=32-bit counters; this "
            f"{clock_a.dtype.name} batch falls back to the 'rank' path. "
            "Build the universe with CrdtConfig(counter_bits=32) (see "
            "CrdtConfig.tpu_default()) to stay on the TPU fast paths.",
            stacklevel=2,
        )
    if (
        impl in ("unrolled", "pallas")
        and clock_a.dtype.itemsize <= 4
        and ids_a.shape[-1] <= _ALIGN_MATCH_MAX_M
    ):
        # the tile math unrolls Python loops over the slot axes, so wide
        # member tables (elastic regrowth) stay on the rank path's
        # sort-aligned _merge_wide below; rank-polymorphic
        # (ellipsis-based tile math), so any batch shape dispatches.
        # impl == "pallas" is an alias of unrolled for PAIRWISE merges
        # (round-5 keep-or-kill: the fused pairwise kernel lost 5x
        # on-chip and is bench-only — see resolve_merge_impl); the fused
        # Pallas product arm is the R-way fold_merge below
        from . import orswot_unrolled

        return orswot_unrolled.merge_unrolled(
            clock_a, ids_a, dots_a, dids_a, dclocks_a,
            clock_b, ids_b, dots_b, dids_b, dclocks_b,
            m_cap, d_cap,
        )
    if ids_a.shape[-1] > _ALIGN_MATCH_MAX_M:
        return _merge_wide(
            clock_a, ids_a, dots_a, dids_a, dclocks_a,
            clock_b, ids_b, dots_b, dids_b, dclocks_b,
            m_cap, d_cap,
        )
    from jax import lax

    clock = clock_ops.merge(clock_a, clock_b)
    # the whole-batch cond dispatch reads every object, but both branches
    # compute the same lattice join — per-shard the predicate just picks
    # the shard's own fast path, so the fold is a dispatch hint, not data
    any_deferred = jnp.any(dids_a != EMPTY) | jnp.any(dids_b != EMPTY)  # crdtlint: disable=SC01 — fast-path dispatch, branches agree
    operands = (
        clock, clock_a, ids_a, dots_a, dids_a, dclocks_a,
        clock_b, ids_b, dots_b, dids_b, dclocks_b,
    )
    ids, out_dots, d_ids, d_clocks, over = lax.cond(
        any_deferred,
        lambda args: _merge_narrow_deferred(*args, m_cap, d_cap),
        lambda args: _merge_narrow_fast(*args, m_cap, d_cap),
        operands,
    )
    return clock, ids, out_dots, d_ids, d_clocks, over


def _member_match(ids_a, ids_b):
    """Boolean member alignment: match matrix reductions only (no clock
    data enters the quadratic term)."""
    valid_a = ids_a != EMPTY
    valid_b = ids_b != EMPTY
    match = valid_a[..., :, None] & (ids_a[..., :, None] == ids_b[..., None, :])
    a_matched = jnp.any(match, axis=-1)
    j_idx = jnp.argmax(match, axis=-1).astype(jnp.int32)
    b_only = valid_b & ~jnp.any(match, axis=-2)
    return valid_a, a_matched, j_idx, b_only


def _rank_select_merge(
    clock_a, ids_a, dots_a, clock_b, ids_b, dots_b, m_cap: int,
):
    """Shared merge core: survival reduces → rank-select → compute.

    Survival of every slot is decidable from OR-reductions over the actor
    axis (no merged clock is ever written), so the only ``[..., *, A]``
    arrays materialized are the gathers feeding the final ``m_cap``-slot
    algebra.  Returns ``(out_ids, out_dots, n_survivors)`` — the member
    table in canonical ascending-id order, pre-deferred-replay."""
    ma = ids_a.shape[-1]
    valid_a, a_matched, j_idx, b_only = _member_match(ids_a, ids_b)
    sc = clock_a[..., None, :]
    oc = clock_b[..., None, :]

    # per-(slot, actor) survival predicates, OR-reduced over actors:
    # matched  — the dot-algebra output has a non-zero lane
    #            (`orswot.rs:105-129`)
    # a-only   — some dot is novel wrt other's set clock (`orswot.rs:94-103`)
    # b-only   — some dot is novel wrt self's set clock  (`orswot.rs:132-138`)
    e2 = jnp.take_along_axis(dots_b, j_idx[..., None], axis=-2)
    same = dots_a == e2
    both_lane = (same & (dots_a > 0)) | (~same & ((dots_a > oc) | (e2 > sc)))
    a_novel = jnp.any(dots_a > oc, axis=-1)
    a_surv = valid_a & jnp.where(a_matched, jnp.any(both_lane, axis=-1), a_novel)
    b_surv = b_only & jnp.any(dots_b > sc, axis=-1)

    n_surv = jnp.sum(a_surv, axis=-1) + jnp.sum(b_surv, axis=-1)

    # rank-select the m_cap smallest surviving member ids (canonical
    # ascending-id order, same as compact_by_id)
    keys = jnp.concatenate(
        [jnp.where(a_surv, ids_a, _SORT_MAX), jnp.where(b_surv, ids_b, _SORT_MAX)],
        axis=-1,
    )
    sel = _stable_order(keys)[..., :m_cap]  # concat-space source slot
    out_ids_key = jnp.take_along_axis(keys, sel, axis=-1)
    live = out_ids_key != _SORT_MAX
    out_ids = jnp.where(live, out_ids_key, EMPTY)

    # gather algebra inputs for the selected slots only; the "other side"
    # clock is one combined gather from dots_b — the b-only slot's own
    # dots and the matched a-slot's counterpart live in the same table
    is_b = sel >= ma
    sel_a = jnp.where(is_b, 0, sel)
    src_a = jnp.take_along_axis(dots_a, sel_a[..., None], axis=-2)
    sel_matched = jnp.take_along_axis(a_matched, sel_a, axis=-1) & ~is_b
    j_sel = jnp.take_along_axis(j_idx, sel_a, axis=-1)
    j_comb = jnp.where(is_b, sel - ma, j_sel)
    src_other = jnp.take_along_axis(dots_b, j_comb[..., None], axis=-2)

    # dot algebra on [..., m_cap, A] (`orswot.rs:105-138`)
    common = clock_ops.intersection(src_a, src_other)
    c1 = clock_ops.subtract(clock_ops.subtract(src_a, common), oc)
    c2 = clock_ops.subtract(clock_ops.subtract(src_other, common), sc)
    out_both = jnp.maximum(common, jnp.maximum(c1, c2))
    out_a = jnp.where(sel_matched[..., None], out_both, src_a)
    out_dots = jnp.where(is_b[..., None], clock_ops.subtract(src_other, sc), out_a)
    out_dots = jnp.where(live[..., None], out_dots, 0)
    return out_ids, out_dots, n_surv


def _merge_narrow_fast(
    clock, clock_a, ids_a, dots_a, dids_a, dclocks_a,
    clock_b, ids_b, dots_b, dids_b, dclocks_b,
    m_cap: int, d_cap: int,
):
    """Deferred-free merge — the rank-select core alone.  Bit-exact with
    the deferred pipeline because replay over empty deferred tables is the
    identity; the output deferred tables are empty by construction of the
    dispatch."""
    out_ids, out_dots, n_surv = _rank_select_merge(
        clock_a, ids_a, dots_a, clock_b, ids_b, dots_b, m_cap
    )
    m_over = n_surv > m_cap
    d_shape = dids_a.shape[:-1] + (d_cap,)
    d_ids = jnp.full(d_shape, EMPTY, dids_a.dtype)
    d_clocks = jnp.zeros(d_shape + dclocks_a.shape[-1:], dclocks_a.dtype)
    d_over = jnp.zeros(m_over.shape, bool)
    return out_ids, out_dots, d_ids, d_clocks, jnp.stack([m_over, d_over], axis=-1)


def _merge_narrow_deferred(
    clock, clock_a, ids_a, dots_a, dids_a, dclocks_a,
    clock_b, ids_b, dots_b, dids_b, dclocks_b,
    m_cap: int, d_cap: int,
):
    """Merge for batches carrying deferred rows: the rank-select core,
    then union + dedup + replay of the deferred tables
    (`orswot.rs:141-155`) at ``m_cap`` width, then a repack of whatever
    the replay emptied.

    Replaying after compaction is exact whenever the survivor set fits
    ``m_cap``; when it does not, the member-overflow flag is already set
    (from the pre-replay survivor count — marginally more conservative
    than counting post-replay, in the rare case a replay would have freed
    enough slots) and the host discards the state and regrows, so the
    truncated replay is never observed."""
    out_ids, out_dots, n_surv = _rank_select_merge(
        clock_a, ids_a, dots_a, clock_b, ids_b, dots_b, m_cap
    )
    m_over = n_surv > m_cap

    # union + dedup the deferred tables (`orswot.rs:141-148`), replay
    # after the clock join (`orswot.rs:153-155`)
    d_ids = jnp.concatenate([dids_a, dids_b], axis=-1)
    d_clocks = jnp.concatenate([dclocks_a, dclocks_b], axis=-2)
    d_ids, d_clocks = _dedup_deferred(d_ids, d_clocks)
    out_ids, out_dots, d_ids, d_clocks = _apply_deferred(
        clock, out_ids, out_dots, d_ids, d_clocks
    )

    # repack slots the replay emptied (canonical ascending-id order is
    # preserved — subtraction never changes ids)
    out_ids, out_dots, _ = compact_by_id(out_ids, out_dots, m_cap)
    d_ids, d_clocks, d_over = compact(d_ids, d_clocks, d_cap)
    return out_ids, out_dots, d_ids, d_clocks, jnp.stack([m_over, d_over], axis=-1)


def _merge_wide(
    clock_a, ids_a, dots_a, dids_a, dclocks_a,
    clock_b, ids_b, dots_b, dids_b, dclocks_b,
    m_cap: int, d_cap: int,
):
    """Sort-aligned merge pipeline for member tables wider than
    ``_ALIGN_MATCH_MAX_M`` (same semantics, O(M log M) alignment)."""
    ids, e1, e2, valid = _align_sorted(ids_a, dots_a, ids_b, dots_b)
    p1 = ~clock_ops.is_empty(e1) & valid
    p2 = ~clock_ops.is_empty(e2) & valid
    out_dots = _merge_aligned(e1, e2, p1, p2, clock_a, clock_b)
    survive = ~clock_ops.is_empty(out_dots)
    ids = jnp.where(survive, ids, EMPTY)
    out_dots = jnp.where(survive[..., None], out_dots, 0)

    d_ids = jnp.concatenate([dids_a, dids_b], axis=-1)
    d_clocks = jnp.concatenate([dclocks_a, dclocks_b], axis=-2)
    d_ids, d_clocks = _dedup_deferred(d_ids, d_clocks)

    clock = clock_ops.merge(clock_a, clock_b)
    ids, out_dots, d_ids, d_clocks = _apply_deferred(clock, ids, out_dots, d_ids, d_clocks)

    ids, out_dots, m_over = compact_by_id(ids, out_dots, m_cap)
    d_ids, d_clocks, d_over = compact(d_ids, d_clocks, d_cap)
    return clock, ids, out_dots, d_ids, d_clocks, jnp.stack([m_over, d_over], axis=-1)


def fold_merge(
    clock, ids, dots, dids, dclocks, m_cap: int, d_cap: int,
    plunger: bool = True, impl: str | None = None, u_cap: int | None = None,
):
    """Left-fold ``R`` stacked replica fleets (arrays ``[R, N, ...]``)
    into one ``[N, ...]`` state, with the defer-plunger self-merge
    (`/root/reference/test/orswot.rs:45-62`) — the anti-entropy join.

    This is the level where the fused Pallas arm lives (round-5
    keep-or-kill decision, `PERF.md`): with ``impl="pallas"`` and
    eligible shapes (uint32 counters, ``[R, N, ...]`` rank-3 planes) the
    whole fold runs in one union-aligned kernel
    (:mod:`~crdt_tpu.ops.orswot_fold_aligned`) that reads each replica
    state exactly once — ``(R+1)/R`` states of HBM traffic per merge
    instead of the sequential fold's 3.  Overflow flagged by the kernel
    is conservative (see its module docstring); callers discard and
    regrow exactly as with the pairwise flags.  Other ``impl`` choices
    (or ineligible shapes) run the sequential pairwise fold.

    Returns ``(clock, ids, dots, d_ids, d_clocks, overflow)``."""
    resolved = resolve_merge_impl(impl)
    if (
        resolved == "pallas"
        and clock.dtype.itemsize <= 4
        and clock.ndim == 3
        and ids.shape[-1] <= _ALIGN_MATCH_MAX_M
    ):
        from . import orswot_fold_aligned

        return orswot_fold_aligned.fold_merge(
            clock, ids, dots, dids, dclocks, m_cap, d_cap,
            u_cap=u_cap, plunger=plunger,
        )
    return fold_merge_sequential(
        clock, ids, dots, dids, dclocks, m_cap, d_cap,
        plunger=plunger, impl=impl,
    )


def fold_merge_sequential(
    clock, ids, dots, dids, dclocks, m_cap: int, d_cap: int,
    plunger: bool = True, impl: str | None = None,
):
    """The canonical sequential left fold over stacked ``[R, N, ...]``
    planes, ORing capacity overflow across every pairwise merge — THE
    one place the canonical-order + overflow invariant lives: the fused
    :func:`fold_merge` dispatch, the collective join
    (`parallel/collective.py`), and the on-device anti-entropy fold all
    route through here."""
    state = (clock, ids, dots, dids, dclocks)
    acc = tuple(x[0] for x in state)
    over_acc = jnp.zeros(clock.shape[1:-1] + (2,), bool)
    for i in range(1, clock.shape[0]):
        out = merge(*acc, *(x[i] for x in state), m_cap, d_cap, impl=impl)
        acc, over_acc = out[:5], over_acc | out[5]
    if plunger:
        out = merge(*acc, *acc, m_cap, d_cap, impl=impl)
        acc, over_acc = out[:5], over_acc | out[5]
    return acc + (over_acc,)


def fold_merge_tree(
    clock, ids, dots, dids, dclocks, m_cap: int, d_cap: int,
    plunger: bool = True, impl: str | None = None,
):
    """Join ``R`` stacked replica fleets (arrays ``[R, N, ...]``) into one
    ``[N, ...]`` state by pairwise tree reduction.

    Same R-1 merges (plus an optional defer-plunger self-merge,
    `/root/reference/test/orswot.rs:61-62`) as the sequential left fold,
    but tree level ``l`` executes its ``R / 2**l`` pairwise merges as ONE
    batched :func:`merge` call over a ``[R/2**l, N, ...]`` leading axis —
    a log-depth dependency chain with maximal batch per launch, which is
    the shape accelerators want.

    Equivalence to the left fold: for deferred-free states the merge is
    a pure lattice join (`orswot.rs:89-156`) over a canonical encoding
    (ascending-id member order, pointwise-max clocks), so tree and left
    fold are **bit-identical**.  When causally-future removes are in
    flight, the reference's own semantics are fold-order-sensitive in
    the *dot tables*: ``apply_deferred`` (`orswot.rs:195-211,235-243`)
    subtracts the remove clock during every intermediate merge, so which
    dots it erases depends on which partner states have already been
    joined — the scalar engine reproduces exactly this (verified in
    ``tests/test_orswot.py::TestFoldMergeTree``).  ``value()``, the set
    clock, and the member table remain order-independent, which is the
    CRDT convergence guarantee; this function is bit-faithful to the
    scalar engine folding in the same tree order.

    Returns ``(clock, ids, dots, d_ids, d_clocks, overflow)`` with
    ``overflow`` OR-reduced over every merge in the tree.
    """
    state = (clock, ids, dots, dids, dclocks)
    r = clock.shape[0]
    over_acc = jnp.zeros(clock.shape[1:-1] + (2,), bool)
    while r > 1:
        half = r // 2
        lhs = tuple(x[0 : 2 * half : 2] for x in state)
        rhs = tuple(x[1 : 2 * half : 2] for x in state)
        out = merge(*lhs, *rhs, m_cap, d_cap, impl=impl)
        merged, over = out[:5], out[5]
        over_acc = over_acc | jnp.any(over, axis=0)
        if r % 2:
            # odd fleet carries through to the next level
            merged = tuple(
                jnp.concatenate([m, x[-1:]], axis=0)
                for m, x in zip(merged, state)
            )
        state = merged
        r = half + r % 2
    state = tuple(x[0] for x in state)
    if plunger:
        out = merge(*state, *state, m_cap, d_cap, impl=impl)
        state, over = out[:5], out[5]
        over_acc = over_acc | over
    return state + (over_acc,)


def apply_add(clock, ids, dots, dids, dclocks, actor_idx, counter, member_id):
    """Batched ``Op::Add`` (`orswot.rs:66-79`): one add per object.

    Returns updated state + overflow flag (no free member slot)."""
    seen = jnp.take_along_axis(clock, actor_idx[..., None], axis=-1)[..., 0] >= counter

    existing = ids == member_id[..., None]  # [..., M]
    has_slot = jnp.any(existing, axis=-1)
    free = ids == EMPTY
    has_free = jnp.any(free, axis=-1)
    slot = jnp.where(
        has_slot, jnp.argmax(existing, axis=-1), jnp.argmax(free, axis=-1)
    )
    overflow = ~seen & ~has_slot & ~has_free

    do = (~seen & (has_slot | has_free))[..., None]
    onehot = jnp.arange(ids.shape[-1]) == slot[..., None]
    new_ids = jnp.where(do & onehot, member_id[..., None], ids)
    # witness the dot on the member clock and the set clock
    dot_update = (do & onehot)[..., None] & (
        jnp.arange(dots.shape[-1]) == actor_idx[..., None, None]
    )
    new_dots = jnp.where(dot_update, jnp.maximum(dots, counter[..., None, None]), dots)
    new_clock = jnp.where(
        do & (jnp.arange(clock.shape[-1]) == actor_idx[..., None]),
        jnp.maximum(clock, counter[..., None]),
        clock,
    )
    new_ids2, new_dots2, d_ids, d_clocks = _apply_deferred(
        new_clock, new_ids, new_dots, dids, dclocks
    )
    return new_clock, new_ids2, new_dots2, d_ids, d_clocks, overflow


def apply_remove(clock, ids, dots, dids, dclocks, rm_clock, member_id):
    """Batched ``Op::Rm`` → ``apply_remove`` (`orswot.rs:195-211`).

    Defers when the remove clock is ahead of the set clock, and always
    subtracts the remove clock from the member's dots.  Returns updated
    state + overflow flag (deferred table full)."""
    ahead = ~clock_ops.leq(rm_clock, clock)  # [...]

    # dedup: an identical (member, clock) row may already be buffered
    d_valid = dids != EMPTY
    same = (dids == member_id[..., None]) & clock_ops.eq(
        dclocks, rm_clock[..., None, :]
    ) & d_valid
    already = jnp.any(same, axis=-1)
    want_defer = ahead & ~already
    free = ~d_valid
    has_free = jnp.any(free, axis=-1)
    slot = jnp.argmax(free, axis=-1)
    overflow = want_defer & ~has_free
    do = (want_defer & has_free)[..., None]
    onehot = jnp.arange(dids.shape[-1]) == slot[..., None]
    new_dids = jnp.where(do & onehot, member_id[..., None], dids)
    new_dclocks = jnp.where((do & onehot)[..., None], rm_clock[..., None, :], dclocks)

    # subtract the remove clock from the member's dots (`orswot.rs:205-210`)
    target = ids == member_id[..., None]
    sub = clock_ops.subtract(dots, rm_clock[..., None, :])
    new_dots = jnp.where(target[..., None], sub, dots)
    live = ~clock_ops.is_empty(new_dots) & (ids != EMPTY)
    new_ids = jnp.where(live, ids, EMPTY)
    new_dots = jnp.where(live[..., None], new_dots, 0)
    return clock, new_ids, new_dots, new_dids, new_dclocks, overflow


def contains(ids, member_id):
    """Membership bitmap (`orswot.rs:214-224`)."""
    return jnp.any(ids == member_id[..., None], axis=-1)


def member_mask(ids):
    """Live-member mask — ``value()`` as a bitmap over slots."""
    return ids != EMPTY
