"""Dense vector-clock arithmetic — the batched causality kernel (L1 on TPU).

A clock batch is an unsigned integer array whose **last axis is the actor
axis** (size A, dense interned actor ids); leading axes are free batch axes.
Absent actors hold 0 (`/root/reference/src/vclock.rs:206-210`), which makes
every VClock operation an elementwise arithmetic op:

=====================  =====================================================
reference               dense kernel
=====================  =====================================================
``merge``               pointwise max                  (`vclock.rs:131-137`)
``intersection``        ``where(a == b, a, 0)``        (`vclock.rs:219-228`)
``subtract``            ``where(a > b, a, 0)``         (`vclock.rs:236-242`)
``truncate`` (GLB)      pointwise min                  (`vclock.rs:103-120`)
``partial_cmp``         all/any reductions over A      (`vclock.rs:59-71`)
``witness``             scatter-max                    (`vclock.rs:159-163`)
=====================  =====================================================

These six primitives are the entire inner loop of Orswot/Map/MVReg merge
(SURVEY.md §3.2) — on TPU they vectorize over both the object and actor axes
and fuse into single VPU passes under XLA.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..config import counter_dtype


def zeros(shape, dtype=None):
    """An empty clock batch (all actors absent)."""
    return jnp.zeros(shape, dtype=dtype or counter_dtype())


def merge(a, b):
    """Lattice join: pointwise max (`vclock.rs:131-137`)."""
    return jnp.maximum(a, b)


def intersection(a, b):
    """Common dots: same actor AND same counter (`vclock.rs:219-228`)."""
    return jnp.where(a == b, a, 0)


def subtract(a, b):
    """Forget actors whose dots in ``b`` descend ``a``'s: keep ``a[i]`` iff
    ``a[i] > b[i]`` (`vclock.rs:236-242`; with absent==0 the reference's
    "actor present in other with counter >= ours" collapses to ``>``)."""
    return jnp.where(a > b, a, 0)


def truncate(a, b):
    """Causal truncate: greatest lower bound, pointwise min
    (`vclock.rs:103-120`; min with 0 removes, matching implied-zero)."""
    return jnp.minimum(a, b)


def is_empty(a):
    """True where the clock has no dots, reduced over the actor axis."""
    return jnp.all(a == 0, axis=-1)


def dominates_or_eq(a, b):
    """``a >= b`` in the lattice partial order: every dot of ``b`` is covered
    (`vclock.rs:63`). Reduced over the actor axis."""
    return jnp.all(a >= b, axis=-1)


def eq(a, b):
    """Structural equality (same dots), reduced over the actor axis."""
    return jnp.all(a == b, axis=-1)


def leq(a, b):
    """``a <= b``: b covers every dot of a (`vclock.rs:65`)."""
    return jnp.all(a <= b, axis=-1)


def lt(a, b):
    """Strict ``a < b``: covered and not equal."""
    return leq(a, b) & ~eq(a, b)


def concurrent(a, b):
    """Diverged: neither covers the other (`vclock.rs:200-202`)."""
    return ~leq(a, b) & ~dominates_or_eq(a, b)


def witness(clock, actor_idx, counter):
    """Scatter-max a dot into a clock batch (`vclock.rs:159-163`).

    ``clock``: ``[..., A]``; ``actor_idx``/``counter``: scalars or ``[...]``.
    """
    current = jnp.take_along_axis(clock, actor_idx[..., None], axis=-1)
    new = jnp.maximum(current, counter[..., None]).astype(clock.dtype)
    return jnp.put_along_axis(clock, actor_idx[..., None], new, axis=-1, inplace=False)


def inc_counter(clock, actor_idx):
    """Next counter for an actor: ``get + 1`` (`vclock.rs:182-185`)."""
    return jnp.take_along_axis(clock, actor_idx[..., None], axis=-1)[..., 0] + 1


def value_sum(a):
    """Sum of all counters — GCounter ``value`` (`gcounter.rs:76-78`)."""
    return jnp.sum(a, axis=-1)
