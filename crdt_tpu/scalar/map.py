"""Map — composition of CRDTs with reset-remove semantics (L4).

Mirrors `/root/reference/src/map.rs`.  Values must be causal CRDTs
(``Causal + CmRDT + CvRDT + Default`` — `map.rs:16-25`), so any causal type
nests, including another Map.  *Reset-remove* (`map.rs:27-33`): if one
replica removes an entry while another concurrently edits it, after sync the
entry survives but every edit seen by the remover is gone.

State mirrors Orswot (`map.rs:83-99`): a map clock, per-key entries carrying
an entry clock plus the nested CRDT, and a deferred-removal buffer.  Ops are
``Nop`` / ``Rm {clock, key}`` / ``Up {dot, key, op}`` (`map.rs:104-123`);
``update`` builds the nested op via a closure over the current (or default)
value (`map.rs:306-317`); merge runs the Orswot dot-algebra per key plus
recursive ``val.merge`` and reset-remove ``val.truncate`` (`map.rs:192-269`).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Hashable, Set, Type

from ..traits import Causal, CmRDT, CvRDT
from .ctx import AddCtx, ReadCtx, RmCtx
from .vclock import ClockKey, Dot, VClock

Key = Hashable


@dataclasses.dataclass
class Entry:
    """Per-key state: which actors edited it + the nested CRDT (`map.rs:91-99`)."""

    clock: VClock
    val: Any

    def clone(self) -> "Entry":
        return Entry(clock=self.clock.clone(), val=self.val.clone())


@dataclasses.dataclass(frozen=True)
class Nop:
    """No change to the CRDT (`map.rs:105-106`)."""


@dataclasses.dataclass(frozen=True)
class Rm:
    """Remove a key under a witnessing clock (`map.rs:107-113`)."""

    clock: VClock
    key: Any


@dataclasses.dataclass(frozen=True)
class Up:
    """Update the entry under ``key`` with a nested op (`map.rs:114-122`)."""

    dot: Dot
    key: Any
    op: Any


class Map(CvRDT, CmRDT, Causal):
    """
    Runnable mirror of the reference's doc example (`map.rs:35-80`) —
    nested updates build one op, applied atomically under one dot:

    >>> from .mvreg import MVReg
    >>> m = Map(lambda: Map(MVReg))
    >>> ctx = m.get("config").derive_add_ctx("admin")
    >>> op = m.update(
    ...     "config", ctx,
    ...     lambda inner, c: inner.update("theme", c,
    ...                                   lambda reg, c2: reg.set("dark", c2)),
    ... )
    >>> m.apply(op)
    >>> m.get("config").val.get("theme").val.read().val
    ['dark']
    >>> rm = m.rm("config", m.get("config").derive_rm_ctx())
    >>> m.apply(rm)
    >>> m.get("config").val is None
    True
    """

    __slots__ = ("val_type", "clock", "entries", "deferred")

    def __init__(self, val_type: Callable[[], Any]):
        # val_type plays the role of the V: Val<A> generic + Default bound
        # (map.rs:16-25): a zero-arg constructor for the nested CRDT.
        self.val_type = val_type
        self.clock = VClock()
        self.entries: Dict[Key, Entry] = {}
        self.deferred: Dict[ClockKey, Set[Key]] = {}

    def default_val(self):
        v = self.val_type()
        return v

    def clone(self) -> "Map":
        m = Map(self.val_type)
        m.clock = self.clock.clone()
        m.entries = {k: e.clone() for k, e in self.entries.items()}
        m.deferred = {k: set(v) for k, v in self.deferred.items()}
        return m

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, Map)
            and self.clock == other.clock
            and self.entries == other.entries
            and self.deferred == other.deferred
        )

    __hash__ = None  # type: ignore[assignment]

    # -- causal truncate (`map.rs:131-158`) --------------------------------

    def truncate(self, clock: VClock) -> None:
        to_remove = []
        for key, entry in self.entries.items():
            entry.clock.subtract(clock)
            if entry.clock.is_empty():
                to_remove.append(key)
            else:
                entry.val.truncate(clock)
        for key in to_remove:
            del self.entries[key]

        deferred: Dict[ClockKey, Set[Key]] = {}
        for rm_clock_key, keys in self.deferred.items():
            rm_clock = VClock.from_key(rm_clock_key)
            rm_clock.subtract(clock)
            if not rm_clock.is_empty():
                deferred[rm_clock.key()] = keys
        self.deferred = deferred

        self.clock.subtract(clock)

    # -- op path (`map.rs:160-189`) ----------------------------------------

    def apply(self, op) -> None:
        if isinstance(op, Nop):
            return
        if isinstance(op, Rm):
            self.apply_rm(op.key, op.clock)
            return
        if isinstance(op, Up):
            actor, counter = op.dot.actor, op.dot.counter
            if self.clock.get(actor) >= counter:
                return  # we've seen this op already
            entry = self.entries.pop(op.key, None)
            if entry is None:
                entry = Entry(clock=VClock(), val=self.default_val())
            try:
                entry.clock.witness(actor, counter)
                entry.val.apply(op.op)
            finally:
                # a raising nested op must not delete the popped entry
                self.entries[op.key] = entry
            self.clock.witness(actor, counter)
            self.apply_deferred()
            return
        raise TypeError(f"not a Map op: {op!r}")

    # -- state path (`map.rs:192-269`) -------------------------------------

    def merge(self, other: "Map") -> None:
        other_remaining = dict(other.entries)
        keep: Dict[Key, Entry] = {}
        for key, entry in list(self.entries.items()):
            entry = entry.clone()
            if key not in other.entries:
                # A key the peer lacks was either removed there (peer clock
                # covers every dot ⇒ drop) or never replicated (novel dots
                # remain ⇒ keep, truncating the nested value by whatever the
                # peer *did* witness — reset-remove).  (`map.rs:198-211`)
                entry.clock.subtract(other.clock)
                if entry.clock.is_empty():
                    pass
                else:
                    deleters = other.clock.clone()
                    deleters.subtract(entry.clock)
                    entry.val.truncate(deleters)
                    keep[key] = entry
            else:
                # present in both — the Orswot dot dance (`map.rs:213-240`)
                other_entry = other.entries[key].clone()
                common = entry.clock.intersection(other_entry.clock)
                entry.clock.subtract(common)
                other_entry.clock.subtract(common)
                entry.clock.subtract(other.clock)
                other_entry.clock.subtract(self.clock)

                common.merge(entry.clock)
                common.merge(other_entry.clock)
                if not common.is_empty():
                    entry.val.merge(other_entry.val)
                    deleters = entry.clock.clone()
                    deleters.merge(other_entry.clock)
                    deleters.subtract(common)
                    entry.val.truncate(deleters)
                    entry.clock = common
                    keep[key] = entry
                del other_remaining[key]

        for key, entry in other_remaining.items():
            # novel entries witnessed by other (`map.rs:244-253`)
            entry = entry.clone()
            entry.clock.subtract(self.clock)
            if not entry.clock.is_empty():
                deleters = self.clock.clone()
                deleters.subtract(entry.clock)
                entry.val.truncate(deleters)
                keep[key] = entry

        # replay other's deferred removals through apply_rm (`map.rs:256-260`);
        # snapshot first — Python allows other IS self, Rust's borrows don't
        for clock_key, deferred in list(other.deferred.items()):
            clock = VClock.from_key(clock_key)
            for key in deferred:
                self.apply_rm(key, clock)

        self.entries = keep
        self.clock.merge(other.clock)
        self.apply_deferred()

    # -- inherent API (`map.rs:271-351`) -----------------------------------

    def len(self) -> ReadCtx:
        """Number of entries with causal context (`map.rs:282-288`)."""
        return ReadCtx(
            add_clock=self.clock.clone(),
            rm_clock=self.clock.clone(),
            val=len(self.entries),
        )

    def get(self, key) -> ReadCtx:
        """Value stored under a key (`map.rs:291-302`)."""
        entry = self.entries.get(key)
        return ReadCtx(
            add_clock=self.clock.clone(),
            rm_clock=entry.clock.clone() if entry is not None else VClock(),
            val=entry.val.clone() if entry is not None else None,
        )

    def update(self, key, ctx: AddCtx, f: Callable[[Any, AddCtx], Any]) -> Up:
        """Update a value under a key; absent keys get the default value
        (`map.rs:306-317`).  ``f(val, ctx) -> nested op``; pure."""
        entry = self.entries.get(key)
        if entry is not None:
            op = f(entry.val, ctx.clone())
        else:
            op = f(self.default_val(), ctx.clone())
        return Up(dot=ctx.dot, key=key, op=op)

    def rm(self, key, ctx: RmCtx) -> Rm:
        """Build a remove op; pure (`map.rs:320-322`)."""
        return Rm(clock=ctx.clock, key=key)

    def apply_deferred(self) -> None:
        """Apply the pending deferred removes (`map.rs:325-333`)."""
        deferred = self.deferred
        self.deferred = {}
        for clock_key, keys in deferred.items():
            clock = VClock.from_key(clock_key)
            for key in keys:
                self.apply_rm(key, clock)

    def apply_rm(self, key, clock: VClock) -> None:
        """Apply a key removal given a clock, deferring if the clock is
        ahead of ours (`map.rs:336-350`)."""
        if not (clock <= self.clock):
            deferred_set = self.deferred.setdefault(clock.key(), set())
            deferred_set.add(key)

        if key in self.entries:
            existing_entry = self.entries.pop(key)
            existing_entry.clock.subtract(clock)
            if not existing_entry.clock.is_empty():
                existing_entry.val.truncate(clock)
                self.entries[key] = existing_entry

    def __repr__(self) -> str:
        return (
            f"Map(clock={self.clock!r}, entries={self.entries!r}, "
            f"deferred={self.deferred!r})"
        )
