"""PNCounter — increment/decrement counter as two GCounters.

Mirrors `/root/reference/src/pncounter.rs`: increments (P) and decrements (N)
live in separate internal G-Counters (`pncounter.rs:33-36`); merge merges P
and N (`pncounter.rs:90-95`); value is P − N (`pncounter.rs:117-119`).
Ops carry a witnessing dot and a direction (`pncounter.rs:39-56`).
"""

from __future__ import annotations

import dataclasses
import enum

from ..traits import CmRDT, CvRDT
from .gcounter import GCounter
from .vclock import Actor, Dot


class Dir(enum.Enum):
    """The direction of an op (`pncounter.rs:39-45`)."""

    POS = "pos"
    NEG = "neg"


@dataclasses.dataclass(frozen=True)
class Op:
    """A counter mutation: witnessing dot + direction (`pncounter.rs:49-56`)."""

    dot: Dot
    dir: Dir


class PNCounter(CvRDT, CmRDT):
    """
    >>> a, b = PNCounter(), PNCounter()
    >>> a.apply(a.inc("A"))
    >>> a.apply(a.inc("A"))
    >>> b.apply(b.dec("B"))
    >>> a.merge(b)
    >>> a.value()                # 2 increments - 1 decrement
    1
    """

    __slots__ = ("p", "n")

    def __init__(self, p: GCounter | None = None, n: GCounter | None = None):
        self.p = p if p is not None else GCounter()
        self.n = n if n is not None else GCounter()

    def clone(self) -> "PNCounter":
        return PNCounter(self.p.clone(), self.n.clone())

    # ordering by value (`pncounter.rs:58-77`)
    def __eq__(self, other) -> bool:
        return isinstance(other, PNCounter) and self.value() == other.value()

    def __lt__(self, other: "PNCounter") -> bool:
        return self.value() < other.value()

    def __le__(self, other: "PNCounter") -> bool:
        return self.value() <= other.value()

    def __gt__(self, other: "PNCounter") -> bool:
        return self.value() > other.value()

    def __ge__(self, other: "PNCounter") -> bool:
        return self.value() >= other.value()

    def __hash__(self):
        return hash((self.p, self.n))

    def apply(self, op: Op) -> None:
        """Route the dot on direction (`pncounter.rs:79-88`)."""
        if op.dir is Dir.POS:
            self.p.apply(op.dot)
        else:
            self.n.apply(op.dot)

    def merge(self, other: "PNCounter") -> None:
        """Merge P with P, N with N (`pncounter.rs:90-95`)."""
        self.p.merge(other.p)
        self.n.merge(other.n)

    def inc(self, actor: Actor) -> Op:
        """Increment op (`pncounter.rs:107-109`)."""
        return Op(dot=self.p.inc(actor), dir=Dir.POS)

    def dec(self, actor: Actor) -> Op:
        """Decrement op (`pncounter.rs:112-114`)."""
        return Op(dot=self.n.inc(actor), dir=Dir.NEG)

    def value(self) -> int:
        """P − N (`pncounter.rs:117-119`)."""
        return self.p.value() - self.n.value()

    def __repr__(self) -> str:
        return f"PNCounter(p={self.p!r}, n={self.n!r})"
