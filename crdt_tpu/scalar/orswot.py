"""Orswot — add-biased observed-remove set WithOut Tombstones (flagship type).

Mirrors `/root/reference/src/orswot.rs` (a port of riak_dt's ORSWOT):

* state: a set clock, per-member dot clocks, and a deferred-removal buffer
  for removes whose witnessing clock is ahead of the set clock
  (`orswot.rs:26-30`);
* ops: ``Add {dot, member}`` / ``Rm {clock, member}`` (`orswot.rs:38-53`);
* apply-Add dedups on the set clock (`orswot.rs:67-70`);
* merge implements the subtle dot-algebra (`orswot.rs:89-156`) — including
  the reference's asymmetry: a member present only in *self* keeps its full
  clock when any dot is novel (`orswot.rs:94-103`), while a member present
  only in *other* keeps the subtracted clock (`orswot.rs:132-138`);
* deferred removes are buffered, merged, and replayed (`orswot.rs:195-243`).

Every regression in the reference's ``quickcheck_evolution.log`` (same-dot
adds, deferred-only-in-other, entry-clock-vs-set-clock, …) has a named
fixture in ``tests/test_orswot.py``.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Hashable, Set

from ..traits import Causal, CmRDT, CvRDT
from .ctx import AddCtx, ReadCtx, RmCtx
from .vclock import ClockKey, Dot, VClock

Member = Hashable


@dataclasses.dataclass(frozen=True)
class Add:
    """Add a member to the set (`orswot.rs:39-45`)."""

    dot: Dot
    member: Any


@dataclasses.dataclass(frozen=True)
class Rm:
    """Remove a member under a witnessing clock (`orswot.rs:46-52`)."""

    clock: VClock
    member: Any


class Orswot(CvRDT, CmRDT, Causal):
    """
    The causal read-modify-write protocol (`ctx.rs:5-9` usage pattern):

    >>> s = Orswot()
    >>> add_op = s.add("apple", s.value().derive_add_ctx("alice"))
    >>> s.apply(add_op)                    # mutators are pure; apply commits
    >>> replica = Orswot()
    >>> replica.apply(add_op)              # ship the op, not the state
    >>> sorted(replica.value().val)
    ['apple']
    >>> rm_op = s.remove("apple", s.contains("apple").derive_rm_ctx())
    >>> s.apply(rm_op)
    >>> s.merge(replica)                   # remove wins: replica never re-adds
    >>> sorted(s.value().val)
    []
    """

    __slots__ = ("clock", "entries", "deferred")

    def __init__(self):
        self.clock = VClock()
        self.entries: Dict[Member, VClock] = {}
        # deferred removals, keyed by the (frozen) witnessing clock
        # (reference: HashMap<VClock, HashSet<M>>, orswot.rs:29)
        self.deferred: Dict[ClockKey, Set[Member]] = {}

    @classmethod
    def default(cls) -> "Orswot":
        return cls()

    def clone(self) -> "Orswot":
        c = Orswot()
        c.clock = self.clock.clone()
        c.entries = {m: vc.clone() for m, vc in self.entries.items()}
        c.deferred = {k: set(v) for k, v in self.deferred.items()}
        return c

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, Orswot)
            and self.clock == other.clock
            and self.entries == other.entries
            and self.deferred == other.deferred
        )

    __hash__ = None  # type: ignore[assignment]

    # -- op path ----------------------------------------------------------

    def apply(self, op) -> None:
        """Apply an Add or Rm (`orswot.rs:64-84`)."""
        if isinstance(op, Add):
            if self.clock.get(op.dot.actor) >= op.dot.counter:
                return  # we've already seen this op
            member_vclock = self.entries.setdefault(op.member, VClock())
            member_vclock.apply(op.dot)
            self.clock.apply(op.dot)
            self.apply_deferred()
        elif isinstance(op, Rm):
            self.apply_remove(op.member, op.clock)
        else:
            raise TypeError(f"not an Orswot op: {op!r}")

    def add(self, member, ctx: AddCtx) -> Add:
        """Build an Add op; pure (`orswot.rs:185-187`)."""
        return Add(dot=ctx.dot, member=member)

    def remove(self, member, ctx: RmCtx) -> Rm:
        """Build a Rm op; pure (`orswot.rs:190-192`)."""
        return Rm(clock=ctx.clock, member=member)

    def apply_remove(self, member, clock: VClock) -> None:
        """Remove under a witnessing clock, deferring if the clock is ahead
        of ours (`orswot.rs:195-211`)."""
        if not (clock <= self.clock):
            deferred_drops = self.deferred.pop(clock.key(), set())
            deferred_drops.add(member)
            self.deferred[clock.key()] = deferred_drops

        if member in self.entries:
            existing_clock = self.entries.pop(member)
            existing_clock.subtract(clock)
            if not existing_clock.is_empty():
                self.entries[member] = existing_clock

    # -- state path -------------------------------------------------------

    def merge(self, other: "Orswot") -> None:
        """The ORSWOT dot-algebra merge (`orswot.rs:89-156`)."""
        other_remaining = {m: vc for m, vc in other.entries.items()}
        keep: Dict[Member, VClock] = {}
        for entry, clock in list(self.entries.items()):
            clock = clock.clone()
            if entry not in other.entries:
                # Absence on the other side is ambiguous: either the peer
                # observed the add and removed it (its set clock covers our
                # dots ⇒ drop), or the add simply never reached it (some dot
                # is novel ⇒ survive, with the full clock — the asymmetry
                # vs the novel-in-other branch below).  (`orswot.rs:94-103`)
                if clock <= other.clock:
                    pass
                else:
                    keep[entry] = clock
            else:
                # Both sides hold the member; survival still depends on the
                # dot algebra, not mere presence.  (`orswot.rs:105-129`)
                other_entry_clock = other.entries[entry].clone()
                common = clock.intersection(other_entry_clock)
                clock.subtract(common)
                other_entry_clock.subtract(common)
                clock.subtract(other.clock)
                other_entry_clock.subtract(self.clock)
                common.merge(clock)
                common.merge(other_entry_clock)
                if not common.is_empty():
                    keep[entry] = common
                del other_remaining[entry]

        for entry, clock in other_remaining.items():
            # novel additions witnessed by other (`orswot.rs:132-138`)
            clock = clock.clone()
            clock.subtract(self.clock)
            if not clock.is_empty():
                keep[entry] = clock

        # merge deferred removals (`orswot.rs:141-148`); snapshot first —
        # unlike Rust's &mut self / &Self split, Python allows other IS self
        for clock_key, deferred in list(other.deferred.items()):
            our_deferred = self.deferred.pop(clock_key, set())
            our_deferred |= deferred
            self.deferred[clock_key] = set(our_deferred)

        self.entries = keep
        self.clock.merge(other.clock)
        self.apply_deferred()

    def truncate(self, clock: VClock) -> None:
        """Causal truncate via merge-with-empty (`orswot.rs:159-172`)."""
        empty_set = Orswot()
        empty_set.clock = clock.clone()
        self.merge(empty_set)
        self.clock.subtract(clock)
        for member_clock in self.entries.values():
            member_clock.subtract(clock)

    def apply_deferred(self) -> None:
        """Replay buffered removes (`orswot.rs:235-243`)."""
        deferred = self.deferred
        self.deferred = {}
        for clock_key, entries in deferred.items():
            clock = VClock.from_key(clock_key)
            for member in entries:
                self.apply_remove(member, clock)

    # -- reads ------------------------------------------------------------

    def contains(self, member) -> ReadCtx:
        """Membership test with causal context (`orswot.rs:214-224`)."""
        member_clock = self.entries.get(member)
        return ReadCtx(
            add_clock=self.clock.clone(),
            rm_clock=member_clock.clone() if member_clock is not None else VClock(),
            val=member_clock is not None,
        )

    def value(self) -> ReadCtx:
        """Current members with causal context (`orswot.rs:227-233`)."""
        return ReadCtx(
            add_clock=self.clock.clone(),
            rm_clock=self.clock.clone(),
            val=set(self.entries.keys()),
        )

    def __repr__(self) -> str:
        return (
            f"Orswot(clock={self.clock!r}, entries={self.entries!r}, "
            f"deferred={self.deferred!r})"
        )
