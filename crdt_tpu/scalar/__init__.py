"""The scalar engine: bit-exact reference semantics on the host.

This is the parity oracle for the TPU batch engine (``crdt_tpu.batch``) —
both engines implement the same ``merge`` / ``apply`` / ``value`` contracts
(`/root/reference/src/traits.rs:9-41`), so every test runs against either.
"""

from .ctx import AddCtx, ReadCtx, RmCtx
from .gcounter import GCounter
from .gset import GSet
from .lwwreg import LWWReg
from .map import Entry, Map
from .map import Nop as MapNop
from .map import Rm as MapRm
from .map import Up as MapUp
from .mvreg import MVReg, Put
from .orswot import Add, Orswot
from .orswot import Rm as OrswotRm
from .pncounter import Dir, Op as PNOp, PNCounter
from .vclock import Actor, ClockKey, Counter, Dot, VClock

__all__ = [
    "Actor",
    "Add",
    "AddCtx",
    "ClockKey",
    "Counter",
    "Dir",
    "Dot",
    "Entry",
    "GCounter",
    "GSet",
    "LWWReg",
    "Map",
    "MapNop",
    "MapRm",
    "MapUp",
    "MVReg",
    "Orswot",
    "OrswotRm",
    "PNCounter",
    "PNOp",
    "Put",
    "ReadCtx",
    "RmCtx",
    "VClock",
]
