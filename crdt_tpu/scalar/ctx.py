"""Read/write contexts — the causal read-modify-write protocol (L2).

Mirrors `/root/reference/src/ctx.rs`.  Reads return a :class:`ReadCtx`
carrying causal metadata; a client derives an :class:`AddCtx` (for mutations
that add information) or :class:`RmCtx` (for removals) from it and ships the
ctx back with the mutation.  Causality travels with the data — no network
layer is assumed.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Generic, TypeVar

from .vclock import Actor, Dot, VClock

V = TypeVar("V")


@dataclasses.dataclass
class AddCtx:
    """Context for mutations that add new information (`ctx.rs:26-32`)."""

    clock: VClock
    dot: Dot

    def clone(self) -> "AddCtx":
        return AddCtx(clock=self.clock.clone(), dot=self.dot)


@dataclasses.dataclass
class RmCtx:
    """Context for mutations that remove information (`ctx.rs:37-40`)."""

    clock: VClock

    def clone(self) -> "RmCtx":
        return RmCtx(clock=self.clock.clone())


@dataclasses.dataclass
class ReadCtx(Generic[V]):
    """Data read from a CRDT plus the causal history of the read (`ctx.rs:12-21`)."""

    add_clock: VClock
    rm_clock: VClock
    val: Any

    def derive_add_ctx(self, actor: Actor) -> AddCtx:
        """Derive an AddCtx for an actor (`ctx.rs:45-53`): clone the add
        clock, mint the actor's next dot, and witness it."""
        clock = self.add_clock.clone()
        dot = clock.inc(actor)
        clock.apply(dot)
        return AddCtx(clock=clock, dot=dot)

    def derive_rm_ctx(self) -> RmCtx:
        """Derive a RmCtx (`ctx.rs:56-60`): clone the rm clock."""
        return RmCtx(clock=self.rm_clock.clone())


def sequential_add_ctxs(base_clock: VClock, actors) -> list:
    """The scalar clone-and-increment LOOP over one object's writes —
    the oracle the batched derive (:func:`crdt_tpu.oplog.records.
    derive_add_ctx`) is parity-pinned against.

    Each write re-reads the clock the previous apply produced: derive
    an AddCtx (`ctx.rs:45-53`), then witness ONLY its dot — which is
    all ``CmRDT::apply`` witnesses (`orswot.rs:75-77`) — before the
    next write's read.  Interleaved actors therefore see each other's
    dots, and an actor absent from the base clock boots from the
    implied 0 (`vclock.rs:206-210`).  Returns one :class:`AddCtx` per
    entry of ``actors``, in order.
    """
    clock = base_clock.clone()
    out = []
    for actor in actors:
        ctx = ReadCtx(add_clock=clock, rm_clock=clock, val=None) \
            .derive_add_ctx(actor)
        out.append(ctx)
        clock.apply(ctx.dot)
    return out
