"""Scalar causality kernel: VClock and Dot — the framework's L1.

Bit-exact reference semantics of `/root/reference/src/vclock.rs`.  Actors may
be any hashable, orderable Python value (the reference's ``Actor`` trait,
`vclock.rs:27-28`); counters are unsigned ints (``Counter = u64``,
`vclock.rs:23`).  An actor absent from the clock has an implied counter of 0
(`vclock.rs:206-210`).

The comparison operators implement the lattice *partial* order
(`vclock.rs:59-71`): concurrent clocks compare False under every operator.
Use :meth:`VClock.compare` to get the four-way outcome explicitly.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Hashable, Iterable, Iterator, Optional, Tuple

Actor = Hashable
Counter = int

# Key type used to index deferred maps (reference keys HashMaps by VClock,
# orswot.rs:29; Python needs an immutable key).
ClockKey = Tuple[Tuple[Actor, Counter], ...]


@dataclasses.dataclass(frozen=True)
class Dot:
    """A version marker for a single actor (`vclock.rs:34-39`)."""

    actor: Actor
    counter: Counter

    def to_vclock(self) -> "VClock":
        """``From<Dot> for VClock`` (`vclock.rs:273-279`)."""
        c = VClock()
        c.witness(self.actor, self.counter)
        return c


class VClock:
    """A standard vector clock: a mapping from actors to counters.

    The causal partial order mirrors `/root/reference/src/vclock.rs:59-71`
    (and the runnable example style of `vclock.rs:88-102`):

    >>> a, b = VClock(), VClock()
    >>> a.apply(a.inc("A"))
    >>> b.apply(b.inc("B"))
    >>> a.concurrent(b)          # neither saw the other's event
    True
    >>> a.merge(b)               # lattice join: pointwise max
    >>> a >= b and a.get("A") == 1 and a.get("B") == 1
    True
    >>> b <= a and not a <= b    # b is now strictly dominated
    True
    """

    __slots__ = ("dots",)

    def __init__(self, dots: Optional[Dict[Actor, Counter]] = None):
        self.dots: Dict[Actor, Counter] = dict(dots) if dots else {}

    # -- construction -----------------------------------------------------

    @classmethod
    def from_iter(cls, it: Iterable[Tuple[Actor, Counter]]) -> "VClock":
        """``FromIterator`` (`vclock.rs:255-265`): witnesses each pair."""
        c = cls()
        for actor, counter in it:
            c.witness(actor, counter)
        return c

    def clone(self) -> "VClock":
        return VClock(self.dots)

    # -- core reads -------------------------------------------------------

    def get(self, actor: Actor) -> Counter:
        """Counter for this actor; absent actors have an implied 0."""
        return self.dots.get(actor, 0)

    def is_empty(self) -> bool:
        return not self.dots

    def __iter__(self) -> Iterator[Tuple[Actor, Counter]]:
        return iter(self.dots.items())

    def __len__(self) -> int:
        return len(self.dots)

    def key(self) -> ClockKey:
        """Immutable snapshot usable as a dict key (sorted for determinism)."""
        return tuple(sorted(self.dots.items(), key=lambda kv: repr(kv[0])))

    @classmethod
    def from_key(cls, key: ClockKey) -> "VClock":
        return cls(dict(key))

    # -- partial order (`vclock.rs:59-71`) -------------------------------

    def compare(self, other: "VClock") -> Optional[int]:
        """-1 if self < other, 0 if equal, 1 if self > other, None if concurrent."""
        if self.dots == other.dots:
            return 0
        if all(self.get(w) >= c for w, c in other.dots.items()):
            return 1
        if all(other.get(w) >= c for w, c in self.dots.items()):
            return -1
        return None

    def __eq__(self, other) -> bool:
        return isinstance(other, VClock) and self.dots == other.dots

    def __hash__(self):
        return hash(self.key())

    def __le__(self, other: "VClock") -> bool:
        cmp = self.compare(other)
        return cmp is not None and cmp <= 0

    def __lt__(self, other: "VClock") -> bool:
        return self.compare(other) == -1

    def __ge__(self, other: "VClock") -> bool:
        cmp = self.compare(other)
        return cmp is not None and cmp >= 0

    def __gt__(self, other: "VClock") -> bool:
        return self.compare(other) == 1

    def concurrent(self, other: "VClock") -> bool:
        """True if the two clocks have diverged (`vclock.rs:200-202`)."""
        return self.compare(other) is None

    # -- mutation ---------------------------------------------------------

    def witness(self, actor: Actor, counter: Counter) -> None:
        """Possibly store a new counter if it dominates (`vclock.rs:159-163`)."""
        if not (self.get(actor) >= counter):
            self.dots[actor] = counter

    def apply(self, dot: Dot) -> None:
        """CmRDT apply: witness the dot (`vclock.rs:123-129`)."""
        self.witness(dot.actor, dot.counter)

    def merge(self, other: "VClock") -> None:
        """CvRDT merge: pointwise max via witness (`vclock.rs:131-137`)."""
        for actor, counter in other.dots.items():
            self.witness(actor, counter)

    def inc(self, actor: Actor) -> Dot:
        """Next dot for this actor; pure — does not mutate (`vclock.rs:182-185`)."""
        return Dot(actor, self.get(actor) + 1)

    def truncate(self, other: "VClock") -> None:
        """Causal truncate: greatest-lower-bound (`vclock.rs:103-120`).

        Each counter drops to ``min(count, other.get(actor))``; actors whose
        min is 0 are removed (implied-zero rule).
        """
        to_remove = []
        for actor, count in self.dots.items():
            min_count = min(count, other.get(actor))
            if min_count > 0:
                self.dots[actor] = min_count
            else:
                to_remove.append(actor)
        for actor in to_remove:
            del self.dots[actor]

    def intersection(self, other: "VClock") -> "VClock":
        """Common (same actor AND same counter) dots (`vclock.rs:219-228`)."""
        dots = {}
        for actor, counter in self.dots.items():
            if other.get(actor) == counter:
                dots[actor] = counter
        return VClock(dots)

    def subtract(self, other: "VClock") -> None:
        """Forget actors that appear in ``other`` with descendent dots
        (`vclock.rs:236-242`): remove actor iff ``other[a] >= self[a]``.
        """
        for actor, counter in other.dots.items():
            if actor in self.dots and counter >= self.dots[actor]:
                del self.dots[actor]

    # -- display (`vclock.rs:73-84`) --------------------------------------

    def __str__(self) -> str:
        # BTreeMap iteration order = sorted by actor (`vclock.rs:76`);
        # mixed-type actor sets (untypical) fall back to repr order
        try:
            items = sorted(self.dots.items())
        except TypeError:
            items = sorted(self.dots.items(), key=lambda kv: repr(kv[0]))
        inner = ", ".join(f"{a}->{c}" for a, c in items)
        return f"({inner})"

    def __repr__(self) -> str:
        return f"VClock({self.dots!r})"
