"""MVReg — multi-value register.

Mirrors `/root/reference/src/mvreg.rs`: on concurrent writes, all values
without an established causal order are kept as an antichain
``vals: [(VClock, V)]`` (`mvreg.rs:44-46`).  Merge keeps mutually-undominated
values from both sides, deduped by clock (`mvreg.rs:121-153`); apply retains
values not dominated by the op clock and skips ops dominated by existing
values (`mvreg.rs:155-187`); ``read()`` returns every concurrent value plus
the folded clock (`mvreg.rs:201-222`).  Equality is set-equality over
``(clock, val)`` pairs (`mvreg.rs:74-96`).
"""

from __future__ import annotations

import dataclasses
from typing import Any, List, Tuple

from ..traits import Causal, CmRDT, CvRDT
from .ctx import ReadCtx
from .vclock import VClock


@dataclasses.dataclass(frozen=True)
class Put:
    """The single MVReg op (`mvreg.rs:51-59`): put a value under a clock."""

    clock: VClock
    val: Any


class MVReg(CvRDT, CmRDT, Causal):
    """
    Concurrent writes both survive; a causally-later write collapses them:

    >>> a, b = MVReg(), MVReg()
    >>> a.apply(a.set("ok", a.read().derive_add_ctx("alice")))
    >>> b.apply(b.set("no", b.read().derive_add_ctx("bob")))
    >>> a.merge(b)
    >>> sorted(a.read().val)               # concurrent: both values
    ['no', 'ok']
    >>> a.apply(a.set("done", a.read().derive_add_ctx("alice")))
    >>> a.read().val                       # dominates both: collapses
    ['done']
    """

    __slots__ = ("vals",)

    def __init__(self, vals: List[Tuple[VClock, Any]] | None = None):
        self.vals: List[Tuple[VClock, Any]] = list(vals) if vals else []

    def clone(self) -> "MVReg":
        return MVReg([(c.clone(), v) for c, v in self.vals])

    @classmethod
    def default(cls) -> "MVReg":
        return cls()

    def __eq__(self, other) -> bool:
        """Set-equality over (clock, val) pairs (`mvreg.rs:74-96`)."""
        if not isinstance(other, MVReg):
            return NotImplemented
        for pair in self.vals:
            if sum(1 for d in other.vals if d == pair) == 0:
                return False
        for pair in other.vals:
            if sum(1 for d in self.vals if d == pair) == 0:
                return False
        return True

    __hash__ = None  # type: ignore[assignment]

    def truncate(self, clock: VClock) -> None:
        """Drop values whose clock is emptied by subtracting ``clock``
        (`mvreg.rs:100-113`)."""
        new_vals = []
        for val_clock, val in self.vals:
            val_clock = val_clock.clone()
            val_clock.subtract(clock)
            if not val_clock.is_empty():
                new_vals.append((val_clock, val))
        self.vals = new_vals

    def merge(self, other: "MVReg") -> None:
        """Keep mutually-undominated values, dedup by clock (`mvreg.rs:121-153`)."""
        vals: List[Tuple[VClock, Any]] = []
        for clock, val in self.vals:
            num_dominating = sum(1 for c, _ in other.vals if clock < c)
            if num_dominating == 0:
                vals.append((clock.clone(), val))
        for clock, val in other.vals:
            num_dominating = sum(1 for c, _ in self.vals if clock < c)
            if num_dominating == 0:
                if all(existing_c != clock for existing_c, _ in vals):
                    vals.append((clock.clone(), val))
        self.vals = vals

    def apply(self, op: Put) -> None:
        """Apply a Put (`mvreg.rs:158-186`): drop dominated values, skip the
        op if an existing value dominates its clock."""
        if not isinstance(op, Put):
            raise TypeError(f"not an MVReg op: {op!r}")
        clock, val = op.clock.clone(), op.val
        if clock.is_empty():
            return
        # filter out all values dominated by the op clock
        self.vals = [(vc, v) for vc, v in self.vals if not (vc <= clock)]
        # check whether an existing entry dominates this op
        should_add = all(not (existing_clock > clock) for existing_clock, _ in self.vals)
        if should_add:
            self.vals.append((clock, val))

    def set(self, val, ctx) -> Put:
        """Build a Put op from an AddCtx; pure (`mvreg.rs:196-198`)."""
        return Put(clock=ctx.clock, val=val)

    def read(self) -> ReadCtx:
        """All concurrent values + the folded clock (`mvreg.rs:201-213`)."""
        clock = self.clock()
        return ReadCtx(
            add_clock=clock,
            rm_clock=clock.clone(),
            val=[v for _, v in self.vals],
        )

    def clock(self) -> VClock:
        """Join of every value clock (`mvreg.rs:216-222`)."""
        accum = VClock()
        for c, _ in self.vals:
            accum.merge(c)
        return accum

    def __str__(self) -> str:
        inner = ", ".join(f"{v}@{c}" for c, v in self.vals)
        return f"|{inner}|"

    def __repr__(self) -> str:
        return f"MVReg({self.vals!r})"
