"""GCounter — grow-only witnessed counter.

Mirrors `/root/reference/src/gcounter.rs`: a newtype over :class:`VClock`
(`gcounter.rs:26-28`); ``inc`` mints a :class:`Dot` op (`gcounter.rs:71-73`);
``value`` is the sum of all counters (`gcounter.rs:76-78`).  Equality and
ordering are by *value*, not structure (`gcounter.rs:30-48`).
"""

from __future__ import annotations

from ..traits import CmRDT, CvRDT
from .vclock import Actor, Dot, VClock


class GCounter(CvRDT, CmRDT):
    """
    >>> a, b = GCounter(), GCounter()
    >>> a.apply(a.inc("A"))
    >>> b.apply(b.inc("B"))
    >>> a.apply(a.inc("A"))
    >>> a.merge(b)               # state-based replication
    >>> a.value()
    3
    >>> a.merge(b); a.value()    # idempotent: re-delivery is safe
    3
    """

    __slots__ = ("inner",)

    def __init__(self, inner: VClock | None = None):
        self.inner = inner if inner is not None else VClock()

    def clone(self) -> "GCounter":
        return GCounter(self.inner.clone())

    # ordering is by value (`gcounter.rs:30-48`)
    def __eq__(self, other) -> bool:
        return isinstance(other, GCounter) and self.value() == other.value()

    def __lt__(self, other: "GCounter") -> bool:
        return self.value() < other.value()

    def __le__(self, other: "GCounter") -> bool:
        return self.value() <= other.value()

    def __gt__(self, other: "GCounter") -> bool:
        return self.value() > other.value()

    def __ge__(self, other: "GCounter") -> bool:
        return self.value() >= other.value()

    def __hash__(self):
        return hash(self.inner)

    def apply(self, op: Dot) -> None:
        """CmRDT apply = witness the dot (`gcounter.rs:50-56`)."""
        self.inner.apply(op)

    def merge(self, other: "GCounter") -> None:
        """CvRDT merge = VClock join (`gcounter.rs:58-62`)."""
        self.inner.merge(other.inner)

    def inc(self, actor: Actor) -> Dot:
        """Increment op for this actor; pure (`gcounter.rs:71-73`)."""
        return self.inner.inc(actor)

    def value(self) -> int:
        """Current sum of the counter (`gcounter.rs:76-78`)."""
        return sum(self.inner.dots.values())

    def __repr__(self) -> str:
        return f"GCounter({self.inner.dots!r})"
