"""LWWReg — last-write-wins register.

Mirrors `/root/reference/src/lwwreg.rs`: a value plus a marker that must grow
monotonically *and* be globally unique (`lwwreg.rs:16-24`).  Merge keeps the
value with the larger marker and raises :class:`ConflictingMarker` when the
markers are equal but the values differ (`lwwreg.rs:43-67`).  Op-based
replication ships the whole register: ``Op = Self``, ``apply = merge``
(`lwwreg.rs:69-77`).  Only the *Funky* (fallible) traits are implemented,
matching the reference.
"""

from __future__ import annotations

from ..error import ConflictingMarker
from ..traits import FunkyCmRDT, FunkyCvRDT


class LWWReg(FunkyCvRDT, FunkyCmRDT):
    """
    Runnable mirror of `/root/reference/src/lwwreg.rs:84-103`:

    >>> r = LWWReg()
    >>> r.update("draft", marker=1)
    >>> r.update("final", marker=9)
    >>> r.update("stale", marker=3)      # older marker: ignored
    >>> r.val
    'final'
    >>> other = LWWReg("conflict!", 9)   # same marker, different value
    >>> try:
    ...     r.merge(other)
    ... except ConflictingMarker:
    ...     print("conflict detected")
    conflict detected
    """

    __slots__ = ("val", "marker")

    def __init__(self, val=None, marker=0):
        # marker defaults to 0, matching the reference's M::default()
        # (`lwwreg.rs:34-41`) so LWWReg().update(v, m) works out of the box
        self.val = val
        self.marker = marker

    def clone(self) -> "LWWReg":
        return LWWReg(self.val, self.marker)

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, LWWReg)
            and self.val == other.val
            and self.marker == other.marker
        )

    def __hash__(self):
        return hash((self.val, self.marker))

    def merge(self, other: "LWWReg") -> None:
        """Keep the larger marker; raise on equal-marker/different-val
        (`lwwreg.rs:56-66`)."""
        if other.marker > self.marker:
            self.val = other.val
            self.marker = other.marker
        elif other.marker == self.marker and other.val != self.val:
            raise ConflictingMarker()

    def apply(self, op: "LWWReg") -> None:
        """Op = the register itself; apply = merge (`lwwreg.rs:69-77`)."""
        self.merge(op)

    def update(self, val, marker) -> None:
        """Update witnessed by the given marker (`lwwreg.rs:104-118`).

        Smaller marker: no-op.  Equal marker with different val: raises.
        """
        if self.marker < marker:
            self.val = val
            self.marker = marker
        elif self.marker == marker and val != self.val:
            raise ConflictingMarker()
        # else: seen already or identical — no-op

    def __repr__(self) -> str:
        return f"LWWReg(val={self.val!r}, marker={self.marker!r})"
