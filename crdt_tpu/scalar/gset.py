"""GSet — grow-only set.

Mirrors `/root/reference/src/gset.rs`: a set whose merge is union
(`gset.rs:30-34`).  Like the reference, it exposes inherent methods only
(the reference does not implement the CvRDT/CmRDT traits for GSet and does
not re-export it from `lib.rs:6-15`; the README marks it unchecked).
"""

from __future__ import annotations

from typing import Hashable, Set


class GSet:
    """
    >>> a, b = GSet(), GSet()
    >>> a.insert(1); b.insert(2)
    >>> a.merge(b)                         # union
    >>> a.contains(1) and a.contains(2)
    True
    """

    __slots__ = ("value",)

    def __init__(self, value: Set[Hashable] | None = None):
        self.value: Set[Hashable] = set(value) if value else set()

    def clone(self) -> "GSet":
        return GSet(self.value)

    def __eq__(self, other) -> bool:
        return isinstance(other, GSet) and self.value == other.value

    def __hash__(self):
        return hash(frozenset(self.value))

    def merge(self, other: "GSet") -> None:
        """Union (`gset.rs:30-34`)."""
        for e in other.value:
            self.insert(e)

    def insert(self, element: Hashable) -> None:
        """Insert an element (`gset.rs:46-48`)."""
        self.value.add(element)

    def contains(self, element: Hashable) -> bool:
        """Membership test (`gset.rs:60-62`)."""
        return element in self.value

    def __repr__(self) -> str:
        return f"GSet({sorted(self.value, key=repr)!r})"
