"""Stage-level profile of the ORSWOT merge at north-star shapes.

Times each kernel stage as a device-side chain (the only honest timing
through the remote-TPU tunnel — reports/TPU_LATENCY.md), plus a raw
`jnp.maximum` bandwidth probe over the same footprint, so "optimize the
merge" has a concrete target on the platform that matters.  Works on any
backend; run on TPU when the tunnel is up:

    python scripts/profile_stages.py            # north-star chunk shapes
    python scripts/profile_stages.py --config4  # BASELINE config-4 shapes
"""
from __future__ import annotations

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    import jax

    if "--cpu" in sys.argv:
        # the ambient axon plugin overrides the JAX_PLATFORMS env var;
        # only the config knob reliably forces a local-CPU smoke run
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    from jax import lax

    from crdt_tpu.ops import clock_ops, orswot_ops
    from crdt_tpu.utils.testdata import random_orswot_arrays

    if "--config4" in sys.argv:
        n, a, m, d = 100_000, 16, 8, 4
        iters = 20
    else:  # one north-star chunk
        n, a, m, d = 62_500, 64, 16, 2
        iters = 20

    rng = np.random.RandomState(0)
    lhs = tuple(jnp.asarray(x) for x in random_orswot_arrays(
        rng, n, a, m, d, min_live=m, deferred_frac=0.25))
    rhs = tuple(jnp.asarray(x) for x in random_orswot_arrays(
        rng, n, a, m, d, min_live=m))
    clock_a, ids_a, dots_a, dids_a, dclocks_a = lhs
    clock_b, ids_b, dots_b, dids_b, dclocks_b = rhs
    state_bytes = sum(x.nbytes for x in lhs)
    print(f"backend={jax.default_backend()} n={n} a={a} m={m} d={d} "
          f"state={state_bytes/1e6:.0f} MB/side")

    from crdt_tpu.utils.benchtime import sync_overhead

    sync = sync_overhead()
    print(f"sync overhead: {sync*1e3:.1f} ms")

    def chain_time(step, init, label, bytes_moved=None, consts=()):
        """step: (state, *consts) -> state, chained iters times.

        Thin wrapper over crdt_tpu.utils.benchtime.chain_timer (one
        jitted lax.scan; sync constant subtracted; device arrays flow in
        as jit parameters via ``consts``, never closures — the tunnel's
        remote-compile helper rejects oversized request bodies).
        """
        from crdt_tpu.utils.benchtime import chain_timer

        t, _ = chain_timer(step, init, iters, consts=consts,
                           sync_overhead_s=sync)
        bw = f"  {bytes_moved/t/1e9:6.1f} GB/s" if bytes_moved else ""
        print(f"{label:34s} {t*1e3:9.2f} ms{bw}")
        return t

    # raw bandwidth floor: elementwise max over the dots footprint
    chain_time(lambda s, db: (jnp.maximum(s[0], db),),
               (dots_a,), "bandwidth: maximum(dots,dots)",
               bytes_moved=3 * dots_a.nbytes, consts=(dots_b,))

    # full pairwise merge (the real thing, deferred rows present)
    chain_time(
        lambda s, *r: orswot_ops.merge(*s, *r, m, d)[:5], lhs,
        "full merge (deferred present)",
        bytes_moved=3 * state_bytes, consts=rhs)

    # deferred-free merge → rank-select fast path via the cond
    lhs_nd = (clock_a, ids_a, dots_a,
              jnp.full_like(dids_a, -1), jnp.zeros_like(dclocks_a))
    chain_time(
        lambda s, *r: orswot_ops.merge(*s, *r, m, d)[:5],
        lhs_nd, "merge fast path (no deferred)",
        bytes_moved=3 * state_bytes, consts=lhs_nd)

    # stage: member match (quadratic bool)
    def step_match(s, idb):
        va, am, j_idx, bo = orswot_ops._member_match(s[0], idb)
        # consume every output so nothing is DCE'd out of the chain
        return (jnp.where(am & va & ~bo, s[0], j_idx),)
    chain_time(step_match, (ids_a,), "_member_match [N,M,M] bool",
               consts=(ids_b,))

    # stage: rank-select core alone (survival reduces + rank + gathers)
    def step_core(s, cb, idb, db):
        clock, ids, dots = s
        out_ids, out_dots, n_surv = orswot_ops._rank_select_merge(
            clock, ids, dots, cb, idb, db, m)
        clock2 = clock_ops.merge(clock, jnp.max(out_dots, axis=-2))
        return (clock2, out_ids, out_dots)
    chain_time(step_core, (clock_a, ids_a, dots_a), "_rank_select_merge core",
               consts=(clock_b, ids_b, dots_b))

    # stage: counting-rank order over 2M keys, vs XLA argsort
    keys = jnp.concatenate([ids_a, ids_b], axis=-1)
    def step_order(s):
        o = orswot_ops._stable_order(s[0])
        return (jnp.take_along_axis(s[0], o, axis=-1),)
    chain_time(step_order, (keys,), "_stable_order [N,2M] + gather")

    def step_sort(s):
        o = jnp.argsort(s[0], axis=-1, stable=True)
        return (jnp.take_along_axis(s[0], o, axis=-1),)
    chain_time(step_sort, (keys,), "jnp.argsort [N,2M] + gather")

    # stage: deferred pipeline (dedup + replay)
    def step_deferred(s, ca, ia, da):
        d_ids, d_clocks = orswot_ops._dedup_deferred(s[0], s[1])
        ids2, dots2, d_ids2, d_clocks2 = orswot_ops._apply_deferred(
            ca, ia, da, d_ids, d_clocks)
        # keep the member-side replay (dots2) live in the carry
        return (d_ids2, jnp.maximum(d_clocks2, dots2[..., :d, :]))
    chain_time(step_deferred, (dids_a, dclocks_a), "deferred dedup+replay",
               consts=(clock_a, ids_a, dots_a))

    # the unrolled tile math (crdt_tpu/ops/orswot_unrolled.py, the TPU
    # default since the round-3 A/B).  TPU-only: on CPU it is
    # memory-bound by design (O(M) extra passes) and eats minutes of a
    # tunnel window's budget for a number we already know.
    if jax.default_backend() == "tpu" or "--all-stages" in sys.argv:
        from crdt_tpu.ops import orswot_pallas, orswot_unrolled

        chain_time(
            lambda s, *r: orswot_unrolled.merge_unrolled(*s, *r, m, d)[:5],
            lhs, "merge_unrolled (std layout)",
            bytes_moved=3 * state_bytes, consts=rhs)

        # unrolled-path internal stages (the shared tile math of
        # crdt_tpu/ops/orswot_pallas.py, biased-int32 domain) — the TPU
        # default dispatches here since the round-3 A/B, so the stage
        # attribution that matters on-chip is THIS path's
        op = orswot_pallas
        u32 = [tuple(x.astype(jnp.uint32) if x.dtype != jnp.int32 else x
                     for x in side) for side in (lhs, rhs)]
        ka = op._to_kernel_dtype(u32[0])
        kb = op._to_kernel_dtype(u32[1])

        def step_align(s, kb1, kb2):
            e2, bm = op._align_against(s[1], s[0], kb1, kb2)
            return (jnp.maximum(s[0], jnp.where(op._emask(bm), e2, op.ZERO)),
                    s[1])
        chain_time(step_align, (ka[2], ka[1]), "unrolled: align (M^2 select)",
                   consts=(kb[1], kb[2]))

        e2_0, bm_0 = op._align_against(ka[1], ka[2], kb[1], kb[2])

        def step_rule(s, ka1, ka0, kb0):
            dots, e2 = s
            valid_a = ka1 != op.EMPTY
            out = op._merge_rule(
                dots, e2, valid_a & op._nonempty(dots),
                valid_a & op._nonempty(e2), valid_a, ka0, kb0)
            # both carries data-depend on the output so XLA can neither
            # hoist the rule nor constant-fold e2 into the loop body
            return (jnp.maximum(dots, out), jnp.maximum(e2, out))
        chain_time(step_rule, (ka[2], e2_0), "unrolled: dot-algebra rule",
                   consts=(ka[1], ka[0], kb[0]))

        ids_cat0 = jnp.concatenate([ka[1], kb[1]], axis=-1)

        def step_rank(s, idc):
            big = jnp.iinfo(jnp.int32).max
            live = idc != op.EMPTY
            m_keys = jnp.where(live, idc, big)
            out_ids, out_dots, n_surv = op._rank_select(
                m_keys, live, idc, s[0], m)
            # consume ids and the survivor count too, or XLA DCEs the
            # id-pack sums and overflow reduce out of the timed stage
            salt = (out_ids[..., :1] + n_surv[..., None])[..., None]
            return (jnp.concatenate(
                [jnp.maximum(out_dots, s[0][..., :m, :] ^ salt),
                 s[0][..., m:, :]], axis=-2),)
        chain_time(step_rank, (jnp.concatenate([ka[2], kb[2]], axis=-2),),
                   "unrolled: member rank-select", consts=(ids_cat0,))
    else:
        print("unrolled variant + stages skipped (non-TPU backend; "
              "--all-stages to force)")


if __name__ == "__main__":
    main()
