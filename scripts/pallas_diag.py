"""Bisect the Mosaic RecursionError seen in compiled orswot_pallas.

Runs a ladder of probes on the default backend, printing PASS/FAIL per
probe, so the offending primitive/dtype pair is pinned down.  Temporary
diagnostic tool; safe to run on CPU (interpret) or TPU (compiled).
"""
from __future__ import annotations

import os
import sys
import traceback

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

sys.setrecursionlimit(2000)


def probe(name, fn):
    try:
        out = fn()
        jax.block_until_ready(out)
        print(f"PASS {name}")
        return True
    except Exception as e:  # noqa: BLE001
        tb = traceback.format_exc()
        first = "\n".join(tb.splitlines()[:3])
        last = "\n".join(tb.splitlines()[-3:])
        print(f"FAIL {name}: {type(e).__name__}\n{first}\n...\n{last}")
        return False


def run_kernel(body, outs, *args):
    def kernel(*refs):
        ins = refs[: len(args)]
        os = refs[len(args):]
        vals = body(*[r[...] for r in ins])
        if not isinstance(vals, tuple):
            vals = (vals,)
        for r, v in zip(os, vals):
            r[...] = v

    return pl.pallas_call(
        kernel,
        out_shape=outs,
        interpret=False,
    )(*args)


def main():
    print("backend:", jax.default_backend())
    t, a = 8, 128
    u = jnp.ones((t, a), jnp.uint32)
    i = jnp.ones((t, a), jnp.int32)
    b = jnp.ones((t, a), bool)

    probe("trivial add u32", lambda: run_kernel(
        lambda x, y: x + y, jax.ShapeDtypeStruct((t, a), jnp.uint32), u, u))
    probe("bool.astype(int32)", lambda: run_kernel(
        lambda x: x.astype(jnp.int32), jax.ShapeDtypeStruct((t, a), jnp.int32), b))
    probe("bool sum dtype=int32", lambda: run_kernel(
        lambda x: jnp.sum(x, axis=-1, dtype=jnp.int32, keepdims=True),
        jax.ShapeDtypeStruct((t, 1), jnp.int32), b))
    probe("uint32.astype(int32)", lambda: run_kernel(
        lambda x: x.astype(jnp.int32), jax.ShapeDtypeStruct((t, a), jnp.int32), u))
    probe("int32.astype(uint32)", lambda: run_kernel(
        lambda x: x.astype(jnp.uint32), jax.ShapeDtypeStruct((t, a), jnp.uint32), i))
    probe("bool.astype(uint32)", lambda: run_kernel(
        lambda x: x.astype(jnp.uint32), jax.ShapeDtypeStruct((t, a), jnp.uint32), b))
    probe("where(bool,u32,0)", lambda: run_kernel(
        lambda x, y: jnp.where(y, x, 0), jax.ShapeDtypeStruct((t, a), jnp.uint32), u, b))
    probe("max-reduce u32", lambda: run_kernel(
        lambda x: jnp.max(x, axis=-1, keepdims=True),
        jax.ShapeDtypeStruct((t, 1), jnp.uint32), u))
    probe("bool any-reduce", lambda: run_kernel(
        lambda x: jnp.any(x, axis=-1, keepdims=True),
        jax.ShapeDtypeStruct((t, 1), bool), b))

    # the real kernels at bench shapes
    from crdt_tpu.ops import orswot_pallas
    from crdt_tpu.utils.testdata import anti_entropy_fleets, random_orswot_arrays

    rng = np.random.RandomState(5)
    n, aa, m, d = 256, 16, 8, 2
    L = tuple(jnp.asarray(x) for x in random_orswot_arrays(rng, n, aa, m, d))
    R = tuple(jnp.asarray(x) for x in random_orswot_arrays(rng, n, aa, m, d))
    probe("orswot_pallas.merge compiled", lambda: orswot_pallas.merge(
        *L, *R, m, d, interpret=False))

    fleets = anti_entropy_fleets(rng, n, aa, m, d, 4, base=5, novel=0)
    stacked = tuple(
        jnp.stack([jnp.asarray(rep[k]) for rep in fleets]) for k in range(5)
    )
    probe("orswot_pallas.fold_merge compiled", lambda: orswot_pallas.fold_merge(
        *stacked, m, d, interpret=False))


if __name__ == "__main__":
    main()
