"""TPU validation — compiled-Pallas parity + timing vs the jnp fold.

Run as a TIMEBOXED subprocess (a Mosaic hang through the remote tunnel must
not wedge the caller — `bench.py` invokes this with a timeout and captures
the output):

    python scripts/tpu_validate.py --pallas     # pallas-vs-jnp on the default backend
    python scripts/tpu_validate.py --merge      # jnp merge parity TPU vs CPU oracle

Prints one JSON line per check.  Exit code 0 = all requested checks passed.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _timeit(fn, *args, iters=3):
    import jax

    out = fn(*args)
    jax.block_until_ready(out)
    ts = []
    for _ in range(iters):
        t0 = time.perf_counter()
        out = fn(*args)
        jax.block_until_ready(out)
        ts.append(time.perf_counter() - t0)
    return float(np.median(ts)), out


def check_pallas():
    """Compiled (interpret=False) Pallas fused fold vs the jnp fold:
    bit-exact outputs and a timing comparison, on the default backend."""
    import jax
    import jax.numpy as jnp

    from crdt_tpu.ops import orswot_ops, orswot_pallas
    from crdt_tpu.utils.testdata import anti_entropy_fleets

    backend = jax.default_backend()
    interpret = backend != "tpu"  # Mosaic lowers only on TPU
    rng = np.random.RandomState(5)
    n, a, m, d, r = 4_096, 16, 8, 2, 4
    fleets = anti_entropy_fleets(rng, n, a, m, d, r, base=5, novel=0)
    stacked = tuple(
        jnp.stack([jnp.asarray(rep[k]) for rep in fleets]) for k in range(5)
    )

    def jnp_fold(stack):
        acc = tuple(x[0] for x in stack)
        for i in range(1, r):
            acc = orswot_ops.merge(*acc, *(x[i] for x in stack), m, d)[:5]
        # fold_merge finishes with a defer-plunger self-merge; match it
        return orswot_ops.merge(*acc, *acc, m, d)[:5]

    t_jnp, want = _timeit(jax.jit(jnp_fold), stacked)
    t_pal, got = _timeit(
        jax.jit(
            lambda s: orswot_pallas.fold_merge(*s, m, d, interpret=interpret)
        ),
        stacked,
    )
    parity = all(
        bool(jnp.array_equal(g, w)) for g, w in zip(got[:5], want)
    )
    print(json.dumps({
        "check": "pallas_fold",
        "backend": backend,
        "compiled": not interpret,
        "parity": parity,
        "jnp_ms": round(t_jnp * 1e3, 2),
        "pallas_ms": round(t_pal * 1e3, 2),
        "speedup_vs_jnp": round(t_jnp / t_pal, 3) if t_pal else None,
        "shapes": {"n": n, "a": a, "m": m, "d": d, "r": r},
    }))
    return parity


def check_merge_parity():
    """jnp merge on the default backend vs the same program forced to CPU —
    guards against accelerator-specific numeric/layout divergence."""
    import jax
    import jax.numpy as jnp

    from crdt_tpu.ops import orswot_ops
    from crdt_tpu.utils.testdata import random_orswot_arrays

    backend = jax.default_backend()
    rng = np.random.RandomState(6)
    n, a, m, d = 2_048, 16, 8, 4
    L = random_orswot_arrays(rng, n, a, m, d)
    R = random_orswot_arrays(rng, n, a, m, d)

    def run(device):
        with jax.default_device(device):
            lhs = tuple(jnp.asarray(x) for x in L)
            rhs = tuple(jnp.asarray(x) for x in R)
            out = jax.jit(lambda x, y: orswot_ops.merge(*x, *y, m, d)[:5])(lhs, rhs)
            return [np.asarray(x) for x in out]

    got = run(jax.devices()[0])
    cpu = jax.devices("cpu")[0] if backend != "cpu" else jax.devices()[0]
    want = run(cpu)
    parity = all(np.array_equal(g, w) for g, w in zip(got, want))
    print(json.dumps({
        "check": "merge_parity_accel_vs_cpu",
        "backend": backend,
        "parity": parity,
        "n": n,
    }))
    return parity


def main():
    args = set(sys.argv[1:]) or {"--pallas", "--merge"}
    ok = True
    if "--merge" in args:
        ok &= check_merge_parity()
    if "--pallas" in args:
        ok &= check_pallas()
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    main()
