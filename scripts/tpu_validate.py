"""TPU validation — compiled-Pallas parity + timing vs the jnp fold.

Run as a TIMEBOXED subprocess (a Mosaic hang through the remote tunnel must
not wedge the caller — `bench.py` invokes this with a timeout and captures
the output):

    python scripts/tpu_validate.py --pallas     # pallas-vs-jnp on the default backend
    python scripts/tpu_validate.py --merge      # jnp merge parity TPU vs CPU oracle

Prints one JSON line per check.  Exit code 0 = all requested checks passed.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _timeit(fn, *args, iters=3):
    import jax

    out = fn(*args)
    jax.block_until_ready(out)
    ts = []
    for _ in range(iters):
        t0 = time.perf_counter()
        out = fn(*args)
        jax.block_until_ready(out)
        ts.append(time.perf_counter() - t0)
    return float(np.median(ts)), out


def _jnp_chain_fold(stack, r, m, d):
    """Reference left fold + defer-plunger self-merge — must match
    fold_merge's semantics exactly (the plunger flushes buffered
    removes)."""
    from crdt_tpu.ops import orswot_ops

    acc = tuple(x[0] for x in stack)
    for i in range(1, r):
        acc = orswot_ops.merge(*acc, *(x[i] for x in stack), m, d)[:5]
    return orswot_ops.merge(*acc, *acc, m, d)[:5]


def check_pallas():
    """Compiled (interpret=False) Pallas fused fold vs the jnp fold:
    bit-exact outputs and a timing comparison, on the default backend."""
    import jax
    import jax.numpy as jnp

    from crdt_tpu.ops import orswot_ops, orswot_pallas
    from crdt_tpu.utils.testdata import anti_entropy_fleets

    backend = jax.default_backend()
    interpret = backend != "tpu"  # Mosaic lowers only on TPU
    rng = np.random.RandomState(5)
    n, a, m, d, r = 4_096, 16, 8, 2, 4
    fleets = anti_entropy_fleets(rng, n, a, m, d, r, base=5, novel=0)
    stacked = tuple(
        jnp.stack([jnp.asarray(rep[k]) for rep in fleets]) for k in range(5)
    )

    def jnp_fold(stack):
        return _jnp_chain_fold(stack, r, m, d)

    t_jnp, want = _timeit(jax.jit(jnp_fold), stacked)
    t_pal, got = _timeit(
        jax.jit(
            lambda s: orswot_pallas.fold_merge(*s, m, d, interpret=interpret)
        ),
        stacked,
    )
    parity = all(
        bool(jnp.array_equal(g, w)) for g, w in zip(got[:5], want)
    )
    print(json.dumps({
        "check": "pallas_fold",
        "backend": backend,
        "compiled": not interpret,
        "parity": parity,
        "jnp_ms": round(t_jnp * 1e3, 2),
        "pallas_ms": round(t_pal * 1e3, 2),
        "speedup_vs_jnp": round(t_jnp / t_pal, 3) if t_pal else None,
        "shapes": {"n": n, "a": a, "m": m, "d": d, "r": r},
        "tile": os.environ.get("CRDT_PALLAS_TILE", "auto"),
    }), flush=True)
    return parity


def check_pallas_northstar():
    """The fused Pallas fold vs the jnp chain fold on ONE north-star
    chunk (r=8, 62.5k objects, a=64, m=16, deferred present): parity +
    chained device-side timing (the per-dispatch tunnel sync would dwarf
    a single fold, so both folds run as a salted lax.scan like the
    benchmark's own timing path).  The local v5e AOT matrix
    (`reports/PALLAS_LOCAL_AOT.md`) puts this compile at ~1 min."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from crdt_tpu.ops import orswot_ops, orswot_pallas
    from crdt_tpu.utils.testdata import anti_entropy_fleets

    backend = jax.default_backend()
    interpret = backend != "tpu"
    rng = np.random.RandomState(9)
    r, n, a, m, d = 8, 62_500, 64, 16, 2
    iters = 4
    fleets = anti_entropy_fleets(
        rng, n, a, m, d, r, base=6, novel=1, deferred_frac=0.25
    )
    stacked = tuple(
        jnp.stack([jnp.asarray(rep[k]).astype(jnp.uint32)
                   if rep[k].dtype.kind == "u" else jnp.asarray(rep[k])
                   for rep in fleets])
        for k in range(5)
    )

    def jnp_fold(stack):
        return _jnp_chain_fold(stack, r, m, d)

    # the Pallas chain runs PRE-BIASED — pad + uint32↔int32 conversion
    # hoisted out of the loop, exactly like the bench's headline attempt
    # (bench.py bench_pallas_north_star); XOR salting commutes with the
    # bias, and max/&/| on biased values preserves the salt chain's
    # data-dependence
    def pal_fold(stack):
        return orswot_pallas.fold_merge(
            *stack, m, d, interpret=interpret, prebiased=True
        )[:5]

    def unbias(out):
        return (
            orswot_pallas.from_kernel_domain(out[0], jnp.uint32)[:n], out[1][:n],
            orswot_pallas.from_kernel_domain(out[2], jnp.uint32)[:n], out[3][:n],
            orswot_pallas.from_kernel_domain(out[4], jnp.uint32)[:n],
        )

    def chain_time(fold, source):
        # crdt_tpu.utils.benchtime.chain_timer: one jitted lax.scan,
        # same-window sync subtracted, and ``source`` (the ~2.5 GB
        # replica stack) flows in as a jit parameter — a closure would
        # inline it as dense constants and the tunnel's remote-compile
        # helper rejects the oversized request (HTTP 413)
        from crdt_tpu.utils.benchtime import chain_timer

        def step(carry, *src):
            salt, _ = carry
            out = fold((src[0] ^ salt,) + src[1:])
            s32 = src[0].dtype.type
            return ((jnp.max(out[2]).astype(src[0].dtype) & s32(7)) | s32(1), out)

        init = (source[0].dtype.type(1), tuple(x[0] for x in source))
        t, out = chain_timer(step, init, iters, consts=source)
        return t, out[1]

    t_jnp, want = chain_time(jnp_fold, stacked)
    # bias AFTER the jnp timing: the ~2.5 GB padded+biased copy must not
    # shrink device headroom while the jnp chain runs
    biased = orswot_pallas.to_kernel_domain(
        orswot_pallas.pad_to_tile(stacked, m, d, n_states=r + 1)
    )
    t_pal, got_biased = chain_time(pal_fold, biased)
    got = unbias(got_biased)
    parity = all(bool(jnp.array_equal(g, w)) for g, w in zip(got, want))
    print(json.dumps({
        "check": "pallas_fold_northstar_chunk",
        "backend": backend,
        "compiled": not interpret,
        "parity": parity,
        "jnp_ms_per_fold": round(t_jnp * 1e3, 2),
        "pallas_ms_per_fold": round(t_pal * 1e3, 2),
        "speedup_vs_jnp": round(t_jnp / t_pal, 3) if t_pal else None,
        "pallas_merges_per_sec": round(n * r / t_pal, 1) if t_pal else None,
        "shapes": {"n": n, "a": a, "m": m, "d": d, "r": r},
        "tile": os.environ.get("CRDT_PALLAS_TILE", "auto"),
    }), flush=True)
    return parity


def check_merge_parity():
    """jnp merge on the default backend vs the same program forced to CPU —
    guards against accelerator-specific numeric/layout divergence."""
    import jax
    import jax.numpy as jnp

    from crdt_tpu.ops import orswot_ops
    from crdt_tpu.utils.testdata import random_orswot_arrays

    backend = jax.default_backend()
    rng = np.random.RandomState(6)
    n, a, m, d = 2_048, 16, 8, 4
    L = random_orswot_arrays(rng, n, a, m, d)
    R = random_orswot_arrays(rng, n, a, m, d)

    def run(device):
        with jax.default_device(device):
            lhs = tuple(jnp.asarray(x) for x in L)
            rhs = tuple(jnp.asarray(x) for x in R)
            out = jax.jit(lambda x, y: orswot_ops.merge(*x, *y, m, d)[:5])(lhs, rhs)
            return [np.asarray(x) for x in out]

    got = run(jax.devices()[0])
    cpu = jax.devices("cpu")[0] if backend != "cpu" else jax.devices()[0]
    want = run(cpu)
    parity = all(np.array_equal(g, w) for g, w in zip(got, want))
    print(json.dumps({
        "check": "merge_parity_accel_vs_cpu",
        "backend": backend,
        "parity": parity,
        "n": n,
    }), flush=True)
    return parity


def main():
    args = set(sys.argv[1:]) or {"--pallas", "--merge"}
    ok = True
    if "--merge" in args:
        ok &= check_merge_parity()
    if "--pallas" in args:
        # small-shape parity first (its t=128 tile is the slow compile —
        # force a faster one; the env var is read at trace time and the
        # north-star shapes retrace anyway)
        import jax

        user_tile = "CRDT_PALLAS_TILE" in os.environ
        force_tile = not user_tile and jax.default_backend() == "tpu"
        if force_tile:
            # interpret mode prefers the big default tile (fewer python
            # grid steps); compiled mode prefers the fast-compiling one
            os.environ["CRDT_PALLAS_TILE"] = "32"
        ok &= check_pallas()
        if force_tile:
            del os.environ["CRDT_PALLAS_TILE"]
        # the north-star chunk only on a real TPU backend (interpret mode
        # at 62.5k x 8 would grind for hours); CRDT_PALLAS_NS=1 forces
        if jax.default_backend() == "tpu" or os.environ.get("CRDT_PALLAS_NS") == "1":
            try:
                ok &= check_pallas_northstar()
            except Exception as e:  # the small-shape result must survive
                print(json.dumps({
                    "check": "pallas_fold_northstar_chunk",
                    "error": str(e)[:300],
                }))
                ok = False
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    main()
