#!/bin/bash
# Poll the axon tunnel; when it revives, immediately capture a full TPU
# bench run and a compiled-Pallas attempt before it can wedge again.
cd /root/repo
for i in $(seq 1 200); do
    if timeout 75 python -c "import jax; jax.devices()" >/dev/null 2>&1; then
        echo "$(date -u +%H:%M:%S) tunnel ALIVE - capturing bench" | tee -a /tmp/tunnel_watch.log
        timeout 3000 python bench.py > /tmp/bench_tpu3.log 2>&1
        echo "bench exit: $? (log: /tmp/bench_tpu3.log)" | tee -a /tmp/tunnel_watch.log
        tail -1 /tmp/bench_tpu3.log | tee -a /tmp/tunnel_watch.log
        timeout 1200 python scripts/profile_stages.py > /tmp/profile_tpu.log 2>&1
        echo "profile exit: $?" | tee -a /tmp/tunnel_watch.log
        timeout 9000 python scripts/tpu_experiments.py > /tmp/experiments_tpu.log 2>&1
        echo "experiments exit: $?" | tee -a /tmp/tunnel_watch.log
        exit 0
    fi
    echo "$(date -u +%H:%M:%S) tunnel down (attempt $i)" >> /tmp/tunnel_watch.log
    sleep 60
done
