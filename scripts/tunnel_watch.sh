#!/bin/bash
# Poll the axon tunnel; whenever it is alive, run every capture step that
# has not yet succeeded (marker files under /tmp/tw_done), until all have.
# A window that closes mid-capture just means the remaining steps retry
# on the next window.  Order matters: everything that needs the tunnel's
# remote-compile helper runs BEFORE the compiled-Pallas attempt (inside
# the final bench.py's validation step) — a Mosaic crash has been
# observed to take the compile helper down with it (reports/TPU_LATENCY.md).
cd /root/repo
# persistent XLA compilation cache: repeated captures across tunnel
# windows skip recompiling unchanged programs, so a window spends its
# minutes measuring instead of compiling
export JAX_COMPILATION_CACHE_DIR=${JAX_COMPILATION_CACHE_DIR:-/tmp/jax_comp_cache}
MARK=/tmp/tw_done
mkdir -p "$MARK"

step() {  # step <name> <timeout> <log> <cmd...>
    local name=$1 tmo=$2 log=$3; shift 3
    [ -e "$MARK/$name" ] && return 0
    echo "$(date -u +%H:%M:%S) step $name starting" | tee -a /tmp/tunnel_watch.log
    timeout "$tmo" "$@" > "$log" 2>&1
    local rc=$?
    echo "$(date -u +%H:%M:%S) step $name exit $rc (log: $log)" | tee -a /tmp/tunnel_watch.log
    tail -1 "$log" | tee -a /tmp/tunnel_watch.log
    [ $rc -eq 0 ] && touch "$MARK/$name"
    return $rc
}

for i in $(seq 1 200); do
    if timeout 150 python -c "import jax; jax.devices()" >/dev/null 2>&1; then
        echo "$(date -u +%H:%M:%S) tunnel ALIVE - capturing" | tee -a /tmp/tunnel_watch.log
        step profile 2400 /tmp/profile_tpu.log \
            python scripts/profile_stages.py
        step experiments 5400 /tmp/experiments_tpu.log \
            env CRDT_EXP_MODES=merge_scatter,merge_scatterless,merge_unrolled,merge_lanes,gather_take,gather_onehot,gather_mxu,gather_mxu8,scatter_put \
            python scripts/tpu_experiments.py
        step bench_lanes 2400 /tmp/bench_tpu_lanes.log \
            env CRDT_LANES=1 CRDT_SKIP_TPU_VALIDATE=1 python bench.py
        step bench 4500 /tmp/bench_tpu3.log \
            python bench.py
        if [ -e "$MARK/profile" ] && [ -e "$MARK/experiments" ] && \
           [ -e "$MARK/bench_lanes" ] && [ -e "$MARK/bench" ]; then
            echo "$(date -u +%H:%M:%S) all captures done" | tee -a /tmp/tunnel_watch.log
            exit 0
        fi
    else
        echo "$(date -u +%H:%M:%S) tunnel down (attempt $i)" >> /tmp/tunnel_watch.log
    fi
    sleep 60
done
