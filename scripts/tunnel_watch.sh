#!/bin/bash
# Poll the axon tunnel; when it revives, immediately capture the pending
# TPU measurements before it can wedge again.  Order matters: everything
# that needs the tunnel's remote-compile helper runs BEFORE the
# compiled-Pallas attempt (inside bench.py's validation step) — a Mosaic
# crash has been observed to take the compile helper down with it
# (reports/TPU_LATENCY.md), so the bench goes last.
cd /root/repo
# persistent XLA compilation cache: repeated captures across tunnel
# windows skip recompiling unchanged programs, so a window spends its
# minutes measuring instead of compiling
export JAX_COMPILATION_CACHE_DIR=${JAX_COMPILATION_CACHE_DIR:-/tmp/jax_comp_cache}
for i in $(seq 1 200); do
    if timeout 75 python -c "import jax; jax.devices()" >/dev/null 2>&1; then
        echo "$(date -u +%H:%M:%S) tunnel ALIVE - capturing" | tee -a /tmp/tunnel_watch.log
        timeout 2400 python scripts/profile_stages.py > /tmp/profile_tpu.log 2>&1
        echo "profile exit: $?" | tee -a /tmp/tunnel_watch.log
        CRDT_EXP_MODES=${CRDT_EXP_MODES:-merge_scatter,merge_scatterless,merge_unrolled,merge_lanes,gather_take,gather_onehot,gather_mxu,scatter_put} \
            timeout 5400 python scripts/tpu_experiments.py > /tmp/experiments_tpu.log 2>&1
        echo "experiments exit: $?" | tee -a /tmp/tunnel_watch.log
        CRDT_LANES=1 CRDT_SKIP_TPU_VALIDATE=1 timeout 2400 python bench.py > /tmp/bench_tpu_lanes.log 2>&1
        echo "lanes bench exit: $?" | tee -a /tmp/tunnel_watch.log
        tail -1 /tmp/bench_tpu_lanes.log | tee -a /tmp/tunnel_watch.log
        timeout 4500 python bench.py > /tmp/bench_tpu3.log 2>&1
        echo "bench exit: $? (log: /tmp/bench_tpu3.log)" | tee -a /tmp/tunnel_watch.log
        tail -1 /tmp/bench_tpu3.log | tee -a /tmp/tunnel_watch.log
        exit 0
    fi
    echo "$(date -u +%H:%M:%S) tunnel down (attempt $i)" >> /tmp/tunnel_watch.log
    sleep 60
done
