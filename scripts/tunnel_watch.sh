#!/bin/bash
# Poll the axon tunnel; whenever it is alive, run every capture step that
# has not yet succeeded (marker files under /tmp/tw_done.<rev>), until all
# have.  A window that closes mid-capture just means the remaining steps
# retry on the next window.  ROUND-4 ORDER (post-bridge-retirement):
# bench first — it banks every jnp metric, then attempts the
# compiled-Pallas fused scan through the remote-compile helper as its
# LAST stage (small program text: one Mosaic kernel; every known Mosaic
# crash class was fixed offline in round 3) and self-banks the compiled
# executable axon-side for compile-free reuse.  Then merge-parity
# validation, the axon-serialize probe, and secondary evidence
# (profile/experiments).  The standalone remote-compile Mosaic attempts
# stay DEAD LAST: a helper-path Mosaic crash has wedged the device for
# a whole window before (reports/TPU_LATENCY.md, PALLAS_TPU_ATTEMPT.txt).
#
# Markers are keyed to a content hash of the measured code paths, so a
# capture from an older build never satisfies a step after bench/kernel
# changes (advisor finding r2) — while commits that don't change that
# code (docs, reports, committing the already-captured code verbatim)
# never discard a capture.
cd /root/repo
# persistent XLA compilation cache: repeated captures across tunnel
# windows skip recompiling unchanged programs, so a window spends its
# minutes measuring instead of compiling
export JAX_COMPILATION_CACHE_DIR=${JAX_COMPILATION_CACHE_DIR:-/root/repo/.jax_cache}
# libtpu-init workaround from the captured Mosaic failure
# (reports/PALLAS_TPU_ATTEMPT.txt:12-14) — every step that might compile
# Pallas (bench auto-attempt, experiments_pallas, tpu_validate) needs it,
# and it is harmless for the rest
export TPU_ACCELERATOR_TYPE=${TPU_ACCELERATOR_TYPE:-v5litepod-1}
export TPU_WORKER_HOSTNAMES=${TPU_WORKER_HOSTNAMES:-localhost}

step() {  # step <name> <timeout> <log> <cmd...>
    local name=$1 tmo=$2 log=$3; shift 3
    [ -e "$MARK/$name" ] && return 0
    echo "$(date -u +%H:%M:%S) step $name starting (rev $REV)" | tee -a /tmp/tunnel_watch.log
    # -k: a python wedged in the tunnel plugin can ignore TERM; without
    # the KILL fallback `timeout` waits on it forever and the watcher
    # stalls mid-iteration
    timeout -k 30 "$tmo" "$@" > "$log" 2>&1
    local rc=$?
    echo "$(date -u +%H:%M:%S) step $name exit $rc (log: $log)" | tee -a /tmp/tunnel_watch.log
    tail -1 "$log" | tee -a /tmp/tunnel_watch.log
    [ $rc -eq 0 ] && touch "$MARK/$name"
    return $rc
}

tunnel_alive() {
    # returns the probe's own rc so callers can discriminate: 0 = alive;
    # 124/137 = the timeout wrapper killed a HUNG probe (wedged window);
    # anything else = jax.devices() failed FAST (window simply closed /
    # plugin error).  Fallback behavior is the same either way, but the
    # log line must not claim "wedged" for a fast failure (advisor r5).
    timeout -k 15 150 python -c "import jax; jax.devices()" >/dev/null 2>&1
}

wedge_probe() {  # wedge_probe <context> — fresh-process aliveness probe
    # after a suspicious step outcome.  A wedged window hangs EVERY
    # device call — including this probe (round-2 diagnostics,
    # reports/TPU_TUNNEL_STATUS.md) — so probe-hang means the
    # iteration's remaining steps are doomed and the watcher should
    # fall back to the outer probe loop instead of burning hours of
    # step timeouts (2026-08-02: a wedge right after the bench would
    # have cost ~3.5h of doomed secondaries before the re-probe).  A
    # live window answers in seconds, so the probe is cheap when it
    # matters least.
    # two attempts: a transiently slow live window must not be
    # misclassified as wedged off one 150s miss (the second attempt
    # only runs when the first failed, so the live path stays cheap)
    local prc=0
    for _try in 1 2; do
        tunnel_alive
        prc=$?
        if [ "$prc" -eq 0 ]; then
            echo "$(date -u +%H:%M:%S) $1 - tunnel still answers, continuing" \
                | tee -a /tmp/tunnel_watch.log
            return 1
        fi
    done
    # wedged (probe HUNG until the timeout killed it) vs window closed
    # (probe failed fast) — distinct diagnoses for later debugging even
    # though both fall back to the outer probe loop
    if [ "$prc" -eq 124 ] || [ "$prc" -eq 137 ]; then
        echo "$(date -u +%H:%M:%S) $1 - tunnel probe hangs (rc $prc): wedged, back to outer probe" \
            | tee -a /tmp/tunnel_watch.log
    else
        echo "$(date -u +%H:%M:%S) $1 - tunnel probe fails fast (rc $prc): window closed, back to outer probe" \
            | tee -a /tmp/tunnel_watch.log
    fi
    return 0
}

wedged() {  # wedged <rc> <name> — true when a failed step left the
    # window wedged.  ANY nonzero exit is suspicious, not just the
    # timeout kills (124 TERM / 137 KILL fallback): the documented
    # wedge-inducer is a fast-crashing Mosaic compile (rc 1/139,
    # reports/PALLAS_TPU_ATTEMPT.txt) that exits long before its
    # timeout yet leaves the device hung for the rest of the window.
    # The probe discriminates — slow-but-live steps (or OOM kills on a
    # healthy window) keep capturing.
    [ "$1" -ne 0 ] || return 1
    wedge_probe "step $2 died (rc $1)"
}

publish_bench() {  # publish_bench <log>
    # Persist the captured on-chip bench line as a repo artifact so a
    # mid-round window survives even if the driver's end-of-round probe
    # misses the next window (the driver commits uncommitted files).
    # captured_rev records BOTH the nearest commit (human-locatable
    # provenance) and the content hash the markers are keyed on.
    # Publish ONLY a genuinely live on-chip headline: a banked-seed or
    # watchdog-rescued record re-stamped with fresh captured_at/rev
    # would launder stale provenance (code-review r4).
    python - "$1" "$(git rev-parse --short HEAD 2>/dev/null || echo norev).$REV" <<'EOF'
import json, sys, time
lines = [l for l in open(sys.argv[1]) if l.startswith('{"metric"')]
if lines:
    rec = json.loads(lines[-1])
    # budget_watchdog=fired does NOT disqualify: a headline that is
    # live+tpu was measured in THIS window before the wedge — only
    # banked/seed headlines (headline_source != live) would launder
    if (rec.get("headline_source") == "live" and rec.get("platform") == "tpu"
            and rec.get("value")):
        rec["captured_at"] = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
        rec["captured_rev"] = sys.argv[2]
        with open("BENCH_tpu_window.json", "w") as f:
            f.write(json.dumps(rec) + "\n")
        print("published BENCH_tpu_window.json:", json.dumps(rec))
    else:
        print("publish_bench: record not a live on-chip headline; not published")
EOF
}

for i in $(seq 1 600); do
    # re-key markers every iteration: an edit to the measured code
    # invalidates earlier captures and the steps re-run on the next
    # window.  The key is a pure CONTENT hash of the code paths (tracked
    # + untracked working-tree contents) — deliberately NOT the git HEAD
    # rev, so committing docs/reports (or committing the very code that
    # ran, unchanged) never discards a capture; only changing what a
    # capture measures does.
    CODE="crdt_tpu scripts bench.py benchkit __graft_entry__.py"
    REV=$( { git ls-files -z -- $CODE 2>/dev/null; \
             git ls-files -o --exclude-standard -z -- $CODE 2>/dev/null; } \
           | LC_ALL=C sort -z | xargs -0 cat 2>/dev/null | sha1sum | cut -c1-12 )
    MARK=/tmp/tw_done.$REV
    mkdir -p "$MARK"
    if tunnel_alive; then
        echo "$(date -u +%H:%M:%S) tunnel ALIVE - capturing (rev $REV)" | tee -a /tmp/tunnel_watch.log
        # ROUND-4 NOTE: the local-AOT bridge is DEAD — the axon
        # runtime only loads executables in its own serialization format
        # ("axon format v9"); blobs from the local libtpu compile-only
        # topology are rejected at PJRT_Executable_DeserializeAndLoad
        # (first-ever load attempt, 2026-08-01 window; see
        # reports/TPU_LATENCY.md item 7).  The compiled-Pallas headline
        # now rides bench.py's helper-path attempt (the fused scan is
        # one Mosaic kernel — small program text, inside the helper's
        # body limit), and the axon_serialize probe below tests whether
        # helper-compiled executables can be banked axon-side for
        # compile-free reuse in later windows.
        #
        # 1) the full bench (seeds from whatever is already banked;
        #    publish only when this iteration actually ran it — a marker
        #    short-circuit must not re-stamp the artifact's capture time).
        #    PROBE_TIMEOUT at the old 900s ladder: the aliveness gate only
        #    proved jax.devices(); a live-but-slow window must not be
        #    misclassified as wedged by the 120s default.
        # ROUND-5: validation UN-skipped (VERDICT r4 item 2 — one
        # artifact whose headline, parity gate, elision check and floor
        # share one rev and one window); the 4200 s budget covers the
        # ~113 s elision check + ~240 s validation alongside the timed
        # stages, and the budget watchdog still guarantees rc=0
        if [ ! -e "$MARK/bench" ]; then
            step bench 4500 /tmp/bench_tpu3.log \
                env CRDT_RUN_ELISION_CHECK=1 CRDT_BENCH_BUDGET_S=4200 \
                CRDT_BENCH_PROBE_TIMEOUT=900 \
                python bench.py
            brc=$?
            if [ $brc -eq 0 ]; then
                # publish whatever live on-chip headline landed (the gate
                # inside publish_bench refuses banked/seed records); a
                # watchdog-rescued run exits 0 by design for the DRIVER,
                # but for the WATCHER the capture is incomplete — drop the
                # marker so the remaining stages re-run on the next window
                publish_bench /tmp/bench_tpu3.log 2>&1 | tee -a /tmp/tunnel_watch.log
                if tail -5 /tmp/bench_tpu3.log | grep -q '"budget_watchdog": "fired"'; then
                    echo "$(date -u +%H:%M:%S) bench watchdog fired - capture incomplete, re-arming" \
                        | tee -a /tmp/tunnel_watch.log
                    rm -f "$MARK/bench"
                    # the watchdog fires when a stage blocks past the
                    # budget — usually a wedge, but a live-slow window
                    # can trip it too; let the probe decide whether the
                    # secondaries still have a window to capture in
                    if wedge_probe "bench watchdog fired"; then
                        sleep 60; continue
                    fi
                fi
            else
                wedged $brc bench && { sleep 60; continue; }
            fi
        fi
        step validate_merge 900 /tmp/validate_merge_tpu.log \
            python scripts/tpu_validate.py --merge
        rc=$?  # captured immediately: an inserted line would silently break $?
        wedged "$rc" validate_merge && { sleep 60; continue; }
        # 2) can the axon client serialize its own executables?  If yes,
        #    one helper compile of the fused scan can be banked for
        #    compile-free reuse across windows (the local-AOT direction
        #    is format-incompatible — see header)
        step axon_serialize 600 /tmp/axon_serialize_tpu.log \
            python scripts/axon_serialize_probe.py
        rc=$?
        wedged "$rc" axon_serialize && { sleep 60; continue; }
        # 3) secondary evidence, after everything headline-bearing
        step profile 2400 /tmp/profile_tpu.log \
            python scripts/profile_stages.py
        rc=$?
        wedged "$rc" profile && { sleep 60; continue; }
        # the 7-mode layout A/B concluded in the 2026-07-31 window
        # (reports/LAYOUT_AB_TPU.md); only the still-undecided fold-shape
        # contenders remain
        step experiments 5000 /tmp/experiments_tpu.log \
            env CRDT_EXP_MODES=fold_seq,fold_tree,fold_seq_rank \
            python scripts/tpu_experiments.py
        rc=$?
        wedged "$rc" experiments && { sleep 60; continue; }
        if [ -e "$MARK/experiments" ]; then
            BLOG=/dev/null
            [ -e "$MARK/bench" ] && BLOG=/tmp/bench_tpu3.log
            python scripts/layout_decision.py /tmp/experiments_tpu.log \
                "$BLOG" >> /tmp/tunnel_watch.log 2>&1 || true
        fi
        # 4) remote-compile Mosaic attempts DEAD LAST: these go through
        #    the compile helper, whose Mosaic crashes have wedged the
        #    device for a whole window (PALLAS_TPU_ATTEMPT.txt:12-14)
        step pallas 1800 /tmp/pallas_tpu.log \
            env TPU_ACCELERATOR_TYPE=v5litepod-1 TPU_WORKER_HOSTNAMES=localhost \
            python scripts/tpu_validate.py --pallas
        rc=$?
        wedged "$rc" pallas && { sleep 60; continue; }
        step experiments_pallas 1800 /tmp/experiments_pallas_tpu.log \
            env CRDT_EXP_MODES=merge_pallas \
            python scripts/tpu_experiments.py
        rc=$?
        wedged "$rc" experiments_pallas && { sleep 60; continue; }
        # done only when every step has its marker
        if [ -e "$MARK/profile" ] && [ -e "$MARK/experiments" ] && \
           [ -e "$MARK/bench" ] && [ -e "$MARK/axon_serialize" ] && \
           [ -e "$MARK/validate_merge" ] && [ -e "$MARK/pallas" ] && \
           [ -e "$MARK/experiments_pallas" ]; then
            echo "$(date -u +%H:%M:%S) all captures done (rev $REV)" | tee -a /tmp/tunnel_watch.log
            exit 0
        fi
    else
        echo "$(date -u +%H:%M:%S) tunnel down (attempt $i)" >> /tmp/tunnel_watch.log
    fi
    sleep 60
done
