#!/bin/bash
# Poll the axon tunnel; whenever it is alive, run every capture step that
# has not yet succeeded (marker files under /tmp/tw_done.<rev>), until all
# have.  A window that closes mid-capture just means the remaining steps
# retry on the next window.  Order matters: everything that needs the
# tunnel's remote-compile helper runs BEFORE the compiled-Pallas attempt —
# a Mosaic crash has been observed to take the compile helper down with it
# (reports/TPU_LATENCY.md).
#
# Markers are keyed to a content hash of the measured code paths, so a
# capture from an older build never satisfies a step after bench/kernel
# changes (advisor finding r2) — while commits that don't change that
# code (docs, reports, committing the already-captured code verbatim)
# never discard a capture.
cd /root/repo
# persistent XLA compilation cache: repeated captures across tunnel
# windows skip recompiling unchanged programs, so a window spends its
# minutes measuring instead of compiling
export JAX_COMPILATION_CACHE_DIR=${JAX_COMPILATION_CACHE_DIR:-/tmp/jax_comp_cache}
# libtpu-init workaround from the captured Mosaic failure
# (reports/PALLAS_TPU_ATTEMPT.txt:12-14) — every step that might compile
# Pallas (bench auto-attempt, experiments_pallas, tpu_validate) needs it,
# and it is harmless for the rest
export TPU_ACCELERATOR_TYPE=${TPU_ACCELERATOR_TYPE:-v5litepod-1}
export TPU_WORKER_HOSTNAMES=${TPU_WORKER_HOSTNAMES:-localhost}

step() {  # step <name> <timeout> <log> <cmd...>
    local name=$1 tmo=$2 log=$3; shift 3
    [ -e "$MARK/$name" ] && return 0
    echo "$(date -u +%H:%M:%S) step $name starting (rev $REV)" | tee -a /tmp/tunnel_watch.log
    # -k: a python wedged in the tunnel plugin can ignore TERM; without
    # the KILL fallback `timeout` waits on it forever and the watcher
    # stalls mid-iteration
    timeout -k 30 "$tmo" "$@" > "$log" 2>&1
    local rc=$?
    echo "$(date -u +%H:%M:%S) step $name exit $rc (log: $log)" | tee -a /tmp/tunnel_watch.log
    tail -1 "$log" | tee -a /tmp/tunnel_watch.log
    [ $rc -eq 0 ] && touch "$MARK/$name"
    return $rc
}

publish_bench() {  # publish_bench <log>
    # Persist the captured on-chip bench line as a repo artifact so a
    # mid-round window survives even if the driver's end-of-round probe
    # misses the next window (the driver commits uncommitted files).
    # captured_rev records BOTH the nearest commit (human-locatable
    # provenance) and the content hash the markers are keyed on.
    python - "$1" "$(git rev-parse --short HEAD 2>/dev/null || echo norev).$REV" <<'EOF'
import json, sys, time
lines = [l for l in open(sys.argv[1]) if l.startswith('{"metric"')]
if lines:
    rec = json.loads(lines[-1])
    rec["captured_at"] = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    rec["captured_rev"] = sys.argv[2]
    with open("BENCH_tpu_window.json", "w") as f:
        f.write(json.dumps(rec) + "\n")
    print("published BENCH_tpu_window.json:", json.dumps(rec))
EOF
}

for i in $(seq 1 600); do
    # re-key markers every iteration: an edit to the measured code
    # invalidates earlier captures and the steps re-run on the next
    # window.  The key is a pure CONTENT hash of the code paths (tracked
    # + untracked working-tree contents) — deliberately NOT the git HEAD
    # rev, so committing docs/reports (or committing the very code that
    # ran, unchanged) never discards a capture; only changing what a
    # capture measures does.
    CODE="crdt_tpu scripts bench.py __graft_entry__.py"
    REV=$( { git ls-files -z -- $CODE 2>/dev/null; \
             git ls-files -o --exclude-standard -z -- $CODE 2>/dev/null; } \
           | LC_ALL=C sort -z | xargs -0 cat 2>/dev/null | sha1sum | cut -c1-12 )
    MARK=/tmp/tw_done.$REV
    mkdir -p "$MARK"
    if timeout -k 15 150 python -c "import jax; jax.devices()" >/dev/null 2>&1; then
        echo "$(date -u +%H:%M:%S) tunnel ALIVE - capturing (rev $REV)" | tee -a /tmp/tunnel_watch.log
        step profile 2400 /tmp/profile_tpu.log \
            python scripts/profile_stages.py
        # AOT-bridge probe EARLY and CHEAP: can locally-compiled
        # executables be deserialized into the axon client at all?
        # (scripts/aot_exec_bridge.py — bypasses the remote-compile
        # helper's size limits).  tiny + merge4 only; the big loads run
        # after the bench so an unknown plugin code path cannot cost the
        # jnp captures.  A completed attempt exits 0 (conclusive, marker
        # stamps) whatever the verdict; the big loads are gated on the
        # bridge's probe_ok file, written only on a fully-green tiny
        # load.
        if [ -e /tmp/aot_exec/tiny.pkl ]; then
            step aot_probe 600 /tmp/aot_probe_tpu.log bash -c \
                "python scripts/aot_exec_bridge.py load tiny && \
                 { [ ! -e /tmp/aot_exec/merge4.pkl ] || \
                   python scripts/aot_exec_bridge.py load merge4; }"
        fi
        # the 7-mode layout A/B concluded in the 2026-07-31 window
        # (reports/LAYOUT_AB_TPU.md — unrolled default, lanes deleted);
        # re-running the full suite would burn ~90 min of a window, so
        # only the still-undecided fold-shape contenders stay (outer
        # timeout covers all three inner 1500s mode timeouts)
        step experiments 5000 /tmp/experiments_tpu.log \
            env CRDT_EXP_MODES=fold_seq,fold_tree,fold_seq_rank \
            python scripts/tpu_experiments.py
        # publish only when this iteration actually ran the bench (marker
        # absent before the call) — a marker short-circuit must not
        # re-stamp the artifact's capture time
        # PROBE_TIMEOUT back at the old 900s ladder inside a window: the
        # watcher's aliveness gate only proved jax.devices(), but the
        # bench probe also needs a tiny dispatch — on a live-but-slow
        # window the new 120s default could misclassify the backend as
        # wedged and burn the whole window on a CPU fallback
        if [ ! -e "$MARK/bench" ] && step bench 4500 /tmp/bench_tpu3.log \
            env CRDT_SKIP_TPU_VALIDATE=1 CRDT_BENCH_BUDGET_S=4200 \
            CRDT_BENCH_PROBE_TIMEOUT=900 \
            python bench.py; then
            publish_bench /tmp/bench_tpu3.log 2>&1 | tee -a /tmp/tunnel_watch.log
        fi
        step validate_merge 900 /tmp/validate_merge_tpu.log \
            python scripts/tpu_validate.py --merge
        # distill the captures into a committable decision report (the
        # driver commits uncommitted files at round end, so the analysis
        # survives even if no builder session sees this window).  Only
        # logs whose marker exists for THIS rev are fed in — a stale
        # /tmp bench log from an older build must not color the verdict.
        if [ -e "$MARK/experiments" ]; then
            BLOG=/dev/null
            [ -e "$MARK/bench" ] && BLOG=/tmp/bench_tpu3.log
            python scripts/layout_decision.py /tmp/experiments_tpu.log \
                "$BLOG" >> /tmp/tunnel_watch.log 2>&1 || true
        fi
        # the big jnp AOT-bridge load after the jnp captures are banked:
        # scan_ns is the program the helper 500s on.  No Mosaic inside —
        # safe before the Pallas block.  Only attempted if the cheap
        # probe proved the deserialize path works.
        if [ -e /tmp/aot_exec/probe_ok ] && [ -e /tmp/aot_exec/scan_ns.pkl ]; then
            step aot_scan 2400 /tmp/aot_scan_tpu.log \
                python scripts/aot_exec_bridge.py load scan_ns
        fi
        # Compiled-Pallas attempts LAST: a Mosaic crash can wedge the
        # remote compile helper / device for the rest of the window.
        # Workaround env from the captured failure log
        # (PALLAS_TPU_ATTEMPT.txt:12-14).
        step pallas 1800 /tmp/pallas_tpu.log \
            env TPU_ACCELERATOR_TYPE=v5litepod-1 TPU_WORKER_HOSTNAMES=localhost \
            python scripts/tpu_validate.py --pallas
        # pairwise compiled-Mosaic contender, also crash-risky
        step experiments_pallas 1800 /tmp/experiments_pallas_tpu.log \
            env CRDT_EXP_MODES=merge_pallas \
            python scripts/tpu_experiments.py
        # compiled-Mosaic EXECUTION via the AOT bridge — the headline
        # candidate but also the least-known plugin code path: very last
        # so a crash cannot cost any other capture this window.
        if [ -e /tmp/aot_exec/probe_ok ] && [ -e /tmp/aot_exec/pallas_scan_ns.pkl ]; then
            step aot_pallas_scan 2400 /tmp/aot_pallas_scan_tpu.log \
                python scripts/aot_exec_bridge.py load pallas_scan_ns
        fi
        # fold any green bridge verdicts into BENCH_tpu_window.json NOW —
        # the bench that would promote them ran earlier in this window,
        # and the next window may never come (idempotent, headline can
        # only go up; bench.py's banked-seed path then carries it into
        # the driver artifact)
        timeout -k 15 120 python scripts/publish_bridge_capture.py \
            >> /tmp/tunnel_watch.log 2>&1 || true
        # done only when every step whose precondition exists has its
        # marker — including the AOT loads, so a window that closes
        # mid-load leaves them to retry next window
        AOT_OK=1
        [ -e /tmp/aot_exec/tiny.pkl ] && [ ! -e "$MARK/aot_probe" ] && AOT_OK=0
        [ -e /tmp/aot_exec/probe_ok ] && [ -e /tmp/aot_exec/scan_ns.pkl ] && \
            [ ! -e "$MARK/aot_scan" ] && AOT_OK=0
        [ -e /tmp/aot_exec/probe_ok ] && [ -e /tmp/aot_exec/pallas_scan_ns.pkl ] && \
            [ ! -e "$MARK/aot_pallas_scan" ] && AOT_OK=0
        if [ -e "$MARK/profile" ] && [ -e "$MARK/experiments" ] && \
           [ -e "$MARK/bench" ] && \
           [ -e "$MARK/validate_merge" ] && [ -e "$MARK/pallas" ] && \
           [ -e "$MARK/experiments_pallas" ] && [ "$AOT_OK" = 1 ]; then
            echo "$(date -u +%H:%M:%S) all captures done (rev $REV)" | tee -a /tmp/tunnel_watch.log
            exit 0
        fi
    else
        echo "$(date -u +%H:%M:%S) tunnel down (attempt $i)" >> /tmp/tunnel_watch.log
    fi
    sleep 60
done
