"""Stage-level profile of the ORSWOT merge kernel at config-4 shapes.

Times each internal stage of ``orswot_ops.merge`` in isolation (each stage
jitted on its own) plus the fused whole, and reports bytes-moved estimates
so the dominant cost is visible.  Run on CPU or TPU:

    JAX_PLATFORMS=cpu python scripts/profile_orswot.py
"""

from __future__ import annotations

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

# the ambient axon site-hook registers its backend regardless of the
# JAX_PLATFORMS env var; the live config knob is the reliable override
if os.environ.get("CRDT_PROFILE_PLATFORM", "cpu") == "cpu":
    jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp

from crdt_tpu.ops import clock_ops, orswot_ops
from crdt_tpu.utils.testdata import random_orswot_arrays


def timeit(fn, *args, iters=5):
    out = fn(*args)
    jax.block_until_ready(out)
    ts = []
    for _ in range(iters):
        t0 = time.perf_counter()
        out = fn(*args)
        jax.block_until_ready(out)
        ts.append(time.perf_counter() - t0)
    return float(np.median(ts))


def main():
    n, a, m, d = 100_000, 16, 8, 4
    rng = np.random.RandomState(1)
    L = tuple(jnp.asarray(x) for x in random_orswot_arrays(rng, n, a, m, d))
    R = tuple(jnp.asarray(x) for x in random_orswot_arrays(rng, n, a, m, d))
    clock_a, ids_a, dots_a, dids_a, dclocks_a = L
    clock_b, ids_b, dots_b, dids_b, dclocks_b = R

    print(f"backend={jax.default_backend()} n={n} A={a} M={m} D={d} dtype={dots_a.dtype}")

    t = timeit(jax.jit(lambda L, R: orswot_ops.merge(*L, *R, m, d)[:5]), L, R)
    print(f"full merge (fast path, no deferred): {t*1e3:8.2f}ms  {n/t/1e6:6.2f}M merges/s")

    clock = clock_ops.merge(clock_a, clock_b)

    t = timeit(
        jax.jit(
            lambda L, R: orswot_ops._merge_narrow_fast(clock, *L, *R, m, d)
        ),
        L,
        R,
    )
    print(f"_merge_narrow_fast  (rank-select)  : {t*1e3:8.2f}ms")

    t = timeit(
        jax.jit(
            lambda L, R: orswot_ops._merge_narrow_deferred(clock, *L, *R, m, d)
        ),
        L,
        R,
    )
    print(f"_merge_narrow_deferred (full-width): {t*1e3:8.2f}ms")

    # sub-stages of the fast path
    t = timeit(jax.jit(orswot_ops._member_match), ids_a, ids_b)
    print(f"_member_match                      : {t*1e3:8.2f}ms")
    t = timeit(
        jax.jit(lambda k: orswot_ops._stable_order(k)),
        jnp.concatenate([ids_a, ids_b], axis=-1),
    )
    print(f"_stable_order (rank sort, 2M keys) : {t*1e3:8.2f}ms")

    # bytes accounting (u32): state in+out
    bpe = dots_a.dtype.itemsize
    state = n * (a * bpe + m * 4 + m * a * bpe + d * 4 + d * a * bpe)
    print(f"state bytes/side   : {state/1e6:.1f} MB (in 2x, out 1x => {3*state/1e6:.1f} MB min traffic)")
    m2 = 2 * m
    inter = n * (m2 * a * bpe * 2)  # e1+e2
    print(f"aligned intermed.  : {inter/1e6:.1f} MB")
    bigmatch = n * m * m * a * bpe
    print(f"[N,M,M,A] broadcast: {bigmatch/1e6:.1f} MB (materialized only if XLA fails to fuse)")


if __name__ == "__main__":
    main()
