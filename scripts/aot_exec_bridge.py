"""AOT-compile locally, execute through the tunnel: the helper bypass.

The axon tunnel routes every jit compile through a remote-compile HTTP
helper that rejects large programs (HTTP 413 on oversized request
bodies, HTTP 500 on the north-star scan — `reports/TPU_LATENCY.md`,
`reports/ROUND3_NOTES.md`).  But the big programs all COMPILE clean on
the local compile-only v5e topology (`reports/PALLAS_LOCAL_AOT.md`).
This bridge closes the loop:

    build:  compile a staged program against the local v5e topology
            (real Mosaic/XLA, no device needed), serialize the
            executable via jax.experimental.serialize_executable, and
            stash it with its arg/out pytrees + a code fingerprint.
    load:   on a live tunnel window, deserialize the executable into
            the axon PJRT client (no remote compile at all), run it on
            real data, check parity against small per-step programs
            that DO fit through the helper, and print chained timing.

Programs (shapes mirror bench.py's north star / BASELINE config 4):

    tiny            smoke test of the deserialize path itself
    merge4          pairwise ORSWOT merge, config-4 shapes (unrolled)
    scan_ns         bench's salted jnp scan over north-star chunk folds
                    (the program the helper 500s on)
    pallas_scan_ns  bench's prebiased fused-Pallas salted scan — the
                    compiled-Pallas headline candidate

Run one `build` at a time (libtpu takes /tmp/libtpu_lockfile).
Artifacts land in /tmp/aot_exec/ (tmpfs: rebuild after reboots).

RETIRED (round 4, 2026-08-01): the first-ever `load` attempt through a
live window failed with ``PJRT_Executable_DeserializeAndLoad: cached
executable is axon format v268602841, this build is v9`` — the axon
runtime only loads executables serialized by the axon client itself;
blobs from the local libtpu compile-only topology are format-
incompatible (reports/TPU_LATENCY.md item 7).  Kept for the build-side
technique (offline Mosaic verification, reports/PALLAS_LOCAL_AOT.md),
which remains the fast iteration loop for kernel debugging.  The
working replacements are bench.py's axon-side self-banking
(_pallas_bank_executable) and the repo-persistent JAX compilation
cache (.jax_cache/), both populated by helper compiles on live
windows.
"""
from __future__ import annotations

import json
import os
import pickle
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

ART_DIR = "/tmp/aot_exec"

# deterministic program identity: the merge-impl dispatch reads env at
# trace time and the backend default differs (cpu topology vs tpu), so
# pin the TPU choices explicitly for both build and load
PINNED_ENV = {
    "CRDT_MERGE_IMPL": os.environ.get("CRDT_MERGE_IMPL", "unrolled"),
    "CRDT_SCATTERLESS": os.environ.get("CRDT_SCATTERLESS", "1"),
}
os.environ.update(PINNED_ENV)
os.environ.setdefault("TPU_ACCELERATOR_TYPE", "v5litepod-1")
os.environ.setdefault("TPU_WORKER_HOSTNAMES", "localhost")

sys.setrecursionlimit(100000)


def _code_fingerprint() -> str:
    """Content hash over the kernel sources a staged program traces."""
    from crdt_tpu.utils.fingerprint import ops_fingerprint

    return ops_fingerprint()


# ---------------------------------------------------------------- programs


def _northstar_shapes(small: bool):
    if small:
        return dict(n=2_000, a=16, m=8, d=2, r=4, chunk=1_000, base=4, novel=1)
    return dict(n=1_250_000, a=64, m=16, d=2, r=8, chunk=62_500, base=6, novel=1)


def _program_counts(name: str, small: bool) -> dict:
    """The merge counts a program's baked-in structure embodies.

    Stored in the artifact meta at build time and used for every rate
    computation at load time: the executable's lax.scan length is fixed
    when it is compiled, so a consumer computing rates from its OWN
    constants would silently misreport if shapes drifted (advisor r3)."""
    shp = _northstar_shapes(small)
    n_chunks = max(2, shp["n"] // shp["chunk"])
    if name in ("scan_ns", "pallas_scan_ns"):
        # scan_ns folds two templates per step over n_chunks//2 steps;
        # pallas_scan_ns folds one template over n_chunks steps — both
        # execute n_chunks chunk-folds of r merges over `chunk` objects
        return {"n_chunks": n_chunks, "chunk": shp["chunk"], "r": shp["r"]}
    if name == "merge4":
        return {"n_chunks": 1, "chunk": 2_000 if small else 100_000, "r": 1}
    return {}


def _check_art_dir() -> bool:
    """Refuse pickle traffic through a directory another user could have
    planted files in (advisor r3: fixed world-writable /tmp path)."""
    try:
        st = os.stat(ART_DIR)
    except FileNotFoundError:
        return True  # build creates it with default umask below
    return st.st_uid == os.getuid() and not (st.st_mode & 0o022)


def _make_templates(jnp, shp, n_templates=2):
    """Same recipe/seed as bench.bench_north_star (bench.py)."""
    import numpy as np

    from crdt_tpu.utils.testdata import anti_entropy_fleets

    rng = np.random.RandomState(2)
    out = []
    for _ in range(n_templates):
        reps = anti_entropy_fleets(
            rng, shp["chunk"], shp["a"], shp["m"], shp["d"], shp["r"],
            base=shp["base"], novel=shp["novel"], deferred_frac=0.25,
        )
        out.append(tuple(jnp.stack([rep[k] for rep in reps]) for k in range(5)))
    return out


def _program(name: str, small: bool):
    """Returns (fn, example_args) — fn is closure-free over device data."""
    import jax.numpy as jnp
    from jax import lax

    if name == "tiny":
        def fn(x):
            return x * jnp.uint32(2) + jnp.uint32(1)

        return fn, (jnp.arange(8, dtype=jnp.uint32),)

    from crdt_tpu.ops import orswot_ops

    shp = _northstar_shapes(small)
    m, d, r = shp["m"], shp["d"], shp["r"]

    if name == "merge4":
        import numpy as np

        from crdt_tpu.utils.testdata import random_orswot_arrays

        rng = np.random.RandomState(1)
        n, a, mm, dd = (2_000, 8, 4, 2) if small else (100_000, 16, 8, 4)
        lhs = tuple(jnp.asarray(x) for x in random_orswot_arrays(rng, n, a, mm, dd))
        rhs = tuple(jnp.asarray(x) for x in random_orswot_arrays(rng, n, a, mm, dd))

        def fn(lhs, rhs):
            return orswot_ops.merge(*lhs, *rhs, mm, dd)[:5]

        return fn, (lhs, rhs)

    def fold_join(stack):
        acc = tuple(x[0] for x in stack)
        for i in range(1, r):
            acc = orswot_ops.merge(*acc, *(x[i] for x in stack), m, d)[:5]
        return orswot_ops.merge(*acc, *acc, m, d)[:5]  # defer plunger

    n_chunks = max(2, shp["n"] // shp["chunk"])

    if name == "scan_ns":
        # bench.bench_north_star's run_chunks, verbatim semantics
        def salted_fold(tpl, salt):
            return fold_join((tpl[0] ^ salt,) + tpl[1:])

        def next_salt(acc):
            return (jnp.max(acc[2]) & jnp.uint32(7)) | jnp.uint32(1)

        def fn(t0_, t1_):
            def body(carry, _):
                salt, _prev = carry
                o0 = salted_fold(t0_, salt)
                o1 = salted_fold(t1_, next_salt(o0))
                return (next_salt(o1), o1), None

            init = (jnp.uint32(1), tuple(x[0] for x in t0_))
            (_salt, out), _ = lax.scan(body, init, None, length=n_chunks // 2)
            return out

        t0_, t1_ = _make_templates(jnp, shp)
        return fn, (t0_, t1_)

    if name == "pallas_scan_ns":
        # bench.bench_pallas_north_star's run_chunks (prebiased domain)
        from crdt_tpu.ops import orswot_pallas

        def fold_biased(stack):
            return orswot_pallas.fold_merge(
                *stack, m, d, interpret=False, prebiased=True
            )[:5]

        def next_salt(acc):
            return (jnp.max(acc[2]).astype(jnp.int32) & jnp.int32(7)) | jnp.int32(1)

        def fn(tpl_):
            def body(carry, _):
                salt, _prev = carry
                o = fold_biased((tpl_[0] ^ salt,) + tpl_[1:])
                return (next_salt(o), o), None

            init = (jnp.int32(1), tuple(x[0] for x in tpl_))
            (_salt, out), _ = lax.scan(body, init, None, length=n_chunks)
            return out

        (tpl,) = _make_templates(jnp, shp, n_templates=1)
        biased = orswot_pallas.to_kernel_domain(
            orswot_pallas.pad_to_tile(tpl, m, d, n_states=r + 1)
        )
        return fn, (biased,)

    raise SystemExit(f"unknown program {name!r}")


# ------------------------------------------------------------- build / load


def build(name: str, small: bool):
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)

    from jax.experimental import topologies
    from jax.experimental.serialize_executable import serialize
    from jax.sharding import SingleDeviceSharding

    fn, args = _program(name, small)
    topo = topologies.get_topology_desc("v5e:2x2", platform="tpu")
    sh = SingleDeviceSharding(topo.devices[0])
    shaped = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=sh), args
    )
    t0 = time.time()
    compiled = jax.jit(fn).trace(*shaped).lower().compile()
    t_compile = time.time() - t0
    payload, in_tree, out_tree = serialize(compiled)
    os.makedirs(ART_DIR, exist_ok=True)
    path = os.path.join(ART_DIR, f"{name}{'_small' if small else ''}.pkl")
    with open(path, "wb") as f:
        pickle.dump(
            {
                "payload": payload,
                "in_tree": in_tree,
                "out_tree": out_tree,
                "meta": {
                    "program": name,
                    "small": small,
                    "env": PINNED_ENV,
                    "tile": os.environ.get("CRDT_PALLAS_TILE", "auto"),
                    "code": _code_fingerprint(),
                    "jax": jax.__version__,
                    "compile_s": round(t_compile, 1),
                    "counts": _program_counts(name, small),
                },
            },
            f,
        )
    print(
        json.dumps(
            {
                "built": name,
                "path": path,
                "bytes": os.path.getsize(path),
                "compile_s": round(t_compile, 1),
                "code": _code_fingerprint(),
            }
        ),
        flush=True,
    )


def load(name: str, small: bool):
    """Exit codes define the watcher's retry economics:

    * 0 — CONCLUSIVE: the load+execute attempt completed (whatever the
      parity verdict) OR the deserialize path was refused 3 windows in a
      row (recorded as given up).  The watcher stamps its marker and
      stops retrying.  /tmp/aot_exec/probe_ok is touched only on a
      fully-green tiny/merge4 probe — the gate for the big loads.
    * 1 — retry-worthy: missing artifact, non-TPU backend, or a
      (possibly transient) deserialize/execute failure.
    """
    import numpy as np

    import jax
    import jax.numpy as jnp

    jax.config.update("jax_enable_x64", True)
    from jax.experimental.serialize_executable import deserialize_and_load

    path = os.path.join(ART_DIR, f"{name}{'_small' if small else ''}.pkl")
    if not os.path.exists(path):
        print(json.dumps({"loaded": name, "error": f"no artifact at {path}"}))
        return 1
    if not _check_art_dir():
        print(json.dumps({"loaded": name,
                          "error": f"{ART_DIR} not exclusively ours; refusing "
                                   "to unpickle"}))
        return 1
    with open(path, "rb") as f:
        art = pickle.load(f)
    stale = art["meta"]["code"] != _code_fingerprint()

    backend = jax.default_backend()
    result = {
        "loaded": name,
        "backend": backend,
        "stale_code": stale,
        "artifact_bytes": os.path.getsize(path),
    }
    if backend != "tpu":
        result["error"] = "default backend is not tpu; nothing to prove"
        print(json.dumps(result), flush=True)
        return 1

    refusal_marker = os.path.join(ART_DIR, "probe_refusals")

    def _refusal_giveup():
        # a definitive plugin-side refusal looks identical to a transient
        # one; give the probe 3 windows before declaring it conclusive so
        # the watcher can finish instead of retrying forever
        if name != "tiny":
            return False
        count = 1
        if os.path.exists(refusal_marker):
            with open(refusal_marker) as f:
                count = int(f.read().strip() or 0) + 1
        with open(refusal_marker, "w") as f:
            f.write(str(count))
        return count >= 3

    try:
        t0 = time.time()
        compiled = deserialize_and_load(
            art["payload"], art["in_tree"], art["out_tree"], backend="tpu"
        )
        result["deserialize_s"] = round(time.time() - t0, 2)
        if os.path.exists(refusal_marker):
            os.remove(refusal_marker)  # the path works; reset give-up count
    except Exception as e:  # the capture IS the result if the plugin refuses
        result["error"] = f"deserialize_and_load: {type(e).__name__}: {str(e)[:300]}"
        if _refusal_giveup():
            result["gave_up"] = True
            print(json.dumps(result), flush=True)
            return 0
        print(json.dumps(result), flush=True)
        return 1

    fn, args = _program(name, small)
    flat_args = jax.device_put(args)
    try:
        t0 = time.time()
        out = compiled(*flat_args)
        jax.block_until_ready(out)
        result["first_exec_s"] = round(time.time() - t0, 2)
    except Exception as e:
        result["error"] = f"execute: {type(e).__name__}: {str(e)[:300]}"
        if _refusal_giveup():
            result["gave_up"] = True
            print(json.dumps(result), flush=True)
            return 0
        print(json.dumps(result), flush=True)
        return 1

    # parity: the same math as small per-step programs that fit through
    # the remote-compile helper
    try:
        if name == "tiny":
            want = np.asarray(flat_args[0]) * 2 + 1
            ok = bool(np.array_equal(np.asarray(out), want))
        elif name == "merge4":
            from crdt_tpu.ops import orswot_ops

            mm, dd = (4, 2) if small else (8, 4)
            want = jax.jit(
                lambda l, r: orswot_ops.merge(*l, *r, mm, dd)[:5]
            )(*flat_args)
            ok = all(
                bool(jnp.array_equal(g, w)) for g, w in zip(out, want)
            )
        else:
            # replay the salt chain per-step (separately compiled small
            # programs); bit-equality doubles as a work-elision check
            ok = _stepped_parity(name, small, flat_args, out,
                                 compiled=compiled)
        if isinstance(ok, str) and ok.startswith("determinism:"):
            # per-step oracle unavailable (helper rejected it); the
            # loaded program re-executed bit-equal — determinism floor
            result["parity"] = None
            result["determinism"] = ok == "determinism:True"
        else:
            result["parity"] = bool(ok)
    except Exception as e:
        result["parity"] = None
        result["parity_error"] = f"{type(e).__name__}: {str(e)[:300]}"

    # chained timing: re-run the loaded executable; dispatch-chain with a
    # scalar fetch at the end (the executable is one program — sync once)
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        out = compiled(*flat_args)
        np.asarray(jax.tree_util.tree_leaves(out)[0].ravel()[0])
        times.append(time.perf_counter() - t0)
    t = float(np.median(times))
    result["exec_s"] = round(t, 3)
    # rate from the ARTIFACT's own baked-in counts (meta written at build
    # time), never from this process's constants — see _program_counts
    counts = art["meta"].get("counts") or _program_counts(name, small)
    if counts:
        merges = counts["n_chunks"] * counts["chunk"] * counts["r"]
        result["merges_per_sec"] = round(merges / t, 1)
        result["counts"] = counts
    print(json.dumps(result), flush=True)
    # persist the verdict beside the artifact: bench.py's bridge-headline
    # path consumes it (only a parity-true verdict BOUND to this exact
    # artifact's fingerprint lets the driver's bench deserialize instead
    # of compiling)
    result["artifact_code"] = art["meta"]["code"]
    suffix = "_small" if small else ""
    with open(os.path.join(ART_DIR, f"{name}{suffix}.verdict.json"), "w") as f:
        f.write(json.dumps(result) + "\n")
    # a fully-green tiny probe opens the gate for the big loads
    if name == "tiny" and result.get("parity") is True:
        open(os.path.join(ART_DIR, "probe_ok"), "w").close()
    return 0  # the attempt completed: conclusive either way


def _stepped_parity(name, small, args, scan_out, compiled=None):
    """Replay the scan's salt chain as per-step jit dispatches.

    Returns a bool verdict, or the string ``"determinism:<bool>"`` when
    the per-step oracle itself cannot compile through the helper and the
    fallback (re-execute the LOADED program, demand bit-equality) ran.
    """
    import jax
    import jax.numpy as jnp

    from crdt_tpu.ops import orswot_ops

    shp = _northstar_shapes(small)
    m, d, r = shp["m"], shp["d"], shp["r"]
    n_chunks = max(2, shp["n"] // shp["chunk"])

    def fold_join(stack):
        acc = tuple(x[0] for x in stack)
        for i in range(1, r):
            acc = orswot_ops.merge(*acc, *(x[i] for x in stack), m, d)[:5]
        return orswot_ops.merge(*acc, *acc, m, d)[:5]

    if name == "scan_ns":
        t0_, t1_ = args

        sf = jax.jit(lambda tpl, salt: fold_join((tpl[0] ^ salt,) + tpl[1:]))
        ns = jax.jit(lambda acc: (jnp.max(acc[2]) & jnp.uint32(7)) | jnp.uint32(1))
        salt = jnp.uint32(1)
        out = None
        for _ in range(n_chunks // 2):
            o0 = sf(t0_, salt)
            o1 = sf(t1_, ns(o0))
            salt = ns(o1)
            out = o1
    elif name == "pallas_scan_ns":
        # the jnp stepped fold in the UNBIASED domain is the oracle; the
        # loaded executable's output converts back for comparison
        from crdt_tpu.ops import orswot_pallas

        (biased,) = args
        sf = jax.jit(
            lambda tpl, salt: orswot_pallas.fold_merge(
                *((tpl[0] ^ salt,) + tpl[1:]), m, d, prebiased=True
            )[:5]
        )
        # per-step Pallas through the helper may itself fail (that is the
        # point of the bridge) — fall back to comparing two executions of
        # the LOADED program (determinism floor) if the helper rejects it
        ns = jax.jit(
            lambda acc: (jnp.max(acc[2]).astype(jnp.int32) & jnp.int32(7))
            | jnp.int32(1)
        )
        try:
            salt = jnp.int32(1)
            out = None
            for _ in range(n_chunks):
                out = sf(biased, salt)
                salt = ns(out)
        except Exception:
            if compiled is None:
                return None
            rerun = compiled(*args)
            jax.block_until_ready(rerun)
            same = all(
                bool(jnp.array_equal(g, w)) for g, w in zip(scan_out, rerun)
            )
            return f"determinism:{same}"
    else:
        return None
    return all(bool(jnp.array_equal(g, w)) for g, w in zip(scan_out, out))


def main():
    argv = [a for a in sys.argv[1:] if not a.startswith("--")]
    small = "--small" in sys.argv
    if len(argv) != 2 or argv[0] not in ("build", "load"):
        print(__doc__)
        raise SystemExit(2)
    cmd, name = argv
    if cmd == "build":
        build(name, small)
    else:
        raise SystemExit(load(name, small))


if __name__ == "__main__":
    main()
