#!/usr/bin/env python
"""crdtlint entry point — identical to ``python -m crdt_tpu.analysis``.

Both tiers: the default stdlib-only AST lint, and ``--kernels`` for the
jaxpr tier (kernelcheck, KC01-KC05 — imports jax under
``JAX_PLATFORMS=cpu``; see PERF.md "Kernel contracts").

Kept as a script so CI configs and editors can point at a file; all
logic lives in :mod:`crdt_tpu.analysis.__main__`.  Works from any CWD:
the repo root is derived from this file's location, not the caller's.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from crdt_tpu.analysis.__main__ import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
