"""Turn a captured TPU layout A/B into a committed decision report.

The tunnel watcher (`scripts/tunnel_watch.sh`) runs this after its capture
steps succeed.  It parses the A/B menu output (`RESULT <mode>: ... ms`),
the bench log's JSON line, applies the decision rule from
`reports/ORSWOT_PROFILE.md` ("Layout candidates staged for the next tunnel
window"), and writes `reports/LAYOUT_AB_TPU.md` with the ranked table and
the EXACT flip to make — so a window that opens with no builder session
attached still produces an actionable, committable analysis artifact (the
driver commits uncommitted files at round end).

The flip itself is deliberately NOT automated: a detached process must not
edit kernel source mid-round.

Usage: python scripts/layout_decision.py [experiments_log] [bench_log]
       (defaults: the watcher's /tmp paths)
"""

from __future__ import annotations

import json
import os
import re
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# the pairwise-merge contenders the decision rule ranks (everything else in
# the menu — gathers, scatters, sort primitives — is diagnostic context)
MERGE_MODES = ("merge_scatter", "merge_scatterless", "merge_unrolled")
# mode -> the one-line change that makes it the TPU default
FLIP = {
    "merge_scatter": (
        "crdt_tpu/ops/orswot_ops.py::_scatterless_default — return False "
        "(one-hot sum is the default everywhere since the r3 CPU A/B)"
    ),
    "merge_scatterless": (
        "no change (one-hot sum is already the default on every backend "
        "via orswot_ops._scatterless_default)"
    ),
    "merge_unrolled": (
        "no change (unrolled is already the TPU default via "
        "orswot_ops._merge_impl_default since the r3 on-chip A/B; "
        "the lanes-last contender lost 2x and was deleted)"
    ),
}


def _row(mode, ms):
    return f"| {mode} | {'FAILED/TIMEOUT' if ms is None else f'{ms:.2f}'} |"


def parse_results(path):
    """``RESULT <mode>: <float> ms...`` lines -> {mode: ms | None}."""
    out = {}
    if not os.path.exists(path):
        return out
    for line in open(path, errors="replace"):
        m = re.match(r"RESULT (\w+): ([0-9.]+) ms", line)
        if m:
            out[m.group(1)] = float(m.group(2))
        else:
            m = re.match(r"RESULT (\w+): (FAILED|TIMEOUT)", line)
            if m:
                out[m.group(1)] = None
    return out


def parse_bench(path):
    """Last ``{"metric": ...}`` JSON line of a bench log, or None."""
    if not os.path.exists(path):
        return None
    rec = None
    for line in open(path, errors="replace"):
        if line.startswith('{"metric"'):
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                pass
    return rec


def main():
    args = sys.argv[1:]
    exp_log = args[0] if len(args) > 0 else "/tmp/experiments_tpu.log"
    bench_log = args[1] if len(args) > 1 else "/tmp/bench_tpu3.log"

    results = parse_results(exp_log)
    bench = parse_bench(bench_log)

    merge_rows = [(m, results.get(m)) for m in MERGE_MODES if m in results]
    ranked = sorted(
        (r for r in merge_rows if r[1] is not None), key=lambda r: r[1]
    )
    out_path = os.path.join(REPO, "reports", "LAYOUT_AB_TPU.md")
    if not merge_rows and os.path.exists(out_path):
        # A capture with no merge contenders (e.g. the fold-only
        # experiment menu after the A/B concluded) must not clobber the
        # committed merge-layout decision with "no decision" — but the
        # fold results themselves still need a committable artifact (a
        # window can open with no builder session attached; /tmp does not
        # survive the round).
        fold_path = os.path.join(REPO, "reports", "FOLD_AB_TPU.md")
        lines = [
            "# TPU fold-shape A/B — capture",
            "",
            f"Generated {time.strftime('%Y-%m-%dT%H:%M:%SZ', time.gmtime())} "
            f"by `scripts/layout_decision.py` from "
            f"`{exp_log}`.  Merge-layout decision unchanged — see "
            "`LAYOUT_AB_TPU.md`.",
            "",
            "| mode | ms |",
            "|---|---|",
        ]
        lines += [_row(mode, ms) for mode, ms in sorted(results.items())]
        with open(fold_path, "w") as f:
            f.write("\n".join(lines) + "\n")
        print(f"no merge contenders in {exp_log}; wrote {fold_path}, "
              f"keeping existing {out_path}")
        return

    lines = [
        "# TPU layout A/B — decision report",
        "",
        f"Generated {time.strftime('%Y-%m-%dT%H:%M:%SZ', time.gmtime())} by "
        "`scripts/layout_decision.py` from the tunnel watcher's captures "
        f"(`{exp_log}`).  Decision rule: `reports/ORSWOT_PROFILE.md` "
        '"Layout candidates staged for the next tunnel window".',
        "",
        "## Pairwise-merge contenders (config-4 shapes)",
        "",
        "| mode | ms/merge |",
        "|---|---|",
    ]
    lines += [_row(mode, ms) for mode, ms in merge_rows]
    if ranked:
        winner = ranked[0][0]
        lines += [
            "",
            f"**Winner: `{winner}`"
            + (
                f" ({ranked[0][1]:.2f} ms vs runner-up {ranked[1][1]:.2f} ms)"
                if len(ranked) > 1
                else ""
            )
            + ".**",
            "",
            f"Flip to apply: {FLIP[winner]}",
        ]
    else:
        lines += ["", "**No merge contender completed — no decision.**"]

    diag = {m: v for m, v in results.items() if m not in MERGE_MODES}
    if diag:
        lines += ["", "## Diagnostic modes", "", "| mode | ms |", "|---|---|"]
        lines += [_row(mode, ms) for mode, ms in sorted(diag.items())]

    lines += ["", "## North-star fold (bench captures)", ""]
    if bench is None:
        lines.append("* default fold: no captured JSON line")
    else:
        lines.append(
            f"* default fold: {bench.get('value', '?')} {bench.get('unit', '')} on "
            f"platform={bench.get('platform')} "
            f"(vs_baseline {bench.get('vs_baseline')})"
        )

    # standing record — regenerated with every report so a watcher rerun
    # can never destroy the rationale for decisions already applied
    lines += [
        "",
        "## Pruning applied (round 3)",
        "",
        'Per the round-2 verdict ("the layout A/B must conclude in round 3',
        'and losers must be deleted or demoted"), from the 2026-07-31 on-chip',
        "captures (config-4: scatter 64.42 / scatterless 57.73 / unrolled",
        "54.03 / lanes 120.07 ms):",
        "",
        "* **`merge_lanes` / the lanes-last layout: DELETED** (module",
        "  trimmed to `crdt_tpu/ops/orswot_unrolled.py`).  2× loss at",
        "  config-4 rules it out; the boundary transposes and broadcast",
        "  selects cost more than the lane under-utilization they recover.",
        "  `CRDT_LANES` bench path, `fold_merge_t`, `to_lanes`/`from_lanes`,",
        "  and their tests removed with it.",
        "* **`merge_unrolled`: TPU default** via",
        "  `orswot_ops._merge_impl_default` (54.03 ms vs rank 57.73 ms).",
        "  CPU default stays `rank` (unrolled measured 17% slower there).",
        "* **scatter rank-inversion**: already non-default everywhere; kept",
        "  behind `CRDT_SCATTERLESS=0` as the A/B control.",
        "* Diagnostic gather modes (take/onehot/mxu/mxu8) measured within 2%",
        "  of each other — the gather primitive is NOT the dominant cost at",
        "  config-4 shapes, redirecting the roofline investigation toward",
        "  the stage profile (`scripts/profile_stages.py`).",
    ]

    os.makedirs(os.path.dirname(out_path), exist_ok=True)
    with open(out_path, "w") as f:
        f.write("\n".join(lines) + "\n")
    print(f"wrote {out_path}")
    for line in lines:
        print(line)


if __name__ == "__main__":
    main()
