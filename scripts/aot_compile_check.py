"""Local v5e AOT compile checks — no TPU device or tunnel needed.

Builds a compile-only PJRT TPU topology from the local libtpu and runs
the REAL Mosaic/XLA compile pipeline on the framework's hot programs,
printing compile time and the executable's memory plan.  This is the
loop that broke the two-round compiled-Pallas barrier and caught a
17.3 GB memory plan before it could OOM a 16 GB chip — see
`reports/PALLAS_LOCAL_AOT.md` for findings and caveats (notably: libtpu
takes `/tmp/libtpu_lockfile`, so run one instance at a time).

    python scripts/aot_compile_check.py merge      # pairwise Pallas merge
    python scripts/aot_compile_check.py fold       # small fused fold (r=4)
    python scripts/aot_compile_check.py fold_ns    # north-star fold (r=8, 62.5k)
    python scripts/aot_compile_check.py scan_ns    # bench's prebiased salted scan
    python scripts/aot_compile_check.py jnp_ns     # jnp chunk-fold (HLO stats)

Honors CRDT_PALLAS_TILE for tile experiments.
"""
from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("TPU_ACCELERATOR_TYPE", "v5litepod-1")
os.environ.setdefault("TPU_WORKER_HOSTNAMES", "localhost")

sys.setrecursionlimit(100000)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp  # noqa: E402
from jax import lax  # noqa: E402
from jax.experimental import topologies  # noqa: E402
from jax.sharding import SingleDeviceSharding  # noqa: E402


def _topology_sharding():
    # "v5e:1x1" is rejected (not divisible by the default 2x2x1
    # chips-per-host bounds); 2x2 compiles the identical single-core
    # program
    topo = topologies.get_topology_desc("v5e:2x2", platform="tpu")
    return SingleDeviceSharding(topo.devices[0])


def _report(lowered):
    t0 = time.time()
    compiled = lowered.compile()
    dt = time.time() - t0
    ma = compiled.memory_analysis()
    total = ma.argument_size_in_bytes + ma.temp_size_in_bytes + ma.output_size_in_bytes
    print(f"COMPILE_OK in {dt:.1f}s")
    print(
        f"memory plan: args {ma.argument_size_in_bytes/1e9:.2f} GB  "
        f"temp {ma.temp_size_in_bytes/1e9:.2f} GB  "
        f"out {ma.output_size_in_bytes/1e9:.2f} GB  "
        f"TOTAL {total/1e9:.2f} GB  (v5e HBM: 16 GB)"
    )
    return compiled


def _stack_specs(sh, r, n, a, m, d, dtype):
    return (
        jax.ShapeDtypeStruct((r, n, a), dtype, sharding=sh),
        jax.ShapeDtypeStruct((r, n, m), jnp.int32, sharding=sh),
        jax.ShapeDtypeStruct((r, n, m, a), dtype, sharding=sh),
        jax.ShapeDtypeStruct((r, n, d), jnp.int32, sharding=sh),
        jax.ShapeDtypeStruct((r, n, d, a), dtype, sharding=sh),
    )


def main():
    which = sys.argv[1] if len(sys.argv) > 1 else "fold_ns"
    sh = _topology_sharding()
    from crdt_tpu.ops import orswot_pallas

    if which == "merge":
        # default = the merge_pallas experiment's config-4 shapes
        # (scripts/tpu_experiments.py); override with CRDT_AOT_SHAPE=n,a,m,d
        shape = os.environ.get("CRDT_AOT_SHAPE", "100000,16,8,4")
        n, a, m, d = (int(x) for x in shape.split(","))
        side = (
            jax.ShapeDtypeStruct((n, a), jnp.uint32, sharding=sh),
            jax.ShapeDtypeStruct((n, m), jnp.int32, sharding=sh),
            jax.ShapeDtypeStruct((n, m, a), jnp.uint32, sharding=sh),
            jax.ShapeDtypeStruct((n, d), jnp.int32, sharding=sh),
            jax.ShapeDtypeStruct((n, d, a), jnp.uint32, sharding=sh),
        )
        lowered = jax.jit(
            lambda L, R: orswot_pallas.merge(*L, *R, m, d, interpret=False)
        ).trace(side, side).lower()
        _report(lowered)
        return

    if which == "fold":
        r, n, a, m, d = 4, 4096, 16, 8, 2
    else:
        r, n, a, m, d = 8, 62_500, 64, 16, 2

    if which in ("fold", "fold_ns"):
        shaped = _stack_specs(sh, r, n, a, m, d, jnp.uint32)
        lowered = jax.jit(
            lambda *s: orswot_pallas.fold_merge(*s, m, d, interpret=False)
        ).trace(*shaped).lower()
        _report(lowered)
        return

    if which in ("fold_aligned", "fold_aligned_ns"):
        from crdt_tpu.ops import orswot_fold_aligned

        if which == "fold_aligned":
            r, n, a, m, d = 4, 4096, 16, 8, 2
        u_cap = int(os.environ.get("CRDT_AOT_UCAP", str(m)))
        shaped = _stack_specs(sh, r, n, a, m, d, jnp.uint32)
        lowered = jax.jit(
            lambda *s: orswot_fold_aligned.fold_merge(
                *s, m, d, u_cap=u_cap, interpret=False
            )
        ).trace(*shaped).lower()
        _report(lowered)
        return

    if which == "scan_aligned_ns":
        # the aligned-fold version of the bench's salted prebiased scan
        from crdt_tpu.ops import orswot_fold_aligned

        u_cap = int(os.environ.get("CRDT_AOT_UCAP", str(m)))
        n_total = 1_250_000
        n_chunks = n_total // n
        t = orswot_fold_aligned._tile_size(a, m, d, r, u_cap)
        n_pad = n + ((-n) % t)
        shaped = _stack_specs(sh, r, n_pad, a, m, d, jnp.int32)
        i32 = jnp.int32

        def run_chunks(*tpl):
            def fold_biased(stack):
                return orswot_fold_aligned.fold_merge(
                    *stack, m, d, u_cap=u_cap, interpret=False, prebiased=True
                )[:5]

            def next_salt(acc):
                return (jnp.max(acc[2]).astype(i32) & i32(7)) | i32(1)

            def body(carry, _):
                salt, _prev = carry
                o = fold_biased((tpl[0] ^ salt,) + tpl[1:])
                return (next_salt(o), o), None

            init = (i32(1), tuple(x[0] for x in tpl))
            (_, out), _ = lax.scan(body, init, None, length=n_chunks)
            return out

        lowered = jax.jit(run_chunks).trace(*shaped).lower()
        _report(lowered)
        return

    if which == "scan_ns":
        # the bench's actual timed program: salted scan of prebiased
        # folds.  MIRRORS bench.py bench_pallas_north_star's run_chunks —
        # if that changes (chunk size, salt formula, scan length), update
        # this copy or its memory plan stops describing the real bench
        n_total = 1_250_000  # bench north-star object count
        n_chunks = n_total // n
        t = orswot_pallas._tile_size(a, m, d, n_states=r + 1)
        n_pad = n + ((-n) % t)
        shaped = _stack_specs(sh, r, n_pad, a, m, d, jnp.int32)
        i32 = jnp.int32

        def run_chunks(*tpl):
            def fold_biased(stack):
                return orswot_pallas.fold_merge(
                    *stack, m, d, interpret=False, prebiased=True
                )[:5]

            def next_salt(acc):
                return (jnp.max(acc[2]).astype(i32) & i32(7)) | i32(1)

            def body(carry, _):
                salt, _prev = carry
                o = fold_biased((tpl[0] ^ salt,) + tpl[1:])
                return (next_salt(o), o), None

            init = (i32(1), tuple(x[0] for x in tpl))
            (_, out), _ = lax.scan(body, init, None, length=n_chunks)
            return out

        lowered = jax.jit(run_chunks).trace(*shaped).lower()
        _report(lowered)
        return

    if which == "jnp_ns":
        os.environ.setdefault("CRDT_MERGE_IMPL", "unrolled")
        from crdt_tpu.ops import orswot_ops

        shaped = _stack_specs(sh, r, n, a, m, d, jnp.uint32)

        def fold(*stack):
            acc = tuple(x[0] for x in stack)
            for k in range(1, r):
                acc = orswot_ops.merge(*acc, *(x[k] for x in stack), m, d)[:5]
            return orswot_ops.merge(*acc, *acc, m, d)[:5]

        lowered = jax.jit(fold).trace(*shaped).lower()
        compiled = _report(lowered)
        txt = compiled.as_text()
        import re
        from collections import Counter

        ops = Counter(re.findall(r"= \S+ (\w+)\(", txt))
        print("top HLO ops:", ops.most_common(8))
        print("fusions:", txt.count("fusion("), " HLO lines:", txt.count("\n"))
        return

    raise SystemExit(f"unknown program {which!r}")


if __name__ == "__main__":
    main()
