"""One-window TPU kernel A/B menu.

The remote-TPU tunnel comes and goes; when a window opens, this script
collects every pending kernel decision in one run (chained device-side
timing throughout — reports/TPU_LATENCY.md):

  1. sequential vs tree fold at a north-star chunk (fold shape choice)
  2. scatter vs scatterless rank inversion inside the full merge
  3. counting-rank vs XLA argsort at merge slot counts
  4. u32 vs u64 counter planes (64-bit emulation cost on TPU)

Each experiment subprocesses with its own env so jit caches can't leak
between variants.  Results print as one table; exit 0 even if individual
experiments fail (a partial table beats none).
"""
from __future__ import annotations

import os
import subprocess
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)

WORKER = r'''
import os, sys, time
sys.path.insert(0, %(repo)r)
import numpy as np, jax
if os.environ.get("EXP_FORCE_CPU") == "1":
    # the ambient axon plugin overrides the JAX_PLATFORMS env var; only
    # the config knob reliably forces a local-CPU smoke run
    jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
from jax import lax
from crdt_tpu.ops import orswot_ops
from crdt_tpu.utils.testdata import anti_entropy_fleets, random_orswot_arrays

mode = os.environ["EXP_MODE"]
rng = np.random.RandomState(0)

def chain(step, init, iters, consts=()):
    # crdt_tpu.utils.benchtime.chain_timer: one jitted lax.scan, the
    # same-window sync constant subtracted, and every device array the
    # step needs flowing in as a jit parameter (a closure would inline
    # it as dense constants and the tunnel's remote-compile helper
    # rejects oversized request bodies — HTTP 413 at ~300 MB observed).
    from crdt_tpu.utils.benchtime import chain_timer

    return chain_timer(step, init, iters, consts=consts)[0]

if mode in ("fold_seq", "fold_tree", "fold_seq_rank"):
    # fold_seq_rank: the same sequential fold with CRDT_MERGE_IMPL=rank
    # (parent sets the env) — local AOT shows rank compiles to FEWER
    # kernels (583 vs 785 fusions) but MORE temp (4.8 vs 3.2 GB) at
    # north-star shapes, so the config-4 A/B verdict may not transfer
    n, a, m, d, r = 62_500, 64, 16, 2, 8
    fleets = anti_entropy_fleets(rng, n, a, m, d, r, base=6, novel=1,
                                 deferred_frac=0.25)
    stacked = tuple(jnp.stack([jnp.asarray(rep[k]) for rep in fleets])
                    for k in range(5))
    if mode == "fold_tree":
        def fold(stack):
            return orswot_ops.fold_merge_tree(*stack, m, d)[:5]
    else:
        def fold(stack):
            acc = tuple(x[0] for x in stack)
            for i in range(1, r):
                acc = orswot_ops.merge(*acc, *(x[i] for x in stack), m, d)[:5]
            return orswot_ops.merge(*acc, *acc, m, d)[:5]
    def step(carry, *stk):
        salt, _ = carry
        out = fold((stk[0] ^ salt,) + stk[1:])
        return ((jnp.max(out[2]) & jnp.uint32(7)) | jnp.uint32(1), out)
    init = (jnp.uint32(1), tuple(x[0] for x in stacked))
    t = chain(step, init, iters=4, consts=stacked)
    print(f"RESULT {mode}: {t*1e3:.1f} ms/chunk-fold "
          f"({n*r/t/1e6:.2f}M merges/s equiv)")

elif mode in ("merge_scatter", "merge_scatterless"):
    # CRDT_SCATTERLESS set by the parent
    n, a, m, d = 100_000, 16, 8, 4
    lhs = tuple(jnp.asarray(x) for x in random_orswot_arrays(rng, n, a, m, d))
    rhs = tuple(jnp.asarray(x) for x in random_orswot_arrays(rng, n, a, m, d))
    t = chain(lambda acc, *r: orswot_ops.merge(*acc, *r, m, d)[:5], lhs,
              iters=20, consts=rhs)
    print(f"RESULT {mode}: {t*1e3:.2f} ms/merge ({n/t/1e6:.2f}M merges/s)")

elif mode == "merge_unrolled":
    # gather/sort-free tile math (crdt_tpu/ops/orswot_unrolled.py) — the
    # round-3 A/B winner, kept in the menu so future windows re-validate
    # the default against the rank path
    from crdt_tpu.ops import orswot_unrolled
    n, a, m, d = 100_000, 16, 8, 4
    lhs = tuple(jnp.asarray(x) for x in random_orswot_arrays(rng, n, a, m, d))
    rhs = tuple(jnp.asarray(x) for x in random_orswot_arrays(rng, n, a, m, d))
    t = chain(
        lambda acc, *r: orswot_unrolled.merge_unrolled(*acc, *r, m, d)[:5],
        lhs, iters=20, consts=rhs,
    )
    print(f"RESULT {mode}: {t*1e3:.2f} ms/merge ({n/t/1e6:.2f}M merges/s)")

elif mode == "merge_pallas":
    # fused single-HBM-pass pairwise kernel via the CRDT_MERGE_IMPL=pallas
    # dispatch (compiled Mosaic on TPU).  Run LAST in any window: a Mosaic
    # crash can wedge the tunnel's remote-compile helper.
    n, a, m, d = 100_000, 16, 8, 4
    lhs = tuple(jnp.asarray(x) for x in random_orswot_arrays(rng, n, a, m, d))
    rhs = tuple(jnp.asarray(x) for x in random_orswot_arrays(rng, n, a, m, d))
    from crdt_tpu.ops import orswot_pallas
    t = chain(
        lambda acc, *r: orswot_pallas.merge(*acc, *r, m, d)[:5], lhs,
        iters=20, consts=rhs)
    print(f"RESULT {mode}: {t*1e3:.2f} ms/merge ({n/t/1e6:.2f}M merges/s)")

elif mode in ("order_rank", "order_argsort"):
    n, s = 200_000, 32
    keys = jnp.asarray(rng.randint(0, 1 << 20, size=(n, s)).astype(np.int32))
    if mode == "order_rank":
        def step(c):
            o = orswot_ops._stable_order(c[0])
            return (jnp.take_along_axis(c[0], o, axis=-1),)
    else:
        def step(c):
            o = jnp.argsort(c[0], axis=-1, stable=True)
            return (jnp.take_along_axis(c[0], o, axis=-1),)
    t = chain(step, (keys,), iters=20)
    print(f"RESULT {mode}: {t*1e3:.2f} ms")

elif mode in ("gather_mxu", "gather_mxu8"):
    # one-hot gather as an MXU matmul; traffic is near-minimal because
    # the MXU reuses the [S, A] operand across the 16 output slots.
    # TPU matmuls round f32 inputs to bf16 at default precision, so
    # exactness needs one of:
    #   gather_mxu  — 16-bit halves in f32 with Precision.HIGHEST
    #                 (multi-pass f32 emulation; 2 einsums)
    #   gather_mxu8 — 8-bit bytes at DEFAULT precision: 0..255 operands
    #                 and 0/1 one-hots are bf16-exact, and each output
    #                 sums exactly one nonzero product (4 einsums at
    #                 native MXU speed)
    n, s_slots, a = 62_500, 32, 64
    payload = jnp.asarray(rng.randint(0, 1 << 31, size=(n, s_slots, a)).astype(np.uint32))
    idx = jnp.asarray(rng.randint(0, s_slots, size=(n, 16)).astype(np.int32))
    onehot = (idx[..., None] == jnp.arange(s_slots)[None, None, :]).astype(jnp.float32)
    if mode == "gather_mxu":
        def step(c, oh):
            lo = (c[0] & jnp.uint32(0xFFFF)).astype(jnp.float32)
            hi = (c[0] >> 16).astype(jnp.float32)
            glo = jnp.einsum("nks,nsa->nka", oh, lo,
                             precision=jax.lax.Precision.HIGHEST)
            ghi = jnp.einsum("nks,nsa->nka", oh, hi,
                             precision=jax.lax.Precision.HIGHEST)
            g = (ghi.astype(jnp.uint32) << 16) | glo.astype(jnp.uint32)
            return (jnp.concatenate(
                [jnp.maximum(c[0][:, :16], g), c[0][:, 16:]], axis=1),)
    else:
        def step(c, oh):
            g = jnp.zeros((n, 16, a), jnp.uint32)
            for shift in (0, 8, 16, 24):
                byte = ((c[0] >> shift) & jnp.uint32(0xFF)).astype(jnp.float32)
                gb = jnp.einsum("nks,nsa->nka", oh, byte)
                g = g | (gb.astype(jnp.uint32) << shift)
            return (jnp.concatenate(
                [jnp.maximum(c[0][:, :16], g), c[0][:, 16:]], axis=1),)
    t = chain(step, (payload,), iters=20, consts=(onehot,))
    print(f"RESULT {mode}: {t*1e3:.2f} ms")

elif mode in ("gather_take", "gather_onehot", "scatter_put"):
    # primitive isolation at merge shapes: the rank-select core's gathers
    # (take_along_axis over the slot axis) and the scatter the CPU path
    # uses for rank inversion are the prime TPU-inefficiency suspects
    n, s_slots, a = 62_500, 32, 64
    payload = jnp.asarray(rng.randint(0, 1000, size=(n, s_slots, a)).astype(np.uint32))
    idx = jnp.asarray(rng.randint(0, s_slots, size=(n, 16)).astype(np.int32))
    if mode == "gather_take":
        def step(c, ix):
            g = jnp.take_along_axis(c[0], ix[..., None], axis=-2)  # [n,16,a]
            return (jnp.concatenate(
                [jnp.maximum(c[0][:, :16], g), c[0][:, 16:]], axis=1),)
        cs = (idx,)
    elif mode == "gather_onehot":
        onehot = (idx[..., None] == jnp.arange(s_slots)[None, None, :])
        def step(c, oh):
            g = jnp.einsum("nks,nsa->nka", oh.astype(jnp.uint32), c[0])
            return (jnp.concatenate([jnp.maximum(c[0][:, :16], g), c[0][:, 16:]], axis=1),)
        cs = (onehot,)
    else:  # scatter_put
        ranks = jnp.asarray(
            np.argsort(rng.rand(n, s_slots), axis=-1).astype(np.int32))
        def step(c, rk):
            iota = jnp.arange(s_slots, dtype=jnp.int32)
            perm = jnp.put_along_axis(
                jnp.zeros(rk.shape, jnp.int32), rk,
                jnp.broadcast_to(iota, rk.shape), axis=-1, inplace=False)
            return (c[0] ^ perm[..., None].astype(c[0].dtype),)
        cs = (ranks,)
    t = chain(step, (payload,), iters=20, consts=cs)
    print(f"RESULT {mode}: {t*1e3:.2f} ms")

elif mode in ("dtype_u32", "dtype_u64"):
    dt = np.uint32 if mode == "dtype_u32" else np.uint64
    n, a, m, d = 100_000, 16, 8, 4
    lhs = tuple(jnp.asarray(x) for x in random_orswot_arrays(rng, n, a, m, d, dtype=dt))
    rhs = tuple(jnp.asarray(x) for x in random_orswot_arrays(rng, n, a, m, d, dtype=dt))
    t = chain(lambda acc, *r: orswot_ops.merge(*acc, *r, m, d)[:5], lhs,
              iters=10, consts=rhs)
    print(f"RESULT {mode}: {t*1e3:.2f} ms/merge")
''' % {"repo": REPO}


def run(mode, env_extra=None, timeout=900):
    env = dict(os.environ)
    env["EXP_MODE"] = mode
    env.update(env_extra or {})
    try:
        proc = subprocess.run(
            [sys.executable, "-u", "-c", WORKER],
            timeout=timeout, capture_output=True, text=True, env=env,
        )
        for line in proc.stdout.splitlines():
            if line.startswith("RESULT"):
                print(line, flush=True)
                return
        print(f"RESULT {mode}: FAILED rc={proc.returncode} "
              f"{proc.stderr.strip().splitlines()[-1][:160] if proc.stderr.strip() else ''}",
              flush=True)
    except subprocess.TimeoutExpired:
        print(f"RESULT {mode}: TIMEOUT after {timeout}s", flush=True)


def main():
    print(f"tpu_experiments on backend env JAX_PLATFORMS="
          f"{os.environ.get('JAX_PLATFORMS')!r}", flush=True)
    menu = [
        ("merge_scatter", {"CRDT_SCATTERLESS": "0", "CRDT_MERGE_IMPL": "rank"}, 900),
        ("merge_scatterless", {"CRDT_SCATTERLESS": "1", "CRDT_MERGE_IMPL": "rank"}, 900),
        ("merge_unrolled", None, 900),
        ("order_rank", None, 900),
        ("order_argsort", None, 900),
        ("gather_take", None, 900),
        ("gather_onehot", None, 900),
        ("gather_mxu", None, 900),
        ("gather_mxu8", None, 900),
        ("scatter_put", None, 900),
        ("dtype_u32", {"CRDT_TPU_NO_X64": "0"}, 900),
        ("dtype_u64", {"CRDT_TPU_NO_X64": "0"}, 900),
        # fold impls pinned explicitly: an ambient CRDT_MERGE_IMPL would
        # otherwise turn the seq-vs-rank A/B into a self-comparison
        ("fold_seq", {"CRDT_MERGE_IMPL": "unrolled"}, 1500),
        ("fold_tree", {"CRDT_MERGE_IMPL": "unrolled"}, 1500),
        ("fold_seq_rank", {"CRDT_MERGE_IMPL": "rank"}, 1500),
        # compiled-Mosaic contender: keep LAST — a Mosaic crash can wedge
        # the tunnel's remote-compile helper for the rest of the window
        ("merge_pallas", None, 1500),
    ]
    # CRDT_EXP_MODES=comma,separated,subset restricts the menu (tunnel
    # windows are short — spend them on the undecided experiments)
    subset = os.environ.get("CRDT_EXP_MODES")
    if subset:
        wanted = set(subset.split(","))
        known = {row[0] for row in menu}
        for name in sorted(wanted - known):
            print(f"WARNING: unknown CRDT_EXP_MODES entry {name!r} "
                  f"(known: {','.join(sorted(known))})", flush=True)
        menu = [row for row in menu if row[0] in wanted]
    for mode, env_extra, timeout in menu:
        run(mode, env_extra, timeout=timeout)


if __name__ == "__main__":
    main()
