"""Probe: can the axon PJRT client serialize ITS OWN executables?

Round-4 finding (first-ever bridge load attempt): the axon runtime
rejects executables serialized by the local libtpu compile-only
topology — ``PJRT_Executable_DeserializeAndLoad: cached executable is
axon format v<garbage>, this build is v9``.  The AOT bridge
(scripts/aot_exec_bridge.py) therefore cannot ship locally-compiled
programs into the tunnel; the serialization formats are disjoint.

This probe tests the reverse direction, which the error message implies
exists: executables the axon client compiled itself (through the
remote-compile helper) should serialize in "axon format v9" and
round-trip through deserialize_and_load.  If that holds, the bridge
strategy flips: compile small-text programs (the fused Pallas scan is
one Mosaic kernel) through the helper ONCE on a live window, serialize
axon-side, stash, and every later window loads without any compile.

Also reports whether the JAX persistent compilation cache
(JAX_COMPILATION_CACHE_DIR) gained entries from the compile — if the
axon plugin participates, cross-window reuse may already be free.

Usage (live tunnel only):  python scripts/axon_serialize_probe.py
"""
from __future__ import annotations

import glob
import json
import os
import pickle
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

CACHE_DIR = os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR", os.path.join(REPO, ".jax_cache")
)
os.environ.setdefault("TPU_ACCELERATOR_TYPE", "v5litepod-1")
os.environ.setdefault("TPU_WORKER_HOSTNAMES", "localhost")

ART = "/tmp/aot_exec/axon_tiny.pkl"


# error signatures that mean "the axon runtime does not do this", as
# opposed to a transient tunnel/helper failure worth re-probing
_STRUCTURAL_MARKERS = (
    "unimplemented",
    "not supported",
    "unsupported",
    "notimplemented",
    "invalid_argument",
    "axon format",
)


def _definitive(rec: dict) -> int:
    """Decide whether a serialize/deserialize failure is the ANSWER
    (axon doesn't support it → rc=0, the watcher marks the step done)
    or a transient failure (→ rc=1, re-probe next window).  Two gates:
    the device must still run a trivial op (else the WINDOW died, not
    the feature), and the error text must carry a structural signature
    (unimplemented / unsupported / format mismatch) — a deadline or RPC
    flap on a live device is still transient."""
    import jax
    import jax.numpy as jnp

    try:
        alive = int(jax.block_until_ready(jnp.int32(20) + jnp.int32(3))) == 23
    except Exception as e:  # noqa: BLE001
        alive = False
        rec["aliveness_error"] = f"{type(e).__name__}: {str(e)[:120]}"
    structural = any(
        m in rec.get("error", "").lower() for m in _STRUCTURAL_MARKERS
    )
    rec["device_alive_after_failure"] = alive
    rec["error_is_structural"] = structural
    definitive = alive and structural
    rec["verdict"] = (
        "definitive_negative" if definitive else "inconclusive_transient"
    )
    print(json.dumps(rec))
    return 0 if definitive else 1


def main() -> int:
    rec: dict = {"probe": "axon_serialize"}
    import jax
    import jax.numpy as jnp
    from jax.experimental import serialize_executable as se

    rec["backend"] = jax.default_backend()
    if rec["backend"] != "tpu":
        rec["error"] = "no TPU backend; run on a live window"
        print(json.dumps(rec))
        return 1

    cache_before = set(glob.glob(os.path.join(CACHE_DIR, "*")))

    @jax.jit
    def f(x, y):
        return (x * 2 + y).sum(axis=-1)

    x = jnp.arange(8 * 128, dtype=jnp.int32).reshape(8, 128)
    y = jnp.ones((8, 128), jnp.int32)
    t0 = time.perf_counter()
    compiled = f.trace(x, y).lower().compile()
    rec["compile_s"] = round(time.perf_counter() - t0, 3)
    expect = jax.block_until_ready(compiled(x, y))

    cache_after = set(glob.glob(os.path.join(CACHE_DIR, "*")))
    rec["persistent_cache_new_entries"] = len(cache_after - cache_before)

    # --- serialize from the axon client
    try:
        t0 = time.perf_counter()
        payload, in_tree, out_tree = se.serialize(compiled)
        rec["serialize_s"] = round(time.perf_counter() - t0, 3)
        rec["serialized_bytes"] = len(payload)
    except Exception as e:  # noqa: BLE001 - probe records any failure
        rec["error"] = f"serialize: {type(e).__name__}: {str(e)[:300]}"
        rec["ok"] = False
        return _definitive(rec)

    # --- round-trip: deserialize into the same client and run
    try:
        t0 = time.perf_counter()
        loaded = se.deserialize_and_load(payload, in_tree, out_tree)
        rec["deserialize_s"] = round(time.perf_counter() - t0, 3)
        got = jax.block_until_ready(loaded(x, y))
        import numpy as np

        rec["roundtrip_parity"] = bool((np.asarray(got) == np.asarray(expect)).all())
    except Exception as e:  # noqa: BLE001
        rec["error"] = f"deserialize_and_load: {type(e).__name__}: {str(e)[:300]}"
        rec["ok"] = False
        return _definitive(rec)

    os.makedirs(os.path.dirname(ART), exist_ok=True)
    with open(ART, "wb") as fh:
        pickle.dump(
            {"payload": payload, "in_tree": in_tree, "out_tree": out_tree}, fh
        )
    rec["artifact"] = ART
    rec["ok"] = bool(rec.get("roundtrip_parity"))
    print(json.dumps(rec))
    return 0  # definitive result either way; rc=1 is reserved for no-TPU


if __name__ == "__main__":
    sys.exit(main())
