#!/usr/bin/env bash
# The one CI gate: crdtlint (exit-code gated), kernelcheck (the jaxpr
# tier, exit-code gated), shardcheck (the sharding-contract tier,
# exit-code gated), then the tier-1 pytest line from ROADMAP.md —
# builder and CI invoke the SAME entrypoint, so "it passed locally" and
# "it passed in CI" mean the same command.
#
#   scripts/ci.sh            # lint + kernelcheck + shardcheck + tier-1
#   scripts/ci.sh --lint     # AST lint only (seconds, jax-free)
#
# The tier-1 line mirrors ROADMAP.md "Tier-1 verify" verbatim: CPU
# backend, `not slow`, collection errors don't abort, and the trailing
# DOTS_PASSED count makes pass-count regressions diffable from the log.

set -euo pipefail
cd "$(dirname "$0")/.."

echo "== crdtlint =="
python -m crdt_tpu.analysis

if [[ "${1:-}" == "--lint" ]]; then
    exit 0
fi

echo "== kernelcheck =="
# the jaxpr tier: traces every manifested kernel abstractly on CPU and
# lints the jaxprs (KC01-KC05).  The JSON artifact keeps the coverage
# numbers (kernels/traced/cases/mosaic) diffable from the CI log.
JAX_PLATFORMS=cpu python -m crdt_tpu.analysis --kernels --json \
    > /tmp/kernelcheck.json || {
    cat /tmp/kernelcheck.json
    echo "kernelcheck FAILED (see findings above)" >&2
    exit 1
}
python - <<'EOF'
import json
kc = json.load(open("/tmp/kernelcheck.json"))["kernelcheck"]
print(f"kernelcheck OK: {kc['kernels']} kernels, {kc['traced']} traced, "
      f"{kc['cases']} cases, {len(kc['skipped'])} declared no-trace, "
      f"{kc['elapsed_s']}s (artifact: /tmp/kernelcheck.json)")
EOF

echo "== shardcheck =="
# the sharding-contract tier: re-traces every manifested kernel under
# abstract object-axis meshes and checks each kernel's declared
# ShardContract (SC01-SC05).  Same artifact pattern as kernelcheck —
# the contract-class counts stay diffable from the CI log.
JAX_PLATFORMS=cpu python -m crdt_tpu.analysis --shard --json \
    > /tmp/shardcheck.json || {
    cat /tmp/shardcheck.json
    echo "shardcheck FAILED (see findings above)" >&2
    exit 1
}
python - <<'EOF'
import json
sc = json.load(open("/tmp/shardcheck.json"))["shardcheck"]
contracts = " ".join(f"{k}={v}" for k, v in sorted(sc["contracts"].items()))
print(f"shardcheck OK: {sc['kernels']} kernels ({contracts}), "
      f"{sc['traced']} traced, {sc['cases']} cases incl "
      f"{sc['mesh_cases']} mesh-shaped, {len(sc['skipped'])} declared "
      f"no-trace, {sc['elapsed_s']}s (artifact: /tmp/shardcheck.json)")
EOF

echo "== mesh suite (8-way forced-host-device mesh) =="
# the mesh-sharded fleet suite gets its own visible stage: conftest
# already forces the 8-device CPU mesh for tier-1, but the explicit
# XLA_FLAGS here makes the {1,2,4,8} runtime ladder's precondition part
# of the CI contract (not a conftest implementation detail), and the
# separate invocation keeps mesh-size-invariance regressions diffable
# from the log before the full tier-1 run buries them.
timeout -k 10 300 env JAX_PLATFORMS=cpu \
    XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    python -m pytest tests/test_mesh.py -q -m mesh \
    -p no:cacheprovider -p no:xdist -p no:randomly

echo "== tier-1 pytest =="
rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu \
    python -m pytest tests/ -q -m 'not slow' \
    --continue-on-collection-errors \
    -p no:cacheprovider -p no:xdist -p no:randomly \
    2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)
exit "$rc"
