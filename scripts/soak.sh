#!/bin/bash
# One-command CI soak (VERDICT r3 item 7): a deep hypothesis pass at
# 1000 examples/property, then 3 repeated full-suite passes (hypothesis
# draws fresh cases each pass — profiles are not derandomized, see
# tests/conftest.py).  Everything tees into one committed log under
# reports/ so the soak is a reproducible artifact, not a round-notes
# claim.
#
# Usage: bash scripts/soak.sh [logfile]
#   CRDT_SOAK_DEEP_EXAMPLES  examples/property for the deep pass (1000)
#   CRDT_SOAK_PASSES         repeated standard passes after it (3)
set -u
cd "$(dirname "$0")/.."

LOG=${1:-reports/SOAK_$(date -u +%Y%m%d).log}
DEEP=${CRDT_SOAK_DEEP_EXAMPLES:-1000}
PASSES=${CRDT_SOAK_PASSES:-3}
mkdir -p "$(dirname "$LOG")"
: > "$LOG"

# NOTE: pass/fail state must live in THIS shell — `{ ...; } | tee` would
# mutate `fail` inside the pipeline subshell and the final exit would
# always see 0.  Each step pipes through tee individually and reports
# its real status via PIPESTATUS.
note() { echo "$@" 2>&1 | tee -a "$LOG"; }
runp() { "$@" 2>&1 | tee -a "$LOG"; return "${PIPESTATUS[0]}"; }

fail=0
note "# soak $(date -u +%Y-%m-%dT%H:%M:%SZ)  rev $(git rev-parse --short HEAD 2>/dev/null || echo norev)"
note "# deep pass: CRDT_HYP_EXAMPLES=$DEEP; then $PASSES standard passes"

note "== deep hypothesis pass (CRDT_HYP_EXAMPLES=$DEEP) =="
runp env CRDT_HYP_EXAMPLES="$DEEP" python -m pytest tests/ -q --tb=short || fail=1

for i in $(seq 1 "$PASSES"); do
    note "== standard pass $i/$PASSES (PYTHONHASHSEED=$i, fresh hypothesis cases) =="
    runp env PYTHONHASHSEED="$i" python -m pytest tests/ -q --tb=short || fail=1
done

if [ "$fail" = 0 ]; then
    note "SOAK GREEN: deep pass + $PASSES repeated passes all passed"
else
    note "SOAK FAILED: see above"
fi
exit "$fail"
