"""Bank AOT-bridge load results as the on-chip bench artifact.

`aot_exec_bridge.py load <name>` executes a locally-AOT-compiled
north-star program on the live TPU, checks parity, and writes a verdict
JSON with chained timing (`merges_per_sec`).  That IS headline evidence —
but it lands in /tmp, and the full bench that would normally promote it
ran EARLIER in the same tunnel window (the watcher risk-orders Mosaic
execution last).  This publisher closes the gap: run it after the bridge
loads and it folds any green, fingerprint-fresh verdict into
`BENCH_tpu_window.json`, which both the round driver (committed artifact)
and bench.py's banked-seed path (VERDICT r4 item 2) consume.

RETIRED (round 4, 2026-08-01): the bridge-load flow this publishes for
is dead — the axon runtime rejects locally-serialized executables
("axon format v9" mismatch, reports/TPU_LATENCY.md item 7), so no
verdict JSONs are produced anymore.  bench.py now self-banks the
axon-side executable and publishes through the watcher's publish_bench;
this script is kept only as provenance for the r03 window artifacts.

Idempotent; keeps the existing record's fields and only raises the
headline, never lowers it.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

ART_DIR = "/tmp/aot_exec"
OUT = os.path.join(REPO, "BENCH_tpu_window.json")

# verdict file -> the kernel label the bench would have used
CANDIDATES = [
    ("pallas_scan_ns", "pallas_fused_fold_bridge"),
    ("scan_ns", "jnp_scan_bridge"),
]


def main() -> int:
    from crdt_tpu.utils.fingerprint import ops_fingerprint

    # same trust boundary as bench.py's bridge path and the bridge's own
    # load: verdicts in a directory another user could write to must not
    # become the committed TPU headline
    try:
        st = os.stat(ART_DIR)
    except FileNotFoundError:
        print("publish_bridge: no artifact dir; nothing to publish")
        return 0
    if st.st_uid != os.getuid() or (st.st_mode & 0o022):
        print(f"publish_bridge: {ART_DIR} not exclusively ours; refusing")
        return 0

    code_now = ops_fingerprint()
    best = None
    for name, kernel in CANDIDATES:
        path = os.path.join(ART_DIR, f"{name}.verdict.json")
        if not os.path.exists(path):
            continue
        try:
            with open(path) as f:
                v = json.load(f)
        except (OSError, ValueError):
            continue
        if v.get("parity") is not True:
            print(f"publish_bridge: {name}: parity={v.get('parity')!r} — skip")
            continue
        if v.get("artifact_code") != code_now:
            print(
                f"publish_bridge: {name}: artifact code {v.get('artifact_code')}"
                f" != current ops fingerprint {code_now} — stale, skip"
            )
            continue
        rate = v.get("merges_per_sec")
        if not isinstance(rate, (int, float)) or rate <= 0:
            continue
        if best is None or rate > best[0]:
            best = (rate, kernel, v)
    if best is None:
        print("publish_bridge: no green fresh verdicts to publish")
        return 0

    rate, kernel, v = best
    rec = {}
    if os.path.exists(OUT):
        try:
            with open(OUT) as f:
                rec = json.loads(f.read().strip() or "{}")
        except (OSError, ValueError):
            rec = {}
    old = rec.get("value")
    if isinstance(old, (int, float)) and old >= rate:
        print(
            f"publish_bridge: existing record {old} >= bridge {rate} — keeping"
        )
        return 0

    try:
        rev = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"], cwd=REPO,
            capture_output=True, text=True, timeout=10,
        ).stdout.strip() or "norev"
    except Exception:
        rev = "norev"
    rec.update(
        {
            "metric": "orswot_merges_per_sec_to_fixpoint",
            "value": round(float(rate), 1),
            "unit": "merges/s",
            "vs_baseline": round(float(rate) / 1e7, 4),
            "kernel": kernel,
            "platform": "tpu",
            "backend_fallback": False,
            "bridge_exec_s": v.get("exec_s"),
            "bridge_counts": v.get("counts"),
            "captured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
            "captured_rev": f"{rev}.{code_now}",
            "note": "AOT-bridge execution (no remote compile); parity-gated "
                    "vs per-step oracle — scripts/aot_exec_bridge.py",
        }
    )
    with open(OUT, "w") as f:
        f.write(json.dumps(rec) + "\n")
    print(f"publish_bridge: published {kernel} {rate} merges/s to {OUT}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
