"""Benchmark harness — the BASELINE.md configs on the live JAX backend.

Prints ONE JSON line to stdout:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}
Everything else (per-config results, parity anchor) goes to stderr.

Configs (BASELINE.md / BASELINE.json):
  1. GCounter::merge  — 2 replicas, 4 actors (scalar CPU parity anchor)
  2. VClock::merge    — 1k clocks × 64 actors
  3. PNCounter::merge — 1M replicas × 32 actors
  4. Orswot::merge    — 100k sets × 16 actors
  5. LWWReg::merge    — 10M registers
  ★  North star: N-way Orswot anti-entropy to fixpoint, 64 actors,
     reported as merges/sec (pairwise object-merges per second), with
     value() parity vs the scalar engine asserted on a sample.

The reference publishes no numbers (BASELINE.md); vs_baseline is reported
against the BASELINE.json target of 10M merged replicas in <1s ⇒ 1e7
merges/sec ⇒ vs_baseline = value / 1e7.

Set CRDT_BENCH_SMALL=1 for a quick smoke run (CI / laptops).
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np


def log(*args):
    print(*args, file=sys.stderr, flush=True)


SMALL = os.environ.get("CRDT_BENCH_SMALL") == "1"


def timeit(fn, *args, iters=5):
    """Median wall time of jitted fn over `iters` runs (post-warmup)."""
    import jax

    out = fn(*args)
    jax.block_until_ready(out)  # compile + warmup
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        out = fn(*args)
        jax.block_until_ready(out)
        times.append(time.perf_counter() - t0)
    return float(np.median(times)), out


def rand_clocks(rng, shape, hi=1000):
    return rng.randint(0, hi, size=shape).astype(np.uint32)


def bench_clock_merges():
    import jax
    import jax.numpy as jnp

    from crdt_tpu.ops import clock_ops

    rng = np.random.RandomState(0)

    # config 2: VClock 1k × 64
    n, a = (1000, 64) if not SMALL else (100, 16)
    x = jnp.asarray(rand_clocks(rng, (n, a)))
    y = jnp.asarray(rand_clocks(rng, (n, a)))
    t, _ = timeit(jax.jit(clock_ops.merge), x, y)
    log(f"config2 vclock_merge   n={n} A={a}: {t*1e6:.1f}us  {n/t/1e6:.2f}M merges/s")

    # config 3: PNCounter 1M × 32 (planes [N, 2, A])
    n, a = (1_000_000, 32) if not SMALL else (10_000, 8)
    p = jnp.asarray(rand_clocks(rng, (n, 2, a)))
    q = jnp.asarray(rand_clocks(rng, (n, 2, a)))
    t, _ = timeit(jax.jit(clock_ops.merge), p, q)
    log(f"config3 pncounter_merge n={n} A={a}: {t*1e3:.2f}ms  {n/t/1e6:.2f}M merges/s")

    # config 5: LWWReg 10M
    from crdt_tpu.ops import lww_ops

    n = 10_000_000 if not SMALL else 100_000
    va = jnp.asarray(rng.randint(0, 1 << 30, size=n).astype(np.uint32))
    ma = jnp.asarray(rng.randint(0, 1 << 30, size=n).astype(np.uint32))
    vb = jnp.asarray(rng.randint(0, 1 << 30, size=n).astype(np.uint32))
    mb = jnp.asarray(rng.randint(0, 1 << 30, size=n).astype(np.uint32))
    t, _ = timeit(jax.jit(lww_ops.merge), va, ma, vb, mb)
    log(f"config5 lwwreg_merge   n={n}: {t*1e3:.2f}ms  {n/t/1e6:.2f}M merges/s")


def bench_orswot_pairwise():
    import jax
    import jax.numpy as jnp

    from crdt_tpu.ops import orswot_ops
    from crdt_tpu.utils.testdata import random_orswot_arrays

    rng = np.random.RandomState(1)
    # config 4: 100k sets × 16 actors
    n, a, m, d = (100_000, 16, 8, 4) if not SMALL else (2_000, 8, 4, 2)
    lhs = tuple(jnp.asarray(x) for x in random_orswot_arrays(rng, n, a, m, d))
    rhs = tuple(jnp.asarray(x) for x in random_orswot_arrays(rng, n, a, m, d))

    merge = jax.jit(
        lambda L, R: orswot_ops.merge(*L, *R, m, d)[:5]
    )
    t, _ = timeit(merge, lhs, rhs)
    log(f"config4 orswot_merge   n={n} A={a} M={m}: {t*1e3:.2f}ms  {n/t/1e6:.2f}M merges/s")
    return n / t


def bench_north_star():
    """N-way anti-entropy to fixpoint: R replica fleets of N objects each,
    left-fold join + plunger rounds, all on device."""
    import jax
    import jax.numpy as jnp

    from crdt_tpu.ops import orswot_ops
    from crdt_tpu.utils.testdata import random_orswot_arrays

    rng = np.random.RandomState(2)
    if SMALL:
        n, a, m, d, r = 2_000, 16, 4, 2, 4
    else:
        n, a, m, d, r = 125_000, 64, 4, 2, 8

    replicas = [
        tuple(jnp.asarray(x) for x in random_orswot_arrays(rng, n, a, m, d))
        for _ in range(r)
    ]
    stacked = tuple(jnp.stack([rep[i] for rep in replicas]) for i in range(5))

    if os.environ.get("CRDT_PALLAS") == "1" and jax.default_backend() == "tpu":
        # fused Pallas fold: accumulator stays in VMEM across all R joins.
        # Opt-in only, and only on a real TPU backend — Mosaic cannot lower
        # on CPU, so the flag degrades to the jnp fold after a CPU fallback
        # (see crdt_tpu/ops/orswot_pallas.py deployment note).
        from crdt_tpu.ops import orswot_pallas

        fold = lambda stack: orswot_pallas.fold_merge(*stack, m, d, interpret=False)
        t, joined = timeit(fold, stacked, iters=3)
        merges = n * r
        rate = merges / t
        log(
            f"north★  (pallas fused fold) n={n} R={r} A={a} M={m}: "
            f"{t*1e3:.2f}ms  {rate/1e6:.2f}M merges/s"
        )
        return rate

    def fold_join(stack):
        acc = tuple(x[0] for x in stack)
        for i in range(1, r):
            acc = orswot_ops.merge(*acc, *(x[i] for x in stack), m, d)[:5]
        # defer plunger: one self-merge pass flushes deferred removes
        acc = orswot_ops.merge(*acc, *acc, m, d)[:5]
        return acc

    t, joined = timeit(jax.jit(fold_join), stacked, iters=3)
    merges = n * r  # r-1 fold merges + 1 plunger, each over n objects
    rate = merges / t
    log(
        f"north★  orswot anti-entropy fixpoint n={n} R={r} A={a} M={m}: "
        f"{t*1e3:.2f}ms  {rate/1e6:.2f}M merges/s"
    )
    return rate


def parity_anchor():
    """Config 1 + value() parity: scalar CPU reference vs batch path."""
    from crdt_tpu import GCounter, Orswot
    from crdt_tpu.batch import GCounterBatch, OrswotBatch
    from crdt_tpu.config import CrdtConfig
    from crdt_tpu.utils.interning import Universe

    # GCounter: 2 replicas, 4 actors (config 1)
    uni = Universe(CrdtConfig(num_actors=4, member_capacity=8, deferred_capacity=4))
    a, b = GCounter(), GCounter()
    for actor in ("A", "B", "A", "C"):
        a.apply(a.inc(actor))
    for actor in ("B", "D"):
        b.apply(b.inc(actor))
    expected = a.clone()
    expected.merge(b)
    got = (
        GCounterBatch.from_scalar([a], uni)
        .merge(GCounterBatch.from_scalar([b], uni))
        .to_scalar(uni)[0]
    )
    # a = {A:2, B:1, C:1}, b = {B:1, D:1} ⇒ join value 2+1+1+1 = 5
    assert got.value() == expected.value() == 5, (got.value(), expected.value())

    # Orswot sample: batch N-way join value() == scalar N-way join value()
    uni = Universe(CrdtConfig(num_actors=8, member_capacity=16, deferred_capacity=8))
    rng = np.random.RandomState(3)
    fleets = []
    for _ in range(4):
        row = []
        for _ in range(8):
            s = Orswot()
            for _ in range(rng.randint(0, 6)):
                actor, member = int(rng.randint(0, 8)), int(rng.randint(0, 9))
                ctx = s.value().derive_add_ctx(actor)
                s.apply(s.add(member, ctx))
            row.append(s)
        fleets.append(row)
    batches = [OrswotBatch.from_scalar(row, uni) for row in fleets]
    acc = batches[0]
    for nxt in batches[1:]:
        acc = acc.merge(nxt)
    got_sets = acc.value_sets(uni)
    expected_sets = []
    for i in range(8):
        merged = Orswot()
        for row in fleets:
            merged.merge(row[i])
        merged.merge(Orswot())
        expected_sets.append(merged.value().val)
    assert got_sets == expected_sets, "value() parity violation"
    log("config1 parity anchor: scalar == batch (GCounter value, Orswot value sets)")


def _probe_backend(timeout_s: float) -> bool:
    """True when the default JAX backend initializes in a fresh process.

    Remote-TPU tunnels can wedge so hard that ``jax.devices()`` blocks
    forever; probing in a killable subprocess lets the harness fall back
    to CPU instead of hanging the whole benchmark run."""
    import subprocess
    import sys

    try:
        proc = subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            timeout=timeout_s,
            capture_output=True,
        )
        return proc.returncode == 0
    except subprocess.TimeoutExpired:
        return False


def main():
    plat = os.environ.get("CRDT_BENCH_PLATFORM")
    fallback = False
    probe_timeout = float(os.environ.get("CRDT_BENCH_PROBE_TIMEOUT", "300"))
    if not plat and not _probe_backend(probe_timeout):
        log(
            f"WARNING: default backend unreachable within {probe_timeout:.0f}s "
            "(wedged tunnel?) — falling back to cpu; numbers are NOT accelerator "
            "numbers (platform recorded in the JSON line)"
        )
        plat = "cpu"
        fallback = True

    import jax

    # local smoke runs force a platform (the ambient axon plugin overrides
    # the JAX_PLATFORMS env var, so use the config knob directly)
    if plat:
        jax.config.update("jax_platforms", plat)

    log(f"backend: {jax.default_backend()}  devices: {len(jax.devices())}  small={SMALL}")
    parity_anchor()
    bench_clock_merges()
    bench_orswot_pairwise()
    rate = bench_north_star()

    print(
        json.dumps(
            {
                "metric": "orswot_merges_per_sec_to_fixpoint",
                "value": round(rate, 1),
                "unit": "merges/s",
                "vs_baseline": round(rate / 1e7, 4),
                "platform": jax.default_backend(),
                "backend_fallback": fallback,
            }
        )
    )


if __name__ == "__main__":
    main()
